"""Attention: fused scaled-dot-product attention + multi-head attention layer.

Reference parity:
  * AttentionBlock — include/nn/blocks_impl/attention_block.hpp:21 — q/k/v/out Dense
    projections + batched QK^T -> causal mask -> softmax -> xV via cuBLAS strided-batch
    (src/nn/blocks_impl/attention_block.cpp:109-315; CPU path throws).
  * FlashAttentionBlock — cuDNN-frontend fused SDPA (src/nn/blocks_impl/flash_attention_block.cpp:74-338).
  * SDPALayer — layers_impl/sdpa_layer.hpp:23.

TPU-first: one SDPA implementation with pluggable backends — "xla" (lax ops XLA fuses
well, works everywhere) and "pallas" (blockwise online-softmax flash kernel for long
sequences, tnn_tpu/ops/pallas/flash_attention.py). Both are O(S^2) FLOPs but pallas is
O(block) memory like the reference's flash path. Unlike the reference, attention runs on
every backend (the reference throws on CPU).

KV-cache decode support (``apply_cached``) exceeds the reference, which recomputes the
full sequence per generated token (examples/gpt2_inference.cpp:71-91).
"""
from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp

from ..core import dtypes as dt
from ..core.module import Module, register_module
from . import initializers


# Ring (sequence-parallel) context: inside ``with ring_context(mesh):``, every
# sdpa call that CAN run as a ring (no mask/kv_offset) does — regardless of the
# model's configured backend. The context is authoritative because sequence
# parallelism is a run-time deployment choice, not model configuration: the
# model object is never mutated, so checkpoints keep their original backend
# and the same model decodes single-chip after seq-parallel training.
# sdpa(backend="ring") outside any context is an error (nothing to ring over).
_RING_CTX = {"mesh": None, "axis": "seq", "batch_axis": None, "method": "ring"}


class ring_context:
    """with ring_context(mesh, axis="seq"): step(...) — seq-parallel attention.
    ``batch_axis`` (a name or tuple of names) composes dp/fsdp x sp: each batch
    shard runs its own ring instead of all-gathering at the shard_map boundary.
    ``method`` picks the context-parallel scheme: "ring" (K/V rotation — any
    head count) or "ulysses" (all-to-all head re-sharding — needs
    num_heads % sp == 0, runs the Pallas flash kernel locally)."""

    def __init__(self, mesh, axis: str = "seq", batch_axis=None,
                 method: str = "ring"):
        if method not in ("ring", "ulysses"):
            raise ValueError(f"unknown seq-parallel method {method!r}")
        self.mesh, self.axis, self.batch_axis = mesh, axis, batch_axis
        self.method = method

    def __enter__(self):
        self._prev = dict(_RING_CTX)
        _RING_CTX.update(mesh=self.mesh, axis=self.axis,
                         batch_axis=self.batch_axis, method=self.method)
        return self

    def __exit__(self, *exc):
        _RING_CTX.update(self._prev)


def count_attention_modules(module) -> int:
    """How many submodules carry a switchable attention ``backend`` — used to
    validate that a seq-parallel layout has attention to parallelize.
    (backend=None in set_attention_backend counts without mutating.)"""
    return set_attention_backend(module, None)


def set_attention_backend(module, backend) -> int:
    """Recursively set ``backend`` on every attention-bearing submodule.

    Returns how many modules were switched. Retargets a model built with
    backend="xla" to "pallas" (etc.) without rebuilding it — the attribute is
    read at trace time, not baked at init. (Sequence parallelism does NOT need
    this: ring_context overrides backends without mutating the model.)

    The walk follows Module attributes, list/tuple elements, dict values, and
    non-Module wrappers exposing ``.module`` (Graph's GraphNode)."""
    from ..core.module import Module

    seen = set()
    count = 0

    def walk(m):
        nonlocal count
        if id(m) in seen or not isinstance(m, Module):
            return
        seen.add(id(m))
        if hasattr(m, "backend"):
            if backend is not None:
                m.backend = backend
            count += 1
        for v in vars(m).values():
            for x in _iter_candidates(v):
                walk(x)

    def _iter_candidates(v):
        if isinstance(v, Module):
            yield v
        elif isinstance(v, (list, tuple)):
            for x in v:
                yield from _iter_candidates(x)
        elif isinstance(v, dict):
            for x in v.values():
                yield from _iter_candidates(x)
        elif hasattr(v, "module"):  # GraphNode-style wrapper
            yield from _iter_candidates(v.module)

    walk(module)
    return count


def sdpa(q, k, v, *, causal: bool = False, mask: Optional[jax.Array] = None,
         scale: Optional[float] = None, backend: str = "xla",
         kv_offset: Optional[jax.Array] = None):
    """Scaled dot-product attention over (B, H, S, Dh) tensors.

    ``kv_offset``: during cached decode, absolute position of q[0] within the kv
    sequence — builds the correct causal mask for S_q != S_kv. May be a scalar
    (uniform batch) or a (B,) array (ragged batch — serving's continuous
    batching, where every row sits at its own decode position).
    """
    ragged = kv_offset is not None and getattr(kv_offset, "ndim", 0) > 0
    # GQA + seq parallelism: ring is GQA-aware for any group ratio; ulysses
    # validates H_kv % shards itself (ulysses_attention raises a ValueError
    # naming the ring fallback when kv heads cannot split)
    ringable = mask is None and kv_offset is None
    if _RING_CTX["mesh"] is not None and ringable:
        # context wins over the configured backend: inside a seq-parallel step
        # the activations are seq-sharded, so local/full attention would be
        # wrong or all-gather; mask/kv_offset calls (cached decode) fall
        # through to their normal path untouched
        if _RING_CTX["method"] == "ulysses":
            from ..parallel.ulysses import ulysses_attention

            return ulysses_attention(q, k, v, _RING_CTX["mesh"],
                                     axis=_RING_CTX["axis"], causal=causal,
                                     scale=scale,
                                     batch_axis=_RING_CTX["batch_axis"])
        from ..parallel.ring_attention import ring_attention

        return ring_attention(q, k, v, _RING_CTX["mesh"],
                              axis=_RING_CTX["axis"], causal=causal,
                              scale=scale, batch_axis=_RING_CTX["batch_axis"])
    if backend == "ring":
        raise RuntimeError(
            "backend='ring' needs an enclosing nn.attention.ring_context(mesh)"
            " — e.g. train_model with mesh_axes={'seq': N}" if ringable else
            "ring attention does not support mask/kv_offset (cached decode); "
            "run decode outside the ring context with backend='xla'")
    if backend == "pallas" and not ragged:
        # the flash kernel takes a scalar kv_offset only; ragged
        # assembled-cache batches route to the XLA path (ragged decode's
        # native route is the paged path — apply_paged over
        # ops.pallas.paged_attention, no assembled cache at all)
        from ..ops.pallas.flash_attention import flash_attention

        return flash_attention(q, k, v, causal=causal, scale=scale,
                               mask=mask, kv_offset=kv_offset)
    return local_xla_attention(q, k, v, causal=causal, mask=mask, scale=scale,
                               kv_offset=kv_offset)


def apply_rope(x, offset=0, theta: float = 10000.0):
    """Rotary position embedding over (B, H, S, Dh) — half-split (NeoX-style)
    pair rotation. ``offset`` is the absolute position of x[..., 0, :] (the
    cached-decode case); may be a traced scalar, or a (B,) array for ragged
    decode batches where every row sits at its own position. Rotation is a
    function of ABSOLUTE position, so cached decode rotates keys at insert
    time and the cache stores rotated keys."""
    d = x.shape[-1]
    if d % 2:
        raise ValueError(f"RoPE needs an even head dim, got {d}")
    half = d // 2
    if getattr(offset, "ndim", 0):  # per-row offsets: (B, S) positions
        pos = offset[:, None] + jnp.arange(x.shape[-2])
    else:
        pos = offset + jnp.arange(x.shape[-2])
    inv = theta ** (-jnp.arange(half, dtype=jnp.float32) / half)
    freqs = pos[..., None].astype(jnp.float32) * inv   # (..., S, half)
    cos, sin = jnp.cos(freqs), jnp.sin(freqs)
    if cos.ndim == 3:  # ragged: (B, S, half) -> broadcast over the head dim
        cos, sin = cos[:, None], sin[:, None]
    x1 = x[..., :half].astype(jnp.float32)
    x2 = x[..., half:].astype(jnp.float32)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


def local_xla_attention(q, k, v, *, causal: bool = False,
                        mask: Optional[jax.Array] = None,
                        scale: Optional[float] = None,
                        kv_offset: Optional[jax.Array] = None):
    """The plain XLA softmax-attention math — sdpa's "xla" backend, and the
    single source of truth for any caller that must bypass the seq-parallel
    context routing (e.g. ulysses' off-TPU local attention, which would
    recurse through sdpa)."""
    sq, skv = q.shape[-2], k.shape[-2]
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if k.shape[1] != q.shape[1]:
        # grouped-query attention: materialize the shared kv heads for the
        # reference path (XLA folds the broadcast); the pallas kernel is the
        # zero-copy route (q-head grid index -> kv head in its index maps)
        g = q.shape[1] // k.shape[1]
        k = jnp.repeat(k, g, axis=1)
        v = jnp.repeat(v, g, axis=1)
    # QK^T with f32 accumulation on the MXU.
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    live = None
    if causal:
        qpos = jnp.arange(sq)[:, None]
        if kv_offset is not None:
            if getattr(kv_offset, "ndim", 0):  # per-row (B,) -> (B, 1, sq, 1)
                qpos = qpos + kv_offset[:, None, None, None]
            else:
                qpos = qpos + kv_offset
        kpos = jnp.arange(skv)[None, :]
        live = qpos >= kpos
        logits = jnp.where(live, logits, dt.neg_inf(logits.dtype))
    if mask is not None:
        live = mask if live is None else jnp.logical_and(mask, live)
        logits = jnp.where(mask, logits, dt.neg_inf(logits.dtype))
    probs = jax.nn.softmax(logits, axis=-1)
    if mask is not None:
        # a fully-masked row attends to NOTHING (output 0) — softmax alone
        # would silently return uniform attention over the masked keys; the
        # flash kernel's online-softmax (l=0 -> 0) already behaves this way
        row_live = jnp.any(jnp.broadcast_to(live, logits.shape), axis=-1,
                           keepdims=True)
        probs = jnp.where(row_live, probs, 0.0)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)
    return out.astype(v.dtype)


@register_module("multihead_attention")
class MultiHeadAttention(Module):
    """Multi-head self-attention over (N, S, D).

    Parity: AttentionBlock (4 Dense projections q/k/v/out + batched SDPA,
    blocks_impl/attention_block.cpp:109-315). Fused qkv projection (one matmul instead of
    three — better MXU utilisation).
    """

    def __init__(self, num_heads: int, causal: bool = False, dropout: float = 0.0,
                 backend: str = "xla", kernel_init: str = "xavier_uniform",
                 num_kv_heads: Optional[int] = None,
                 kv_cache_dtype: Optional[str] = None,
                 rope_theta: Optional[float] = None, use_bias: bool = True,
                 name=None, policy=None):
        super().__init__(name=name, policy=policy)
        self.num_heads = int(num_heads)
        # grouped-query attention (beyond reference): H_kv < H shares each
        # kv head across a group of query heads, shrinking the decode KV
        # cache (the decode bandwidth floor) by H/H_kv
        self.num_kv_heads = int(num_kv_heads) if num_kv_heads else self.num_heads
        if self.num_kv_heads <= 0 or self.num_heads % self.num_kv_heads:
            raise ValueError(f"num_kv_heads {self.num_kv_heads} must be a "
                             f"positive divisor of num_heads {self.num_heads}")
        # "int8": decode KV cache stored as per-row symmetric int8 + f32
        # scale — halves cache residency/traffic (composes with GQA's H/H_kv)
        if kv_cache_dtype not in (None, "int8"):
            raise ValueError(f"kv_cache_dtype {kv_cache_dtype!r}: only "
                             "None (compute dtype) or 'int8' supported")
        self.kv_cache_dtype = kv_cache_dtype
        # rotary position embedding (Llama-family): applied to q/k after the
        # projection split; absolute-position offsets flow through cached
        # decode. None = no rotation (positions come from elsewhere, e.g. a
        # learned wpe as in GPT-2).
        self.rope_theta = float(rope_theta) if rope_theta else None
        self.use_bias = bool(use_bias)
        self.causal = bool(causal)
        self.dropout = float(dropout)
        self.backend = backend
        self.kernel_init = kernel_init
        from .layers import Dropout  # local import: layers has no dep on attention

        self._drop = Dropout(self.dropout, policy=self.policy)

    def _init(self, rng, input_shape):
        d = input_shape[-1]
        if d % self.num_heads:
            raise ValueError(f"model dim {d} not divisible by num_heads {self.num_heads}")
        kv_d = (d // self.num_heads) * self.num_kv_heads
        init = initializers.get(self.kernel_init)
        k1, k2 = jax.random.split(rng)
        pd = self.policy.param_dtype
        params = {
            "qkv_kernel": init(k1, (d, d + 2 * kv_d), pd),
            "out_kernel": init(k2, (d, d), pd),
        }
        if self.use_bias:
            params["qkv_bias"] = jnp.zeros((d + 2 * kv_d,), pd)
            params["out_bias"] = jnp.zeros((d,), pd)
        return params, {}

    def _split_heads(self, x, h=None):
        n, s, d = x.shape
        h = h or self.num_heads
        return x.reshape(n, s, h, d // h).transpose(0, 2, 1, 3)

    def _merge_heads(self, x):
        n, h, s, dh = x.shape
        return x.transpose(0, 2, 1, 3).reshape(n, s, h * dh)

    def _project_qkv(self, params, x):
        from ..ops.pallas.quant_matmul import qmatmul

        x = self.policy.cast_in(x)
        w = self.policy.cast_param(params["qkv_kernel"])
        qkv = qmatmul(x, w).astype(x.dtype)
        if self.use_bias:
            qkv = qkv + params["qkv_bias"].astype(x.dtype)
        d = x.shape[-1]
        kv_d = (d // self.num_heads) * self.num_kv_heads
        q, k, v = jnp.split(qkv, [d, d + kv_d], axis=-1)
        return (self._split_heads(q), self._split_heads(k, self.num_kv_heads),
                self._split_heads(v, self.num_kv_heads))

    def _project_out(self, params, attn, train, rng):
        from ..ops.pallas.quant_matmul import qmatmul

        y = self._merge_heads(attn)
        w = self.policy.cast_param(params["out_kernel"])
        y = qmatmul(y, w).astype(y.dtype)
        if self.use_bias:
            y = y + params["out_bias"].astype(y.dtype)
        y, _ = self._drop.apply({}, y, train=train, rng=rng)
        return self.policy.cast_out(y)

    def _apply(self, params, state, x, *, train, rng):
        q, k, v = self._project_qkv(params, x)
        if self.rope_theta:
            q = apply_rope(q, 0, self.rope_theta)
            k = apply_rope(k, 0, self.rope_theta)
        attn = sdpa(q, k, v, causal=self.causal, backend=self.backend)
        return self._project_out(params, attn, train, rng), state

    # -- cached autoregressive decode (exceeds reference) ----------------------

    def init_cache(self, batch: int, max_len: int, d_model: int):
        """Allocate a (k, v) ring cache for decode — sized to the KV heads,
        so GQA shrinks the cache (and the decode HBM floor) by H/H_kv;
        ``kv_cache_dtype="int8"`` halves it again (int8 rows + f32 scales)."""
        h = self.num_kv_heads
        dh = d_model // self.num_heads
        if self.kv_cache_dtype == "int8":
            z8 = jnp.zeros((batch, h, max_len, dh), jnp.int8)
            zs = jnp.zeros((batch, h, max_len, 1), jnp.float32)
            return {"k": z8, "v": z8, "k_scale": zs, "v_scale": zs}
        dtype = self.policy.compute_dtype
        return {
            "k": jnp.zeros((batch, h, max_len, dh), dtype),
            "v": jnp.zeros((batch, h, max_len, dh), dtype),
        }

    @staticmethod
    def _quant_rows(x):
        """Symmetric per-row (per position, per head) int8: scale = amax/127."""
        xf = x.astype(jnp.float32)
        scale = jnp.maximum(jnp.max(jnp.abs(xf), axis=-1, keepdims=True),
                            1e-8) / 127.0
        q = jnp.clip(jnp.round(xf / scale), -127, 127).astype(jnp.int8)
        return q, scale

    def apply_cached(self, variables, x, cache, offset):
        """Decode step: x is (N, S_new, D); cache holds keys/values for [0, offset).

        Returns (out, new_cache). The full cache buffer participates in attention with a
        position mask, keeping shapes static for jit.

        ``offset`` may be a scalar (uniform batch) or a (N,) array — the
        ragged case, where each row writes and masks at its own position
        (serving's continuous batching over pool-assembled caches).
        """
        params = variables["params"]
        q, k_new, v_new = self._project_qkv(params, x)
        if self.rope_theta:
            # rotation depends on ABSOLUTE position: rotate q and the new
            # keys at their true offsets; the cache stores rotated keys
            q = apply_rope(q, offset, self.rope_theta)
            k_new = apply_rope(k_new, offset, self.rope_theta)
        if getattr(offset, "ndim", 0):  # per-row write positions
            upd = lambda buf, new: jax.vmap(  # noqa: E731
                lambda b, n, o: jax.lax.dynamic_update_slice_in_dim(
                    b, n, o, axis=1))(buf, new, offset)
        else:
            upd = lambda buf, new: jax.lax.dynamic_update_slice_in_dim(  # noqa: E731
                buf, new, offset, axis=2)
        if self.kv_cache_dtype == "int8":
            kq, ks = self._quant_rows(k_new)
            vq, vs = self._quant_rows(v_new)
            cache = {"k": upd(cache["k"], kq), "v": upd(cache["v"], vq),
                     "k_scale": upd(cache["k_scale"], ks),
                     "v_scale": upd(cache["v_scale"], vs)}
            cd = self.policy.compute_dtype
            # dequant at use. On the XLA backend the int8 read + scale can
            # fuse into the attention contraction (traffic = int8 bytes); on
            # backend="pallas" the dequantized arrays are pallas_call
            # operands — a fusion boundary — so THIS contiguous-cache path
            # materializes compute-dtype K/V and only the residency win
            # remains. The paged serving path does not share the caveat:
            # the pool's kv_dtype="int8" QuantPages feed the ragged paged
            # kernel as int8 operands and dequantize in-VMEM inside its
            # online-softmax loop, so HBM traffic is int8 bytes there too.
            k = (cache["k"].astype(jnp.float32) * cache["k_scale"]).astype(cd)
            v = (cache["v"].astype(jnp.float32) * cache["v_scale"]).astype(cd)
        else:
            cache = {"k": upd(cache["k"], k_new), "v": upd(cache["v"], v_new)}
            k, v = cache["k"], cache["v"]
        # decode follows the model's configured backend — a "pallas" model
        # runs the flash kernel with kv_offset instead of falling back to XLA
        out = sdpa(q, k, v, causal=True, kv_offset=offset,
                   backend=self.backend if self.backend != "ring" else "xla")
        y = self._project_out(params, out, False, None)
        return y, cache

    def apply_paged(self, variables, x, pages_k, pages_v, block_tables,
                    offsets, layer=0, q_lens=None):
        """One step straight against the paged KV pool.

        The serving hot path (docs/serving.md): instead of assembling a
        contiguous cache (``apply_cached`` over ``kv_pool.gather_kv``), the
        new tokens' K/V rows are scattered into their pages and attention
        streams the pages the block table names
        (``ops.pallas.paged_attention``).

        x : (B, Q, D) — this step's new tokens per row (Q = 1 for pure
            decode; Q > 1 for ragged prefill chunks).
        pages_k / pages_v : the pool's (L, N, H_kv, bs, Dh) arrays; ``layer``
            selects this block's slice without copying it.
        block_tables : (B, nb) page ids; offsets : (B,) the position each row
            writes first (its kv length BEFORE this step's tokens).
        q_lens : (B,) live tokens per row this step, or None for the decode
            form (Q must then be 1). Tokens past ``q_lens[b]`` are padding:
            their KV lands in the pool's scratch page and their outputs are
            garbage the caller must ignore.

        Returns (out (B, Q, D), pages_k, pages_v) — pages updated only at the
        written rows, so with the pool buffers donated through jit the update
        is in place.
        """
        if self.kv_cache_dtype == "int8":
            raise NotImplementedError(
                "paged decode with int8 KV pages is future work — pool pages "
                "are compute-dtype (see docs/serving.md limits)")
        params = variables["params"]
        q, k_new, v_new = self._project_qkv(params, x)   # (B, H*, Q, Dh)
        if self.rope_theta:
            q = apply_rope(q, offsets, self.rope_theta)
            k_new = apply_rope(k_new, offsets, self.rope_theta)
        from ..ops.pallas import paged_attention as pa

        quant_pool = isinstance(pages_k, pa.QuantPages)
        if q_lens is None and x.shape[1] == 1:
            # decode form, kept verbatim: the pure-decode compiled step must
            # stay bit-identical to the pre-chunking program (QuantPages
            # skip the dtype cast — scatter quantizes the rows itself)
            rows_k, rows_v = k_new[:, :, 0], v_new[:, :, 0]
            if not quant_pool:
                rows_k = rows_k.astype(pages_k.dtype)
                rows_v = rows_v.astype(pages_v.dtype)
            pages_k = pa.scatter_kv_rows(pages_k, block_tables, offsets,
                                         rows_k, layer=layer)
            pages_v = pa.scatter_kv_rows(pages_v, block_tables, offsets,
                                         rows_v, layer=layer)
            out = pa.paged_attention(q[:, :, 0], pages_k, pages_v,
                                     block_tables, kv_lens=offsets + 1,
                                     layer=layer)
            y = self._project_out(params, out[:, :, None, :], False, None)
            return y, pages_k, pages_v
        if q_lens is None:
            raise ValueError("apply_paged with Q > 1 requires q_lens")
        # ragged chunk form: scatter the whole chunk's KV first, then attend
        # each row's live tokens against its own chunk + all prior positions
        chunk_k = k_new.transpose(0, 2, 1, 3)
        chunk_v = v_new.transpose(0, 2, 1, 3)
        if not quant_pool:
            chunk_k = chunk_k.astype(pages_k.dtype)
            chunk_v = chunk_v.astype(pages_v.dtype)
        pages_k = pa.scatter_kv_chunk(pages_k, block_tables, offsets, chunk_k,
                                      q_lens, layer=layer)
        pages_v = pa.scatter_kv_chunk(pages_v, block_tables, offsets, chunk_v,
                                      q_lens, layer=layer)
        out = pa.paged_attention(q.transpose(0, 2, 1, 3), pages_k, pages_v,
                                 block_tables, kv_lens=offsets + q_lens,
                                 q_lens=q_lens, layer=layer)
        y = self._project_out(params, out.transpose(0, 2, 1, 3), False, None)
        return y, pages_k, pages_v

    def output_shape(self, input_shape):
        return tuple(input_shape)

    def _config(self):
        cfg = {"num_heads": self.num_heads, "causal": self.causal,
               "dropout": self.dropout, "backend": self.backend,
               "num_kv_heads": self.num_kv_heads,
               "kernel_init": initializers.name_of(self.kernel_init)}
        if self.kv_cache_dtype:
            cfg["kv_cache_dtype"] = self.kv_cache_dtype
        if self.rope_theta:
            cfg["rope_theta"] = self.rope_theta
        if not self.use_bias:
            cfg["use_bias"] = False
        return cfg
