"""Weight initializers.

Parity with the reference's per-layer init (e.g. DenseLayer He-style init at
src/nn/layers_impl/dense_layer.cpp:46; fill_random_{uniform,normal} ops at
include/ops/ops.hpp). Implemented as (rng, shape, dtype) -> array callables with a
string registry so layer configs serialize.
"""
from __future__ import annotations

import math
from typing import Callable, Dict

import jax
import jax.numpy as jnp

Initializer = Callable[[jax.Array, tuple, jnp.dtype], jax.Array]

_REGISTRY: Dict[str, Initializer] = {}


def register(name: str):
    def wrap(fn):
        _REGISTRY[name] = fn
        fn.init_name = name
        return fn

    return wrap


def get(name_or_fn) -> Initializer:
    if callable(name_or_fn):
        return name_or_fn
    if name_or_fn not in _REGISTRY:
        raise KeyError(f"unknown initializer {name_or_fn!r}; known: {sorted(_REGISTRY)}")
    return _REGISTRY[name_or_fn]


def name_of(fn) -> str:
    return getattr(fn, "init_name", "he_normal")


def _fans(shape):
    """fan_in/fan_out. Dense: (in, out). Conv HWIO: (h, w, cin, cout)."""
    if len(shape) < 1:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    receptive = math.prod(shape[:-2]) if len(shape) > 2 else 1
    fan_in = shape[-2] * receptive
    fan_out = shape[-1] * receptive
    return fan_in, fan_out


@register("zeros")
def zeros(rng, shape, dtype=jnp.float32):
    del rng
    return jnp.zeros(shape, dtype)


@register("ones")
def ones(rng, shape, dtype=jnp.float32):
    del rng
    return jnp.ones(shape, dtype)


@register("he_normal")
def he_normal(rng, shape, dtype=jnp.float32):
    fan_in, _ = _fans(shape)
    std = math.sqrt(2.0 / max(1, fan_in))
    return (jax.random.normal(rng, shape, jnp.float32) * std).astype(dtype)


@register("he_uniform")
def he_uniform(rng, shape, dtype=jnp.float32):
    fan_in, _ = _fans(shape)
    limit = math.sqrt(6.0 / max(1, fan_in))
    return jax.random.uniform(rng, shape, jnp.float32, -limit, limit).astype(dtype)


@register("xavier_normal")
def xavier_normal(rng, shape, dtype=jnp.float32):
    fan_in, fan_out = _fans(shape)
    std = math.sqrt(2.0 / max(1, fan_in + fan_out))
    return (jax.random.normal(rng, shape, jnp.float32) * std).astype(dtype)


@register("xavier_uniform")
def xavier_uniform(rng, shape, dtype=jnp.float32):
    fan_in, fan_out = _fans(shape)
    limit = math.sqrt(6.0 / max(1, fan_in + fan_out))
    return jax.random.uniform(rng, shape, jnp.float32, -limit, limit).astype(dtype)


@register("normal")
def normal(rng, shape, dtype=jnp.float32):
    return (jax.random.normal(rng, shape, jnp.float32) * 0.02).astype(dtype)


def scaled_normal(std: float) -> Initializer:
    def fn(rng, shape, dtype=jnp.float32):
        return (jax.random.normal(rng, shape, jnp.float32) * std).astype(dtype)

    fn.init_name = "normal"
    return fn
