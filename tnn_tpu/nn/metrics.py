"""Metrics.

Parity: reference accuracy (compute_class_corrects argmax-match, include/nn/accuracy.hpp:14-38,
CPU+CUDA kernels in accuracy_impl/). Pure jnp; composes into the jit'd eval step.

Integer labels < 0 mark ignored positions (padding) and are excluded from both the
numerator and denominator — consistent with losses.softmax_cross_entropy's mask.
"""
from __future__ import annotations

import jax.numpy as jnp


def _labels_mask(labels, class_ndim):
    """Collapse one-hot labels and derive the ignore mask (integer labels < 0)."""
    if labels.ndim == class_ndim + 1:
        labels = jnp.argmax(labels, axis=-1)
        mask = jnp.ones(labels.shape, jnp.bool_)
    elif jnp.issubdtype(labels.dtype, jnp.integer):
        mask = labels >= 0
    else:
        mask = jnp.ones(labels.shape, jnp.bool_)
    return labels, mask


def class_corrects(logits, labels) -> jnp.ndarray:
    """Number of argmax matches (parity: compute_class_corrects, accuracy.hpp:14)."""
    pred = jnp.argmax(logits, axis=-1)
    labels, mask = _labels_mask(labels, pred.ndim)
    return jnp.sum((pred == labels) & mask, dtype=jnp.int32)


def accuracy(logits, labels) -> jnp.ndarray:
    pred = jnp.argmax(logits, axis=-1)
    labels, mask = _labels_mask(labels, pred.ndim)
    return jnp.sum((pred == labels) & mask, dtype=jnp.float32) / jnp.maximum(
        jnp.sum(mask, dtype=jnp.float32), 1.0)


def topk_accuracy(logits, labels, k: int = 5) -> jnp.ndarray:
    labels, mask = _labels_mask(labels, logits.ndim - 1)
    topk = jnp.argsort(logits, axis=-1)[..., -k:]
    hit = jnp.any(topk == labels[..., None], axis=-1)
    return jnp.sum(hit & mask, dtype=jnp.float32) / jnp.maximum(
        jnp.sum(mask, dtype=jnp.float32), 1.0)


def perplexity(mean_nll) -> jnp.ndarray:
    return jnp.exp(mean_nll)
