"""Metrics.

Parity: reference accuracy (compute_class_corrects argmax-match, include/nn/accuracy.hpp:14-38,
CPU+CUDA kernels in accuracy_impl/). Pure jnp; composes into the jit'd eval step.
"""
from __future__ import annotations

import jax.numpy as jnp


def class_corrects(logits, labels) -> jnp.ndarray:
    """Number of argmax matches (parity: compute_class_corrects, accuracy.hpp:14)."""
    pred = jnp.argmax(logits, axis=-1)
    if labels.ndim == pred.ndim + 1:
        labels = jnp.argmax(labels, axis=-1)
    return jnp.sum((pred == labels).astype(jnp.int32))


def accuracy(logits, labels) -> jnp.ndarray:
    pred = jnp.argmax(logits, axis=-1)
    if labels.ndim == pred.ndim + 1:
        labels = jnp.argmax(labels, axis=-1)
    return jnp.mean((pred == labels).astype(jnp.float32))


def topk_accuracy(logits, labels, k: int = 5) -> jnp.ndarray:
    if labels.ndim == logits.ndim:
        labels = jnp.argmax(labels, axis=-1)
    topk = jnp.argsort(logits, axis=-1)[..., -k:]
    hit = jnp.any(topk == labels[..., None], axis=-1)
    return jnp.mean(hit.astype(jnp.float32))


def perplexity(mean_nll) -> jnp.ndarray:
    return jnp.exp(mean_nll)
