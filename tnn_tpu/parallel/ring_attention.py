"""Ring attention — sequence/context parallelism over the "seq" mesh axis.

Beyond the reference: TNN has NO sequence/context parallelism of any kind (verified in
SURVEY.md §5 — its long-context story is single-device flash attention at fixed
seq_len=1024). Here sequences shard over devices; K/V blocks rotate around the ring via
collective-permute over ICI while each device accumulates its queries' attention with
online softmax (the flash-attention recurrence across devices). Memory per device is
O(S/ring); the full sequence never materialises anywhere.

Differentiable: built from jnp ops + ppermute, so jax.grad produces the reverse ring.
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from ..ops import softmax_merge
from . import mesh as mesh_lib


def _ring_attention_local(q, k, v, *, axis: str, causal: bool, scale: float):
    """Per-device body under shard_map. q: (B, H, S_local, D); k/v may carry
    H_kv < H heads (GQA) — the blocks ROTATE at H_kv size (the ICI-traffic
    win scales with the cache shrink) and repeat to H only at compute."""
    ring = mesh_lib.mapped_axis_size(axis)
    idx = jax.lax.axis_index(axis)
    s_local = q.shape[-2]
    group = q.shape[1] // k.shape[1]

    qpos = (idx * s_local + jnp.arange(s_local))[:, None]  # global query positions

    b, h, s, d = q.shape
    m0 = jnp.full((b, h, s, 1), -1e30, jnp.float32)
    l0 = jnp.zeros((b, h, s, 1), jnp.float32)
    acc0 = jnp.zeros((b, h, s, d), jnp.float32)

    perm = [(i, (i + 1) % ring) for i in range(ring)]

    def attend(m_prev, l_prev, acc, k_blk, v_blk, r):
        """One online-softmax block update against the K/V block held after r hops."""
        # after r hops this device holds the block originally owned by (idx - r) % ring
        owner = jnp.mod(idx - r, ring)
        if group > 1:  # GQA: broadcast kv heads at compute (XLA folds it)
            k_blk = jnp.repeat(k_blk, group, axis=1)
            v_blk = jnp.repeat(v_blk, group, axis=1)
        logits = jnp.einsum("bhqd,bhkd->bhqk", q, k_blk,
                            preferred_element_type=jnp.float32) * scale
        if causal:
            kpos = (owner * s_local + jnp.arange(s_local))[None, :]
            logits = jnp.where(qpos >= kpos, logits, -1e30)
        # the online-softmax recurrence lives in ops.softmax_merge — the
        # single source of the partitioned-attention math, shared with the
        # sequence-parallel serving combine (serving/sp.py)
        return softmax_merge.block_update(m_prev, l_prev, acc, logits, v_blk)

    def block(carry, r):
        # lax.scan (not a Python loop): one compiled body regardless of ring size,
        # so compile time stays flat as the ring grows.
        m_prev, l_prev, acc, k_blk, v_blk = carry
        m_new, l_new, acc = attend(m_prev, l_prev, acc, k_blk, v_blk, r)
        k_blk = jax.lax.ppermute(k_blk, axis, perm)
        v_blk = jax.lax.ppermute(v_blk, axis, perm)
        return (m_new, l_new, acc, k_blk, v_blk), None

    # Scan the first ring-1 blocks (each ending with a K/V hop); the final block
    # attends outside the scan so no ICI hop is wasted shipping K/V a full circle.
    (m, l, acc, k_last, v_last), _ = jax.lax.scan(
        block, (m0, l0, acc0, k, v), jnp.arange(ring - 1))
    m, l, acc = attend(m, l, acc, k_last, v_last, ring - 1)
    return softmax_merge.finalize(m, l, acc, q.dtype)


def ring_attention(q, k, v, mesh: Mesh, *, axis: str = "seq", causal: bool = False,
                   scale: Optional[float] = None, batch_axis: Optional[str] = None):
    """Attention over (B, H, S, D) tensors whose S dim is sharded over ``axis``.

    Call with global arrays sharded P(None, None, axis, None); returns the same
    sharding. S must divide evenly by the ring size. ``batch_axis`` (one axis
    name or a tuple, e.g. ("data", "fsdp")) additionally shards the batch dim:
    each batch shard runs its own ring — without it, a batch-sharded input
    would be all-gathered at the shard_map boundary.
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    ring = mesh_lib.axis_size(mesh, axis)
    if q.shape[-2] % ring:
        raise ValueError(f"seq len {q.shape[-2]} not divisible by ring size {ring}")
    if q.shape[1] % k.shape[1] or v.shape[1] != k.shape[1]:
        raise ValueError(f"q has {q.shape[1]} heads but k/v have "
                         f"{k.shape[1]}/{v.shape[1]}; need H % H_kv == 0")
    body = functools.partial(_ring_attention_local, axis=axis, causal=causal, scale=scale)
    return mesh_lib.seq_shard_map(body, mesh, axis, batch_axis)(q, k, v)
