"""Data parallelism (+ FSDP parameter sharding).

Parity-and-beyond: the reference's DP mode ships full model configs to every worker and
steps each on its own grads with NO gradient all-reduce — replicas drift
(include/distributed/coordinator.hpp:37-40,414-416; SURVEY.md §2.4 flags this as a gap).
Here DP is the textbook-correct version: batch sharded over the "data" axis, parameters
replicated (or sharded over "fsdp"), and XLA/GSPMD inserts the gradient all-reduce over
ICI automatically because the output sharding of params is replicated.

Everything is sharding annotations on the SAME jitted train step — no separate
distributed code path (the reference needs coordinator+worker+wire-format machinery,
~4.4k LoC; SURVEY.md §2.4).
"""
from __future__ import annotations

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..train.step import TrainState
from . import mesh as mesh_lib


def place_by_specs(params, mesh: Mesh, specs):
    """device_put every leaf per its PartitionSpec — the one placement map
    behind shard_params_fsdp/shard_params_tp/merged place_state."""
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, specs)


def shard_params_fsdp(params, mesh: Mesh, min_size: int = 2 ** 16):
    """ZeRO-3-style sharding: split each large param's first divisible dim over "fsdp".

    Small params stay replicated (collective overhead beats memory win).
    """
    return place_by_specs(params, mesh, fsdp_spec_tree(params, mesh, min_size))


def fsdp_spec_tree(params, mesh: Mesh, min_size: int = 2 ** 16):
    fsdp = mesh_lib.axis_size(mesh, "fsdp")

    def spec_for(x):
        if fsdp <= 1 or x.size < min_size:
            return P()
        for dim, d in enumerate(x.shape):
            if d % fsdp == 0:
                spec = [None] * x.ndim
                spec[dim] = "fsdp"
                return P(*spec)
        return P()

    return jax.tree_util.tree_map(spec_for, params)


def make_dp_train_step(model, optimizer, mesh: Mesh, loss_fn="softmax_cross_entropy",
                       scheduler=None, fsdp: bool = False, donate: bool = True,
                       tp: bool = False, ep: bool = False, **step_kw):
    """Build a data-parallel train step over ``mesh``.

    Returns (step, place_state, place_batch):
      step(state, data, labels) -> (state, metrics) — jitted with shardings
      place_state(state) -> state placed per the chosen param strategy
      place_batch(data, labels) -> batch sharded over the data axis

    ``tp=True`` shards transformer params over the "model" axis per the
    Megatron rules in tensor_parallel.py; ``ep=True`` shards MoE expert stacks
    over the "expert" axis; ``fsdp=True`` splits remaining large params over
    "fsdp". The strategies COMPOSE: per-leaf specs from each enabled rule set
    are merged (first non-replicated spec wins, in tp -> ep -> fsdp order) and
    applied in one placement pass; GSPMD then propagates the activation
    shardings and inserts the collectives (beyond the reference, which has
    none of tp/ep/fsdp).

    Extra keyword args (grad_accum, augment, ...) pass through to make_train_step.
    """
    from ..train.step import make_train_step

    step = make_train_step(model, optimizer, loss_fn=loss_fn, scheduler=scheduler,
                           donate=donate, **step_kw)
    batch_sharding = NamedSharding(mesh, P(("data", "fsdp") if fsdp else "data"))
    repl = mesh_lib.replicated(mesh)

    def place_state(state: TrainState) -> TrainState:
        if fsdp or tp or ep:
            spec_trees = []
            if tp:
                from .tensor_parallel import spec_tree

                spec_trees.append(spec_tree(state.params))
            if ep:
                from ..nn.moe import ep_rules
                from .tensor_parallel import spec_tree

                spec_trees.append(spec_tree(state.params, ep_rules()))
            if fsdp:
                spec_trees.append(fsdp_spec_tree(state.params, mesh))

            def merge(*specs):
                for s in specs:
                    if s != P():
                        return s
                return P()

            merged = jax.tree_util.tree_map(
                merge, *spec_trees, is_leaf=lambda x: isinstance(x, P))
            params = place_by_specs(state.params, mesh, merged)
            # moments follow their param's sharding where shapes match
            opt_state = _match_opt_sharding(state.opt_state, params, mesh)
            return TrainState(params, opt_state, jax.device_put(state.net_state, repl),
                              jax.device_put(state.step, repl),
                              jax.device_put(state.rng, repl))
        return jax.device_put(state, repl)

    def place_batch(data, labels):
        return (jax.device_put(data, batch_sharding),
                jax.device_put(labels, batch_sharding))

    def wrapped(state, data, labels):
        with mesh:
            return step(state, data, labels)

    return wrapped, place_state, place_batch


def _match_opt_sharding(opt_state, params, mesh: Mesh):
    """Give optimizer moments the same sharding as their parameter when the pytree
    structure mirrors params (velocity/m/v/vmax); everything else replicated."""
    repl = mesh_lib.replicated(mesh)
    param_leaves = jax.tree_util.tree_leaves(params)
    shard_by_shape = {}
    for leaf in param_leaves:
        shard_by_shape.setdefault(leaf.shape, leaf.sharding)

    def place(x):
        sh = shard_by_shape.get(x.shape)
        return jax.device_put(x, sh if sh is not None else repl)

    return jax.tree_util.tree_map(place, opt_state)
