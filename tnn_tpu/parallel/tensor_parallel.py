"""Tensor (model) parallelism via GSPMD sharding rules.

Beyond the reference: TNN has no tensor parallelism (SURVEY.md preamble). On TPU,
Megatron-style TP is expressed as sharding annotations over the "model" mesh axis —
column-parallel for qkv/fc-in kernels, row-parallel for out/fc-proj kernels — and GSPMD
inserts the all-reduces over ICI. No custom kernels or communication code.

Rules are (regex on the param path) -> PartitionSpec, applied to any model's param
pytree — the same mechanism t5x/maxtext use, fitted to this framework's param naming.

The SERVING side reuses this exact layout (column-parallel qkv/fc, row-parallel
out/proj, two all-reduces per layer) but not this module: ``serving/tp.py`` builds
explicit ``shard_map`` step bodies instead of GSPMD annotations, because the engine
needs donation of the head-sharded paged KV pool and a compile key per geometry —
see docs/serving.md "Tensor-parallel serving". Training TP rules and serving TP
shards agree on the "model" axis semantics, so a checkpoint sharded here loads
there unchanged.
"""
from __future__ import annotations

import re
from typing import Dict, List, Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.module import _path_str

# Default rules for this framework's layer naming (ordered; first match wins).
# Transformer blocks: qkv/fc column-parallel (shard output dim), out/proj row-parallel
# (shard input dim). Embedding table sharded over vocab (output head all-reduces).
DEFAULT_TP_RULES: List[Tuple[str, P]] = [
    (r".*attn/qkv_kernel$", P(None, "model")),
    (r".*attn/qkv_bias$", P("model")),
    (r".*attn/out_kernel$", P("model", None)),
    (r".*attn/out_bias$", P()),
    (r".*fc/kernel$", P(None, "model")),
    (r".*fc/bias$", P("model")),
    (r".*proj/kernel$", P("model", None)),
    (r".*proj/bias$", P()),
    # Llama SwiGLU MLP: gate/up column-parallel, down row-parallel — the
    # silu(gate) * up product stays shard-local, one all-reduce after down.
    # The lookbehind keeps the MoE ROUTER gate (".../moe/gate/kernel") out —
    # it must replicate (nn/moe.py ep_rules invariant) — and the required
    # path prefix (.+/) keeps a BARE param tree (top-level "gate/kernel",
    # e.g. spec_tree on a standalone MoE module) at the replicated default.
    (r".+/(?<!moe/)gate/kernel$", P(None, "model")),
    (r".+/up/kernel$", P(None, "model")),
    (r".+/down/kernel$", P("model", None)),
    (r".*wte/table$", P("model", None)),
    (r".*embedding/table$", P("model", None)),
]


def spec_tree(params, rules: Optional[Sequence[Tuple[str, P]]] = None):
    """Map a param pytree to a pytree of PartitionSpecs via path-regex rules."""
    rules = list(rules) if rules is not None else DEFAULT_TP_RULES
    compiled = [(re.compile(pat), spec) for pat, spec in rules]
    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    out = []
    for path, _ in flat:
        key = "/".join(_path_str(p) for p in path)
        out.append(next((spec for pat, spec in compiled if pat.match(key)), P()))
    return jax.tree_util.tree_unflatten(treedef, out)


def shard_params_tp(params, mesh: Mesh, rules=None):
    """Place params per the TP rules; un-matched params replicate."""
    from .data_parallel import place_by_specs

    return place_by_specs(params, mesh, spec_tree(params, rules))


def logical_constraint(x, mesh: Mesh, spec: P):
    """Mid-computation sharding hint (activation annotations)."""
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))
