"""Pipeline parallelism.

Parity-and-beyond with the reference's microbatch pipeline runtime
(docs/pipeline_architecture.md; Coordinator chain wiring coordinator.hpp:418-433; Worker
FORWARD_JOB/BACKWARD_JOB loop worker.hpp:145-193; Job{tensor, mb_id} job.hpp:93-129).

Two TPU-native implementations:

1. ``spmd_pipeline`` — the performance path. Stages are a stacked pytree of
   identical-structure block params sharded over the "pipe" mesh axis; the GPipe
   fill/drain schedule is a lax.scan over ticks inside shard_map, activations hop
   stages via collective-permute over ICI. jax.grad straight through it yields the
   backward pipeline automatically (ppermute transposes to the reverse hop) — no
   hand-written BACKWARD_JOB protocol. One compiled XLA program, zero host round trips
   per microbatch (the reference serializes every hop through TCP/RDMA).

2. ``StagePipeline`` — the generality path, mirroring the reference's
   coordinator/worker shape for heterogeneous stages: each stage is a separate jitted
   program placed on its own device; microbatches flow via device-to-device transfers;
   JAX's async dispatch overlaps stages like the reference's semi-async schedule.
   Activation residuals are held by jax.vjp closures — the analog of the reference's
   per-mb layer caches (include/nn/layer.hpp:113-114).
"""
from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from . import mesh as mesh_lib


# ---------------------------------------------------------------------------
# 1. Compiled SPMD pipeline (shard_map + ppermute + scan)
# ---------------------------------------------------------------------------


def spmd_pipeline(block_fn: Callable, stacked_params, x_microbatches, mesh: Mesh,
                  axis: str = "pipe"):
    """Run microbatches through a chain of identical-structure stages.

    Args:
      block_fn: (stage_params, activation) -> activation. stage_params is one slice of
        ``stacked_params`` along its leading axis (a stage may hold several layers —
        stack them inside and scan in block_fn).
      stacked_params: pytree; every leaf has leading dim == mesh pipe size.
      x_microbatches: (num_mb, mb_size, ...) inputs to stage 0.
      mesh: mesh containing ``axis``.

    Returns: (num_mb, mb_size, ...) outputs of the last stage.
    Differentiable end-to-end.
    """
    pp = mesh_lib.axis_size(mesh, axis)
    num_mb = x_microbatches.shape[0]
    if num_mb < 1:
        raise ValueError("need at least one microbatch")
    # activation dtype/shape between stages = block output (stages are homogeneous)
    stage0 = jax.tree_util.tree_map(lambda a: a[0], stacked_params)
    act = jax.eval_shape(block_fn, stage0, jax.ShapeDtypeStruct(
        x_microbatches.shape[1:], x_microbatches.dtype))
    if act.shape != x_microbatches.shape[1:]:
        raise ValueError(f"pipeline stages must preserve activation shape, got "
                         f"{x_microbatches.shape[1:]} -> {act.shape}")

    def per_device(params, xs):
        # shard_map keeps the sharded leading dim at local size 1 — drop it
        params = jax.tree_util.tree_map(lambda a: a[0], params)
        # xs: full microbatch queue (replicated)
        stage = jax.lax.axis_index(axis)
        mb_shape = xs.shape[1:]
        zero = jnp.zeros(mb_shape, act.dtype)
        outputs0 = jnp.zeros((num_mb,) + mb_shape, act.dtype)

        def tick(carry, t):
            recv, outputs = carry
            inject = xs[jnp.minimum(t, num_mb - 1)].astype(act.dtype)
            inp = jnp.where(stage == 0, inject, recv)
            out = block_fn(params, inp).astype(act.dtype)
            # last stage: record mb (t - (pp-1)) when valid
            out_idx = t - (pp - 1)
            valid = jnp.logical_and(stage == pp - 1,
                                    jnp.logical_and(out_idx >= 0, out_idx < num_mb))
            idx = jnp.clip(out_idx, 0, num_mb - 1)
            cur = jax.lax.dynamic_index_in_dim(outputs, idx, 0, keepdims=False)
            upd = jnp.where(valid, out, cur)
            outputs = jax.lax.dynamic_update_index_in_dim(outputs, upd, idx, 0)
            # hop to the next stage over ICI
            perm = [(i, (i + 1) % pp) for i in range(pp)]
            recv = jax.lax.ppermute(out, axis, perm)
            return (recv, outputs), None

        (recv, outputs), _ = jax.lax.scan(
            tick, (zero, outputs0), jnp.arange(num_mb + pp - 1))
        return outputs[None]  # re-add pipe dim for out_specs

    in_specs = (jax.tree_util.tree_map(lambda _: P(axis), stacked_params), P())
    out_specs = P(axis)
    fn = jax.shard_map(per_device, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                       check_vma=False)
    stacked_out = fn(stacked_params, x_microbatches)  # (pp, num_mb, ...)
    return stacked_out[-1]


def stack_stage_params(per_stage_params: Sequence) -> Any:
    """Stack a list of identical-structure stage params into one pytree with a leading
    stage axis (the SPMD pipeline's input layout)."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *per_stage_params)


# ---------------------------------------------------------------------------
# 2. Host-orchestrated heterogeneous-stage pipeline
# ---------------------------------------------------------------------------


class StagePipeline:
    """Generic pipeline over heterogeneous stage modules, one device each.

    The TPU-native analog of the reference's coordinator+workers (SURVEY.md §3.2):
    CONFIG_TRANSFER -> constructor; FORWARD_JOB/BACKWARD_JOB -> jitted per-stage
    programs + async dispatch; TCP/RoCE hops -> jax.device_put over ICI.
    """

    def __init__(self, stages: Sequence, optimizer, loss_fn, devices=None,
                 train: bool = False):
        self.stages = list(stages)
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        devices = list(devices) if devices is not None else jax.devices()
        if len(devices) < len(self.stages):
            raise ValueError(f"{len(self.stages)} stages need as many devices, "
                             f"have {len(devices)}")
        self.devices = devices[:len(self.stages)]
        self.variables: List[Any] = []
        self.opt_states: List[Any] = []
        self._fwd = []
        for i, stage in enumerate(self.stages):
            # pure apply for vjp; BatchNorm runs in inference mode inside the pipeline.
            # net state is a real argument (closing over it would bake it into the
            # compiled program and ignore later updates).
            def apply_fn(params, net_state, x, stage=stage):
                out, _ = stage.apply({"params": params, "state": net_state},
                                     x, train=False)
                return out

            # params are committed to the stage's device, so the jitted program runs there
            self._fwd.append(jax.jit(apply_fn))

    def init(self, rng, input_shape, input_dtype=None):
        """Initialize every stage, placing its params on its device
        (parity: deploy_stages, coordinator.hpp:368)."""
        shape = tuple(input_shape)
        dtype = input_dtype
        self.variables, self.opt_states = [], []
        keys = jax.random.split(rng, len(self.stages))
        for i, stage in enumerate(self.stages):
            if dtype is not None:
                v = stage.init(keys[i], shape, input_dtype=dtype)
            else:
                v = stage.init(keys[i], shape)
            v = jax.device_put(v, self.devices[i])
            self.variables.append(v)
            self.opt_states.append(
                jax.device_put(self.optimizer.init(v["params"]), self.devices[i]))
            dummy = jax.ShapeDtypeStruct(tuple(shape), dtype or jnp.float32)
            out = jax.eval_shape(self._fwd[i], v["params"], v["state"], dummy)
            shape, dtype = out.shape, out.dtype
        return self

    def forward(self, x):
        """Inference pass: microbatch-free, stage hop = device transfer."""
        for i in range(len(self.stages)):
            x = jax.device_put(x, self.devices[i])
            x = self._fwd[i](self.variables[i]["params"], self.variables[i]["state"], x)
        return x

    def train_batch(self, data, labels, num_microbatches: int = 4):
        """One training step: GPipe fill/drain with gradient accumulation
        (parity: async_train_batch, coordinator.hpp:165-223 + distributed/train.hpp:19-79).

        Async dispatch overlaps stage work across microbatches without explicit
        scheduling — the queueing the reference does by hand.
        """
        n = len(self.stages)
        mbs = jnp.split(data, num_microbatches)
        lbs = jnp.split(labels, num_microbatches)
        grads = [None] * n

        # fill: forward all microbatches, keeping vjp closures (activation residuals)
        vjps = []  # [mb][stage]
        outs = []
        for mb in mbs:
            stage_vjps = []
            x = mb
            for i in range(n):
                x = jax.device_put(x, self.devices[i])
                fwd, st = self._fwd[i], self.variables[i]["state"]
                x, vjp = jax.vjp(lambda p, xx, fwd=fwd, st=st: fwd(p, st, xx),
                                 self.variables[i]["params"], x)
                stage_vjps.append(vjp)
            vjps.append(stage_vjps)
            outs.append(x)

        # drain: loss grad per microbatch, backward through stages in reverse
        scale = 1.0 / num_microbatches
        losses = []
        for out, lb, stage_vjps in zip(outs, lbs, vjps):
            lb = jax.device_put(lb, self.devices[-1])
            loss, loss_vjp = jax.vjp(lambda o: self.loss_fn(o, lb), out)
            losses.append(loss)  # keep on device — a float() here would stall the pipeline
            (g,) = loss_vjp(jnp.asarray(scale, jnp.float32))
            for i in reversed(range(n)):
                g = jax.device_put(g, self.devices[i])
                gp, g = stage_vjps[i](g)
                grads[i] = gp if grads[i] is None else jax.tree_util.tree_map(
                    jnp.add, grads[i], gp)

        # optimizer step per stage (parity: UPDATE_PARAMETERS, worker.hpp:194-207)
        for i in range(n):
            new_params, self.opt_states[i] = self.optimizer.update(
                grads[i], self.opt_states[i], self.variables[i]["params"])
            self.variables[i] = {"params": new_params, "state": self.variables[i]["state"]}
        return float(sum(float(l) for l in losses) * scale)
