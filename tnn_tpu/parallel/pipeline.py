"""Pipeline parallelism.

Parity-and-beyond with the reference's microbatch pipeline runtime
(docs/pipeline_architecture.md; Coordinator chain wiring coordinator.hpp:418-433; Worker
FORWARD_JOB/BACKWARD_JOB loop worker.hpp:145-193; Job{tensor, mb_id} job.hpp:93-129).

Three TPU-native implementations:

1. ``spmd_pipeline`` — homogeneous stages (stacked identical-structure params
   sharded over the "pipe" axis); GPipe fill/drain as a lax.scan inside shard_map
   with ppermute hops. jax.grad straight through it yields the backward pipeline.

2. ``HeteroPipeline`` / ``make_pipeline_train_step`` — the flagship path:
   ARBITRARY heterogeneous stages (shape-changing conv groups, different param
   structures) in ONE compiled SPMD program. Per-stage params/state are packed
   into padded f32 rows stacked over the pipe axis; activations hop as padded
   flat buffers over ICI; lax.switch on the stage index runs each device's own
   decode -> stage.apply -> encode. BatchNorm statistics update correctly under
   pipelining: each stage's packed net_state threads through the schedule scan
   and is committed only on ticks where that stage processed a real microbatch,
   reproducing the per-microbatch BN semantics of single-device gradient
   accumulation exactly. This is the capability the reference runs as its
   headline distributed benchmark (WRN-16-8 CIFAR-100 through a multi-stage
   pipeline, sample_logs/cifar100_wrn16_8) — there via per-hop TCP/RDMA
   serialization, here as one XLA program with zero host round trips.

3. ``StagePipeline`` — the generality path mirroring the reference's
   coordinator/worker shape: each stage a separate jitted program on its own
   device, microbatches flowing via device-to-device transfers, JAX async
   dispatch overlapping stages like the reference's semi-async schedule.
"""
from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import mesh as mesh_lib


# ---------------------------------------------------------------------------
# 1. Compiled SPMD pipeline (shard_map + ppermute + scan)
# ---------------------------------------------------------------------------


def _homogeneous_pipeline_setup(block_fn, stacked_params, x_microbatches,
                                mesh: Mesh, axis: str):
    """Shared validation + activation-shape inference for the homogeneous
    compiled pipelines (spmd_pipeline / spmd_pipeline_interleaved).

    Returns (pp, num_mb, act) where ``act`` is the per-microbatch activation
    ShapeDtypeStruct every stage must preserve."""
    pp = mesh_lib.axis_size(mesh, axis)
    num_mb = x_microbatches.shape[0]
    if num_mb < 1:
        raise ValueError("need at least one microbatch")
    stage0 = jax.tree_util.tree_map(lambda a: a[0], stacked_params)
    act = jax.eval_shape(block_fn, stage0, jax.ShapeDtypeStruct(
        x_microbatches.shape[1:], x_microbatches.dtype))
    if act.shape != x_microbatches.shape[1:]:
        raise ValueError(f"pipeline stages must preserve activation shape, got "
                         f"{x_microbatches.shape[1:]} -> {act.shape}")
    return pp, num_mb, act


def spmd_pipeline(block_fn: Callable, stacked_params, x_microbatches, mesh: Mesh,
                  axis: str = "pipe"):
    """Run microbatches through a chain of identical-structure stages.

    Args:
      block_fn: (stage_params, activation) -> activation. stage_params is one slice of
        ``stacked_params`` along its leading axis (a stage may hold several layers —
        stack them inside and scan in block_fn).
      stacked_params: pytree; every leaf has leading dim == mesh pipe size.
      x_microbatches: (num_mb, mb_size, ...) inputs to stage 0.
      mesh: mesh containing ``axis``.

    Returns: (num_mb, mb_size, ...) outputs of the last stage.
    Differentiable end-to-end.
    """
    pp, num_mb, act = _homogeneous_pipeline_setup(
        block_fn, stacked_params, x_microbatches, mesh, axis)

    def per_device(params, xs):
        # shard_map keeps the sharded leading dim at local size 1 — drop it
        params = jax.tree_util.tree_map(lambda a: a[0], params)
        # xs: full microbatch queue (replicated)
        stage = jax.lax.axis_index(axis)
        mb_shape = xs.shape[1:]
        zero = jnp.zeros(mb_shape, act.dtype)
        outputs0 = jnp.zeros((num_mb,) + mb_shape, act.dtype)

        def tick(carry, t):
            recv, outputs = carry
            inject = xs[jnp.minimum(t, num_mb - 1)].astype(act.dtype)
            inp = jnp.where(stage == 0, inject, recv)
            out = block_fn(params, inp).astype(act.dtype)
            # last stage: record mb (t - (pp-1)) when valid
            out_idx = t - (pp - 1)
            valid = jnp.logical_and(stage == pp - 1,
                                    jnp.logical_and(out_idx >= 0, out_idx < num_mb))
            idx = jnp.clip(out_idx, 0, num_mb - 1)
            cur = jax.lax.dynamic_index_in_dim(outputs, idx, 0, keepdims=False)
            upd = jnp.where(valid, out, cur)
            outputs = jax.lax.dynamic_update_index_in_dim(outputs, upd, idx, 0)
            # hop to the next stage over ICI
            perm = [(i, (i + 1) % pp) for i in range(pp)]
            recv = jax.lax.ppermute(out, axis, perm)
            return (recv, outputs), None

        (recv, outputs), _ = jax.lax.scan(
            tick, (zero, outputs0), jnp.arange(num_mb + pp - 1))
        return outputs[None]  # re-add pipe dim for out_specs

    in_specs = (jax.tree_util.tree_map(lambda _: P(axis), stacked_params), P())
    out_specs = P(axis)
    fn = mesh_lib.shard_map(per_device, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                       check_vma=False)
    stacked_out = fn(stacked_params, x_microbatches)  # (pp, num_mb, ...)
    return stacked_out[-1]


def stack_stage_params(per_stage_params: Sequence) -> Any:
    """Stack a list of identical-structure stage params into one pytree with a leading
    stage axis (the SPMD pipeline's input layout)."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *per_stage_params)


def spmd_pipeline_interleaved(block_fn: Callable, stacked_params, x_microbatches,
                              mesh: Mesh, axis: str = "pipe",
                              virtual: int = 2):
    """Interleaved (Megatron-style) schedule: beats the plain GPipe bubble.

    The reference's best schedule is semi-async 1F1B (coordinator.hpp:165-223),
    whose bubble equals GPipe's — only INTERLEAVING virtual stages shrinks it.
    Here the L = virtual*pp stages place round-robin (stage s on device s%pp),
    so each device holds ``virtual`` chunks of 1/v the work; the bubble drops
    from (pp-1)*T to (pp-1)*T/v.

    This maps onto a compiled lockstep scan because the interleaved forward
    schedule is TIGHT: with sub-tick
        tau(s=c*pp+d, m) = d + (m %% pp) + pp*(c + v*(m // pp))
    every stage's input arrives over ICI exactly at the sub-tick it is
    consumed (the chunk-boundary hop d=pp-1 -> d=0 has slack 1, same as the
    in-chunk hop), so no inter-stage queues exist — one ppermute per sub-tick
    and a dynamic chunk-select per device. jax.grad transposes the scan into
    the interleaved backward.

    Args mirror ``spmd_pipeline`` with ``stacked_params`` leading dim
    L = virtual * pp (stage s params at index s). num_mb must be a multiple
    of pp (Megatron's constraint — the round-robin rounds must fill).
    """
    v = int(virtual)
    pp, num_mb, act = _homogeneous_pipeline_setup(
        block_fn, stacked_params, x_microbatches, mesh, axis)
    L = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    if v < 1 or L != v * pp:
        raise ValueError(f"stacked_params leading dim {L} != virtual {v} * pipe {pp}")
    if num_mb % pp:
        raise ValueError(f"interleaved schedule needs num_microbatches "
                         f"({num_mb}) divisible by pipe size ({pp})")
    # round-robin placement: device d's local chunk c is global stage c*pp + d,
    # so re-order rows to (d*v + c) before sharding the leading axis over pp
    order = np.argsort([(s % pp) * v + s // pp for s in range(L)], kind="stable")
    placed = jax.tree_util.tree_map(lambda a: a[order], stacked_params)
    # last sub-tick: stage L-1 = (c=v-1, d=pp-1) processing mb num_mb-1
    n_ticks = ((pp - 1) + ((num_mb - 1) % pp)
               + pp * ((v - 1) + v * ((num_mb - 1) // pp)) + 1)

    def per_device(params, xs):
        # local params: (v, ...) — this device's chunks; chunk c = stage c*pp+d
        d = jax.lax.axis_index(axis)
        mb_shape = xs.shape[1:]
        outputs0 = jnp.zeros((num_mb,) + mb_shape, act.dtype)
        zero = jnp.zeros(mb_shape, act.dtype)
        perm = [(i, (i + 1) % pp) for i in range(pp)]

        def tick(carry, u):
            recv, outputs = carry
            # invert tau: which (chunk c, microbatch m) does device d run now?
            w = u - d
            q, j = w // pp, jnp.mod(w, pp)
            c = jnp.mod(q, v)
            m = (q // v) * pp + j
            active = jnp.logical_and(w >= 0, m < num_mb)
            m_idx = jnp.clip(m, 0, num_mb - 1)
            inject = jnp.logical_and(c == 0, d == 0)
            inp = jnp.where(inject, xs[m_idx].astype(act.dtype), recv)
            chunk = jax.tree_util.tree_map(
                lambda a: jax.lax.dynamic_index_in_dim(a, c, 0, keepdims=False),
                params)
            out = block_fn(chunk, inp).astype(act.dtype)
            emit = jnp.logical_and(active,
                                   jnp.logical_and(c == v - 1, d == pp - 1))
            cur = jax.lax.dynamic_index_in_dim(outputs, m_idx, 0, keepdims=False)
            outputs = jax.lax.dynamic_update_index_in_dim(
                outputs, jnp.where(emit, out, cur), m_idx, 0)
            recv = jax.lax.ppermute(out, axis, perm)
            return (recv, outputs), None

        (recv, outputs), _ = jax.lax.scan(
            tick, (zero, outputs0), jnp.arange(n_ticks))
        return outputs[None]

    in_specs = (jax.tree_util.tree_map(lambda _: P(axis), placed), P())
    fn = mesh_lib.shard_map(per_device, mesh=mesh, in_specs=in_specs,
                       out_specs=P(axis), check_vma=False)
    stacked_out = fn(placed, x_microbatches)  # (pp, num_mb, ...)
    return stacked_out[-1]


# ---------------------------------------------------------------------------
# 2. Compiled heterogeneous-stage pipeline (shape-changing stages, correct BN)
# ---------------------------------------------------------------------------


class _TreeCodec:
    """Pack/unpack a fixed-structure pytree into one flat f32 vector.

    Static metadata (treedef + per-leaf shape/dtype/offset) is captured once at
    init; packing casts every leaf to f32 (lossless for f32/bf16 params and the
    f32 BatchNorm stats used here) so heterogeneous stage structures become
    uniform (pp, max_len) rows shardable over the pipe mesh axis.
    """

    def __init__(self, template):
        leaves, self.treedef = jax.tree_util.tree_flatten(template)
        self.info = []
        off = 0
        for leaf in leaves:
            n = int(np.prod(leaf.shape)) if leaf.shape else 1
            self.info.append((tuple(leaf.shape), jnp.dtype(leaf.dtype), off, n))
            off += n
        self.size = off

    def pack(self, tree, padded_len: int) -> jax.Array:
        leaves = jax.tree_util.tree_leaves(tree)
        if not leaves:
            return jnp.zeros((padded_len,), jnp.float32)
        vec = jnp.concatenate(
            [jnp.ravel(x).astype(jnp.float32) for x in leaves])
        return jnp.pad(vec, (0, padded_len - vec.shape[0]))

    def unpack(self, vec: jax.Array):
        leaves = [vec[o:o + n].reshape(shape).astype(dt)
                  for shape, dt, o, n in self.info]
        return jax.tree_util.tree_unflatten(self.treedef, leaves)


class HeteroPipeline:
    """Compile-time plan for a heterogeneous-stage SPMD pipeline.

    Built from a list of stage Modules (e.g. ``partitioner.partition_model``
    output). Owns the static metadata — per-stage activation shapes from shape
    propagation, packed param/state codecs, buffer sizes — and provides
    ``pipeline_loss``, the differentiable (packed_params, packed_state, data,
    labels, rng) -> (loss, aux) function whose jax.grad IS the backward
    pipeline (ppermute transposes to the reverse hop; the scan's saved
    residuals are the per-microbatch activation caches the reference keeps by
    hand, include/nn/layer.hpp:113-114).
    """

    def __init__(self, stages: Sequence, mesh: Mesh, input_shape,
                 input_dtype=jnp.bfloat16, num_microbatches: int = 4,
                 axis: str = "pipe", loss_fn: Optional[Callable] = None,
                 compute_accuracy: bool = True, data_axis: Optional[str] = None,
                 remat: "bool | str" = False, virtual: int = 1):
        from ..nn import losses as losses_lib

        self.stages = list(stages)
        self.mesh = mesh
        self.axis = axis
        self.pp = mesh_lib.axis_size(mesh, axis)
        self.v = int(virtual)
        # dp x pp in ONE program: the microbatch batch dim shards over the data
        # axis (each data rank pipelines its slice; grads auto-psum because the
        # params are replicated over data in the shard_map in_specs). The
        # reference offers dp OR pp per run, never composed — and its dp never
        # all-reduces (coordinator.hpp:37-40).
        self.data_axis = data_axis if (
            data_axis and mesh_lib.axis_size(mesh, data_axis) > 1) else None
        self.dp = mesh_lib.axis_size(mesh, data_axis) if self.data_axis else 1
        # input_shape is the per-microbatch GLOBAL shape; stages see local slices
        if self.dp > 1:
            if input_shape[0] % self.dp:
                raise ValueError(f"microbatch size {input_shape[0]} not "
                                 f"divisible by data axis {self.dp}")
            input_shape = (input_shape[0] // self.dp,) + tuple(input_shape[1:])
        if self.v * self.pp != len(self.stages):
            raise ValueError(f"{len(self.stages)} stages != virtual {self.v} "
                             f"x mesh {axis} size {self.pp}")
        self.L = len(self.stages)  # global stage count (v chunks per device)
        self.num_mb = int(num_microbatches)
        if self.v > 1 and self.num_mb % self.pp:
            raise ValueError(f"interleaved schedule needs num_microbatches "
                             f"({self.num_mb}) divisible by pipe ({self.pp})")
        # device-order row layout: row r = d*v + c holds global stage c*pp + d,
        # so sharding the leading axis over pipe gives device d its v chunks
        # contiguously (identity when v == 1)
        self._stage_of_row = [(r % self.v) * self.pp + r // self.v
                              for r in range(self.L)]
        if isinstance(loss_fn, (str, dict)) or loss_fn is None:
            loss_fn = losses_lib.get(loss_fn or "softmax_cross_entropy")
        self.loss_fn = loss_fn
        self.compute_accuracy = bool(compute_accuracy)
        # Schedule note: v == 1 is compiled lockstep GPipe — bubble fraction
        # (pp-1)/(num_mb+pp-1). Event-driven 1F1B (the reference's semi-async
        # schedule, coordinator.hpp:165-223) has the SAME bubble as GPipe; its
        # memory benefit comes here from ``remat=True`` (saved activations per
        # tick shrink to the hop buffers), and hops cost ~0 (ICI ppermute
        # inside one XLA program vs per-hop TCP/RDMA serialization), so
        # num_mb can be raised until the bubble vanishes. ``virtual=v > 1``
        # runs the interleaved (Megatron-style) schedule — device d holds the
        # v chunks c*pp+d, and the bubble drops to (pp-1)/v stage-times: with
        # sub-tick tau(s=c*pp+d, m) = d + (m%%pp) + pp*(c + v*(m//pp)) every
        # hop (in-chunk d->d+1 AND chunk-boundary pp-1->0) has slack exactly
        # 1, so one ppermute per sub-tick suffices and the whole schedule
        # stays a single compiled scan (same tightness argument as
        # ``spmd_pipeline_interleaved``, here with heterogeneous stages).
        # bool OR a policy name ("dots", ...) — resolved once here so a typo
        # raises at build time on this path too (train.step.resolve_remat_policy)
        self.remat = bool(remat)
        self._remat_policy = None
        if remat:
            from ..train.step import resolve_remat_policy

            self._remat_policy = resolve_remat_policy(remat)

        # shape propagation (parity: deploy_stages shape chain,
        # coordinator.hpp:368-456): microbatch-shaped activations per boundary
        self.in_shapes: List[Tuple[int, ...]] = []
        self.in_dtypes: List[Any] = []
        shape, dtype = tuple(input_shape), jnp.dtype(input_dtype)
        self._init_shape0 = shape
        rng0 = jax.random.PRNGKey(0)
        self._stage_vars_shape = []
        for stage in self.stages:
            self.in_shapes.append(shape)
            self.in_dtypes.append(dtype)
            v_shape = jax.eval_shape(
                lambda s=stage, sh=shape: s.init(rng0, sh))
            out = jax.eval_shape(
                lambda v, x, s=stage: s.apply(v, x, train=False)[0],
                v_shape, jax.ShapeDtypeStruct(shape, dtype))
            self._stage_vars_shape.append(v_shape)
            shape, dtype = out.shape, out.dtype
        self.out_shape, self.out_dtype = shape, dtype

        # packed-row codecs; rows padded to the widest stage
        self.p_codecs = [_TreeCodec(v["params"]) for v in self._stage_vars_shape]
        self.s_codecs = [_TreeCodec(v["state"]) for v in self._stage_vars_shape]
        self.p_len = max(max(c.size for c in self.p_codecs), 1)
        self.s_len = max(max(c.size for c in self.s_codecs), 1)
        # activation hop buffer: elements of the widest boundary, one dtype wide
        # enough for every boundary (bf16 boundaries stay bf16; mixed promotes)
        self.buf_elems = max(int(np.prod(s)) for s in self.in_shapes[1:] + [self.out_shape]) \
            if self.pp > 1 else int(np.prod(self.out_shape))
        self.buf_dtype = self.in_dtypes[1] if self.pp > 1 else self.out_dtype
        for d in self.in_dtypes[2:] + [self.out_dtype]:
            self.buf_dtype = jnp.promote_types(self.buf_dtype, d)
        # the stage-0 injection rides the same buffer: its dtype must survive
        # the round trip. Integer inputs (token ids) go through f32 — exact for
        # ids < 2^24 — because jax's lattice would otherwise pick bf16 and
        # silently round ids > 256.
        d0 = self.in_dtypes[0]
        if jnp.issubdtype(d0, jnp.integer):
            self.buf_dtype = jnp.promote_types(self.buf_dtype, jnp.float32)
        else:
            self.buf_dtype = jnp.promote_types(self.buf_dtype, d0)
        # stage-0 injection buffer must fit the raw input too
        self.buf_elems = max(self.buf_elems, int(np.prod(self.in_shapes[0])))

    # -- state management -----------------------------------------------------

    def init_packed(self, rng: jax.Array) -> Tuple[jax.Array, jax.Array]:
        """Initialize every stage and pack into ((L, p_len), (L, s_len)) rows
        in DEVICE order (row d*v + c = stage c*pp + d), placed sharded over
        the pipe axis."""
        keys = jax.random.split(rng, self.L)
        vars_by_stage = [stage.init(keys[i], self.in_shapes[i])
                         for i, stage in enumerate(self.stages)]
        return self.pack_stage_variables(vars_by_stage)

    def unpack_stage_variables(self, packed_params, packed_state) -> List[dict]:
        """Back to per-stage {"params", "state"} pytrees in GLOBAL stage order
        (checkpoint/export)."""
        pr = np.asarray(packed_params)
        sr = np.asarray(packed_state)
        out = [None] * self.L
        for r, s in enumerate(self._stage_of_row):
            out[s] = {"params": self.p_codecs[s].unpack(jnp.asarray(pr[r])),
                      "state": self.s_codecs[s].unpack(jnp.asarray(sr[r]))}
        return out

    def place_train_state(self, state):
        """Re-apply the pipe-axis sharding to a TrainState whose leaves lost
        placement (e.g. after a checkpoint restore loads host arrays)."""
        rows = NamedSharding(self.mesh, P(self.axis))

        def place(x):
            spec = P(self.axis) if getattr(x, "ndim", 0) >= 1 else P()
            return jax.device_put(x, NamedSharding(self.mesh, spec))

        return state._replace(
            params=jax.device_put(state.params, rows),
            opt_state=jax.tree_util.tree_map(place, state.opt_state),
            net_state=jax.device_put(state.net_state, rows))

    def pack_stage_variables(self, variables: Sequence[dict]):
        """Inverse of unpack: per-stage variables (global order) -> device-order
        packed rows (restore from a per-stage checkpoint)."""
        sharding = NamedSharding(self.mesh, P(self.axis))
        p = jnp.stack([self.p_codecs[s].pack(variables[s]["params"], self.p_len)
                       for s in self._stage_of_row])
        s_ = jnp.stack([self.s_codecs[s].pack(variables[s]["state"], self.s_len)
                        for s in self._stage_of_row])
        return jax.device_put(p, sharding), jax.device_put(s_, sharding)

    # -- the compiled schedule ------------------------------------------------

    def _encode(self, x) -> jax.Array:
        flat = jnp.ravel(x).astype(self.buf_dtype)
        return jnp.pad(flat, (0, self.buf_elems - flat.shape[0]))

    def _make_branch(self, i: int, train: bool):
        """Branch i of the per-tick lax.switch: decode this stage's input from
        the hop buffer, run the stage, encode the output, and (last stage only)
        compute loss/corrects against the tick's labels."""
        stage = self.stages[i]
        in_shape, in_dtype = self.in_shapes[i], self.in_dtypes[i]
        p_codec, s_codec = self.p_codecs[i], self.s_codecs[i]
        is_last = i == self.L - 1

        def run_stage(p_vec, s_vec, x, key):
            from ..train.step import aux_loss_sum

            variables = {"params": p_codec.unpack(p_vec),
                         "state": s_codec.unpack(s_vec)}
            out, new_state = stage.apply(variables, x, train=train, rng=key)
            # every stage reports its own aux losses (MoE load balancing,
            # nn/moe.py) — the schedule adds them to the training loss per
            # active microbatch, matching make_train_step's aux_loss_sum
            aux = aux_loss_sum(new_state) if train else jnp.zeros(
                (), jnp.float32)
            return out, s_codec.pack(new_state, self.s_len), aux

        if self.remat and train:
            if self._remat_policy is None:
                run_stage = jax.checkpoint(run_stage)
            else:
                run_stage = jax.checkpoint(run_stage,
                                           policy=self._remat_policy)

        def branch(p_vec, s_vec, buf, labels_mb, key):
            x = buf[:int(np.prod(in_shape))].reshape(in_shape).astype(in_dtype)
            out, new_s_vec, aux = run_stage(p_vec, s_vec, x, key)
            if is_last:
                loss = self.loss_fn(out, labels_mb).astype(jnp.float32) + aux
                if self.compute_accuracy:
                    from ..nn import metrics as metrics_lib

                    corr = metrics_lib.class_corrects(out, labels_mb).astype(
                        jnp.float32)
                else:
                    corr = jnp.zeros((), jnp.float32)
            else:
                loss = aux
                corr = jnp.zeros((), jnp.float32)
            return (self._vary(self._encode(out)), self._vary(new_s_vec),
                    self._vary(loss), self._vary(corr))

        return branch

    def _vary(self, x):
        """Join ``x``'s replication type to "varying over pipe (+data)".

        Under shard_map replication tracking (``check_rep=True`` on jax
        0.4.x), ``lax.switch`` requires every branch to produce identical
        replication types. Non-last branches return constant-zero
        loss/corrects (inferred replicated) while the last branch computes
        them from device-varying data — add a zero derived from
        ``axis_index`` so all branches agree. XLA folds the add away."""
        bump = jax.lax.axis_index(self.axis)
        if self.data_axis is not None:
            bump = bump + jax.lax.axis_index(self.data_axis)
        return x + (0 * bump).astype(x.dtype)

    def _prep(self, data, labels, train: bool):
        """Shared prologue: reshape the batch to (num_mb, mb_global, ...) and
        build the per-tick switch branches + tick count."""
        num_mb, pp, v = self.num_mb, self.pp, self.v
        mb = self.in_shapes[0][0]  # LOCAL microbatch size (per data shard)
        mb_global = mb * self.dp
        if data.shape[0] != num_mb:
            if data.shape[0] != num_mb * mb_global:
                raise ValueError(f"batch {data.shape[0]} != num_microbatches "
                                 f"{num_mb} x microbatch {mb_global}")
            data = data.reshape((num_mb, mb_global) + data.shape[1:])
            labels = labels.reshape((num_mb, mb_global) + labels.shape[1:])
        branches = [self._make_branch(i, train) for i in range(self.L)]
        if v == 1:
            n_ticks = num_mb + pp - 1
        else:
            # last sub-tick: stage L-1 = (c=v-1, d=pp-1) on microbatch num_mb-1
            n_ticks = ((pp - 1) + ((num_mb - 1) % pp)
                       + pp * ((v - 1) + v * ((num_mb - 1) // pp)) + 1)
        return data, labels, mb_global, branches, n_ticks

    def _device_schedule(self, branches, n_ticks, p_rows, s_rows, data_mb,
                         labels_mb, key):
        """The fill/drain schedule for ONE device; call inside shard_map.

        Returns (new state rows, loss sum, corrects sum) — data-axis
        reductions already applied, so all three are data-axis invariant."""
        num_mb, pp, axis, v = self.num_mb, self.pp, self.axis, self.v
        d = jax.lax.axis_index(axis)
        if self.data_axis is not None:
            # distinct dropout masks per data shard — without this every
            # shard would reuse the replicated key on different samples
            key = jax.random.fold_in(key, jax.lax.axis_index(self.data_axis))
        # encode all injected microbatches once (stage c=0, d=0 consumes)
        inject = jax.vmap(self._encode)(data_mb)

        def tick(carry, t):
            recv, s_rows_l, loss_acc, corr_acc = carry
            if v == 1:
                c = jnp.zeros((), jnp.int32)
                m = t - d
                active = jnp.logical_and(d <= t, m < num_mb)
            else:
                # invert tau: which (chunk c, microbatch m) runs now?
                w = t - d
                q, j = w // pp, jnp.mod(w, pp)
                c = jnp.mod(q, v)
                m = (q // v) * pp + j
                active = jnp.logical_and(w >= 0, m < num_mb)
            m_idx = jnp.clip(m, 0, num_mb - 1)
            inject_here = jnp.logical_and(c == 0, d == 0)
            inp = jnp.where(inject_here, inject[m_idx], recv)
            s_vec = jax.lax.dynamic_index_in_dim(s_rows_l, c, 0,
                                                 keepdims=False)
            p_vec = jax.lax.dynamic_index_in_dim(p_rows, c, 0,
                                                 keepdims=False)
            gstage = c * pp + d
            key_t = jax.random.fold_in(jax.random.fold_in(key, t), gstage)
            out_buf, new_s, loss, corr = jax.lax.switch(
                gstage, branches, p_vec, s_vec, inp, labels_mb[m_idx],
                key_t)
            # a stage holds a real microbatch only during its active window;
            # outside it the input is schedule garbage — state/loss must not
            # absorb it (this is what keeps BatchNorm statistics exact)
            s_rows_l = jax.lax.dynamic_update_index_in_dim(
                s_rows_l, jnp.where(active, new_s, s_vec), c, 0)
            # every ACTIVE stage contributes (non-last stages return their
            # aux losses only — 0 unless the stage carries MoE routing);
            # accuracy still comes from the emitting last stage alone
            emit = jnp.logical_and(
                active, jnp.logical_and(d == pp - 1, c == v - 1))
            loss_acc = loss_acc + jnp.where(active, loss, 0.0)
            corr_acc = corr_acc + jnp.where(emit, corr, 0.0)
            perm = [(i, (i + 1) % pp) for i in range(pp)]
            recv = jax.lax.ppermute(out_buf, axis, perm)
            return (recv, s_rows_l, loss_acc, corr_acc), None

        zero_buf = jnp.zeros((self.buf_elems,), self.buf_dtype)
        (recv, s_rows_l, loss_acc, corr_acc), _ = jax.lax.scan(
            tick, (zero_buf, s_rows, jnp.zeros((), jnp.float32),
                   jnp.zeros((), jnp.float32)),
            jnp.arange(n_ticks))
        if self.data_axis is not None:
            # data ranks saw different samples: average the running-stat
            # updates (sync-BN-style state merge; normalization itself used
            # per-shard batch stats — standard "ghost BN" dp semantics) and
            # reduce loss/corrects so outputs are data-axis invariant
            s_rows_l = jax.lax.pmean(s_rows_l, self.data_axis)
            loss_acc = jax.lax.pmean(loss_acc, self.data_axis)
            corr_acc = jax.lax.psum(corr_acc, self.data_axis)
        # local (v, s_len) state rows; caller decides how to expose them
        return s_rows_l, loss_acc, corr_acc

    def _in_specs(self):
        dp_ax = self.data_axis
        return (P(self.axis), P(self.axis), P(None, dp_ax), P(None, dp_ax),
                P())

    def _collect(self, losses, corrects, mb_global):
        """Device-concatenated per-device sums -> (mean loss, metrics)."""
        # summing over devices collects the last stage's data losses AND every
        # stage's aux losses, averaged per microbatch — the same total
        # make_train_step's loss_fn + aux_loss_sum produces under grad accum
        loss = jnp.sum(losses) / self.num_mb
        metrics = {"loss": loss}
        if self.compute_accuracy:
            metrics["accuracy"] = jnp.sum(corrects) / (self.num_mb * mb_global)
        return loss, metrics

    def pipeline_loss(self, packed_params, packed_state, data, labels, rng,
                      train: bool = True):
        """(mean loss over microbatches, (new_packed_state, metrics)).

        ``data``: (num_mb * mb, ...) or (num_mb, mb, ...); labels likewise.
        Differentiable w.r.t. packed_params. Run under ``self.mesh``.
        """
        data, labels, mb_global, branches, n_ticks = self._prep(
            data, labels, train)

        def per_device(p_rows, s_rows, data_mb, labels_mb, key):
            s_rows_l, loss_acc, corr_acc = self._device_schedule(
                branches, n_ticks, p_rows, s_rows, data_mb, labels_mb, key)
            # local (v, s_len) rows concatenate over pipe to (L, s_len)
            return s_rows_l, loss_acc[None], corr_acc[None]

        fn = mesh_lib.shard_map(
            per_device, mesh=self.mesh, in_specs=self._in_specs(),
            out_specs=(P(self.axis), P(self.axis), P(self.axis)),
            check_vma=False)
        new_state, losses, corrects = fn(packed_params, packed_state, data,
                                         labels, rng)
        loss, metrics = self._collect(losses, corrects, mb_global)
        return loss, (new_state, metrics)

    def pipeline_value_and_grad(self, packed_params, packed_state, data,
                                labels, rng):
        """(loss, new_packed_state, metrics, grads) for one train batch.

        Same math as ``jax.value_and_grad(pipeline_loss)``, but the VJP runs
        INSIDE the shard_map body: each device differentiates the global
        scalar loss (psum over pipe of its schedule's contribution) w.r.t.
        its own packed rows, with the collectives transposed per device
        (ppermute -> inverse permutation, psum -> identity + a manual psum of
        the row grads over the data axis). shard_map's own transpose rule is
        never invoked — on jax 0.4.x it mishandles grad-of-switch programs
        (scalar residual out-specs, symbolic-zero cotangents), and this path
        sidesteps all of it while staying exactly as parallel.
        """
        data, labels, mb_global, branches, n_ticks = self._prep(
            data, labels, True)

        def per_device(p_rows, s_rows, data_mb, labels_mb, key):
            def local_loss(p):
                s_l, loss_acc, corr_acc = self._device_schedule(
                    branches, n_ticks, p, s_rows, data_mb, labels_mb, key)
                # the SAME global scalar on every device: sum each device's
                # (data-reduced) contribution over the pipe ring
                gloss = jax.lax.psum(loss_acc, self.axis) / self.num_mb
                return gloss, (s_l, loss_acc, corr_acc)

            (_, (s_l, loss_acc, corr_acc)), gp = jax.value_and_grad(
                local_loss, has_aux=True)(p_rows)
            if self.data_axis is not None:
                # per-device psum transpose is identity, so gp holds only this
                # data shard's term of d(loss)/d(rows) — sum the shards
                gp = jax.lax.psum(gp, self.data_axis)
            return gp, s_l, loss_acc[None], corr_acc[None]

        fn = mesh_lib.shard_map(
            per_device, mesh=self.mesh, in_specs=self._in_specs(),
            out_specs=(P(self.axis),) * 4, check_vma=False)
        grads, new_state, losses, corrects = fn(
            packed_params, packed_state, data, labels, rng)
        loss, metrics = self._collect(losses, corrects, mb_global)
        return loss, new_state, metrics, grads


def make_pipeline_train_step(stages: Sequence, optimizer, mesh: Mesh,
                             input_shape, *, loss_fn=None,
                             num_microbatches: int = 4, axis: str = "pipe",
                             input_dtype=jnp.bfloat16, scheduler=None,
                             donate: bool = True, compute_accuracy: bool = True,
                             data_axis: Optional[str] = None,
                             augment: Optional[Callable] = None,
                             remat: "bool | str" = False, virtual: int = 1):
    """Config-to-running-pipeline in one call (parity: the reference's
    coordinator deploy + async_train_batch + UPDATE_PARAMETERS cycle,
    coordinator.hpp:165-223, as ONE jitted program).

    ``virtual=v > 1`` selects the interleaved schedule: pass v*pp stages and
    the GPipe bubble shrinks to (pp-1)/v stage-times.
    ``input_shape`` is the per-MICROBATCH input shape (mb, H, W, C).
    Returns ``(pipe, step_fn, init_fn)``:
      * ``init_fn(rng) -> TrainState`` — packed params/state sharded over pipe,
        optimizer state over the packed rows (elementwise optimizers are
        leaf-order invariant, so packed updates match per-tree updates exactly).
      * ``step_fn(state, data, labels) -> (state, metrics)`` — full batch of
        num_microbatches * mb samples through fill/drain, grads from jax.grad
        of the schedule, one optimizer update (microbatch gradient
        accumulation, parity: distributed/train.hpp:19-79).
    """
    from ..nn.schedulers import NoOp
    from ..train.step import TrainState

    pipe = HeteroPipeline(stages, mesh, input_shape, input_dtype=input_dtype,
                          num_microbatches=num_microbatches, axis=axis,
                          loss_fn=loss_fn, compute_accuracy=compute_accuracy,
                          data_axis=data_axis, remat=remat, virtual=virtual)
    scheduler = scheduler or NoOp()
    host_driven = getattr(scheduler, "host_driven", False)

    def init_fn(rng: jax.Array) -> TrainState:
        init_rng, step_rng = jax.random.split(rng)
        p, s = pipe.init_packed(init_rng)
        state = TrainState(params=p, opt_state=optimizer.init(p), net_state=s,
                           step=jnp.zeros((), jnp.int32), rng=step_rng)
        return pipe.place_train_state(state)  # one placement rule for init+resume

    def step(state: TrainState, data, labels, lr_scale):
        rng, aug_rng, sub = jax.random.split(state.rng, 3)
        if augment is not None:  # on-device augmentation, fused into the step
            data = augment(aug_rng, data)
        # the schedule averages over microbatches, so grads carry the 1/num_mb
        # factor — same math as single-device gradient accumulation
        loss, new_net, metrics, grads = pipe.pipeline_value_and_grad(
            state.params, state.net_state, data, labels, sub)
        if not host_driven:
            lr_scale = scheduler.scale(state.step)
        new_params, new_opt = optimizer.update(grads, state.opt_state,
                                               state.params, lr_scale=lr_scale)
        metrics = dict(metrics, lr_scale=lr_scale)
        return TrainState(new_params, new_opt, new_net,
                          state.step + 1, rng), metrics

    jitted = jax.jit(step, donate_argnums=(0,) if donate else ())

    if host_driven:
        def step_fn(state, data, labels):
            with mesh:
                return jitted(state, data, labels,
                              jnp.asarray(scheduler.current_scale(), jnp.float32))
    else:
        one = jnp.ones((), jnp.float32)  # hoisted: no per-step H2D transfer

        def step_fn(state, data, labels):
            with mesh:
                return jitted(state, data, labels, one)

    return pipe, step_fn, init_fn


def make_pipeline_eval_step(pipe: HeteroPipeline):
    """Jitted (state, data, labels) -> metrics through the same pipeline
    (train=False: BatchNorm uses running stats, no state mutation)."""

    def ev(state, data, labels):
        _, (_, metrics) = pipe.pipeline_loss(
            state.params, state.net_state, data, labels,
            jax.random.PRNGKey(0), False)
        if "accuracy" in metrics:
            mb_global = pipe.in_shapes[0][0] * pipe.dp
            metrics = dict(metrics, corrects=metrics.pop("accuracy")
                           * (pipe.num_mb * mb_global))
        return metrics

    jitted = jax.jit(ev)

    def eval_fn(state, data, labels):
        with pipe.mesh:
            return jitted(state, data, labels)

    return eval_fn


# ---------------------------------------------------------------------------
# 3. Host-orchestrated heterogeneous-stage pipeline
# ---------------------------------------------------------------------------


class StagePipeline:
    """Generic pipeline over heterogeneous stage modules, one device each.

    The TPU-native analog of the reference's coordinator+workers (SURVEY.md §3.2):
    CONFIG_TRANSFER -> constructor; FORWARD_JOB/BACKWARD_JOB -> jitted per-stage
    programs + async dispatch; TCP/RoCE hops -> jax.device_put over ICI.
    """

    def __init__(self, stages: Sequence, optimizer, loss_fn, devices=None):
        self.stages = list(stages)
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        self._step_count = 0  # advances the default dropout rng per step
        devices = list(devices) if devices is not None else jax.devices()
        if len(devices) < len(self.stages):
            raise ValueError(f"{len(self.stages)} stages need as many devices, "
                             f"have {len(devices)}")
        self.devices = devices[:len(self.stages)]
        self.variables: List[Any] = []
        self.opt_states: List[Any] = []
        self._fwd = []
        self._fwd_train = []
        for i, stage in enumerate(self.stages):
            # pure apply for vjp; net state is a real argument (closing over it
            # would bake it into the compiled program and ignore later updates)
            def apply_fn(params, net_state, x, stage=stage):
                out, _ = stage.apply({"params": params, "state": net_state},
                                     x, train=False)
                return out

            def apply_train(params, net_state, x, key, stage=stage):
                # train=True with the new state as aux: BatchNorm statistics
                # update per microbatch exactly like single-device training
                # (the earlier train=False here silently froze BN — a WRN
                # through this pipeline would normalize with init-time stats
                # forever)
                out, new_state = stage.apply(
                    {"params": params, "state": net_state}, x, train=True,
                    rng=key)
                return out, new_state

            # params are committed to the stage's device, so the jitted program runs there
            self._fwd.append(jax.jit(apply_fn))
            self._fwd_train.append(jax.jit(apply_train))

    def init(self, rng, input_shape, input_dtype=None):
        """Initialize every stage, placing its params on its device
        (parity: deploy_stages, coordinator.hpp:368)."""
        shape = tuple(input_shape)
        dtype = input_dtype
        self.variables, self.opt_states = [], []
        keys = jax.random.split(rng, len(self.stages))
        for i, stage in enumerate(self.stages):
            if dtype is not None:
                v = stage.init(keys[i], shape, input_dtype=dtype)
            else:
                v = stage.init(keys[i], shape)
            v = jax.device_put(v, self.devices[i])
            self.variables.append(v)
            self.opt_states.append(
                jax.device_put(self.optimizer.init(v["params"]), self.devices[i]))
            dummy = jax.ShapeDtypeStruct(tuple(shape), dtype or jnp.float32)
            out = jax.eval_shape(self._fwd[i], v["params"], v["state"], dummy)
            shape, dtype = out.shape, out.dtype
        return self

    def forward(self, x):
        """Inference pass: microbatch-free, stage hop = device transfer."""
        for i in range(len(self.stages)):
            x = jax.device_put(x, self.devices[i])
            x = self._fwd[i](self.variables[i]["params"], self.variables[i]["state"], x)
        return x

    def train_batch(self, data, labels, num_microbatches: int = 4, rng=None):
        """One training step: GPipe fill/drain with gradient accumulation
        (parity: async_train_batch, coordinator.hpp:165-223 + distributed/train.hpp:19-79).

        Async dispatch overlaps stage work across microbatches without explicit
        scheduling — the queueing the reference does by hand. BatchNorm state
        threads through the microbatches (mb k normalizes with mb k's batch
        stats and updates the running stats mb k-1 left), matching
        single-device gradient accumulation.

        Returns the mean microbatch loss as a DEVICE scalar — fetching it
        (float()) is the caller's sync point; doing it here would serialize
        every step boundary on the host.
        """
        n = len(self.stages)
        mbs = jnp.split(data, num_microbatches)
        lbs = jnp.split(labels, num_microbatches)
        grads = [None] * n
        if rng is None:
            # default rng advances per step — a fixed key would apply the SAME
            # dropout mask on every training step
            rng = jax.random.fold_in(jax.random.PRNGKey(0), self._step_count)
        self._step_count += 1

        # fill: forward all microbatches, keeping vjp closures (activation
        # residuals) and threading each stage's mutable state forward
        states = [v["state"] for v in self.variables]
        vjps = []  # [mb][stage]
        outs = []
        for m, mb in enumerate(mbs):
            stage_vjps = []
            x = mb
            for i in range(n):
                x = jax.device_put(x, self.devices[i])
                fwd, st = self._fwd_train[i], states[i]
                key = jax.random.fold_in(jax.random.fold_in(rng, m), i)
                x, vjp, new_st = jax.vjp(
                    lambda p, xx, fwd=fwd, st=st, k=key: fwd(p, st, xx, k),
                    self.variables[i]["params"], x, has_aux=True)
                states[i] = new_st
                stage_vjps.append(vjp)
            vjps.append(stage_vjps)
            outs.append(x)
        for i in range(n):
            self.variables[i] = {"params": self.variables[i]["params"],
                                 "state": states[i]}

        # drain: loss grad per microbatch, backward through stages in reverse
        scale = 1.0 / num_microbatches
        losses = []
        for out, lb, stage_vjps in zip(outs, lbs, vjps):
            lb = jax.device_put(lb, self.devices[-1])
            loss, loss_vjp = jax.vjp(lambda o: self.loss_fn(o, lb), out)
            losses.append(loss)  # keep on device — a float() here would stall the pipeline
            (g,) = loss_vjp(jnp.asarray(scale, jnp.float32))
            for i in reversed(range(n)):
                g = jax.device_put(g, self.devices[i])
                gp, g = stage_vjps[i](g)
                grads[i] = gp if grads[i] is None else jax.tree_util.tree_map(
                    jnp.add, grads[i], gp)

        # optimizer step per stage (parity: UPDATE_PARAMETERS, worker.hpp:194-207)
        for i in range(n):
            new_params, self.opt_states[i] = self.optimizer.update(
                grads[i], self.opt_states[i], self.variables[i]["params"])
            self.variables[i] = {"params": new_params, "state": self.variables[i]["state"]}
        # device scalar: a float() here would sync the host every step and
        # serialize step boundaries; callers fetch when they actually log.
        # (All losses are computed on devices[-1] already — no transfers.)
        return sum(losses[1:], losses[0]) * scale
