"""Model partitioner: split a Sequential into pipeline stages.

Parity: reference Partitioner (include/partitioner/partitioner.hpp:50-65,
``SeqPartition{start,length}`` :8, ``split()`` re-instantiating layers per stage via
config round-trip :26-48) and NaivePipelinePartitioner (naive_partitioner.hpp:19-56).
The reference's FLOPs-weighted partitioners were left unfinished
(``FTDPartitioner::partition_model`` undefined, WeightedPipelinePartitioner commented
out — SURVEY.md §1 caveats); the cost-balanced partitioner here finishes that idea.

Stages are rebuilt from layer configs — the same mechanism the reference uses to ship
stages to workers (CONFIG_TRANSFER), reused here for mesh placement.
"""
from __future__ import annotations

import dataclasses
import math
from typing import List, Optional, Sequence, Tuple

from ..core.module import Module, module_from_config
from ..nn.blocks import Sequential


@dataclasses.dataclass(frozen=True)
class SeqPartition:
    """Parity: SeqPartition{start_index, length} (partitioner.hpp:8)."""

    start: int
    length: int


def split(model: Sequential, partitions: Sequence[SeqPartition]) -> List[Sequential]:
    """Clone layer ranges into fresh stage modules via config round-trip
    (parity: Partitioner::split, partitioner.hpp:26-48)."""
    stages = []
    for i, part in enumerate(partitions):
        children = model.children[part.start:part.start + part.length]
        cloned = [module_from_config(c.get_config()) for c in children]
        stages.append(Sequential(cloned, name=f"stage{i}", policy=model.policy))
    return stages


def proportional_partitions(num_layers: int, proportions: Sequence[float]) -> List[SeqPartition]:
    """Parity: NaivePipelinePartitioner proportion-based split (naive_partitioner.hpp:19-56)."""
    if num_layers < len(proportions):
        raise ValueError(f"cannot split {num_layers} layers into {len(proportions)} stages")
    total = sum(proportions)
    counts = [max(1, round(num_layers * p / total)) for p in proportions]
    # fix rounding drift
    while sum(counts) > num_layers:
        counts[counts.index(max(counts))] -= 1
    while sum(counts) < num_layers:
        counts[counts.index(min(counts))] += 1
    parts, start = [], 0
    for c in counts:
        parts.append(SeqPartition(start, c))
        start += c
    return parts


def layer_flops(layer: Module, input_shape: Tuple[int, ...]) -> float:
    """Rough forward FLOPs estimate per layer (drives cost-balanced splitting —
    the finished version of the reference's FTD/Weighted partitioner idea)."""
    out_shape = layer.output_shape(tuple(input_shape))
    t = layer.type_name
    if t == "dense":
        return 2.0 * math.prod(input_shape) * out_shape[-1]
    if t == "conv2d":
        kh, kw = layer.kernel_size
        cin = input_shape[-1] // layer.groups
        return 2.0 * math.prod(out_shape) * kh * kw * cin
    if t in ("multihead_attention", "gpt_block", "encoder_block"):
        n, s, d = input_shape[0], input_shape[-2], input_shape[-1]
        proj = 8.0 * n * s * d * d  # qkv+out
        attn = 4.0 * n * s * s * d
        mlp = 0.0
        if t in ("gpt_block", "encoder_block"):
            mlp = 4.0 * n * s * d * d * layer.mlp_ratio
        return proj + attn + mlp
    if t in ("sequential", "residual", "parallel"):
        total, shape = 0.0, tuple(input_shape)
        children = layer.children
        for child in children:
            total += layer_flops(child, shape)
            if t == "sequential":
                shape = child.output_shape(shape)
        return total
    # elementwise-ish layers: one pass over the data
    return float(math.prod(out_shape))


def balanced_partitions(model: Sequential, num_stages: int,
                        input_shape: Tuple[int, ...],
                        weights: Optional[Sequence[float]] = None) -> List[SeqPartition]:
    """FLOPs-balanced contiguous split into ``num_stages`` (exceeds the reference's
    unfinished FTDPartitioner). ``weights`` optionally scales per-stage capacity."""
    costs = []
    shape = tuple(input_shape)
    for child in model.children:
        costs.append(layer_flops(child, shape))
        shape = child.output_shape(shape)
    n = len(costs)
    if num_stages > n:
        raise ValueError(f"cannot split {n} layers into {num_stages} stages")
    weights = list(weights) if weights else [1.0] * num_stages
    total = sum(costs)
    wsum = sum(weights)
    # greedy: cut when the running stage cost passes its proportional share
    parts: List[SeqPartition] = []
    start, acc, stage = 0, 0.0, 0
    for i, c in enumerate(costs):
        acc += c
        remaining_layers = n - i - 1
        remaining_stages = num_stages - stage - 1
        share = total * weights[stage] / wsum
        if stage < num_stages - 1 and (acc >= share or remaining_layers == remaining_stages):
            parts.append(SeqPartition(start, i - start + 1))
            start, acc, stage = i + 1, 0.0, stage + 1
    parts.append(SeqPartition(start, n - start))
    return parts


def partition_model(model: Sequential, num_stages: int, input_shape: Tuple[int, ...],
                    strategy: str = "balanced") -> List[Sequential]:
    """One-call API (parity: Partitioner::partition_model, partitioner.hpp:50-65)."""
    if strategy == "balanced":
        parts = balanced_partitions(model, num_stages, input_shape)
    elif strategy == "uniform":
        parts = proportional_partitions(len(model.children), [1.0] * num_stages)
    else:
        raise ValueError(f"unknown strategy {strategy!r}")
    return split(model, parts)
