"""Parallelism: mesh, data/tensor/pipeline/sequence parallel, partitioner.

Inventory vs the reference (SURVEY.md §2.4): TNN has microbatch pipeline parallelism,
coordinator-mediated data parallelism (without gradient all-reduce — a bug-class we fix
by construction), and intra-op threading. This package adds correct DP, FSDP, tensor
parallelism, and ring-attention sequence parallelism on top — all as sharding
annotations + XLA collectives over ICI, replacing ~4.4k LoC of TCP/RoCE runtime.
"""
from . import (data_parallel, mesh, partitioner, pipeline, ring_attention,
               tensor_parallel, ulysses)
from .data_parallel import make_dp_train_step, shard_params_fsdp
from .mesh import batch_sharding, data_mesh, make_mesh, replicated
from .partitioner import SeqPartition, balanced_partitions, partition_model, split
from .pipeline import (HeteroPipeline, StagePipeline, make_pipeline_eval_step,
                       make_pipeline_train_step, spmd_pipeline,
                       spmd_pipeline_interleaved, stack_stage_params)
from .ring_attention import ring_attention
from .tensor_parallel import DEFAULT_TP_RULES, shard_params_tp, spec_tree
from .ulysses import ulysses_attention

__all__ = [
    "data_parallel", "mesh", "partitioner", "pipeline", "ring_attention", "tensor_parallel",
    "make_dp_train_step", "shard_params_fsdp",
    "batch_sharding", "data_mesh", "make_mesh", "replicated",
    "SeqPartition", "balanced_partitions", "partition_model", "split",
    "HeteroPipeline", "StagePipeline", "make_pipeline_eval_step",
    "make_pipeline_train_step", "spmd_pipeline", "spmd_pipeline_interleaved",
    "stack_stage_params",
    "ring_attention", "ulysses_attention",
    "DEFAULT_TP_RULES", "shard_params_tp", "spec_tree",
]
