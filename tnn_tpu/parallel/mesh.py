"""Device mesh construction and sharding helpers.

The TPU-native replacement for the reference's device/topology bookkeeping
(DeviceManager, include/device/device_manager.hpp:16; Coordinator topology init,
include/distributed/coordinator.hpp:368-456). On TPU the "topology" is a logical mesh
over chips; parallelism = sharding annotations over named axes, XLA inserts the
collectives that the reference hand-rolls over TCP/RoCE.

Canonical axis names:
  data   — data parallelism (batch sharded, grads all-reduced)
  fsdp   — parameter sharding on top of dp (ZeRO-style; beyond the reference)
  model  — tensor parallelism (Megatron-style; beyond the reference)
  pipe   — pipeline stages (parity with the reference's PP)
  seq    — sequence/context parallelism (ring attention; beyond the reference)
  expert — expert parallelism (MoE dispatch/combine; beyond the reference)
"""
from __future__ import annotations

import math
from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

AXES = ("data", "fsdp", "model", "pipe", "seq", "expert")


def shard_map(body, *, mesh, in_specs, out_specs, check_vma: bool = False):
    """jax.shard_map across jax versions: jax>=0.5 exposes ``jax.shard_map``
    with ``check_vma``; 0.4.x has ``jax.experimental.shard_map.shard_map``
    with the analogous knob spelled ``check_rep``.

    On 0.4.x the fallback forces ``check_rep=True`` regardless of
    ``check_vma``: with replication tracking OFF, grad-of-shard_map infers
    fully-sharded out-specs for the residuals it threads to the backward
    pass, which is unsatisfiable for scalar residuals (loss accumulators)
    and raises ``_SpecError``. Tracking costs a little trace time and
    enables the efficient transpose; programs that are correct under
    ``check_vma=False`` on new jax are also correct under it."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(body, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _shard_map

    _install_04x_shard_map_fixes()
    return _shard_map(body, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=True)


def shard_map_unchecked(body, *, mesh, in_specs, out_specs):
    """shard_map with replication tracking OFF — for inference-only bodies.

    The serving hot path never differentiates through the mapped body, so
    the transpose machinery that forces ``check_rep=True`` above is dead
    weight here. More importantly, 0.4.x's replication validator has no
    rules for several primitives that appear in serving step bodies
    (``pallas_call`` from the paged-attention kernel, threefry sampling),
    so unregistered ops get pessimistically tagged "unreplicated" and the
    replicated out-specs the engine relies on (tokens, keys) fail the
    check even though the values are genuinely device-invariant. With
    tracking off, replicated out-specs simply take the (identical) value
    from each shard."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(body, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(body, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=False)


_FIXES_04X_DONE = False


def _install_04x_shard_map_fixes() -> None:
    """Two targeted backports that make grad-of-shard_map work on 0.4.x.

    1. Tolerant cond replication check. 0.4.x's ``check_rep`` validator
       demands every ``lax.cond``/``switch`` branch produce IDENTICAL
       replication types. Under ``jax.grad`` that is unsatisfiable for any
       switch over branches with different parameters: partial-eval appends
       each branch's grad residuals as extra outputs, zero-filled in the
       other branches, and constant zeros check as "replicated" where real
       residuals are "varying". jax's own lowering rewrite
       (``_cond_rewrite``) already tolerates this by intersecting the
       branch reps and pbroadcasting each branch to the meet — and later
       jax versions replaced the strict check with exactly that
       union-of-varying semantics. Install the same meet as the check rule
       so the validator agrees with the rewrite. ``None`` (unknown rep)
       meets to ``None``.

    2. Instantiate symbolic-zero output cotangents before transpose. The
       0.4.x transpose rule threads ``ad.Zero`` placeholders (outputs with
       no cotangent — e.g. the aux new-state rows of a loss function) into
       the inner bind, where the rewrite interpreter crashes
       (``'Zero' object has no attribute 'reshape'``). Materialize them as
       real zeros first; XLA folds the dead zeros away. float0 cotangents
       (integer outputs) are left symbolic — the rule special-cases them."""
    global _FIXES_04X_DONE
    if _FIXES_04X_DONE:
        return
    _FIXES_04X_DONE = True
    from jax._src import dtypes as _dtypes
    from jax._src.interpreters import ad as _ad
    from jax._src.lax.control_flow import conditionals as _conds
    from jax.experimental import shard_map as _smod

    def _meet(a, b):
        if a is None or b is None:
            return None
        return a & b

    def _cond_rule(mesh, *in_rep, branches):
        pred_rep, *args_rep = in_rep
        out_rep = _smod._check_rep(mesh, branches[0].jaxpr, args_rep)
        for branch in branches[1:]:
            out_rep = [_meet(r1, r2) for r1, r2 in zip(
                out_rep, _smod._check_rep(mesh, branch.jaxpr, args_rep))]
        return [_meet(pred_rep, r) for r in out_rep]

    _smod._check_rules[_conds.cond_p] = _cond_rule

    _orig_transpose = _ad.primitive_transposes[_smod.shard_map_p]

    def _transpose_inst_zeros(out_cts, *args, **params):
        out_cts = [
            _ad.instantiate_zeros(ct)
            if type(ct) is _ad.Zero and ct.aval.dtype != _dtypes.float0
            else ct for ct in out_cts]
        return _orig_transpose(out_cts, *args, **params)

    _ad.primitive_transposes[_smod.shard_map_p] = _transpose_inst_zeros


def make_mesh(data: int = 1, fsdp: int = 1, model: int = 1, pipe: int = 1,
              seq: int = 1, expert: int = 1,
              devices: Optional[Sequence] = None) -> Mesh:
    """Build a logical mesh with the canonical axis order.

    Any axis of size 1 is kept (zero cost, lets sharding specs stay uniform).
    """
    sizes = {"data": data, "fsdp": fsdp, "model": model, "pipe": pipe,
             "seq": seq, "expert": expert}
    devices = list(devices) if devices is not None else jax.devices()
    need = math.prod(sizes.values())
    if need > len(devices):
        raise ValueError(f"mesh needs {need} devices, have {len(devices)}")
    arr = np.array(devices[:need]).reshape(*sizes.values())
    return Mesh(arr, axis_names=AXES)


def data_mesh(n: Optional[int] = None, devices=None) -> Mesh:
    devices = list(devices) if devices is not None else jax.devices()
    n = n or len(devices)
    return make_mesh(data=n, devices=devices)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def batch_sharding(mesh: Mesh, extra_axes: Tuple[str, ...] = ()) -> NamedSharding:
    """Shard the leading (batch) dim over the data axis (+any extra non-degenerate axes)."""
    axes = ["data"] + [a for a in extra_axes if a in mesh.axis_names]
    present = tuple(a for a in axes if mesh.shape.get(a, 1) > 1)
    if not present:
        return NamedSharding(mesh, PartitionSpec())
    return NamedSharding(mesh, PartitionSpec(present))


def axis_size(mesh: Mesh, name: str) -> int:
    return int(mesh.shape.get(name, 1))


def mapped_axis_size(axis: str) -> int:
    """Size of a mapped axis from INSIDE a shard_map body, as a static int.

    jax>=0.5 has jax.lax.axis_size; on 0.4.x a psum of the literal 1
    constant-folds to the axis size at trace time."""
    if hasattr(jax.lax, "axis_size"):
        return int(jax.lax.axis_size(axis))
    return int(jax.lax.psum(1, axis))


def seq_shard_map(body, mesh: Mesh, axis: str, batch_axis=None):
    """Wrap a per-device (q, k, v) -> out body for context-parallel attention.

    Shared by ring_attention and ulysses_attention so the two schemes stay
    drop-in interchangeable: activations are (B, H, S, D) with S sharded over
    ``axis``; ``batch_axis`` (one name or a tuple, e.g. ("data", "fsdp"))
    additionally shards B so each batch shard runs its own ring/all-to-all
    group — without it, a batch-sharded input would be all-gathered at the
    shard_map boundary. Degenerate (size-1) batch axes are dropped.
    """
    if batch_axis is None:
        ba = None
    else:
        names = (batch_axis,) if isinstance(batch_axis, str) else tuple(batch_axis)
        live = tuple(n for n in names if axis_size(mesh, n) > 1)
        ba = live or None
    spec = PartitionSpec(ba, None, axis, None)
    return shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, check_vma=False)


def local_mesh_info() -> Dict[str, int]:
    """Device census (parity: HardwareInfo intent, utils/hardware_info.hpp:126)."""
    devs = jax.devices()
    return {
        "device_count": len(devs),
        "local_device_count": jax.local_device_count(),
        "process_count": jax.process_count(),
        "platform": devs[0].platform if devs else "none",
    }
