"""Device mesh construction and sharding helpers.

The TPU-native replacement for the reference's device/topology bookkeeping
(DeviceManager, include/device/device_manager.hpp:16; Coordinator topology init,
include/distributed/coordinator.hpp:368-456). On TPU the "topology" is a logical mesh
over chips; parallelism = sharding annotations over named axes, XLA inserts the
collectives that the reference hand-rolls over TCP/RoCE.

Canonical axis names:
  data   — data parallelism (batch sharded, grads all-reduced)
  fsdp   — parameter sharding on top of dp (ZeRO-style; beyond the reference)
  model  — tensor parallelism (Megatron-style; beyond the reference)
  pipe   — pipeline stages (parity with the reference's PP)
  seq    — sequence/context parallelism (ring attention; beyond the reference)
  expert — expert parallelism (MoE dispatch/combine; beyond the reference)
"""
from __future__ import annotations

import math
from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

AXES = ("data", "fsdp", "model", "pipe", "seq", "expert")


def make_mesh(data: int = 1, fsdp: int = 1, model: int = 1, pipe: int = 1,
              seq: int = 1, expert: int = 1,
              devices: Optional[Sequence] = None) -> Mesh:
    """Build a logical mesh with the canonical axis order.

    Any axis of size 1 is kept (zero cost, lets sharding specs stay uniform).
    """
    sizes = {"data": data, "fsdp": fsdp, "model": model, "pipe": pipe,
             "seq": seq, "expert": expert}
    devices = list(devices) if devices is not None else jax.devices()
    need = math.prod(sizes.values())
    if need > len(devices):
        raise ValueError(f"mesh needs {need} devices, have {len(devices)}")
    arr = np.array(devices[:need]).reshape(*sizes.values())
    return Mesh(arr, axis_names=AXES)


def data_mesh(n: Optional[int] = None, devices=None) -> Mesh:
    devices = list(devices) if devices is not None else jax.devices()
    n = n or len(devices)
    return make_mesh(data=n, devices=devices)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def batch_sharding(mesh: Mesh, extra_axes: Tuple[str, ...] = ()) -> NamedSharding:
    """Shard the leading (batch) dim over the data axis (+any extra non-degenerate axes)."""
    axes = ["data"] + [a for a in extra_axes if a in mesh.axis_names]
    present = tuple(a for a in axes if mesh.shape.get(a, 1) > 1)
    if not present:
        return NamedSharding(mesh, PartitionSpec())
    return NamedSharding(mesh, PartitionSpec(present))


def axis_size(mesh: Mesh, name: str) -> int:
    return int(mesh.shape.get(name, 1))


def seq_shard_map(body, mesh: Mesh, axis: str, batch_axis=None):
    """Wrap a per-device (q, k, v) -> out body for context-parallel attention.

    Shared by ring_attention and ulysses_attention so the two schemes stay
    drop-in interchangeable: activations are (B, H, S, D) with S sharded over
    ``axis``; ``batch_axis`` (one name or a tuple, e.g. ("data", "fsdp"))
    additionally shards B so each batch shard runs its own ring/all-to-all
    group — without it, a batch-sharded input would be all-gathered at the
    shard_map boundary. Degenerate (size-1) batch axes are dropped.
    """
    if batch_axis is None:
        ba = None
    else:
        names = (batch_axis,) if isinstance(batch_axis, str) else tuple(batch_axis)
        live = tuple(n for n in names if axis_size(mesh, n) > 1)
        ba = live or None
    spec = PartitionSpec(ba, None, axis, None)
    return jax.shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, check_vma=False)


def local_mesh_info() -> Dict[str, int]:
    """Device census (parity: HardwareInfo intent, utils/hardware_info.hpp:126)."""
    devs = jax.devices()
    return {
        "device_count": len(devs),
        "local_device_count": jax.local_device_count(),
        "process_count": jax.process_count(),
        "platform": devs[0].platform if devs else "none",
    }
