"""Ulysses (all-to-all) sequence parallelism — the second context-parallel scheme.

Beyond the reference: TNN has NO sequence/context parallelism (SURVEY.md §5 — its
long-context story is single-device flash attention at fixed seq_len=1024). The build
charter asks for "ring attention or all-to-all sequence/context parallelism"; this
package ships BOTH, because they trade off differently:

  * ring_attention: K/V blocks rotate via ppermute; works for any head count, ICI
    traffic overlaps compute, but the blockwise accumulation runs as jnp ops (the
    online-softmax recurrence spans devices, so the single-chip Pallas kernel can't
    cover the cross-device loop).
  * ulysses_attention (this module): one all-to-all re-shards activations from
    seq-sharded to HEAD-sharded; each device then holds the FULL sequence for H/sp
    heads and runs the tuned single-chip Pallas flash kernel locally; a second
    all-to-all restores seq sharding. Per DeepSpeed-Ulysses (arXiv:2309.14509) the
    a2a moves O(S·d/sp) bytes per device vs ring's O(S·d) — but requires
    num_heads % sp == 0.

Differentiable end-to-end: all_to_all transposes to all_to_all in the VJP and the
local attention is the custom-VJP flash kernel (or XLA softmax attention off-TPU).
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
from jax.sharding import Mesh

from . import mesh as mesh_lib


def _local_full_attention(q, k, v, *, causal: bool, scale: float):
    """Single-device attention on (B, h_local, S, D) — full sequence present, so
    plain causal masking is correct. Pallas flash on TPU, the shared XLA
    softmax math elsewhere (interpret-mode pallas is too slow for the test
    matrix; local_xla_attention bypasses sdpa's context routing, which would
    recurse back into ulysses)."""
    if jax.default_backend() == "tpu":
        from ..ops.pallas.flash_attention import flash_attention

        return flash_attention(q, k, v, causal, scale)
    from ..nn.attention import local_xla_attention

    return local_xla_attention(q, k, v, causal=causal, scale=scale)


def _ulysses_local(q, k, v, *, axis: str, causal: bool, scale: float):
    """Per-device body under shard_map. q/k/v: (B, H, S_local, D) — the full
    head dim with a sequence shard. Two all-to-alls bracket local attention."""
    # (B, H, S/sp, D) -> (B, H/sp, S, D): scatter heads, gather sequence
    fwd = functools.partial(jax.lax.all_to_all, axis_name=axis, split_axis=1,
                            concat_axis=2, tiled=True)
    qh, kh, vh = fwd(q), fwd(k), fwd(v)
    oh = _local_full_attention(qh, kh, vh, causal=causal, scale=scale)
    # (B, H/sp, S, D) -> (B, H, S/sp, D): scatter sequence, gather heads
    return jax.lax.all_to_all(oh, axis_name=axis, split_axis=2, concat_axis=1,
                              tiled=True)


def ulysses_attention(q, k, v, mesh: Mesh, *, axis: str = "seq",
                      causal: bool = False, scale: Optional[float] = None,
                      batch_axis: Optional[str] = None):
    """Attention over (B, H, S, D) tensors whose S dim is sharded over ``axis``.

    Same contract as ``ring_attention`` (call with global arrays sharded
    P(None, None, axis, None); returns the same sharding) so the two schemes are
    drop-in interchangeable where num_heads % sp == 0. ``batch_axis`` composes
    dp/fsdp x sp exactly as in ring_attention.
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    sp = mesh_lib.axis_size(mesh, axis)
    heads, seq = q.shape[1], q.shape[-2]
    if seq % sp:
        raise ValueError(f"seq len {seq} not divisible by sp size {sp}")
    if heads % sp:
        raise ValueError(
            f"num_heads {heads} not divisible by sp size {sp} — Ulysses shards "
            f"heads during attention; use ring_attention for this layout")
    if v.shape[1] != k.shape[1] or heads % k.shape[1]:
        raise ValueError(f"q has {heads} heads but k/v have "
                         f"{k.shape[1]}/{v.shape[1]}; need H % H_kv == 0")
    if k.shape[1] % sp:
        # the kv all-to-all splits the head dim over the seq axis; fewer kv
        # heads than shards cannot split (GQA-aware ring_attention can)
        raise ValueError(
            f"kv heads {k.shape[1]} not divisible by sp size {sp} — use "
            f"ring_attention (GQA-aware) for this layout")
    body = functools.partial(_ulysses_local, axis=axis, causal=causal, scale=scale)
    return mesh_lib.seq_shard_map(body, mesh, axis, batch_axis)(q, k, v)
