"""Profiling: event tracing, cross-process merge, Chrome-trace export, XPlane hooks.

Parity: reference ``include/profiling/`` — ``Event{type, start, end, name, source}``
(event.hpp:11,30), thread-safe ``Profiler`` accumulator with cross-process merge that
re-bases timestamps (profiler.hpp:52-63), process-global ``GlobalProfiler``
(profiler.hpp:132). Rendered by visualizers/visualize_profiler.py as a Gantt chart; here
the export is standard Chrome trace JSON (chrome://tracing / Perfetto) instead.

TPU-first addition: ``device_trace`` wraps ``jax.profiler`` so device-side XPlane traces
(per-op HLO timing on the TPU) are captured alongside the host-side event timeline.
"""
from .profiler import (
    Event,
    EventType,
    GlobalProfiler,
    Profiler,
    device_trace,
    profiled,
)

__all__ = [
    "Event",
    "EventType",
    "Profiler",
    "GlobalProfiler",
    "device_trace",
    "profiled",
]
