"""Host-side event profiler with cross-process merge and Chrome-trace export.

Parity map (reference -> here):
- ``Event`` / ``EventType {COMPUTE, COMMUNICATION, OTHER}`` (include/profiling/event.hpp:11,30)
  -> ``Event`` / ``EventType`` (DATA added for loader/staging spans).
- thread-safe ``Profiler`` with ``add_event`` and merge-with-rebase (profiler.hpp:52-63)
  -> ``Profiler.add_event`` / ``Profiler.merge`` (rebase aligns the other profiler's
  clock by start-time delta, so profiles from hosts with different monotonic origins
  line up on one timeline).
- ``GlobalProfiler`` (profiler.hpp:132) -> module-level singleton with enable gating.
- serialized Profiler travelling the control plane as a message payload
  (message.hpp:21, binary_serializer.hpp:46) -> ``to_dict``/``from_dict`` (JSON-safe).
- communicator per-key microsecond counters (communicator.hpp:157-184) -> ``counters``.
"""
from __future__ import annotations

import contextlib
import enum
import json
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional


class EventType(enum.Enum):
    COMPUTE = "compute"
    COMMUNICATION = "communication"
    DATA = "data"
    OTHER = "other"


@dataclass
class Event:
    type: EventType
    start: float  # seconds on this process's perf_counter clock
    end: float
    name: str
    source: str = ""  # e.g. "host0", "stage1" — who recorded it

    @property
    def duration(self) -> float:
        return self.end - self.start


class Profiler:
    """Thread-safe span accumulator.

    Use ``scope`` to time a block, ``add_event`` for pre-measured spans, ``tick`` for
    key->time counters, ``merge`` to fold in another (possibly remote) profiler.
    """

    def __init__(self, source: str = ""):
        self.source = source
        self._events: List[Event] = []
        self._counters: Dict[str, float] = {}
        self._lock = threading.Lock()
        # clock origin so merges can rebase between processes
        self._origin = time.perf_counter()

    # -- recording ------------------------------------------------------------

    def add_event(self, type: EventType, start: float, end: float, name: str,
                  source: str = "") -> None:
        ev = Event(type, start, end, name, source or self.source)
        with self._lock:
            self._events.append(ev)

    @contextlib.contextmanager
    def scope(self, name: str,
              type: EventType = EventType.COMPUTE) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add_event(type, t0, time.perf_counter(), name)

    def tick(self, key: str, seconds: float) -> None:
        """Accumulate a duration under ``key`` (parity: communicator.hpp:157-184)."""
        with self._lock:
            self._counters[key] = self._counters.get(key, 0.0) + seconds

    # -- access ---------------------------------------------------------------

    @property
    def events(self) -> List[Event]:
        with self._lock:
            return list(self._events)

    @property
    def counters(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._counters)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self._counters.clear()
            self._origin = time.perf_counter()

    # -- merge / serialization ------------------------------------------------

    def merge(self, other: "Profiler") -> None:
        """Fold ``other``'s events into this timeline.

        Rebase rule (parity: profiler.hpp:52-63): shift the other profiler's
        timestamps by the difference of clock origins, so both ranges share this
        profiler's clock. Cross-host skew beyond origin alignment is accepted, as in
        the reference.
        """
        if other is self:
            return
        delta = self._origin - other._origin
        # copy under other's lock, then insert under ours — never hold both
        # (self-merge or concurrent mutual merges would deadlock otherwise)
        with other._lock:
            evs = list(other._events)
            ctrs = dict(other._counters)
        with self._lock:
            for ev in evs:
                self._events.append(Event(ev.type, ev.start + delta, ev.end + delta,
                                          ev.name, ev.source or other.source))
            for k, v in ctrs.items():
                self._counters[k] = self._counters.get(k, 0.0) + v

    def to_dict(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "source": self.source,
                "origin": self._origin,
                "events": [
                    {"type": ev.type.value, "start": ev.start, "end": ev.end,
                     "name": ev.name, "source": ev.source}
                    for ev in self._events
                ],
                "counters": dict(self._counters),
            }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Profiler":
        p = cls(source=d.get("source", ""))
        p._origin = float(d.get("origin", 0.0))
        p._events = [
            Event(EventType(e["type"]), float(e["start"]), float(e["end"]),
                  e["name"], e.get("source", ""))
            for e in d.get("events", [])
        ]
        p._counters = {k: float(v) for k, v in d.get("counters", {}).items()}
        return p

    # -- reporting ------------------------------------------------------------

    def summary(self) -> Dict[str, Dict[str, float]]:
        """Per-name aggregate: count, total seconds, mean seconds."""
        out: Dict[str, Dict[str, float]] = {}
        for ev in self.events:
            s = out.setdefault(ev.name, {"count": 0, "total_s": 0.0, "mean_s": 0.0})
            s["count"] += 1
            s["total_s"] += ev.duration
        for s in out.values():
            s["mean_s"] = s["total_s"] / max(s["count"], 1)
        return out

    def to_chrome_trace(self, path: Optional[str] = None) -> List[Dict[str, Any]]:
        """Chrome trace-event JSON (load in chrome://tracing or Perfetto).

        One 'thread' row per source — the same view the reference's Gantt
        visualizer draws per coordinator/worker (visualizers/visualize_profiler.py).
        """
        sources = sorted({ev.source or "local" for ev in self.events})
        tids = {s: i for i, s in enumerate(sources)}
        trace = [
            {"name": s, "ph": "M", "pid": 0, "tid": tids[s],
             "args": {"name": s}, "cat": "__metadata"}
            for s in sources
        ]
        for ev in self.events:
            trace.append({
                "name": ev.name, "cat": ev.type.value, "ph": "X", "pid": 0,
                "tid": tids[ev.source or "local"],
                "ts": ev.start * 1e6, "dur": ev.duration * 1e6,
            })
        if path is not None:
            with open(path, "w") as f:
                json.dump({"traceEvents": trace}, f)
        return trace


# -- process-global profiler (parity: GlobalProfiler, profiler.hpp:132) -----------

GlobalProfiler = Profiler(source="main")
_enabled = False


def enable(on: bool = True) -> None:
    global _enabled
    _enabled = on


def is_enabled() -> bool:
    return _enabled


@contextlib.contextmanager
def profiled(name: str, type: EventType = EventType.COMPUTE,
             profiler: Optional[Profiler] = None) -> Iterator[None]:
    """Time a block into ``profiler`` (default: GlobalProfiler); no-op when disabled
    and no explicit profiler given — keeps the hot loop clean at zero cost."""
    p = profiler or (GlobalProfiler if _enabled else None)
    if p is None:
        yield
        return
    with p.scope(name, type):
        yield


@contextlib.contextmanager
def device_trace(logdir: str) -> Iterator[None]:
    """Capture a device-side XPlane trace via jax.profiler (view with xprof/
    tensorboard). The TPU-native analog of the reference's COMPUTE event stream —
    per-HLO timing straight from the runtime rather than host-side wall clocks."""
    import jax

    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
