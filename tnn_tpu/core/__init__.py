from . import dtypes, module, rng

__all__ = ["dtypes", "module", "rng"]
