"""Dtype system for the TPU-native framework.

Capability parity with the reference's type layer (``include/type/type.hpp:76`` ``DType_t``,
``TypeTraits`` at ``include/type/type.hpp:30-60``, dispatch macros at ``:226``/``:252``), but
TPU-first: bf16 is the *native* compute type (the reference emulates it in software,
``include/type/bf16.hpp``), and dispatch is by jnp dtype rather than C++ template expansion.

The reference gives every layer three dtypes — io, param, compute
(``include/nn/layer.hpp:117-119``). We keep that exact contract as :class:`DTypePolicy`.
"""
from __future__ import annotations

import dataclasses
from typing import Any

import jax.numpy as jnp
import numpy as np

# Canonical name -> jnp dtype. Mirrors the reference's DType_t enum members
# (include/type/type.hpp:76): f32, f64, f16, bf16, i8..i64, u8..u64, bool.
_NAME_TO_DTYPE = {
    "float32": jnp.float32,
    "float64": jnp.float64,
    "float16": jnp.float16,
    "bfloat16": jnp.bfloat16,
    "int8": jnp.int8,
    "int16": jnp.int16,
    "int32": jnp.int32,
    "int64": jnp.int64,
    "uint8": jnp.uint8,
    "uint16": jnp.uint16,
    "uint32": jnp.uint32,
    "uint64": jnp.uint64,
    "bool": jnp.bool_,
}

_ALIASES = {
    "f32": "float32",
    "f64": "float64",
    "f16": "float16",
    "bf16": "bfloat16",
    "half": "float16",
    "float": "float32",
    "double": "float64",
}


def canonical_name(dtype: Any) -> str:
    """Canonical string name for a dtype or dtype name."""
    if isinstance(dtype, str):
        name = _ALIASES.get(dtype, dtype)
        if name not in _NAME_TO_DTYPE:
            raise ValueError(f"unknown dtype name: {dtype!r}")
        return name
    name = jnp.dtype(dtype).name
    if name not in _NAME_TO_DTYPE:
        raise ValueError(f"unsupported dtype: {dtype!r}")
    return name


def resolve(dtype: Any):
    """Resolve a dtype name/object to a jnp dtype (parity: dtype_of<T>, type.hpp:91)."""
    return _NAME_TO_DTYPE[canonical_name(dtype)]


def size_of(dtype: Any) -> int:
    """Byte size of a dtype (parity: dtype size table, include/type/type.hpp)."""
    return jnp.dtype(resolve(dtype)).itemsize


def is_floating(dtype: Any) -> bool:
    return jnp.issubdtype(resolve(dtype), jnp.floating)


def epsilon(dtype: Any) -> float:
    """Comparison tolerance per dtype (parity: TypeTraits::epsilon, type.hpp:30-60).

    Used by the differential test harness; values are loose enough to absorb
    XLA fusion reassociation.
    """
    name = canonical_name(dtype)
    return {
        "float64": 1e-12,
        "float32": 1e-5,
        "float16": 1e-2,
        "bfloat16": 2e-2,
    }.get(name, 0.0)


@dataclasses.dataclass(frozen=True)
class DTypePolicy:
    """The reference's per-layer (io, param, compute) dtype triple
    (include/nn/layer.hpp:117-119), as an immutable policy object.

    On TPU the idiomatic mixed-precision recipe is bf16 io/compute with f32 params
    (master weights) — matmuls hit the MXU in bf16 while optimizer state stays f32.
    """

    io: str = "bfloat16"
    param: str = "float32"
    compute: str = "bfloat16"

    def __post_init__(self):
        object.__setattr__(self, "io", canonical_name(self.io))
        object.__setattr__(self, "param", canonical_name(self.param))
        object.__setattr__(self, "compute", canonical_name(self.compute))

    @property
    def io_dtype(self):
        return resolve(self.io)

    @property
    def param_dtype(self):
        return resolve(self.param)

    @property
    def compute_dtype(self):
        return resolve(self.compute)

    def cast_in(self, x):
        """Cast an input to the compute dtype (float inputs only)."""
        if is_floating(x.dtype):
            return x.astype(self.compute_dtype)
        return x

    def cast_param(self, p):
        """Cast a parameter to the compute dtype for use inside a kernel."""
        if is_floating(p.dtype):
            return p.astype(self.compute_dtype)
        return p

    def cast_out(self, y):
        if is_floating(y.dtype):
            return y.astype(self.io_dtype)
        return y

    def to_config(self) -> dict:
        return {"io": self.io, "param": self.param, "compute": self.compute}

    @classmethod
    def from_config(cls, cfg: dict | None) -> "DTypePolicy":
        if cfg is None:
            return cls()
        return cls(**cfg)


# Full-precision policy: everything f32 (the reference's default uniform-dtype mode).
FP32 = DTypePolicy(io="float32", param="float32", compute="float32")
# TPU-native default: bf16 io/compute, f32 master params.
MIXED_BF16 = DTypePolicy(io="bfloat16", param="float32", compute="bfloat16")

_default_policy = MIXED_BF16


def default_policy() -> DTypePolicy:
    return _default_policy


def set_default_policy(policy: DTypePolicy) -> None:
    global _default_policy
    _default_policy = policy


def finfo_max(dtype: Any) -> float:
    return float(jnp.finfo(resolve(dtype)).max)


def neg_inf(dtype: Any) -> float:
    """Large negative value for masking, safe in reduced precision (softmax -> exact 0)."""
    del dtype
    return -1e9
