"""PRNG helpers.

The reference seeds per-layer RNG via a `seed` member on Layer (include/nn/layer.hpp) and
Philox-style CUDA kernels (src/ops/cuda/kernels.cu RNG). JAX's splittable threefry keys are
the idiomatic equivalent; these helpers keep key plumbing terse inside containers.
"""
from __future__ import annotations

from typing import Iterator, Optional

import jax


def split_for(rng: Optional[jax.Array], n: int):
    """Split an optional key into n optional keys."""
    if rng is None:
        return [None] * n
    return list(jax.random.split(rng, n))


def key_stream(rng: jax.Array) -> Iterator[jax.Array]:
    """Infinite stream of fresh keys."""
    while True:
        rng, sub = jax.random.split(rng)
        yield sub
