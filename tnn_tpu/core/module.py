"""Functional module system — the TPU-native replacement for the reference's Layer contract.

Reference capability being matched (not ported):
  * ``Layer`` base class — ``include/nn/layer.hpp:44`` — with three dtypes
    (``layer.hpp:117-119``), weight init (``init_impl``), forward/backward, and JSON config
    round-trip via ``get_config()/create_from_config`` (how checkpointing *and* pipeline stage
    shipping work in the reference).
  * ``LayerFactory`` registry — ``include/nn/layers.hpp:96-164``.

TPU-first redesign: layers are *static configuration* objects; parameters and mutable state
live in pytrees owned by the caller. ``apply`` is pure, so an entire train step
(forward + loss + backward + optimizer update) JITs into ONE XLA program — per-op eager
dispatch (the reference's Task/Flow machinery, ``include/device/task.hpp:28``) is unnecessary
because XLA schedules and fuses the whole program. Backward passes come from ``jax.grad``
rather than hand-written ``backward_impl`` kernels.

Variables layout (a plain dict pytree):
  ``{"params": {...}, "state": {...}}``
``state`` holds non-gradient mutable collections (BatchNorm running stats). Layers without
state contribute empty dicts which are pruned.
"""
from __future__ import annotations

import json
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from . import dtypes as dt

# ---------------------------------------------------------------------------
# Registry (parity: LayerFactory, include/nn/layers.hpp:96-164)
# ---------------------------------------------------------------------------

_REGISTRY: Dict[str, type] = {}


def register_module(name: str):
    """Class decorator: register under ``name`` for config round-trip."""

    def wrap(cls):
        if name in _REGISTRY and _REGISTRY[name] is not cls:
            raise ValueError(f"module type {name!r} already registered")
        _REGISTRY[name] = cls
        cls.type_name = name
        return cls

    return wrap


def registered_types() -> Sequence[str]:
    return sorted(_REGISTRY)


def module_from_config(cfg: Dict[str, Any]) -> "Module":
    """Instantiate any registered module from its config dict
    (parity: LayerFactory::create_from_config, include/nn/layers.hpp:125-164)."""
    cfg = dict(cfg)
    type_name = cfg.pop("type")
    if type_name not in _REGISTRY:
        raise KeyError(f"unknown module type {type_name!r}; known: {registered_types()}")
    return _REGISTRY[type_name].from_config(cfg)


# ---------------------------------------------------------------------------
# Module base
# ---------------------------------------------------------------------------


class Module:
    """Base class for all layers/blocks.

    Subclasses define:
      * ``_init(rng, *input_shapes) -> (params, state)`` — shape-inferring param creation
        (parity: Layer::init_impl weight init, e.g. src/nn/layers_impl/dense_layer.cpp:46).
      * ``_apply(params, state, *inputs, train, rng) -> (output, new_state)`` — pure forward.

    ``name`` gives the parameter subtree key; anonymous modules get positional names from
    their parent container.
    """

    type_name: str = "module"

    def __init__(self, name: Optional[str] = None, policy: Optional[dt.DTypePolicy] = None):
        self.name = name
        self.policy = policy or dt.default_policy()

    # -- shape/param plumbing ------------------------------------------------

    def init(self, rng: jax.Array, *input_shapes) -> Dict[str, Any]:
        """Create variables for the given input shapes (tuples of ints).

        Returns ``{"params": ..., "state": ...}``.
        """
        input_shapes = tuple(_as_shape(s) for s in input_shapes)
        params, state = self._init(rng, *input_shapes)
        return {"params": params, "state": state}

    def apply(
        self,
        variables: Dict[str, Any],
        *inputs,
        train: bool = False,
        rng: Optional[jax.Array] = None,
        **kwargs,
    ):
        """Pure forward. Returns ``(output, new_state)``.

        ``new_state`` echoes ``variables["state"]`` (updated when train=True for stateful
        layers such as BatchNorm). Extra kwargs pass through to ``_apply`` for layers
        with additional knobs (e.g. PositionalEmbedding's ``offset``).
        """
        params = variables.get("params", {})
        state = variables.get("state", {})
        return self._apply(params, state, *inputs, train=train, rng=rng, **kwargs)

    def __call__(self, variables, *inputs, train: bool = False, rng=None, **kwargs):
        out, _ = self.apply(variables, *inputs, train=train, rng=rng, **kwargs)
        return out

    # -- to be overridden ----------------------------------------------------

    def _init(self, rng, *input_shapes):
        return {}, {}

    def _apply(self, params, state, *inputs, train, rng):
        raise NotImplementedError

    def output_shape(self, *input_shapes) -> Tuple[int, ...]:
        """Static shape inference — drives the builder DSL and the partitioner
        (parity: LayerBuilder shape inference, include/nn/layer_builder.hpp:11)."""
        raise NotImplementedError(f"{type(self).__name__} does not implement output_shape")

    # -- config round-trip ---------------------------------------------------

    def get_config(self) -> Dict[str, Any]:
        """JSON-safe config (parity: Layer::get_config, include/nn/layer.hpp).

        Subclasses extend via ``_config()``.
        """
        cfg: Dict[str, Any] = {"type": self.type_name}
        if self.name is not None:
            cfg["name"] = self.name
        cfg["policy"] = self.policy.to_config()
        cfg.update(self._config())
        return cfg

    def _config(self) -> Dict[str, Any]:
        return {}

    @classmethod
    def from_config(cls, cfg: Dict[str, Any]) -> "Module":
        cfg = dict(cfg)
        cfg.pop("type", None)
        policy = cfg.pop("policy", None)
        return cls(**cfg, policy=dt.DTypePolicy.from_config(policy))

    def to_json(self, **kw) -> str:
        return json.dumps(self.get_config(), **kw)

    def __repr__(self):
        cfg = {k: v for k, v in self.get_config().items() if k not in ("policy",)}
        args = ", ".join(f"{k}={v!r}" for k, v in cfg.items() if k != "type")
        return f"{type(self).__name__}({args})"


def _as_shape(s) -> Tuple[int, ...]:
    if hasattr(s, "shape"):
        return tuple(s.shape)
    return tuple(int(d) for d in s)


# ---------------------------------------------------------------------------
# Param tree utilities (parity: GraphContext param slab bookkeeping,
# include/nn/graph_context.hpp:37-89 — on TPU, XLA owns placement, we keep the census)
# ---------------------------------------------------------------------------


def param_count(tree) -> int:
    leaves = jax.tree_util.tree_leaves(tree)
    return int(sum(x.size for x in leaves))


def param_bytes(tree) -> int:
    leaves = jax.tree_util.tree_leaves(tree)
    return int(sum(x.size * jnp.dtype(x.dtype).itemsize for x in leaves))


def tree_paths(tree) -> Dict[str, Any]:
    """Flatten a pytree into {'a/b/c': leaf} path dict (checkpoint naming)."""
    out = {}
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    for path, leaf in flat:
        key = "/".join(_path_str(p) for p in path)
        out[key] = leaf
    return out


def _path_str(p) -> str:
    if isinstance(p, jax.tree_util.DictKey):
        return str(p.key)
    if isinstance(p, jax.tree_util.SequenceKey):
        return str(p.idx)
    if isinstance(p, jax.tree_util.GetAttrKey):
        return str(p.name)
    return str(p)


def zeros_like_tree(tree):
    return jax.tree_util.tree_map(jnp.zeros_like, tree)
