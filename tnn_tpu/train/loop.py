"""The full training loop: epochs, validation, checkpointing, metrics.

Parity: reference ``train_model`` (src/nn/train.cpp:367) -> ``train_val`` (:219) /
``train_step`` (:274) -> ``train_epoch`` (:129): per-batch forward/loss/backward/update,
progress prints every N batches with loss/accuracy/ms-per-batch, per-epoch validation
(``validate_model`` :388), best-validation checkpointing to ``model_snapshots/``
(:242-255), RSS memory prints (:269).

TPU-first differences: the per-batch body is ONE compiled XLA program (make_train_step);
batches stream through a background prefetcher that overlaps host assembly + H2D with
device compute; checkpoints capture optimizer/scheduler/loader state so resume is exact
(the reference restarts moments and data order — SURVEY.md §5).
"""
from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import numpy as np

from ..checkpoint import Checkpoint
from ..data.loader import DataLoader, prefetch
from ..profiling import EventType, GlobalProfiler, profiled
from ..profiling import profiler as _prof_mod
from ..utils.config import TrainingConfig
from ..utils.hardware import memory_usage_kb
from ..utils.logging import get_logger
from .step import TrainState, create_train_state, make_eval_step, make_train_step


def _staged_batches(loader: DataLoader, batch_size: int, config: TrainingConfig,
                    reset: bool = True, limit: int = -1, place=None):
    """io-dtype cast on the producer thread + async device_put, so both the cast and
    the H2D transfer overlap device compute (prefetch's to_device staging).

    ``limit`` bounds the number of batches at the SOURCE (not a consumer-side break):
    the prefetch producer must not advance the loader cursor past what the step loop
    consumes, or mid-epoch checkpoints would record an overshot dataset position.
    """
    import itertools

    import jax.numpy as jnp

    io_dtype = jnp.dtype(config.io_dtype)

    def gen():
        it = loader.batches(batch_size, reset=reset)
        if limit >= 0:
            it = itertools.islice(it, limit)
        for data, labels in it:
            if np.issubdtype(data.dtype, np.floating):
                data = data.astype(io_dtype)
            yield data, labels

    return prefetch(gen(), to_device=place if place is not None else True)


def evaluate(eval_step, state: TrainState, loader: DataLoader, batch_size: int,
             config: Optional[TrainingConfig] = None,
             place=None) -> Dict[str, float]:
    """Full-dataset validation (parity: validate_model, src/nn/train.cpp:388) —
    aggregates corrects/loss over all complete batches."""
    total, corrects, loss_sum, batches = 0, 0.0, 0.0, 0
    cfg = config or TrainingConfig()
    for data, labels in _staged_batches(loader, batch_size, cfg, place=place):
        m = eval_step(state, data, labels)
        loss_sum += float(m["loss"])
        if "corrects" in m:
            corrects += float(m["corrects"])
        total += len(labels)
        batches += 1
    if batches == 0:
        # dataset smaller than one batch (drop-remainder): report honestly rather
        # than a fake perfect loss; NaN also never wins the best-val comparison
        return {"loss": float("nan")}
    out = {"loss": loss_sum / batches}
    if total:
        out["accuracy"] = corrects / total
    return out


def train_model(
    model,
    config: TrainingConfig,
    train_loader: DataLoader,
    val_loader: Optional[DataLoader] = None,
    optimizer=None,
    scheduler=None,
    augment: Optional[Callable] = None,
    state: Optional[TrainState] = None,
    metric_hook: Optional[Callable[[int, Dict[str, Any]], None]] = None,
    state_hook: Optional[Callable[[TrainState], None]] = None,
) -> Tuple[TrainState, List[Dict[str, Any]]]:
    """Train ``model`` per ``config``; returns (final_state, per-epoch history).

    The reference equivalent is train_model (src/nn/train.cpp:367) driving
    train_epoch/validate_model with best-val snapshots.

    ``state_hook`` receives the live TrainState at setup, at every progress-print
    interval, and at each epoch end — it is how a control-plane save RPC arriving
    MID-training can snapshot current weights (parity: worker SAVE_TO_FILE,
    include/distributed/worker.hpp:287-303, which the reference can service any
    time because its weights live in mutable host/device slabs).
    """
    log = get_logger("tnn.train")
    if config.log_file:
        # per-run file: replace sinks from previous runs, but leave caller-attached
        # sinks alone when this run doesn't request a file
        log.set_file_sink(config.log_file)
    profiler_mode = config.profiler_type.upper()
    profiling_on = profiler_mode not in ("", "NONE")
    cumulative_prof = profiler_mode == "CUMULATIVE"
    optimizer = optimizer or config.make_optimizer()
    scheduler = scheduler or config.make_scheduler()
    plateau = getattr(scheduler, "host_driven", False)

    batch_size = int(config.batch_size)
    sample_shape = tuple(train_loader.data_shape)
    input_shape = (batch_size,) + sample_shape
    rng = jax.random.PRNGKey(config.seed)

    # multi-chip: mesh_axes drives the parallel layout from config (parity:
    # the reference's mode/endpoint config, examples/tcp_coordinator.cpp:27-97):
    #   {"data": 8}                 -> DP, grads all-reduced by GSPMD
    #   {"data": 4, "fsdp": 2}      -> DP + ZeRO-style param sharding
    #   {"data": 2, "model": 4}     -> DP x Megatron TP (transformers)
    #   {"pipe": 4}                 -> compiled heterogeneous pipeline
    #   {"data": 2, "pipe": 4}      -> DP x PP in one program
    # (the reference offers data OR pipeline per run; its DP never all-reduces,
    # coordinator.hpp:37-40)
    axes = {k: int(v) for k, v in (config.mesh_axes or {}).items() if int(v) > 1}
    mesh = None
    place_batch = None
    pipe = None
    if "pipe" in axes:
        from .. import parallel
        from ..parallel import partitioner
        from ..parallel.pipeline import (make_pipeline_eval_step,
                                         make_pipeline_train_step)

        bad = set(axes) - {"pipe", "data"}
        if bad:
            raise ValueError(f"pipeline runs compose with 'data' only; got {axes}")
        pp, dp = axes["pipe"], axes.get("data", 1)
        if int(config.gradient_accumulation_steps) > 1:
            raise ValueError(
                "pipeline runs accumulate over num_microbatches; "
                "gradient_accumulation_steps > 1 would be silently ignored — "
                "set num_microbatches instead")
        num_mb = max(1, int(config.num_microbatches))
        if batch_size % (num_mb * dp):
            raise ValueError(f"batch_size {batch_size} not divisible by "
                             f"num_microbatches*data = {num_mb}*{dp}")
        mb_global = batch_size // num_mb
        mesh = parallel.make_mesh(data=dp, pipe=pp)
        virtual = max(1, int(getattr(config, "pipeline_virtual", 1)))
        stages = partitioner.partition_model(
            model, virtual * pp, (mb_global,) + sample_shape,
            strategy="balanced")
        io_dtype = jax.numpy.dtype(config.io_dtype)
        pipe, step_fn, init_fn = make_pipeline_train_step(
            stages, optimizer, mesh, (mb_global,) + sample_shape,
            loss_fn=config.loss, num_microbatches=num_mb,
            input_dtype=io_dtype, scheduler=scheduler,
            data_axis="data" if dp > 1 else None, augment=augment,
            remat=config.remat, virtual=virtual)
        if state is None:
            state = init_fn(rng)
        eval_fn = make_pipeline_eval_step(pipe)
        log.info("pipeline mesh %s: %d stages x %d microbatches (dp=%d, v=%d)",
                 dict(mesh.shape), virtual * pp, num_mb, dp, virtual)
    else:
        if state is None:
            state = create_train_state(model, optimizer, rng, input_shape)
        ring = None  # set by the seq branch; wraps eval too
        if axes:
            from .. import parallel

            unsupported = set(axes) - {"data", "fsdp", "model", "seq", "expert"}
            if unsupported:
                raise ValueError(
                    f"train_model auto-sharding handles data/fsdp/model/seq/"
                    f"expert/pipe axes; got {axes}.")
            shard_ways = axes.get("data", 1) * axes.get("fsdp", 1)
            if batch_size % shard_ways:
                raise ValueError(
                    f"batch_size {batch_size} not divisible by the "
                    f"data*fsdp mesh size {shard_ways} (mesh_axes={axes})")
            if axes.get("expert", 1) > 1:
                # same guard as the seq branch: an expert axis with nothing to
                # shard silently replicates all work N ways
                from jax.sharding import PartitionSpec as _P

                from ..nn.moe import ep_rules
                from ..parallel.tensor_parallel import spec_tree

                ep_specs = spec_tree(state.params, ep_rules())
                if all(s == _P() for s in jax.tree_util.tree_leaves(
                        ep_specs, is_leaf=lambda x: isinstance(x, _P))):
                    raise ValueError(
                        f"mesh_axes={{'expert': {axes['expert']}}} but the "
                        f"model has no MoE expert parameters — "
                        f"{axes['expert']}x devices would replicate work "
                        f"with zero speedup")
            mesh = parallel.make_mesh(
                **{k: axes.get(k, 1)
                   for k in ("data", "fsdp", "model", "seq", "expert")})
            step_fn, place_state, _place = parallel.make_dp_train_step(
                model, optimizer, mesh, loss_fn=config.loss, scheduler=scheduler,
                fsdp=axes.get("fsdp", 1) > 1, tp=axes.get("model", 1) > 1,
                ep=axes.get("expert", 1) > 1,
                grad_accum=config.gradient_accumulation_steps, augment=augment,
                remat=config.remat)
            if axes.get("seq", 1) > 1:
                # sequence/context parallelism: run steps inside a ring
                # context — every sdpa call becomes ring attention with K/V
                # rotating over ICI, with NO model mutation (checkpoints keep
                # their configured backend, decode works after training).
                # Beyond the reference, which has no sequence parallelism at
                # all (SURVEY.md preamble).
                from ..nn.attention import (count_attention_modules,
                                            ring_context)

                if count_attention_modules(model) == 0:
                    raise ValueError(
                        f"mesh_axes={{'seq': {axes['seq']}}} but the model has "
                        f"no attention modules — {axes['seq']}x devices would "
                        f"replicate work with zero speedup")
                if len(sample_shape) == 1 and sample_shape[0] % axes["seq"]:
                    raise ValueError(
                        f"sequence length {sample_shape[0]} not divisible by "
                        f"mesh_axes['seq'] = {axes['seq']}")
                batch_axes = tuple(a for a in ("data", "fsdp")
                                   if axes.get(a, 1) > 1)
                ring = ring_context(mesh, batch_axis=batch_axes or None,
                                    method=config.seq_parallel_method)
                base_step = step_fn

                def step_fn(state, data, labels, _f=base_step, _r=ring):
                    with _r:
                        return _f(state, data, labels)
            state = place_state(state)
            place_batch = lambda batch: _place(*batch)  # noqa: E731
            log.info("mesh %s: batch sharded over %d devices",
                     dict(mesh.shape), mesh.size)
        else:
            step_fn = make_train_step(
                model, optimizer, loss_fn=config.loss, scheduler=scheduler,
                grad_accum=config.gradient_accumulation_steps, augment=augment,
                remat=config.remat)
        base_eval = make_eval_step(model, loss_fn=config.loss)
        if mesh is not None:
            def eval_fn(state, data, labels, _f=base_eval, _m=mesh, _r=ring):
                if _r is not None:
                    with _m, _r:
                        return _f(state, data, labels)
                with _m:
                    return _f(state, data, labels)
        else:
            eval_fn = base_eval

    ckpt = Checkpoint(config.snapshot_dir)
    best_val = -float("inf")
    resumed = False
    if config.resume:
        state, meta = Checkpoint(config.resume).restore(
            state, scheduler=scheduler, loader=train_loader)
        best_val = float(meta.get("extra", {}).get("best_val", -float("inf")))
        resumed = True
        log.info("resumed from %s at step %d", config.resume, int(state.step))
        # restore loads host arrays with no sharding — re-apply the layout or
        # a resumed FSDP/TP/pipeline run silently trains fully replicated
        if pipe is not None:
            state = pipe.place_train_state(state)
        elif mesh is not None:
            state = place_state(state)

    history: List[Dict[str, Any]] = []
    if state_hook:
        state_hook(state)
    if config.shuffle and not resumed:
        train_loader.shuffle()

    # profiler state is touched ONLY when this run asked for profiling (a NONE run
    # never clobbers a caller's own enable()/events), and only right before the
    # try whose finally restores it — no leak on early setup failures
    if profiling_on:
        GlobalProfiler.clear()
        _prof_mod.enable(True)
    try:
        for epoch in range(int(config.epochs)):
            t_epoch = time.perf_counter()
            window_t0 = time.perf_counter()
            n_batches = 0
            m: Dict[str, Any] = {}

            # a resumed first epoch continues mid-epoch from the restored cursor/order
            # (an end-of-epoch checkpoint has no batches left -> start a fresh epoch)
            continue_epoch = (resumed and epoch == 0
                              and train_loader.remaining_batches(batch_size) > 0)
            for data, labels in _staged_batches(train_loader, batch_size, config,
                                                reset=not continue_epoch,
                                                limit=config.max_steps,
                                                place=place_batch):
                # host-side span = dispatch of one compiled step (device runs async; use
                # profiling.device_trace for per-HLO timing). CUMULATIVE keeps only
                # constant-memory counters; NORMAL records one event per step.
                if cumulative_prof:
                    t_step = time.perf_counter()
                    state, m = step_fn(state, data, labels)
                    GlobalProfiler.tick("train_step", time.perf_counter() - t_step)
                else:
                    with profiled(f"train_step/epoch{epoch}", EventType.COMPUTE):
                        state, m = step_fn(state, data, labels)
                n_batches += 1
                # async: pull metrics only at print interval so the device never waits
                if n_batches % max(1, config.progress_print_interval) == 0:
                    loss = float(m["loss"])
                    acc = float(m.get("accuracy", 0.0))
                    dt_batch = (time.perf_counter() - window_t0) * 1e3 / max(
                        1, config.progress_print_interval)
                    window_t0 = time.perf_counter()
                    log.info(
                        "epoch %d batch %d: loss=%.4f acc=%.4f %.1f ms/batch (%.0f samples/s)",
                        epoch, n_batches, loss, acc, dt_batch,
                        batch_size * 1e3 / max(dt_batch, 1e-9))
                    if config.print_memory_usage:
                        log.info("host RSS: %.1f MiB", memory_usage_kb() / 1024)
                    if metric_hook:
                        metric_hook(int(state.step),
                                    {"loss": loss, "accuracy": acc, "epoch": epoch})
                    if state_hook:
                        state_hook(state)

            if state_hook:
                state_hook(state)
            # final metric of the epoch (forces one sync)
            epoch_metrics: Dict[str, Any] = {
                "epoch": epoch,
                "train_loss": float(m["loss"]) if n_batches else float("nan"),
                "train_accuracy": float(m.get("accuracy", 0.0)) if n_batches else 0.0,
                "batches": n_batches,
                "epoch_seconds": time.perf_counter() - t_epoch,
            }

            if val_loader is not None:
                val = evaluate(eval_fn, state, val_loader, batch_size, config,
                               place=place_batch)
                epoch_metrics["val_loss"] = val["loss"]
                epoch_metrics["val_accuracy"] = val.get("accuracy", 0.0)
                if plateau and np.isfinite(val["loss"]):
                    scheduler.observe(val["loss"])
                score = val.get("accuracy", -val["loss"])
                if score > best_val:
                    best_val = score
                    path = ckpt.save(state, model=model, scheduler=scheduler,
                                     loader=train_loader,
                                     extra={"epoch": epoch, **val}, best=True)
                    log.info("new best val %.4f -> %s", score, path)

            # per-epoch snapshot overlaps its disk write with the next epoch
            # (block=False); best-val saves above stay blocking — their path
            # is logged and may be read back immediately
            ckpt.save(state, model=model, scheduler=scheduler, loader=train_loader,
                      extra={**epoch_metrics, "best_val": best_val}, block=False)
            log.info(
                "epoch %d done in %.1fs: train loss=%.4f acc=%.4f%s", epoch,
                epoch_metrics["epoch_seconds"], epoch_metrics["train_loss"],
                epoch_metrics["train_accuracy"],
                (f" | val loss={epoch_metrics['val_loss']:.4f} "
                 f"acc={epoch_metrics.get('val_accuracy', 0):.4f}")
                if val_loader is not None else "")
            history.append(epoch_metrics)
    finally:
        ckpt.wait()  # the last epoch's async snapshot must land before return
        if profiling_on:
            for name, s in sorted(GlobalProfiler.summary().items()):
                log.info("profile %s: n=%d total=%.3fs mean=%.1fms", name,
                         int(s["count"]), s["total_s"], s["mean_s"] * 1e3)
            for key, total in sorted(GlobalProfiler.counters.items()):
                log.info("profile counter %s: total=%.3fs", key, total)
            _prof_mod.enable(False)

    return state, history
