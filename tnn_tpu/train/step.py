"""Compiled train/eval steps.

This is the TPU-first replacement for the reference's eager per-batch hot loop
(src/nn/train.cpp:150-206: forward -> loss -> gradient -> backward -> optimizer step ->
flow sync). Here the ENTIRE step — forward, loss, backward (jax.grad), optimizer update,
metric — is one XLA program, compiled once and cached, with buffer donation so params and
optimizer state update in place on device (the reference's GraphContext slab residency,
include/nn/graph_context.hpp:37-89, maps to donated device buffers).

TrainState is the step carry: params + optimizer state + mutable net state (BatchNorm
stats) + step counter + rng key.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from ..nn import losses as losses_lib
from ..nn import metrics as metrics_lib
from ..nn.optimizers import Optimizer
from ..nn.schedulers import Scheduler, NoOp


class TrainState(NamedTuple):
    params: Any
    opt_state: Any
    net_state: Any
    step: jax.Array
    rng: jax.Array


def create_train_state(model, optimizer: Optimizer, rng: jax.Array, input_shape,
                       input_dtype=None) -> TrainState:
    init_rng, step_rng = jax.random.split(rng)
    if input_dtype is not None:
        variables = model.init(init_rng, input_shape, input_dtype=input_dtype)
    else:
        variables = model.init(init_rng, input_shape)
    return TrainState(
        params=variables["params"],
        opt_state=optimizer.init(variables["params"]),
        net_state=variables["state"],
        step=jnp.zeros((), jnp.int32),
        rng=step_rng,
    )


def make_train_step(
    model,
    optimizer: Optimizer,
    loss_fn: Callable | str = "softmax_cross_entropy",
    scheduler: Optional[Scheduler] = None,
    compute_accuracy: bool = True,
    donate: bool = True,
) -> Callable[[TrainState, jax.Array, jax.Array], Tuple[TrainState, Dict[str, jax.Array]]]:
    """Build a jitted (state, data, labels) -> (state, metrics) step.

    The scheduler's scale is traced from the step counter, so LR schedules do not
    retrigger compilation.
    """
    if isinstance(loss_fn, str):
        loss_fn = losses_lib.get(loss_fn)
    scheduler = scheduler or NoOp()
    host_driven = getattr(scheduler, "host_driven", False)

    def step(state: TrainState, data, labels, lr_scale):
        rng, sub = jax.random.split(state.rng)

        def compute_loss(params):
            out, new_net_state = model.apply(
                {"params": params, "state": state.net_state}, data, train=True, rng=sub)
            loss = loss_fn(out, labels)
            return loss, (out, new_net_state)

        (loss, (out, new_net_state)), grads = jax.value_and_grad(
            compute_loss, has_aux=True)(state.params)
        if not host_driven:
            lr_scale = scheduler.scale(state.step)
        new_params, new_opt_state = optimizer.update(
            grads, state.opt_state, state.params, lr_scale=lr_scale)
        metrics = {"loss": loss, "lr_scale": lr_scale}
        if compute_accuracy:
            metrics["accuracy"] = metrics_lib.accuracy(out, labels)
        new_state = TrainState(new_params, new_opt_state, new_net_state, state.step + 1, rng)
        return new_state, metrics

    donate_argnums = (0,) if donate else ()
    jitted = jax.jit(step, donate_argnums=donate_argnums)

    if host_driven:
        # Host-driven schedulers (ReduceLROnPlateau) feed their factor in as a runtime
        # operand — tracing scheduler.scale() would constant-fold it into the program.
        def wrapped(state, data, labels):
            return jitted(state, data, labels,
                          jnp.asarray(scheduler.current_scale(), jnp.float32))
    else:
        def wrapped(state, data, labels):
            return jitted(state, data, labels, jnp.ones((), jnp.float32))

    return wrapped


def make_eval_step(model, loss_fn: Callable | str = "softmax_cross_entropy",
                   compute_accuracy: bool = True):
    """Jitted (state, data, labels) -> metrics (no state mutation; BN uses running stats)."""
    if isinstance(loss_fn, str):
        loss_fn = losses_lib.get(loss_fn)

    @jax.jit
    def step(state: TrainState, data, labels):
        out, _ = model.apply({"params": state.params, "state": state.net_state},
                             data, train=False)
        metrics = {"loss": loss_fn(out, labels)}
        if compute_accuracy:
            metrics["corrects"] = metrics_lib.class_corrects(out, labels)
        return metrics

    return step


def make_predict(model):
    @jax.jit
    def predict(state: TrainState, data):
        out, _ = model.apply({"params": state.params, "state": state.net_state},
                             data, train=False)
        return out

    return predict
