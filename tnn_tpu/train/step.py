"""Compiled train/eval steps.

This is the TPU-first replacement for the reference's eager per-batch hot loop
(src/nn/train.cpp:150-206: forward -> loss -> gradient -> backward -> optimizer step ->
flow sync). Here the ENTIRE step — forward, loss, backward (jax.grad), optimizer update,
metric — is one XLA program, compiled once and cached, with buffer donation so params and
optimizer state update in place on device (the reference's GraphContext slab residency,
include/nn/graph_context.hpp:37-89, maps to donated device buffers).

TrainState is the step carry: params + optimizer state + mutable net state (BatchNorm
stats) + step counter + rng key.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from ..nn import losses as losses_lib
from ..nn import metrics as metrics_lib
from ..nn.optimizers import Optimizer
from ..nn.schedulers import Scheduler, NoOp


class TrainState(NamedTuple):
    params: Any
    opt_state: Any
    net_state: Any
    step: jax.Array
    rng: jax.Array


def aux_loss_sum(net_state) -> jax.Array:
    """Sum every "aux_loss" leaf a layer reported through its mutable state —
    the channel MoE layers use for their load-balancing term (nn/moe.py). A
    model with no such leaves contributes exactly 0."""
    total = jnp.zeros((), jnp.float32)
    flat, _ = jax.tree_util.tree_flatten_with_path(net_state)
    for path, leaf in flat:
        if path and getattr(path[-1], "key", None) == "aux_loss":
            total = total + leaf.astype(jnp.float32)
    return total


def create_train_state(model, optimizer: Optimizer, rng: jax.Array, input_shape,
                       input_dtype=None) -> TrainState:
    init_rng, step_rng = jax.random.split(rng)
    if input_dtype is not None:
        variables = model.init(init_rng, input_shape, input_dtype=input_dtype)
    else:
        variables = model.init(init_rng, input_shape)
    return TrainState(
        params=variables["params"],
        opt_state=optimizer.init(variables["params"]),
        net_state=variables["state"],
        step=jnp.zeros((), jnp.int32),
        rng=step_rng,
    )


def resolve_remat_policy(remat):
    """Map a ``remat`` value to a jax.checkpoint policy (None = recompute
    everything). Shared by the single-device step and the pipeline so a
    policy name means the same thing — and a typo raises — on every path."""
    if remat is True or remat in ("full", "true"):
        return None
    policies = {
        "dots": jax.checkpoint_policies.dots_saveable,
        "dots_no_batch":
            jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
        # factory: returns the policy configured for HBM -> host offload
        "offload_dots": jax.checkpoint_policies.offload_dot_with_no_batch_dims(
            "device", "pinned_host"),
    }
    if remat not in policies:
        raise ValueError(f"unknown remat policy {remat!r}; choose "
                         f"from {sorted(policies)} or True/'full'")
    return policies[remat]


def make_train_step(
    model,
    optimizer: Optimizer,
    loss_fn: Callable | str = "softmax_cross_entropy",
    scheduler: Optional[Scheduler] = None,
    compute_accuracy: bool = True,
    donate: bool = True,
    grad_accum: int = 1,
    augment: Optional[Callable] = None,
    remat: "bool | str" = False,
    lm_head_chunk: Optional[int] = None,
    steps_per_call: int = 1,
) -> Callable[[TrainState, jax.Array, jax.Array], Tuple[TrainState, Dict[str, jax.Array]]]:
    """Build a jitted (state, data, labels) -> (state, metrics) step.

    The scheduler's scale is traced from the step counter, so LR schedules do not
    retrigger compilation.

    ``grad_accum`` > 1 splits the batch into that many microbatches inside the compiled
    program (lax.scan), averaging grads before ONE optimizer update — the single-process
    analog of the reference's microbatch gradient accumulation
    (gradient_accumulation_steps, src/nn/train.cpp:176-199), with peak activation
    memory divided by the accumulation factor.

    ``augment`` is an on-device ``(rng, data) -> data`` transform (an
    AugmentationPipeline.apply); fusing it into the step keeps augmentation off the
    host (the reference runs augmentation on CPU inside the loader).

    ``remat`` rematerializes the forward in the backward (jax.checkpoint
    around model.apply): activations are recomputed instead of stored, trading
    ~1/3 more FLOPs for a large cut in peak HBM — the knob that lets long-
    context/large-batch configs fit (numerically identical, tested). Beyond
    True (recompute everything), a policy name picks the middle grounds:
    "dots" (jax.checkpoint_policies.dots_saveable) keeps MXU outputs and
    recomputes only the cheap elementwise chains — most of the memory win
    for almost no extra FLOPs; "dots_no_batch" additionally drops batch-dim
    dot outputs (closer to full remat); "offload_dots" offloads the no-batch
    dot outputs to host instead of recomputing (HBM -> DCN tradeoff).

    ``lm_head_chunk``: for LM models exposing ``apply_hidden``/``head_table``
    (GPT-2), compute the loss with nn.lm_loss.lm_head_loss — the streaming
    logsumexp over vocab chunks that never materializes (tokens, vocab) f32
    logits (the largest tensor in LM training). Replaces ``loss_fn``; logits
    do not exist, so requires compute_accuracy=False.

    ``steps_per_call`` > 1 runs that many optimizer steps in ONE dispatch via
    lax.scan: the returned function takes (W, B, ...) data/labels and returns
    mean metrics plus a per-step ``loss_trace``. This exists because each
    dispatch pays a host->device round trip — over the TPU relay tunnel here,
    milliseconds — which dominates small models (the round-4 "28k tok/s tiny
    model vs 116k synthetic GPT-2-small" cliff was exactly this per-step
    latency; the synthetic bench loops on device and syncs once). Host-driven
    schedulers see one scale per call, not per step.
    """
    if lm_head_chunk is not None:
        if compute_accuracy:
            raise ValueError("lm_head_chunk computes no logits; pass "
                             "compute_accuracy=False")
        if not (hasattr(model, "apply_hidden") and hasattr(model, "head_table")):
            raise ValueError(f"{type(model).__name__} lacks apply_hidden/"
                             "head_table; lm_head_chunk needs an LM model")
    if isinstance(loss_fn, (str, dict)):
        loss_fn = losses_lib.get(loss_fn)
    scheduler = scheduler or NoOp()
    host_driven = getattr(scheduler, "host_driven", False)
    grad_accum = int(grad_accum)

    if lm_head_chunk is None:
        def apply_model(params, net_state, data, sub):
            return model.apply({"params": params, "state": net_state}, data,
                               train=True, rng=sub)
    else:
        def apply_model(params, net_state, data, sub):
            return model.apply_hidden({"params": params, "state": net_state},
                                      data, train=True, rng=sub)

    if remat:
        policy = resolve_remat_policy(remat)
        if policy is None:
            apply_model = jax.checkpoint(apply_model)
        else:
            apply_model = jax.checkpoint(apply_model, policy=policy)

    def compute_loss(params, net_state, data, labels, sub):
        out, new_net_state = apply_model(params, net_state, data, sub)
        if lm_head_chunk is not None:
            from ..nn.lm_loss import lm_head_loss

            loss = lm_head_loss(out, model.head_table(params), labels,
                                lm_head_chunk)
        else:
            loss = loss_fn(out, labels)
        loss = loss + aux_loss_sum(new_net_state)
        return loss, (out, new_net_state)

    def step(state: TrainState, data, labels, lr_scale):
        rng, aug_rng, sub = jax.random.split(state.rng, 3)
        if augment is not None:
            data = augment(aug_rng, data)

        grad_fn = jax.value_and_grad(compute_loss, has_aux=True)
        if grad_accum == 1:
            (loss, (out, new_net_state)), grads = grad_fn(
                state.params, state.net_state, data, labels, sub)
            acc = metrics_lib.accuracy(out, labels) if compute_accuracy else None
        else:
            if data.shape[0] % grad_accum:
                raise ValueError(
                    f"batch size {data.shape[0]} not divisible by "
                    f"grad_accum {grad_accum}")
            n = data.shape[0] // grad_accum
            mb_data = data.reshape((grad_accum, n) + data.shape[1:])
            mb_labels = labels.reshape((grad_accum, n) + labels.shape[1:])
            subkeys = jax.random.split(sub, grad_accum)

            def mb_step(carry, mb):
                grads_acc, net_state, loss_acc, acc_acc = carry
                d, l, k = mb
                (loss, (out, net_state)), grads = grad_fn(
                    state.params, net_state, d, l, k)
                grads_acc = jax.tree_util.tree_map(jnp.add, grads_acc, grads)
                acc_inc = (metrics_lib.accuracy(out, l)
                           if compute_accuracy else jnp.zeros((), jnp.float32))
                return (grads_acc, net_state, loss_acc + loss, acc_acc + acc_inc), None

            zeros = jax.tree_util.tree_map(
                lambda p: jnp.zeros(p.shape, jnp.float32), state.params)
            init = (zeros, state.net_state, jnp.zeros((), jnp.float32),
                    jnp.zeros((), jnp.float32))
            (grads, new_net_state, loss, acc), _ = jax.lax.scan(
                mb_step, init, (mb_data, mb_labels, subkeys))
            inv = 1.0 / grad_accum
            grads = jax.tree_util.tree_map(lambda g: g * inv, grads)
            loss, acc = loss * inv, acc * inv

        if not host_driven:
            lr_scale = scheduler.scale(state.step)
        new_params, new_opt_state = optimizer.update(
            grads, state.opt_state, state.params, lr_scale=lr_scale)
        metrics = {"loss": loss, "lr_scale": lr_scale}
        if compute_accuracy:
            metrics["accuracy"] = acc
        new_state = TrainState(new_params, new_opt_state, new_net_state, state.step + 1, rng)
        return new_state, metrics

    steps_per_call = int(steps_per_call)
    if steps_per_call > 1:
        base_step = step

        def step(state: TrainState, data, labels, lr_scale):  # noqa: F811
            def body(st, xs):
                st, m = base_step(st, xs[0], xs[1], lr_scale)
                return st, m

            state, ms = jax.lax.scan(body, state, (data, labels))
            metrics = {k: jnp.mean(v) for k, v in ms.items()}
            metrics["loss_trace"] = ms["loss"]
            return state, metrics

    donate_argnums = (0,) if donate else ()
    jitted = jax.jit(step, donate_argnums=donate_argnums)

    if host_driven:
        # Host-driven schedulers (ReduceLROnPlateau) feed their factor in as a runtime
        # operand — tracing scheduler.scale() would constant-fold it into the program.
        def wrapped(state, data, labels):
            return jitted(state, data, labels,
                          jnp.asarray(scheduler.current_scale(), jnp.float32))
    else:
        one = jnp.ones((), jnp.float32)  # hoisted: no per-step H2D transfer

        def wrapped(state, data, labels):
            return jitted(state, data, labels, one)

    return wrapped


def make_eval_step(model, loss_fn: Callable | str = "softmax_cross_entropy",
                   compute_accuracy: bool = True):
    """Jitted (state, data, labels) -> metrics (no state mutation; BN uses running stats)."""
    if isinstance(loss_fn, (str, dict)):
        loss_fn = losses_lib.get(loss_fn)

    @jax.jit
    def step(state: TrainState, data, labels):
        out, _ = model.apply({"params": state.params, "state": state.net_state},
                             data, train=False)
        metrics = {"loss": loss_fn(out, labels)}
        if compute_accuracy:
            metrics["corrects"] = metrics_lib.class_corrects(out, labels)
        return metrics

    return step


def make_predict(model):
    """Jitted (params, net_state, data) -> logits — inference needs no TrainState."""

    @jax.jit
    def predict(params, net_state, data):
        out, _ = model.apply({"params": params, "state": net_state},
                             data, train=False)
        return out

    return predict
