from .loop import evaluate, train_model
from .step import TrainState, create_train_state, make_eval_step, make_predict, make_train_step

__all__ = ["TrainState", "create_train_state", "make_eval_step", "make_predict",
           "make_train_step", "train_model", "evaluate"]
