"""Online-softmax merge: the shared reassociation behind every partitioned
attention in this repo.

softmax(x) @ V over a row split into partitions P_1..P_N can be computed
per-partition and combined, because the partial state (m, l, acc) —

    m   = max_j x_j                      (running row max)
    l   = sum_j exp(x_j - m)             (normalizer at that max)
    acc = sum_j exp(x_j - m) * v_j       (UNnormalized weighted values)

— forms a commutative monoid under :func:`merge`. Ring attention
(``parallel/ring_attention.py``) folds partitions sequentially with
:func:`block_update`; sequence-parallel serving (``serving/sp.py``) computes
every shard's partial at once and combines across the mesh with
:func:`merge_psum`. Both are algebraically identical to one full-row
softmax; the only nonassociativity is fp rounding in ``exp``/``+``.

Identity element: ``(m, l, acc) = (-inf_proxy, 0, 0)`` — a partition that
saw no keys. :func:`merge` and :func:`merge_psum` both treat it as a true
identity, and a row whose EVERY partition is empty yields ``acc = 0``
(matching the flash/paged kernels' ``l == 0 -> output 0`` convention rather
than dividing by zero).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

#: finite stand-in for -inf so exp(m - m) stays well-defined on empty rows
NEG_INF = -1e30


def block_update(m_prev, l_prev, acc, logits, v_blk):
    """Fold one block of logits into running (m, l, acc) state — the exact
    recurrence ring attention's per-hop update has always used (kept
    verbatim so extracting it here is bit-identical for existing callers).

    ``logits``: (..., S_q, S_kv_blk) pre-softmax scores, already scaled and
    masked (dead positions at <= NEG_INF); ``v_blk``: values for the block.
    ``m_prev``/``l_prev`` are (..., S_q, 1); ``acc`` is (..., S_q, Dh).
    Returns the updated ``(m, l, acc)``.
    """
    m_cur = jnp.max(logits, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    p = jnp.exp(logits - m_new)
    l_cur = jnp.sum(p, axis=-1, keepdims=True)
    alpha = jnp.exp(m_prev - m_new)
    l_new = alpha * l_prev + l_cur
    acc = acc * alpha + jnp.einsum(
        "...qk,...kd->...qd", p.astype(v_blk.dtype), v_blk,
        preferred_element_type=jnp.float32)
    return m_new, l_new, acc


def finalize(m, l, acc, dtype=None):  # noqa: E741 — l is the normalizer
    """(m, l, acc) -> attention output: acc / l with the l == 0 -> 0 guard
    (an all-empty row attends to nothing, not to garbage)."""
    del m
    lsafe = jnp.where(l == 0.0, 1.0, l)
    out = acc / lsafe
    return out.astype(dtype) if dtype is not None else out


def merge(a, b):
    """Pairwise merge of two partial-softmax states ``(m, l, acc)``.

    Associative and commutative up to fp rounding — merge(a, merge(b, c))
    equals the single-pass state over the concatenated partitions. The
    empty state ``(NEG_INF, 0, 0)`` is the identity.
    """
    m_a, l_a, acc_a = a
    m_b, l_b, acc_b = b
    m = jnp.maximum(m_a, m_b)
    alpha_a = jnp.exp(m_a - m)
    alpha_b = jnp.exp(m_b - m)
    l = alpha_a * l_a + alpha_b * l_b  # noqa: E741
    acc = alpha_a * acc_a + alpha_b * acc_b
    return m, l, acc


def merge_psum(out, m, l, axis_name):  # noqa: E741
    """Cross-mesh combine of per-shard NORMALIZED attention outputs.

    Each shard of a sequence-parallel sweep produces its local
    ``out = acc / max(l, 1)`` plus the stats ``(m, l)`` the kernel already
    tracked — re-weighting by ``l * exp(m - m_global)`` and psum-ing
    recovers exactly the full-row softmax:

        num = sum_s out_s * l_s * exp(m_s - m*)   (= sum_s acc_s * exp(m_s - m*))
        den = sum_s l_s   * exp(m_s - m*)
        result = num / den

    ``m``/``l`` are (..., 1) per attention row, broadcast against ``out``'s
    trailing head dim. An empty shard (m = NEG_INF, l = 0) contributes 0 to
    both sums; a row empty on EVERY shard returns 0 (den == 0 guard),
    matching :func:`finalize`.
    """
    m_max = jax.lax.pmax(m, axis_name)
    w = l * jnp.exp(m - m_max)
    den = jax.lax.psum(w, axis_name)
    num = jax.lax.psum(out.astype(jnp.float32) * w, axis_name)
    den = jnp.where(den == 0.0, 1.0, den)
    return (num / den).astype(out.dtype)
