from . import flash_attention
from . import paged_attention
from . import runtime

__all__ = ["flash_attention", "paged_attention", "runtime"]
