"""Fused whole-stack decode kernel: every transformer block of one decode step
in ONE Pallas call.

Why: bs=1 autoregressive decode is latency-bound on op DISPATCH, not math.
The unfused int8 decode step issues ~1000 XLA ops per token (49 matmuls +
norms/attention/cache plumbing x 12 layers); profiling on the v5e chip showed
~75ns of sequencer gap per op plus sub-us fusions adding up to ~55% of the
254us/token device time. This kernel collapses the entire L-layer stack into a
single launch: the residual stream lives in a VMEM scratch accumulator across
a (layers, mlp-chunks) grid, per-layer int8 weights stream in as
double-buffered VMEM blocks, and the KV cache stays in HBM — each step DMAs
layer l's cache into VMEM, appends the new row at position t, and writes just
that row back through an aliased output.

Numerics exactly mirror the unfused w8a8 decode path (quant_matmul.w8a8_matmul):
activations are re-quantized to int8 per row at each matmul input (ln1 out,
attention context, ln2 out, gelu out), contractions run int8 x int8 -> int32 on
the MXU, and the per-row / per-output-channel scales multiply the int32
accumulator. The one intentional difference: the MLP runs in C chunks of the
hidden dim F (to fit VMEM), so the gelu-output quantization scale is per-chunk
absmax rather than whole-row — a strictly finer-grained (more accurate)
quantization.

Attention without per-head batched matmuls (B is tiny, T is the long axis):
  scores(h,t') = sum_d maskq[h,d] * k[t',d]   with maskq = one_hot(head) * q
one "nt" MXU gemm (Hp=128 padded heads x T), masked online over positions <= t,
then ctx(h,d) = probs @ V (one "nn" gemm) and a head-select reduction back to
(1, D). Requires head_dim == 64 x const? No — only that D = H * Dh; the head
select masks are built from iota at trace time.

Reference anchor: the reference's inference loop re-runs the FULL sequence
through the graph per generated token (examples/gpt2_inference.cpp:71-122);
this kernel is the TPU-native opposite end of that design space.
"""
from __future__ import annotations

import functools
from typing import Any, Dict

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax 0.4.x spells it TPUCompilerParams; the kwargs used here are identical
_CompilerParams = getattr(pltpu, "CompilerParams", None) \
    or pltpu.TPUCompilerParams

_HP = 128  # heads padded to one lane tile; H <= 128 covers every GPT-2 size


def _layernorm(x, scale, bias, eps=1e-5):
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    mean2 = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    var = jnp.maximum(mean2 - jnp.square(mean), 0.0)
    y = (xf - mean) * jax.lax.rsqrt(var + eps)
    return y * scale + bias


def _quant_rows(x):
    """Per-row symmetric int8 quantization (matches w8a8_matmul)."""
    absmax = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
    sx = jnp.where(absmax == 0, 1.0, absmax / 127.0)
    xi = jnp.clip(jnp.round(x / sx), -127, 127).astype(jnp.int8)
    return xi, sx


def _i8dot_nt(xi, w_q):
    """(B, K) i8 x (N, K) i8 -> (B, N) i32 on the MXU."""
    return jax.lax.dot_general(xi, w_q, (((1,), (1,)), ((), ())),
                               preferred_element_type=jnp.int32)


def _decode_kernel(t_ref, x_ref, kc, vc,
                   ln1_s, ln1_b, ln2_s, ln2_b,
                   qkv_q, qkv_s, qkv_b, out_q, out_s, out_b,
                   fc_q, fc_s, fc_b, proj_q, proj_s, proj_b,
                   x_out, kc_out, vc_out,
                   x_acc, h_ln2, kbuf, vbuf, sem_k, sem_v, sem_wb,
                   *, num_heads: int, chunks: int, scale: float):
    l = pl.program_id(0)
    c = pl.program_id(1)
    t = t_ref[0]
    B, D = x_acc.shape
    T = kbuf.shape[1]
    dh = D // num_heads

    @pl.when(jnp.logical_and(l == 0, c == 0))
    def _init():
        x_acc[...] = x_ref[...].astype(jnp.float32)

    @pl.when(c == 0)
    def _attention():
        ck = pltpu.make_async_copy(kc.at[l], kbuf, sem_k)
        cv = pltpu.make_async_copy(vc.at[l], vbuf, sem_v)
        ck.start()
        cv.start()

        x = x_acc[...]
        h = _layernorm(x, ln1_s[...], ln1_b[...])
        hi, hs = _quant_rows(h)
        qkv = (_i8dot_nt(hi, qkv_q[0]).astype(jnp.float32)
               * hs * qkv_s[...] + qkv_b[...])          # (B, 3D) f32
        q = qkv[:, :D]
        k_new = qkv[:, D:2 * D]
        v_new = qkv[:, 2 * D:]

        ck.wait()
        cv.wait()
        kbuf[:, pl.ds(t, 1), :] = k_new[:, None, :].astype(kbuf.dtype)
        vbuf[:, pl.ds(t, 1), :] = v_new[:, None, :].astype(vbuf.dtype)

        # head-select masks from iota: mask_hd[h, d] = (d // dh == h)
        hid = jax.lax.broadcasted_iota(jnp.int32, (_HP, D), 0)
        did = jax.lax.broadcasted_iota(jnp.int32, (_HP, D), 1)
        mask_hd = (did // dh == hid).astype(jnp.float32)    # (Hp, D)
        live = (jax.lax.broadcasted_iota(jnp.int32, (1, T), 1) <= t)

        # per-batch-row attention, accumulated into h_ln2's buffer reused as
        # ctx scratch via static row slices (Mosaic's concatenate support is
        # limited; indexed stores are not). B is tiny (decode); unrolled.
        for b in range(B):
            qmask = mask_hd * q[b:b + 1, :]                  # (Hp, D)
            kb = kbuf[b].astype(jnp.float32)                 # (T, D)
            scores = jax.lax.dot_general(
                qmask, kb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale  # (Hp, T)
            scores = jnp.where(live, scores, -jnp.inf)
            m = jnp.max(scores, axis=-1, keepdims=True)
            p = jnp.exp(scores - m)
            p = p / jnp.sum(p, axis=-1, keepdims=True)       # (Hp, T)
            vb = vbuf[b].astype(jnp.float32)                 # (T, D)
            ctx_full = jax.lax.dot_general(
                p, vb, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)          # (Hp, D)
            h_ln2[b:b + 1, :] = jnp.sum(ctx_full * mask_hd, axis=0,
                                        keepdims=True)       # (1, D)
        ctx = h_ln2[...]

        ci, cs = _quant_rows(ctx)
        attn_out = (_i8dot_nt(ci, out_q[0]).astype(jnp.float32)
                    * cs * out_s[...] + out_b[...])
        x_mid = x + attn_out
        h_ln2[...] = _layernorm(x_mid, ln2_s[...], ln2_b[...])
        # proj bias added once (chunk partials accumulate on top)
        x_acc[...] = x_mid + proj_b[...]

        # write the appended row back to the HBM cache (aliased in/out)
        wk = pltpu.make_async_copy(kbuf.at[:, pl.ds(t, 1), :],
                                   kc_out.at[l, :, pl.ds(t, 1), :], sem_wb)
        wk.start()
        wk.wait()
        wv = pltpu.make_async_copy(vbuf.at[:, pl.ds(t, 1), :],
                                   vc_out.at[l, :, pl.ds(t, 1), :], sem_wb)
        wv.start()
        wv.wait()

    # MLP chunk c: x_acc += proj_c(gelu(fc_c(h_ln2)))
    hi, hs = _quant_rows(h_ln2[...])
    fc = (_i8dot_nt(hi, fc_q[0]).astype(jnp.float32)
          * hs * fc_s[...] + fc_b[...])                      # (B, F/C)
    g = jax.nn.gelu(fc, approximate=True)
    gi, gs = _quant_rows(g)
    part = (_i8dot_nt(gi, proj_q[0]).astype(jnp.float32)
            * gs * proj_s[...])                              # (B, D)
    x_acc[...] = x_acc[...] + part
    x_out[...] = x_acc[...].astype(x_out.dtype)


@functools.partial(jax.jit,
                   static_argnames=("num_heads", "chunks", "interpret"))
def fused_decode_stack(x, t, k_cache, v_cache, stacks: Dict[str, Any], *,
                       num_heads: int, chunks: int = 2,
                       interpret: bool = False):
    """Run all L transformer blocks of one decode step in one Pallas call.

    x: (B, D) embedded token (wte + wpe). t: scalar int32 position (number of
    cached positions). k_cache/v_cache: (L, B, T, D) in compute dtype —
    DONATED/aliased, updated in place at position t. stacks: layer-stacked
    weights from models.fused_decode.stack_decode_weights.
    Returns (x_out (B, D), k_cache, v_cache).
    """
    B, D = x.shape
    L, Bc, T, Dc = k_cache.shape
    assert (Bc, Dc) == (B, D), (k_cache.shape, x.shape)
    F = stacks["fc_s"].shape[1]  # full hidden dim
    assert F % chunks == 0, (F, chunks)
    fchunk = F // chunks
    scale = 1.0 / (D // num_heads) ** 0.5

    t_arr = jnp.reshape(t, (1,)).astype(jnp.int32)

    def vec(name, last):
        # per-layer vectors as (L, last) f32, block (1, last)
        return pl.BlockSpec((1, last), lambda l, c: (l, 0),
                            memory_space=pltpu.VMEM)

    grid = (L, chunks)
    in_specs = [
        pl.BlockSpec(memory_space=pltpu.SMEM),                       # t
        pl.BlockSpec((B, D), lambda l, c: (0, 0),
                     memory_space=pltpu.VMEM),                       # x
        pl.BlockSpec(memory_space=pl.ANY),                           # k_cache
        pl.BlockSpec(memory_space=pl.ANY),                           # v_cache
        vec("ln1_s", D), vec("ln1_b", D), vec("ln2_s", D), vec("ln2_b", D),
        pl.BlockSpec((1, 3 * D, D), lambda l, c: (l, 0, 0),
                     memory_space=pltpu.VMEM),                       # qkv_q
        vec("qkv_s", 3 * D), vec("qkv_b", 3 * D),
        pl.BlockSpec((1, D, D), lambda l, c: (l, 0, 0),
                     memory_space=pltpu.VMEM),                       # out_q
        vec("out_s", D), vec("out_b", D),
        pl.BlockSpec((1, fchunk, D), lambda l, c: (l, c, 0),
                     memory_space=pltpu.VMEM),                       # fc_q
        pl.BlockSpec((1, fchunk), lambda l, c: (l, c),
                     memory_space=pltpu.VMEM),                       # fc_s
        pl.BlockSpec((1, fchunk), lambda l, c: (l, c),
                     memory_space=pltpu.VMEM),                       # fc_b
        pl.BlockSpec((1, D, fchunk), lambda l, c: (l, 0, c),
                     memory_space=pltpu.VMEM),                       # proj_q
        vec("proj_s", D), vec("proj_b", D),
    ]
    out_specs = [
        pl.BlockSpec((B, D), lambda l, c: (0, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec(memory_space=pl.ANY),
        pl.BlockSpec(memory_space=pl.ANY),
    ]
    out_shape = [
        jax.ShapeDtypeStruct((B, D), x.dtype),
        jax.ShapeDtypeStruct(k_cache.shape, k_cache.dtype),
        jax.ShapeDtypeStruct(v_cache.shape, v_cache.dtype),
    ]
    kern = functools.partial(_decode_kernel, num_heads=num_heads,
                             chunks=chunks, scale=scale)
    f = pl.pallas_call(
        kern, grid=grid,
        in_specs=in_specs, out_specs=out_specs, out_shape=out_shape,
        input_output_aliases={2: 1, 3: 2},
        scratch_shapes=[
            pltpu.VMEM((B, D), jnp.float32),        # x_acc
            pltpu.VMEM((B, D), jnp.float32),        # h_ln2
            pltpu.VMEM((B, T, D), k_cache.dtype),   # kbuf
            pltpu.VMEM((B, T, D), v_cache.dtype),   # vbuf
            pltpu.SemaphoreType.DMA, pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")),
        interpret=interpret,
    )
    x_out, kc, vc = f(
        t_arr, x, k_cache, v_cache,
        stacks["ln1_s"], stacks["ln1_b"], stacks["ln2_s"], stacks["ln2_b"],
        stacks["qkv_q"], stacks["qkv_s"], stacks["qkv_b"],
        stacks["out_q"], stacks["out_s"], stacks["out_b"],
        stacks["fc_q"], stacks["fc_s"], stacks["fc_b"],
        stacks["proj_q"], stacks["proj_s"], stacks["proj_b"],
    )
    return x_out, kc, vc
