"""Ragged paged-attention kernel (Pallas TPU) + XLA-lax reference.

The serving engine's attention hot path (arXiv:2604.15464's storage model):
each request's KV cache lives in fixed-size pages of the pool arrays

    pages_k, pages_v : (L, num_blocks, H_kv, block_size, head_dim)

and a per-request *block table* names its pages in logical order. The old
decode step materialized every live request's full cache contiguously
(``serving.kv_pool.gather_kv``) before attending — O(B * T_max) HBM copies per
token. This kernel consumes the pages DIRECTLY: the block tables and per-row
kv lengths are scalar-prefetched, the BlockSpec index maps chase the tables,
and flash-style online softmax accumulates over the streamed pages — so the
only KV traffic per step is the KV actually attended over, and no contiguous
cache ever exists.

Queries are RAGGED MULTI-TOKEN: each row carries ``q_lens[b]`` live query
tokens (1 for a decode row, up to the padded chunk width for a prefill
chunk), already scattered into the row's pages, so row b's token t sits at
absolute position ``kv_lens[b] - q_lens[b] + t`` and attends causally against
its own chunk plus every previously written position. ``q_lens = 1``
reproduces the PR 2 decode kernel exactly; this is what lets the engine pack
decode rows and prefill chunks into ONE compiled mixed step.

Grid: ``(B, H_kv, num_table_entries)`` — the innermost axis sweeps one row's
block table; the (m, l, acc) scratch carries the online softmax across it.
Because the grid's head axis never mixes heads, tensor-parallel serving
(``serving/tp.py``) runs this kernel UNMODIFIED per shard: each shard's pool
slice holds ``H_kv/tp`` heads of every page, the kernel sweeps it with the
same block tables (replicated host-side), and the head axis of q/out is just
locally smaller.
Grouped-query attention is zero-copy: q is viewed as (B, Q, H_kv, G, Dh) and
each grid step attends the whole (Q * G)-row query block against one fetched
kv page. Pages past a row's live length clamp their fetch index to the last
live page, so the Pallas pipeline elides the dead DMAs (same trick as
flash_attention's causal dead-block clamp), and ``pl.when`` skips their
compute.

``paged_attention_reference`` is the same math in plain lax (gather the tables
into a contiguous cache, masked softmax) — the parity oracle for the kernel
and the CPU/interpret fallback the router picks off-TPU, mirroring how
``flash_attention`` routes. ``scatter_kv_rows`` / ``scatter_kv_chunk`` are the
write half of the page contract: the new KV rows per sequence per step.

INT8 PAGES (``QuantPages``): decode is HBM-bandwidth-bound on KV bytes, so
the pool may store pages as int8 with a per-(position, head) f32 scale
sidecar riding alongside (same block ids, same layout, Dh collapsed to 1).
The scatters quantize rows symmetrically at write time (``quantize_kv_rows``
— the same scale = amax/127 rule as ``nn.attention``'s per-model int8
cache), and both consumers dequantize at READ: the kernel inside its
online-softmax loop (K/V HBM traffic stays int8 bytes + one f32 scale per
row; compute-dtype K/V never exists in HBM), the XLA reference at its
gather. Quantized attention is gated by closeness, not bit-exactness — the
f32 code paths below are byte-untouched.
"""
from __future__ import annotations

import functools
import math
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .runtime import interpret_default

# jax 0.4.x spells it TPUCompilerParams; the kwargs used here are identical
_CompilerParams = getattr(pltpu, "CompilerParams", None) \
    or pltpu.TPUCompilerParams

_NEG_INF = -1e30


class QuantPages(NamedTuple):
    """Int8 KV pages + per-(position, head) f32 scale sidecar.

    ``data`` is the pool page array quantized to int8, ``scale`` the same
    layout with the head_dim axis collapsed to 1 — scale[l, n, h, s, 0]
    dequantizes row data[l, n, h, s, :]. A NamedTuple is a pytree, so the
    bundle flows through jit (``donate_argnums`` donates BOTH buffers) and
    through ``pool.update_pages`` unchanged; the two arrays share one
    block-id space, so alloc/free/fork/evict bookkeeping needs no second
    ledger.
    """
    data: jax.Array    # (L, N, H_kv, bs, Dh) int8
    scale: jax.Array   # (L, N, H_kv, bs, 1)  float32


def quantize_kv_rows(x):
    """Symmetric per-row (per position, per head) int8 over the last axis:
    scale = amax/127 — the same quantizer as ``nn.attention``'s per-model
    int8 cache, so pool-int8 and cache-int8 closeness gates measure the
    same arithmetic. Returns (int8 values, f32 scales with last axis 1)."""
    xf = x.astype(jnp.float32)
    scale = jnp.maximum(jnp.max(jnp.abs(xf), axis=-1, keepdims=True),
                        1e-8) / 127.0
    q = jnp.clip(jnp.round(xf / scale), -127, 127).astype(jnp.int8)
    return q, scale


def _attn_kernel(tables_ref, lens_ref, qlens_ref, layer_ref, q_ref, k_ref,
                 v_ref, *refs, scale: float, bs: int, g: int, qw: int,
                 stats: bool = False):
    del layer_ref  # consumed by the index maps, not the body

    def load_kv():
        return k_ref[0, 0, 0], v_ref[0, 0, 0]    # (bs, Dh) — one page

    _attn_step(tables_ref, lens_ref, qlens_ref, q_ref, load_kv, refs,
               scale=scale, bs=bs, g=g, qw=qw, stats=stats)


def _attn_kernel_int8(tables_ref, lens_ref, qlens_ref, layer_ref, q_ref,
                      k_ref, v_ref, ks_ref, vs_ref, *refs, scale: float,
                      bs: int, g: int, qw: int, stats: bool = False):
    del layer_ref

    def load_kv():
        # in-VMEM dequant inside the online-softmax sweep: the page arrives
        # as int8 + one f32 scale per row, so HBM traffic is int8 bytes on
        # this backend too (the load runs under the same pl.when as the
        # block's compute — dead pages fetch nothing extra). NOTE: int8's
        # minimum TPU tile is (32, 128) sublane x lane; blocks smaller than
        # that lean on Mosaic's relayout and lose part of the traffic win.
        k = k_ref[0, 0, 0].astype(jnp.float32) * ks_ref[0, 0, 0]
        v = v_ref[0, 0, 0].astype(jnp.float32) * vs_ref[0, 0, 0]
        return k, v

    _attn_step(tables_ref, lens_ref, qlens_ref, q_ref, load_kv, refs,
               scale=scale, bs=bs, g=g, qw=qw, stats=stats)


def _attn_step(tables_ref, lens_ref, qlens_ref, q_ref, load_kv, refs, *,
               scale: float, bs: int, g: int, qw: int, stats: bool):
    """Shared online-softmax body: the f32 and int8 kernels differ ONLY in
    how a page's K/V reaches the MXU (``load_kv``), keeping the two in
    lockstep by construction.

    ``refs`` is (o_ref, [m_ref, l_ref when stats], m_scr, l_scr, acc_scr) —
    with ``stats`` the kernel also emits its per-row online-softmax state
    (running max ``m``, normalizer ``l``), which is exactly the partial a
    sequence-parallel shard needs for ``ops.softmax_merge.merge_psum``.
    """
    if stats:
        o_ref, m_ref, l_ref, m_scr, l_scr, acc_scr = refs
    else:
        (o_ref, m_scr, l_scr, acc_scr), m_ref, l_ref = refs, None, None
    b = pl.program_id(0)
    j = pl.program_id(2)
    nj = pl.num_programs(2)
    dh = q_ref.shape[-1]

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)   # (Q*g, 1) running max
        l_scr[:] = jnp.zeros_like(l_scr)            # (Q*g, 1) running denom
        acc_scr[:] = jnp.zeros_like(acc_scr)        # (Q*g, Dh) output acc

    kv_len = lens_ref[b]
    q_live = qlens_ref[b]

    # a NEGATIVE table entry is a dead hole — sequence-parallel serving
    # stamps -1 at positions another shard owns; the fetch index map clamps
    # it to page 0 and this predicate skips the block entirely
    @pl.when((j * bs < kv_len) & (tables_ref[b, j] >= 0))
    def _block():
        q = q_ref[0, :, 0].reshape(qw * g, dh)   # whole ragged query chunk
        k, v = load_kv()
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        kpos = j * bs + jax.lax.broadcasted_iota(jnp.int32, (qw * g, bs), 1)
        trow = jax.lax.broadcasted_iota(jnp.int32, (qw, g), 0) \
            .reshape(qw * g, 1)
        # query token t sits at absolute position start + t with
        # start = kv_len - q_live: causal over its own chunk AND over every
        # previously written position; rows past q_live are fully masked
        # (q_live = 1 degenerates to the decode mask kpos < kv_len)
        mask = (kpos <= kv_len - q_live + trow) & (trow < q_live)
        s = jnp.where(mask, s, _NEG_INF)
        m_prev, l_prev = m_scr[:], l_scr[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.where(mask, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m_prev - m_new)
        l_scr[:] = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = m_new

    @pl.when(j == nj - 1)
    def _final():
        l = l_scr[:]
        lsafe = jnp.where(l == 0.0, 1.0, l)  # fully-masked rows -> exactly 0
        o_ref[0, :, 0] = (acc_scr[:] / lsafe).astype(o_ref.dtype) \
            .reshape(qw, g, dh)
        if stats:
            m_ref[0, :, 0] = m_scr[:].reshape(qw, g, 1)
            l_ref[0, :, 0] = l[:].reshape(qw, g, 1)


def _paged_attention_pallas(q, pages_k, pages_v, block_tables, kv_lens,
                            q_lens, layer, scale, interpret, stats=False):
    quant = isinstance(pages_k, QuantPages)
    b, qw, h, dh = q.shape
    _, _, hkv, bs, _ = (pages_k.data if quant else pages_k).shape
    g = h // hkv
    nb = block_tables.shape[1]
    qg = q.reshape(b, qw, hkv, g, dh)
    tables = block_tables.astype(jnp.int32)
    lens = kv_lens.astype(jnp.int32)
    qlens = q_lens.astype(jnp.int32)
    layer_arr = jnp.reshape(jnp.asarray(layer, jnp.int32), (1,))

    def kv_index(bi, hi, j, tbl, ln, qln, ly):
        # clamp dead trailing pages to the row's last live page: the repeated
        # block index lets the pipeline elide the DMA (compute is pl.when-
        # skipped); max(len, 1) keeps fully-dead rows fetching page 0, and
        # the outer max clamps -1 holes (pages another SP shard owns — their
        # compute is pl.when-skipped on the table-entry sign) to page 0 too
        nlive = (jnp.maximum(ln[bi], 1) + bs - 1) // bs
        return (ly[0], jnp.maximum(tbl[bi, jnp.minimum(j, nlive - 1)], 0),
                hi, 0, 0)

    def q_index(bi, hi, j, tbl, ln, qln, ly):
        return (bi, 0, hi, 0, 0)

    in_specs = [
        pl.BlockSpec((1, qw, 1, g, dh), q_index),
        pl.BlockSpec((1, 1, 1, bs, dh), kv_index),
        pl.BlockSpec((1, 1, 1, bs, dh), kv_index),
    ]
    operands = [qg]
    if quant:
        # the scale sidecars chase the SAME block-table index maps as their
        # pages, so a clamped dead-page fetch elides both DMAs together
        in_specs += [pl.BlockSpec((1, 1, 1, bs, 1), kv_index),
                     pl.BlockSpec((1, 1, 1, bs, 1), kv_index)]
        operands += [pages_k.data, pages_v.data, pages_k.scale,
                     pages_v.scale]
        kernel = _attn_kernel_int8
    else:
        operands += [pages_k, pages_v]
        kernel = _attn_kernel

    out_specs = pl.BlockSpec((1, qw, 1, g, dh), q_index)
    out_shape = jax.ShapeDtypeStruct((b, qw, hkv, g, dh), q.dtype)
    if stats:
        # per-row online-softmax state rides along as two extra outputs —
        # the sequence-parallel merge's inputs (ops.softmax_merge)
        stat_spec = pl.BlockSpec((1, qw, 1, g, 1), q_index)
        stat_shape = jax.ShapeDtypeStruct((b, qw, hkv, g, 1), jnp.float32)
        out_specs = (out_specs, stat_spec, stat_spec)
        out_shape = (out_shape, stat_shape, stat_shape)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(b, hkv, nb),
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=[
            pltpu.VMEM((qw * g, 1), jnp.float32),
            pltpu.VMEM((qw * g, 1), jnp.float32),
            pltpu.VMEM((qw * g, dh), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(kernel, scale=scale, bs=bs, g=g, qw=qw,
                          stats=stats),
        grid_spec=grid_spec,
        out_shape=out_shape,
        # scratch carries only along the innermost (page) sweep
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(tables, lens, qlens, layer_arr, *operands)
    if stats:
        o, m, l = out  # noqa: E741
        return (o.reshape(b, qw, h, dh), m.reshape(b, qw, h, 1),
                l.reshape(b, qw, h, 1))
    return out.reshape(b, qw, h, dh)


def _gather_pages(pages, block_tables, layer, b, hkv, t, dh):
    if isinstance(pages, QuantPages):
        x = pages.data[layer][block_tables]  # (B, nb, Hkv, bs, Dh) int8
        s = pages.scale[layer][block_tables]
        x = x.astype(jnp.float32) * s        # dequant AT the gather
        return x.transpose(0, 2, 1, 3, 4).reshape(b, hkv, t, dh)
    x = pages[layer][block_tables]           # (B, nb, Hkv, bs, Dh)
    return x.transpose(0, 2, 1, 3, 4).reshape(b, hkv, t, dh)


def _pages_shape(pages):
    return pages.data.shape if isinstance(pages, QuantPages) else pages.shape


def _live_positions(block_tables, kv_lens, t, bs):
    """(B, T) live mask: positions inside kv_lens whose table entry is a
    real page — NEGATIVE entries are dead holes (pages another SP shard
    owns) and mask out their whole block. Identity when no -1 is present."""
    live = jnp.arange(t)[None, :] < kv_lens[:, None]
    return live & jnp.repeat(block_tables >= 0, bs, axis=1)


def _paged_attention_xla(q, pages_k, pages_v, block_tables, kv_lens, layer,
                         scale, stats=False):
    """Single-token (decode) reference — the PR 2 math (dead -1 table
    entries additionally masked, a numeric no-op when none are present)."""
    b, h, dh = q.shape
    _, _, hkv, bs, _ = _pages_shape(pages_k)
    g = h // hkv
    t = block_tables.shape[1] * bs

    tbl = jnp.maximum(block_tables, 0)   # clamp -1 holes for the gather
    k = _gather_pages(pages_k, tbl, layer, b, hkv, t, dh)
    v = _gather_pages(pages_v, tbl, layer, b, hkv, t, dh)
    qg = q.reshape(b, hkv, g, dh)
    s = jnp.einsum("bhgd,bhtd->bhgt", qg, k,
                   preferred_element_type=jnp.float32) * scale
    live = _live_positions(block_tables, kv_lens, t, bs)  # (B, T)
    s = jnp.where(live[:, None, None, :], s, _NEG_INF)
    if stats:
        # unnormalized form, emitting the same (m, l) state as the kernel's
        # online softmax — the SP merge's inputs
        m = jnp.max(s, axis=-1, keepdims=True)            # (B, Hkv, G, 1)
        p = jnp.where(live[:, None, None, :], jnp.exp(s - m), 0.0)
        l = jnp.sum(p, axis=-1, keepdims=True)  # noqa: E741
        out = jnp.einsum("bhgt,bhtd->bhgd", p.astype(v.dtype), v,
                         preferred_element_type=jnp.float32)
        out = out / jnp.where(l == 0.0, 1.0, l)
        return (out.astype(q.dtype).reshape(b, h, dh),
                m.reshape(b, h, 1), l.reshape(b, h, 1))
    p = jax.nn.softmax(s, axis=-1)
    # rows with NO live position attend to NOTHING (output 0), matching the
    # kernel's l == 0 guard — softmax alone would return uniform garbage
    p = jnp.where(jnp.any(live, axis=-1)[:, None, None, None], p, 0.0)
    out = jnp.einsum("bhgt,bhtd->bhgd", p.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)
    return out.astype(q.dtype).reshape(b, h, dh)


def _paged_attention_xla_mq(q, pages_k, pages_v, block_tables, kv_lens,
                            q_lens, layer, scale, stats=False):
    """Multi-token-query reference: same ragged causal mask as the kernel
    (and the same dead -1 table-entry masking)."""
    b, qw, h, dh = q.shape
    _, _, hkv, bs, _ = _pages_shape(pages_k)
    g = h // hkv
    t = block_tables.shape[1] * bs

    tbl = jnp.maximum(block_tables, 0)   # clamp -1 holes for the gather
    k = _gather_pages(pages_k, tbl, layer, b, hkv, t, dh)
    v = _gather_pages(pages_v, tbl, layer, b, hkv, t, dh)
    qg = q.reshape(b, qw, hkv, g, dh)
    s = jnp.einsum("bqhgd,bhtd->bqhgt", qg, k,
                   preferred_element_type=jnp.float32) * scale
    start = (kv_lens - q_lens)[:, None]                   # (B, 1)
    tpos = jnp.arange(qw)[None, :]                        # (1, Q)
    kpos = jnp.arange(t)
    live = (kpos[None, None, :] <= (start + tpos)[:, :, None]) \
        & (tpos < q_lens[:, None])[:, :, None]            # (B, Q, T)
    live = live & jnp.repeat(block_tables >= 0, bs, axis=1)[:, None, :]
    s = jnp.where(live[:, :, None, None, :], s, _NEG_INF)
    if stats:
        m = jnp.max(s, axis=-1, keepdims=True)        # (B, Q, Hkv, G, 1)
        p = jnp.where(live[:, :, None, None, :], jnp.exp(s - m), 0.0)
        l = jnp.sum(p, axis=-1, keepdims=True)  # noqa: E741
        out = jnp.einsum("bqhgt,bhtd->bqhgd", p.astype(v.dtype), v,
                         preferred_element_type=jnp.float32)
        out = out / jnp.where(l == 0.0, 1.0, l)
        return (out.astype(q.dtype).reshape(b, qw, h, dh),
                m.reshape(b, qw, h, 1), l.reshape(b, qw, h, 1))
    p = jax.nn.softmax(s, axis=-1)
    # fully-masked query rows (padding past q_lens, or q_lens/kv_lens == 0)
    # output exactly 0, matching the kernel's l == 0 guard
    row_live = (tpos < q_lens[:, None]) & (start + tpos >= 0)   # (B, Q)
    row_live = row_live & jnp.any(live, axis=-1)
    p = jnp.where(row_live[:, :, None, None, None], p, 0.0)
    out = jnp.einsum("bqhgt,bhtd->bqhgd", p.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)
    return out.astype(q.dtype).reshape(b, qw, h, dh)


def paged_attention_reference(q, pages_k, pages_v, block_tables, kv_lens, *,
                              q_lens=None, layer=0,
                              scale: Optional[float] = None):
    """XLA-lax reference: gather the tables contiguous, masked softmax.

    Same signature/semantics as ``paged_attention`` — the parity oracle for
    the kernel and the off-TPU fallback (it IS a gather, which is exactly
    what the kernel exists to avoid on TPU)."""
    q, was_3d, q_lens, pages_k, pages_v, scale = _check_args(
        q, pages_k, pages_v, block_tables, kv_lens, q_lens, scale)
    if was_3d:
        return _paged_attention_xla(q[:, 0], pages_k, pages_v, block_tables,
                                    kv_lens, layer, scale)
    return _paged_attention_xla_mq(q, pages_k, pages_v, block_tables,
                                   kv_lens, q_lens, layer, scale)


def _check_args(q, pages_k, pages_v, block_tables, kv_lens, q_lens, scale):
    if isinstance(pages_k, QuantPages) != isinstance(pages_v, QuantPages):
        raise ValueError("pages_k / pages_v must both be QuantPages or "
                         "both plain arrays")
    if isinstance(pages_k, QuantPages):
        if pages_k.data.ndim == 4:   # single-layer: add the unit layer axis
            pages_k = QuantPages(pages_k.data[None], pages_k.scale[None])
            pages_v = QuantPages(pages_v.data[None], pages_v.scale[None])
        pk, pv = pages_k.data, pages_v.data
        for p, s in ((pages_k.data, pages_k.scale),
                     (pages_v.data, pages_v.scale)):
            if s.shape != p.shape[:-1] + (1,):
                raise ValueError(f"QuantPages scale {s.shape} must be pages "
                                 f"{p.shape} with the last axis collapsed "
                                 "to 1")
    else:
        if pages_k.ndim == 4:  # single-layer pages: add the unit layer axis
            pages_k, pages_v = pages_k[None], pages_v[None]
        pk, pv = pages_k, pages_v
    if pk.shape != pv.shape or pk.ndim != 5:
        raise ValueError(f"pages must both be (L, N, H_kv, bs, Dh); got "
                         f"{pk.shape} / {pv.shape}")
    was_3d = q.ndim == 3
    if was_3d:
        if q_lens is not None:
            raise ValueError("q_lens requires multi-token q (B, Q, H, Dh); "
                             f"got q {q.shape}")
        q = q[:, None]
    if q.ndim != 4:
        raise ValueError(f"q must be (B, H, Dh) or (B, Q, H, Dh); "
                         f"got {q.shape}")
    b, qw, h, dh = q.shape
    hkv = pk.shape[2]
    if h % hkv or pk.shape[4] != dh:
        raise ValueError(f"q has {h} heads / Dh {dh} but pages carry "
                         f"{hkv} kv heads / Dh {pk.shape[4]}; "
                         "need H % H_kv == 0 and equal head dims")
    if block_tables.shape[0] != b or kv_lens.shape != (b,):
        raise ValueError(f"block_tables {block_tables.shape} / kv_lens "
                         f"{kv_lens.shape} do not match batch {b}")
    if q_lens is None:
        q_lens = jnp.full((b,), qw, jnp.int32)
    elif q_lens.shape != (b,):
        raise ValueError(f"q_lens {q_lens.shape} does not match batch {b}")
    if scale is None:
        scale = 1.0 / math.sqrt(dh)
    return q, was_3d, q_lens, pages_k, pages_v, scale


def paged_attention(q, pages_k, pages_v, block_tables, kv_lens, *,
                    q_lens=None, layer=0, scale: Optional[float] = None,
                    backend: str = "auto",
                    interpret: Optional[bool] = None,
                    return_stats: bool = False):
    """Ragged attention for the current step's query rows over paged KV.

    q : (B, H, Dh) — decode form, one token per sequence — or (B, Q, H, Dh)
        for ragged multi-token chunks (``q_lens[b]`` live tokens per row,
        left-aligned; the rest is padding and outputs exactly 0).
    pages_k / pages_v : (L, N, H_kv, bs, Dh) pool pages (or a single layer's
        (N, H_kv, bs, Dh); ``layer`` then ignored). Never copied: the kernel
        fetches only the pages the tables name.
    block_tables : (B, nb) int32 — page ids in logical order; entries past a
        row's live pages may be anything in-range (the pool pads with its
        scratch page 0).
    kv_lens : (B,) int32 — live KV positions per row INCLUDING the rows
        written this step (the engine scatters the new rows first and passes
        ``offsets + q_lens``). A 0 row outputs exactly 0.
    q_lens : (B,) int32 — live query tokens per row (only with 4-D q;
        defaults to the full width Q). Token t of row b sits at absolute
        position ``kv_lens[b] - q_lens[b] + t`` and attends causally.
    layer : which layer's pages to read (static or traced scalar).
    backend : "pallas" (the kernel; interprets off-TPU), "xla" (the gather
        reference), or "auto" — kernel on TPU, reference elsewhere (the
        reference is faster than interpret mode and numerically identical
        up to reduction order).

    GQA: H % H_kv == 0; each kv head's page is fetched once and attended by
    its whole query-head group. Returns q's shape.

    Block-table entries may be NEGATIVE: a -1 marks a dead hole (a page
    another sequence-parallel shard owns) whose positions are skipped as if
    masked. With ``return_stats`` the per-row online-softmax state rides
    along — returns ``(out, m, l)`` with m/l shaped like out with the head
    dim collapsed to 1 — which is exactly what
    ``ops.softmax_merge.merge_psum`` needs to combine shard partials into
    the full-row softmax.
    """
    q, was_3d, q_lens, pages_k, pages_v, scale = _check_args(
        q, pages_k, pages_v, block_tables, kv_lens, q_lens, scale)
    if backend == "auto":
        backend = "pallas" if jax.default_backend() == "tpu" else "xla"
    if backend == "xla":
        if was_3d:
            out = _paged_attention_xla(q[:, 0], pages_k, pages_v,
                                       block_tables, kv_lens, layer, scale,
                                       stats=return_stats)
        else:
            out = _paged_attention_xla_mq(q, pages_k, pages_v, block_tables,
                                          kv_lens, q_lens, layer, scale,
                                          stats=return_stats)
        return out
    if backend != "pallas":
        raise ValueError(f"unknown paged-attention backend {backend!r}")
    if interpret is None:
        interpret = interpret_default()
    out = _paged_attention_pallas(q, pages_k, pages_v, block_tables,
                                  kv_lens, q_lens, layer, scale, interpret,
                                  stats=return_stats)
    if return_stats:
        o, m, l = out  # noqa: E741
        return (o[:, 0], m[:, 0], l[:, 0]) if was_3d else (o, m, l)
    return out[:, 0] if was_3d else out


def scatter_kv_rows(pages, block_tables, offsets, rows, *, layer=None):
    """Write one new KV row per sequence at its decode position.

    The write half of the page contract: ``pages`` is (L, N, H, bs, Dh) with
    ``layer`` naming the layer (or a single layer's (N, H, bs, Dh));
    ``block_tables`` (B, nb); ``offsets`` (B,) the position each row writes;
    ``rows`` (B, H, Dh). Rows whose table points at the pool's scratch page
    land there harmlessly. Returns the updated pages — under jit with the
    pool buffers donated this lowers to an in-place dynamic-update-scatter.

    QuantPages: rows are quantized HERE (write time) and the int8 data and
    f32 scale scatter through the same block-table math, so a row's scale
    can never drift from its page slot.
    """
    if isinstance(pages, QuantPages):
        qrows, srows = quantize_kv_rows(rows)
        return QuantPages(
            scatter_kv_rows(pages.data, block_tables, offsets, qrows,
                            layer=layer),
            scatter_kv_rows(pages.scale, block_tables, offsets, srows,
                            layer=layer))
    bs = pages.shape[-2]
    blk = jnp.take_along_axis(block_tables, (offsets // bs)[:, None],
                              axis=1)[:, 0]
    # -1 holes (positions another SP shard owns) divert to the scratch page
    # instead of wrapping to the LAST page and corrupting live KV
    blk = jnp.maximum(blk, 0)
    slot = offsets % bs
    # two advanced indices (blk, slot) around the sliced head axis put the
    # batch dim first in the update operand: rows is already (B, H, Dh)
    if pages.ndim == 5:
        if layer is None:
            raise ValueError("layer is required for (L, N, H, bs, Dh) pages")
        return pages.at[layer, blk, :, slot, :].set(rows)
    return pages.at[blk, :, slot, :].set(rows)


def scatter_kv_chunk(pages, block_tables, starts, rows, q_lens, *,
                     layer=None):
    """Write a ragged chunk of new KV rows per sequence.

    ``rows`` is (B, Q, H, Dh): row b's tokens t < q_lens[b] land at positions
    ``starts[b] + t`` through its block table; padding tokens (and whole rows
    with q_lens == 0) are redirected to the pool's scratch page 0, which is
    never allocated to a request, so they can't corrupt live KV. Same layer /
    donation / write-time-quantization semantics as ``scatter_kv_rows``.
    """
    if isinstance(pages, QuantPages):
        qrows, srows = quantize_kv_rows(rows)
        return QuantPages(
            scatter_kv_chunk(pages.data, block_tables, starts, qrows, q_lens,
                             layer=layer),
            scatter_kv_chunk(pages.scale, block_tables, starts, srows, q_lens,
                             layer=layer))
    bs = pages.shape[-2]
    qw = rows.shape[1]
    nbt = block_tables.shape[1]
    pos = starts[:, None] + jnp.arange(qw)                # (B, Q)
    live = jnp.arange(qw)[None, :] < q_lens[:, None]      # (B, Q)
    blk = jnp.take_along_axis(block_tables,
                              jnp.clip(pos // bs, 0, nbt - 1), axis=1)
    # dead tokens AND -1 table holes (positions another SP shard owns) land
    # in the scratch page — a raw -1 would wrap to the last page
    blk = jnp.maximum(jnp.where(live, blk, 0), 0)
    slot = pos % bs
    # advanced (blk, slot) indices around the sliced head axis broadcast to
    # (B, Q) and lead the update operand: rows is already (B, Q, H, Dh)
    if pages.ndim == 5:
        if layer is None:
            raise ValueError("layer is required for (L, N, H, bs, Dh) pages")
        return pages.at[layer, blk, :, slot, :].set(rows)
    return pages.at[blk, :, slot, :].set(rows)
