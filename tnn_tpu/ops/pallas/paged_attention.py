"""Ragged paged-attention decode kernel (Pallas TPU) + XLA-lax reference.

The serving engine's decode hot path (arXiv:2604.15464's storage model): each
request's KV cache lives in fixed-size pages of the pool arrays

    pages_k, pages_v : (L, num_blocks, H_kv, block_size, head_dim)

and a per-request *block table* names its pages in logical order. The old
decode step materialized every live request's full cache contiguously
(``serving.kv_pool.gather_kv``) before attending — O(B * T_max) HBM copies per
token. This kernel consumes the pages DIRECTLY: the block tables and per-row
kv lengths are scalar-prefetched, the BlockSpec index maps chase the tables,
and flash-style online softmax accumulates over the streamed pages — so the
only KV traffic per step is the KV actually attended over, and no contiguous
cache ever exists.

Grid: ``(B, H_kv, num_table_entries)`` — the innermost axis sweeps one row's
block table; the (m, l, acc) scratch carries the online softmax across it.
Grouped-query attention is zero-copy: q is viewed as (B, H_kv, G, Dh) and each
grid step attends its whole q-head group against one fetched kv page. Pages
past a row's live length clamp their fetch index to the last live page, so the
Pallas pipeline elides the dead DMAs (same trick as flash_attention's causal
dead-block clamp), and ``pl.when`` skips their compute.

``paged_attention_reference`` is the same math in plain lax (gather the tables
into a contiguous cache, masked softmax) — the parity oracle for the kernel
and the CPU/interpret fallback the router picks off-TPU, mirroring how
``flash_attention`` routes. ``scatter_kv_rows`` is the write half of the page
contract: the one new KV row per sequence per step.
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .runtime import interpret_default

# jax 0.4.x spells it TPUCompilerParams; the kwargs used here are identical
_CompilerParams = getattr(pltpu, "CompilerParams", None) \
    or pltpu.TPUCompilerParams

_NEG_INF = -1e30


def _decode_kernel(tables_ref, lens_ref, layer_ref, q_ref, k_ref, v_ref,
                   o_ref, m_scr, l_scr, acc_scr, *, scale: float, bs: int,
                   g: int):
    del tables_ref, layer_ref  # consumed by the index maps, not the body
    b = pl.program_id(0)
    j = pl.program_id(2)
    nj = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)   # (g, 1) running max
        l_scr[:] = jnp.zeros_like(l_scr)            # (g, 1) running denom
        acc_scr[:] = jnp.zeros_like(acc_scr)        # (g, Dh) output acc

    kv_len = lens_ref[b]

    @pl.when(j * bs < kv_len)
    def _block():
        q = q_ref[0, 0]        # (g, Dh) — one kv head's whole query group
        k = k_ref[0, 0, 0]     # (bs, Dh) — one page
        v = v_ref[0, 0, 0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        kpos = j * bs + jax.lax.broadcasted_iota(jnp.int32, (g, bs), 1)
        mask = kpos < kv_len   # ragged tail of the last live page
        s = jnp.where(mask, s, _NEG_INF)
        m_prev, l_prev = m_scr[:], l_scr[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.where(mask, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m_prev - m_new)
        l_scr[:] = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = m_new

    @pl.when(j == nj - 1)
    def _final():
        l = l_scr[:]
        lsafe = jnp.where(l == 0.0, 1.0, l)  # kv_len == 0 rows -> output 0
        o_ref[0, 0] = (acc_scr[:] / lsafe).astype(o_ref.dtype)


def _paged_attention_pallas(q, pages_k, pages_v, block_tables, kv_lens,
                            layer, scale, interpret):
    b, h, dh = q.shape
    _, _, hkv, bs, _ = pages_k.shape
    g = h // hkv
    nb = block_tables.shape[1]
    qg = q.reshape(b, hkv, g, dh)
    tables = block_tables.astype(jnp.int32)
    lens = kv_lens.astype(jnp.int32)
    layer_arr = jnp.reshape(jnp.asarray(layer, jnp.int32), (1,))

    def kv_index(bi, hi, j, tbl, ln, ly):
        # clamp dead trailing pages to the row's last live page: the repeated
        # block index lets the pipeline elide the DMA (compute is pl.when-
        # skipped); max(len, 1) keeps fully-dead rows fetching page 0
        nlive = (jnp.maximum(ln[bi], 1) + bs - 1) // bs
        return (ly[0], tbl[bi, jnp.minimum(j, nlive - 1)], hi, 0, 0)

    def q_index(bi, hi, j, tbl, ln, ly):
        return (bi, hi, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(b, hkv, nb),
        in_specs=[
            pl.BlockSpec((1, 1, g, dh), q_index),
            pl.BlockSpec((1, 1, 1, bs, dh), kv_index),
            pl.BlockSpec((1, 1, 1, bs, dh), kv_index),
        ],
        out_specs=pl.BlockSpec((1, 1, g, dh), q_index),
        scratch_shapes=[
            pltpu.VMEM((g, 1), jnp.float32),
            pltpu.VMEM((g, 1), jnp.float32),
            pltpu.VMEM((g, dh), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_decode_kernel, scale=scale, bs=bs, g=g),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, hkv, g, dh), q.dtype),
        # scratch carries only along the innermost (page) sweep
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(tables, lens, layer_arr, qg, pages_k, pages_v)
    return out.reshape(b, h, dh)


def _paged_attention_xla(q, pages_k, pages_v, block_tables, kv_lens, layer,
                         scale):
    b, h, dh = q.shape
    _, _, hkv, bs, _ = pages_k.shape
    g = h // hkv
    t = block_tables.shape[1] * bs

    def gather(pages):
        x = pages[layer][block_tables]           # (B, nb, Hkv, bs, Dh)
        return x.transpose(0, 2, 1, 3, 4).reshape(b, hkv, t, dh)

    k, v = gather(pages_k), gather(pages_v)
    qg = q.reshape(b, hkv, g, dh)
    s = jnp.einsum("bhgd,bhtd->bhgt", qg, k,
                   preferred_element_type=jnp.float32) * scale
    live = jnp.arange(t)[None, :] < kv_lens[:, None]      # (B, T)
    s = jnp.where(live[:, None, None, :], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    # kv_len == 0 rows attend to NOTHING (output 0), matching the kernel's
    # l == 0 guard — softmax alone would return uniform garbage attention
    p = jnp.where(kv_lens[:, None, None, None] > 0, p, 0.0)
    out = jnp.einsum("bhgt,bhtd->bhgd", p.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)
    return out.astype(q.dtype).reshape(b, h, dh)


def paged_attention_reference(q, pages_k, pages_v, block_tables, kv_lens, *,
                              layer=0, scale: Optional[float] = None):
    """XLA-lax reference: gather the tables contiguous, masked softmax.

    Same signature/semantics as ``paged_attention`` — the parity oracle for
    the kernel and the off-TPU fallback (it IS a gather, which is exactly
    what the kernel exists to avoid on TPU)."""
    q, pages_k, pages_v, scale = _check_args(q, pages_k, pages_v,
                                             block_tables, kv_lens, scale)
    return _paged_attention_xla(q, pages_k, pages_v, block_tables, kv_lens,
                                layer, scale)


def _check_args(q, pages_k, pages_v, block_tables, kv_lens, scale):
    if pages_k.ndim == 4:      # single-layer pages: add the unit layer axis
        pages_k, pages_v = pages_k[None], pages_v[None]
    if pages_k.shape != pages_v.shape or pages_k.ndim != 5:
        raise ValueError(f"pages must both be (L, N, H_kv, bs, Dh); got "
                         f"{pages_k.shape} / {pages_v.shape}")
    b, h, dh = q.shape
    hkv = pages_k.shape[2]
    if h % hkv or pages_k.shape[4] != dh:
        raise ValueError(f"q has {h} heads / Dh {dh} but pages carry "
                         f"{hkv} kv heads / Dh {pages_k.shape[4]}; "
                         "need H % H_kv == 0 and equal head dims")
    if block_tables.shape[0] != b or kv_lens.shape != (b,):
        raise ValueError(f"block_tables {block_tables.shape} / kv_lens "
                         f"{kv_lens.shape} do not match batch {b}")
    if scale is None:
        scale = 1.0 / math.sqrt(dh)
    return q, pages_k, pages_v, scale


def paged_attention(q, pages_k, pages_v, block_tables, kv_lens, *,
                    layer=0, scale: Optional[float] = None,
                    backend: str = "auto",
                    interpret: Optional[bool] = None):
    """Decode attention for the current step's q rows over paged KV.

    q : (B, H, Dh) — this step's query rows (one token per sequence).
    pages_k / pages_v : (L, N, H_kv, bs, Dh) pool pages (or a single layer's
        (N, H_kv, bs, Dh); ``layer`` then ignored). Never copied: the kernel
        fetches only the pages the tables name.
    block_tables : (B, nb) int32 — page ids in logical order; entries past a
        row's live pages may be anything in-range (the pool pads with its
        scratch page 0).
    kv_lens : (B,) int32 — live KV positions per row INCLUDING the row
        written this step (the engine scatters the new row first and passes
        ``offsets + 1``). A 0 row outputs exactly 0.
    layer : which layer's pages to read (static or traced scalar).
    backend : "pallas" (the kernel; interprets off-TPU), "xla" (the gather
        reference), or "auto" — kernel on TPU, reference elsewhere (the
        reference is faster than interpret mode and numerically identical
        up to reduction order).

    GQA: H % H_kv == 0; each kv head's page is fetched once and attended by
    its whole query-head group.
    """
    q, pages_k, pages_v, scale = _check_args(q, pages_k, pages_v,
                                             block_tables, kv_lens, scale)
    if backend == "auto":
        backend = "pallas" if jax.default_backend() == "tpu" else "xla"
    if backend == "xla":
        return _paged_attention_xla(q, pages_k, pages_v, block_tables,
                                    kv_lens, layer, scale)
    if backend != "pallas":
        raise ValueError(f"unknown paged-attention backend {backend!r}")
    if interpret is None:
        interpret = interpret_default()
    return _paged_attention_pallas(q, pages_k, pages_v, block_tables,
                                   kv_lens, layer, scale, interpret)


def scatter_kv_rows(pages, block_tables, offsets, rows, *, layer=None):
    """Write one new KV row per sequence at its decode position.

    The write half of the page contract: ``pages`` is (L, N, H, bs, Dh) with
    ``layer`` naming the layer (or a single layer's (N, H, bs, Dh));
    ``block_tables`` (B, nb); ``offsets`` (B,) the position each row writes;
    ``rows`` (B, H, Dh). Rows whose table points at the pool's scratch page
    land there harmlessly. Returns the updated pages — under jit with the
    pool buffers donated this lowers to an in-place dynamic-update-scatter.
    """
    bs = pages.shape[-2]
    blk = jnp.take_along_axis(block_tables, (offsets // bs)[:, None],
                              axis=1)[:, 0]
    slot = offsets % bs
    # two advanced indices (blk, slot) around the sliced head axis put the
    # batch dim first in the update operand: rows is already (B, H, Dh)
    if pages.ndim == 5:
        if layer is None:
            raise ValueError("layer is required for (L, N, H, bs, Dh) pages")
        return pages.at[layer, blk, :, slot, :].set(rows)
    return pages.at[blk, :, slot, :].set(rows)
