"""Int8 weight-only matmul with in-VMEM dequantization (Pallas TPU kernel).

Why this exists: bs=1 GPT-2 decode is HBM-bandwidth-bound on the WEIGHTS —
docs/perf.md measured bf16 decode at ~91% of the bf16 HBM roofline, so the only
route to faster tokens/sec is moving fewer bytes. Storing weights as int8 +
per-output-channel f32 scales halves the bytes; the dequantize happens in VMEM
inside the kernel (XLA cannot fuse a dequant into a dot operand — it
materializes the bf16 weight matrix back to HBM, erasing the saving, which is
why this is a Pallas kernel and not `(q * s) @ x`).

Reference anchor: the never-implemented `CompressionType::QUANTIZATION`
(/root/reference/include/distributed/packet.hpp:10-57) and the fp32-only
inference loop (/root/reference/examples/gpt2_inference.cpp:71-122) — this
exceeds the reference, which ships no quantization at all.

Layout convention: a logical (K, N) matmul weight is stored TRANSPOSED as
``q: (N, K) int8`` with ``scale: (N,) f32`` (absmax/127 per output channel).
That makes the quantization axis the leading one (natural for per-channel
gather/dequant — e.g. the GPT-2 tied embedding (vocab, d) is already in this
layout) and the kernel contracts K on both operands (an "nt" gemm, which the
MXU handles natively). Because the scale is per-N, it factors out of the K
accumulation: out = (x @ q^T) * scale — one multiply per output element, after
the loop.

Padding happens ONCE, at quantize time: ``quantize_int8`` zero-pads the stored
int8 to multiples of 128 on both axes and remembers the logical dims. The
kernel then picks block sizes that exactly divide the stored dims, so the hot
path never pads (an earlier version padded the weight inside the jitted step —
for GPT-2's K=768 with block_k=512 that re-copied every weight through HBM per
decoded token and made int8 SLOWER than bf16).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .runtime import interpret_default

# jax 0.4.x spells it TPUCompilerParams; the kwargs used here are identical
_CompilerParams = getattr(pltpu, "CompilerParams", None) \
    or pltpu.TPUCompilerParams

# Upper bounds on block sizes (VMEM: x 256x2048x2 + q 1024x2048x1 + acc
# 256x1024x4 + out ≈ 6 MB with double buffering — comfortably inside VMEM).
MAX_BLOCK_M = 256
MAX_BLOCK_N = 1024
MAX_BLOCK_K = 2048


class Int8Weight:
    """A quantized (K, N) matmul weight: ``q`` (N', K') int8, ``scale`` (N',)
    f32, where N'/K' are N/K zero-padded up to multiples of 128 and ``n``/``k``
    are the logical dims.

    Registered as a jax pytree so it can live inside a params tree and cross
    jit boundaries. Decode-time representation only — checkpoints store the
    original float params and quantize after load (tnn_tpu.nn.quant)."""

    def __init__(self, q, scale, n=None, k=None):
        self.q = q
        self.scale = scale
        self.n = int(n) if n is not None else q.shape[0]
        self.k = int(k) if k is not None else q.shape[1]

    @property
    def shape(self):  # logical (K, N), matching the float kernel it replaces
        return (self.k, self.n)

    @property
    def dtype(self):
        return self.q.dtype

    def dequant(self, dtype=jnp.float32):
        """(K, N) float materialization — reference path for tests/fallback."""
        full = self.q.astype(jnp.float32) * self.scale[:, None]
        return full[: self.n, : self.k].T.astype(dtype)

    def tree_flatten(self):
        return (self.q, self.scale), (self.n, self.k)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, n=aux[0], k=aux[1])

    def __repr__(self):
        return f"Int8Weight(K={self.k}, N={self.n})"


jax.tree_util.register_pytree_node_class(Int8Weight)


def _pad_to_multiple(x, mult, axis, value=0):
    pad = (-x.shape[axis]) % mult
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths, constant_values=value)


def quantize_int8(w) -> Int8Weight:
    """Symmetric per-output-channel quantization of a (K, N) weight.

    scale[n] = absmax(w[:, n]) / 127; q[n, k] = round(w[k, n] / scale[n]).
    The stored int8 is zero-padded to multiples of 128 on both axes so the
    matmul kernel never has to pad at run time; padded output channels carry
    scale 1.0 and all-zero rows (their outputs are zero and sliced away).
    """
    w = jnp.asarray(w, jnp.float32)
    k_dim, n_dim = w.shape
    absmax = jnp.max(jnp.abs(w), axis=0)          # (N,)
    scale = jnp.where(absmax == 0, 1.0, absmax / 127.0)
    q = jnp.clip(jnp.round(w / scale[None, :]), -127, 127).astype(jnp.int8).T
    q = _pad_to_multiple(_pad_to_multiple(q, 128, 0), 128, 1)
    scale = _pad_to_multiple(scale, 128, 0, value=1.0)
    return Int8Weight(q, scale, n=n_dim, k=k_dim)


def _kernel(x_ref, q_ref, s_ref, o_ref, acc, *, nk: int):
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)

    x = x_ref[...]                      # (bm, bk) compute dtype
    w = q_ref[...].astype(x.dtype)      # (bn, bk) int8 -> dequant IN VMEM
    acc[:] += jax.lax.dot_general(x, w, (((1,), (1,)), ((), ())),
                                  preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _final():
        # per-N scale factors out of the K loop: one multiply at the end
        o_ref[...] = (acc[:] * s_ref[...]).astype(o_ref.dtype)


def _block_divisor(size: int, cap: int) -> int:
    """Largest multiple-of-128 divisor of ``size`` (itself a multiple of 128)
    that is <= cap. Falls back to 128, which always divides."""
    c = size // 128
    for b in range(min(cap // 128, c), 0, -1):
        if c % b == 0:
            return 128 * b
    return 128


@functools.partial(jax.jit, static_argnames=("n", "k", "out_dtype"))
def int8_matmul(x, q, scale, *, n: int | None = None, k: int | None = None,
                out_dtype=None):
    """``x @ W`` where W is int8-quantized: x (..., K), q (N', K'), scale (N').

    ``n``/``k`` are W's logical dims (default: q's stored dims). Returns
    (..., n) in ``out_dtype`` (default x.dtype) with f32 accumulation in
    between. Heads pass out_dtype=f32 so logits never round-trip through bf16
    (greedy argmax is sensitive to bf16's 8-bit mantissa). The int8 block is
    dequantized to the compute dtype in VMEM — HBM traffic for the weight is
    K*N bytes instead of bf16's 2*K*N, and the weight is never copied or
    padded inside the step (see module docstring).
    """
    out_dtype = out_dtype or x.dtype
    *lead, k_in = x.shape
    n = q.shape[0] if n is None else n
    k = k_in if k is None else k
    if k_in != k:
        raise ValueError(f"x K dim {k_in} != weight logical K {k}")
    if q.shape[1] < k:
        raise ValueError(f"stored K {q.shape[1]} < logical K {k}")
    # fallback for raw un-padded int8 (direct kernel tests); Int8Weight from
    # quantize_int8 is always pre-padded so this is a no-op on the decode path
    q = _pad_to_multiple(_pad_to_multiple(q, 128, 0), 128, 1)
    scale = _pad_to_multiple(scale, 128, 0, value=1.0)
    np_, kp = q.shape
    m = 1
    for d in lead:
        m *= d
    xf = x.reshape(m, k)

    bm = min(MAX_BLOCK_M, (m + 7) // 8 * 8)
    bn = _block_divisor(np_, MAX_BLOCK_N)
    bk = _block_divisor(kp, MAX_BLOCK_K)
    mp = pl.cdiv(m, bm) * bm

    # x is the small operand (decode: one row per sequence) — padding it is
    # cheap; the weight is untouched
    xf = jnp.pad(xf, ((0, mp - m), (0, kp - k)))
    sp = scale.reshape(1, np_)

    out = pl.pallas_call(
        functools.partial(_kernel, nk=kp // bk),
        grid=(mp // bm, np_ // bn, kp // bk),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda mi, ni, ki: (mi, ki),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bn, bk), lambda mi, ni, ki: (ni, ki),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bn), lambda mi, ni, ki: (0, ni),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda mi, ni, ki: (mi, ni),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((mp, np_), out_dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret_default(),
    )(xf, q, sp)
    return out[:m, :n].reshape(*lead, n)


def w8a8_matmul(x, w: Int8Weight, out_dtype=None):
    """``x @ W`` via the MXU's NATIVE int8 path: dynamically quantize the
    activation per token (absmax over K), contract int8 x int8 -> int32 with a
    plain ``dot_general`` (XLA lowers this straight onto the MXU — the weight
    streams from HBM as int8, nothing is dequantized or copied), then rescale
    by sx[m] * sw[n].

    This is the decode hot path. A Pallas kernel pays a fixed few-us
    invocation cost; at bs=1 GPT-2 decode that's 49 kernels/token and the
    overhead alone exceeds the int8 bandwidth saving (measured round 4:
    per-layer Pallas matmuls ran at ~3.5-4.7us vs the ~2.2us roofline). XLA's
    int8 dot has no such overhead AND doubles MXU throughput. The added
    activation-quantization error (per-token absmax, ~0.4%/element) is covered
    by the decode benchmark's logits-vs-float verification gate.
    """
    out_dtype = out_dtype or x.dtype
    *lead, k_in = x.shape
    if k_in != w.k:
        raise ValueError(f"x K dim {k_in} != weight logical K {w.k}")
    xf = x.reshape(-1, k_in).astype(jnp.float32)  # rank-stable like int8_matmul
    absmax = jnp.max(jnp.abs(xf), axis=-1, keepdims=True)
    sx = jnp.where(absmax == 0, 1.0, absmax / 127.0)
    xi = jnp.clip(jnp.round(xf / sx), -127, 127).astype(jnp.int8)
    # zero-pad the activation K to the stored (128-multiple) K — zero int8
    # columns contribute nothing; the WEIGHT is never sliced or copied
    pad = w.q.shape[1] - k_in
    if pad:
        xi = jnp.pad(xi, ((0, 0), (0, pad)))
    acc = jax.lax.dot_general(xi, w.q, (((1,), (1,)), ((), ())),
                              preferred_element_type=jnp.int32)
    out = acc.astype(jnp.float32) * sx * w.scale[None, :]
    return out[:, : w.n].astype(out_dtype).reshape(*lead, w.n)


# Below this many activation rows, per-kernel Pallas overhead beats the
# bandwidth saving and the XLA-native w8a8 path wins; above it (prefill,
# verification forwards) the weight-only in-VMEM-dequant kernel is exact on
# the activation side and the overhead amortizes.
W8A8_MAX_ROWS = 256


def qmatmul(x, w, out_dtype=None):
    """Dispatch ``x @ w``: Int8Weight -> int8 decode paths (w8a8 for small
    activation counts, the in-VMEM-dequant Pallas kernel otherwise); anything
    else -> plain dot_general with f32 accumulation. The single call-site hook
    for layers that want to be quantization-transparent."""
    if isinstance(w, Int8Weight):
        rows = 1
        for d in x.shape[:-1]:
            rows *= d
        if rows <= W8A8_MAX_ROWS:
            return w8a8_matmul(x, w, out_dtype=out_dtype)
        return int8_matmul(x, w.q, w.scale, n=w.n, k=w.k, out_dtype=out_dtype)
    out = jax.lax.dot_general(x, w, (((x.ndim - 1,), (0,)), ((), ())),
                              preferred_element_type=jnp.float32)
    return out.astype(out_dtype) if out_dtype is not None else out
