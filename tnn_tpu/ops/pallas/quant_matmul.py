"""Int8 weight-only matmul with in-VMEM dequantization (Pallas TPU kernel).

Why this exists: bs=1 GPT-2 decode is HBM-bandwidth-bound on the WEIGHTS —
docs/perf.md measured bf16 decode at ~91% of the bf16 HBM roofline, so the only
route to faster tokens/sec is moving fewer bytes. Storing weights as int8 +
per-output-channel f32 scales halves the bytes; the dequantize happens in VMEM
inside the kernel (XLA cannot fuse a dequant into a dot operand — it
materializes the bf16 weight matrix back to HBM, erasing the saving, which is
why this is a Pallas kernel and not `(q * s) @ x`).

Reference anchor: the never-implemented `CompressionType::QUANTIZATION`
(/root/reference/include/distributed/packet.hpp:10-57) and the fp32-only
inference loop (/root/reference/examples/gpt2_inference.cpp:71-122) — this
exceeds the reference, which ships no quantization at all.

Layout convention: a logical (K, N) matmul weight is stored TRANSPOSED as
``q: (N, K) int8`` with ``scale: (N,) f32`` (absmax/127 per output channel).
That makes the quantization axis the leading one (natural for per-channel
gather/dequant — e.g. the GPT-2 tied embedding (vocab, d) is already in this
layout) and the kernel contracts K on both operands (an "nt" gemm, which the
MXU handles natively). Because the scale is per-N, it factors out of the K
accumulation: out = (x @ q^T) * scale — one multiply per output element, after
the loop.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Block sizes sized for decode/prefill matmuls (K, N up to a few thousand;
# VMEM: x 256x512x2 + q 512x512x1 + acc 256x512x4 < 1 MB).
DEFAULT_BLOCK_M = 256
DEFAULT_BLOCK_N = 512
DEFAULT_BLOCK_K = 512


class Int8Weight:
    """A quantized (K, N) matmul weight: ``q`` (N, K) int8, ``scale`` (N,) f32.

    Registered as a jax pytree so it can live inside a params tree and cross
    jit boundaries. Decode-time representation only — checkpoints store the
    original float params and quantize after load (tnn_tpu.nn.quant)."""

    def __init__(self, q, scale):
        self.q = q
        self.scale = scale

    @property
    def shape(self):  # logical (K, N), matching the float kernel it replaces
        return (self.q.shape[1], self.q.shape[0])

    @property
    def dtype(self):
        return self.q.dtype

    def dequant(self, dtype=jnp.float32):
        """(K, N) float materialization — reference path for tests/fallback."""
        return (self.q.astype(jnp.float32) * self.scale[:, None]).T.astype(dtype)

    def tree_flatten(self):
        return (self.q, self.scale), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    def __repr__(self):
        return f"Int8Weight(K={self.q.shape[1]}, N={self.q.shape[0]})"


jax.tree_util.register_pytree_node_class(Int8Weight)


def quantize_int8(w) -> Int8Weight:
    """Symmetric per-output-channel quantization of a (K, N) weight.

    scale[n] = absmax(w[:, n]) / 127; q[n, k] = round(w[k, n] / scale[n]).
    """
    w = jnp.asarray(w, jnp.float32)
    absmax = jnp.max(jnp.abs(w), axis=0)          # (N,)
    scale = jnp.where(absmax == 0, 1.0, absmax / 127.0)
    q = jnp.clip(jnp.round(w / scale[None, :]), -127, 127).astype(jnp.int8)
    return Int8Weight(q.T, scale)


def _kernel(x_ref, q_ref, s_ref, o_ref, acc, *, nk: int):
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)

    x = x_ref[...]                      # (bm, bk) compute dtype
    w = q_ref[...].astype(x.dtype)      # (bn, bk) int8 -> dequant IN VMEM
    acc[:] += jax.lax.dot_general(x, w, (((1,), (1,)), ((), ())),
                                  preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _final():
        # per-N scale factors out of the K loop: one multiply at the end
        o_ref[...] = (acc[:] * s_ref[...]).astype(o_ref.dtype)


def _pad_axis(x, size, axis):
    pad = size - x.shape[axis]
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


@functools.partial(jax.jit,
                   static_argnames=("block_m", "block_n", "block_k",
                                    "out_dtype"))
def int8_matmul(x, q, scale, *, block_m: int = DEFAULT_BLOCK_M,
                block_n: int = DEFAULT_BLOCK_N, block_k: int = DEFAULT_BLOCK_K,
                out_dtype=None):
    """``x @ W`` where W is int8-quantized: x (..., K), q (N, K), scale (N,).

    Returns (..., N) in ``out_dtype`` (default x.dtype) with f32 accumulation
    in between. Heads pass out_dtype=f32 so logits never round-trip through
    bf16 (greedy argmax is sensitive to bf16's 8-bit mantissa). The int8
    block is dequantized to the compute dtype in VMEM — HBM traffic for the
    weight is K*N bytes instead of bf16's 2*K*N.
    """
    out_dtype = out_dtype or x.dtype
    *lead, k_dim = x.shape
    n_dim = q.shape[0]
    m = 1
    for d in lead:
        m *= d
    xf = x.reshape(m, k_dim)

    bm = min(block_m, max(m, 8))
    bn = min(block_n, max(n_dim, 128))
    bk = min(block_k, max(k_dim, 128))
    mp, np_, kp = (pl.cdiv(m, bm) * bm, pl.cdiv(n_dim, bn) * bn,
                   pl.cdiv(k_dim, bk) * bk)

    xf = _pad_axis(_pad_axis(xf, mp, 0), kp, 1)
    qp = _pad_axis(_pad_axis(q, np_, 0), kp, 1)      # zero-padded K adds 0
    sp = _pad_axis(scale.reshape(1, n_dim), np_, 1)

    out = pl.pallas_call(
        functools.partial(_kernel, nk=kp // bk),
        grid=(mp // bm, np_ // bn, kp // bk),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda mi, ni, ki: (mi, ki),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bn, bk), lambda mi, ni, ki: (ni, ki),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bn), lambda mi, ni, ki: (0, ni),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda mi, ni, ki: (mi, ni),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((mp, np_), out_dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        interpret=jax.default_backend() != "tpu",
    )(xf, qp, sp)
    return out[:m, :n_dim].reshape(*lead, n_dim)


def qmatmul(x, w, out_dtype=None):
    """Dispatch ``x @ w``: Int8Weight -> the in-VMEM-dequant kernel; anything
    else -> plain dot_general with f32 accumulation. The single call-site hook
    for layers that want to be quantization-transparent."""
    if isinstance(w, Int8Weight):
        return int8_matmul(x, w.q, w.scale, out_dtype=out_dtype)
    out = jax.lax.dot_general(x, w, (((x.ndim - 1,), (0,)), ((), ())),
                              preferred_element_type=jnp.float32)
    return out.astype(out_dtype) if out_dtype is not None else out
