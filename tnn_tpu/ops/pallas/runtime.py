"""Shared Pallas runtime knobs.

One switch for every kernel in this package: whether ``pallas_call`` runs in
interpret mode. Off-TPU backends (CPU tests, the forced 8-device virtual
platform in tests/conftest.py) have no Mosaic compiler, so kernels interpret
there by default; ``TNN_PALLAS_INTERPRET=1|0`` overrides either way (the
test-suite fixture forces ``1`` for ``@pytest.mark.kernel`` tests so tier-1
exercises the real kernel code paths on CPU).
"""
from __future__ import annotations

import os

import jax


def interpret_default() -> bool:
    """Resolve the interpret flag for a pallas_call at trace time."""
    env = os.environ.get("TNN_PALLAS_INTERPRET")
    if env:
        return env not in ("0", "false", "False")
    return jax.default_backend() != "tpu"
