"""Blockwise fused attention (FlashAttention-2 style) as a Pallas TPU kernel.

The TPU-native replacement for the reference's flash-attention capability
(FlashAttentionBlock delegating to cuDNN-frontend fused SDPA,
src/nn/blocks_impl/flash_attention_block.cpp:74-338; an abandoned CPU blockwise kernel
at include/nn/blocks_impl/cpu/flash_attention.hpp:18-80 used Br=64/Bc=64 online softmax —
same algorithm, here actually working and TPU-tiled).

Forward: online-softmax accumulation over key blocks with O(block) VMEM, grid
(batch*heads, q_blocks, k_blocks), causal blocks fully above the diagonal skipped;
the per-row logsumexp L is written out for the backward.
Backward: blockwise Pallas kernels too (FlashAttention-2 style) — one pass
accumulating dQ over key blocks, one accumulating dK/dV over query blocks, both
O(block) memory, so long-context TRAINING never materializes the (S, S) logits
(the earlier XLA recompute backward OOMed at S=8k).

Falls back to interpret mode off-TPU so the same code path tests on CPU.
"""
from __future__ import annotations

import functools
import math
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .runtime import interpret_default

# jax 0.4.x spells it TPUCompilerParams; the kwargs used here are identical
_CompilerParams = getattr(pltpu, "CompilerParams", None) \
    or pltpu.TPUCompilerParams

# Tuned on v5e (honest difference-timing, B=8/H=12/D=64). Forward is best at
# 1024/1024 (S=1024: 0.42ms = 30.9 TFLOP/s; S=4096: 5.36ms = 38.5 TFLOP/s —
# 4-5x the stock jax.experimental pallas flash kernel on the same shapes, and
# ~78% of the D=64-contraction MXU ceiling). The backward prefers smaller q
# blocks (S=4096 fwd+bwd: 512/1024 -> 36.2 TFLOP/s-equiv vs 28.1 at
# 1024/1024), so fwd and bwd carry separate block defaults. 2048-wide blocks
# fail to compile (VMEM).
DEFAULT_BLOCK_Q = 1024
DEFAULT_BLOCK_K = 1024
DEFAULT_BLOCK_Q_BWD = 512
DEFAULT_BLOCK_K_BWD = 1024
_NEG_INF = -1e30


def _fwd_kernel(off_ref, q_ref, k_ref, v_ref, *rest, scale: float, causal: bool,
                bq: int, bk: int, kv_len: int, has_mask: bool):
    if has_mask:
        mask_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr = rest
    else:
        mask_ref, (o_ref, lse_ref, m_scr, l_scr, acc_scr) = None, rest
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)  # (bq, 1)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    q_start = qi * bq
    k_start = ki * bk
    off = off_ref[0]  # absolute position of q row 0 in the kv sequence
    # Causal: a key block strictly above the diagonal contributes nothing.
    live = (k_start <= q_start + off + bq - 1) if causal else True

    @pl.when(live)
    def _block():
        q = q_ref[0]  # (bq, d)
        k = k_ref[0]  # (bk, d)
        v = v_ref[0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        kpos = k_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        mask = kpos < kv_len  # padded keys
        if causal:
            qpos = off + q_start + jax.lax.broadcasted_iota(
                jnp.int32, (bq, bk), 0)
            mask = jnp.logical_and(mask, qpos >= kpos)
        if has_mask:
            mask = jnp.logical_and(mask, mask_ref[0] != 0)
        s = jnp.where(mask, s, _NEG_INF)

        m_prev = m_scr[:]                              # (bq, 1)
        l_prev = l_scr[:]
        m_cur = jnp.max(s, axis=1, keepdims=True)      # (bq, 1)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                         # (bq, bk) f32
        # rows with NO live key so far have m_new == _NEG_INF, which would
        # give the masked entries exp(0) = 1; zero them explicitly so fully
        # masked rows end with l == 0 (-> output 0, lse +inf)
        p = jnp.where(mask, p, 0.0)
        l_cur = jnp.sum(p, axis=1, keepdims=True)
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_prev + l_cur
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = m_new
        l_scr[:] = l_new

    @pl.when(ki == nk - 1)
    def _final():
        l = l_scr[:]
        lsafe = jnp.where(l == 0.0, 1.0, l)  # fully-masked rows -> 0 output
        o_ref[0] = (acc_scr[:] / lsafe).astype(o_ref.dtype)
        # logsumexp per row for the backward; +inf on fully-masked/padded rows
        # makes their p = exp(s - L) exactly 0 there (never NaN)
        m = m_scr[:]
        # compact (bq, 1) column — 4 bytes/row in HBM end to end, vs the
        # lane-replicated 128-lane layout that cost ~400MB transient f32 at
        # B=8/H=12/S=8k (Mosaic pads narrow minor dims in VMEM transparently)
        lse_ref[0] = jnp.where(l > 0.0, m + jnp.log(lsafe), jnp.inf)


def _block_geometry(sq: int, skv: int, block_q: int, block_k: int):
    """Block sizing + padded lengths. Forward and backward call this with
    their OWN block sizes — the lse residual is saved unpadded and the
    backward re-pads it (+inf) to its own geometry."""
    bq = min(block_q, max(sq, 8))
    bk = min(block_k, max(skv, 8))
    return bq, bk, pl.cdiv(sq, bq) * bq, pl.cdiv(skv, bk) * bk


def _pad_to(x, size, axis, value=0):
    pad = size - x.shape[axis]
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths, constant_values=value)


def _norm_mask(mask, b, h, sq, skv):
    """Normalize a boolean mask broadcastable to (B, H, Sq, Skv) into the
    kernel's grouped (G, Sq, Skv) int8 layout, G in {1, B, B*H} — the block
    index map selects the right group per (batch*head), so a (B, 1, Sq, Skv)
    padding mask is NOT materialized H times."""
    if mask.ndim == 2:
        mask = mask[None, None]
    elif mask.ndim == 3:
        # ambiguous: numpy broadcasting would align the leading axis with H,
        # but a (B, Sq, Skv) padding mask is the likelier intent — demand 4-D
        # so the two sdpa backends can never silently disagree
        if mask.shape[0] != 1:
            raise ValueError(
                f"3-D mask with leading dim {mask.shape[0]} is ambiguous "
                "(B or H?); pass a 4-D mask shaped (B, 1, Sq, Skv) or "
                "(1, H, Sq, Skv)")
        mask = mask[None]
    mb, mh = mask.shape[0], mask.shape[1]
    if (mb, mh) == (1, 1):
        g = mask.reshape(1, *mask.shape[2:])
    elif mh == 1:
        g = mask.reshape(mb, *mask.shape[2:])
    else:  # per-head masks: materialize (b*h) groups
        g = jnp.broadcast_to(mask, (b, h) + mask.shape[2:]).reshape(
            b * h, *mask.shape[2:])
    g = jnp.broadcast_to(g, (g.shape[0], sq, skv))
    return g.astype(jnp.int8)


def _mask_pick(groups: int, b: int, h: int):
    """Flattened batch*head grid index -> mask group index."""
    if groups == 1:
        return lambda bh: 0
    if groups == b:
        return lambda bh: bh // h
    return lambda bh: bh  # groups == b*h


def _mask_spec(mask, b, h, bq, bk, block_idx):
    """BlockSpec for the grouped (G, Sq, Skv) int8 mask. ``block_idx`` maps
    the kernel's grid indices -> (q block, k block), so each grid order (and
    any dead-block fetch clamping) plugs in its own mapping."""
    pick = _mask_pick(mask.shape[0], b, h)
    return pl.BlockSpec((1, bq, bk),
                        lambda *g: (pick(g[0]),) + tuple(block_idx(*g)),
                        memory_space=pltpu.VMEM)


_OFF_SPEC = pl.BlockSpec(memory_space=pltpu.SMEM)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10, 11))
def _flash(q, k, v, mask, off, causal, scale, block_q, block_k,
           block_q_bwd, block_k_bwd, clamp_dead):
    return _flash_fwd(q, k, v, mask, off, causal, scale, block_q, block_k,
                      clamp_dead=clamp_dead)[0]


def flash_attention(q, k, v, causal: bool = False, scale: Optional[float] = None,
                    block_q: int = DEFAULT_BLOCK_Q, block_k: int = DEFAULT_BLOCK_K,
                    block_q_bwd: Optional[int] = None,
                    block_k_bwd: Optional[int] = None,
                    mask: Optional[jax.Array] = None,
                    kv_offset=None):
    """Fused attention over (B, H, S, Dh) tensors. Differentiable; O(block) fwd memory.

    Forward and backward take independent block geometry (the backward's three
    matmul chain prefers smaller q blocks — see the tuning note above).
    ``block_*_bwd=None`` resolves to min(caller's fwd block, tuned bwd
    default): a caller shrinking blocks to fit VMEM shrinks the backward too,
    while the stock defaults give the tuned (512, 1024) backward.

    ``mask``: boolean, broadcastable to (B, H, Sq, Skv); True = attend. Kept
    in its broadcast-group form ((B,1,..) padding masks are never tiled per
    head). ``kv_offset``: absolute position of q[0] in the kv sequence
    (cached decode with S_q != S_kv); may be a traced scalar. Both compose
    with ``causal``.

    When causal and kv_offset is statically absent (the self-attention
    training case), blocks strictly above the diagonal are not just
    compute-skipped but FETCH-skipped: their index maps clamp to the last
    live block, and the Pallas pipeline elides the DMA when a block index
    repeats — at S=8192 that removes ~40% of the K/V HBM traffic.

    Grouped-query attention: ``k``/``v`` may carry H_kv heads with
    H % H_kv == 0 (e.g. MQA at H_kv=1). The kernels never materialize the
    repeated heads — each q head's grid index maps to its kv head inside the
    BlockSpec index maps, so a shared kv block is fetched once and reused by
    the whole group (consecutive grid steps repeat the index; the pipeline
    elides the copy)."""
    b, h, sq, d = q.shape
    skv = k.shape[2]
    hkv = k.shape[1]
    if h % hkv or v.shape[1] != hkv:
        raise ValueError(f"q has {h} heads but k/v have {k.shape[1]}/"
                         f"{v.shape[1]}; need H % H_kv == 0 and k == v heads")
    if mask is not None:
        mask = _norm_mask(jnp.asarray(mask), b, h, sq, skv)
    clamp_dead = causal and kv_offset is None
    if kv_offset is None:
        off = jnp.zeros((1,), jnp.int32)
    else:
        off = jnp.asarray(kv_offset, jnp.int32).reshape(1)
    return _flash(q, k, v, mask, off, causal, scale, block_q, block_k,
                  block_q_bwd, block_k_bwd, clamp_dead)


def _bwd_blocks(block_q, block_k, block_q_bwd, block_k_bwd):
    bq = block_q_bwd if block_q_bwd is not None else min(block_q, DEFAULT_BLOCK_Q_BWD)
    bk = block_k_bwd if block_k_bwd is not None else min(block_k, DEFAULT_BLOCK_K_BWD)
    return bq, bk


def _kv_head_map(h: int, hkv: int):
    """Flattened batch*q-head grid index -> flattened batch*kv-head index
    (identity when h == hkv); the zero-copy GQA mapping."""
    if h == hkv:
        return lambda bh: bh
    group = h // hkv
    return lambda bh: (bh // h) * hkv + (bh % h) // group


def _flash_fwd(q, k, v, mask, off, causal, scale, block_q, block_k,
               block_q_bwd=None, block_k_bwd=None, clamp_dead=False):
    b, h, sq, d = q.shape
    hkv, skv = k.shape[1], k.shape[2]
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    bq, bk, sq_p, skv_p = _block_geometry(sq, skv, block_q, block_k)

    qf = _pad_to(q.reshape(b * h, sq, d), sq_p, 1)
    kf = _pad_to(k.reshape(b * hkv, skv, d), skv_p, 1)
    vf = _pad_to(v.reshape(b * hkv, skv, d), skv_p, 1)
    kv_head = _kv_head_map(h, hkv)

    grid = (b * h, sq_p // bq, skv_p // bk)
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               bq=bq, bk=bk, kv_len=skv,
                               has_mask=mask is not None)
    if clamp_dead and causal:
        # causal + no kv_offset: a k block with ki > max_live is all-masked.
        # Clamping its fetch index to the row's last live block repeats the
        # previous step's index, so the pipeline elides the DMA entirely
        # (the kernel's pl.when(live) already skips the compute).
        def kv_idx(bh, qi, ki):
            return (kv_head(bh), jnp.minimum(ki, (qi * bq + bq - 1) // bk), 0)
    else:
        def kv_idx(bh, qi, ki):
            return (kv_head(bh), ki, 0)
    in_specs = [
        _OFF_SPEC,
        pl.BlockSpec((1, bq, d), lambda bh, qi, ki: (bh, qi, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, bk, d), kv_idx, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, bk, d), kv_idx, memory_space=pltpu.VMEM),
    ]
    inputs = [off, qf, kf, vf]
    if mask is not None:
        mp = _pad_to(_pad_to(mask, sq_p, 1), skv_p, 2)  # pad = masked out
        in_specs.append(_mask_spec(
            mp, b, h, bq, bk,
            lambda bh, qi, ki: (qi, kv_idx(bh, qi, ki)[1])))
        inputs.append(mp)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, qi, ki: (bh, qi, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bq, 1), lambda bh, qi, ki: (bh, qi, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, sq_p, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, sq_p, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, 1), jnp.float32),  # running max
            pltpu.VMEM((bq, 1), jnp.float32),  # running denominator
            pltpu.VMEM((bq, d), jnp.float32),  # output accumulator
        ],
        # scratch carries only along the innermost (ki) sweep; bh and qi
        # iterations are independent, which lets Mosaic pipeline them
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret_default(),
    )(*inputs)
    out = out[:, :sq].reshape(b, h, sq, d)
    # residual is the compact UNPADDED (b*h, sq) row vector — the backward may
    # use different block geometry and re-pads with +inf itself
    return out, (q, k, v, mask, off, out, lse[:, :sq, 0])


def _attn_probs(q, k, lse_col, k_start, q_start, off, mask_blk, *, scale,
                causal, bq, bk, kv_len):
    """Recompute P_ij = exp(S_ij - L_i) for one (q block, k block) tile, masked."""
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    kpos = k_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    mask = kpos < kv_len
    if causal:
        qpos = off + q_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        mask = jnp.logical_and(mask, qpos >= kpos)
    if mask_blk is not None:
        mask = jnp.logical_and(mask, mask_blk != 0)
    s = jnp.where(mask, s, _NEG_INF)
    # L = +inf on fully-masked/padded rows -> p = 0 there (see _fwd_kernel)
    return jnp.exp(s - lse_col)


def _bwd_dq_kernel(off_ref, q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref,
                   *rest, scale, causal, bq, bk, kv_len, has_mask):
    if has_mask:
        mask_ref, dq_ref, dq_scr = rest
    else:
        mask_ref, (dq_ref, dq_scr) = None, rest
    qi, ki, nk = pl.program_id(1), pl.program_id(2), pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    q_start, k_start = qi * bq, ki * bk
    off = off_ref[0]
    live = (k_start <= q_start + off + bq - 1) if causal else True

    @pl.when(live)
    def _block():
        q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
        lse_col = lse_ref[0]                           # (bq, 1), compact
        do32 = do.astype(jnp.float32)
        # delta_i = rowsum(dO_i * O_i), recomputed per block (elementwise, cheap)
        delta = jnp.sum(do32 * o_ref[0].astype(jnp.float32), axis=1,
                        keepdims=True)
        p = _attn_probs(q, k, lse_col, k_start, q_start, off,
                        mask_ref[0] if has_mask else None, scale=scale,
                        causal=causal, bq=bq, bk=bk, kv_len=kv_len)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale                  # (bq, bk) f32
        dq_scr[:] += jax.lax.dot_general(ds.astype(k.dtype), k,
                                         (((1,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _final():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(off_ref, q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref,
                    *rest, scale, causal, bq, bk, kv_len, has_mask):
    if has_mask:
        mask_ref, dk_ref, dv_ref, dk_scr, dv_scr = rest
    else:
        mask_ref, (dk_ref, dv_ref, dk_scr, dv_scr) = None, rest
    # grid: (bh, k_blocks, q_blocks) — accumulate over q for one k/v block
    ki, qi, nq = pl.program_id(1), pl.program_id(2), pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    q_start, k_start = qi * bq, ki * bk
    off = off_ref[0]
    live = (k_start <= q_start + off + bq - 1) if causal else True

    @pl.when(live)
    def _block():
        q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
        lse_col = lse_ref[0]                           # (bq, 1), compact
        delta = jnp.sum(do.astype(jnp.float32) * o_ref[0].astype(jnp.float32),
                        axis=1, keepdims=True)
        p = _attn_probs(q, k, lse_col, k_start, q_start, off,
                        mask_ref[0] if has_mask else None, scale=scale,
                        causal=causal, bq=bq, bk=bk, kv_len=kv_len)
        pt = p.astype(do.dtype)
        dv_scr[:] += jax.lax.dot_general(pt, do, (((0,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - delta) * scale).astype(q.dtype)
        dk_scr[:] += jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)

    @pl.when(qi == nq - 1)
    def _final():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _bwd_fused_kernel(off_ref, q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref,
                      *rest, scale, causal, bq, bk, kv_len, has_mask):
    """Single-pass backward: dQ, dK, dV in ONE sweep, 5 matmuls per live tile
    (the FlashAttention-2 ideal) vs 7 across the split dq/dkv kernels (S and
    dO@V^T were each computed twice). Grid (bh, k block j, q block i): dK/dV
    accumulate in per-block scratch over the inner i loop; dQ accumulates in a
    FULL-SEQUENCE f32 VMEM scratch (sq x d = 2 MB at S=8192/D=64 — the cheap
    side; dK+dV would need twice that) and is written out once per bh. The
    TPU grid is sequential per core, which is what makes the whole-sweep
    scratch accumulation sound."""
    if has_mask:
        mask_ref, dq_ref, dk_ref, dv_ref, dq_scr, dk_scr, dv_scr = rest
    else:
        mask_ref, (dq_ref, dk_ref, dv_ref, dq_scr, dk_scr, dv_scr) = None, rest
    ki, qi = pl.program_id(1), pl.program_id(2)
    nk, nq = pl.num_programs(1), pl.num_programs(2)

    @pl.when(jnp.logical_and(ki == 0, qi == 0))
    def _init_dq():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    @pl.when(qi == 0)
    def _init_dkv():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    q_start, k_start = qi * bq, ki * bk
    off = off_ref[0]
    live = (k_start <= q_start + off + bq - 1) if causal else True

    @pl.when(live)
    def _block():
        q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
        lse_col = lse_ref[0]                           # (bq, 1), compact
        delta = jnp.sum(do.astype(jnp.float32) * o_ref[0].astype(jnp.float32),
                        axis=1, keepdims=True)
        p = _attn_probs(q, k, lse_col, k_start, q_start, off,
                        mask_ref[0] if has_mask else None, scale=scale,
                        causal=causal, bq=bq, bk=bk, kv_len=kv_len)
        pt = p.astype(do.dtype)
        dv_scr[:] += jax.lax.dot_general(pt, do, (((0,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - delta) * scale).astype(q.dtype)
        dk_scr[:] += jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)
        dq_scr[pl.ds(q_start, bq), :] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == nq - 1)
    def _final_dkv():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)

    @pl.when(jnp.logical_and(ki == nk - 1, qi == nq - 1))
    def _final_dq():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


# VMEM budget for the fused backward's resident set; above it the split
# two-kernel path runs instead. 12 MB keeps S=16384 at D=64 (f32) on the
# fused path (~10.5 MB estimated) inside the ~16 MB/core VMEM envelope.
_FUSED_BWD_MAX_BYTES = int(
    os.environ.get("TNN_FLASH_FUSED_BWD_MAX_BYTES", 12 * 2**20))


def _fused_bwd_applicable(sq_p: int, d: int, bq: int = 512, bk: int = 512,
                          itemsize: int = 4) -> bool:
    """Estimate the fused kernel's whole VMEM-resident set — not just the
    full-seq dQ scratch: the dQ OUTPUT block is also full-seq (constant index
    map, so it stays resident), the per-block q/o/do/k/v operands and dk/dv
    outputs are double-buffered by the pipeline, and the dk/dv accumulators
    are f32 scratch. Underestimating here fails inside Mosaic at lowering
    time instead of cleanly taking the split path."""
    if os.environ.get("TNN_FLASH_FUSED_BWD", "1") == "0":
        return False
    dq_bytes = sq_p * d * (itemsize + 4)      # dq out block + f32 accumulator
    blk_in = (3 * bq + 2 * bk) * d * itemsize + bq * 4  # q/o/do, k/v, lse
    blk_out = 2 * bk * d * itemsize                     # dk/dv out blocks
    acc = 2 * bk * d * 4                                # dk/dv f32 scratch
    resident = dq_bytes + 2 * (blk_in + blk_out) + acc
    return resident <= _FUSED_BWD_MAX_BYTES


def _flash_bwd(causal, scale, block_q, block_k, block_q_bwd, block_k_bwd,
               clamp_dead, residuals, g):
    """Blockwise Pallas backward: never materializes the (S, S) matrix."""
    q, k, v, mask, off, o, lse_row = residuals
    b, h, sq, d = q.shape
    skv = k.shape[2]
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    # Fused single-pass backward when the full-seq dQ scratch fits VMEM;
    # its own block default (512, 512) keeps the bq x bk f32 intermediates
    # ~1 MB so blocks + dq scratch + outputs stay inside ~16 MB at S=16384.
    bq_f = block_q_bwd if block_q_bwd is not None else 512
    bk_f = block_k_bwd if block_k_bwd is not None else 512
    bqp, bkp, sq_pf, _ = _block_geometry(sq, skv, bq_f, bk_f)
    if _fused_bwd_applicable(sq_pf, d, bqp, bkp, q.dtype.itemsize):
        return _flash_bwd_fused(causal, scale, bqp, bkp, clamp_dead,
                                residuals, g)
    bq_bwd, bk_bwd = _bwd_blocks(block_q, block_k, block_q_bwd, block_k_bwd)
    bq, bk, sq_p, skv_p = _block_geometry(sq, skv, bq_bwd, bk_bwd)
    hkv = k.shape[1]
    kv_head = _kv_head_map(h, hkv)

    qf = _pad_to(q.reshape(b * h, sq, d), sq_p, 1)
    kf = _pad_to(k.reshape(b * hkv, skv, d), skv_p, 1)
    vf = _pad_to(v.reshape(b * hkv, skv, d), skv_p, 1)
    of = _pad_to(o.reshape(b * h, sq, d), sq_p, 1)
    dof = _pad_to(g.reshape(b * h, sq, d), sq_p, 1)
    # +inf on padded q rows makes their recomputed p exactly 0, so they add
    # nothing to dK/dV (their dQ rows are sliced off anyway)
    lse = _pad_to(lse_row, sq_p, 1, value=jnp.inf)[:, :, None]

    has_mask = mask is not None
    maskp = (_pad_to(_pad_to(mask, sq_p, 1), skv_p, 2) if has_mask else None)

    interpret = interpret_default()
    common = dict(scale=scale, causal=causal, bq=bq, bk=bk, kv_len=skv,
                  has_mask=has_mask)
    # dead-block DMA elision, same as forward/fused: dq grid (bh, i, j) has
    # its dead k blocks at the END of each j sweep — clamp their fetch index
    # to the row's last live block so the pipeline skips the copy
    if clamp_dead and causal:
        def j_idx(i, j):
            return jnp.minimum(j, (i * bq + bq - 1) // bk)
    else:
        def j_idx(i, j):
            return j
    q_spec = pl.BlockSpec((1, bq, d), lambda bh, i, j: (bh, i, 0),
                          memory_space=pltpu.VMEM)
    lse_spec = pl.BlockSpec((1, bq, 1), lambda bh, i, j: (bh, i, 0),
                            memory_space=pltpu.VMEM)
    kv_spec = pl.BlockSpec((1, bk, d),
                           lambda bh, i, j: (kv_head(bh), j_idx(i, j), 0),
                           memory_space=pltpu.VMEM)

    in_specs = [_OFF_SPEC, q_spec, kv_spec, kv_spec, q_spec, q_spec, lse_spec]
    inputs = [off, qf, kf, vf, of, dof, lse]
    if has_mask:
        in_specs.append(_mask_spec(maskp, b, h, bq, bk,
                                   lambda bh, i, j: (i, j_idx(i, j))))
        inputs.append(maskp)
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, **common),
        grid=(b * h, sq_p // bq, skv_p // bk),
        in_specs=in_specs,
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((b * h, sq_p, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(*inputs)

    # transposed grid: blocks indexed (bh, k block, q block); dead q blocks
    # sit at the START of each i sweep — clamp to the first live row (with
    # the in-range guard for sq < skv)
    if clamp_dead and causal:
        def i_idx(j, i):
            return jnp.minimum(jnp.maximum(i, (j * bk) // bq),
                               sq_p // bq - 1)
    else:
        def i_idx(j, i):
            return i
    qT_spec = pl.BlockSpec((1, bq, d), lambda bh, j, i: (bh, i_idx(j, i), 0),
                           memory_space=pltpu.VMEM)
    lseT_spec = pl.BlockSpec((1, bq, 1),
                             lambda bh, j, i: (bh, i_idx(j, i), 0),
                             memory_space=pltpu.VMEM)
    kvT_fetch = pl.BlockSpec((1, bk, d),
                             lambda bh, j, i: (kv_head(bh), j, 0),
                             memory_space=pltpu.VMEM)
    # dk/dv are written PER Q HEAD (grid bh), group-summed after the kernel
    kvT_spec = pl.BlockSpec((1, bk, d), lambda bh, j, i: (bh, j, 0),
                            memory_space=pltpu.VMEM)
    in_specsT = [_OFF_SPEC, qT_spec, kvT_fetch, kvT_fetch, qT_spec, qT_spec,
                 lseT_spec]
    inputsT = [off, qf, kf, vf, of, dof, lse]
    if has_mask:
        in_specsT.append(_mask_spec(maskp, b, h, bq, bk,
                                    lambda bh, j, i: (i_idx(j, i), j)))
        inputsT.append(maskp)
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, **common),
        grid=(b * h, skv_p // bk, sq_p // bq),
        in_specs=in_specsT,
        out_specs=[kvT_spec, kvT_spec],
        out_shape=[jax.ShapeDtypeStruct((b * h, skv_p, d), k.dtype),
                   jax.ShapeDtypeStruct((b * h, skv_p, d), v.dtype)],
        scratch_shapes=[pltpu.VMEM((bk, d), jnp.float32),
                        pltpu.VMEM((bk, d), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(*inputsT)

    dq = dq[:, :sq].reshape(b, h, sq, d)
    dk, dv = _group_sum_kv_grads(dk, dv, b, h, hkv, skv, d)
    dmask, doff = _zero_cotangents(mask, off)
    return dq, dk, dv, dmask, doff


def _group_sum_kv_grads(dk, dv, b, h, hkv, skv, d):
    """Per-q-head dK/dV (b*h, skv_p, d) -> per-kv-head (b, hkv, skv, d):
    the kernels emit each q head's contribution separately (a shared output
    block would be revisited non-consecutively across the grid, which the
    sequential pipeline cannot accumulate), and the group sum runs as one
    XLA reduction here."""
    dk_dt, dv_dt = dk.dtype, dv.dtype
    dk = dk[:, :skv].reshape(b, h, skv, d)
    dv = dv[:, :skv].reshape(b, h, skv, d)
    if h != hkv:
        g = h // hkv
        dk = dk.reshape(b, hkv, g, skv, d).astype(jnp.float32).sum(2)
        dv = dv.reshape(b, hkv, g, skv, d).astype(jnp.float32).sum(2)
    return dk.astype(dk_dt), dv.astype(dv_dt)


def _zero_cotangents(mask, off):
    import numpy as _np

    from jax import dtypes as _jdt

    # mask (bool/int8) and kv_offset (int32) have no gradient; their cotangent
    # type is float0
    dmask = (None if mask is None
             else _np.zeros(mask.shape, _jdt.float0))
    return dmask, _np.zeros(off.shape, _jdt.float0)


def _flash_bwd_fused(causal, scale, bq, bk, clamp_dead, residuals, g):
    """One-sweep backward (see _bwd_fused_kernel). Grid (bh, j, i): k/v blocks
    stay VMEM-resident across the inner q loop (constant index map), dK/dV
    write once per j, dQ once per bh from the full-seq scratch."""
    q, k, v, mask, off, o, lse_row = residuals
    b, h, sq, d = q.shape
    hkv, skv = k.shape[1], k.shape[2]
    kv_head = _kv_head_map(h, hkv)
    _, _, sq_p, skv_p = _block_geometry(sq, skv, bq, bk)
    bq = min(bq, sq_p)
    bk = min(bk, skv_p)

    qf = _pad_to(q.reshape(b * h, sq, d), sq_p, 1)
    kf = _pad_to(k.reshape(b * hkv, skv, d), skv_p, 1)
    vf = _pad_to(v.reshape(b * hkv, skv, d), skv_p, 1)
    of = _pad_to(o.reshape(b * h, sq, d), sq_p, 1)
    dof = _pad_to(g.reshape(b * h, sq, d), sq_p, 1)
    lse = _pad_to(lse_row, sq_p, 1, value=jnp.inf)[:, :, None]
    has_mask = mask is not None
    maskp = (_pad_to(_pad_to(mask, sq_p, 1), skv_p, 2) if has_mask else None)

    # grid (bh, k block j, q block i) — q-side blocks indexed by i (pos 2).
    # Causal + no kv_offset: q blocks with i < first live row for this k
    # block are all-masked; clamping their fetch index to the first live row
    # repeats the block index so the pipeline elides the DMA (mirrors the
    # forward's dead-block clamp, transposed).
    if clamp_dead and causal:
        # min() guard: with sq < skv a trailing k block's first live row can
        # land past the last q block; those steps are fully dead and must
        # keep fetching an in-range block
        def q_idx(bh, j, i):
            return jnp.minimum(jnp.maximum(i, (j * bk) // bq),
                               sq_p // bq - 1)
    else:
        def q_idx(bh, j, i):
            return i
    q_spec = pl.BlockSpec((1, bq, d), lambda bh, j, i: (bh, q_idx(bh, j, i), 0),
                          memory_space=pltpu.VMEM)
    lse_spec = pl.BlockSpec((1, bq, 1),
                            lambda bh, j, i: (bh, q_idx(bh, j, i), 0),
                            memory_space=pltpu.VMEM)
    kv_spec = pl.BlockSpec((1, bk, d), lambda bh, j, i: (kv_head(bh), j, 0),
                           memory_space=pltpu.VMEM)
    in_specs = [_OFF_SPEC, q_spec, kv_spec, kv_spec, q_spec, q_spec, lse_spec]
    inputs = [off, qf, kf, vf, of, dof, lse]
    if has_mask:
        in_specs.append(_mask_spec(maskp, b, h, bq, bk,
                                   lambda bh, j, i: (q_idx(bh, j, i), j)))
        inputs.append(maskp)
    dq, dk, dv = pl.pallas_call(
        functools.partial(_bwd_fused_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, kv_len=skv, has_mask=has_mask),
        grid=(b * h, skv_p // bk, sq_p // bq),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, sq_p, d), lambda bh, j, i: (bh, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, d), lambda bh, j, i: (bh, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, d), lambda bh, j, i: (bh, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, sq_p, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, skv_p, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, skv_p, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((sq_p, d), jnp.float32),  # full-seq dQ accumulator
            pltpu.VMEM((bk, d), jnp.float32),    # dK block accumulator
            pltpu.VMEM((bk, d), jnp.float32),    # dV block accumulator
        ],
        # the dQ scratch carries across the whole (j, i) sweep of one bh, so
        # both inner dims are "arbitrary"; bh segments are independent
        # (re-initialized at (0, 0)). The explicit VMEM budget keeps the
        # full-seq scratch from tripping Mosaic's conservative default check
        # at S=16384.
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary", "arbitrary"),
            vmem_limit_bytes=100 * 2**20),
        interpret=interpret_default(),
    )(*inputs)

    dq = dq[:, :sq].reshape(b, h, sq, d)
    dk, dv = _group_sum_kv_grads(dk, dv, b, h, hkv, skv, d)
    dmask, doff = _zero_cotangents(mask, off)
    return dq, dk, dv, dmask, doff


_flash.defvjp(_flash_fwd, _flash_bwd)
