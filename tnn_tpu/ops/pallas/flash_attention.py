"""Blockwise fused attention (FlashAttention-2 style) as a Pallas TPU kernel.

The TPU-native replacement for the reference's flash-attention capability
(FlashAttentionBlock delegating to cuDNN-frontend fused SDPA,
src/nn/blocks_impl/flash_attention_block.cpp:74-338; an abandoned CPU blockwise kernel
at include/nn/blocks_impl/cpu/flash_attention.hpp:18-80 used Br=64/Bc=64 online softmax —
same algorithm, here actually working and TPU-tiled).

Forward: online-softmax accumulation over key blocks with O(block) VMEM, grid
(batch*heads, q_blocks, k_blocks), causal blocks fully above the diagonal skipped.
Backward: recompute-based VJP in plain XLA (correct everywhere; a fused Pallas backward
is a later optimisation).

Falls back to interpret mode off-TPU so the same code path tests on CPU.
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Tuned on v5e at GPT-2 geometry (B=8,H=12,S=1024,D=64): 128/128 -> 2.04ms,
# 512/512 -> 0.54ms, 512/1024 -> 0.43ms (vs 0.82ms XLA-fused SDPA). Large k
# blocks amortize the per-grid-step overhead; VMEM at D<=128 stays ~1-2MB.
DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 1024
_NEG_INF = -1e30


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr, *,
                scale: float, causal: bool, bq: int, bk: int, kv_len: int):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    q_start = qi * bq
    k_start = ki * bk
    # Causal: a key block strictly above the diagonal contributes nothing.
    live = (k_start <= q_start + bq - 1) if causal else True

    @pl.when(live)
    def _block():
        q = q_ref[0]  # (bq, d)
        k = k_ref[0]  # (bk, d)
        v = v_ref[0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        kpos = k_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        mask = kpos < kv_len  # padded keys
        if causal:
            qpos = q_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            mask = jnp.logical_and(mask, qpos >= kpos)
        s = jnp.where(mask, s, _NEG_INF)

        m_prev = m_scr[:, :1]                          # (bq, 1)
        l_prev = l_scr[:, :1]
        m_cur = jnp.max(s, axis=1, keepdims=True)      # (bq, 1)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                         # (bq, bk) f32
        l_cur = jnp.sum(p, axis=1, keepdims=True)
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_prev + l_cur
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ki == nk - 1)
    def _final():
        l = l_scr[:, :1]
        l = jnp.where(l == 0.0, 1.0, l)  # fully-masked rows -> 0 output
        o_ref[0] = (acc_scr[:] / l).astype(o_ref.dtype)


def _pad_to(x, size, axis):
    pad = size - x.shape[axis]
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal: bool = False, scale: Optional[float] = None,
                    block_q: int = DEFAULT_BLOCK_Q, block_k: int = DEFAULT_BLOCK_K):
    """Fused attention over (B, H, S, Dh) tensors. Differentiable; O(block) fwd memory."""
    return _flash_fwd(q, k, v, causal, scale, block_q, block_k)[0]


def _flash_fwd(q, k, v, causal, scale, block_q, block_k):
    b, h, sq, d = q.shape
    skv = k.shape[2]
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    bq = min(block_q, max(sq, 8))
    bk = min(block_k, max(skv, 8))
    sq_p = pl.cdiv(sq, bq) * bq
    skv_p = pl.cdiv(skv, bk) * bk

    qf = _pad_to(q.reshape(b * h, sq, d), sq_p, 1)
    kf = _pad_to(k.reshape(b * h, skv, d), skv_p, 1)
    vf = _pad_to(v.reshape(b * h, skv, d), skv_p, 1)

    grid = (b * h, sq_p // bq, skv_p // bk)
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               bq=bq, bk=bk, kv_len=skv)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, qi, ki: (bh, qi, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, d), lambda bh, qi, ki: (bh, ki, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, d), lambda bh, qi, ki: (bh, ki, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda bh, qi, ki: (bh, qi, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b * h, sq_p, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((bq, 128), jnp.float32),  # running max (lanes broadcast)
            pltpu.VMEM((bq, 128), jnp.float32),  # running denominator
            pltpu.VMEM((bq, d), jnp.float32),    # output accumulator
        ],
        interpret=jax.default_backend() != "tpu",
    )(qf, kf, vf)
    out = out[:, :sq].reshape(b, h, sq, d)
    return out, (q, k, v, out)


def _flash_bwd(causal, scale, block_q, block_k, residuals, g):
    """Recompute-based backward in plain XLA (softmax re-derived in f32)."""
    q, k, v, o = residuals
    d = q.shape[-1]
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    sq, skv = q.shape[-2], k.shape[-2]
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        qpos = jnp.arange(sq)[:, None]
        kpos = jnp.arange(skv)[None, :]
        logits = jnp.where(qpos >= kpos, logits, _NEG_INF)
    p = jax.nn.softmax(logits, axis=-1)  # (b,h,q,k) f32
    g32 = g.astype(jnp.float32)
    dv = jnp.einsum("bhqk,bhqd->bhkd", p, g32)
    dp = jnp.einsum("bhqd,bhkd->bhqk", g32, v.astype(jnp.float32))
    delta = jnp.sum(g32 * o.astype(jnp.float32), axis=-1, keepdims=True)  # (b,h,q,1)
    ds = p * (dp - delta) * scale
    dq = jnp.einsum("bhqk,bhkd->bhqd", ds, k.astype(jnp.float32))
    dk = jnp.einsum("bhqk,bhqd->bhkd", ds, q.astype(jnp.float32))
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


flash_attention.defvjp(_flash_fwd, _flash_bwd)
