from . import pallas  # noqa: F401

__all__ = ["pallas"]
