"""Checkpointing: model save/load and full training-state snapshots.

Parity-and-beyond with the reference's checkpoint path (SURVEY.md §3.5):
  * reference ``Graph::save_state`` writes architecture JSON + raw param blobs
    (include/nn/graph.hpp:119-126, include/tensor/tensor.hpp:585-606); ``load_state``
    rebuilds via the LayerFactory then loads blobs (graph.hpp:172-183). ``save_model``/
    ``load_model`` here are the equivalent single-file format: JSON header (module
    config via the registry round-trip) + named raw tensors.
  * the reference does NOT checkpoint optimizer state or dataloader position
    (SURVEY.md §5); ``Checkpoint.save``/``resume`` snapshots params + optimizer
    moments + net state (BatchNorm stats) + step + rng + scheduler + loader cursor,
    so resume is bit-exact, not approximate.

Binary layout of a ``.tnn`` tensor file:
  magic ``TNNTPU1\\n`` | u64 header_len | header JSON | concatenated raw tensor bytes.
  Header: {"tensors": [{"key", "dtype", "shape", "offset", "nbytes"}...], "meta": {...}}.
  Tensors are keyed by pytree path, so loading is template-shaped: the caller supplies a
  tree of the right structure (fresh ``init``) and leaves are replaced by key.
"""
from __future__ import annotations

import atexit
import json
import os
import shutil
import struct
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np

_MAGIC = b"TNNTPU1\n"


def _np_dtype(name: str):
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def _flatten_with_keys(tree) -> Dict[str, Any]:
    from .core.module import tree_paths

    return tree_paths(tree)


# ---------------------------------------------------------------------------
# Tensor-file primitives
# ---------------------------------------------------------------------------


def save_tensors(path: str, trees: Dict[str, Any], meta: Optional[Dict] = None) -> None:
    """Write named pytrees of arrays to one binary file. ``trees`` maps a section name
    ("params", "opt_state", ...) to a pytree; keys become "section/leaf/path"."""
    from .ops.pallas.quant_matmul import Int8Weight

    for section, tree in trees.items():
        for leaf in jax.tree_util.tree_leaves(
                tree, is_leaf=lambda x: isinstance(x, Int8Weight)):
            if isinstance(leaf, Int8Weight):
                # the custom pytree would silently reload as a plain dict and
                # break layers downstream; quantization is a decode-time view
                raise ValueError(
                    f"section {section!r} contains Int8Weight leaves — "
                    "checkpoints store float params; quantize AFTER load "
                    "(nn.quantize_for_decode)")
    entries = []
    arrays = []
    offset = 0
    for section, tree in trees.items():
        for key, leaf in _flatten_with_keys(tree).items():
            arr = np.asarray(leaf)
            if not arr.flags["C_CONTIGUOUS"]:  # ascontiguousarray would 1-d-ify 0-d
                arr = np.ascontiguousarray(arr)
            full_key = f"{section}/{key}" if key else section
            entries.append({"key": full_key, "dtype": str(arr.dtype),
                            "shape": list(arr.shape), "offset": offset,
                            "nbytes": arr.nbytes})
            arrays.append(arr)
            offset += arr.nbytes
    header = json.dumps({"tensors": entries, "meta": meta or {}}).encode()
    tmp = path + ".tmp"
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(tmp, "wb") as f:
        f.write(_MAGIC)
        f.write(struct.pack("<Q", len(header)))
        f.write(header)
        # stream each array's buffer directly — no serialized second copy of the
        # whole state in host memory (uint8 view: ml_dtypes like bf16 don't
        # implement the buffer protocol themselves)
        for arr in arrays:
            f.write(arr.reshape(-1).view(np.uint8).data)
    os.replace(tmp, path)  # atomic: no torn checkpoints on crash


def read_tensor_file(path: str) -> Tuple[Dict[str, np.ndarray], Dict]:
    """Read back {full_key: array}, meta."""
    with open(path, "rb") as f:
        if f.read(len(_MAGIC)) != _MAGIC:
            raise ValueError(f"{path}: not a TNNTPU tensor file")
        (hlen,) = struct.unpack("<Q", f.read(8))
        header = json.loads(f.read(hlen))
        base = f.tell()
        out = {}
        for e in header["tensors"]:
            f.seek(base + e["offset"])
            raw = f.read(e["nbytes"])
            arr = np.frombuffer(raw, dtype=_np_dtype(e["dtype"])).reshape(e["shape"])
            out[e["key"]] = arr
    return out, header.get("meta", {})


def load_tensors(path: str, templates: Dict[str, Any]) -> Tuple[Dict[str, Any], Dict]:
    """Load sections into template-shaped pytrees (keys must match exactly —
    a structural mismatch is an error, not a silent partial load)."""
    flat, meta = read_tensor_file(path)
    out = {}
    for section, template in templates.items():
        leaves_with_path, treedef = jax.tree_util.tree_flatten_with_path(template)
        tmpl_keys = _flatten_with_keys(template)
        want = {f"{section}/{k}" if k else section for k in tmpl_keys}
        have = {k for k in flat if k == section or k.startswith(section + "/")}
        if want != have:
            missing, surplus = sorted(want - have), sorted(have - want)
            raise KeyError(f"checkpoint section {section!r} mismatch: "
                           f"missing={missing[:5]} surplus={surplus[:5]}")
        new_leaves = []
        for (pathk, leaf), key in zip(leaves_with_path, tmpl_keys):
            full = f"{section}/{key}" if key else section
            arr = flat[full]
            if tuple(arr.shape) != tuple(np.shape(leaf)):
                raise ValueError(f"{full}: shape {arr.shape} != template {np.shape(leaf)}")
            new_leaves.append(arr)
        out[section] = jax.tree_util.tree_unflatten(treedef, new_leaves)
    return out, meta


# ---------------------------------------------------------------------------
# Model save/load (parity: Graph::save_state / load_state)
# ---------------------------------------------------------------------------


def save_model(path: str, model, params, net_state=None) -> None:
    """Single-file model snapshot: module config + params (+ BatchNorm stats)."""
    trees = {"params": params}
    if net_state:
        trees["net_state"] = net_state
    save_tensors(path, trees, meta={"model_config": model.get_config()})


def load_model(path: str, rng: Optional[jax.Array] = None,
               input_shape=None) -> Tuple[Any, Dict[str, Any]]:
    """Rebuild the module from its stored config (registry round-trip, parity:
    Graph::create_from_config) and return ``(model, variables)``.

    The stored arrays are loaded positionally-by-path into a template built from a
    fresh ``model.init`` when ``input_shape`` is given; otherwise arrays are returned
    in a path-keyed dict nested by '/' (no template needed).
    """
    from .core.module import module_from_config

    flat, meta = read_tensor_file(path)
    model = module_from_config(meta["model_config"])
    if input_shape is not None:
        variables = model.init(rng if rng is not None else jax.random.PRNGKey(0),
                               input_shape)
        templates = {"params": variables["params"]}
        if any(k.startswith("net_state/") for k in flat):
            templates["net_state"] = variables["state"]
        loaded, _ = load_tensors(path, templates)
        return model, {"params": loaded["params"],
                       "state": loaded.get("net_state", variables["state"])}
    # no template: reconstruct nested dicts from the path keys
    nested: Dict[str, Any] = {}
    for key, arr in flat.items():
        parts = key.split("/")
        d = nested
        for p in parts[:-1]:
            d = d.setdefault(p, {})
        d[parts[-1]] = arr
    return model, {"params": nested.get("params", {}),
                   "state": nested.get("net_state", {})}


# ---------------------------------------------------------------------------
# Full training-state checkpoints (exceeds reference)
# ---------------------------------------------------------------------------


class Checkpoint:
    """Directory checkpoints of the FULL training state with retention.

    Layout: ``<dir>/step_<N>/state.tnn`` + ``meta.json``; ``<dir>/best/`` mirrors the
    best-validation snapshot (parity: best-val save in src/nn/train.cpp:242-255).
    """

    def __init__(self, directory: str, keep: int = 3):
        self.directory = directory
        self.keep = int(keep)
        self._pending = None  # in-flight async writer thread
        # a block=False save still in flight at interpreter exit would be
        # killed mid-write (daemon thread) — join it so the newest checkpoint
        # is complete on clean shutdown
        atexit.register(self._join_at_exit)

    def _join_at_exit(self) -> None:
        try:
            self.wait()
        except Exception as e:  # noqa: BLE001 — exit path: report, don't raise
            import sys

            print(f"checkpoint: async save failed at exit: {e}",
                  file=sys.stderr)

    # -- write ---------------------------------------------------------------

    def save(self, train_state, model=None, scheduler=None, loader=None,
             extra: Optional[Dict] = None, best: bool = False,
             block: bool = True) -> str:
        """Snapshot the full training state.

        ``block=False`` overlaps the disk write with training (the orbax-style
        async save): the state is fetched to HOST first — synchronously,
        because the train step donates its input buffers and a background
        read of device arrays would race the next step's donation — then the
        serialization + file write + retention GC run on a daemon thread.
        Writes are serialized (a new save joins the previous one); call
        :meth:`wait` before reading the newest checkpoint back.
        """
        from .train.step import TrainState

        assert isinstance(train_state, TrainState)
        step = int(train_state.step)
        name = "best" if best else f"step_{step}"
        target = os.path.join(self.directory, name)
        meta: Dict[str, Any] = {"step": step, "extra": extra or {}}
        if model is not None:
            meta["model_config"] = model.get_config()
        if scheduler is not None:
            meta["scheduler"] = {"config": scheduler.get_config(),
                                 "state": getattr(scheduler, "state_dict", dict)()}
        if loader is not None:
            meta["loader"] = loader.state_dict()
        trees = {
            "params": train_state.params,
            "opt_state": train_state.opt_state,
            "net_state": train_state.net_state,
            "step": train_state.step,
            "rng": train_state.rng,
        }
        if not block:
            # host copy BEFORE the writer thread exists and BEFORE this call
            # returns: the caller's next donated train step invalidates the
            # device buffers, so the thread must never see them
            trees = jax.device_get(trees)

        def write(trees=trees, meta=meta, target=target, best=best):
            os.makedirs(target, exist_ok=True)
            save_tensors(os.path.join(target, "state.tnn"), trees, meta=meta)
            with open(os.path.join(target, "meta.json"), "w") as f:
                json.dump(meta, f, indent=2, default=str)
            if not best:
                self._gc()

        if block:
            self.wait()  # keep writes ordered with any in-flight async save
            write()
        else:
            import threading

            self.wait()

            def guarded():
                try:
                    write()
                except BaseException as e:  # noqa: BLE001 — surfaced by wait()
                    self._error = e

            self._error = None
            self._pending = threading.Thread(target=guarded, daemon=True)
            self._pending.start()
        return target

    def wait(self) -> None:
        """Join an in-flight ``block=False`` save; re-raises its failure (a
        silently missing checkpoint must not read as success)."""
        if self._pending is not None:
            self._pending.join()
            self._pending = None
            err, self._error = getattr(self, "_error", None), None
            if err is not None:
                raise err

    def _gc(self):
        steps = sorted(self._step_dirs())
        for s in steps[: max(0, len(steps) - self.keep)]:
            shutil.rmtree(os.path.join(self.directory, f"step_{s}"), ignore_errors=True)

    def _step_dirs(self):
        if not os.path.isdir(self.directory):
            return []
        out = []
        for d in os.listdir(self.directory):
            if not d.startswith("step_"):
                continue
            # write() creates state.tnn before meta.json, so meta.json marks
            # a COMPLETE snapshot — a crash between the two must not leave a
            # torn step dir restorable (or GC-countable) as the latest
            if not os.path.isfile(os.path.join(self.directory, d,
                                               "meta.json")):
                continue
            try:
                out.append(int(d[5:]))
            except ValueError:
                pass
        return out

    # -- read ----------------------------------------------------------------

    def latest_path(self) -> Optional[str]:
        steps = self._step_dirs()
        if steps:
            return os.path.join(self.directory, f"step_{max(steps)}")
        # ``directory`` may itself be a concrete checkpoint (e.g. resume=".../best"
        # or ".../step_120")
        if os.path.isfile(os.path.join(self.directory, "state.tnn")):
            return self.directory
        return None

    def restore(self, train_state, path: Optional[str] = None,
                scheduler=None, loader=None):
        """Restore into a template TrainState (fresh ``create_train_state``). Returns
        ``(train_state, meta)``; also rehydrates scheduler/loader in place."""
        path = path or self.latest_path()
        if path is None:
            raise FileNotFoundError(f"no checkpoints under {self.directory}")
        loaded, meta = load_tensors(os.path.join(path, "state.tnn"), {
            "params": train_state.params,
            "opt_state": train_state.opt_state,
            "net_state": train_state.net_state,
            "step": train_state.step,
            "rng": train_state.rng,
        })
        new_state = train_state._replace(
            params=loaded["params"], opt_state=loaded["opt_state"],
            net_state=loaded["net_state"],
            step=jax.numpy.asarray(loaded["step"]),
            rng=jax.numpy.asarray(loaded["rng"]))
        if scheduler is not None and "scheduler" in meta:
            sd = meta["scheduler"].get("state") or {}
            if hasattr(scheduler, "load_state_dict"):
                scheduler.load_state_dict(sd)
        if loader is not None and "loader" in meta:
            loader.load_state_dict(meta["loader"])
        return new_state, meta
