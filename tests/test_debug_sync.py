"""TNN_DEBUG_SYNC transfer-guard tests.

Under ``TNN_DEBUG_SYNC=1`` the engine wraps every ``step()`` in
``jax.transfer_guard("disallow")``: all host<->device traffic inside the
step must flow through the explicit ``_put`` / ``jax.device_get`` points,
and any implicit transfer (a raw numpy array or Python scalar committed at
jit dispatch, an implicit fetch) raises at the exact line that caused it.

Two directions, both required:

* a CLEAN step runs unchanged under the guard — same tokens, no errors —
  proving the hot path really is transfer-explicit, and
* a PLANTED implicit transfer (``_put`` monkeypatched back to the raw
  host array it used to pass) trips the guard and fails the request with
  a "transfer" error, proving the guard actually has teeth.
"""
import importlib
import threading

import numpy as np
import pytest

import jax

from tnn_tpu.serving import InferenceEngine, RequestState

KW = dict(num_blocks=32, block_size=4, max_batch_size=4, max_seq_len=32)


@pytest.fixture(scope="module")
def tiny_lm():
    from tnn_tpu.models.gpt2 import GPT2

    model = GPT2(vocab_size=128, max_len=64, num_layers=2, d_model=32,
                 num_heads=2)
    params = model.init(jax.random.PRNGKey(0), (1, 8))["params"]
    return model, params


def _run(model, params, **kw):
    eng = InferenceEngine(model, params, **{**KW, **kw})
    rng = np.random.default_rng(7)
    prompts = [rng.integers(0, 128, p).astype(np.int32) for p in (5, 9, 12)]
    rids = [eng.submit(p, 8) for p in prompts]
    out = eng.run_until_complete()
    return [out[r] for r in rids]


@pytest.fixture(scope="module")
def baseline(tiny_lm):
    """Guard-off greedy reference, shared across the parity tests (each
    engine run recompiles the step shapes — one reference run, not three).
    Spec-on greedy output equals spec-off (test_serving's parity gates), so
    this one baseline serves the spec test too."""
    model, params = tiny_lm
    return _run(model, params)


class TestDebugSync:
    def test_guard_off_by_default(self, tiny_lm):
        model, params = tiny_lm
        eng = InferenceEngine(model, params, **KW)
        assert eng.debug_sync is False

    def test_clean_step_token_exact_under_guard(self, tiny_lm, baseline,
                                                monkeypatch):
        """The guarded step is a no-op for correct code: token-for-token
        identical to the unguarded run, nothing raises."""
        model, params = tiny_lm
        monkeypatch.setenv("TNN_DEBUG_SYNC", "1")
        assert _run(model, params) == baseline

    def test_spec_decode_clean_under_guard(self, tiny_lm, baseline,
                                           monkeypatch):
        """Drafters run INSIDE the step's guard; the draft-model drafter's
        own dispatch/fetch must therefore be explicit too."""
        model, params = tiny_lm
        monkeypatch.setenv("TNN_DEBUG_SYNC", "1")
        got = _run(model, params, spec="draft", draft_model=model,
                   draft_params=params, spec_k=3)
        assert got == baseline

    def test_planted_transfer_trips_guard(self, tiny_lm, monkeypatch):
        """Reintroduce the implicit host->device commit the explicit _put
        replaced: under the guard the step must fail the request with a
        transfer error rather than silently syncing."""
        model, params = tiny_lm
        monkeypatch.setenv("TNN_DEBUG_SYNC", "1")
        monkeypatch.setattr(InferenceEngine, "_put",
                            lambda self, x, dtype=None: np.asarray(x, dtype))
        eng = InferenceEngine(model, params, **KW)
        rid = eng.submit(np.arange(5, dtype=np.int32), 4)
        eng.run_until_complete()
        res = eng.result(rid)
        assert res.state is RequestState.FAILED
        assert "transfer" in res.error.lower()

    def test_planted_transfer_harmless_without_guard(self, tiny_lm, baseline,
                                                     monkeypatch):
        """Negative control: the same plant without TNN_DEBUG_SYNC decodes
        normally — the guard, not the plant, is what raises."""
        model, params = tiny_lm
        monkeypatch.setattr(InferenceEngine, "_put",
                            lambda self, x, dtype=None: np.asarray(x, dtype))
        assert _run(model, params) == baseline


class TestWorkerOnlyRuntime:
    """TNN_DEBUG_THREADS=1 arms @worker_only's owning-thread assert (the
    static side of the contract is the cross-thread-engine-access lint
    rule, tests/test_lint.py)."""

    def test_assert_fires_only_cross_thread(self, monkeypatch):
        from tnn_tpu.serving import ownership

        monkeypatch.setenv("TNN_DEBUG_THREADS", "1")
        mod = importlib.reload(ownership)  # the knob is read at import
        try:
            class Owner:
                _thread = None

                @mod.worker_only
                def poke(self):
                    return 1

            o = Owner()
            assert o.poke() == 1                  # no worker: caller owns
            o._thread = threading.current_thread()
            assert o.poke() == 1                  # on the owning thread
            o._thread = threading.Thread(name="worker-0")
            with pytest.raises(AssertionError, match="owned by"):
                o.poke()
        finally:
            monkeypatch.delenv("TNN_DEBUG_THREADS")
            importlib.reload(mod)
