"""tnnlint tests: one positive and one negative fixture per rule, the
suppression/baseline machinery, and the repo-wide tier-1 gate.

The fixtures are the executable spec of each contract: the positive shows
the exact anti-pattern the rule exists to catch, the negative shows the
blessed idiom that must stay clean. The gate at the bottom is the real
enforcement: ``tnn_tpu/`` lints to zero findings against an EMPTY baseline,
so any new violation fails tier-1 until it is fixed or suppressed with an
inline justification.
"""
from pathlib import Path

import pytest

from tools.tnnlint import lint_source, lint_paths, rule_registry
from tools.tnnlint.baseline import compare, read_baseline, write_baseline
from tools.tnnlint.cli import main
from tools.tnnlint.config import load_config
from tools.tnnlint.core import BARE_SUPPRESSION

REPO = Path(__file__).resolve().parent.parent


def _rules(src, select):
    return [v.rule for v in lint_source(src, select=[select])]


# -- rule fixtures: positive (must flag) / negative (must stay clean) ---------


class TestUnboundedCompileKey:
    def test_raw_length_in_key_flags(self):
        assert _rules('''
class E:
    def step(self, n):
        key = (n, self.mode)
        fn = self._jit.get(key)
''', "unbounded-compile-key") == ["unbounded-compile-key"]

    def test_bucketed_key_clean(self):
        assert _rules('''
from tnn_tpu.utils.bucketing import pow2_bucket
class E:
    def step(self, n):
        key = (pow2_bucket(n), self.mode)
        fn = self._jit.get(key)
''', "unbounded-compile-key") == []

    def test_min_against_fixed_geometry_clean(self):
        # min() has bounded range as soon as ONE argument is bounded
        assert _rules('''
class E:
    def step(self, n):
        key = (min(n, self.max_batch_size),)
        fn = self._jit[key]
''', "unbounded-compile-key") == []

    # the step_build split: engines key their jit cache on step.key where
    # step came from a packer that buckets internally — bounded only when
    # the packer is a configured bucket_helper
    PACKED = '''
from tnn_tpu.serving import step_build
class E:
    def step(self, rows):
        step = step_build.pack_mixed(rows, b=self.b, nb=self.nb)
        fn = self._jit.get(step.key)
'''

    def test_packed_step_key_clean_with_helper(self):
        vios = lint_source(
            self.PACKED, select=["unbounded-compile-key"],
            options={"unbounded-compile-key":
                     {"bucket_helpers": ["pow2_bucket", "pack_mixed"]}})
        assert vios == []

    def test_attr_of_unbounded_local_still_flags(self):
        # without the helper blessing, step is opaque and step.key raw
        assert _rules(self.PACKED, "unbounded-compile-key") == \
            ["unbounded-compile-key"]


class TestUseAfterDonate:
    BUILDER = '''
import jax
class E:
    def _step_fn(self):
        def fn(pages_k, pages_v):
            return pages_k, pages_v
        return jax.jit(fn, donate_argnums=(0, 1))

    def step(self):
        fn = self._jit.get(key)
        if fn is None:
            fn = self._jit[key] = self._step_fn()
        pk, pv = fn(self.pool.pages_k, self.pool.pages_v)
'''

    def test_read_after_donation_flags(self):
        src = self.BUILDER + '''
        shape = self.pool.pages_k.shape
        self.pool.update_pages(pk, pv)
'''
        assert _rules(src, "use-after-donate") == ["use-after-donate"]

    def test_read_after_readoption_clean(self):
        src = self.BUILDER + '''
        self.pool.update_pages(pk, pv)
        shape = self.pool.pages_k.shape
'''
        assert _rules(src, "use-after-donate") == []

    # quantized pools: int8 pages travel with separate scale sidecars and
    # BOTH are donated — re-adopting only the pages leaves the scales dead
    SIDECAR_BUILDER = '''
import jax
class E:
    def _step_fn(self):
        def fn(pages_k, pages_v, scales_k, scales_v):
            return pages_k, pages_v, scales_k, scales_v
        return jax.jit(fn, donate_argnums=(0, 1, 2, 3))

    def step(self):
        fn = self._jit.get(key)
        if fn is None:
            fn = self._jit[key] = self._step_fn()
        pk, pv, sk, sv = fn(self.pool.pages_k, self.pool.pages_v,
                            self.pool.scales_k, self.pool.scales_v)
'''

    def test_dropped_scale_sidecar_flags(self):
        src = self.SIDECAR_BUILDER + '''
        self.pool.update_pages(pk, pv)
'''
        # one finding per dropped sidecar (scales_k AND scales_v)
        assert _rules(src, "use-after-donate") == [
            "use-after-donate", "use-after-donate"]

    def test_full_sidecar_readoption_clean(self):
        src = self.SIDECAR_BUILDER + '''
        self.pool.update_pages(pk, pv, sk, sv)
        shape = self.pool.pages_k.shape
'''
        assert _rules(src, "use-after-donate") == []

    # tensor-parallel builders: no direct jax.jit — the builder returns
    # self._jit_step(fn, donate_argnums=D), which compiles a plain jit at
    # tp=1 and a per-shard shard_map at tp>1. Donation happens on every
    # shard; the rule must keep tracking it through the wrapper.
    WRAPPED_BUILDER = '''
class E:
    def _step_fn(self):
        def fn(params, pages_k, pages_v):
            return pages_k, pages_v
        return self._jit_step(fn, donate_argnums=(1, 2))

    def step(self):
        fn = self._jit.get(key)
        if fn is None:
            fn = self._jit[key] = self._step_fn()
        pk, pv = fn(self.params, self.pool.pages_k, self.pool.pages_v)
'''

    def test_wrapped_builder_read_after_donation_flags(self):
        src = self.WRAPPED_BUILDER + '''
        shape = self.pool.pages_k.shape
        self.pool.update_pages(pk, pv)
'''
        assert _rules(src, "use-after-donate") == ["use-after-donate"]

    def test_wrapped_builder_readoption_clean(self):
        src = self.WRAPPED_BUILDER + '''
        self.pool.update_pages(pk, pv)
        shape = self.pool.pages_k.shape
'''
        assert _rules(src, "use-after-donate") == []

    # sequence-parallel builders route through SPContext.jit_step — same
    # wrapper name, extra routing kwargs (tables_argnum tells the context
    # mesh which argument is the per-shard table stack). Donation happens
    # on every context-mesh shard; the kwargs must not confuse the rule's
    # donated-position extraction.
    SP_BUILDER = '''
class E:
    def _step_fn(self):
        def fn(params, pages_k, pages_v, toks, offsets, tables):
            return pages_k, pages_v
        return self._sp.jit_step(fn, donate_argnums=(1, 2), n_outs=2,
                                 tables_argnum=5)

    def step(self):
        fn = self._jit.get(key)
        if fn is None:
            fn = self._jit[key] = self._step_fn()
        pk, pv = fn(self.params, self.pool.pages_k, self.pool.pages_v,
                    toks, offsets, tables)
'''

    def test_sp_builder_read_after_donation_flags(self):
        src = self.SP_BUILDER + '''
        shape = self.pool.pages_k.shape
        self.pool.update_pages(pk, pv)
'''
        assert _rules(src, "use-after-donate") == ["use-after-donate"]

    def test_sp_builder_readoption_clean(self):
        src = self.SP_BUILDER + '''
        self.pool.update_pages(pk, pv)
        shape = self.pool.pages_k.shape
'''
        assert _rules(src, "use-after-donate") == []


class TestHostSyncInStepPath:
    def test_int_on_device_value_flags(self):
        assert _rules('''
class InferenceEngine:
    def step(self):
        fn = self._jit[("d", 4)]
        tok = fn(self.params)
        return int(tok)
''', "host-sync-in-step-path") == ["host-sync-in-step-path"]

    def test_branch_on_device_value_flags(self):
        assert _rules('''
class InferenceEngine:
    def step(self):
        out = self._decode_fn(self.params)
        if out:
            return 1
''', "host-sync-in-step-path") == ["host-sync-in-step-path"]

    def test_batched_device_get_clean(self):
        assert _rules('''
import jax
class InferenceEngine:
    def step(self):
        fn = self._jit[("d", 4)]
        tok = fn(self.params)
        tok = jax.device_get(tok)
        return int(tok)
''', "host-sync-in-step-path") == []

    def test_off_step_path_clean(self):
        # same sync pattern outside the configured roots: not a finding
        assert _rules('''
class Offline:
    def generate(self):
        tok = self._decode_fn(self.params)
        return int(tok)
''', "host-sync-in-step-path") == []


class TestFetchOutsideCommit:
    def test_fetch_in_step_helper_flags(self):
        # a second device_get hidden in a build/commit helper: a stealth
        # pipeline barrier — the exact thing the overlapped loop forbids
        assert _rules('''
import jax
class InferenceEngine:
    def step(self):
        self._commit_rec()

    def _commit_rec(self):
        return int(jax.device_get(self._dev)[0])
''', "fetch-outside-commit") == ["fetch-outside-commit"]

    def test_fetch_inside_commit_helper_clean(self):
        assert _rules('''
import jax
class InferenceEngine:
    def step(self):
        out = self._fetch_bundle([self._dev])

    def _fetch_bundle(self, devs):
        return jax.device_get(tuple(devs))
''', "fetch-outside-commit") == []

    def test_fetch_off_step_path_clean(self):
        # tools/tests off the configured roots may fetch freely
        assert _rules('''
import jax
class Exporter:
    def snapshot(self):
        return jax.device_get(self._dev)
''', "fetch-outside-commit") == []

    # the sharded step: TPContext.jit_step returns a dispatch closure that
    # runs on EVERY engine step — a device_get hidden in it would barrier
    # all tp shards per step, so closures of reachable functions are on
    # the step path too
    TP_OPTS = {"fetch-outside-commit":
               {"step_roots": ["TPContext.jit_step"],
                "commit_helpers": ["InferenceEngine._fetch_bundle"]}}

    def test_fetch_in_tp_dispatch_closure_flags(self):
        vios = lint_source('''
import jax
class TPContext:
    def jit_step(self, fn):
        jitted = self._compile(fn)
        def dispatch(*args):
            return jax.device_get(jitted(*args))
        return dispatch
''', select=["fetch-outside-commit"], options=self.TP_OPTS)
        assert [v.rule for v in vios] == ["fetch-outside-commit"]

    def test_tp_dispatch_returning_device_refs_clean(self):
        vios = lint_source('''
class TPContext:
    def jit_step(self, fn):
        jitted = self._compile(fn)
        def dispatch(*args):
            return jitted(*args)
        return dispatch
''', select=["fetch-outside-commit"], options=self.TP_OPTS)
        assert vios == []

    # same contract for the sequence-parallel dispatcher: the closure
    # SPContext.jit_step returns stages per-shard tables and launches the
    # context-mesh step — a device_get hidden there (say, peeking at the
    # per-shard merge stats) would barrier all sp shards every step
    SP_OPTS = {"fetch-outside-commit":
               {"step_roots": ["SPContext.jit_step"],
                "commit_helpers": ["InferenceEngine._fetch_bundle"]}}

    def test_fetch_in_sp_dispatch_closure_flags(self):
        vios = lint_source('''
import jax
class SPContext:
    def jit_step(self, fn):
        jitted = self._compile(fn)
        def dispatch(*args):
            out = jitted(*args)
            stats = jax.device_get(out[-1])
            return out
        return dispatch
''', select=["fetch-outside-commit"], options=self.SP_OPTS)
        assert [v.rule for v in vios] == ["fetch-outside-commit"]

    def test_sp_dispatch_returning_device_refs_clean(self):
        vios = lint_source('''
class SPContext:
    def jit_step(self, fn):
        jitted = self._compile(fn)
        def dispatch(*args):
            return jitted(*args)
        return dispatch
''', select=["fetch-outside-commit"], options=self.SP_OPTS)
        assert vios == []


class TestPrngKeyReuse:
    def test_double_consumption_flags(self):
        assert _rules('''
def sample(key):
    a = draw(key)
    b = draw(key)
''', "prng-key-reuse") == ["prng-key-reuse"]

    def test_split_between_uses_clean(self):
        assert _rules('''
import jax
def sample(key):
    k1, k2 = jax.random.split(key)
    a = draw(k1)
    b = draw(k2)
''', "prng-key-reuse") == []

    def test_exclusive_branches_clean(self):
        # if/else arms never both execute: one consumption per trace
        assert _rules('''
def sample(key, fast):
    if fast:
        return draw(key)
    else:
        return draw2(key)
''', "prng-key-reuse") == []


class TestCrossThreadEngineAccess:
    def test_unmarked_owner_method_flags(self):
        assert _rules('''
class EngineSupervisor:
    def stats(self):
        return self.engine.metrics.snapshot()
''', "cross-thread-engine-access") == ["cross-thread-engine-access"]

    def test_worker_only_method_clean(self):
        assert _rules('''
from tnn_tpu.serving.ownership import worker_only
class EngineSupervisor:
    @worker_only
    def _tick(self):
        return self.engine.metrics.snapshot()
''', "cross-thread-engine-access") == []

    def test_reach_through_flags(self):
        # any class reaching THROUGH an engine reference is a violation
        assert _rules('''
class Server:
    def health(self):
        return self.sup.engine.scheduler.queue_depth
''', "cross-thread-engine-access") == ["cross-thread-engine-access"]

    def test_passing_engine_reference_clean(self):
        # handing the reference around is fine; dereferencing it is not
        assert _rules('''
class EngineSupervisor:
    def attach(self, sink):
        sink.register(self.engine)
''', "cross-thread-engine-access") == []


class TestUnpairedPoolMutation:
    def test_unchecked_mutation_flags(self):
        assert _rules('''
class PagedKVPool:
    def alloc(self, n):
        block = self._free.pop()
        return block
''', "unpaired-pool-mutation") == ["unpaired-pool-mutation"]

    def test_checked_mutation_clean(self):
        assert _rules('''
class PagedKVPool:
    def alloc(self, n):
        block = self._free.pop()
        self._debug_check()
        return block

    def _debug_check(self):
        if self.debug:
            self.check_invariants()
''', "unpaired-pool-mutation") == []


class TestUnboundedRetry:
    def test_unbounded_retry_loop_flags(self):
        assert _rules('''
class Router:
    def dispatch(self, rec):
        while True:
            try:
                return self._call(lambda: self.sup.submit(rec))
            except ConnectionError:
                continue
''', "unbounded-retry") == ["unbounded-retry"]

    def test_budget_in_condition_clean(self):
        assert _rules('''
class Router:
    def dispatch(self, rec):
        attempt = 0
        while attempt <= self.max_retries:
            attempt += 1
            try:
                return self._call(lambda: self.sup.submit(rec))
            except ConnectionError:
                continue
''', "unbounded-retry") == []

    def test_for_loop_retry_is_inherently_bounded(self):
        # the engine's one-shot decode retry idiom: never flagged
        assert _rules('''
class Engine:
    def decode(self):
        for attempt in (0, 1):
            try:
                return self.engine_step()
            except RuntimeError:
                continue
''', "unbounded-retry") == []

    def test_poll_loop_without_engine_call_clean(self):
        # deadline-bounded queue polls are not retry-around-replica loops
        assert _rules('''
def gather(q, want, deadline):
    got = []
    while len(got) < want:
        try:
            got.append(q.get(timeout=0.5))
        except TimeoutError:
            continue
    return got
''', "unbounded-retry") == []

    def test_unbudgeted_hedge_loop_flags(self):
        # hedge amplification bomb: fire duplicates until something lands
        assert _rules('''
class Router:
    def hedge_all(self, rec):
        while True:
            try:
                return self.fire_hedge(rec)
            except ConnectionError:
                continue
''', "unbounded-retry") == ["unbounded-retry"]

    def test_hedge_budget_in_condition_clean(self):
        assert _rules('''
class Router:
    def hedge_all(self, rec, open_):
        pending = 0
        while pending < self.hedge_budget * open_:
            pending += 1
            try:
                self.fire_hedge(rec)
            except ConnectionError:
                continue
''', "unbounded-retry") == []

    def test_hedge_deadline_in_condition_clean(self):
        # a wall deadline bounds the loop as well as a count budget does
        assert _rules('''
import time
class Router:
    def hedge_until(self, rec, deadline):
        while time.monotonic() < deadline:
            try:
                self.fire_hedge(rec)
            except ConnectionError:
                continue
''', "unbounded-retry") == []

    def test_unbudgeted_scale_up_retry_flags(self):
        # replica-churn bomb: retry a failed join forever against a sick
        # control plane
        assert _rules('''
class Scaler:
    def grow(self):
        while True:
            try:
                return self.router.add_replica(self.factory)
            except ConnectionError:
                continue
''', "unbounded-retry") == ["unbounded-retry"]

    def test_join_retries_budget_clean(self):
        assert _rules('''
class Scaler:
    def grow(self):
        attempts = 0
        while attempts <= self.join_retries:
            attempts += 1
            try:
                return self.router.add_replica(self.factory)
            except ConnectionError:
                continue
''', "unbounded-retry") == []

    def test_hysteresis_bound_counts_as_budget(self):
        # a scaling control loop is bounded by its stability guards, not
        # an attempt counter — hysteresis/cooldown names satisfy the rule
        assert _rules('''
class Scaler:
    def wait_low(self, now):
        while (now - self.low_since) < self.hysteresis_s:
            try:
                now = self.scale_probe()
            except ConnectionError:
                continue
''', "unbounded-retry") == []

    def test_cooldown_bound_counts_as_budget(self):
        assert _rules('''
class Scaler:
    def settle(self, t):
        while (t - self.last_action_t) < self.cooldown_s:
            try:
                t = self.scale_probe()
            except ConnectionError:
                continue
''', "unbounded-retry") == []


class TestTierAdoptUnverified:
    def test_raw_tier_readmit_flags(self):
        assert _rules('''
class Engine:
    def readmit(self, key):
        return self.kv_tier.readmit(key)
''', "tier-adopt-unverified") == ["tier-adopt-unverified"]

    def test_raw_tier_get_flags(self):
        # pulling the raw entry skips the digest check just as surely
        assert _rules('''
class Engine:
    def peek(self, key):
        return self.host_tier.get(key)
''', "tier-adopt-unverified") == ["tier-adopt-unverified"]

    def test_tier_adopt_flags(self):
        assert _rules('''
def splice(tier, key, blk):
    tier.adopt(key, blk)
''', "tier-adopt-unverified") == ["tier-adopt-unverified"]

    def test_verify_readmit_clean(self):
        # the one sanctioned door: digest recomputed, mismatch -> miss
        assert _rules('''
class Engine:
    def readmit(self, key):
        return self.kv_tier.verify_readmit(key)
''', "tier-adopt-unverified") == []

    def test_prefix_cache_adopt_clean(self):
        # device-side index adoption: the receiver is not a tier
        assert _rules('''
class Engine:
    def index(self, key, blk):
        self.prefix_cache.adopt(key, blk)
''', "tier-adopt-unverified") == []

    def test_tier_demote_and_maintenance_clean(self):
        # admission INTO the tier (where the digest is computed) and the
        # stats/maintenance surface are not adoption
        assert _rules('''
class Engine:
    def housekeeping(self, key, leaves):
        self.kv_tier.demote(key, leaves)
        self.kv_tier.clear()
        return self.kv_tier.stats()
''', "tier-adopt-unverified") == []

    # -- cross-replica wire adoption: adopt_blocks on ANY receiver ------------

    def test_wire_adopt_without_verification_flags(self):
        # writing wire bytes into device pages with no digest check in
        # the enclosing function — the disaggregation handoff hole
        assert _rules('''
class Engine:
    def adopt_prefix(self, exports):
        for key, leaves, digest in exports:
            blk = self.pool.alloc(1)
            self.pool.adopt_blocks([(blk[0], leaves[0], leaves[1])],
                                   fn, put)
''', "tier-adopt-unverified") == ["tier-adopt-unverified"]

    def test_wire_adopt_with_tier_digest_clean(self):
        assert _rules('''
class Engine:
    def adopt_prefix(self, exports):
        for key, leaves, digest in exports:
            if tier_digest(key, leaves) != digest:
                break
            blk = self.pool.alloc(1)
            self.pool.adopt_blocks([(blk[0], leaves[0], leaves[1])],
                                   fn, put)
''', "tier-adopt-unverified") == []

    def test_wire_adopt_with_verify_readmit_clean(self):
        # tier re-admission path: verify_readmit IS the digest check
        assert _rules('''
class Engine:
    def readmit(self, key):
        leaves = self.kv_tier.verify_readmit(key)
        if leaves is not None:
            self.pool.adopt_blocks([(3, leaves[0], leaves[1])], fn, put)
''', "tier-adopt-unverified") == []

    def test_wire_adopt_helper_indirection_still_flags(self):
        # the check must be visible AT the adoption site: a verification
        # call in a DIFFERENT function does not sanctify this one
        assert _rules('''
def checked(key, leaves, digest):
    return tier_digest(key, leaves) == digest

class Engine:
    def adopt_prefix(self, exports):
        for key, leaves, digest in exports:
            if not checked(key, leaves, digest):
                break
            self.pool.adopt_blocks([(3, leaves[0], leaves[1])], fn, put)
''', "tier-adopt-unverified") == ["tier-adopt-unverified"]


class TestUnregisteredMetricKey:
    REGISTRY = '''
EXPOSITION = {
    "serve.ttft_s": ("tnn_serve_ttft_seconds", "histogram",
                     "Time to first token", "ttft_ms_p50"),
}
'''

    def test_unregistered_tick_flags(self):
        assert _rules(self.REGISTRY + '''
class M:
    def observe(self, s):
        self._tick("serve.ghost_s", s)
''', "unregistered-metric-key") == ["unregistered-metric-key"]

    def test_registered_tick_clean(self):
        assert _rules(self.REGISTRY + '''
class M:
    def observe(self, s):
        self._tick("serve.ttft_s", s)
''', "unregistered-metric-key") == []

    def test_stale_summary_key_flags(self):
        # the registry names a summary field that summary() no longer has
        assert _rules(self.REGISTRY + '''
class M:
    def summary(self):
        return {"renamed_ttft_p50": 1.0}
''', "unregistered-metric-key") == ["unregistered-metric-key"]

    def test_live_summary_key_clean(self):
        assert _rules(self.REGISTRY + '''
class M:
    def summary(self):
        return {"ttft_ms_p50": 1.0}
''', "unregistered-metric-key") == []

    def test_module_without_registry_ignored(self):
        # engines/supervisors tick through observe_*; only the module
        # owning the registry dict is cross-checked
        assert _rules('''
class Engine:
    def step(self):
        self.metrics._tick("serve.anything", 1.0)
''', "unregistered-metric-key") == []


# -- framework machinery ------------------------------------------------------


POS = '''
class E:
    def step(self, n):
        key = (n,)  {sup}
        fn = self._jit.get(key)
'''


class TestSuppressions:
    def test_justified_suppression_drops_finding(self):
        src = POS.format(
            sup="# tnnlint: disable=unbounded-compile-key -- n is clamped "
                "by the caller")
        assert lint_source(src) == []

    def test_preceding_comment_line_covers_next_line(self):
        src = ('class E:\n'
               '    def step(self, n):\n'
               '        # tnnlint: disable=unbounded-compile-key -- clamped\n'
               '        key = (n,)\n'
               '        fn = self._jit.get(key)\n')
        assert lint_source(src) == []

    def test_bare_suppression_is_itself_a_violation(self):
        src = POS.format(sup="# tnnlint: disable=unbounded-compile-key")
        rules = [v.rule for v in lint_source(src)]
        assert rules == [BARE_SUPPRESSION]

    def test_bare_suppression_cannot_be_suppressed(self):
        src = "x = 1  # tnnlint: disable=bare-suppression -- nice try\n"
        assert [v.rule for v in lint_source(src)] == [BARE_SUPPRESSION]

    def test_unrelated_rule_suppression_does_not_mask(self):
        src = POS.format(sup="# tnnlint: disable=prng-key-reuse -- wrong one")
        assert [v.rule for v in lint_source(src)] == ["unbounded-compile-key"]


class TestDriver:
    def test_all_ten_rules_registered(self):
        assert set(rule_registry()) == {
            "unbounded-compile-key", "use-after-donate",
            "host-sync-in-step-path", "fetch-outside-commit",
            "prng-key-reuse", "cross-thread-engine-access",
            "unpaired-pool-mutation", "unbounded-retry",
            "unregistered-metric-key", "tier-adopt-unverified"}

    def test_unknown_rule_name_rejected(self):
        with pytest.raises(ValueError, match="unknown rule"):
            lint_source("x = 1", select=["no-such-rule"])

    def test_syntax_error_reported_not_raised(self):
        vs = lint_source("def f(:\n")
        assert [v.rule for v in vs] == ["parse-error"]


class TestBaseline:
    def _findings(self):
        return lint_source(POS.format(sup=""), path="fake.py")

    def test_round_trip(self, tmp_path):
        vs = self._findings()
        assert vs
        bl = tmp_path / "baseline.json"
        write_baseline(bl, vs)
        fresh, stale = compare(vs, read_baseline(bl))
        assert fresh == [] and stale == []

    def test_new_finding_is_fresh(self, tmp_path):
        bl = tmp_path / "baseline.json"
        write_baseline(bl, [])
        fresh, stale = compare(self._findings(), read_baseline(bl))
        assert [v.rule for v in fresh] == ["unbounded-compile-key"]
        assert stale == []

    def test_fixed_finding_goes_stale(self, tmp_path):
        bl = tmp_path / "baseline.json"
        write_baseline(bl, self._findings())
        fresh, stale = compare([], read_baseline(bl))
        assert fresh == [] and len(stale) == 1

    def test_fingerprint_survives_line_shift(self):
        a = lint_source(POS.format(sup=""), path="fake.py")[0]
        b = lint_source("\n\n" + POS.format(sup=""), path="fake.py")[0]
        assert a.line != b.line
        assert a.fingerprint() == b.fingerprint()


class TestCli:
    def test_clean_file_exits_zero(self, tmp_path, capsys):
        f = tmp_path / "ok.py"
        f.write_text("x = 1\n")
        assert main([str(f), "--no-baseline"]) == 0
        assert "clean" in capsys.readouterr().out

    def test_violation_exits_one(self, tmp_path, capsys):
        f = tmp_path / "bad.py"
        f.write_text(POS.format(sup=""))
        assert main([str(f), "--no-baseline"]) == 1
        assert "unbounded-compile-key" in capsys.readouterr().out

    def test_write_then_check_baseline(self, tmp_path, capsys):
        f = tmp_path / "bad.py"
        f.write_text(POS.format(sup=""))
        bl = tmp_path / "bl.json"
        assert main([str(f), "--baseline", str(bl), "--write-baseline"]) == 0
        capsys.readouterr()
        # baselined: same findings no longer fail the run
        assert main([str(f), "--baseline", str(bl)]) == 0

    def test_stale_baseline_entry_exits_one(self, tmp_path, capsys):
        f = tmp_path / "bad.py"
        f.write_text(POS.format(sup=""))
        bl = tmp_path / "bl.json"
        assert main([str(f), "--baseline", str(bl), "--write-baseline"]) == 0
        f.write_text("x = 1\n")  # fixed: baseline entry is now stale
        capsys.readouterr()
        assert main([str(f), "--baseline", str(bl)]) == 1
        assert "stale" in capsys.readouterr().out

    def test_unknown_rule_exits_two(self, tmp_path, capsys):
        f = tmp_path / "ok.py"
        f.write_text("x = 1\n")
        assert main([str(f), "--select", "bogus", "--no-baseline"]) == 2


# -- the tier-1 gate ----------------------------------------------------------


class TestRepoGate:
    def test_tnn_tpu_lints_clean(self):
        """The enforced contract: zero findings over the whole package with
        the committed pyproject config. New violations fail here until fixed
        or suppressed with an inline justification."""
        cfg = load_config(REPO)
        vs = lint_paths([str(REPO / p) for p in cfg["paths"]],
                        options=cfg["rules"], ignore=cfg["ignore"],
                        exclude=cfg["exclude"])
        assert vs == [], "\n" + "\n".join(v.render() for v in vs)

    def test_committed_baseline_is_empty(self):
        baseline = read_baseline(REPO / "tools" / "tnnlint" / "baseline.json")
        assert baseline == {}, (
            "the baseline must stay empty — fix new findings or add an "
            "inline justified suppression instead of baselining them")

    def test_cli_default_invocation_clean(self, monkeypatch, capsys):
        monkeypatch.chdir(REPO)
        assert main([]) == 0
