"""Data subsystem tests: loaders, formats, augmentation, tokenizer.

Mirrors the reference's loader/augmentation coverage (SURVEY.md §4) but with generated
fixtures — binary files are written in the reference's on-disk formats and read back, so
format compatibility is what's actually tested.
"""
import os
import struct

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tnn_tpu import data as tdata


# -- loader contract ----------------------------------------------------------


def test_array_loader_epoch_and_shuffle():
    x = np.arange(40, dtype=np.float32).reshape(10, 4)
    y = np.arange(10, dtype=np.int32)
    dl = tdata.ArrayDataLoader(x, y, seed=0)
    assert len(dl) == 10 and dl.data_shape == (4,)

    batches = list(dl.batches(4))
    assert len(batches) == 2  # remainder dropped
    got = np.concatenate([b[1] for b in batches])
    assert np.array_equal(got, np.arange(8))

    dl.shuffle()
    order1 = np.concatenate([b[1] for b in dl.batches(5)])
    order2 = np.concatenate([b[1] for b in dl.batches(5)])
    assert not np.array_equal(order1, np.arange(10)) or not np.array_equal(order2, np.arange(10))
    assert sorted(order1) == list(range(10))


def test_loader_tail_batch():
    dl = tdata.SyntheticDataLoader(10, (3,), 2)
    batches = list(dl.batches(4, drop_remainder=False))
    assert [len(b[0]) for b in batches] == [4, 4, 2]


def test_split_microbatches():
    x = np.zeros((8, 3)); y = np.zeros(8)
    mbs = tdata.split_microbatches(x, y, 4)
    assert len(mbs) == 4 and mbs[0][0].shape == (2, 3)
    with pytest.raises(ValueError):
        tdata.split_microbatches(x, y, 3)


def test_prefetch_matches_direct():
    dl = tdata.SyntheticDataLoader(16, (2,), 4)
    direct = [b[1].tolist() for b in dl.batches(4)]
    fetched = [np.asarray(b[1]).tolist() for b in tdata.prefetch(dl.batches(4))]
    assert direct == fetched


def test_prefetch_abandoned_early_stops_producer():
    import threading

    before = threading.active_count()
    dl = tdata.SyntheticDataLoader(64, (2,), 4)
    it = tdata.prefetch(dl.batches(4), to_device=False)
    next(it)
    it.close()  # early stop: producer must shut down, not leak
    for _ in range(50):
        if threading.active_count() <= before:
            break
        import time
        time.sleep(0.05)
    assert threading.active_count() <= before


def test_prefetch_propagates_errors():
    def bad():
        yield np.zeros(2), np.zeros(2)
        raise RuntimeError("boom")

    it = tdata.prefetch(bad(), to_device=False)
    next(it)
    with pytest.raises(RuntimeError, match="boom"):
        list(it)


# -- on-disk format compatibility --------------------------------------------


def test_mnist_csv_roundtrip(tmp_path):
    rows = []
    rs = np.random.RandomState(0)
    labels = rs.randint(0, 10, 5)
    pixels = rs.randint(0, 256, (5, 784))
    for lab, px in zip(labels, pixels):
        rows.append(",".join([str(lab)] + [str(p) for p in px]))
    p = tmp_path / "mnist_train.csv"
    p.write_text("label," + ",".join(f"p{i}" for i in range(784)) + "\n" + "\n".join(rows))

    dl = tdata.MNISTDataLoader(str(tmp_path), train=True)
    assert dl.data_shape == (28, 28, 1)
    d, l = dl.get_batch(5)
    assert np.array_equal(l, labels)
    assert np.allclose(d.reshape(5, -1), pixels / 255.0, atol=1e-6)


def test_digits_loader_real_data():
    """Bundled sklearn digits: real images, disjoint deterministic split,
    zoo-compatible 32x32x3 shape (the offline convergence-artifact dataset)."""
    pytest.importorskip("sklearn")
    tr = tdata.DigitsDataLoader(train=True, image_size=(32, 32))
    va = tdata.DigitsDataLoader(train=False, image_size=(32, 32))
    assert tr.data_shape == (32, 32, 3) and tr.num_classes == 10
    assert len(tr) + len(va) == 1797 and len(va) == pytest.approx(360, abs=1)
    d, l = tr.get_batch(16)
    assert d.dtype == np.float32 and 0.0 <= d.min() and d.max() <= 1.0
    assert ((l >= 0) & (l < 10)).all()
    # split is a partition: the two loaders' images never overlap
    tr_keys = {bytes(x) for x in (tr.data[:50] * 255).astype(np.uint8)
               .reshape(50, -1)}
    va_keys = {bytes(x) for x in (va.data * 255).astype(np.uint8)
               .reshape(len(va), -1)}
    assert not (tr_keys & va_keys)
    # determinism across constructions
    tr2 = tdata.DigitsDataLoader(train=True, image_size=(32, 32))
    np.testing.assert_array_equal(tr.labels, tr2.labels)


def test_cifar10_bin_format(tmp_path):
    rs = np.random.RandomState(1)
    n = 7
    recs = np.empty((n, 1 + 3072), np.uint8)
    recs[:, 0] = rs.randint(0, 10, n)
    recs[:, 1:] = rs.randint(0, 256, (n, 3072))
    (tmp_path / "data_batch_1.bin").write_bytes(recs.tobytes())

    dl = tdata.CIFAR10DataLoader(str(tmp_path), train=True)
    d, l = dl.get_batch(n)
    assert d.shape == (n, 32, 32, 3)
    assert np.array_equal(l, recs[:, 0])
    # CHW on disk -> NHWC in memory: red plane first on disk = channel 0
    assert np.allclose(d[0, :, :, 0].ravel() * 255, recs[0, 1:1025])


def test_cifar100_bin_format(tmp_path):
    rs = np.random.RandomState(2)
    n = 4
    recs = np.empty((n, 2 + 3072), np.uint8)
    recs[:, 0] = rs.randint(0, 20, n)   # coarse
    recs[:, 1] = rs.randint(0, 100, n)  # fine
    recs[:, 2:] = rs.randint(0, 256, (n, 3072))
    (tmp_path / "train.bin").write_bytes(recs.tobytes())

    dl = tdata.CIFAR100DataLoader(str(tmp_path), train=True)
    _, l = dl.get_batch(n)
    assert np.array_equal(l, recs[:, 1])


def test_image_folder_npy(tmp_path):
    for ci, cname in enumerate(["class_a", "class_b"]):
        d = tmp_path / cname
        d.mkdir()
        np.save(d / "images.npy",
                np.full((3, 8, 8, 3), ci * 100, np.uint8))
    dl = tdata.ImageFolderDataLoader(str(tmp_path), image_size=(8, 8))
    assert len(dl) == 6 and dl.class_names == ["class_a", "class_b"]
    d, l = dl.get_batch(6)
    assert np.array_equal(np.sort(l), [0, 0, 0, 1, 1, 1])


def test_image_folder_tinyimagenet_layout(tmp_path):
    # TinyImageNet layout: <class>/images/<name>.JPEG — decoded lazily per batch
    pytest.importorskip("PIL")
    from PIL import Image
    for ci, cname in enumerate(["n01443537", "n01629819"]):
        d = tmp_path / cname / "images"
        d.mkdir(parents=True)
        (tmp_path / cname / f"{cname}_boxes.txt").write_text("x")
        for i in range(2):
            Image.fromarray(np.full((64, 64, 3), ci * 100 + i, np.uint8)).save(
                d / f"{cname}_{i}.JPEG")
    dl = tdata.ImageFolderDataLoader(str(tmp_path), image_size=(64, 64))
    assert len(dl) == 4
    d_, l_ = dl.get_batch(4)
    assert d_.shape == (4, 64, 64, 3) and sorted(l_) == [0, 0, 1, 1]


def test_image_folder_class_names_order_preserved(tmp_path):
    for cname in ["dog", "cat"]:
        d = tmp_path / cname
        d.mkdir()
        np.save(d / "images.npy", np.zeros((1, 8, 8, 3), np.uint8))
    dl = tdata.ImageFolderDataLoader(str(tmp_path), image_size=(8, 8),
                                     class_names=["dog", "cat"])
    assert dl.class_names == ["dog", "cat"]  # user order pinned, not re-sorted
    _, l_ = dl.get_batch(2)
    assert set(l_) == {0, 1}


def test_token_stream_last_window_usable(tmp_path):
    toks = np.arange(17, dtype=np.uint16)  # exactly S+1 tokens -> one valid window
    p = tmp_path / "t.bin"
    toks.tofile(p)
    dl = tdata.OpenWebTextDataLoader(str(p), context_length=16)
    assert len(dl) == 1
    d, l = dl.random_windows(2)
    assert np.array_equal(d[0], np.arange(16)) and l[0][-1] == 16


def test_tokenizer_reload_clears_specials(tmp_path):
    base = [bytes([i]) for i in range(256)]
    p1, p2 = tmp_path / "v1.bin", tmp_path / "v2.bin"
    _write_vocab(p1, base + [b"<|endoftext|>"])
    _write_vocab(p2, base)
    tok = tdata.Tokenizer().load(str(p1))
    assert tok.eot_token == 256
    tok.load(str(p2))
    assert tok.eot_token is None


def test_token_stream(tmp_path):
    toks = np.arange(1000, dtype=np.uint16)
    p = tmp_path / "tokens.bin"
    toks.tofile(p)
    dl = tdata.OpenWebTextDataLoader(str(p), context_length=16)
    d, l = dl.get_batch(2)
    assert d.shape == (2, 16) and l.shape == (2, 16)
    # labels are inputs shifted by one (next-token prediction)
    assert np.array_equal(l[0], d[0] + 1)
    assert np.array_equal(d[1], np.arange(1, 17))

    d2, _ = dl.random_windows(3)
    assert d2.shape == (3, 16)


def test_factory():
    assert "cifar100" in tdata.available()
    dl = tdata.create("synthetic_cifar", num_samples=64)
    assert dl.data_shape == (32, 32, 3)
    with pytest.raises(KeyError):
        tdata.create("nope")


# -- augmentation -------------------------------------------------------------


@pytest.fixture
def batch():
    rs = np.random.RandomState(0)
    return jnp.asarray(rs.rand(4, 16, 16, 3).astype(np.float32))


def test_normalization(batch):
    aug = tdata.Normalization(mean=(0.5, 0.5, 0.5), std=(0.25, 0.25, 0.25))
    out = aug.apply(jax.random.PRNGKey(0), batch)
    assert np.allclose(out, (np.asarray(batch) - 0.5) / 0.25, atol=1e-6)


def test_horizontal_flip_deterministic(batch):
    aug = tdata.HorizontalFlip(p=1.0)
    out = aug.apply(jax.random.PRNGKey(0), batch)
    assert np.allclose(out, np.asarray(batch)[:, :, ::-1, :])
    noop = tdata.HorizontalFlip(p=0.0).apply(jax.random.PRNGKey(0), batch)
    assert np.allclose(noop, batch)


def test_vertical_flip(batch):
    out = tdata.VerticalFlip(p=1.0).apply(jax.random.PRNGKey(0), batch)
    assert np.allclose(out, np.asarray(batch)[:, ::-1, :, :])


def test_random_crop_shape_preserved(batch):
    out = tdata.RandomCrop(padding=2).apply(jax.random.PRNGKey(1), batch)
    assert out.shape == batch.shape
    assert not np.allclose(out, batch)  # virtually certain some sample shifted


def test_cutout_zeroes_square(batch):
    out = tdata.Cutout(size=6, p=1.0).apply(jax.random.PRNGKey(2), batch)
    assert out.shape == batch.shape
    # every sample must have at least one zeroed pixel (center always inside)
    zeroed = (np.asarray(out) == 0).any(axis=(1, 2, 3))
    assert zeroed.all()
    # zeroed region is at most size x size pixels (exactly size^2 when fully inside)
    per_sample = (np.asarray(out)[..., 0] == 0).sum(axis=(1, 2))
    assert (per_sample <= 36).all()
    big = np.ones((1, 32, 32, 3), np.float32)
    outb = np.asarray(tdata.Cutout(size=4, p=1.0).apply(jax.random.PRNGKey(0),
                                                        jnp.asarray(big)))
    counts = (outb[0, :, :, 0] == 0).sum()
    assert counts <= 16


def test_brightness_contrast_noise_bounded(batch):
    for aug in [tdata.Brightness(0.3, p=1.0), tdata.Contrast(0.5, 1.5, p=1.0),
                tdata.GaussianNoise(0.1, p=1.0)]:
        out = np.asarray(aug.apply(jax.random.PRNGKey(3), batch))
        assert out.min() >= 0.0 and out.max() <= 1.0
        assert not np.allclose(out, batch)


def test_rotation_identity_at_zero(batch):
    out = tdata.Rotation(max_degrees=0.0, p=1.0).apply(jax.random.PRNGKey(4), batch)
    assert np.allclose(out, batch, atol=1e-5)
    rot = tdata.Rotation(max_degrees=30.0, p=1.0).apply(jax.random.PRNGKey(5), batch)
    assert rot.shape == batch.shape and not np.allclose(rot, batch)


def test_pipeline_builder_and_config(batch):
    pipe = (tdata.AugmentationBuilder()
            .random_crop(2).horizontal_flip(0.5).cutout(4, 0.5)
            .normalization((0.5,) * 3, (0.25,) * 3).build())
    out = pipe(jax.random.PRNGKey(0), batch)
    assert out.shape == batch.shape

    cfg = pipe.get_config()
    assert [c["type"] for c in cfg] == ["random_crop", "horizontal_flip", "cutout",
                                        "normalization"]
    pipe2 = tdata.AugmentationPipeline.from_config(cfg)
    out2 = pipe2(jax.random.PRNGKey(0), batch)
    assert np.allclose(out, out2, atol=1e-6)


def test_pipeline_is_jittable(batch):
    pipe = tdata.cifar_train_pipeline()
    out = jax.jit(pipe.apply)(jax.random.PRNGKey(0), batch)
    assert out.shape == batch.shape


# -- tokenizer ----------------------------------------------------------------


def _write_vocab(path, tokens):
    with open(path, "wb") as f:
        f.write(struct.pack("<I", len(tokens)))
        for t in tokens:
            f.write(struct.pack("<I", len(t)))
            f.write(t)


def test_tokenizer_vocab_bin_roundtrip(tmp_path):
    # byte-level base vocab + merges appended in merge order (GPT-2 layout)
    base = [bytes([i]) for i in range(256)]
    merges = [b"he", b"ll", b"hell", b"o ", b"hello "]
    vocab = base + merges + [b"<|endoftext|>"]
    p = tmp_path / "vocab.bin"
    _write_vocab(p, vocab)

    tok = tdata.Tokenizer().load(str(p))
    assert tok.vocab_size == len(vocab)
    assert tok.decode([256 + 4]) == "hello "
    assert tok.decode_token(10 ** 6) == b"<unk>"

    # save() writes the identical format back
    p2 = tmp_path / "vocab2.bin"
    tok.save(str(p2))
    assert p.read_bytes() == p2.read_bytes()


def test_tokenizer_encode_respects_merge_order(tmp_path):
    base = [bytes([i]) for i in range(256)]
    merges = [b"he", b"ll", b"hell", b"hello"]
    p = tmp_path / "vocab.bin"
    _write_vocab(p, base + merges)
    tok = tdata.Tokenizer().load(str(p))

    ids = tok.encode("hello")
    assert tok.decode(ids) == "hello"
    # lowest-id (earliest merge) pairs first: he+ll -> hell, then hell+o -> hello
    assert ids == [256 + 3]

    # unknown text falls back to raw bytes
    ids = tok.encode("xyz")
    assert ids == [ord("x"), ord("y"), ord("z")]
    assert tok.decode(ids) == "xyz"


def test_tokenizer_unicode_pretokenization(tmp_path):
    base = [bytes([i]) for i in range(256)]
    p = tmp_path / "vocab.bin"
    _write_vocab(p, base)
    tok = tdata.Tokenizer().load(str(p))
    # accented letters stay in one letter-run (GPT-2 \p{L} semantics), so the
    # UTF-8 bytes of " café" come out contiguously and round-trip
    ids = tok.encode(" café!")
    assert tok.decode(ids) == " café!"
    assert ids == list(" café!".encode("utf-8"))


def test_image_folder_npy_resizes_to_image_size(tmp_path):
    d = tmp_path / "class_a"
    d.mkdir()
    np.save(d / "images.npy", np.full((2, 64, 64, 3), 128, np.uint8))
    dl = tdata.ImageFolderDataLoader(str(tmp_path), image_size=(32, 32))
    assert dl.data_shape == (32, 32, 3)


def test_tokenizer_eot(tmp_path):
    base = [bytes([i]) for i in range(256)]
    p = tmp_path / "vocab.bin"
    _write_vocab(p, base + [b"<|endoftext|>"])
    tok = tdata.Tokenizer().load(str(p))
    assert tok.eot_token == 256
    ids = tok.encode("a<|endoftext|>b")
    assert ids == [ord("a"), 256, ord("b")]


def test_masked_label_loss():
    from tnn_tpu.nn import losses
    logits = jnp.asarray(np.random.RandomState(0).randn(4, 8).astype(np.float32))
    labels = jnp.asarray([1, 2, -1, -1], jnp.int32)
    full = losses.softmax_cross_entropy(logits[:2], labels[:2])
    masked = losses.softmax_cross_entropy(logits, labels)
    assert np.allclose(full, masked, atol=1e-6)


def test_masked_label_metrics():
    from tnn_tpu.nn import metrics
    logits = jnp.eye(4, dtype=jnp.float32)  # pred = [0,1,2,3]
    labels = jnp.asarray([0, 1, -1, -1], jnp.int32)
    # ignored positions excluded from numerator AND denominator
    assert float(metrics.accuracy(logits, labels)) == 1.0
    assert int(metrics.class_corrects(logits, labels)) == 2
    assert float(metrics.topk_accuracy(logits, labels, k=2)) == 1.0


def test_synthetic_loader_shuffle_reorders_not_resamples():
    dl = tdata.SyntheticDataLoader(16, (2,), 4, seed=0)
    plain = np.sort(np.concatenate([b[0].ravel() for b in dl.batches(4)]))
    dl.shuffle()
    shuffled = np.sort(np.concatenate([b[0].ravel() for b in dl.batches(4)]))
    assert np.allclose(plain, shuffled)
    dl2 = tdata.SyntheticDataLoader(16, (2,), 4, seed=123)
    assert not np.allclose(dl.data, dl2.data)


def test_factory_image_size_override(tmp_path):
    d = tmp_path / "c0"
    d.mkdir()
    np.save(d / "images.npy", np.zeros((2, 64, 64, 3), np.uint8))
    dl = tdata.create("tiny_imagenet", str(tmp_path), image_size=(32, 32))
    assert dl.data_shape == (32, 32, 3)


def test_token_stream_too_short_clear_error(tmp_path):
    p = tmp_path / "t.bin"
    np.arange(10, dtype=np.uint16).tofile(p)
    dl = tdata.OpenWebTextDataLoader(str(p), context_length=16)
    assert dl.get_batch(1) is None
    with pytest.raises(ValueError, match="too short"):
        dl.random_windows(1)


class TestRegressionCSV:
    def _write_csv(self, path, n=40, f=5, t=2, header=False):
        rs = np.random.default_rng(0)
        X = rs.standard_normal((n, f)).astype(np.float32)
        Y = (X @ rs.standard_normal((f, t))).astype(np.float32)
        rows = np.concatenate([X, Y], 1)
        with open(path, "w") as fh:
            if header:
                fh.write(",".join(f"c{i}" for i in range(f + t)) + "\n")
            for r in rows:
                fh.write(",".join(f"{v:.6f}" for v in r) + "\n")
        return X, Y

    def test_split_and_normalize(self, tmp_path):
        from tnn_tpu.data.datasets import RegressionCSVDataLoader

        p = tmp_path / "wifi.csv"
        X, Y = self._write_csv(str(p))
        dl = RegressionCSVDataLoader(str(p), num_targets=2)
        assert dl.data.shape == (40, 5) and dl.labels.shape == (40, 2)
        # standardized features; targets untouched
        np.testing.assert_allclose(dl.data.mean(0), 0.0, atol=1e-5)
        np.testing.assert_allclose(dl.labels, Y, rtol=1e-5)

    def test_eval_split_uses_train_stats(self, tmp_path):
        from tnn_tpu.data.datasets import RegressionCSVDataLoader

        ptr, pte = tmp_path / "train.csv", tmp_path / "test.csv"
        self._write_csv(str(ptr), n=64)
        Xte, _ = self._write_csv(str(pte), n=16)
        train = RegressionCSVDataLoader(str(ptr), num_targets=2)
        test = RegressionCSVDataLoader(str(pte), num_targets=2, stats=train.stats)
        np.testing.assert_allclose(
            test.data, (Xte - train.stats[0]) / train.stats[1], rtol=1e-5)

    def test_factory_and_header(self, tmp_path):
        from tnn_tpu.data import factory

        p = tmp_path / "r.csv"
        self._write_csv(str(p), header=True)
        dl = factory.create("regression_csv", str(p), num_targets=2)
        assert len(dl) == 40

    def test_trains_with_mse(self, tmp_path):
        """Regression loader end-to-end with a Dense head + MSE (the reference's
        WiFi-localisation use case)."""
        from tnn_tpu import nn
        from tnn_tpu.data.datasets import RegressionCSVDataLoader
        from tnn_tpu.train import create_train_state, make_train_step
        import jax

        p = tmp_path / "r.csv"
        self._write_csv(str(p), n=64)
        dl = RegressionCSVDataLoader(str(p), num_targets=2)
        model = nn.Sequential([nn.Dense(16, activation="relu"), nn.Dense(2)])
        opt = nn.Adam(lr=1e-2)
        state = create_train_state(model, opt, jax.random.PRNGKey(0), (16, 5))
        step = make_train_step(model, opt, loss_fn="mse")
        losses = []
        for _ in range(10):
            for data, labels in dl.batches(16):
                state, m = step(state, data, labels)
            losses.append(float(m["loss"]))
        assert losses[-1] < losses[0]


def test_image_folder_threaded_decode_matches_serial(tmp_path):
    """The decode thread-pool must be a pure speedup: identical batches to the
    serial path (order preserved through pool.map)."""
    rng = np.random.default_rng(0)
    for c in range(2):
        cdir = tmp_path / f"c{c}"
        cdir.mkdir()
        np.save(str(cdir / "images.npy"),
                rng.integers(0, 255, (6, 12, 12, 3), np.uint8))
    serial = tdata.ImageFolderDataLoader(str(tmp_path), image_size=(8, 8),
                                         num_workers=1)
    pooled = tdata.ImageFolderDataLoader(str(tmp_path), image_size=(8, 8),
                                         num_workers=4)
    d1, l1 = serial.get_batch(8)
    d2, l2 = pooled.get_batch(8)
    np.testing.assert_array_equal(d1, d2)
    np.testing.assert_array_equal(l1, l2)


def test_bilinear_resize_quality():
    """Bilinear must actually interpolate (a 2x2 checker upsampled has mid
    values; nearest only has the two extremes) — quality parity with the
    reference's stb resize path."""
    from tnn_tpu.data.datasets import _resize_bilinear, _resize_nearest

    img = np.zeros((1, 2, 2, 1), np.uint8)
    img[0, 0, 0, 0] = img[0, 1, 1, 0] = 255
    up_b = _resize_bilinear(img, (8, 8))
    up_n = _resize_nearest(img, (8, 8))
    assert set(np.unique(up_n)) == {0, 255}
    mids = np.logical_and(up_b > 40, up_b < 215)
    assert mids.sum() > 8, "bilinear produced no interpolated values"
    # identity resize is exact
    same = _resize_bilinear(img, (2, 2))
    np.testing.assert_array_equal(same, img)


class TestTrainBpe:
    """BPE vocabulary TRAINING (the reference outsources this to tiktoken;
    here train -> save -> encode -> decode is fully standalone)."""

    def test_round_trip_and_compression(self):
        from tnn_tpu.data.tokenizer import train_bpe

        corpus = ("the quick brown fox jumps over the lazy dog. " * 50
                  + "pack my box with five dozen liquor jugs. " * 50)
        tok = train_bpe([corpus], vocab_size=400)
        assert 256 < tok.vocab_size <= 400
        ids = tok.encode(corpus)
        assert tok.decode(ids) == corpus            # lossless
        assert len(ids) < len(corpus.encode()) / 2  # merges actually compress

    def test_save_load_and_native_parity(self, tmp_path):
        from tnn_tpu import native
        from tnn_tpu.data.tokenizer import Tokenizer, train_bpe

        text = "hello hello world, worldly words withhold wholly. " * 30
        tok = train_bpe([text], vocab_size=320)
        path = str(tmp_path / "vocab.bin")
        tok.save(path)
        loaded = Tokenizer().load(path)
        assert loaded.vocab_size == tok.vocab_size
        ids = tok.encode(text)
        assert loaded.encode(text) == ids
        assert loaded.decode(ids) == text
        if native.available():  # native engine speaks the same trained vocab
            assert loaded._native is not None
            assert loaded._native.encode(text).tolist() == ids

    def test_eot_token_reserved(self):
        from tnn_tpu.data.tokenizer import train_bpe

        tok = train_bpe(["abc " * 10], vocab_size=300)
        assert tok.eot_token == tok.vocab_size - 1
        ids = tok.encode("abc<|endoftext|>abc")
        assert tok.eot_token in ids
