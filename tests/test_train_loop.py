"""Tests: full training loop (epochs, validation, checkpointing), grad accumulation,
in-step augmentation, evaluate."""
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tnn_tpu import models, nn
from tnn_tpu.data import SyntheticDataLoader, cifar_train_pipeline
from tnn_tpu.train import (
    create_train_state,
    evaluate,
    make_eval_step,
    make_train_step,
    train_model,
)
from tnn_tpu.utils import TrainingConfig


def tiny_config(tmp_path, **kw):
    base = dict(
        epochs=2, batch_size=16, progress_print_interval=2,
        model_name="mnist_cnn", snapshot_dir=str(tmp_path / "snaps"),
        optimizer={"type": "sgd", "lr": 0.05, "momentum": 0.9},
        io_dtype="float32")
    base.update(kw)
    return TrainingConfig().update(base)


class TestTrainModel:
    def test_loss_decreases_and_checkpoints(self, tmp_path):
        cfg = tiny_config(tmp_path)
        model = models.create(cfg.model_name)
        train = SyntheticDataLoader(64, (28, 28, 1), 10, seed=0)
        val = SyntheticDataLoader(32, (28, 28, 1), 10, seed=1)
        state, history = train_model(model, cfg, train, val_loader=val)
        assert len(history) == 2
        assert int(state.step) == 2 * (64 // 16)
        assert history[-1]["val_accuracy"] >= 0
        # per-epoch + best checkpoints exist
        assert os.path.isdir(os.path.join(cfg.snapshot_dir, "best"))
        assert any(d.startswith("step_") for d in os.listdir(cfg.snapshot_dir))

    def test_resume_continues_step_count(self, tmp_path):
        cfg = tiny_config(tmp_path, epochs=1)
        model = models.create(cfg.model_name)
        train = SyntheticDataLoader(64, (28, 28, 1), 10, seed=0)
        state1, _ = train_model(model, cfg, train)

        cfg2 = tiny_config(tmp_path, epochs=1, resume=cfg.snapshot_dir)
        state2, _ = train_model(model, cfg2, train)
        assert int(state2.step) == int(state1.step) + 64 // 16

    def test_mid_epoch_resume_continues_cursor(self, tmp_path):
        # max_steps cuts epoch 0 after 2 of 4 batches; the checkpoint stores the
        # mid-epoch cursor, and a resumed run continues from it without reshuffling.
        cfg = tiny_config(tmp_path, epochs=1, max_steps=2)
        model = models.create(cfg.model_name)
        train = SyntheticDataLoader(64, (28, 28, 1), 10, seed=0)
        train_model(model, cfg, train)
        saved = train.state_dict()
        assert saved["cursor"] == 2 * 16

        train2 = SyntheticDataLoader(64, (28, 28, 1), 10, seed=0)
        cfg2 = tiny_config(tmp_path, epochs=1, resume=cfg.snapshot_dir)
        state2, history2 = train_model(model, cfg2, train2)
        # continued epoch ran only the remaining 2 batches
        assert history2[0]["batches"] == 2
        assert int(state2.step) == 4

    def test_loader_state_reproduces_order_without_storing_it(self):
        a = SyntheticDataLoader(64, (4,), 10, seed=3)
        a.shuffle()
        a.get_batch(8)
        sd = a.state_dict()
        assert "order" not in sd  # permutation is NOT serialized
        b = SyntheticDataLoader(64, (4,), 10, seed=3)
        b._rng.standard_normal(5)  # desync the rng; load must restore it
        b.load_state_dict(sd)
        np.testing.assert_array_equal(a._order, b._order)
        da, la = a.get_batch(8)
        db, lb = b.get_batch(8)
        np.testing.assert_array_equal(da, db)

    def test_max_steps(self, tmp_path):
        cfg = tiny_config(tmp_path, epochs=1, max_steps=2)
        model = models.create(cfg.model_name)
        train = SyntheticDataLoader(64, (28, 28, 1), 10, seed=0)
        state, history = train_model(model, cfg, train)
        assert int(state.step) == 2
        assert history[0]["batches"] == 2

    def test_plateau_scheduler_observes(self, tmp_path):
        cfg = tiny_config(tmp_path, epochs=3,
                          scheduler={"type": "reduce_on_plateau", "patience": 0,
                                     "factor": 0.5})
        model = models.create(cfg.model_name)
        sched = cfg.make_scheduler()
        train = SyntheticDataLoader(32, (28, 28, 1), 10, seed=0)
        val = SyntheticDataLoader(32, (28, 28, 1), 10, seed=1)
        train_model(model, cfg, train, val_loader=val, scheduler=sched)
        # after 3 epochs of noisy val loss the plateau scheduler has state
        assert sched.current_scale() <= 1.0


class TestGradAccum:
    def test_grad_accum_matches_full_batch_linear(self):
        # On a pure-linear model (no BN), accumulating grads over microbatches
        # must equal the full-batch gradient step. f32 policy: in bf16 one big
        # matmul and four small ones round differently.
        from tnn_tpu.core.dtypes import DTypePolicy

        model = nn.Dense(4, activation=None,
                         policy=DTypePolicy(io="float32", param="float32",
                                            compute="float32"))
        opt = nn.SGD(lr=0.1)
        rng = jax.random.PRNGKey(0)
        data = jax.random.normal(rng, (8, 6), jnp.float32)
        labels = jax.random.randint(rng, (8,), 0, 4)

        s1 = create_train_state(model, opt, rng, (8, 6))
        s2 = create_train_state(model, opt, rng, (8, 6))
        step_full = make_train_step(model, opt, donate=False)
        step_accum = make_train_step(model, opt, donate=False, grad_accum=4)
        s1, m1 = step_full(s1, data, labels)
        s2, m2 = step_accum(s2, data, labels)
        np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-5)
        for a, b in zip(jax.tree_util.tree_leaves(s1.params),
                        jax.tree_util.tree_leaves(s2.params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-6)

    def test_steps_per_call_matches_sequential_steps(self):
        """W scanned steps in one dispatch must equal W sequential dispatches
        exactly (same data order, same rng stream consumption)."""
        from tnn_tpu.core.dtypes import DTypePolicy

        model = nn.Dense(4, activation=None,
                         policy=DTypePolicy(io="float32", param="float32",
                                            compute="float32"))
        opt = nn.SGD(lr=0.1)
        rng = jax.random.PRNGKey(0)
        W, B = 3, 4
        data = jax.random.normal(rng, (W, B, 6), jnp.float32)
        labels = jax.random.randint(rng, (W, B), 0, 4)

        s1 = create_train_state(model, opt, rng, (B, 6))
        s2 = create_train_state(model, opt, rng, (B, 6))
        step1 = make_train_step(model, opt, donate=False)
        stepW = make_train_step(model, opt, donate=False, steps_per_call=W)
        losses = []
        for w in range(W):
            s1, m1 = step1(s1, data[w], labels[w])
            losses.append(float(m1["loss"]))
        s2, m2 = stepW(s2, data, labels)
        assert int(s2.step) == W
        np.testing.assert_allclose(np.asarray(m2["loss_trace"]), losses,
                                   rtol=1e-6)
        np.testing.assert_allclose(float(m2["loss"]), np.mean(losses),
                                   rtol=1e-6)
        for a, b in zip(jax.tree_util.tree_leaves(s1.params),
                        jax.tree_util.tree_leaves(s2.params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-6)

    def test_augment_in_step(self):
        model = models.create("cifar10_resnet9")
        opt = nn.SGD(lr=0.01)
        rng = jax.random.PRNGKey(0)
        pipe = cifar_train_pipeline()
        step = make_train_step(model, opt, donate=False, augment=pipe.apply)
        state = create_train_state(model, opt, rng, (4, 32, 32, 3))
        data = jax.random.normal(rng, (4, 32, 32, 3), jnp.float32)
        labels = jax.random.randint(rng, (4,), 0, 10)
        state, m = step(state, data, labels)
        assert np.isfinite(float(m["loss"]))


class TestEvaluate:
    def test_evaluate_aggregates(self):
        model = models.create("mnist_cnn")
        opt = nn.SGD(lr=0.01)
        state = create_train_state(model, opt, jax.random.PRNGKey(0), (16, 28, 28, 1))
        eval_fn = make_eval_step(model)
        loader = SyntheticDataLoader(48, (28, 28, 1), 10, seed=2)
        out = evaluate(eval_fn, state, loader, 16,
                       TrainingConfig(io_dtype="float32"))
        assert 0.0 <= out["accuracy"] <= 1.0
        assert np.isfinite(out["loss"])


class TestMeshTrainModel:
    def test_mesh_axes_dp_matches_single_device(self, tmp_path):
        """config.mesh_axes={"data": 8} trains on the virtual mesh; same data and
        seed must give ~the same losses as the single-device path (true DP with
        gradient all-reduce — not the reference's drifting replicas)."""
        import jax

        from tnn_tpu import nn
        from tnn_tpu.data.loader import SyntheticDataLoader
        from tnn_tpu.train import train_model
        from tnn_tpu.utils.config import TrainingConfig

        def run(mesh_axes, subdir):
            model = nn.Sequential([nn.Flatten(),
                                   nn.Dense(32, activation="relu"), nn.Dense(10)])
            loader = SyntheticDataLoader(128, (8, 8, 3), 10, seed=0)
            cfg = TrainingConfig(epochs=2, batch_size=32, shuffle=False,
                                 snapshot_dir=str(tmp_path / subdir),
                                 optimizer={"type": "sgd", "lr": 0.05},
                                 mesh_axes=mesh_axes)
            _, hist = train_model(model, cfg, loader)
            return [h["train_loss"] for h in hist]

        single = run({}, "s")
        dp = run({"data": 8}, "dp")
        assert len(jax.devices()) >= 8
        np.testing.assert_allclose(dp, single, rtol=2e-2)

    def test_mesh_axes_fsdp_runs(self, tmp_path):
        from tnn_tpu import nn
        from tnn_tpu.data.loader import SyntheticDataLoader
        from tnn_tpu.train import train_model
        from tnn_tpu.utils.config import TrainingConfig

        import jax

        # Dense(512) kernel is 192x512 = 98KB > the 64KB FSDP threshold, so it
        # must come back sharded over "fsdp" (and stay so through the step)
        model = nn.Sequential([nn.Flatten(), nn.Dense(512, activation="relu"),
                               nn.Dense(10)])
        loader = SyntheticDataLoader(64, (8, 8, 3), 10, seed=0)
        cfg = TrainingConfig(epochs=1, batch_size=16,
                             snapshot_dir=str(tmp_path / "f"),
                             mesh_axes={"data": 2, "fsdp": 4})
        state, hist = train_model(model, cfg, loader)
        assert np.isfinite(hist[0]["train_loss"])
        shardings = {str(l.sharding.spec)
                     for l in jax.tree_util.tree_leaves(state.params)}
        assert any("fsdp" in s for s in shardings), shardings

    def test_unsupported_axis_raises(self, tmp_path):
        from tnn_tpu import nn
        from tnn_tpu.data.loader import SyntheticDataLoader
        from tnn_tpu.train import train_model
        from tnn_tpu.utils.config import TrainingConfig

        model = nn.Sequential([nn.Flatten(), nn.Dense(10)])
        loader = SyntheticDataLoader(32, (8, 8, 3), 10)
        cfg = TrainingConfig(epochs=1, batch_size=16,
                             snapshot_dir=str(tmp_path / "x"),
                             mesh_axes={"tensor": 8})  # not a known layout axis
        with pytest.raises(ValueError, match="data/fsdp"):
            train_model(model, cfg, loader)

    @pytest.mark.parametrize("axes,method", [
        ({"data": 2, "seq": 4}, "ring"),
        ({"data": 2, "seq": 4}, "ulysses"),
        ({"data": 2, "model": 2, "seq": 2}, "ring"),  # dp x tp x sp compose
    ])
    def test_config_driven_seq_parallel_gpt(self, tmp_path, axes, method):
        """mesh_axes with a seq axis: the model's attention is retargeted to
        the configured context-parallel scheme and the train step runs
        dp x sp — and dp x tp x sp in ONE step (the reference offers one
        parallelism mode per run) — from config alone, matching the
        single-device loss."""
        import jax
        import jax.numpy as jnp

        from tnn_tpu import models, nn
        from tnn_tpu.data.loader import ArrayDataLoader
        from tnn_tpu.train import (create_train_state, make_train_step,
                                   train_model)
        from tnn_tpu.utils.config import TrainingConfig

        seq, batch = 32, 8
        rng = np.random.default_rng(0)
        tokens = rng.integers(0, 64, (64, seq)).astype(np.int32)
        labels = np.roll(tokens, -1, axis=1).astype(np.int32)

        def fresh():
            return models.GPT2(vocab_size=64, max_len=seq, num_layers=2,
                               d_model=32, num_heads=4, dropout=0.0)

        loader = ArrayDataLoader(tokens, labels, seed=0)
        cfg = TrainingConfig(epochs=1, batch_size=batch, shuffle=False,
                             snapshot_dir=str(tmp_path / "sp"),
                             mesh_axes=axes,
                             seq_parallel_method=method,
                             optimizer={"type": "sgd", "lr": 0.1},
                             progress_print_interval=100)
        state, history = train_model(fresh(), cfg, loader)
        assert np.isfinite(history[0]["train_loss"])

        # single-device reference over the same data/order/steps
        ref_model = fresh()
        opt = nn.SGD(lr=0.1)
        rstate = create_train_state(ref_model, opt, jax.random.PRNGKey(cfg.seed),
                                    (batch, seq))
        step = make_train_step(ref_model, opt, donate=False)
        ref_loader = ArrayDataLoader(tokens, labels, seed=0)
        for data, lab in ref_loader.batches(batch):
            rstate, rm = step(rstate, jnp.asarray(data), jnp.asarray(lab))
        np.testing.assert_allclose(history[0]["train_loss"], float(rm["loss"]),
                                   rtol=2e-2)

    def test_config_driven_pipeline_and_tp(self, tmp_path):
        """mesh_axes={'data':2,'pipe':4} and {'data':4,'model':2} both train
        end-to-end from config alone (parity: the reference's mode-driven
        tcp_coordinator.cpp:27-97 — here one config knob, no runtime fork)."""
        from tnn_tpu import nn
        from tnn_tpu.data.loader import SyntheticDataLoader
        from tnn_tpu.train import train_model
        from tnn_tpu.utils.config import TrainingConfig

        conv = nn.Sequential([
            nn.Conv2D(4, 3, padding="same", use_bias=False), nn.BatchNorm(),
            nn.Activation("relu"), nn.GlobalAvgPool(), nn.Dense(10)])
        loader = SyntheticDataLoader(64, (8, 8, 3), 10)
        cfg = TrainingConfig(epochs=1, batch_size=16, num_microbatches=2,
                             snapshot_dir=str(tmp_path / "pp"),
                             mesh_axes={"data": 2, "pipe": 4},
                             progress_print_interval=2)
        state, history = train_model(conv, cfg, loader)
        assert len(history) == 1 and np.isfinite(history[0]["train_loss"])

        # data x model (Megatron TP) from config — param-name rules shard the
        # transformer kernels; non-matching conv params just replicate, so the
        # same code path runs any model
        cfg2 = TrainingConfig(epochs=1, batch_size=16, max_steps=2,
                              snapshot_dir=str(tmp_path / "tp"),
                              mesh_axes={"data": 4, "model": 2},
                              progress_print_interval=2)
        state2, history2 = train_model(conv, cfg2, loader)
        assert len(history2) == 1 and np.isfinite(history2[0]["train_loss"])


class TestRemat:
    def test_remat_numerically_identical(self):
        """remat=True recomputes the forward in the backward — same losses and
        params as the stored-activation path, bit for bit."""
        import jax
        import jax.numpy as jnp

        from tnn_tpu import nn
        from tnn_tpu.train import create_train_state, make_train_step

        model = nn.Sequential([
            nn.Conv2D(8, 3, padding="same"), nn.BatchNorm(),
            nn.Activation("relu"), nn.GlobalAvgPool(), nn.Dense(10)])
        opt = nn.SGD(lr=0.1, momentum=0.9)
        rs = np.random.RandomState(0)
        x = jnp.asarray(rs.randn(8, 12, 12, 3), jnp.bfloat16)
        y = jnp.asarray(rs.randint(0, 10, 8), jnp.int32)

        states = []
        for remat in (False, True, "dots", "dots_no_batch"):
            st = create_train_state(model, opt, jax.random.PRNGKey(0),
                                    (8, 12, 12, 3))
            step = make_train_step(model, opt, donate=False, remat=remat)
            for _ in range(3):
                st, m = step(st, x, y)
            states.append((st, float(m["loss"])))
        for st, loss in states[1:]:
            assert loss == states[0][1]

        with pytest.raises(ValueError, match="unknown remat policy"):
            make_train_step(model, opt, remat="typo")
        for a, b in zip(jax.tree_util.tree_leaves(states[0][0].params),
                        jax.tree_util.tree_leaves(states[1][0].params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
