"""Arbitrary-DAG graph tests (parity: Graph/GraphBuilder JSON round-trip +
executor, include/nn/graph.hpp:18-191, graph_builder.hpp:51-108; the reference's
graph_test example). Multi-input joins, multi-output heads, config round-trip,
training through the DAG."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tnn_tpu import nn
from tnn_tpu.core.module import module_from_config
from tnn_tpu.nn.graph import Graph


def _branchy_graph():
    """input -> a -> {b1, b2} -> add -> head ; b2 also exported (multi-output)."""
    return Graph(
        nodes=[
            ("a", nn.Dense(16, activation="relu"), ["input"]),
            ("b1", nn.Dense(16, activation="relu"), ["a"]),
            ("b2", nn.Dense(16, activation="tanh"), ["a"]),
            ("join", nn.Add(), ["b1", "b2"]),
            ("head", nn.Dense(4), ["join"]),
        ],
        inputs=["input"],
        outputs=["head", "b2"],
    )


def test_forward_multi_output(rng):
    g = _branchy_graph()
    v = g.init(rng, (8, 8))
    x = jnp.ones((8, 8), jnp.float32)
    (head, b2), _ = g.apply(v, x)
    assert head.shape == (8, 4) and b2.shape == (8, 16)
    assert g.output_shape((8, 8)) == ((8, 4), (8, 16))


def test_multi_input_graph(rng):
    """Two graph inputs fused by concat — beyond nested containers."""
    g = Graph(
        nodes=[
            ("ea", nn.Dense(8), ["xa"]),
            ("eb", nn.Dense(8), ["xb"]),
            ("cat", nn.Concat(axis=-1), ["ea", "eb"]),
            ("head", nn.Dense(3), ["cat"]),
        ],
        inputs=["xa", "xb"],
    )
    v = g.init(rng, (4, 5), (4, 7))
    out, _ = g.apply(v, jnp.ones((4, 5)), jnp.ones((4, 7)))
    assert out.shape == (4, 3)


def test_config_round_trip(rng):
    g = _branchy_graph()
    cfg = g.get_config()
    g2 = module_from_config(cfg)
    assert isinstance(g2, Graph)
    assert [n.name for n in g2._order] == [n.name for n in g._order]
    v = g.init(rng, (2, 8))
    x = jnp.ones((2, 8), jnp.float32)
    (h1, _), _ = g.apply(v, x)
    (h2, _), _ = g2.apply(v, x)  # same params work on the rebuilt graph
    np.testing.assert_allclose(np.asarray(h1), np.asarray(h2))


def test_validation_errors():
    with pytest.raises(ValueError, match="cycle"):
        Graph(nodes=[("a", nn.Add(), ["b"]), ("b", nn.Add(), ["a"])],
              outputs=["b"])
    with pytest.raises(ValueError, match="unknown"):
        Graph(nodes=[("a", nn.Dense(4), ["nope"])])
    with pytest.raises(ValueError, match="duplicate"):
        Graph(nodes=[("a", nn.Dense(4), ["input"]),
                     ("a", nn.Dense(4), ["input"])])


def test_out_of_order_declaration_toposorts(rng):
    """Nodes declared in any order; Kahn fixes execution order."""
    g = Graph(
        nodes=[
            ("head", nn.Dense(2), ["join"]),
            ("join", nn.Add(), ["p", "q"]),
            ("q", nn.Dense(6), ["input"]),
            ("p", nn.Dense(6), ["input"]),
        ],
        outputs=["head"],
    )
    v = g.init(rng, (3, 4))
    out, _ = g.apply(v, jnp.ones((3, 4)))
    assert out.shape == (3, 2)


def test_training_through_graph(rng):
    """jax.grad through the DAG trains it (executor bwd = reverse edges in the
    reference; here autodiff of the traced forward), including BatchNorm state
    flowing back out of graph nodes."""
    from tnn_tpu.train import create_train_state, make_train_step

    g = Graph(
        nodes=[
            ("c1", nn.Conv2D(4, 3, padding="same"), ["input"]),
            ("bn", nn.BatchNorm(), ["c1"]),
            ("act", nn.Activation("relu"), ["bn"]),
            ("skip", nn.Add(), ["act", "c1"]),
            ("pool", nn.GlobalAvgPool(), ["skip"]),
            ("head", nn.Dense(3), ["pool"]),
        ],
        outputs=["head"],
    )
    opt = nn.SGD(lr=0.2, momentum=0.9)
    state = create_train_state(g, opt, rng, (16, 8, 8, 2))
    step = make_train_step(g, opt, donate=False)
    rs = np.random.RandomState(0)
    pat = rs.randn(3, 8, 8, 2)
    y = rs.randint(0, 3, 16)
    x = jnp.asarray(pat[y] + rs.randn(16, 8, 8, 2) * 0.05, jnp.float32)
    yj = jnp.asarray(y, jnp.int32)
    first = None
    for _ in range(25):
        state, m = step(state, x, yj)
        if first is None:
            first = float(m["loss"])
    assert float(m["loss"]) < first * 0.5
    # BN state updated through the graph
    assert float(jnp.abs(state.net_state["bn"]["mean"]).sum()) > 0
