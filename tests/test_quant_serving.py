"""Quantized serving path: int8 paged KV blocks (+ optional int8 weights).

The contract is CLOSENESS, not exactness: quantizing the KV pool changes
logits by rounding error, so int8 runs are gated on top-1 token agreement
against the f32 engine (measured 0.94-1.0 on the fixed-seed tiny model,
gated at 0.8) — while everything *structural* stays exact: the pool's
block bookkeeping, zero-leak drain, COW privacy, and determinism of an
int8 engine against itself. f32 engines must be byte-untouched by this PR;
their exactness matrix lives in test_serving.py / test_overlap.py.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tnn_tpu.ops.pallas.paged_attention import QuantPages
from tnn_tpu.serving import (TERMINAL_STATES, FaultPlan, InferenceEngine,
                             PagedKVPool, RequestState)
from tnn_tpu.serving import kv_pool as kv_pool_lib

KW = dict(num_blocks=32, block_size=4, max_batch_size=4, max_seq_len=32)


@pytest.fixture(scope="module")
def tiny_lm():
    from tnn_tpu.models.gpt2 import GPT2

    model = GPT2(vocab_size=128, max_len=64, num_layers=2, d_model=32,
                 num_heads=2)
    params = model.init(jax.random.PRNGKey(0), (1, 8))["params"]
    return model, params


def _prompts(n=4, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, 128, int(l)).astype(np.int32)
            for l in rng.integers(5, 14, n)]


def _run(model, params, prompts, max_new=8, stagger=0, **kw):
    merged = dict(KW)
    merged.update(kw)
    eng = InferenceEngine(model, params, **merged)
    rids = []
    for i, p in enumerate(prompts):
        rids.append(eng.submit(p, max_new))
        if stagger and i % stagger == stagger - 1:
            eng.step()
    out = eng.run_until_complete()
    return eng, [out[r] for r in rids]


def _agreement(a_runs, b_runs):
    """Fraction of positions where two engines emitted the same token."""
    match = total = 0
    for a, b in zip(a_runs, b_runs):
        assert len(a) == len(b)
        total += len(a)
        match += sum(int(x == y) for x, y in zip(a, b))
    return match / max(total, 1)


def _assert_drained(eng):
    states = {r.rid: r.state for r in eng.requests.values()}
    assert all(s in TERMINAL_STATES for s in states.values()), states
    assert not eng.has_work
    assert eng.pool.num_allocated == 0
    assert eng.pool.num_free + eng.pool.num_evictable == eng.pool.capacity
    eng.check_invariants()


# -- pool: int8 pages + scale sidecar lifecycle -------------------------------


class TestInt8Pool:
    def _pool(self, **kw):
        kw.setdefault("num_layers", 2)
        kw.setdefault("num_kv_heads", 2)
        kw.setdefault("head_dim", 8)
        kw.setdefault("num_blocks", 8)
        kw.setdefault("block_size", 4)
        kw.setdefault("kv_dtype", "int8")
        return PagedKVPool(**kw)

    def test_layout_and_byte_accounting(self):
        pool = self._pool(dtype=jnp.bfloat16)
        assert isinstance(pool.pages_k, QuantPages)
        assert pool.pages_k.data.dtype == jnp.int8
        assert pool.pages_k.scale.dtype == jnp.float32
        assert pool.pages_k.scale.shape == pool.pages_k.data.shape[:-1] + (1,)
        assert pool.page_itemsize == 1
        # K+V across layers, page arrays only: 2 * L * H_kv * Dh * 1 byte
        assert pool.kv_bytes_per_token == 2 * 2 * 2 * 8
        assert pool.kv_scale_bytes_per_token == 2 * 2 * 2 * 4
        # the acceptance ratio: a bf16 pool's pages are EXACTLY 2x int8's
        f32_pool = PagedKVPool(num_layers=2, num_kv_heads=2, head_dim=8,
                               num_blocks=8, block_size=4,
                               dtype=jnp.bfloat16)
        assert f32_pool.kv_bytes_per_token == 2 * pool.kv_bytes_per_token
        assert f32_pool.kv_scale_bytes_per_token == 0

    def test_lifecycle_and_invariants(self):
        """alloc/fork/free/truncate run unchanged on an int8 pool and the
        invariant checker verifies the scale sidecar stays in agreement."""
        pool = self._pool()
        blocks = pool.alloc(3)
        pool.check_invariants([blocks])
        forked = pool.fork(blocks)
        pool.check_invariants([blocks, forked])
        kept = pool.truncate(forked, 1)
        pool.check_invariants([blocks, kept])
        pool.free(kept)
        pool.free(blocks)
        pool.check_invariants([])
        # corrupt the bundle: a scale leaf of the wrong shape must be caught
        pool.pages_k = QuantPages(pool.pages_k.data,
                                  pool.pages_k.scale[..., 0])
        with pytest.raises(ValueError, match="scale"):
            pool.check_invariants([])

    def test_scatter_gather_roundtrip(self):
        """Write-time quantization: prefill + token scatters store int8 and
        gather_kv dequantizes back within quantization error."""
        pool = self._pool()
        rng = np.random.default_rng(0)
        blocks = pool.alloc(2)
        # (L, H, nb*bs, Dh) contiguous prefill cache, the engine's layout
        kv = jnp.asarray(rng.normal(size=(2, 2, 8, 8)), jnp.float32)
        pool.pages_k = kv_pool_lib.scatter_prefill(
            pool.pages_k, jnp.asarray(blocks, jnp.int32), kv)
        assert pool.pages_k.data.dtype == jnp.int8
        table = jnp.asarray([pool.padded_table(blocks, 2)], jnp.int32)
        got = kv_pool_lib.gather_kv(pool.pages_k, pool.pages_v, table)[0]
        assert got.dtype == jnp.float32
        np.testing.assert_allclose(np.asarray(got[:, 0]),
                                   np.asarray(kv), atol=3e-2)
        # out_dtype lands where asked (the engine passes compute_dtype)
        got16 = kv_pool_lib.gather_kv(pool.pages_k, pool.pages_v, table,
                                      out_dtype=jnp.bfloat16)[0]
        assert got16.dtype == jnp.bfloat16
        pool.free(blocks)

    def test_copy_blocks_and_reset_move_both_leaves(self):
        pool = self._pool()
        rng = np.random.default_rng(1)
        rows = jnp.asarray(rng.normal(size=(2, 1, 2, 8)), jnp.float32)
        table = jnp.asarray([[2, 0]], jnp.int32)
        pool.pages_k = kv_pool_lib.scatter_token(
            pool.pages_k, table, jnp.asarray([1], jnp.int32), rows)
        copied = kv_pool_lib.copy_blocks(pool.pages_k, [2], [5])
        np.testing.assert_array_equal(np.asarray(copied.data[:, 5]),
                                      np.asarray(pool.pages_k.data[:, 2]))
        np.testing.assert_array_equal(np.asarray(copied.scale[:, 5]),
                                      np.asarray(pool.pages_k.scale[:, 2]))
        pool.reset_pages()
        assert isinstance(pool.pages_k, QuantPages)
        assert not np.any(np.asarray(pool.pages_k.data))
        assert not np.any(np.asarray(pool.pages_k.scale))


# -- engine: closeness gates, both decode paths -------------------------------


class TestInt8EngineCloseness:
    @pytest.mark.parametrize("path", ["paged", "standard"])
    def test_closeness_vs_f32(self, tiny_lm, path):
        """The quantization quality gate: int8-KV outputs agree with the f32
        engine token-for-token at >= 0.8 (measured 0.94-1.0), drain with
        zero leaks, and report the halved page bytes."""
        model, params = tiny_lm
        prompts = _prompts(4, seed=0)
        f32_eng, f32_out = _run(model, params, prompts, decode_path=path)
        eng, out = _run(model, params, prompts, decode_path=path,
                        kv_dtype="int8")
        assert _agreement(out, f32_out) >= 0.8
        assert eng.stats()["kv_dtype"] == "int8"
        assert eng.stats()["kv_bytes_per_token"] * 2 == \
            f32_eng.stats()["kv_bytes_per_token"]
        assert eng.stats()["kv_scale_bytes_per_token"] > 0
        _assert_drained(eng)

    @pytest.mark.parametrize(
        "path", ["paged", pytest.param("standard", marks=pytest.mark.slow)])
    def test_spec_prefix_overlap_compose(self, tiny_lm, path):
        """spec=ngram + prefix cache + overlapped loop all ride on int8
        blocks; the composed run stays close to its f32 twin and an int8
        engine is deterministic against itself."""
        model, params = tiny_lm
        base = (np.arange(16) * 5 % 128).astype(np.int32)
        prompts = [base[:12], base[:9],
                   np.concatenate([base[:8], base[:4] + 1]).astype(np.int32)]
        kw = dict(decode_path=path, spec="ngram", prefix_cache=True,
                  overlap=True)
        _, f32_out = _run(model, params, prompts, **kw)
        eng, out = _run(model, params, prompts, kv_dtype="int8", **kw)
        _, out2 = _run(model, params, prompts, kv_dtype="int8", **kw)
        assert out == out2, "int8 engine is not deterministic"
        assert _agreement(out, f32_out) >= 0.8
        _assert_drained(eng)

    def test_quant_weights_compose(self, tiny_lm):
        model, params = tiny_lm
        prompts = _prompts(3, seed=2)
        _, f32_out = _run(model, params, prompts, decode_path="paged")
        eng, out = _run(model, params, prompts, decode_path="paged",
                        kv_dtype="int8", quant_weights=True)
        assert _agreement(out, f32_out) >= 0.8
        assert eng.stats()["quant_weights"]
        _assert_drained(eng)

    def test_fused_path_gated_off(self, tiny_lm):
        """The fused kernel assembles a contiguous compute-dtype cache —
        no bandwidth win over int8 pages, so int8 refuses it explicitly
        and "auto" records the fallback reason."""
        model, params = tiny_lm
        with pytest.raises(ValueError, match="int8 pages"):
            InferenceEngine(model, params, **KW, decode_path="fused",
                            kv_dtype="int8")
        # "auto" under int8 still resolves to a working path, fused stays off
        eng = InferenceEngine(model, params, **KW, decode_path="auto",
                              kv_dtype="int8")
        assert eng._fused is None
        assert eng.stats()["kv_dtype"] == "int8"

    def test_cow_at_partial_block_boundary_int8(self, tiny_lm):
        """COW on quantized blocks: a full-cover prefix hit re-quantizes
        only its recomputed last token into a PRIVATE copy, so the twin is
        token-identical to the original (same int8 cache bytes, greedy) and
        the published blocks survive for the next twin."""
        model, params = tiny_lm
        p = np.arange(8, dtype=np.int32)   # exactly 2 full blocks
        eng = InferenceEngine(model, params, **KW, kv_dtype="int8",
                              decode_path="paged")
        r0 = eng.submit(p, 8)
        ref = eng.run_until_complete()[r0]
        assert eng.metrics.prefix_cows == 0
        r1 = eng.submit(p, 8)
        assert eng.run_until_complete()[r1] == ref
        assert eng.metrics.prefix_cows == 1
        r2 = eng.submit(p, 8)
        assert eng.run_until_complete()[r2] == ref
        assert eng.metrics.prefix_cows == 2
        _assert_drained(eng)

    @pytest.mark.slow
    def test_chaos_gate_int8(self, tiny_lm):
        """The fault-tolerance gate on int8 blocks: alloc faults + a NaN
        row never leak a page OR its scale sidecar — every request reaches
        a terminal state, survivors match a fault-free int8 run exactly,
        and check_invariants (which audits the quantized bundle) is clean."""
        model, params = tiny_lm
        prompts = _prompts(8, seed=6)
        kw = dict(num_blocks=16, block_size=4, max_batch_size=4,
                  max_seq_len=32, decode_path="paged", kv_dtype="int8")

        def run(plan=None):
            eng = InferenceEngine(model, params, faults=plan, **kw)
            rids = [eng.submit(p, 8) for p in prompts]
            eng.run_until_complete()
            return eng, rids

        ref_eng, ref_rids = run()
        plan = FaultPlan(seed=9, alloc_fail_prob=0.12, nan_logit_calls=(5,))
        eng, rids = run(plan)
        assert plan.fired["pool.alloc"] >= 1, "chaos never fired — dead test"
        states = [eng.result(r).state for r in rids]
        assert all(s in TERMINAL_STATES for s in states)
        for rid, ref_rid in zip(rids, ref_rids):
            if eng.result(rid).state is RequestState.FINISHED:
                assert list(eng.requests[rid].out_tokens) == \
                    list(ref_eng.requests[ref_rid].out_tokens)
        _assert_drained(eng)

    def test_gauges_and_exposition(self, tiny_lm):
        model, params = tiny_lm
        eng, _ = _run(model, params, _prompts(2, seed=3), kv_dtype="int8")
        fams = {f["name"]: f for f in eng.metrics.prometheus_series()}
        fam = fams["tnn_serve_kv_bytes_per_token"]
        assert fam["type"] == "gauge"
        assert fam["samples"][0][-1] == float(eng.pool.kv_bytes_per_token)
        assert eng.metrics.summary()["kv_bytes_per_token"] == \
            eng.pool.kv_bytes_per_token


# -- acceptance: gpt2_small closeness (slow lane) -----------------------------


@pytest.mark.slow
@pytest.mark.parametrize("path", ["paged", "standard"])
def test_gpt2_small_int8_closeness(path):
    """Closeness at depth: on gpt2_small, every int8-engine token must be
    the f32 teacher-forced argmax or within a near-tie margin of it — the
    same methodology as the f32 acceptance gate, with the margin widened to
    absorb int8 rounding (logit deltas ~1e-2 on this model)."""
    from tnn_tpu.models.zoo import create

    model = create("gpt2_small")
    params = model.init(jax.random.PRNGKey(0), (1, 8))["params"]
    rng = np.random.default_rng(0)
    prompts = rng.integers(0, model.vocab_size, (4, 12)).astype(np.int32)
    max_new = 12

    eng = InferenceEngine(model, params, num_blocks=14, block_size=16,
                          max_batch_size=4, max_seq_len=32,
                          decode_path=path, kv_dtype="int8")
    rids = [eng.submit(p, max_new) for p in prompts]
    out = eng.run_until_complete()
    assert all(len(out[r]) == max_new for r in rids)
    assert eng.pool.num_allocated == 0

    seqs = np.stack([np.concatenate([prompts[i], out[rids[i]]])
                     for i in range(len(rids))])
    caches = model.init_cache(len(rids), seqs.shape[1])
    logits, _ = model.apply_cached(params, jnp.asarray(seqs), caches, 0)
    logits = np.asarray(logits, np.float64)
    plen = prompts.shape[1]
    exact, margins = 0, []
    for i in range(len(rids)):
        for j in range(max_new):
            row = logits[i, plen + j - 1]
            chosen = seqs[i, plen + j]
            if chosen == row.argmax():
                exact += 1
            else:
                margins.append(float(row.max() - row[chosen]))
    total = len(rids) * max_new
    assert exact >= 0.75 * total, f"only {exact}/{total} tokens were argmax"
    assert all(m < 0.25 for m in margins), f"beyond quant noise: {margins}"
