"""Layer numeric tests — the differential-test pattern from the reference
(unit_tests/layer_device_agnosticity_test.cpp, cuda_*_ops_test.cpp): compare framework
output against an independent NumPy reference within the dtype's epsilon."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tnn_tpu import nn
from tnn_tpu.core import dtypes as dt

F32 = dt.FP32


def test_dense_matches_numpy(rng):
    layer = nn.Dense(8, policy=F32)
    v = layer.init(rng, (4, 16))
    x = np.random.RandomState(0).randn(4, 16).astype(np.float32)
    y = layer(v, jnp.asarray(x))
    ref = x @ np.asarray(v["params"]["kernel"]) + np.asarray(v["params"]["bias"])
    np.testing.assert_allclose(np.asarray(y), ref, rtol=1e-5, atol=1e-5)


def test_dense_shapes_and_activation(rng):
    layer = nn.Dense(32, activation="relu", policy=F32)
    v = layer.init(rng, (2, 3, 16))
    x = jnp.asarray(np.random.randn(2, 3, 16), jnp.float32)
    y = layer(v, x)
    assert y.shape == (2, 3, 32)
    assert layer.output_shape((2, 3, 16)) == (2, 3, 32)
    assert (np.asarray(y) >= 0).all()


def test_conv2d_matches_scipy(rng):
    layer = nn.Conv2D(4, kernel_size=3, padding="valid", use_bias=False, policy=F32)
    v = layer.init(rng, (1, 8, 8, 3))
    x = np.random.RandomState(1).randn(1, 8, 8, 3).astype(np.float32)
    y = np.asarray(layer(v, jnp.asarray(x)))
    k = np.asarray(v["params"]["kernel"])  # HWIO
    ref = np.zeros((1, 6, 6, 4), np.float32)
    for oc in range(4):
        for ic in range(3):
            for i in range(6):
                for j in range(6):
                    ref[0, i, j, oc] += np.sum(x[0, i:i + 3, j:j + 3, ic] * k[:, :, ic, oc])
    np.testing.assert_allclose(y, ref, rtol=1e-4, atol=1e-4)
    assert layer.output_shape((1, 8, 8, 3)) == (1, 6, 6, 4)


def test_conv2d_same_stride2(rng):
    layer = nn.Conv2D(8, kernel_size=3, strides=2, padding="same", policy=F32)
    v = layer.init(rng, (2, 32, 32, 3))
    y = layer(v, jnp.zeros((2, 32, 32, 3), jnp.float32))
    assert y.shape == (2, 16, 16, 8)
    assert layer.output_shape((2, 32, 32, 3)) == (2, 16, 16, 8)


def test_maxpool(rng):
    layer = nn.MaxPool2D(2, policy=F32)
    x = jnp.arange(16, dtype=jnp.float32).reshape(1, 4, 4, 1)
    y = layer({"params": {}, "state": {}}, x, train=False, rng=None)
    v = layer.init(rng, (1, 4, 4, 1))
    y = layer(v, x)
    ref = np.array([[[5, 7], [13, 15]]], np.float32).reshape(1, 2, 2, 1)
    np.testing.assert_array_equal(np.asarray(y), ref)


def test_avgpool(rng):
    layer = nn.AvgPool2D(2, policy=F32)
    v = layer.init(rng, (1, 4, 4, 1))
    x = jnp.arange(16, dtype=jnp.float32).reshape(1, 4, 4, 1)
    y = layer(v, x)
    ref = np.array([[2.5, 4.5], [10.5, 12.5]], np.float32).reshape(1, 2, 2, 1)
    np.testing.assert_allclose(np.asarray(y), ref)


def test_batchnorm_train_and_eval(rng):
    layer = nn.BatchNorm(policy=F32)
    v = layer.init(rng, (8, 4))
    x = jnp.asarray(np.random.RandomState(2).randn(8, 4) * 3 + 1, jnp.float32)
    y, new_state = layer.apply(v, x, train=True)
    np.testing.assert_allclose(np.asarray(y).mean(0), 0.0, atol=1e-5)
    np.testing.assert_allclose(np.asarray(y).std(0), 1.0, atol=1e-2)
    # running stats moved toward batch stats
    assert not np.allclose(np.asarray(new_state["mean"]), 0.0)
    # eval mode uses running stats, state unchanged
    y2, st2 = layer.apply({"params": v["params"], "state": new_state}, x, train=False)
    assert st2 is new_state


def test_layernorm(rng):
    layer = nn.LayerNorm(policy=F32)
    v = layer.init(rng, (2, 6))
    x = jnp.asarray(np.random.RandomState(3).randn(2, 6) * 5, jnp.float32)
    y = layer(v, x)
    np.testing.assert_allclose(np.asarray(y).mean(-1), 0.0, atol=1e-5)
    np.testing.assert_allclose(np.asarray(y).std(-1), 1.0, atol=1e-2)


def test_groupnorm(rng):
    layer = nn.GroupNorm(groups=2, policy=F32)
    v = layer.init(rng, (2, 4, 4, 8))
    x = jnp.asarray(np.random.RandomState(4).randn(2, 4, 4, 8), jnp.float32)
    y = layer(v, x)
    assert y.shape == x.shape


def test_dropout(rng):
    layer = nn.Dropout(0.5, policy=F32)
    v = layer.init(rng, (128, 128))
    x = jnp.ones((128, 128), jnp.float32)
    y_eval = layer(v, x, train=False)
    np.testing.assert_array_equal(np.asarray(y_eval), np.asarray(x))
    y_train, _ = layer.apply(v, x, train=True, rng=jax.random.PRNGKey(1))
    frac_zero = float((np.asarray(y_train) == 0).mean())
    assert 0.4 < frac_zero < 0.6
    # inverted dropout preserves expectation
    assert abs(float(np.asarray(y_train).mean()) - 1.0) < 0.05


def test_embedding(rng):
    layer = nn.Embedding(100, 16, policy=F32)
    v = layer.init(rng, (2, 5))
    ids = jnp.asarray([[1, 2, 3, 4, 5], [0, 0, 99, 98, 97]], jnp.int32)
    y = layer(v, ids)
    assert y.shape == (2, 5, 16)
    np.testing.assert_allclose(np.asarray(y[0, 0]), np.asarray(v["params"]["table"][1]))


def test_shape_layers(rng):
    f = nn.Flatten(policy=F32)
    vf = f.init(rng, (2, 3, 4, 5))
    assert f(vf, jnp.zeros((2, 3, 4, 5))).shape == (2, 60)
    t = nn.Transpose((1, 0), policy=F32)
    vt = t.init(rng, (2, 3, 4))
    assert t(vt, jnp.zeros((2, 3, 4))).shape == (2, 4, 3)
    s = nn.Slice(axis=0, start=1, length=2, policy=F32)
    vs = s.init(rng, (2, 5, 4))
    assert s(vs, jnp.zeros((2, 5, 4))).shape == (2, 2, 4)


def test_config_roundtrip(rng):
    """Parity: every layer serializes via get_config/from_config
    (reference Layer JSON round-trip, include/nn/layer.hpp)."""
    from tnn_tpu.core.module import module_from_config

    layers = [
        nn.Dense(8, activation="gelu"),
        nn.Conv2D(4, kernel_size=(3, 5), strides=2, padding="same", groups=1),
        nn.MaxPool2D(2),
        nn.BatchNorm(momentum=0.95),
        nn.LayerNorm(),
        nn.GroupNorm(groups=4),
        nn.Dropout(0.3),
        nn.Embedding(10, 4),
        nn.Flatten(),
        nn.Activation("relu"),
    ]
    for layer in layers:
        cfg = layer.get_config()
        rebuilt = module_from_config(cfg)
        assert rebuilt.get_config() == cfg, f"round-trip mismatch for {layer}"


def test_conv2d_pair_int_padding_config():
    """Regression: (ph, pw) int-pair padding must serialize and round-trip."""
    from tnn_tpu.core.module import module_from_config
    layer = nn.Conv2D(4, 3, padding=(1, 2))
    cfg = layer.get_config()
    rebuilt = module_from_config(cfg)
    assert rebuilt.get_config() == cfg


def test_registry_populated_from_top_level_import():
    """Regression: `import tnn_tpu` alone must register all builtin layer types."""
    import subprocess, sys
    code = ("import tnn_tpu; "
            "m = tnn_tpu.module_from_config({'type': 'dense', 'units': 4}); "
            "print(m.units)")
    out = subprocess.run([sys.executable, "-c", code], capture_output=True, text=True,
                         cwd="/root/repo")
    assert out.returncode == 0, out.stderr
    assert out.stdout.strip() == "4"
