"""Container block tests (parity intent: residual_block_test.cpp, sequential behavior)."""
import jax
import jax.numpy as jnp
import numpy as np

from tnn_tpu import nn
from tnn_tpu.core import dtypes as dt
from tnn_tpu.core.module import module_from_config, param_count

F32 = dt.FP32


def mlp():
    return nn.Sequential([
        nn.Dense(32, activation="relu", policy=F32),
        nn.Dense(16, activation="relu", policy=F32),
        nn.Dense(4, policy=F32),
    ], policy=F32)


def test_sequential_forward(rng):
    model = mlp()
    v = model.init(rng, (2, 8), input_dtype=jnp.float32)
    y = model(v, jnp.ones((2, 8), jnp.float32))
    assert y.shape == (2, 4)
    assert model.output_shape((2, 8)) == (2, 4)


def test_sequential_param_structure(rng):
    model = mlp()
    v = model.init(rng, (2, 8), input_dtype=jnp.float32)
    keys = sorted(v["params"])
    assert keys == ["00_dense", "01_dense", "02_dense"]
    assert param_count(v["params"]) == (8 * 32 + 32) + (32 * 16 + 16) + (16 * 4 + 4)


def test_residual_identity_shortcut(rng):
    block = nn.Residual([nn.Dense(8, policy=F32)], policy=F32)
    v = block.init(rng, (2, 8))
    x = jnp.ones((2, 8), jnp.float32)
    y = block(v, x)
    main = nn.Dense(8, policy=F32)
    ref = x @ v["params"]["00_dense"]["kernel"] + v["params"]["00_dense"]["bias"] + x
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), rtol=1e-5)


def test_residual_projection_shortcut(rng):
    block = nn.Residual(
        [nn.Dense(16, policy=F32), nn.Dense(16, use_bias=False, policy=F32)],
        activation="relu", policy=F32)
    v = block.init(rng, (2, 8))
    y = block(v, jnp.ones((2, 8), jnp.float32))
    assert y.shape == (2, 16)
    assert (np.asarray(y) >= 0).all()


def test_parallel_joins(rng):
    add = nn.Parallel([nn.Dense(8, policy=F32), nn.Dense(8, policy=F32)], join="add", policy=F32)
    v = add.init(rng, (2, 4))
    assert add(v, jnp.ones((2, 4), jnp.float32)).shape == (2, 8)
    cat = nn.Parallel([nn.Dense(8, policy=F32), nn.Dense(4, policy=F32)], join="concat", policy=F32)
    v2 = cat.init(rng, (2, 4))
    assert cat(v2, jnp.ones((2, 4), jnp.float32)).shape == (2, 12)
    assert cat.output_shape((2, 4)) == (2, 12)


def test_nested_blocks_config_roundtrip(rng):
    """Blocks serialize recursively (parity: Graph JSON config round-trip,
    include/nn/graph.hpp:119-183 — how the reference ships pipeline stages)."""
    model = nn.Sequential([
        nn.Conv2D(8, 3, padding="same", policy=F32),
        nn.BatchNorm(policy=F32),
        nn.Activation("relu", policy=F32),
        nn.Residual([nn.Sequential([nn.Conv2D(8, 3, padding="same", policy=F32)], policy=F32)], policy=F32),
        nn.Flatten(policy=F32),
        nn.Dense(10, policy=F32),
    ], policy=F32)
    cfg = model.get_config()
    rebuilt = module_from_config(cfg)
    assert rebuilt.get_config() == cfg
    # rebuilt model initializes and runs identically given the same rng
    v1 = model.init(rng, (2, 8, 8, 3), input_dtype=jnp.float32)
    v2 = rebuilt.init(rng, (2, 8, 8, 3), input_dtype=jnp.float32)
    x = jnp.ones((2, 8, 8, 3), jnp.float32)
    np.testing.assert_allclose(np.asarray(model(v1, x)), np.asarray(rebuilt(v2, x)), rtol=1e-6)


def test_stateful_sequential_updates_bn(rng):
    model = nn.Sequential([nn.Dense(8, policy=F32), nn.BatchNorm(policy=F32)], policy=F32)
    v = model.init(rng, (4, 4), input_dtype=jnp.float32)
    x = jnp.asarray(np.random.RandomState(0).randn(4, 4), jnp.float32)
    _, new_state = model.apply(v, x, train=True)
    assert "01_batchnorm" in new_state


def test_layer_builder_dsl(rng):
    """Parity: LayerBuilder chained shape-inferring DSL (layer_builder.hpp:11-624)."""
    import jax.numpy as jnp
    from tnn_tpu.nn.builder import LayerBuilder

    model = (LayerBuilder((32, 32, 3), policy=F32)
             .conv2d(32, 3, activation="relu")
             .batchnorm()
             .maxpool(2)
             .basic_residual_block(64, strides=2)
             .global_avgpool()
             .dense(10)
             .build(name="builder_cnn"))
    v = model.init(rng, (2, 32, 32, 3), input_dtype=jnp.float32)
    y = model(v, jnp.zeros((2, 32, 32, 3), jnp.float32))
    assert y.shape == (2, 10)


def test_layer_builder_shape_tracking():
    from tnn_tpu.nn.builder import LayerBuilder

    b = LayerBuilder((32, 32, 3), policy=F32).conv2d(16, 3, strides=2).maxpool(2)
    assert b.shape == (8, 8, 16)
    b = b.flatten()
    assert b.shape == (8 * 8 * 16,)


def test_layer_builder_transformer(rng):
    import jax.numpy as jnp
    from tnn_tpu.nn.builder import LayerBuilder

    model = (LayerBuilder((16,), policy=F32)
             .embedding(100, 32)
             .positional_embedding()
             .gpt_block(4)
             .layernorm()
             .dense(100)
             .build())
    v = model.init(rng, (2, 16), input_dtype=jnp.int32)
    y = model(v, jnp.zeros((2, 16), jnp.int32))
    assert y.shape == (2, 16, 100)


def test_layer_builder_llama_block(rng):
    """Builder DSL entry for the Llama-family block (beyond reference)."""
    import jax.numpy as jnp

    from tnn_tpu.nn.builder import LayerBuilder

    model = (LayerBuilder((8, 32), policy=F32)
             .llama_block(4, 64, num_kv_heads=2)
             .llama_block(4, 64, num_kv_heads=2)
             .build(name="builder_llama"))
    v = model.init(rng, (2, 8, 32), input_dtype=jnp.float32)
    y = model(v, jnp.zeros((2, 8, 32), jnp.float32))
    assert y.shape == (2, 8, 32)
