"""ops.softmax_merge: the shared partitioned-attention math, standalone.

The ring-attention and SP-serving tests gate end-to-end behavior; these pin
the algebra itself — associativity against a single-pass reference, the
empty-partition identity, and bf16 tolerance — so a regression points at
the merge, not at whichever caller noticed first.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tnn_tpu.ops import softmax_merge as sm
from tnn_tpu.parallel import mesh as mesh_lib


def _state(logits, v):
    """Single-block partial state from scratch (the kernel's view)."""
    m0 = jnp.full(logits.shape[:-1] + (1,), sm.NEG_INF, jnp.float32)
    l0 = jnp.zeros_like(m0)
    acc0 = jnp.zeros(logits.shape[:-1] + (v.shape[-1],), jnp.float32)
    return sm.block_update(m0, l0, acc0, logits, v)


def _ref(logits, v):
    """One-shot softmax over the full (concatenated) row."""
    p = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("...qk,...kd->...qd", p, v)


def test_merge_matches_single_pass(rng):
    rs = np.random.RandomState(0)
    parts = [(jnp.asarray(rs.randn(2, 3, 4, 8), jnp.float32),
              jnp.asarray(rs.randn(2, 3, 8, 16), jnp.float32))
             for _ in range(3)]
    a, b, c = (_state(lg, v) for lg, v in parts)
    merged = sm.merge(a, sm.merge(b, c))
    full = _ref(jnp.concatenate([lg for lg, _ in parts], axis=-1),
                jnp.concatenate([v for _, v in parts], axis=-2))
    out = sm.finalize(*merged)
    np.testing.assert_allclose(np.asarray(out), np.asarray(full),
                               rtol=1e-5, atol=1e-6)
    # commutative + associative the other way around too
    alt = sm.finalize(*sm.merge(sm.merge(c, a), b))
    np.testing.assert_allclose(np.asarray(alt), np.asarray(out),
                               rtol=1e-5, atol=1e-6)


def test_empty_partition_is_identity(rng):
    rs = np.random.RandomState(1)
    lg = jnp.asarray(rs.randn(1, 2, 4, 8), jnp.float32)
    v = jnp.asarray(rs.randn(1, 2, 8, 16), jnp.float32)
    a = _state(lg, v)
    empty = (jnp.full_like(a[0], sm.NEG_INF), jnp.zeros_like(a[1]),
             jnp.zeros_like(a[2]))
    for pair in (sm.merge(a, empty), sm.merge(empty, a)):
        for got, want in zip(pair, a):
            np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                       rtol=1e-6, atol=0)
    # all partitions empty: output is 0, not NaN (the l == 0 guard)
    zero = sm.finalize(*sm.merge(empty, empty))
    assert np.all(np.asarray(zero) == 0.0)


def test_bf16_values_tolerance(rng):
    """bf16 V flows through block_update (acc accumulates f32); the merged
    result must track the f32 reference inside bf16 resolution."""
    rs = np.random.RandomState(2)
    lg1 = jnp.asarray(rs.randn(1, 2, 4, 8), jnp.float32)
    lg2 = jnp.asarray(rs.randn(1, 2, 4, 8), jnp.float32)
    v1 = jnp.asarray(rs.randn(1, 2, 8, 16), jnp.float32)
    v2 = jnp.asarray(rs.randn(1, 2, 8, 16), jnp.float32)
    out = sm.finalize(*sm.merge(
        _state(lg1, v1.astype(jnp.bfloat16)),
        _state(lg2, v2.astype(jnp.bfloat16))))
    full = _ref(jnp.concatenate([lg1, lg2], axis=-1),
                jnp.concatenate([v1, v2], axis=-2))
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(full), rtol=2e-2, atol=2e-2)


def test_merge_psum_matches_merge(rng):
    """The cross-mesh combine (normalized outs + stats, psum-weighted) must
    agree with the host-side pairwise merge of the same partials — including
    a shard whose every row is empty."""
    if jax.device_count() < 4:
        pytest.skip("needs the 4+ device virtual mesh")
    rs = np.random.RandomState(3)
    sp = 4
    lgs = jnp.asarray(rs.randn(sp, 1, 2, 4, 8), jnp.float32)
    vs = jnp.asarray(rs.randn(sp, 1, 2, 8, 16), jnp.float32)
    # shard 3 sees no keys at all: dead logits -> empty state
    lgs = lgs.at[3].set(sm.NEG_INF)
    mesh = mesh_lib.make_mesh(seq=sp)
    P = jax.sharding.PartitionSpec

    def body(lg, v):
        m, l, acc = _state(lg[0], v[0])  # noqa: E741
        out = sm.finalize(m, l, acc)
        return sm.merge_psum(out, m, l, "seq")[None]

    out = mesh_lib.shard_map_unchecked(
        body, mesh=mesh, in_specs=(P("seq"), P("seq")),
        out_specs=P("seq"))(lgs, vs)
    states = [_state(lgs[i], vs[i]) for i in range(sp)]
    want = states[0]
    for s in states[1:]:
        want = sm.merge(want, s)
    want = sm.finalize(*want)
    for i in range(sp):  # combine is replicated row-wise across shards
        np.testing.assert_allclose(np.asarray(out[i]), np.asarray(want),
                                   rtol=1e-5, atol=1e-6)
