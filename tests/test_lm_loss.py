"""Chunked LM-head loss vs the materialized softmax-CE reference — values and
gradients, ragged vocab (chunk not dividing V), bf16 inputs."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tnn_tpu.nn.lm_loss import lm_head_loss


def ref_loss(hidden, table, labels):
    logits = (hidden.reshape(-1, hidden.shape[-1]).astype(jnp.float32)
              @ table.astype(jnp.float32).T)
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    zl = jnp.take_along_axis(logits, labels.reshape(-1, 1), axis=1)[:, 0]
    return jnp.mean(lse - zl)


@pytest.mark.parametrize("v,chunk", [(1000, 256), (512, 512), (777, 256)])
def test_loss_matches_reference(v, chunk):
    rs = np.random.RandomState(0)
    h = jnp.asarray(rs.randn(4, 8, 64), jnp.float32)
    w = jnp.asarray(rs.randn(v, 64) * 0.1, jnp.float32)
    y = jnp.asarray(rs.randint(0, v, (4, 8)).astype(np.int32))
    got = float(lm_head_loss(h, w, y, chunk))
    want = float(ref_loss(h, w, y))
    assert got == pytest.approx(want, rel=1e-5)


def test_grads_match_reference():
    rs = np.random.RandomState(1)
    h = jnp.asarray(rs.randn(3, 5, 32), jnp.float32)
    w = jnp.asarray(rs.randn(300, 32) * 0.1, jnp.float32)
    y = jnp.asarray(rs.randint(0, 300, (3, 5)).astype(np.int32))
    gh, gw = jax.grad(lambda h, w: lm_head_loss(h, w, y, 128),
                      argnums=(0, 1))(h, w)
    rh, rw = jax.grad(lambda h, w: ref_loss(h, w, y), argnums=(0, 1))(h, w)
    np.testing.assert_allclose(np.asarray(gh), np.asarray(rh),
                               rtol=2e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(gw), np.asarray(rw),
                               rtol=2e-4, atol=1e-6)


def test_bf16_inputs_grad_dtypes():
    rs = np.random.RandomState(2)
    h = jnp.asarray(rs.randn(2, 4, 32), jnp.bfloat16)
    w = jnp.asarray(rs.randn(200, 32) * 0.1, jnp.bfloat16)
    y = jnp.asarray(rs.randint(0, 200, (2, 4)).astype(np.int32))
    loss, (gh, gw) = jax.value_and_grad(
        lambda h, w: lm_head_loss(h, w, y, 128), argnums=(0, 1))(h, w)
    assert gh.dtype == jnp.bfloat16 and gw.dtype == jnp.bfloat16
    want = float(ref_loss(h, w, y))
    assert float(loss) == pytest.approx(want, rel=2e-2)


def test_train_step_fused_head_matches_standard():
    """One GPT-2 train step with lm_head_chunk equals the materialized-logits
    step: same loss, same updated params (f32 policy for exact comparison)."""
    from tnn_tpu import nn
    from tnn_tpu.core.dtypes import DTypePolicy
    from tnn_tpu.models.gpt2 import GPT2
    from tnn_tpu.train import create_train_state, make_train_step

    f32 = DTypePolicy(io="float32", param="float32", compute="float32")
    kw = dict(vocab_size=300, max_len=16, num_layers=2, d_model=64,
              num_heads=2, policy=f32)
    rs = np.random.RandomState(4)
    data = jnp.asarray(rs.randint(0, 300, (2, 8)).astype(np.int32))
    labels = jnp.asarray(rs.randint(0, 300, (2, 8)).astype(np.int32))

    results = []
    for chunk in (None, 128):
        model = GPT2(**kw)
        opt = nn.SGD(lr=0.1)
        state = create_train_state(model, opt, jax.random.PRNGKey(0), (2, 8))
        step = make_train_step(model, opt, compute_accuracy=False,
                               lm_head_chunk=chunk)
        state, m = step(state, data, labels)
        results.append((float(m["loss"]), state.params))
    (l0, p0), (l1, p1) = results
    assert l1 == pytest.approx(l0, rel=1e-5)
    flat0 = jax.tree_util.tree_leaves(p0)
    flat1 = jax.tree_util.tree_leaves(p1)
    for a, b in zip(flat0, flat1):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   rtol=5e-4, atol=1e-6)


def test_jit_and_scan_composable():
    rs = np.random.RandomState(3)
    h = jnp.asarray(rs.randn(2, 4, 32), jnp.float32)
    w = jnp.asarray(rs.randn(200, 32) * 0.1, jnp.float32)
    y = jnp.asarray(rs.randint(0, 200, (2, 4)).astype(np.int32))
    f = jax.jit(lambda h, w: jax.grad(
        lambda h: lm_head_loss(h, w, y, 64))(h))
    g = f(h, w)
    assert g.shape == h.shape and np.isfinite(np.asarray(g)).all()
