"""Sampling strategies: greedy/temperature/top-k/top-p semantics and their
wiring through generate() (the reference's loop is greedy-only,
examples/gpt2_inference.cpp:107-119)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tnn_tpu.models.sampling import make_sampler


def test_greedy_is_argmax():
    logits = jnp.asarray([[0.1, 2.0, -1.0], [3.0, 0.0, 0.0]])
    s = make_sampler(0.0)
    toks = s(logits, jax.random.PRNGKey(0))
    np.testing.assert_array_equal(np.asarray(toks), [1, 0])


def test_top_k_restricts_support():
    rs = np.random.RandomState(0)
    logits = jnp.asarray(rs.randn(1, 50) * 3)
    top3 = set(np.asarray(jnp.argsort(logits[0])[-3:]).tolist())
    s = make_sampler(1.0, top_k=3)
    seen = {int(s(logits, jax.random.PRNGKey(i))[0]) for i in range(64)}
    assert seen <= top3 and len(seen) >= 2


def test_top_k_1_equals_greedy():
    logits = jnp.asarray(np.random.RandomState(1).randn(4, 20))
    s = make_sampler(0.7, top_k=1)
    toks = np.asarray(s(logits, jax.random.PRNGKey(0)))
    np.testing.assert_array_equal(toks, np.asarray(jnp.argmax(logits, -1)))


def test_top_p_nucleus_mass():
    # crafted distribution: probs ~ [0.5, 0.3, 0.1, 0.1]; top_p=0.7 keeps
    # exactly the first two (0.5 < 0.7, 0.8-0.3=0.5 < 0.7, 0.9-0.1=0.8 >= 0.7)
    probs = np.asarray([0.5, 0.3, 0.1, 0.1])
    logits = jnp.asarray(np.log(probs))[None]
    s = make_sampler(1.0, top_p=0.7)
    seen = {int(s(logits, jax.random.PRNGKey(i))[0]) for i in range(128)}
    assert seen == {0, 1}, seen


def test_top_p_always_keeps_best():
    logits = jnp.asarray([[10.0, 0.0, 0.0]])
    s = make_sampler(1.0, top_p=1e-6)
    for i in range(8):
        assert int(s(logits, jax.random.PRNGKey(i))[0]) == 0


def test_generate_with_sampling_runs():
    from tnn_tpu.models.gpt2 import GPT2, generate

    model = GPT2(vocab_size=128, max_len=32, num_layers=1, d_model=64,
                 num_heads=2)
    v = model.init(jax.random.PRNGKey(0), (1, 8))
    prompt = jnp.zeros((1, 4), jnp.int32)
    toks = generate(model, v["params"], prompt, 4, temperature=0.8,
                    top_k=10, top_p=0.9)
    assert toks.shape == (1, 4)
    assert ((np.asarray(toks) >= 0) & (np.asarray(toks) < 128)).all()
    # deterministic given the same rng
    toks2 = generate(model, v["params"], prompt, 4, temperature=0.8,
                     top_k=10, top_p=0.9)
    np.testing.assert_array_equal(np.asarray(toks), np.asarray(toks2))


# -- per-row (ragged) sampling: the serving engine's vectorized kernel --------


def test_ragged_matches_scalar_same_key():
    """Per-row arrays with every row at the same params must reproduce the
    scalar sampler exactly (same key, same categorical draw)."""
    from tnn_tpu.models.sampling import sample_ragged

    rs = np.random.RandomState(2)
    logits = jnp.asarray(rs.randn(4, 50) * 2)
    key = jax.random.PRNGKey(7)
    for t, k, p in [(0.0, 0, 0.0), (1.0, 0, 0.0), (0.8, 5, 0.0),
                    (1.2, 0, 0.6), (0.7, 8, 0.9)]:
        want = np.asarray(make_sampler(t, k, p)(logits, key))
        got = np.asarray(sample_ragged(
            logits, key, jnp.full((4,), t), jnp.full((4,), k, jnp.int32),
            jnp.full((4,), p)))
        np.testing.assert_array_equal(got, want, err_msg=f"t={t} k={k} p={p}")


def test_ragged_mixed_rows():
    """Greedy and stochastic rows coexist: temperature 0 rows are exact
    argmax; top-k rows stay inside their own row's k-support."""
    from tnn_tpu.models.sampling import sample_ragged

    rs = np.random.RandomState(3)
    logits = jnp.asarray(rs.randn(3, 40))
    t = jnp.asarray([0.0, 1.0, 1.0])
    k = jnp.asarray([0, 3, 0], jnp.int32)
    p = jnp.asarray([0.0, 0.0, 0.9])
    top3 = set(np.asarray(jnp.argsort(logits[1])[-3:]).tolist())
    for i in range(32):
        toks = np.asarray(sample_ragged(logits, jax.random.PRNGKey(i),
                                        t, k, p))
        assert toks[0] == int(jnp.argmax(logits[0]))
        assert int(toks[1]) in top3
        assert 0 <= int(toks[2]) < 40


def test_make_sampler_accepts_perrow_arrays():
    logits = jnp.asarray(np.random.RandomState(4).randn(2, 30))
    s = make_sampler(jnp.asarray([0.0, 1.0]), top_k=jnp.asarray([0, 4]))
    toks = np.asarray(s(logits, jax.random.PRNGKey(0)))
    assert toks[0] == int(jnp.argmax(logits[0]))
    top4 = set(np.asarray(jnp.argsort(logits[1])[-4:]).tolist())
    assert int(toks[1]) in top4


def test_ragged_jits_with_traced_params():
    """The engine passes t/k/p as TRACED arrays inside one compiled decode
    step — the kernel must not branch on their values."""
    from tnn_tpu.models.sampling import sample_ragged

    f = jax.jit(sample_ragged)
    logits = jnp.asarray(np.random.RandomState(5).randn(2, 20))
    toks = np.asarray(f(logits, jax.random.PRNGKey(0),
                        jnp.asarray([0.0, 0.9]), jnp.asarray([0, 5]),
                        jnp.asarray([0.0, 0.8])))
    assert toks.shape == (2,)
    assert toks[0] == int(jnp.argmax(logits[0]))


# -- filter_logits: the shared filtering core ---------------------------------


def test_filter_logits_matches_scalar_sampler_draws():
    """softmax(filter_logits(...)) IS the sampler's categorical
    distribution: drawing from it with the scalar path's key must reproduce
    make_sampler draw-for-draw (byte-identical filtered logits)."""
    from tnn_tpu.models.sampling import filter_logits

    rs = np.random.RandomState(6)
    logits = jnp.asarray(rs.randn(4, 50) * 2)
    for t, k, p in [(1.0, 0, 0.0), (0.8, 5, 0.0), (1.2, 0, 0.6),
                    (0.7, 8, 0.9)]:
        for i in range(8):
            key = jax.random.PRNGKey(i)
            want = np.asarray(make_sampler(t, k, p)(logits, key))
            got = np.asarray(jax.random.categorical(
                key, filter_logits(logits, t, k, p), axis=-1))
            np.testing.assert_array_equal(got, want,
                                          err_msg=f"t={t} k={k} p={p}")


def test_filter_logits_keepall_defaults_are_identity():
    """Out-of-range params degrade to keep-all: t<=0 scales by 1, k outside
    [1, V) and p outside (0, 1) filter nothing."""
    from tnn_tpu.models.sampling import filter_logits

    logits = jnp.asarray(np.random.RandomState(7).randn(3, 20), jnp.float32)
    for t, k, p in [(1.0, 0, 0.0), (0.0, 20, 1.0), (-1.0, -3, 2.0),
                    (1.0, 50, 0.0)]:
        np.testing.assert_array_equal(
            np.asarray(filter_logits(logits, t, k, p)), np.asarray(logits))
    # temperature really scales
    np.testing.assert_allclose(
        np.asarray(filter_logits(logits, 2.0, 0, 0.0)),
        np.asarray(logits) / 2.0, rtol=1e-6)


def test_filter_logits_perrow_supports():
    """Per-row params: a top-k row keeps exactly its k best tokens, a
    nucleus row keeps a probability-ordered prefix that includes the best
    token, and a default row is untouched."""
    from tnn_tpu.models.sampling import NEG_INF, filter_logits

    rs = np.random.RandomState(8)
    logits = jnp.asarray(rs.randn(3, 12))
    out = np.asarray(filter_logits(
        logits, jnp.asarray([1.0, 1.0, 1.0]),
        jnp.asarray([3, 0, 0], jnp.int32), jnp.asarray([0.0, 0.7, 0.0])))
    row0 = np.asarray(logits[0])
    kept0 = set(np.flatnonzero(out[0] > float(NEG_INF) / 2).tolist())
    assert kept0 == set(np.argsort(row0)[-3:].tolist())
    row1 = np.asarray(logits[1])
    kept1 = np.flatnonzero(out[1] > float(NEG_INF) / 2)
    dropped1 = np.setdiff1d(np.arange(12), kept1)
    assert int(row1.argmax()) in kept1.tolist()
    assert 1 <= len(kept1) < 12
    assert row1[kept1].min() > row1[dropped1].max()  # a prefix by prob
    np.testing.assert_array_equal(out[2], np.asarray(logits[2], np.float32))
