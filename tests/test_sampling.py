"""Sampling strategies: greedy/temperature/top-k/top-p semantics and their
wiring through generate() (the reference's loop is greedy-only,
examples/gpt2_inference.cpp:107-119)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tnn_tpu.models.sampling import make_sampler


def test_greedy_is_argmax():
    logits = jnp.asarray([[0.1, 2.0, -1.0], [3.0, 0.0, 0.0]])
    s = make_sampler(0.0)
    toks = s(logits, jax.random.PRNGKey(0))
    np.testing.assert_array_equal(np.asarray(toks), [1, 0])


def test_top_k_restricts_support():
    rs = np.random.RandomState(0)
    logits = jnp.asarray(rs.randn(1, 50) * 3)
    top3 = set(np.asarray(jnp.argsort(logits[0])[-3:]).tolist())
    s = make_sampler(1.0, top_k=3)
    seen = {int(s(logits, jax.random.PRNGKey(i))[0]) for i in range(64)}
    assert seen <= top3 and len(seen) >= 2


def test_top_k_1_equals_greedy():
    logits = jnp.asarray(np.random.RandomState(1).randn(4, 20))
    s = make_sampler(0.7, top_k=1)
    toks = np.asarray(s(logits, jax.random.PRNGKey(0)))
    np.testing.assert_array_equal(toks, np.asarray(jnp.argmax(logits, -1)))


def test_top_p_nucleus_mass():
    # crafted distribution: probs ~ [0.5, 0.3, 0.1, 0.1]; top_p=0.7 keeps
    # exactly the first two (0.5 < 0.7, 0.8-0.3=0.5 < 0.7, 0.9-0.1=0.8 >= 0.7)
    probs = np.asarray([0.5, 0.3, 0.1, 0.1])
    logits = jnp.asarray(np.log(probs))[None]
    s = make_sampler(1.0, top_p=0.7)
    seen = {int(s(logits, jax.random.PRNGKey(i))[0]) for i in range(128)}
    assert seen == {0, 1}, seen


def test_top_p_always_keeps_best():
    logits = jnp.asarray([[10.0, 0.0, 0.0]])
    s = make_sampler(1.0, top_p=1e-6)
    for i in range(8):
        assert int(s(logits, jax.random.PRNGKey(i))[0]) == 0


def test_generate_with_sampling_runs():
    from tnn_tpu.models.gpt2 import GPT2, generate

    model = GPT2(vocab_size=128, max_len=32, num_layers=1, d_model=64,
                 num_heads=2)
    v = model.init(jax.random.PRNGKey(0), (1, 8))
    prompt = jnp.zeros((1, 4), jnp.int32)
    toks = generate(model, v["params"], prompt, 4, temperature=0.8,
                    top_k=10, top_p=0.9)
    assert toks.shape == (1, 4)
    assert ((np.asarray(toks) >= 0) & (np.asarray(toks) < 128)).all()
    # deterministic given the same rng
    toks2 = generate(model, v["params"], prompt, 4, temperature=0.8,
                     top_k=10, top_p=0.9)
    np.testing.assert_array_equal(np.asarray(toks), np.asarray(toks2))
