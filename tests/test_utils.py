"""Tests: env loader, TrainingConfig, logger, hardware introspection."""
import json
import os

import pytest

from tnn_tpu.utils import (
    Env,
    TrainingConfig,
    device_info,
    get_logger,
    load_env_file,
    memory_usage_kb,
)


class TestEnv:
    def test_env_file_parsing(self, tmp_path, monkeypatch):
        envf = tmp_path / ".env"
        envf.write_text(
            "# comment\n"
            "EPOCHS=5\n"
            "NAME = hello world  # inline comment\n"
            'QUOTED="keep # this"\n'
            "BAD KEY=skip\n"
            "\n"
            "FLOATY=0.25\n")
        parsed = load_env_file(str(envf), export=False)
        assert parsed == {"EPOCHS": "5", "NAME": "hello world",
                          "QUOTED": "keep # this", "FLOATY": "0.25"}

    def test_inline_comment_after_quoted_value(self, tmp_path):
        envf = tmp_path / ".env"
        envf.write_text('MODEL_PATH="snap/model.tnn" # prod checkpoint\n'
                        "PLAIN='x y' # trailing\n")
        parsed = load_env_file(str(envf), export=False)
        assert parsed == {"MODEL_PATH": "snap/model.tnn", "PLAIN": "x y"}

    def test_env_file_exports(self, tmp_path, monkeypatch):
        envf = tmp_path / ".env"
        envf.write_text("TNN_TEST_EXPORT_KEY=42\n")
        monkeypatch.delenv("TNN_TEST_EXPORT_KEY", raising=False)
        load_env_file(str(envf))
        assert os.environ["TNN_TEST_EXPORT_KEY"] == "42"
        monkeypatch.delenv("TNN_TEST_EXPORT_KEY")

    def test_missing_file_is_empty(self, tmp_path):
        assert load_env_file(str(tmp_path / "nope.env")) == {}

    def test_typed_get(self, monkeypatch):
        monkeypatch.setenv("TNN_T_INT", "7")
        monkeypatch.setenv("TNN_T_BOOL", "true")
        monkeypatch.setenv("TNN_T_BAD", "xyz")
        assert Env.get("TNN_T_INT", 1) == 7
        assert Env.get("TNN_T_BOOL", False) is True
        assert Env.get("TNN_T_BAD", 3) == 3  # unparseable -> default
        assert Env.get("TNN_T_UNSET", "d") == "d"


class TestTrainingConfig:
    def test_defaults_and_env_overlay(self, monkeypatch):
        monkeypatch.setenv("EPOCHS", "3")
        monkeypatch.setenv("BATCH_SIZE", "64")
        monkeypatch.setenv("MODEL_NAME", "mnist_cnn")
        cfg = TrainingConfig().load_from_env()
        assert cfg.epochs == 3 and cfg.batch_size == 64
        assert cfg.model_name == "mnist_cnn"

    def test_json_overlay_and_unknown_key(self, tmp_path):
        p = tmp_path / "cfg.json"
        p.write_text(json.dumps({"epochs": 2, "optimizer": {"type": "adam", "lr": 0.01}}))
        cfg = TrainingConfig().load_from_json(str(p))
        assert cfg.epochs == 2
        opt = cfg.make_optimizer()
        assert opt.opt_name == "adam" and opt.lr == 0.01

        p.write_text(json.dumps({"eppochs": 2}))
        with pytest.raises(KeyError):
            TrainingConfig().load_from_json(str(p))

    def test_factories(self):
        cfg = TrainingConfig(optimizer={"type": "sgd", "lr": 0.1, "momentum": 0.9},
                             scheduler={"type": "cosine", "t_max": 100})
        assert cfg.make_optimizer().momentum == 0.9
        assert cfg.make_scheduler().sched_name == "cosine"
        assert cfg.make_scheduler().get_config()["t_max"] == 100
        assert TrainingConfig().make_scheduler().sched_name == "noop"

    def test_round_trip(self):
        cfg = TrainingConfig(epochs=7)
        cfg2 = TrainingConfig().update(json.loads(cfg.to_json()))
        assert cfg2.epochs == 7


class TestLoggerHardware:
    def test_logger_file_sink(self, tmp_path):
        log = get_logger("tnn.test_sink", log_file=str(tmp_path / "x.log"))
        log.info("hello %d", 42)
        text = (tmp_path / "x.log").read_text()
        assert "hello 42" in text

    def test_cached_logger_picks_up_new_file_sink(self, tmp_path):
        log = get_logger("tnn.test_sink_pickup")
        late = tmp_path / "late.log"
        log2 = get_logger("tnn.test_sink_pickup", log_file=str(late))
        assert log2 is log
        log2.info("hello late sink")
        assert "hello late sink" in late.read_text()
        # requesting the same file again must not duplicate the handler
        get_logger("tnn.test_sink_pickup", log_file=str(late))
        log2.info("once")
        assert late.read_text().count("once") == 1

    def test_memory_and_devices(self):
        assert memory_usage_kb() > 0
        info = device_info()
        assert info and "platform" in info[0]


class TestAffinity:
    """Parity: ThreadAffinity (utils/thread_affinity.hpp:22-158) + deep
    HardwareInfo topology (hardware_info.hpp:13-168)."""

    def test_cpu_sets_and_core_types(self):
        from tnn_tpu.utils import affinity

        cpus = affinity.available_cpus()
        assert cpus and all(isinstance(c, int) for c in cpus)
        types = affinity.core_types()
        assert set(types) == set(cpus)
        assert set(types.values()) <= {"P", "E"}
        io = affinity.io_cpu_set()
        assert set(io) <= set(cpus) and io

    def test_parse_cpu_list(self):
        from tnn_tpu.utils import affinity

        assert affinity.parse_cpu_list("0-3,8,10-11") == [0, 1, 2, 3, 8, 10, 11]
        assert affinity.parse_cpu_list("2") == [2]

    def test_pin_current_thread_roundtrip(self):
        import os

        from tnn_tpu.utils import affinity

        before = affinity.available_cpus()
        assert affinity.pin_current_thread(before)  # pin to the full set: no-op
        assert sorted(os.sched_getaffinity(0)) == before

    def test_env_override_and_opt_in(self, monkeypatch):
        from tnn_tpu.utils import affinity

        monkeypatch.setenv("TNN_IO_CPUS", "0")
        assert affinity.io_cpu_set() == [0]
        monkeypatch.delenv("TNN_PIN_IO", raising=False)
        assert affinity.pin_io_thread() is False  # off unless TNN_PIN_IO=1

    def test_cpu_topology_report(self):
        from tnn_tpu.utils.hardware import cpu_topology

        topo = cpu_topology()
        assert topo["logical_cores"] >= 1
        assert topo["p_cores"] + topo["e_cores"] == len(
            __import__("tnn_tpu.utils.affinity", fromlist=["x"]).available_cpus())
        assert topo.get("mem_total_kb", 1) > 0
