"""Sequence-parallel serving: sp=2 must be TOKEN-EXACT against sp=1.

SP shards each request's KV blocks position-wise over a context mesh:
every shard sweeps its own pages with the ragged paged kernel and the
per-shard partials merge through one online-softmax psum per layer
(ops/softmax_merge.py). The merge itself is exact to float tolerance, so
— exactly like the TP lane — the gate here is byte-exactness of sampled
token streams on fixed seeds: every composition that works at sp=1 (both
decode paths, spec decode, prefix cache, the overlapped loop, int8 KV)
must emit identical tokens at sp=2, through preemption and a mid-run
supervisor crash. The headline capability gate is the long-context one:
a prompt whose KV exceeds a single chip's pool must SERVE at sp=2 and
fail cleanly at sp=1.

Runs on the conftest's 8-device virtual CPU platform; the ``sp`` fixture
skips on real single-chip hosts.
"""
import os
import subprocess
import sys

import numpy as np
import pytest

import jax

from tnn_tpu.serving import (TERMINAL_STATES, EngineSupervisor, FaultPlan,
                             InferenceEngine, PagedKVPool, PoolExhausted,
                             RequestState, compile_cache)

pytestmark = pytest.mark.sp

KW = dict(num_blocks=32, block_size=4, max_batch_size=4, max_seq_len=32)


@pytest.fixture(scope="module")
def tiny_lm():
    from tnn_tpu.models.gpt2 import GPT2

    model = GPT2(vocab_size=128, max_len=64, num_layers=2, d_model=32,
                 num_heads=2)
    params = model.init(jax.random.PRNGKey(0), (1, 8))["params"]
    return model, params


def _prompts(n=4, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, 128, int(l)).astype(np.int32)
            for l in rng.integers(5, 14, n)]


def _greedy_ref(model, params, prompt, max_new, max_len):
    from tnn_tpu.models.gpt2 import generate

    return np.asarray(generate(model, params, prompt[None], max_new,
                               max_len=max_len))[0].tolist()


def _run(model, params, prompts, max_new=8, stagger=0, **kw):
    merged = dict(KW)
    merged.update(kw)
    eng = InferenceEngine(model, params, **merged)
    rids = []
    for i, p in enumerate(prompts):
        rids.append(eng.submit(p, max_new))
        if stagger and i % stagger == stagger - 1:
            eng.step()
    out = eng.run_until_complete()
    return eng, [out[r] for r in rids]


def _assert_drained(eng):
    states = {r.rid: r.state for r in eng.requests.values()}
    assert all(s in TERMINAL_STATES for s in states.values()), states
    assert not eng.has_work
    assert eng.pool.num_allocated == 0
    assert eng.pool.num_free + eng.pool.num_evictable == eng.pool.capacity
    eng.check_invariants()


def _shard_devices(eng):
    """The distinct devices actually holding the engine's KV pages."""
    pages = eng.pool.pages_k
    data = pages.data if hasattr(pages, "data") else pages
    return {d for d in data.sharding.device_set}


# -- fail-fast validation -----------------------------------------------------


class TestSPValidation:
    def test_rejects_sp_over_device_count(self, tiny_lm, sp):
        model, params = tiny_lm
        toomany = jax.device_count() + 1
        with pytest.raises(ValueError, match="device"):
            InferenceEngine(model, params, sp=toomany, **KW)

    def test_rejects_sp_with_tp(self, tiny_lm, sp):
        model, params = tiny_lm
        with pytest.raises(ValueError, match="ONE of sp / tp"):
            InferenceEngine(model, params, sp=sp, tp=2, **KW)

    def test_rejects_sp_with_host_tier(self, tiny_lm, sp):
        model, params = tiny_lm
        with pytest.raises(ValueError, match="host"):
            InferenceEngine(model, params, sp=sp, host_tier_bytes=1 << 20,
                            **KW)

    def test_rejects_quant_weights(self, tiny_lm, sp):
        model, params = tiny_lm
        with pytest.raises(ValueError, match="quant"):
            InferenceEngine(model, params, sp=sp, quant_weights=True, **KW)

    def test_rejects_indivisible_num_blocks(self, tiny_lm, sp):
        model, params = tiny_lm
        kw = dict(KW)
        kw["num_blocks"] = 33
        with pytest.raises(ValueError, match="divide"):
            InferenceEngine(model, params, sp=sp, **kw)

    def test_rejects_indivisible_assembly_width(self, tiny_lm, sp):
        """blocks_per_seq %% sp is a pre-flight: an sp=2 engine whose
        max_seq_len rounds to an odd block count dies with a pointed
        message, not a shard_map shape error mid-request."""
        model, params = tiny_lm
        kw = dict(KW)
        kw["max_seq_len"] = 12     # ceil(12 / 4) = 3 blocks, 3 % 2 != 0
        with pytest.raises(ValueError, match="blocks_per_seq"):
            InferenceEngine(model, params, sp=sp, **kw)

    def test_fused_decode_gated_off(self, tiny_lm, sp):
        """Explicit fused selection errors (like TP); auto falls back."""
        model, params = tiny_lm
        with pytest.raises(ValueError, match="fused"):
            InferenceEngine(model, params, sp=sp, decode_path="fused", **KW)
        eng = InferenceEngine(model, params, sp=sp, decode_path="standard",
                              **KW)
        assert eng._fused is None

    def test_cli_preflight_rejects_sp_with_tp(self, sp, capsys):
        """tnn-serve dies with a pointed one-liner BEFORE touching model
        weights, not a shard_map traceback out of engine construction."""
        from tnn_tpu.cli import serve as serve_cli
        with pytest.raises(SystemExit):
            serve_cli.main(["--sp", str(sp), "--tp", "2"])
        assert "pick ONE of --sp / --tp" in capsys.readouterr().err

    def test_cli_preflight_rejects_sp_with_host_tier(self, sp, capsys):
        from tnn_tpu.cli import serve as serve_cli
        with pytest.raises(SystemExit):
            serve_cli.main(["--sp", str(sp), "--host-tier-bytes", "1048576"])
        err = capsys.readouterr().err
        assert "--host-tier-bytes is incompatible with --sp" in err

    def test_cli_preflight_rejects_indivisible_blocks(self, sp, capsys):
        from tnn_tpu.cli import serve as serve_cli
        with pytest.raises(SystemExit):
            serve_cli.main(["--sp", "3", "--num-blocks", "64"])
        assert "does not divide" in capsys.readouterr().err


# -- pool: round-robin placement and bottleneck capacity ----------------------


class TestSPPool:
    def _pool(self, sp=2, num_blocks=16):
        return PagedKVPool(num_blocks=num_blocks, block_size=4,
                           num_layers=1, num_kv_heads=1, head_dim=4, sp=sp)

    def test_round_robin_ownership(self):
        """Table position j allocates from shard j %% sp, and ownership is
        derivable from the block ID range alone (what shard_tables uses)."""
        pool = self._pool()
        blocks = pool.alloc(6)
        for j, g in enumerate(blocks):
            assert pool.owner(g) == j % 2
        pool.free(blocks)

    def test_num_allocatable_is_bottleneck(self):
        """Aggregate capacity is gated by the SCARCEST shard: admission
        (scheduler budgets consult num_allocatable) must not plan blocks a
        round-robin alloc cannot actually place."""
        pool = self._pool()
        assert pool.capacity == 14              # 16 - one scratch per shard
        held = pool.alloc(4, start=0)           # balanced: 2 + 2
        assert pool.num_allocatable == 10
        skew = [pool.alloc(1, start=0)[0] for _ in range(3)]  # shard 0 only
        assert all(pool.owner(g) == 0 for g in skew)
        # shard 0 has 2 free, shard 1 has 5 -> bottleneck caps at 2 * 2
        assert pool.num_allocatable == 4
        pool.free(held + skew)
        assert pool.num_allocatable == pool.capacity

    def test_exhaustion_names_the_shard(self):
        pool = self._pool(num_blocks=4)         # 1 usable block per shard
        pool.alloc(1, start=0)
        with pytest.raises(PoolExhausted, match="shard"):
            pool.alloc(1, start=0)              # shard 0 is out; shard 1 free

    def test_shard_tables_by_id_range(self):
        from tnn_tpu.serving.step_build import shard_tables

        tables = np.array([[0, 9, 3, 12]], np.int32)    # blocks_per_shard=8
        out = shard_tables(tables, 2, 8)
        assert out.shape == (2, 1, 4)
        np.testing.assert_array_equal(out[0, 0], [0, -1, 3, -1])
        np.testing.assert_array_equal(out[1, 0], [-1, 1, -1, 4])


# -- exactness: sp=2 == sp=1 == offline reference -----------------------------


class TestSPExactness:
    @pytest.mark.parametrize("path", ["paged", "standard"])
    def test_staggered_parity_both_paths(self, tiny_lm, sp, path):
        """Staggered admission (ragged offsets) on both decode paths:
        sp=2 streams must equal sp=1 streams AND the offline greedy
        reference, token for token."""
        model, params = tiny_lm
        prompts = _prompts(4, seed=7)
        kw = dict(decode_path=path, stagger=2)
        eng1, base = _run(model, params, prompts, **kw)
        eng2, sharded = _run(model, params, prompts, sp=sp, **kw)
        assert sharded == base
        for toks, p in zip(sharded, prompts):
            assert toks == _greedy_ref(model, params, p, 8,
                                       eng2.assembly_len)
        assert eng2.stats()["sp_degree"] == sp
        assert len(_shard_devices(eng2)) == sp
        _assert_drained(eng2)

    def test_full_composition_exact(self, tiny_lm, sp):
        """The whole stack at once — int8 KV + ngram spec decode + prefix
        cache + overlapped loop on the paged path — must match the same
        composition at sp=1 exactly (int8 rounding happens at the scatter,
        before sharding, so even the closeness-gated lane is parity)."""
        model, params = tiny_lm
        prompts = _prompts(4, seed=7) + _prompts(2, seed=7)[:1]  # a repeat
        kw = dict(decode_path="paged", kv_dtype="int8", spec="ngram",
                  prefix_cache=True, overlap=True)
        eng1, base = _run(model, params, prompts, **kw)
        eng2, sharded = _run(model, params, prompts, sp=sp, **kw)
        assert sharded == base
        assert eng2.stats()["kv_dtype"] == "int8"
        _assert_drained(eng2)

    def test_preemption_parity(self, tiny_lm, sp):
        """A starved pool preempts identically under SP: recompute-requeue
        of a sequence-sharded request produces byte-identical output and
        no shard leaks a block."""
        model, params = tiny_lm
        prompts = _prompts(4, seed=1)
        kw = dict(num_blocks=10, decode_path="paged")
        eng1, base = _run(model, params, prompts, max_new=10, **kw)
        eng2, sharded = _run(model, params, prompts, max_new=10, sp=sp, **kw)
        assert eng2.metrics.preemptions > 0, "pool was never exhausted"
        assert sharded == base
        _assert_drained(eng2)

    def test_sampled_rows_deterministic(self, tiny_lm, sp):
        """Stochastic sampling inside the shard_map body: same seed, same
        tokens as sp=1 (the PRNG key replicates and the merged logits
        agree on this model)."""
        model, params = tiny_lm
        p = np.arange(5, dtype=np.int32)

        def run(**kw):
            eng = InferenceEngine(model, params, seed=3, **KW, **kw)
            g = eng.submit(p, 8)
            s = eng.submit(p, 8, temperature=0.9, top_k=16, top_p=0.9)
            out = eng.run_until_complete()
            return eng, out[g], out[s]

        eng1, g1, s1 = run()
        eng2, g2, s2 = run(sp=sp)
        assert g2 == g1 == _greedy_ref(model, params, p, 8,
                                       eng2.assembly_len)
        assert s2 == s1
        assert all(0 <= t < model.vocab_size for t in s2)


# -- the capability gate: context beyond one chip's pool ----------------------


class TestSPLongContext:
    def test_long_prompt_needs_the_context_mesh(self, tiny_lm, sp):
        """THE reason sp exists: a prompt whose KV exceeds a single chip's
        pool serves at sp=2 (aggregate pool ~ N x) and fails cleanly — a
        pointed admission error, not an OOM or a hang — at sp=1 on the
        same per-chip footprint."""
        model, params = tiny_lm
        long_p = (np.arange(40, dtype=np.int32) * 7 + 3) % 128
        per_chip = dict(num_blocks=8, block_size=4, max_batch_size=2,
                        max_seq_len=64)
        eng1 = InferenceEngine(model, params, **per_chip)
        with pytest.raises(ValueError, match="exceeds"):
            eng1.submit(long_p, 4)
        # same 8-block per-chip footprint, sp=2 -> 16 blocks aggregate
        both = dict(per_chip)
        both["num_blocks"] = 16
        eng2 = InferenceEngine(model, params, sp=sp, **both)
        assert eng2.pool.blocks_per_shard == 8
        r = eng2.submit(long_p, 4)
        out = eng2.run_until_complete()[r]
        assert out == _greedy_ref(model, params, long_p, 4,
                                  eng2.assembly_len)
        _assert_drained(eng2)


# -- failure handling ---------------------------------------------------------


class TestSPFailures:
    def test_supervisor_crash_restart_exact(self, tiny_lm, sp):
        """A mid-run engine crash under SP: the supervisor's restart resets
        the pool — the reset must purge EVERY context-mesh shard's pages —
        and the migrated requests finish token-exact."""
        model, params = tiny_lm
        plan = FaultPlan(step_crash_calls=(2,))
        eng = InferenceEngine(model, params, sp=sp, faults=plan,
                              decode_path="paged", num_blocks=32,
                              block_size=4, max_batch_size=2, max_seq_len=32)
        events = []
        sup = EngineSupervisor(eng, event_sink=events.append,
                               restart_backoff_s=0.0, max_restarts=2)
        prompts = _prompts(4, seed=9)
        refs = [_greedy_ref(model, params, p, 5, eng.assembly_len)
                for p in prompts]
        rids = [sup.submit(p, 5) for p in prompts]
        sup.run_sync()
        assert sup.restarts == 1
        term = {e["id"]: e for e in events if e["event"] != "token"}
        assert sorted(term) == sorted(rids)
        for rid, ref in zip(rids, refs):
            assert term[rid]["event"] == "done"
            assert term[rid]["tokens"] == ref
        # the reset pool is still block-sharded across all sp devices
        assert len(_shard_devices(eng)) == sp
        _assert_drained(eng)

    def test_chunk_alloc_failure_zero_leaks_per_shard(self, tiny_lm, sp):
        """Injected alloc faults at chunk boundaries and mid-decode: every
        failure path must return a sequence-sharded request's blocks to
        their owning shards — zero leaks on ANY shard, survivors match a
        fault-free run."""
        model, params = tiny_lm
        prompts = _prompts(6, seed=6)
        kw = dict(num_blocks=16, block_size=4, max_batch_size=4,
                  max_seq_len=32, decode_path="paged", sp=sp)

        def run(plan=None):
            eng = InferenceEngine(model, params, faults=plan, **kw)
            rids = [eng.submit(p, 8) for p in prompts]
            eng.run_until_complete()
            return eng, rids

        ref_eng, ref_rids = run()
        plan = FaultPlan(seed=9, alloc_fail_prob=0.12)
        eng, rids = run(plan)
        assert plan.fired["pool.alloc"] >= 1, "chaos never fired — dead test"
        assert all(eng.result(r).state in TERMINAL_STATES for r in rids)
        for rid, ref_rid in zip(rids, ref_rids):
            if eng.result(rid).state is RequestState.FINISHED:
                assert list(eng.requests[rid].out_tokens) == \
                    list(ref_eng.requests[ref_rid].out_tokens)
        # zero leaks per shard, not just in aggregate
        for s in range(sp):
            assert eng.pool._shard_avail(s) == eng.pool.blocks_per_shard - 1
        _assert_drained(eng)


# -- persistent compilation cache ---------------------------------------------


_CC_CHILD = r"""
import os, sys
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8"
                           + " --xla_backend_optimization_level=0")
import numpy as np, jax
from tnn_tpu.serving import InferenceEngine, compile_cache
from tnn_tpu.models.gpt2 import GPT2

cache = compile_cache.enable(sys.argv[1])
before = compile_cache.entry_count(cache)
model = GPT2(vocab_size=128, max_len=64, num_layers=2, d_model=32,
             num_heads=2)
params = model.init(jax.random.PRNGKey(0), (1, 8))["params"]
eng = InferenceEngine(model, params, num_blocks=16, block_size=4,
                      max_batch_size=2, max_seq_len=32)
r = eng.submit(np.arange(7, dtype=np.int32), 6)
out = eng.run_until_complete()[r]
print("CC", before, compile_cache.entry_count(cache), out)
"""


class TestCompileCache:
    def test_enable_mechanics(self, tmp_path):
        """enable() must defeat JAX's once-only cache initialization (any
        compile before it would otherwise pin the cache off for the whole
        process) and entry_count() must read warmth without jax internals."""
        d = str(tmp_path / "cc")
        assert compile_cache.entry_count(d) == 0    # missing dir == empty
        try:
            cache = compile_cache.enable(d)
            assert compile_cache.active_dir() == cache
            salt = np.float32(os.getpid() % 97)     # a never-seen program
            jax.jit(lambda x: x * salt + 41.5)(
                np.arange(8, dtype=np.float32))
            assert compile_cache.entry_count(cache) > 0
        finally:
            compile_cache.disable()
        assert compile_cache.active_dir() is None

    def test_compile_cache_warm_restart_token_exact(self, tmp_path):
        """The serving story: a process restart against the same cache dir
        re-serves from persisted executables — the warm build adds ZERO new
        entries and emits the exact same tokens. (Two subprocesses because
        that IS the deployment shape — restart / scale-up — and JAX's
        in-process executable reload is not exercised by a live engine.)"""
        d = str(tmp_path / "cc")
        env = dict(os.environ, PYTHONPATH=os.getcwd())

        def launch():
            out = subprocess.run(
                [sys.executable, "-c", _CC_CHILD, d], env=env,
                capture_output=True, text=True, timeout=600)
            assert out.returncode == 0, out.stderr[-2000:]
            line = [l for l in out.stdout.splitlines()
                    if l.startswith("CC ")][-1]
            before, after, toks = line[3:].split(" ", 2)
            return int(before), int(after), toks

        b1, a1, toks1 = launch()
        assert b1 == 0 and a1 > 0, "cold run persisted nothing"
        b2, a2, toks2 = launch()
        assert b2 == a1, "warm run did not see the cold run's entries"
        assert a2 == a1, f"warm run recompiled: {a1} -> {a2} entries"
        assert toks2 == toks1


# -- observability ------------------------------------------------------------


class TestSPObservability:
    def test_gauges_and_exposition(self, tiny_lm, sp):
        model, params = tiny_lm
        eng, _ = _run(model, params, _prompts(2, seed=3), sp=sp,
                      decode_path="paged")
        s = eng.stats()
        assert s["sp_degree"] == sp
        assert s["pool_blocks_per_shard"] == eng.pool.blocks_per_shard
        assert eng.pool.blocks_per_shard * sp == KW["num_blocks"]
        fams = {f["name"]: f for f in eng.metrics.prometheus_series()}
        fam = fams["tnn_serve_sp_degree"]
        assert fam["type"] == "gauge"
        assert fam["samples"][0][-1] == float(sp)
        assert eng.metrics.summary()["sp_degree"] == sp

    def test_spmerge_span_traced(self, tiny_lm, sp):
        """With tracing on, SP dispatch wraps the step in a serve.spmerge
        span carrying the degree and the per-step merge count (one
        online-softmax psum per layer)."""
        from tnn_tpu.profiling.profiler import Profiler

        model, params = tiny_lm
        prof = Profiler(source="sp-test")
        eng, _ = _run(model, params, _prompts(2, seed=8), sp=sp,
                      profiler=prof, trace=True)
        spans = [e for e in prof.events
                 if e.name.startswith("serve.spmerge")]
        assert spans, "no serve.spmerge span recorded"
        assert f"sp={sp}" in spans[0].name
        assert f"count={model.num_layers}" in spans[0].name
