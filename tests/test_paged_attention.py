"""Parity tests for the ragged paged-attention decode kernel.

The Pallas kernel (``ops/pallas/paged_attention``) runs in interpret mode on
CPU (forced by the ``kernel`` marker's conftest fixture), checked against the
XLA-lax reference in the same module; the reference itself is checked against
a dense softmax-attention oracle built here. Covers ragged lengths, block
sizes, GQA head ratios, layer selection, zero-length rows, and the
``scatter_kv_rows`` write half of the page contract.
"""
import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tnn_tpu.ops.pallas import paged_attention as pa

pytestmark = pytest.mark.kernel


def _random_case(seed, *, num_layers=2, num_blocks=12, block_size=8,
                 num_heads=4, num_kv_heads=2, head_dim=16, batch=3,
                 blocks_per_row=3, dtype=jnp.float32):
    """Random pool pages + block tables with ragged per-row lengths.

    Block 0 plays the pool's reserved-scratch role: live tables draw from
    blocks 1.., and rows' table tails are padded with 0 like the engine does.
    """
    rng = np.random.default_rng(seed)
    shape = (num_layers, num_blocks, num_kv_heads, block_size, head_dim)
    pages_k = jnp.asarray(rng.normal(size=shape), dtype)
    pages_v = jnp.asarray(rng.normal(size=shape), dtype)
    need = batch * blocks_per_row
    assert need <= num_blocks - 1, "test geometry: not enough live blocks"
    perm = rng.permutation(np.arange(1, num_blocks))[:need]
    tables = perm.reshape(batch, blocks_per_row).astype(np.int32)
    # ragged: one short row, one full row, one mid row ending mid-block
    lens = rng.integers(1, blocks_per_row * block_size + 1, size=batch)
    lens[0] = 1
    lens[-1] = blocks_per_row * block_size
    # dead trailing table entries point at scratch, as the engine pads them
    for i in range(batch):
        nb_live = math.ceil(lens[i] / block_size)
        tables[i, nb_live:] = 0
    q = jnp.asarray(rng.normal(size=(batch, num_heads, head_dim)), dtype)
    return q, pages_k, pages_v, jnp.asarray(tables), jnp.asarray(
        lens, jnp.int32)


def _dense_oracle(q, pages_k, pages_v, tables, lens, layer):
    """Plain-numpy masked softmax attention — independent of the module."""
    q = np.asarray(q, np.float32)
    k = np.asarray(pages_k[layer], np.float32)[np.asarray(tables)]
    v = np.asarray(pages_v[layer], np.float32)[np.asarray(tables)]
    b, nb, hkv, bs, dh = k.shape
    h = q.shape[1]
    g = h // hkv
    k = k.transpose(0, 2, 1, 3, 4).reshape(b, hkv, nb * bs, dh)
    v = v.transpose(0, 2, 1, 3, 4).reshape(b, hkv, nb * bs, dh)
    out = np.zeros_like(q)
    for i in range(b):
        n = int(lens[i])
        for qh in range(h):
            kh = qh // g
            if n == 0:
                continue
            s = k[i, kh, :n] @ q[i, qh] / math.sqrt(dh)
            p = np.exp(s - s.max())
            p /= p.sum()
            out[i, qh] = p @ v[i, kh, :n]
    return out


@pytest.mark.parametrize("block_size", [4, 8])
@pytest.mark.parametrize("heads", [(4, 4), (4, 2), (4, 1)],
                         ids=["mha", "gqa2", "mqa"])
def test_kernel_matches_reference_ragged(block_size, heads):
    h, hkv = heads
    q, pk, pv, tables, lens = _random_case(
        block_size * 10 + h, block_size=block_size, num_heads=h,
        num_kv_heads=hkv)
    for layer in range(pk.shape[0]):
        ref = pa.paged_attention_reference(q, pk, pv, tables, lens,
                                           layer=layer)
        out = pa.paged_attention(q, pk, pv, tables, lens, layer=layer,
                                 backend="pallas")
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)


def test_reference_matches_dense_oracle():
    q, pk, pv, tables, lens = _random_case(7)
    for layer in range(pk.shape[0]):
        ref = pa.paged_attention_reference(q, pk, pv, tables, lens,
                                           layer=layer)
        oracle = _dense_oracle(q, pk, pv, tables, lens, layer)
        np.testing.assert_allclose(np.asarray(ref), oracle, atol=1e-5,
                                   rtol=1e-5)


def test_zero_length_rows_output_zero():
    q, pk, pv, tables, lens = _random_case(11)
    lens = lens.at[0].set(0).at[2].set(0)
    for backend in ("pallas", "xla"):
        out = pa.paged_attention(q, pk, pv, tables, lens, backend=backend)
        assert np.all(np.asarray(out[0]) == 0), backend
        assert np.all(np.asarray(out[2]) == 0), backend
        np.testing.assert_allclose(
            np.asarray(out[1]),
            _dense_oracle(q, pk, pv, tables, lens, 0)[1],
            atol=2e-5, rtol=2e-5)


def test_single_token_rows():
    """kv_len == 1 everywhere: attention is the identity over the one row."""
    q, pk, pv, tables, _ = _random_case(13)
    lens = jnp.ones((q.shape[0],), jnp.int32)
    out = pa.paged_attention(q, pk, pv, tables, lens, backend="pallas")
    oracle = _dense_oracle(q, pk, pv, tables, lens, 0)
    np.testing.assert_allclose(np.asarray(out), oracle, atol=2e-5, rtol=2e-5)


def test_single_layer_pages_and_bf16():
    q, pk, pv, tables, lens = _random_case(17, dtype=jnp.bfloat16)
    out = pa.paged_attention(q, pk[0], pv[0], tables, lens, backend="pallas")
    ref = pa.paged_attention_reference(q, pk[0], pv[0], tables, lens)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32), atol=3e-2)


def test_scatter_kv_rows_roundtrip():
    rng = np.random.default_rng(3)
    q, pk, pv, tables, lens = _random_case(19)
    b, h_kv, bs, dh = q.shape[0], pk.shape[2], pk.shape[3], pk.shape[4]
    rows = jnp.asarray(rng.normal(size=(b, h_kv, dh)), jnp.float32)
    offsets = lens - 1  # write at each row's last live position
    pk2 = pa.scatter_kv_rows(pk, tables, offsets, rows, layer=1)
    for i in range(b):
        blk = int(tables[i, int(offsets[i]) // bs])
        slot = int(offsets[i]) % bs
        np.testing.assert_array_equal(np.asarray(pk2[1, blk, :, slot, :]),
                                      np.asarray(rows[i]))
    # layer 0 untouched
    np.testing.assert_array_equal(np.asarray(pk2[0]), np.asarray(pk[0]))
    # 4-D single-layer form
    pk1 = pa.scatter_kv_rows(pk[0], tables, offsets, rows)
    blk0 = int(tables[0, int(offsets[0]) // bs])
    np.testing.assert_array_equal(
        np.asarray(pk1[blk0, :, int(offsets[0]) % bs, :]),
        np.asarray(rows[0]))


def test_jit_and_traced_layer_index():
    """The engine traces layer as a loop-carried python int, but the kernel
    must also accept it traced (scalar-prefetch operand)."""
    q, pk, pv, tables, lens = _random_case(23)

    @jax.jit
    def run(q, pk, pv, tables, lens, layer):
        return pa.paged_attention(q, pk, pv, tables, lens, layer=layer,
                                  backend="pallas")

    for layer in range(pk.shape[0]):
        out = run(q, pk, pv, tables, lens, jnp.asarray(layer, jnp.int32))
        ref = pa.paged_attention_reference(q, pk, pv, tables, lens,
                                           layer=layer)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)


def test_arg_validation():
    q, pk, pv, tables, lens = _random_case(29)
    with pytest.raises(ValueError, match="kv heads"):
        pa.paged_attention(q[:, :3], pk, pv, tables, lens)
    with pytest.raises(ValueError, match="batch"):
        pa.paged_attention(q, pk, pv, tables[:2], lens)
    with pytest.raises(ValueError, match="backend"):
        pa.paged_attention(q, pk, pv, tables, lens, backend="cuda")
    with pytest.raises(ValueError, match="layer is required"):
        pa.scatter_kv_rows(pk, tables, lens - 1,
                           jnp.zeros((3, 2, 16)))
