"""Parity tests for the ragged paged-attention decode kernel.

The Pallas kernel (``ops/pallas/paged_attention``) runs in interpret mode on
CPU (forced by the ``kernel`` marker's conftest fixture), checked against the
XLA-lax reference in the same module; the reference itself is checked against
a dense softmax-attention oracle built here. Covers ragged lengths, block
sizes, GQA head ratios, layer selection, zero-length rows, and the
``scatter_kv_rows`` write half of the page contract.
"""
import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tnn_tpu.ops.pallas import paged_attention as pa

pytestmark = pytest.mark.kernel


def _random_case(seed, *, num_layers=2, num_blocks=12, block_size=8,
                 num_heads=4, num_kv_heads=2, head_dim=16, batch=3,
                 blocks_per_row=3, dtype=jnp.float32):
    """Random pool pages + block tables with ragged per-row lengths.

    Block 0 plays the pool's reserved-scratch role: live tables draw from
    blocks 1.., and rows' table tails are padded with 0 like the engine does.
    """
    rng = np.random.default_rng(seed)
    shape = (num_layers, num_blocks, num_kv_heads, block_size, head_dim)
    pages_k = jnp.asarray(rng.normal(size=shape), dtype)
    pages_v = jnp.asarray(rng.normal(size=shape), dtype)
    need = batch * blocks_per_row
    assert need <= num_blocks - 1, "test geometry: not enough live blocks"
    perm = rng.permutation(np.arange(1, num_blocks))[:need]
    tables = perm.reshape(batch, blocks_per_row).astype(np.int32)
    # ragged: one short row, one full row, one mid row ending mid-block
    lens = rng.integers(1, blocks_per_row * block_size + 1, size=batch)
    lens[0] = 1
    lens[-1] = blocks_per_row * block_size
    # dead trailing table entries point at scratch, as the engine pads them
    for i in range(batch):
        nb_live = math.ceil(lens[i] / block_size)
        tables[i, nb_live:] = 0
    q = jnp.asarray(rng.normal(size=(batch, num_heads, head_dim)), dtype)
    return q, pages_k, pages_v, jnp.asarray(tables), jnp.asarray(
        lens, jnp.int32)


def _dense_oracle(q, pages_k, pages_v, tables, lens, layer):
    """Plain-numpy masked softmax attention — independent of the module."""
    q = np.asarray(q, np.float32)
    k = np.asarray(pages_k[layer], np.float32)[np.asarray(tables)]
    v = np.asarray(pages_v[layer], np.float32)[np.asarray(tables)]
    b, nb, hkv, bs, dh = k.shape
    h = q.shape[1]
    g = h // hkv
    k = k.transpose(0, 2, 1, 3, 4).reshape(b, hkv, nb * bs, dh)
    v = v.transpose(0, 2, 1, 3, 4).reshape(b, hkv, nb * bs, dh)
    out = np.zeros_like(q)
    for i in range(b):
        n = int(lens[i])
        for qh in range(h):
            kh = qh // g
            if n == 0:
                continue
            s = k[i, kh, :n] @ q[i, qh] / math.sqrt(dh)
            p = np.exp(s - s.max())
            p /= p.sum()
            out[i, qh] = p @ v[i, kh, :n]
    return out


@pytest.mark.parametrize("block_size", [4, 8])
@pytest.mark.parametrize("heads", [(4, 4), (4, 2), (4, 1)],
                         ids=["mha", "gqa2", "mqa"])
def test_kernel_matches_reference_ragged(block_size, heads):
    h, hkv = heads
    q, pk, pv, tables, lens = _random_case(
        block_size * 10 + h, block_size=block_size, num_heads=h,
        num_kv_heads=hkv)
    for layer in range(pk.shape[0]):
        ref = pa.paged_attention_reference(q, pk, pv, tables, lens,
                                           layer=layer)
        out = pa.paged_attention(q, pk, pv, tables, lens, layer=layer,
                                 backend="pallas")
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)


def test_reference_matches_dense_oracle():
    q, pk, pv, tables, lens = _random_case(7)
    for layer in range(pk.shape[0]):
        ref = pa.paged_attention_reference(q, pk, pv, tables, lens,
                                           layer=layer)
        oracle = _dense_oracle(q, pk, pv, tables, lens, layer)
        np.testing.assert_allclose(np.asarray(ref), oracle, atol=1e-5,
                                   rtol=1e-5)


def test_zero_length_rows_output_zero():
    q, pk, pv, tables, lens = _random_case(11)
    lens = lens.at[0].set(0).at[2].set(0)
    for backend in ("pallas", "xla"):
        out = pa.paged_attention(q, pk, pv, tables, lens, backend=backend)
        assert np.all(np.asarray(out[0]) == 0), backend
        assert np.all(np.asarray(out[2]) == 0), backend
        np.testing.assert_allclose(
            np.asarray(out[1]),
            _dense_oracle(q, pk, pv, tables, lens, 0)[1],
            atol=2e-5, rtol=2e-5)


def test_single_token_rows():
    """kv_len == 1 everywhere: attention is the identity over the one row."""
    q, pk, pv, tables, _ = _random_case(13)
    lens = jnp.ones((q.shape[0],), jnp.int32)
    out = pa.paged_attention(q, pk, pv, tables, lens, backend="pallas")
    oracle = _dense_oracle(q, pk, pv, tables, lens, 0)
    np.testing.assert_allclose(np.asarray(out), oracle, atol=2e-5, rtol=2e-5)


def test_single_layer_pages_and_bf16():
    q, pk, pv, tables, lens = _random_case(17, dtype=jnp.bfloat16)
    out = pa.paged_attention(q, pk[0], pv[0], tables, lens, backend="pallas")
    ref = pa.paged_attention_reference(q, pk[0], pv[0], tables, lens)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32), atol=3e-2)


def test_scatter_kv_rows_roundtrip():
    rng = np.random.default_rng(3)
    q, pk, pv, tables, lens = _random_case(19)
    b, h_kv, bs, dh = q.shape[0], pk.shape[2], pk.shape[3], pk.shape[4]
    rows = jnp.asarray(rng.normal(size=(b, h_kv, dh)), jnp.float32)
    offsets = lens - 1  # write at each row's last live position
    pk2 = pa.scatter_kv_rows(pk, tables, offsets, rows, layer=1)
    for i in range(b):
        blk = int(tables[i, int(offsets[i]) // bs])
        slot = int(offsets[i]) % bs
        np.testing.assert_array_equal(np.asarray(pk2[1, blk, :, slot, :]),
                                      np.asarray(rows[i]))
    # layer 0 untouched
    np.testing.assert_array_equal(np.asarray(pk2[0]), np.asarray(pk[0]))
    # 4-D single-layer form
    pk1 = pa.scatter_kv_rows(pk[0], tables, offsets, rows)
    blk0 = int(tables[0, int(offsets[0]) // bs])
    np.testing.assert_array_equal(
        np.asarray(pk1[blk0, :, int(offsets[0]) % bs, :]),
        np.asarray(rows[0]))


def test_jit_and_traced_layer_index():
    """The engine traces layer as a loop-carried python int, but the kernel
    must also accept it traced (scalar-prefetch operand)."""
    q, pk, pv, tables, lens = _random_case(23)

    @jax.jit
    def run(q, pk, pv, tables, lens, layer):
        return pa.paged_attention(q, pk, pv, tables, lens, layer=layer,
                                  backend="pallas")

    for layer in range(pk.shape[0]):
        out = run(q, pk, pv, tables, lens, jnp.asarray(layer, jnp.int32))
        ref = pa.paged_attention_reference(q, pk, pv, tables, lens,
                                           layer=layer)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)


def test_arg_validation():
    q, pk, pv, tables, lens = _random_case(29)
    with pytest.raises(ValueError, match="kv heads"):
        pa.paged_attention(q[:, :3], pk, pv, tables, lens)
    with pytest.raises(ValueError, match="batch"):
        pa.paged_attention(q, pk, pv, tables[:2], lens)
    with pytest.raises(ValueError, match="backend"):
        pa.paged_attention(q, pk, pv, tables, lens, backend="cuda")
    with pytest.raises(ValueError, match="layer is required"):
        pa.scatter_kv_rows(pk, tables, lens - 1,
                           jnp.zeros((3, 2, 16)))
    with pytest.raises(ValueError, match="q_lens"):
        pa.paged_attention(q, pk, pv, tables, lens, q_lens=lens)
    with pytest.raises(ValueError, match="layer is required"):
        pa.scatter_kv_chunk(pk, tables, lens - 1, jnp.zeros((3, 4, 2, 16)),
                            jnp.ones((3,), jnp.int32))


# -- ragged multi-token query chunks (chunked prefill) ------------------------


def _random_chunk_case(seed, *, num_layers=2, num_blocks=16, block_size=8,
                       num_heads=4, num_kv_heads=2, head_dim=16, batch=4,
                       blocks_per_row=3, qw=4, dtype=jnp.float32):
    """Random pool history + a ragged chunk per row: row i has ``starts[i]``
    previously written positions and ``q_lens[i]`` new tokens this step
    (0 = absent padding row, 1 = decode-like, up to the full chunk width)."""
    rng = np.random.default_rng(seed)
    shape = (num_layers, num_blocks, num_kv_heads, block_size, head_dim)
    pages_k = jnp.asarray(rng.normal(size=shape), dtype)
    pages_v = jnp.asarray(rng.normal(size=shape), dtype)
    need = batch * blocks_per_row
    assert need <= num_blocks - 1, "test geometry: not enough live blocks"
    perm = rng.permutation(np.arange(1, num_blocks))[:need]
    tables = perm.reshape(batch, blocks_per_row).astype(np.int32)
    cap = blocks_per_row * block_size
    q_lens = rng.integers(0, qw + 1, size=batch)
    q_lens[0] = 0            # absent row: must output exactly 0
    q_lens[1] = 1            # decode-like row inside the chunked launch
    q_lens[-1] = qw          # full chunk
    starts = np.array([int(rng.integers(0, cap - ql + 1))
                       for ql in q_lens], np.int32)
    kv_lens = starts + q_lens
    for i in range(batch):
        nb_live = max(1, math.ceil(max(int(kv_lens[i]), 1) / block_size))
        tables[i, nb_live:] = 0
    q = jnp.asarray(rng.normal(size=(batch, qw, num_heads, head_dim)), dtype)
    rows_k = jnp.asarray(rng.normal(size=(batch, qw, num_kv_heads, head_dim)),
                         dtype)
    rows_v = jnp.asarray(rng.normal(size=(batch, qw, num_kv_heads, head_dim)),
                         dtype)
    return (q, pages_k, pages_v, jnp.asarray(tables),
            jnp.asarray(starts, jnp.int32), jnp.asarray(q_lens, jnp.int32),
            rows_k, rows_v)


def _dense_oracle_mq(q, pages_k, pages_v, tables, kv_lens, q_lens, layer):
    """Numpy oracle for the ragged-chunk form: chunk token t sits at absolute
    position kv_lens - q_lens + t and attends causally over everything up to
    and including itself; dead tokens (t >= q_lens) output exactly 0."""
    q = np.asarray(q, np.float32)
    k = np.asarray(pages_k[layer], np.float32)[np.asarray(tables)]
    v = np.asarray(pages_v[layer], np.float32)[np.asarray(tables)]
    b, nb, hkv, bs, dh = k.shape
    qw, h = q.shape[1], q.shape[2]
    g = h // hkv
    k = k.transpose(0, 2, 1, 3, 4).reshape(b, hkv, nb * bs, dh)
    v = v.transpose(0, 2, 1, 3, 4).reshape(b, hkv, nb * bs, dh)
    out = np.zeros_like(q)
    for i in range(b):
        n, ql = int(kv_lens[i]), int(q_lens[i])
        for t in range(ql):
            m = n - ql + t + 1   # keys visible to chunk token t (causal)
            if m <= 0:
                continue
            for qh in range(h):
                kh = qh // g
                s = k[i, kh, :m] @ q[i, t, qh] / math.sqrt(dh)
                p = np.exp(s - s.max())
                p /= p.sum()
                out[i, t, qh] = p @ v[i, kh, :m]
    return out


@pytest.mark.parametrize("block_size", [4, 8])
@pytest.mark.parametrize("heads", [(4, 4), (4, 2), (4, 1)],
                         ids=["mha", "gqa2", "mqa"])
@pytest.mark.parametrize("qw", [4, 8])
def test_multitoken_kernel_matches_oracle(block_size, heads, qw):
    """Ragged q chunks x GQA ratios x block sizes: the kernel, the XLA
    reference, and the dense oracle agree; scatter_kv_chunk writes the
    chunk's KV where attention then reads it."""
    h, hkv = heads
    q, pk, pv, tables, starts, q_lens, rows_k, rows_v = _random_chunk_case(
        block_size * 100 + h * 10 + qw, block_size=block_size, num_heads=h,
        num_kv_heads=hkv, qw=qw)
    kv_lens = starts + q_lens
    pk = pa.scatter_kv_chunk(pk, tables, starts, rows_k, q_lens, layer=1)
    pv = pa.scatter_kv_chunk(pv, tables, starts, rows_v, q_lens, layer=1)
    ref = pa.paged_attention_reference(q, pk, pv, tables, kv_lens,
                                       q_lens=q_lens, layer=1)
    out = pa.paged_attention(q, pk, pv, tables, kv_lens, q_lens=q_lens,
                             layer=1, backend="pallas")
    oracle = _dense_oracle_mq(q, pk, pv, tables, kv_lens, q_lens, 1)
    np.testing.assert_allclose(np.asarray(ref), oracle, atol=2e-5, rtol=2e-5)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5,
                               rtol=2e-5)
    # dead rows (q_lens 0 / t >= q_lens) are exactly 0, not just close
    assert np.all(np.asarray(out[0]) == 0)
    ql = np.asarray(q_lens)
    for i in range(q.shape[0]):
        assert np.all(np.asarray(out[i, ql[i]:]) == 0), i


def test_multitoken_q1_matches_decode_form():
    """A chunked launch with every row at q_len 1 must reproduce the legacy
    decode form bit-for-bit (same kernel geometry, same mask)."""
    q3, pk, pv, tables, lens = _random_case(31)
    dec = pa.paged_attention(q3, pk, pv, tables, lens, backend="pallas")
    mq = pa.paged_attention(q3[:, None], pk, pv, tables, lens,
                            q_lens=jnp.ones_like(lens), backend="pallas")
    assert mq.shape == (q3.shape[0], 1) + q3.shape[1:]
    np.testing.assert_array_equal(np.asarray(dec), np.asarray(mq[:, 0]))
    ref_dec = pa.paged_attention_reference(q3, pk, pv, tables, lens)
    ref_mq = pa.paged_attention_reference(q3[:, None], pk, pv, tables, lens,
                                          q_lens=jnp.ones_like(lens))
    np.testing.assert_array_equal(np.asarray(ref_dec),
                                  np.asarray(ref_mq[:, 0]))


def test_scatter_kv_chunk_roundtrip_and_scratch_only():
    """Live chunk tokens land at table[pos // bs] slot pos % bs; dead tokens
    write ONLY the reserved scratch block 0; other layers untouched."""
    q, pk, pv, tables, starts, q_lens, rows_k, _ = _random_chunk_case(37)
    bs = pk.shape[3]
    pk2 = pa.scatter_kv_chunk(pk, tables, starts, rows_k, q_lens, layer=1)
    b, qw = rows_k.shape[:2]
    live_slots = set()
    for i in range(b):
        for t in range(int(q_lens[i])):
            pos = int(starts[i]) + t
            blk = int(tables[i, pos // bs])
            slot = pos % bs
            live_slots.add((blk, slot))
            np.testing.assert_array_equal(
                np.asarray(pk2[1, blk, :, slot, :]),
                np.asarray(rows_k[i, t]))
    # any other change is confined to the scratch block
    changed = np.any(np.asarray(pk2[1] != pk[1]), axis=(1, 3))  # (N, bs)
    for blk, slot in zip(*np.nonzero(changed)):
        assert blk == 0 or (int(blk), int(slot)) in live_slots, (blk, slot)
    np.testing.assert_array_equal(np.asarray(pk2[0]), np.asarray(pk[0]))
    # 4-D single-layer form
    pk1 = pa.scatter_kv_chunk(pk[1], tables, starts, rows_k, q_lens)
    np.testing.assert_array_equal(np.asarray(pk1), np.asarray(pk2[1]))


# -- int8 quantized pages (QuantPages) ----------------------------------------


def _quantize(pages):
    """Pool-layout quantization: per-(position x head) scale over head_dim."""
    return pa.QuantPages(*pa.quantize_kv_rows(pages))


@pytest.mark.parametrize("block_size", [4, 8])
@pytest.mark.parametrize("heads", [(4, 4), (4, 2), (4, 1)],
                         ids=["mha", "gqa2", "mqa"])
def test_int8_kernel_matches_reference_ragged(block_size, heads):
    """Decode form on int8 pages: the in-kernel dequant agrees with the XLA
    reference's gather-dequant to f32 accumulation tolerance, and both stay
    within quantization error of the unquantized f32 attention."""
    h, hkv = heads
    q, pk, pv, tables, lens = _random_case(
        block_size * 1000 + h, block_size=block_size, num_heads=h,
        num_kv_heads=hkv)
    qpk, qpv = _quantize(pk), _quantize(pv)
    for layer in range(pk.shape[0]):
        ref = pa.paged_attention_reference(q, qpk, qpv, tables, lens,
                                           layer=layer)
        out = pa.paged_attention(q, qpk, qpv, tables, lens, layer=layer,
                                 backend="pallas")
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)
        f32 = pa.paged_attention_reference(q, pk, pv, tables, lens,
                                           layer=layer)
        np.testing.assert_allclose(np.asarray(out), np.asarray(f32),
                                   atol=5e-2)


@pytest.mark.parametrize("qw", [4, 8])
def test_int8_multitoken_kernel_matches_reference(qw):
    """Ragged q chunks on int8 pages: chunk KV is quantized at write time by
    scatter_kv_chunk, then the kernel and reference agree; dead rows stay
    exactly 0."""
    q, pk, pv, tables, starts, q_lens, rows_k, rows_v = _random_chunk_case(
        4100 + qw, qw=qw)
    kv_lens = starts + q_lens
    qpk, qpv = _quantize(pk), _quantize(pv)
    qpk = pa.scatter_kv_chunk(qpk, tables, starts, rows_k, q_lens, layer=1)
    qpv = pa.scatter_kv_chunk(qpv, tables, starts, rows_v, q_lens, layer=1)
    assert isinstance(qpk, pa.QuantPages) and qpk.data.dtype == jnp.int8
    ref = pa.paged_attention_reference(q, qpk, qpv, tables, kv_lens,
                                       q_lens=q_lens, layer=1)
    out = pa.paged_attention(q, qpk, qpv, tables, kv_lens, q_lens=q_lens,
                             layer=1, backend="pallas")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5,
                               rtol=2e-5)
    assert np.all(np.asarray(out[0]) == 0)
    ql = np.asarray(q_lens)
    for i in range(q.shape[0]):
        assert np.all(np.asarray(out[i, ql[i]:]) == 0), i


def test_int8_scatter_rows_quantizes_at_write():
    """scatter_kv_rows on QuantPages stores int8 + per-row scale; the
    dequantized readback is within quantization error of the f32 rows, and
    untouched blocks keep both leaves bit-identical."""
    rng = np.random.default_rng(41)
    q, pk, pv, tables, lens = _random_case(43)
    qpk = _quantize(pk)
    b, h_kv, dh = q.shape[0], pk.shape[2], pk.shape[4]
    bs = pk.shape[3]
    rows = jnp.asarray(rng.normal(size=(b, h_kv, dh)), jnp.float32)
    offsets = lens - 1
    qpk2 = pa.scatter_kv_rows(qpk, tables, offsets, rows, layer=1)
    assert qpk2.data.dtype == jnp.int8 and qpk2.scale.dtype == jnp.float32
    for i in range(b):
        blk = int(tables[i, int(offsets[i]) // bs])
        slot = int(offsets[i]) % bs
        got = (np.asarray(qpk2.data[1, blk, :, slot, :], np.float32) *
               np.asarray(qpk2.scale[1, blk, :, slot, :]))
        np.testing.assert_allclose(got, np.asarray(rows[i]), atol=3e-2)
    # layer 0 untouched on BOTH leaves
    np.testing.assert_array_equal(np.asarray(qpk2.data[0]),
                                  np.asarray(qpk.data[0]))
    np.testing.assert_array_equal(np.asarray(qpk2.scale[0]),
                                  np.asarray(qpk.scale[0]))


def test_int8_mixed_kind_rejected():
    q, pk, pv, tables, lens = _random_case(47)
    with pytest.raises(ValueError, match="both"):
        pa.paged_attention(q, _quantize(pk), pv, tables, lens)
