"""End-to-end language modeling on a REAL token stream (no synthetic noise):
byte-level tokens over this repo's own source files, streamed through the mmap
loader into the compiled train step — the full path of the reference's GPT-2 +
OpenWebText setup (python/openwebtext.py -> open_webtext_data_loader.hpp),
with training on top (the reference only ever runs GPT-2 inference)."""
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tnn_tpu import nn
from tnn_tpu.data.token_stream import TokenStreamDataLoader
from tnn_tpu.models.gpt2 import GPT2, generate
from tnn_tpu.train import create_train_state, make_train_step

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EOT = 256


@pytest.fixture(scope="module")
def byte_corpus(tmp_path_factory):
    """uint16 byte-token .bin built from real source text (tnn_tpu/*.py)."""
    out = tmp_path_factory.mktemp("corpus") / "train.bin"
    chunks = []
    src = os.path.join(REPO, "tnn_tpu")
    for root, _, files in os.walk(src):
        for name in sorted(files):
            if name.endswith(".py"):
                with open(os.path.join(root, name), "rb") as f:
                    chunks.append(np.frombuffer(f.read(), np.uint8)
                                  .astype(np.uint16))
                chunks.append(np.array([EOT], np.uint16))
    tokens = np.concatenate(chunks)
    assert len(tokens) > 100_000  # real corpus, not a stub
    tokens.tofile(str(out))
    return str(out)


def test_gpt2_learns_real_bytes(byte_corpus):
    """A tiny GPT-2 on real source bytes: loss falls well below the uniform
    -log(1/257)=5.55 floor within 40 steps, proving stream -> windows ->
    compiled LM step works end to end."""
    seq, batch = 64, 8
    loader = TokenStreamDataLoader(byte_corpus, seq)
    model = GPT2(vocab_size=257, max_len=seq, num_layers=2, d_model=64,
                 num_heads=2, dropout=0.0)
    opt = nn.AdamW(lr=1e-3, grad_clip_norm=1.0)
    state = create_train_state(model, opt, jax.random.PRNGKey(0), (batch, seq))
    step = make_train_step(model, opt, compute_accuracy=False)
    rng = np.random.default_rng(0)
    first = None
    for i in range(40):
        data, labels = loader.random_windows(batch, rng)
        state, m = step(state, jnp.asarray(data, jnp.int32),
                        jnp.asarray(labels, jnp.int32))
        if first is None:
            first = float(m["loss"])
    final = float(m["loss"])
    assert final < first * 0.8, (first, final)
    assert final < 4.0, final  # clearly below the 5.55 uniform floor

    # KV-cache sampling from the trained model produces tokens in-vocab
    data, _ = loader.random_windows(1, rng)
    toks = np.asarray(generate(model, state.params,
                               jnp.asarray(data[:, :16], jnp.int32), 8,
                               temperature=0.0, max_len=seq))
    assert toks.shape == (1, 8) and int(toks.max()) < 257
