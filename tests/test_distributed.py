"""Control-plane tests: coordinator/worker protocol over localhost TCP.

The reference tests distributed logic without a cluster via IN_PROCESS endpoints
(SURVEY.md §4); the analog here is coordinator + workers as threads in one process
over loopback sockets — same framed protocol as a real multi-host run.
"""
import threading
import time

import pytest

from tnn_tpu.distributed import Command, Coordinator, Worker
from tnn_tpu.distributed.transport import PyTransport, make_transport
from tnn_tpu.profiling import EventType, GlobalProfiler


def _spawn_worker(port, results, name="w", **kw):
    def run():
        w = Worker("127.0.0.1", port, **kw).start()
        results[name] = w
        w.join(timeout=30)

    t = threading.Thread(target=run, daemon=True)
    t.start()
    return t


def _await_workers(results, n, timeout=60.0):
    """wait_for_workers returns when the coordinator saw the handshake, which can
    be before the worker thread stores its Worker object — wait for both."""
    deadline = time.monotonic() + timeout
    while len(results) < n:
        assert time.monotonic() < deadline, f"only {len(results)}/{n} registered"
        time.sleep(0.01)
    return list(results.values())


class TestProtocol:
    def test_handshake_config_barrier_shutdown(self):
        with Coordinator(num_workers=2) as coord:
            res = {}
            t1 = _spawn_worker(coord.port(), res, "a", heartbeat_interval=0.2)
            t2 = _spawn_worker(coord.port(), res, "b", heartbeat_interval=0.2)
            ranks = coord.wait_for_workers(timeout=60)
            assert ranks == [0, 1]
            _await_workers(res, 2)
            coord.deploy_config({"model": "x", "ranks": {"0": {}, "1": {}}},
                                timeout=60)
            assert all(w.config["model"] == "x" for w in res.values())

            # barrier: workers block until coordinator releases
            done = []

            def at_barrier(w):
                w.barrier("sync1", timeout=60)
                done.append(w.rank)

            bts = [threading.Thread(target=at_barrier, args=(w,))
                   for w in res.values()]
            for t in bts:
                t.start()
            coord.barrier("sync1", timeout=60)
            for t in bts:
                t.join(timeout=60)
            assert sorted(done) == [0, 1]

            coord.set_train_mode(False)
            time.sleep(0.3)
            assert all(not w.training for w in res.values())

            coord.shutdown()
            t1.join(timeout=60)
            t2.join(timeout=60)
            assert not any(w.running for w in res.values())

    def test_explicit_rank_request(self):
        with Coordinator(num_workers=1) as coord:
            res = {}
            t = _spawn_worker(coord.port(), res, rank=5)
            coord.wait_for_workers(timeout=60)
            _await_workers(res, 1)
            assert list(res.values())[0].rank == 5
            coord.shutdown()
            t.join(timeout=60)

    def test_profiling_rpc_merges_workers(self):
        with Coordinator(num_workers=1) as coord:
            res = {}
            t = _spawn_worker(coord.port(), res)
            coord.wait_for_workers(timeout=60)
            _await_workers(res, 1)
            GlobalProfiler.clear()
            GlobalProfiler.add_event(EventType.COMPUTE, 0.0, 1.0, "span-x")
            merged = coord.collect_profiles(timeout=60)
            assert any(e.name == "span-x" for e in merged.events)
            coord.clear_profiling()
            time.sleep(0.3)
            assert GlobalProfiler.events == []
            coord.shutdown()
            t.join(timeout=60)

    def test_custom_rpc(self):
        with Coordinator(num_workers=1) as coord:
            res = {}
            t = _spawn_worker(coord.port(), res)
            coord.wait_for_workers(timeout=60)
            w = _await_workers(res, 1)[0]
            w.on("add", lambda obj: {"sum": obj["a"] + obj["b"]})
            assert coord.send_custom(w.rank, {"name": "add", "a": 2, "b": 3})
            assert coord.recv_custom(timeout=60)["sum"] == 5
            # worker -> coordinator direction
            w.send_custom({"name": "status", "ok": True})
            assert coord.recv_custom(timeout=60)["ok"] is True
            coord.shutdown()
            t.join(timeout=60)

    def test_save_rpc(self, tmp_path):
        with Coordinator(num_workers=1) as coord:
            res = {}
            t = _spawn_worker(coord.port(), res)
            coord.wait_for_workers(timeout=60)
            saved = []
            _await_workers(res, 1)[0].on_save = saved.append
            coord.save_all(str(tmp_path / "snap"), timeout=60)
            assert saved == [str(tmp_path / "snap")]
            coord.shutdown()
            t.join(timeout=60)


class TestFailureDetection:
    def test_disconnect_detected_and_callback_fires(self):
        failed = []
        with Coordinator(num_workers=2, on_failure=failed.append) as coord:
            res = {}
            t1 = _spawn_worker(coord.port(), res, "a")
            t2 = _spawn_worker(coord.port(), res, "b")
            coord.wait_for_workers(timeout=60)
            _await_workers(res, 2)
            victim = res["a"]
            victim_rank = victim.rank
            victim._running = False
            victim._t.close()  # abrupt death (no SHUTDOWN_ACK)
            coord.wait_failed(victim_rank, timeout=60)  # event-driven wake
            assert failed == [victim_rank]
            # broadcasts now skip the dead worker without raising
            coord.set_train_mode(False)
            coord.shutdown()
            t1.join(timeout=60)
            t2.join(timeout=60)

    def test_heartbeat_timeout_detected(self):
        with Coordinator(num_workers=1, heartbeat_timeout=0.6) as coord:
            res = {}
            t = _spawn_worker(coord.port(), res, heartbeat_interval=60.0)
            coord.wait_for_workers(timeout=60)
            w = _await_workers(res, 1)[0]
            # worker is connected but silent (stalled process): one initial
            # heartbeat, then nothing -> flagged after the timeout (staleness
            # has no transport event; wait_failed re-checks on a short cadence)
            coord.wait_failed(w.rank, timeout=60)
            coord.shutdown(timeout=2)
            t.join(timeout=60)


class TestRobustness:
    def test_rank_collision_assigns_free_rank(self):
        with Coordinator(num_workers=2) as coord:
            res = {}
            t1 = _spawn_worker(coord.port(), res, "a", rank=1)
            time.sleep(0.3)  # ensure a registers first
            t2 = _spawn_worker(coord.port(), res, "b")  # auto-rank
            ranks = coord.wait_for_workers(timeout=60)
            assert ranks == [0, 1]
            _await_workers(res, 2)
            assert res["a"].rank == 1 and res["b"].rank == 0
            coord.shutdown()
            t1.join(timeout=60)
            t2.join(timeout=60)

    def test_barrier_releases_when_worker_dies(self):
        """A crash mid-wait shrinks the barrier target instead of hanging."""
        with Coordinator(num_workers=2, heartbeat_timeout=60) as coord:
            res = {}
            t1 = _spawn_worker(coord.port(), res, "a")
            t2 = _spawn_worker(coord.port(), res, "b")
            coord.wait_for_workers(timeout=60)
            _await_workers(res, 2)
            res["a"]._running = False
            res["a"]._t.close()  # dies before reaching the barrier
            survivor = res["b"]
            done = []

            def arrive():
                survivor.barrier("b", timeout=60)
                done.append(True)

            bt = threading.Thread(target=arrive, daemon=True)
            bt.start()
            coord.barrier("b", timeout=60)  # must not wait for the dead worker
            bt.join(timeout=60)
            assert done
            coord.shutdown(timeout=2)
            t1.join(timeout=60)
            t2.join(timeout=60)

    def test_mismatched_barrier_arrivals_not_lost(self):
        """An early arrival for barrier B survives the collection of barrier A."""
        with Coordinator(num_workers=1) as coord:
            res = {}
            t = _spawn_worker(coord.port(), res)
            coord.wait_for_workers(timeout=60)
            w = _await_workers(res, 1)[0]
            order = []

            def go():
                w.barrier("second", timeout=60)  # arrives "early"
                order.append("released")

            bt = threading.Thread(target=go, daemon=True)
            bt.start()
            time.sleep(0.3)  # let the "second" arrival land first
            coord.barrier("second", timeout=60)
            bt.join(timeout=60)
            assert order == ["released"]
            coord.shutdown()
            t.join(timeout=60)

    def test_dead_arrival_cannot_release_barrier_for_absent_worker(self):
        """A arrives, B arrives then dies, C never arrives: the barrier must NOT
        release (count-based barriers released here: 2 arrivals >= 2 live), and
        must release later once C actually arrives."""
        with Coordinator(num_workers=3, heartbeat_timeout=600) as coord:
            res = {}
            ts = [_spawn_worker(coord.port(), res, n) for n in ("a", "b", "c")]
            coord.wait_for_workers(timeout=60)
            _await_workers(res, 3)
            wa, wb, wc = res["a"], res["b"], res["c"]
            released = []

            def arrive(w):
                try:
                    w.barrier("gate", timeout=30)
                    released.append(w.rank)
                except TimeoutError:
                    pass

            ta = threading.Thread(target=arrive, args=(wa,), daemon=True)
            tb = threading.Thread(target=arrive, args=(wb,), daemon=True)
            ta.start()
            tb.start()
            time.sleep(0.4)  # both arrivals land at the coordinator
            wb._running = False
            wb._t.close()  # B dies after arriving
            deadline = time.monotonic() + 10
            while wb.rank not in coord.failed_workers():
                assert time.monotonic() < deadline
                time.sleep(0.05)
            with pytest.raises(TimeoutError):
                coord.barrier("gate", timeout=1.5)  # C never arrived
            assert released == []
            # once C arrives, the barrier completes for the live set {A, C}
            tc = threading.Thread(target=arrive, args=(wc,), daemon=True)
            tc.start()
            coord.barrier("gate", timeout=60)
            ta.join(timeout=60)
            tc.join(timeout=60)
            assert sorted(released) == sorted([wa.rank, wc.rank])
            coord.shutdown(timeout=2)
            for t in ts:
                t.join(timeout=60)

    def test_unknown_command_does_not_kill_pump(self):
        with Coordinator(num_workers=1) as coord:
            res = {}
            t = _spawn_worker(coord.port(), res)
            coord.wait_for_workers(timeout=60)
            w = _await_workers(res, 1)[0]
            # send a raw frame with an out-of-enum command straight at the pump
            w._t.send(w._conn, 999, b'{"x": 1}')
            time.sleep(0.3)
            assert coord._pump.is_alive()
            # protocol still functional afterwards
            w.on("ping", lambda obj: {"pong": 1})
            coord.send_custom(w.rank, {"name": "ping"})
            assert coord.recv_custom(timeout=60)["pong"] == 1
            coord.shutdown()
            t.join(timeout=60)

    def test_save_all_without_handler_raises(self):
        with Coordinator(num_workers=1) as coord:
            res = {}
            t = _spawn_worker(coord.port(), res)
            coord.wait_for_workers(timeout=60)
            _await_workers(res, 1)
            with pytest.raises(RuntimeError, match="did not save"):
                coord.save_all("/tmp/nowhere", timeout=60)
            coord.shutdown()
            t.join(timeout=60)

    def test_failed_worker_can_rejoin(self):
        """Restarting a dead rank re-admits it (reference leaves this a stub)."""
        failed = []
        with Coordinator(num_workers=2, on_failure=failed.append) as coord:
            res = {}
            t1 = _spawn_worker(coord.port(), res, "a")
            t2 = _spawn_worker(coord.port(), res, "b")
            coord.wait_for_workers(timeout=60)
            _await_workers(res, 2)
            dead_rank = res["a"].rank
            res["a"]._running = False
            res["a"]._t.close()
            coord.wait_failed(dead_rank, timeout=60)
            # restart with the same rank
            res2 = {}
            t3 = _spawn_worker(coord.port(), res2, "a2", rank=dead_rank)
            new = _await_workers(res2, 1)[0]
            assert new.rank == dead_rank
            coord.wait_alive(dead_rank, timeout=60)  # woken by the handshake
            coord.shutdown()
            for t in (t1, t2, t3):
                t.join(timeout=60)


class TestHandshakeStorm:
    def test_simultaneous_connects_all_get_acks(self):
        """16 workers connect at once and every one must receive its
        HANDSHAKE_ACK. Regression for the add_conn race (native/src/
        control.cpp): the reader thread could deliver a peer's HANDSHAKE
        before the conn was registered, so the coordinator's ack send
        silently missed — workers stranded in their handshake wait.
        Found by the TSan lane; this pins it at the protocol level."""
        n = 16
        with Coordinator(num_workers=n) as coord:
            res = {}
            errs = []

            def run(i):
                try:
                    w = Worker("127.0.0.1", coord.port(), rank=i,
                               heartbeat_interval=5.0).start()
                    res[i] = w
                except Exception as e:  # noqa: BLE001 — collected for assert
                    errs.append((i, repr(e)))

            threads = [threading.Thread(target=run, args=(i,), daemon=True)
                       for i in range(n)]
            for t in threads:  # start as close to simultaneously as possible
                t.start()
            ranks = coord.wait_for_workers(timeout=90)
            for t in threads:
                t.join(timeout=60)
            assert not errs, errs
            assert ranks == list(range(n))
            assert sorted(res) == list(range(n))
            assert all(res[i].rank == i for i in res)
            coord.shutdown()


class TestTransportInterop:
    def test_python_worker_native_coordinator(self):
        """Wire-format compatibility: both transports speak identical frames."""
        coord = Coordinator(num_workers=1)  # native if available
        try:
            res = {}

            def run():
                w = Worker("127.0.0.1", coord.port(),
                           transport=PyTransport(listen_port=None)).start()
                res["w"] = w
                w.barrier("x", timeout=60)
                w.join(timeout=60)

            t = threading.Thread(target=run, daemon=True)
            t.start()
            coord.wait_for_workers(timeout=60)
            coord.barrier("x", timeout=60)
            coord.shutdown()
            t.join(timeout=60)
            assert "w" in res
        finally:
            coord.close()

    def test_concurrent_large_sends_do_not_interleave(self):
        """PyTransport.send from many threads must not corrupt the stream: each
        large frame arrives whole and byte-identical (per-connection send lock;
        the native transport's send_mu equivalent)."""
        recv = PyTransport(listen_port=0)
        send = PyTransport(listen_port=None)
        try:
            conn = send.connect("127.0.0.1", recv.port())
            n_threads, frames_each, size = 4, 8, 256 * 1024

            def blast(tag):
                payload = bytes([tag]) * size
                for _ in range(frames_each):
                    assert send.send(conn, tag, payload)

            threads = [threading.Thread(target=blast, args=(t,), daemon=True)
                       for t in range(1, n_threads + 1)]
            for t in threads:
                t.start()
            got = 0
            deadline = time.monotonic() + 30
            while got < n_threads * frames_each:
                assert time.monotonic() < deadline, f"only {got} frames arrived"
                ev = recv.recv(timeout=1.0)
                if ev is None or ev[0] != "msg":
                    continue
                _, _, cmd, payload = ev
                assert len(payload) == size
                # an interleaved write shows up as mixed bytes within a frame
                assert payload == bytes([cmd]) * size, \
                    f"frame for tag {cmd} corrupted"
                got += 1
            for t in threads:
                t.join(timeout=60)
        finally:
            send.close()
            recv.close()

    def test_large_payload(self):
        """Frames beyond the 64KB recv buffer go through the two-phase path."""
        with Coordinator(num_workers=1) as coord:
            res = {}
            t = _spawn_worker(coord.port(), res)
            coord.wait_for_workers(timeout=60)
            big = "x" * 300_000
            w = _await_workers(res, 1)[0]
            w.on("echo", lambda obj: {"blob": obj["blob"]})
            coord.send_custom(w.rank, {"name": "echo", "blob": big})
            assert coord.recv_custom(timeout=60)["blob"] == big
            coord.shutdown()
            t.join(timeout=60)
