"""Mixture-of-Experts + expert parallelism tests (beyond the reference, which
has no MoE; part of the dp/tp/pp/sp/ep layout inventory)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tnn_tpu import nn, parallel
from tnn_tpu.core import dtypes as dt
from tnn_tpu.core.module import module_from_config
from tnn_tpu.nn.moe import MoE, shard_params_ep

F32 = dt.FP32


def test_single_expert_equals_dense_ffn(rng):
    """E=1, k=1, ample capacity routes every token to the one expert with
    weight 1.0 — output must equal the plain Dense->act->Dense FFN computed
    from the same weights."""
    moe = MoE(num_experts=1, hidden=32, top_k=1, capacity_factor=4.0,
              activation="gelu", policy=F32)
    v = moe.init(rng, (2, 8, 16))
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, 16), jnp.float32)
    out, st = moe.apply(v, x)
    p = v["params"]
    ref = jnp.einsum("nsd,dh->nsh", x, p["w_in"][0]) + p["b_in"][0]
    ref = jax.nn.gelu(ref)
    ref = jnp.einsum("nsh,hd->nsd", ref, p["w_out"][0]) + p["b_out"][0]
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)
    assert np.isfinite(float(st["aux_loss"]))


def test_topk_routing_and_capacity(rng):
    """Every token's combine weight sums to ~1 under ample capacity; with
    capacity 1 total routed weight drops (tokens overflow, never crash)."""
    moe = MoE(num_experts=4, hidden=16, top_k=2, capacity_factor=4.0,
              policy=F32)
    v = moe.init(rng, (1, 16, 8))
    x = jax.random.normal(jax.random.PRNGKey(2), (1, 16, 8), jnp.float32)
    out, _ = moe.apply(v, x)
    assert out.shape == x.shape and bool(jnp.isfinite(out).all())

    tight = MoE(num_experts=4, hidden=16, top_k=2, capacity_factor=0.1,
                policy=F32)
    out2, _ = tight.apply(v, x)  # same params, tiny capacity
    assert bool(jnp.isfinite(out2).all())
    # overflow must reduce routed mass, not duplicate it
    assert float(jnp.abs(out2).sum()) <= float(jnp.abs(out).sum()) * 1.5


def test_moe_trains_and_balances(rng):
    """Gradients flow through routing; the aux loss pushes toward balanced
    expert usage (loss decreases when trained on it alone)."""
    moe = MoE(num_experts=4, hidden=16, top_k=1, aux_weight=1.0, policy=F32)
    v = moe.init(rng, (4, 8, 8))
    x = jax.random.normal(jax.random.PRNGKey(3), (4, 8, 8), jnp.float32)
    y = jax.random.normal(jax.random.PRNGKey(4), (4, 8, 8), jnp.float32)

    def loss_fn(params):
        out, st = moe.apply({"params": params, "state": {}}, x, train=True,
                            rng=jax.random.PRNGKey(0))
        return jnp.mean((out - y) ** 2) + st["aux_loss"]

    params = v["params"]
    grad_fn = jax.jit(jax.grad(loss_fn))
    l0 = float(loss_fn(params))
    for _ in range(120):
        g = grad_fn(params)
        params = jax.tree_util.tree_map(lambda p, gg: p - 0.05 * gg, params, g)
    assert float(loss_fn(params)) < l0 * 0.93


def test_expert_parallel_sharding_matches_replicated(rng):
    """Expert-sharded params over an 8-way expert axis produce the same output
    as replicated execution (GSPMD inserts the all-to-alls)."""
    mesh = parallel.make_mesh(expert=8)
    moe = MoE(num_experts=8, hidden=16, top_k=2, capacity_factor=4.0,
              policy=F32)
    v = moe.init(rng, (2, 16, 8))
    x = jax.random.normal(jax.random.PRNGKey(5), (2, 16, 8), jnp.float32)
    ref, _ = moe.apply(v, x)

    sharded = shard_params_ep(v["params"], mesh)
    assert any("expert" in str(leaf.sharding.spec)
               for leaf in jax.tree_util.tree_leaves(sharded)
               if hasattr(leaf, "sharding"))

    @jax.jit
    def fwd(params, x):
        out, st = moe.apply({"params": params, "state": {}}, x)
        return out, st["aux_loss"]

    with mesh:
        out, aux = fwd(sharded, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)

    # grads under the sharded layout stay finite (train step viability)
    def loss(params):
        out, st = moe.apply({"params": params, "state": {}}, x, train=True,
                            rng=jax.random.PRNGKey(0))
        return jnp.sum(out ** 2) + st["aux_loss"]

    with mesh:
        g = jax.jit(jax.grad(loss))(sharded)
    assert all(bool(jnp.isfinite(leaf).all())
               for leaf in jax.tree_util.tree_leaves(g))


def test_moe_through_train_step_and_grad_accum(rng):
    """MoE inside a Sequential trains through make_train_step — including the
    grad_accum lax.scan path, which requires the init/apply state structures
    to match exactly — and the aux loss is consumed into the training loss."""
    from tnn_tpu.train import create_train_state, make_train_step
    from tnn_tpu.train.step import aux_loss_sum

    model = nn.Sequential([
        nn.Dense(16, activation="relu", policy=F32),
        MoE(num_experts=4, hidden=32, top_k=2, aux_weight=0.05, policy=F32),
        nn.Flatten(policy=F32),
        nn.Dense(4, policy=F32),
    ], policy=F32)
    opt = nn.Adam(lr=3e-3)
    state = create_train_state(model, opt, rng, (8, 6, 8),
                               input_dtype=jnp.float32)
    assert float(aux_loss_sum(state.net_state)) == 0.0  # init structure
    step = make_train_step(model, opt, grad_accum=2, donate=False,
                           compute_accuracy=False)
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(8, 6, 8), jnp.float32)
    y = jnp.asarray(rs.randint(0, 4, 8), jnp.int32)
    first = None
    for _ in range(30):
        state, m = step(state, x, y)
        if first is None:
            first = float(m["loss"])
    assert float(m["loss"]) < first
    # the state now carries the last step's aux loss (> 0 for a live router)
    assert float(aux_loss_sum(state.net_state)) > 0.0


def test_config_driven_expert_axis(rng, tmp_path):
    """mesh_axes={'data':2,'expert':4} trains an MoE model from config alone."""
    from tnn_tpu.data.loader import SyntheticDataLoader
    from tnn_tpu.train import train_model
    from tnn_tpu.utils.config import TrainingConfig

    model = nn.Sequential([
        nn.Dense(16, activation="relu"),
        MoE(num_experts=4, hidden=32, top_k=2),
        nn.Flatten(),
        nn.Dense(4),
    ])
    loader = SyntheticDataLoader(64, (6, 8), 4)
    cfg = TrainingConfig(epochs=1, batch_size=16,
                         snapshot_dir=str(tmp_path / "ep"),
                         mesh_axes={"data": 2, "expert": 4},
                         progress_print_interval=2)
    state, history = train_model(model, cfg, loader)
    assert len(history) == 1 and np.isfinite(history[0]["train_loss"])


def test_config_round_trip(rng):
    moe = MoE(num_experts=4, hidden=32, top_k=2, capacity_factor=1.5,
              activation="relu", aux_weight=0.02, policy=F32)
    m2 = module_from_config(moe.get_config())
    assert isinstance(m2, MoE)
    v = moe.init(rng, (1, 4, 8))
    x = jnp.ones((1, 4, 8), jnp.float32)
    a, _ = moe.apply(v, x)
    b, _ = m2.apply(v, x)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b))


def test_moe_gpt2_trains_and_decodes(rng):
    """GPT-2 with MoE FFN blocks: trains through make_train_step (aux loss
    consumed, per-block state threads), and KV-cache decode still works."""
    from tnn_tpu import models
    from tnn_tpu.models.gpt2 import generate
    from tnn_tpu.train import create_train_state, make_train_step
    from tnn_tpu.train.step import aux_loss_sum

    model = models.GPT2(vocab_size=64, max_len=16, num_layers=2, d_model=32,
                        num_heads=2, moe_experts=4)
    opt = nn.AdamW(lr=1e-3)
    state = create_train_state(model, opt, rng, (4, 16))
    step = make_train_step(model, opt, compute_accuracy=False)
    rs = np.random.RandomState(0)
    ids = jnp.asarray(rs.randint(0, 64, (4, 16)), jnp.int32)
    labels = jnp.asarray(np.roll(np.asarray(ids), -1, 1), jnp.int32)
    first = None
    for _ in range(15):
        state, m = step(state, ids, labels)
        if first is None:
            first = float(m["loss"])
    assert float(m["loss"]) < first
    assert float(aux_loss_sum(state.net_state)) > 0.0  # router state threads

    toks = np.asarray(generate(model, state.params, ids[:1, :8], 4,
                               temperature=0.0, max_len=16))
    assert toks.shape == (1, 4) and int(toks.max()) < 64

    # config round-trip keeps the MoE blocks
    from tnn_tpu.core.module import module_from_config

    m2 = module_from_config(model.get_config())
    assert m2.moe_experts == 4 and m2.blocks[0].moe is not None


def test_sort_dispatch_matches_einsum(rng):
    """With capacity covering every token (no drops), the sort-based dispatch
    computes EXACTLY the same mixture as the (T, E, C) einsum dispatch —
    outputs, aux loss, and gradients."""
    kw = dict(num_experts=4, hidden=32, top_k=2, capacity_factor=8.0,
              policy=F32)
    einsum_moe = MoE(dispatch="einsum", **kw)
    sort_moe = MoE(dispatch="sort", **kw)
    v = einsum_moe.init(rng, (2, 8, 16))
    x = jax.random.normal(jax.random.PRNGKey(2), (2, 8, 16), jnp.float32)

    out_e, st_e = einsum_moe.apply(v, x)
    out_s, st_s = sort_moe.apply(v, x)
    np.testing.assert_allclose(np.asarray(out_s), np.asarray(out_e),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(float(st_s["aux_loss"]),
                               float(st_e["aux_loss"]), rtol=1e-6)

    def loss(params, moe):
        out, st = moe.apply({"params": params, "state": {}}, x)
        return jnp.sum(out ** 2) + st["aux_loss"]

    ge = jax.grad(loss)(v["params"], einsum_moe)
    gs = jax.grad(loss)(v["params"], sort_moe)
    for a, b in zip(jax.tree_util.tree_leaves(ge),
                    jax.tree_util.tree_leaves(gs)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=1e-5)


def test_sort_dispatch_capacity_drop_and_config(rng):
    """Overflowing an expert drops excess tokens (combine weight zero, finite
    outputs), and dispatch mode survives the config round-trip."""
    moe = MoE(num_experts=2, hidden=16, top_k=1, capacity_factor=0.3,
              dispatch="sort", policy=F32)
    v = moe.init(rng, (1, 16, 8))
    x = jax.random.normal(jax.random.PRNGKey(3), (1, 16, 8), jnp.float32)
    out, st = moe.apply(v, x)
    assert np.isfinite(np.asarray(out)).all()
    rebuilt = module_from_config(moe.get_config())
    assert rebuilt.dispatch == "sort"
    out2, _ = rebuilt.apply(v, x)
    np.testing.assert_allclose(np.asarray(out2), np.asarray(out), rtol=1e-6)
