"""Overlapped engine loop (PR: async overlap): the double-buffered loop is
token-exact against the synchronous loop on every decode path, survives
staggered arrivals / preemption / mid-run crashes, never publishes prefix
blocks for a terminated request, and stays clean under TNN_DEBUG_SYNC=1.

The exactness matrix is the tentpole's hard invariant: overlap changes WHEN
host bookkeeping runs, never WHAT tokens come out. Heavy combinations ride
the documented `slow` lane; tier-1 keeps one representative per axis.
"""
import numpy as np
import pytest

import jax

from tnn_tpu.serving.engine import InferenceEngine
from tnn_tpu.serving.faults import FaultPlan
from tnn_tpu.serving.supervisor import EngineSupervisor

KW = dict(num_blocks=32, block_size=4, max_batch_size=4, max_seq_len=32)


@pytest.fixture(scope="module")
def tiny_lm():
    from tnn_tpu.models.gpt2 import GPT2

    model = GPT2(vocab_size=128, max_len=64, num_layers=2, d_model=32,
                 num_heads=2)
    params = model.init(jax.random.PRNGKey(0), (1, 8))["params"]
    return model, params


@pytest.fixture(scope="module")
def draft_lm(tiny_lm):
    """Vocab-matched stand-in drafter (random weights: acceptance is poor,
    which exercises the reject/rollback arm of verification)."""
    from tnn_tpu.models.gpt2 import GPT2

    model = GPT2(vocab_size=128, max_len=64, num_layers=2, d_model=32,
                 num_heads=2)
    params = model.init(jax.random.PRNGKey(7), (1, 8))["params"]
    return model, params


def _prompts():
    # shared 8-token prefix so the prefix cache actually publishes+matches
    base = (np.arange(16) * 5 % 128).astype(np.int32)
    return [base[:12], base[:9], np.concatenate([base[:8],
                                                 base[:4] + 1]).astype(
                                                     np.int32)]


def _run(model, params, overlap, prompts=None, max_new=8, **kw):
    eng = InferenceEngine(model, params, **KW, overlap=overlap, **kw)
    rids = [eng.submit(p, max_new) for p in (prompts or _prompts())]
    out = eng.run_until_complete()
    return {r: out[r] for r in rids}, eng


class TestOverlapTokenExact:
    @pytest.mark.parametrize("path,spec", [
        ("paged", "off"),
        ("paged", "ngram"),
        ("standard", "off"),
        pytest.param("standard", "ngram", marks=pytest.mark.slow),
        pytest.param("standard", "draft", marks=pytest.mark.slow),
        pytest.param("paged", "draft", marks=pytest.mark.slow),
    ])
    def test_matrix(self, tiny_lm, draft_lm, path, spec):
        model, params = tiny_lm
        kw = dict(decode_path=path, prefix_cache=True)
        if spec != "off":
            kw["spec"] = spec
        if spec == "draft":
            kw["draft_model"], kw["draft_params"] = draft_lm
        off, _ = _run(model, params, overlap=False, **kw)
        on, eng = _run(model, params, overlap=True, **kw)
        assert on == off, f"overlap changed tokens on {path}/{spec}"
        # the loop actually overlapped: the fetch->dispatch gap was measured
        assert len(eng.metrics.host_gap_s) > 0
        assert eng.in_flight is None and not eng._deferred

    def test_staggered_preempted_exact(self, tiny_lm):
        """Arrivals landing WHILE a step is in flight, on a pool small
        enough to preempt, still commit the synchronous loop's tokens."""
        model, params = tiny_lm
        rng = np.random.default_rng(1)
        prompts = [rng.integers(0, 128, p).astype(np.int32)
                   for p in (5, 9, 16, 7)]
        small = dict(KW, num_blocks=9)

        eng_off = InferenceEngine(model, params, **small, overlap=False)
        rids = [eng_off.submit(prompts[0], 10)]
        eng_off.step(); eng_off.step()
        rids += [eng_off.submit(p, 10) for p in prompts[1:]]
        off = eng_off.run_until_complete()

        eng = InferenceEngine(model, params, **small, overlap=True)
        rids = [eng.submit(prompts[0], 10)]
        eng.begin_step(); eng.finish_step()
        eng.begin_step()
        # mid-flight arrivals: scheduled at the next build, exactly like a
        # between-steps arrival in the synchronous loop
        rids += [eng.submit(p, 10) for p in prompts[1:]]
        eng.finish_step()
        on = eng.run_until_complete()
        assert eng.metrics.preemptions > 0, "pool was never exhausted"
        for rid in rids:
            assert on[rid] == off[rid]
        assert eng.pool.num_allocated == 0

    def test_crash_migration_exact(self, tiny_lm):
        """A mid-run engine crash under the supervisor recovers token-exact
        with overlap on, and the crash dump still ends with the dying step."""
        model, params = tiny_lm

        def run(overlap):
            eng = InferenceEngine(
                model, params, **KW, overlap=overlap,
                faults=FaultPlan(step_crash_calls=(3,)))
            sup = EngineSupervisor(eng, max_restarts=3)
            events = []
            rids = [sup.submit(p, 8, listener=events.append)
                    for p in _prompts()]
            sup.run_sync()
            terminals = [e for e in events
                         if e["event"] in ("done", "error", "timeout",
                                           "cancelled")]
            return ({r: list(eng.requests[r].out_tokens) for r in rids},
                    terminals, sup)

        off, term_off, _ = run(False)
        on, term_on, sup = run(True)
        assert on == off
        assert sup.restarts == 1
        assert len(term_on) == len(term_off) == len(_prompts())
        crashed = [r for r in sup.flight.records() if r.get("crashed")]
        assert len(crashed) == 1 and "EngineCrash" in crashed[0]["error"]


class TestSpeculativeSteps:
    def test_adoption_and_exactness(self, tiny_lm):
        """The idle-time speculative build fires on a steady decode batch
        and adopting it never changes tokens."""
        model, params = tiny_lm
        off, _ = _run(model, params, overlap=False)
        eng = InferenceEngine(model, params, **KW, overlap=True)
        rids = [eng.submit(p, 8) for p in _prompts()]
        adopted = 0
        while eng.has_work or eng.in_flight is not None:
            if eng.in_flight is None:
                eng.begin_step()
            eng.try_speculate()
            eng.run_deferred()
            eng.finish_step()
            if eng.in_flight is not None and \
                    eng._step_note.get("speculative"):
                adopted += 1
        eng.run_deferred()
        assert adopted > 0, "speculation never fired on a steady batch"
        assert {r: list(eng.requests[r].out_tokens) for r in rids} == off

    def test_mispredict_rolls_back(self, tiny_lm):
        """An arrival between dispatch and resolve invalidates the
        speculative step: it is rolled back (counted) and the rebuilt step
        commits the synchronous loop's tokens for everyone."""
        model, params = tiny_lm
        eng = InferenceEngine(model, params, **KW, overlap=True)
        prompts = _prompts()
        rids = [eng.submit(p, 8) for p in prompts[:2]]
        # settle into steady decode so try_speculate's gate opens
        for _ in range(3):
            eng.begin_step(); eng.finish_step()
        eng.begin_step()
        assert eng.try_speculate(), "speculation gate unexpectedly closed"
        rids.append(eng.submit(prompts[2], 8))   # invalidates the prediction
        eng.finish_step()
        assert eng.metrics.overlap_rebuilds >= 1
        on = eng.run_until_complete()
        off, _ = _run(model, params, overlap=False)
        for rid, want in zip(rids, off.values()):
            assert on.get(rid, list(eng.requests[rid].out_tokens)) == want


class TestDeferredPhase:
    def test_publish_never_lands_for_terminated(self, tiny_lm):
        """A deferred prefix publish queued at commit is guarded at RUN
        time: cancelling the request before the deferred phase runs must
        drop the publish (its blocks are already freed)."""
        model, params = tiny_lm
        eng = InferenceEngine(model, params, **KW, overlap=True,
                              prefix_cache=True)
        published = []
        real = eng.prefix_cache.publish
        eng.prefix_cache.publish = (
            lambda *a, **k: (published.append(a), real(*a, **k)))
        rid = eng.submit(_prompts()[0], 8)
        for _ in range(12):
            if eng.in_flight is None:
                eng.begin_step()
            eng.finish_step()          # commits defer publishes, not run yet
            if eng._deferred:
                break
        assert eng._deferred, "no deferred publish was queued"
        eng.cancel(rid, "test cancel")
        eng.run_deferred()
        assert published == [], "publish landed for a terminated request"
        # positive control: left alone, the publish lands
        rid2 = eng.submit(_prompts()[1], 8)
        eng.run_until_complete()
        assert published, "publish never landed for a live request"
        assert eng.requests[rid2].state.name == "FINISHED"

    def test_host_gap_observability(self, tiny_lm):
        """host_gap lands in the per-request breakdown, the metrics
        summary, and the Prometheus exposition."""
        model, params = tiny_lm
        eng = InferenceEngine(model, params, **KW, overlap=True)
        sup = EngineSupervisor(eng)
        events = []
        sup.submit(_prompts()[0], 8, listener=events.append)
        sup.run_sync()
        done = [e for e in events if e["event"] == "done"]
        assert done and done[0]["latency_breakdown"]["host_gap_ms"] >= 0.0
        s = eng.metrics.summary()
        assert {"host_gap_ms_mean", "host_gap_ms_p50", "host_gap_ms_p99",
                "overlap_rebuilds"} <= set(s)
        fams = {f["name"] for f in eng.metrics.prometheus_series()}
        assert "tnn_serve_host_gap_seconds_total" in fams
        assert "tnn_serve_overlap_rebuilds_total" in fams
        # commit-time gauges: what /healthz now serves without engine access
        g = sup.health_gauges()
        assert g.pop("age_s") >= 0.0          # staleness of the snapshot
        assert g.pop("step_latency_s") > 0.0  # steps ran: last wall time
        assert g == {
            "queue_depth": 0, "num_running": 0, "kv_dtype": "f32",
            "kv_bytes_per_token": eng.pool.kv_bytes_per_token,
            "quant_weights": 0, "tp_degree": 1, "sp_degree": 1,
            "kv_bytes_per_token_per_shard": eng.pool.kv_bytes_per_token,
            "pool_blocks_per_shard": eng.pool.num_blocks,
            "host_tier_max_bytes": 0, "tier_blocks": 0}


class TestDebugSyncOverlap:
    def test_overlapped_twin_is_clean_and_exact(self, tiny_lm, monkeypatch):
        """jax.transfer_guard('disallow') over the whole overlapped loop:
        build, speculative dispatch, and the single bundle fetch are all
        explicit, so the guarded run neither raises nor diverges."""
        model, params = tiny_lm
        ref, _ = _run(model, params, overlap=True, spec="ngram",
                      decode_path="paged")
        monkeypatch.setenv("TNN_DEBUG_SYNC", "1")
        got, eng = _run(model, params, overlap=True, spec="ngram",
                        decode_path="paged")
        assert eng.debug_sync
        assert got == ref
