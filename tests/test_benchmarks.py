"""Benchmark harness smoke tests (quick shapes, CPU-safe): the verification gates
must pass and each bench must produce a result dict."""
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def test_ops_bench_quick():
    from benchmarks import ops_bench

    results = ops_bench.main(["--quick"])
    results = [r for r in results if r]
    names = {r["bench"] for r in results}
    assert {"gemm_bf16", "conv2d_3x3_bf16", "dense_fwd_bwd_bf16"} <= names
    assert all(r["ms"] > 0 for r in results)
    assert any(n.startswith("sdpa_causal") for n in names)


def test_model_bench_quick():
    from benchmarks import model_bench

    results = model_bench.main(["--quick", "--models", "resnet9,decode"])
    results = [r for r in results if r]
    names = {r["bench"] for r in results}
    assert "resnet9_cifar10_train" in names
    assert "gpt2_small_decode" in names
    img = next(r for r in results if r["bench"] == "resnet9_cifar10_train")
    assert img["img_per_s"] > 0 and 0 < img["mfu"] < 2
