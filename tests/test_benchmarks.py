"""Benchmark harness smoke tests (quick shapes, CPU-safe): the verification gates
must pass and each bench must produce a result dict."""
import pytest


@pytest.mark.slow
def test_ops_bench_quick():
    from benchmarks import ops_bench

    results = ops_bench.main(["--quick"])
    results = [r for r in results if r]
    names = {r["bench"] for r in results}
    assert {"gemm_bf16", "conv2d_3x3_bf16", "dense_fwd_bwd_bf16"} <= names
    assert all(r["ms"] > 0 for r in results)
    assert any(n.startswith("sdpa_causal") for n in names)


@pytest.mark.slow
def test_model_bench_quick():
    from benchmarks import model_bench

    results = model_bench.main(["--quick", "--models", "resnet9,decode"])
    results = [r for r in results if r]
    names = {r["bench"] for r in results}
    assert "resnet9_cifar10_train" in names
    assert "gpt2_small_decode" in names
    img = next(r for r in results if r["bench"] == "resnet9_cifar10_train")
    assert img["img_per_s"] > 0 and 0 < img["mfu"] < 2


class TestBenchGateRetry:
    """bench.py is the driver's official perf record; a relay outage must be
    retried for the whole time budget, not abandoned after one probe (rounds
    1-3 all shipped rc=1 gate JSONs for outages shorter than the gate window).
    """

    def _run(self, monkeypatch, capsys, probe_results):
        import bench

        calls = {"n": 0}

        def fake_probe():
            r = probe_results[min(calls["n"], len(probe_results) - 1)]
            calls["n"] += 1
            return r

        monkeypatch.setattr(bench, "probe_backend", fake_probe)
        monkeypatch.setattr(bench.time, "sleep", lambda s: None)
        monkeypatch.setattr(bench, "TOTAL_BUDGET_S", 10_000)
        rc = bench.main()
        return rc, calls["n"], capsys.readouterr().out

    @pytest.mark.parametrize("evidence,want_rc", [
        ("fresh", 0),   # recent committed run: outage gate may vouch for it
        ("stale", 1),   # evidence older than the age cap must NOT read as ok
        (None, 1),      # no evidence at all
    ])
    def test_transient_probe_failure_retries_to_attempt_cap(
            self, monkeypatch, capsys, evidence, want_rc):
        """A relay outage retries to the attempt cap, then exits 0 only IF a
        committed evidence pointer exists AND is fresh (<= EVIDENCE_MAX_AGE_S)
        — a pointer at arbitrarily old numbers must not mask a prolonged
        regression (VERDICT r04 weak #6)."""
        import json
        import time

        import bench

        age = {"fresh": 60.0, "stale": bench.EVIDENCE_MAX_AGE_S + 3600}.get(evidence)
        monkeypatch.setattr(
            bench, "_last_committed",
            lambda: {"value": 1.0, "unix_time": time.time() - age,
                     "file": "x.json"}
            if evidence else None)
        rc, n_probes, out = self._run(
            monkeypatch, capsys,
            [(None, "backend init hung >60s (relay down?)")])
        assert rc == want_rc
        assert n_probes == bench.MAX_ATTEMPTS  # kept trying, not 1-2 probes
        last = json.loads(out.strip().splitlines()[-1])
        assert "error" in last and last["metric"] == bench.METRIC
        assert ("last_committed" in last) == (evidence is not None)
        if evidence:
            assert last["last_committed"]["evidence_age_s"] >= 0
        if evidence == "stale":
            assert "evidence_stale" in last

    def test_deterministic_probe_failure_fails_fast(self, monkeypatch, capsys):
        rc, n_probes, _ = self._run(
            monkeypatch, capsys,
            [(None, "ModuleNotFoundError: no module named jax")])
        assert rc == 1 and n_probes == 1

    def test_budget_exhaustion_stops_retries(self, monkeypatch, capsys):
        import bench

        t = {"now": 0.0}
        monkeypatch.setattr(bench.time, "monotonic", lambda: t["now"])

        def fake_probe():
            t["now"] += 120.0  # each probe burns 2 simulated minutes
            return None, "backend init hung >60s (relay down?)"

        monkeypatch.setattr(bench, "probe_backend", fake_probe)
        monkeypatch.setattr(bench.time, "sleep", lambda s: None)
        monkeypatch.setattr(bench, "_last_committed", lambda: None)
        rc = bench.main()
        assert rc == 1  # transient, but no evidence pointer -> failure rc
        # default budget is >=15 min of retrying (VERDICT r03 follow-up)
        assert bench.TOTAL_BUDGET_S >= 900
        assert "budget" in capsys.readouterr().out


def test_serve_bench_smoke():
    """Fast (tiny random model) serving benchmark: must complete on CPU and
    report TTFT + tokens/sec for BOTH decode paths (standard/paged A/B) plus
    the mixed-load chunked/whole A/B. Deliberately NOT slow-marked — it is
    the tier-1 guard that the serving suite stays runnable."""
    from benchmarks import serve_bench

    results = [r for r in serve_bench.main(["--smoke"]) if r]
    assert len(results) == 15
    assert [r["bench"] for r in results] == ["serve_smoke_standard",
                                             "serve_smoke_paged",
                                             "serve_smoke_mixed_chunked",
                                             "serve_smoke_mixed_whole",
                                             "serve_smoke_prefix_cached",
                                             "serve_smoke_prefix_nocache",
                                             "serve_smoke_spec_off",
                                             "serve_smoke_spec_ngram",
                                             "serve_smoke_spec_draft",
                                             "serve_smoke_load",
                                             "serve_smoke_overlap_off",
                                             "serve_smoke_overlap_on",
                                             "serve_smoke_quant_f32",
                                             "serve_smoke_quant_int8_kv",
                                             "serve_smoke_quant_int8_kv_w8"]
    for r in results[:6]:                   # the latency/parity A/B rows
        assert r["ms"] > 0
        assert r["tok_per_s"] > 0
        assert r["ttft_ms_mean"] > 0
        assert r["ttft_ms_p99"] >= r["ttft_ms_p50"] > 0
        assert r["requests"] == 6
    # the speculative-decoding A/B rows: the off row is the baseline, the
    # ngram row's headline is > 1 verified token per decode-row step on the
    # repetitive workload (token-exactness is gated in tests/test_serving.py)
    off, ngram, draft = results[6:9]
    for r in (off, ngram, draft):
        assert r["ms"] > 0 and r["tok_per_s"] > 0
        assert r["requests"] == 6
        assert r["token_latency_ms_p99"] >= r["token_latency_ms_p50"] > 0
        assert r["compiled_step_signatures"] >= 1
    assert off["spec"] == "off" and off["spec_k"] == 0
    assert off["spec_draft_tokens"] == 0
    assert off["mean_accepted_per_step"] == 0.0
    assert ngram["spec"] == "ngram" and ngram["spec_k"] == 4
    assert ngram["spec_draft_tokens"] > 0
    assert ngram["spec_acceptance_rate"] > 0
    assert ngram["mean_accepted_per_step"] > 1, \
        "self-drafting never beat sequential decode on cyclic prompts"
    assert draft["spec"] == "draft"
    assert draft["spec_draft_tokens"] > 0
    assert draft["mean_accepted_per_step"] >= 1
    # the supervised sustained-load row: goodput at the TTFT SLO plus the
    # resilience counters — the injected engine crash must have tripped
    # exactly the supervisor (restarts >= 1) without leaking a block
    load = results[9]
    assert load["ms"] > 0 and load["req_per_s"] > 0
    assert load["terminal"] == load["requests_total"]
    assert load["finished"] >= 1
    assert 0 <= load["goodput_at_slo"]
    assert load["engine_restarts"] >= 1
    assert load["leaked_blocks"] == 0
    assert load["drain_duration_s"] >= 0
    assert load["shed_requests"] >= 0 and load["rejected"] >= 0
    # regression: the warmup request must never seed the prefix cache with
    # trace-pool prompts — a leaked warmup hit flatters the timed window
    assert load["warmup_prefix_hits"] == 0
    # the A/B is live: chunked really split prompts, whole never did (wall-
    # clock comparisons between the rows stay informational — CI CPU noise)
    chunked = next(r for r in results
                   if r["bench"] == "serve_smoke_mixed_chunked")
    whole = next(r for r in results if r["bench"] == "serve_smoke_mixed_whole")
    assert chunked["prefill_chunks"] >= 3 * 6      # 24-token prompts, chunk 8
    assert whole["prefill_chunks"] == 0
    # the prefix-cache A/B is live: 5 of 6 requests fork the 48-token shared
    # prefix (the first publishes it), the nocache twin recomputes everything
    # — and skipping that prefill must not make first tokens SLOWER
    cached = next(r for r in results
                  if r["bench"] == "serve_smoke_prefix_cached")
    nocache = next(r for r in results
                   if r["bench"] == "serve_smoke_prefix_nocache")
    assert cached["prefill_tokens_saved"] == 5 * 48
    assert cached["prefix_hits"] == 5 and cached["prefix_lookups"] == 6
    assert 0 < cached["prefix_hit_rate"] < 1
    assert nocache["prefill_tokens_saved"] == 0
    assert nocache["prefix_lookups"] == 0
    assert cached["ttft_ms_p50"] <= nocache["ttft_ms_p50"]
    # the engine-loop A/B: the overlapped row's host gap (fetch->next
    # dispatch, the window the chip idles on host bookkeeping) must be
    # strictly below the synchronous row's — that reduction is structural
    # (speculatively adopted steps contribute zero gap), unlike wall clock.
    # tok/s gets the documented informational slack for CI CPU noise.
    ov_off, ov_on = results[10], results[11]
    for r in (ov_off, ov_on):
        assert r["ms"] > 0 and r["tok_per_s"] > 0
        assert r["requests"] == 4 and r["steps"] >= 24
        assert r["token_latency_ms_p99"] >= r["token_latency_ms_p50"] > 0
    assert ov_on["host_gap_ms_mean"] < ov_off["host_gap_ms_mean"], \
        "overlap never closed the fetch->dispatch gap"
    assert ov_on["host_gap_ms_p50"] <= ov_off["host_gap_ms_p50"]
    assert ov_off["overlap_rebuilds"] == 0   # sync loop never speculates
    assert ov_on["tok_per_s"] >= ov_off["tok_per_s"] * 0.85, \
        "overlap-on decode throughput regressed beyond CI noise"
    # the quantized-serving A/B: the capacity contract is exact — int8 pages
    # are EXACTLY half the f32 bytes/token (the scale sidecar is accounted
    # separately) and the hbm-fit concurrency headline must rise with it.
    # tok/s between the variants is informational off-TPU (in-VMEM dequant
    # is the win's mechanism; on CPU it is pure overhead) and gets the same
    # documented CI-noise slack as the other wall-clock comparisons
    qf32, qkv, qw8 = results[12:15]
    assert qf32["kv_dtype"] == "f32" and not qf32["quant_weights"]
    assert qkv["kv_dtype"] == "int8" and not qkv["quant_weights"]
    assert qw8["kv_dtype"] == "int8" and qw8["quant_weights"]
    assert qf32["kv_scale_bytes_per_token"] == 0
    assert qkv["kv_bytes_per_token"] * 2 == qf32["kv_bytes_per_token"]
    assert qkv["kv_scale_bytes_per_token"] > 0
    assert qkv["max_concurrent_at_slo"] > qf32["max_concurrent_at_slo"] > 0
    for r in (qf32, qkv, qw8):
        assert r["ms"] > 0 and r["tok_per_s"] > 0
        assert r["requests"] == 4
        assert r["ttft_ms_p99"] >= r["ttft_ms_p50"] > 0
        # closeness, not exactness: emitted tokens agree with the f32
        # teacher's top-k (measured 0.98/1.0 at this seed; gated with slack)
        assert r["top1_agreement"] >= 0.8
        assert r["topk_agreement"] >= 0.9
        assert abs(r["ppl_delta"]) <= 0.1 * qf32["ppl"]
        assert r["tok_per_s"] >= qf32["tok_per_s"] * 0.7
    assert qf32["ppl_delta"] == 0.0
    # the smoke artifact persisted with the gated/info split: structural
    # fields (bench names, config echoes) under "gated", timing noise under
    # "info" — tests assert only the former, so re-runs don't churn diffs
    import json
    with open(qw8["artifact_path"]) as f:
        art = json.load(f)
    assert [r["bench"] for r in art["gated"]["rows"]] == [
        "serve_smoke_quant_f32", "serve_smoke_quant_int8_kv",
        "serve_smoke_quant_int8_kv_w8"]
    assert art["gated"]["kv_budget_mb"] > 0
    assert "generated" in art["info"] and "platform" in art["info"]
    assert not any("_ms" in k for row in art["gated"]["rows"] for k in row)


@pytest.mark.tp
def test_serve_bench_tp(tp):
    """The --tp A/B is the benchmark-shaped tensor-parallel gate: the same
    up-front greedy batch through the paged engine at tp=1 vs tp=2 on the
    virtual device mesh. bench_tp self-asserts the exactness contract
    (tp streams token-identical to tp=1, zero leaked blocks); here we gate
    the capacity arithmetic — per-chip KV bytes divide EXACTLY by tp and
    the per-chip-budget concurrency headline strictly rises with it — and
    that the persisted artifact re-parses. Tier-1 so TP serving
    regressions fail fast."""
    import json
    import os

    from benchmarks import serve_bench

    results = [r for r in serve_bench.main(["--tp"]) if r]
    assert [r["bench"] for r in results] == ["serve_tp1", "serve_tp2"]
    tp1, tp2 = results
    for r in results:
        assert r["ms"] > 0 and r["tok_per_s"] > 0
        assert r["requests"] == 4
        assert r["ttft_ms_p99"] >= r["ttft_ms_p50"] > 0
        assert r["exact_vs_tp1"] == 1
    assert tp1["tp"] == 1 and tp2["tp"] == tp
    # the capacity contract is exact arithmetic, not a measurement: each
    # shard holds 1/tp of every page, so per-chip residency divides by tp
    # and the requests-per-chip headline rises with it
    assert tp1["kv_bytes_per_token_per_shard"] == \
        tp1["kv_bytes_per_token_total"]
    assert tp2["kv_bytes_per_token_per_shard"] * tp == \
        tp2["kv_bytes_per_token_total"]
    assert tp2["kv_bytes_per_token_total"] == tp1["kv_bytes_per_token_total"]
    assert tp2["max_concurrent_at_slo"] > tp1["max_concurrent_at_slo"] > 0
    # the smoke artifact persisted and re-parses with both rows
    art = tp2["artifact_path"]
    assert os.path.exists(art)
    with open(art) as f:
        payload = json.load(f)
    assert [row["bench"] for row in payload["gated"]["rows"]] == [
        "serve_tp1", "serve_tp2"]
    assert payload["gated"]["devices"] >= 2
    # timing lives in the informational section so re-runs don't churn
    assert "generated" in payload["info"]
    assert not any(k.endswith("_ms") or k == "ms"
                   for row in payload["gated"]["rows"] for k in row)


def test_serve_bench_longctx(sp):
    """The --longctx A/B is the benchmark-shaped sequence-parallel gate:
    the same per-chip KV footprint at sp=1 vs sp=2 vs sp=4 over the
    context mesh. bench_longctx self-asserts the exactness contract
    (short streams token-identical to sp=1, the long-prompt stream
    matching the teacher-forced greedy reference, zero leaked blocks);
    here we gate the capacity arithmetic — max servable context scales
    EXACTLY ~N x while per-chip residency stays flat, and the headline
    long-prompt row serves at sp>1 but is rejected at sp=1 — and that
    the persisted artifact re-parses. Tier-1 so long-context serving
    regressions fail fast."""
    import json
    import os

    import jax

    from benchmarks import serve_bench

    results = [r for r in serve_bench.main(["--longctx"]) if r]
    degrees = [1, 2, 4] if jax.device_count() >= 4 else [1, 2]
    assert [r["bench"] for r in results] == \
        [f"serve_longctx_sp{d}" for d in degrees]
    sp1 = results[0]
    for r, d in zip(results, degrees):
        assert r["ms"] > 0 and r["requests"] == 3
        assert r["sp"] == d
        assert r["exact_vs_sp1"] == 1
        # the capacity contract is exact arithmetic, not a measurement:
        # per-chip pool depth is CONSTANT across rows while the aggregate
        # (minus one scratch block per shard) scales with the mesh
        assert r["blocks_per_chip"] == sp1["blocks_per_chip"]
        assert r["num_blocks"] == d * r["blocks_per_chip"]
        assert r["max_context_blocks"] == d * (r["blocks_per_chip"] - 1)
        assert r["max_context_tokens"] == \
            d * sp1["max_context_tokens"]
        # each shard sweeps an equal 1/sp span of the assembly width —
        # the per-layer page-sweep parallelism behind the prefill win
        assert r["gate_shard_span"] == 1
    # the headline: a prompt whose KV exceeds one chip's pool serves
    # token-exact on the context mesh and fails CLEANLY on one chip
    assert sp1["gate_long_prompt_rejected"] == 1
    for r in results[1:]:
        assert r["gate_long_prompt_exact"] == 1
        assert r["long_prompt_len"] + 4 > sp1["max_context_tokens"]
    # the smoke artifact persisted and re-parses with every row gated
    art = results[-1]["artifact_path"]
    assert os.path.exists(art)
    with open(art) as f:
        payload = json.load(f)
    assert [row["bench"] for row in payload["gated"]["rows"]] == \
        [f"serve_longctx_sp{d}" for d in degrees]
    assert payload["gated"]["devices"] >= 2
    # timing (incl. the long prompt's prefill wall-clock — informational
    # on the one-core virtual mesh) lives in the info section so re-runs
    # don't churn the committed artifact
    assert "generated" in payload["info"]
    assert not any(k.endswith("_ms") or k == "ms"
                   for row in payload["gated"]["rows"] for k in row)


def test_serve_bench_chaos():
    """The --chaos row is the benchmark-shaped fault-tolerance gate: seeded
    pool-alloc failures + NaN logits, asserting every request terminal and
    zero leaked blocks. Tier-1 so robustness regressions fail fast."""
    from benchmarks import serve_bench

    results = [r for r in serve_bench.main(["--chaos"]) if r]
    assert len(results) == 1
    r = results[0]
    assert r["bench"] == "serve_chaos"
    assert r["terminal"] == 8
    assert r["leaked_blocks"] == 0
    assert r["faults_fired"] >= 1
    assert r["finished"] + r["failed"] <= 8
    # the row runs with spec="ngram" + corrupted draft proposals: poisoned
    # drafts must cost acceptance only — every survivor byte-identical to
    # the fault-free spec-off reference (asserted inside bench_chaos too)
    assert r["draft_poison_fired"] >= 1
    assert r["survivors_exact"] == 1


@pytest.mark.slow
def test_serve_bench_straggler():
    """The --straggler A/B is the benchmark-shaped gray-failure gate: the
    same Poisson trace through a 3-replica Router with one persistently
    slow replica, mitigation off (pure JSQ keeps feeding the straggler)
    vs on (TTFT hedging + health-scored ejection + proactive migration).
    bench_straggler self-asserts the contract (exactly one terminal each,
    token-exact streams, hedges within budget, zero leaks, exit-0 drain);
    here we gate the row shapes, that mitigation actually engaged, that
    the mitigated tail strictly beats the unmitigated one, and that the
    persisted artifact re-parses. Tier-1 so gray-failure regressions fail
    fast."""
    import json
    import os

    from benchmarks import serve_bench

    results = [r for r in serve_bench.main(["--straggler"]) if r]
    assert [r["bench"] for r in results] == ["serve_straggler_off",
                                             "serve_straggler_on"]
    off, on = results
    for r in (off, on):
        assert r["ms"] > 0 and r["req_per_s"] > 0
        assert r["requests"] == 10
        assert r["finished"] == 10 and r["terminal"] == 10
        assert r["replicas"] == 3 and r["slow_replica"] == 0
        assert r["ttft_ms_p99"] >= r["ttft_ms_p50"] > 0
        assert r["exact_vs_ref"] == 1  # token-exact even when hedged
    # the unmitigated row proves the off-switches: nothing fires
    assert off["hedges_fired"] == 0 and off["degraded_ejections"] == 0
    assert off["proactive_migrations"] == 0
    # the mitigated row proves the machinery AND the win
    assert (on["hedges_fired"] + on["degraded_ejections"]
            + on["proactive_migrations"]) >= 1
    assert on["hedges_fired"] <= 5          # budget 0.5 x 10 requests
    assert on["hedges_won"] <= on["hedges_fired"]
    assert on["hedges_cancelled"] <= on["hedges_fired"]
    assert on["ttft_ms_p99"] < off["ttft_ms_p99"]
    art = on["artifact_path"]
    assert os.path.exists(art)
    with open(art) as f:
        payload = json.load(f)
    assert [row["bench"] for row in payload["gated"]["rows"]] == [
        "serve_straggler_off", "serve_straggler_on"]


@pytest.mark.slow
def test_serve_bench_spike():
    """The --spike A/B is the benchmark-shaped elasticity gate: the same
    trickle-then-burst trace through a Router of host-tier-enabled
    replicas, pinned at one replica vs under the load-driven autoscaler.
    bench_spike self-asserts the contract (exactly one terminal per
    accepted request, token-exact survivors, zero leaked blocks in device
    pool AND host tier, on-row goodput strictly above the off twin's,
    tier probe strictly above the no-tier baseline); here we gate the row
    shapes, the actuation evidence (scale-ups recorded, timeline moved,
    off row pinned), and that the persisted artifact re-parses. Slow
    lane: two full router runs with per-replica warmups plus the
    deterministic tier probe."""
    import json
    import os

    from benchmarks import serve_bench

    results = [r for r in serve_bench.main(["--spike"]) if r]
    assert [r["bench"] for r in results] == ["serve_spike_off",
                                             "serve_spike_on"]
    off, on = results
    for r in (off, on):
        assert r["ms"] > 0 and r["req_per_s"] > 0
        assert r["requests"] == 24
        assert r["accepted"] + r["rejected"] == 24
        assert r["finished"] == r["accepted"] and r["terminal"] == r["accepted"]
        assert r["ttft_ms_p99"] >= r["ttft_ms_p50"] > 0
        assert r["exact_vs_ref"] == 1   # token-exact even when migrated
        assert r["tier_demotions"] >= 0 and r["tier_hits"] >= 0
    # the off row proves the pin: one replica, no controller action
    assert off["autoscale"] == 0 and off["replicas_max"] == 1
    assert off["scale_ups"] == 0 and off["scale_downs"] == 0
    assert off["replicas_timeline"] == [[0.0, 1]]
    # the on row proves the machinery AND the win
    assert on["autoscale"] == 1 and on["replicas_max"] > 1
    assert on["scale_ups"] >= 1
    assert len(on["replicas_timeline"]) >= 2
    assert on["goodput_at_slo"] > off["goodput_at_slo"]
    # the deterministic host-tier probe: readmissions on a >pool working
    # set, strictly above the no-tier baseline's structural zero
    assert on["tier_probe_hits"] > on["tier_probe_baseline_hits"] == 0
    assert 0 < on["tier_probe_hit_rate"] <= 1
    art = on["artifact_path"]
    assert os.path.exists(art)
    with open(art) as f:
        payload = json.load(f)
    assert [row["bench"] for row in payload["gated"]["rows"]] == [
        "serve_spike_off", "serve_spike_on"]


@pytest.mark.slow
def test_serve_bench_disagg():
    """The --disagg A/B is the benchmark-shaped disaggregation gate: the
    same long+chat mix all-mixed, with prefill/decode roles but
    recompute-resume handoff, and with real KV-block handoff + the fleet
    prefix directory. bench_disagg self-asserts the timing wins (chat
    TTFT p99 and decode-stall p99 improve vs the mixed twin) and both
    deterministic probes (handoff strictly cheaper than recompute on the
    receiver; fleet prefix cache strictly beats the per-replica
    baseline); here we gate the row shapes, the handoff/probe evidence,
    token-exactness, and that the persisted artifact re-parses with
    timing confined to its info section. Slow lane: three full router
    runs plus two probe fleets."""
    import json
    import os

    from benchmarks import serve_bench

    results = [r for r in serve_bench.main(["--disagg"]) if r]
    assert [r["bench"] for r in results] == [
        "serve_disagg_mixed", "serve_disagg_recompute", "serve_disagg_kv"]
    mixed, rc, kv = results
    for r in results:
        assert r["ms"] > 0
        assert r["requests"] == 18 and r["terminal"] == 18
        assert r["n_long"] == 6 and r["n_chat"] == 12
        assert r["exact_vs_ref"] == 1   # token-exact even across handoffs
        assert r["ttft_ms_p99"] >= r["ttft_ms_p50"] > 0
    # the mixed row proves the off-switch: no roles, nothing crosses
    assert mixed["disagg"] == 0 and mixed["boundary_handoffs"] == 0
    assert mixed["handoff_adopted_blocks"] == 0
    # both disaggregated rows actually hand every long over
    for r in (rc, kv):
        assert r["disagg"] == 1 and r["boundary_handoffs"] >= 1
    assert rc["kv_handoff"] == 0 and rc["handoff_adopted_blocks"] == 0
    # the kv row proves the wire path AND the wins (self-asserted gates)
    assert kv["kv_handoff"] == 1 and kv["fleet_prefix"] == 1
    assert kv["handoff_fallbacks"] == 0    # fault-free run never degrades
    assert kv["handoff_adopted_blocks"] > 0
    assert kv["gate_chat_ttft_p99_improved"] == 1
    assert kv["gate_decode_stall_p99_improved"] == 1
    # deterministic handoff probe: adopting beats recomputing
    assert kv["gate_handoff_cheaper"] == 1
    assert (kv["handoff_probe_recv_chunks_kv"]
            < kv["handoff_probe_recv_chunks_recompute"])
    assert kv["handoff_probe_tokens_from_kv"] > 0
    # deterministic fleet-prefix probe: directory pulls raise hits
    assert kv["gate_fleet_hit_rate"] == 1
    assert kv["fleet_probe_hits"] > kv["fleet_probe_baseline_hits"]
    assert kv["fleet_probe_pulls"] >= 1
    art = kv["artifact_path"]
    assert os.path.exists(art)
    with open(art) as f:
        payload = json.load(f)
    assert [row["bench"] for row in payload["gated"]["rows"]] == [
        "serve_disagg_mixed", "serve_disagg_recompute", "serve_disagg_kv"]
    # timing stays in info: a re-run must not churn the gated section
    assert not any(k == "ms" or "_ms" in k
                   for row in payload["gated"]["rows"] for k in row)
    assert "generated" in payload["info"]


def test_write_artifact_gated_info_split(tmp_path):
    """write_artifact splits rows into asserted structure vs timing noise
    and skips the rewrite when nothing structural moved — the contract
    every serve_bench artifact test leans on."""
    import json

    from benchmarks.common import write_artifact

    path = str(tmp_path / "ab.json")
    row = {"bench": "x", "ms": 12.5, "ttft_ms_p99": 3.0, "req_per_s": 8.0,
           "exact_vs_ref": 1, "gate_win": 1, "artifact_path": "self"}
    write_artifact(path, [row], meta={"devices": 1}, label="t")
    with open(path) as f:
        p1 = json.load(f)
    assert p1["gated"]["devices"] == 1
    assert p1["gated"]["rows"] == [
        {"bench": "x", "exact_vs_ref": 1, "gate_win": 1}]
    assert p1["info"]["rows"] == [
        {"ms": 12.5, "ttft_ms_p99": 3.0, "req_per_s": 8.0}]
    # a timing-only change must not rewrite the file (no diff churn)
    write_artifact(path, [dict(row, ms=99.0, ttft_ms_p99=7.0)],
                   meta={"devices": 1}, label="t")
    with open(path) as f:
        assert json.load(f) == p1
    # a structural change does rewrite
    write_artifact(path, [dict(row, exact_vs_ref=0)],
                   meta={"devices": 1}, label="t")
    with open(path) as f:
        p3 = json.load(f)
    assert p3["gated"]["rows"][0]["exact_vs_ref"] == 0
    assert p3["info"]["rows"][0]["ms"] == 12.5  # rewritten wholesale


@pytest.mark.slow
def test_serve_bench_trace():
    """The --trace row is the benchmark-shaped observability gate: a traced
    2-replica Router run that persists the merged Perfetto trace, flight-
    recorder dumps, and a Prometheus scrape under benchmarks/results/.
    bench_trace self-asserts the artifacts exist; here we gate the row
    shape and re-parse the persisted files from their reported paths."""
    import json
    import os

    from benchmarks import serve_bench

    results = [r for r in serve_bench.main(["--trace"]) if r]
    assert len(results) == 1
    r = results[0]
    assert r["bench"] == "serve_trace"
    assert r["replicas"] == 2
    assert r["trace_events"] > 0 and r["trace_tracks"] >= 3
    assert r["flight_dumps"] >= 2          # one drain dump per replica
    assert r["flight_records"] >= 1
    assert r["prometheus_lines"] > 0
    # the persisted artifacts parse from their reported paths
    with open(r["trace_path"]) as f:
        trace = json.load(f)["traceEvents"]
    assert any(e.get("ph") == "X" for e in trace)
    with open(r["metrics_path"]) as f:
        text = f.read()
    assert 'replica="router"' in text and "# TYPE" in text
    assert os.path.getsize(r["trace_path"]) > 0


@pytest.mark.slow
def test_serve_bench_availability():
    """The --avail A/B is the benchmark-shaped failover gate: the same
    Poisson trace through a 2-replica Router, untouched vs one replica
    hard-killed mid-run. bench_availability self-asserts the contract
    (exactly one terminal each, token-exact resumed streams, survivor
    zero-leak, exit-0 drain); here we gate the row shape and that the kill
    really migrated streams. Slow lane: two router runs with per-replica
    engine warmups."""
    from benchmarks import serve_bench

    results = [r for r in serve_bench.main(["--avail"]) if r]
    assert [r["bench"] for r in results] == ["serve_avail_baseline",
                                             "serve_avail_killed"]
    base, killed = results
    for r in (base, killed):
        assert r["ms"] > 0 and r["req_per_s"] > 0
        assert r["requests"] == 10
        assert r["finished"] == 10 and r["terminal"] == 10
        assert r["goodput_at_slo"] >= 0
        assert r["ttft_ms_p99"] >= r["ttft_ms_p50"] > 0
        assert r["exact_vs_ref"] == 1  # token-exact even across a failover
        assert r["replicas"] == 2
    assert base["migrated_requests"] == 0
    assert base["killed_replica"] == -1
    assert base["replicas_healthy"] == 2
    assert killed["migrated_requests"] >= 1
    assert killed["migration_resume_tokens"] >= 1
    assert killed["killed_replica"] in (0, 1)
    assert killed["replicas_healthy"] == 1


@pytest.mark.slow
def test_paged_attention_bench_quick():
    """The paged-vs-gather ops bench must verify and report its speedup
    column (quick sweep; off-TPU the speedup is informational only)."""
    from benchmarks import ops_bench

    results = [r for r in ops_bench.main(["--quick", "--only", "paged"])
               if r]
    assert len(results) == 1
    r = results[0]
    assert r["bench"].startswith("paged_attn_B8_T512")
    assert r["ms"] > 0 and r["gather_baseline_ms"] > 0
    assert r["speedup_vs_gather"] > 0
