"""Tensor-parallel serving: tp=2 must be TOKEN-EXACT against tp=1.

Unlike the int8 lane (closeness-gated), TP changes nothing numerically
except the all-reduce order of two matmul partial sums per layer — on the
fixed-seed tiny model that drift never flips a sampled token, so the gate
here is byte-exactness: every composition that works at tp=1 (both decode
paths, spec decode, prefix cache, the overlapped loop, int8 KV) must emit
identical token streams at tp=2, through staggered arrivals, preemption,
and a mid-run supervisor crash (whose pool reset must purge EVERY shard).

Runs on the conftest's 8-device virtual CPU platform; the ``tp`` fixture
skips on real single-chip hosts.
"""
import numpy as np
import pytest

import jax

from tnn_tpu.serving import (TERMINAL_STATES, EngineSupervisor, FaultPlan,
                             InferenceEngine, RequestState)

pytestmark = pytest.mark.tp

KW = dict(num_blocks=32, block_size=4, max_batch_size=4, max_seq_len=32)


@pytest.fixture(scope="module")
def tiny_lm():
    from tnn_tpu.models.gpt2 import GPT2

    model = GPT2(vocab_size=128, max_len=64, num_layers=2, d_model=32,
                 num_heads=2)
    params = model.init(jax.random.PRNGKey(0), (1, 8))["params"]
    return model, params


def _prompts(n=4, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, 128, int(l)).astype(np.int32)
            for l in rng.integers(5, 14, n)]


def _greedy_ref(model, params, prompt, max_new, max_len):
    from tnn_tpu.models.gpt2 import generate

    return np.asarray(generate(model, params, prompt[None], max_new,
                               max_len=max_len))[0].tolist()


def _run(model, params, prompts, max_new=8, stagger=0, **kw):
    merged = dict(KW)
    merged.update(kw)
    eng = InferenceEngine(model, params, **merged)
    rids = []
    for i, p in enumerate(prompts):
        rids.append(eng.submit(p, max_new))
        if stagger and i % stagger == stagger - 1:
            eng.step()
    out = eng.run_until_complete()
    return eng, [out[r] for r in rids]


def _assert_drained(eng):
    states = {r.rid: r.state for r in eng.requests.values()}
    assert all(s in TERMINAL_STATES for s in states.values()), states
    assert not eng.has_work
    assert eng.pool.num_allocated == 0
    assert eng.pool.num_free + eng.pool.num_evictable == eng.pool.capacity
    eng.check_invariants()


def _shard_devices(eng):
    """The distinct devices actually holding the engine's KV pages."""
    pages = eng.pool.pages_k
    data = pages.data if hasattr(pages, "data") else pages
    return {d for d in data.sharding.device_set}


# -- fail-fast validation -----------------------------------------------------


class TestTPValidation:
    def test_rejects_indivisible_kv_heads(self, tp):
        from tnn_tpu.models.gpt2 import GPT2

        model = GPT2(vocab_size=128, max_len=64, num_layers=1, d_model=48,
                     num_heads=3)
        params = model.init(jax.random.PRNGKey(0), (1, 8))["params"]
        with pytest.raises(ValueError, match="divisible"):
            InferenceEngine(model, params, tp=tp, **KW)

    def test_rejects_tp_over_device_count(self, tiny_lm, tp):
        model, params = tiny_lm
        toomany = jax.device_count() + 1
        with pytest.raises(ValueError, match="device"):
            InferenceEngine(model, params, tp=toomany, **KW)

    def test_rejects_quant_weights(self, tiny_lm, tp):
        model, params = tiny_lm
        with pytest.raises(ValueError, match="quant"):
            InferenceEngine(model, params, tp=tp, quant_weights=True, **KW)

    def test_fused_decode_gated_off(self, tiny_lm, tp):
        """Explicit fused selection errors (like int8); auto falls back."""
        model, params = tiny_lm
        with pytest.raises(ValueError, match="fused"):
            InferenceEngine(model, params, tp=tp, decode_path="fused", **KW)
        eng = InferenceEngine(model, params, tp=tp, decode_path="standard",
                              **KW)
        assert eng._fused is None


# -- exactness: tp=2 == tp=1 == offline reference -----------------------------


class TestTPExactness:
    @pytest.mark.parametrize("path", ["paged", "standard"])
    def test_staggered_parity_both_paths(self, tiny_lm, tp, path):
        """Staggered admission (ragged offsets) on both decode paths:
        tp=2 streams must equal tp=1 streams AND the offline greedy
        reference, token for token."""
        model, params = tiny_lm
        prompts = _prompts(4, seed=5)
        kw = dict(decode_path=path, stagger=2)
        eng1, base = _run(model, params, prompts, **kw)
        eng2, sharded = _run(model, params, prompts, tp=tp, **kw)
        assert sharded == base
        for toks, p in zip(sharded, prompts):
            assert toks == _greedy_ref(model, params, p, 8,
                                       eng2.assembly_len)
        assert eng2.stats()["tp_degree"] == tp
        assert len(_shard_devices(eng2)) == tp
        _assert_drained(eng2)

    def test_full_composition_exact(self, tiny_lm, tp):
        """The whole stack at once — int8 KV + ngram spec decode + prefix
        cache + overlapped loop on the paged path — must match the same
        composition at tp=1 exactly (int8 rounding is identical on every
        shard, so even the closeness-gated lane becomes parity here)."""
        model, params = tiny_lm
        prompts = _prompts(4, seed=7) + _prompts(2, seed=7)[:1]  # a repeat
        kw = dict(decode_path="paged", kv_dtype="int8", spec="ngram",
                  prefix_cache=True, overlap=True)
        eng1, base = _run(model, params, prompts, **kw)
        eng2, sharded = _run(model, params, prompts, tp=tp, **kw)
        assert sharded == base
        assert eng2.stats()["kv_dtype"] == "int8"
        _assert_drained(eng2)

    def test_preemption_parity(self, tiny_lm, tp):
        """A starved pool preempts identically under TP: recompute-requeue
        produces byte-identical output and no shard leaks a block."""
        model, params = tiny_lm
        prompts = _prompts(4, seed=1)
        kw = dict(num_blocks=9, decode_path="paged")
        eng1, base = _run(model, params, prompts, max_new=10, **kw)
        eng2, sharded = _run(model, params, prompts, max_new=10, tp=tp, **kw)
        assert eng2.metrics.preemptions > 0, "pool was never exhausted"
        assert sharded == base
        _assert_drained(eng2)

    def test_sampled_rows_deterministic(self, tiny_lm, tp):
        """Stochastic sampling inside the shard_map body: same seed, same
        tokens as tp=1 (the PRNG key replicates, threefry is elementwise,
        and the logits agree to the last ulp on this model)."""
        model, params = tiny_lm
        p = np.arange(6, dtype=np.int32)

        def run(**kw):
            eng = InferenceEngine(model, params, seed=3, **KW, **kw)
            g = eng.submit(p, 8)
            s = eng.submit(p, 8, temperature=0.9, top_k=16, top_p=0.9)
            out = eng.run_until_complete()
            return eng, out[g], out[s]

        eng1, g1, s1 = run()
        eng2, g2, s2 = run(tp=tp)
        assert g2 == g1 == _greedy_ref(model, params, p, 8,
                                       eng2.assembly_len)
        assert s2 == s1
        assert all(0 <= t < model.vocab_size for t in s2)

    def test_debug_sync_clean(self, tiny_lm, tp, monkeypatch):
        """TNN_DEBUG_SYNC=1 (transfer guard around every step) must stay
        clean under TP: replication onto the mesh is an EXPLICIT device_put,
        never an implicit host round-trip."""
        monkeypatch.setenv("TNN_DEBUG_SYNC", "1")
        model, params = tiny_lm
        prompts = _prompts(3, seed=2)
        eng, out = _run(model, params, prompts, tp=tp, decode_path="paged",
                        spec="ngram", overlap=True)
        for toks, p in zip(out, prompts):
            assert toks == _greedy_ref(model, params, p, 8,
                                       eng.assembly_len)
        _assert_drained(eng)


# -- failure handling ---------------------------------------------------------


class TestTPFailures:
    def test_supervisor_crash_restart_exact(self, tiny_lm, tp):
        """A mid-run engine crash under TP: the supervisor's restart resets
        the pool — the reset must purge EVERY shard's pages (a stale shard
        would poison resumed attention silently) — and the migrated requests
        finish token-exact."""
        model, params = tiny_lm
        plan = FaultPlan(step_crash_calls=(2,))
        eng = InferenceEngine(model, params, tp=tp, faults=plan,
                              decode_path="paged", num_blocks=32,
                              block_size=4, max_batch_size=2, max_seq_len=32)
        events = []
        sup = EngineSupervisor(eng, event_sink=events.append,
                               restart_backoff_s=0.0, max_restarts=2)
        prompts = _prompts(4, seed=9)
        refs = [_greedy_ref(model, params, p, 5, eng.assembly_len)
                for p in prompts]
        rids = [sup.submit(p, 5) for p in prompts]
        sup.run_sync()
        assert sup.restarts == 1
        term = {e["id"]: e for e in events if e["event"] != "token"}
        assert sorted(term) == sorted(rids)
        for rid, ref in zip(rids, refs):
            assert term[rid]["event"] == "done"
            assert term[rid]["tokens"] == ref
        # the reset pool is still head-sharded across all tp devices
        assert len(_shard_devices(eng)) == tp
        _assert_drained(eng)

    def test_chaos_gate_per_shard(self, tiny_lm, tp):
        """The existing chaos gate at tp=2: alloc faults + a NaN row leak
        zero blocks on any shard, survivors match a fault-free TP run."""
        model, params = tiny_lm
        prompts = _prompts(8, seed=6)
        kw = dict(num_blocks=16, block_size=4, max_batch_size=4,
                  max_seq_len=32, decode_path="paged", tp=tp)

        def run(plan=None):
            eng = InferenceEngine(model, params, faults=plan, **kw)
            rids = [eng.submit(p, 8) for p in prompts]
            eng.run_until_complete()
            return eng, rids

        ref_eng, ref_rids = run()
        plan = FaultPlan(seed=9, alloc_fail_prob=0.12, nan_logit_calls=(5,))
        eng, rids = run(plan)
        assert plan.fired["pool.alloc"] >= 1, "chaos never fired — dead test"
        assert all(eng.result(r).state in TERMINAL_STATES for r in rids)
        for rid, ref_rid in zip(rids, ref_rids):
            if eng.result(rid).state is RequestState.FINISHED:
                assert list(eng.requests[rid].out_tokens) == \
                    list(ref_eng.requests[ref_rid].out_tokens)
        _assert_drained(eng)


# -- observability ------------------------------------------------------------


class TestTPObservability:
    def test_gauges_and_exposition(self, tiny_lm, tp):
        model, params = tiny_lm
        eng, _ = _run(model, params, _prompts(2, seed=3), tp=tp,
                      kv_dtype="int8", decode_path="paged")
        s = eng.stats()
        assert s["tp_degree"] == tp
        per_tok = eng.pool.kv_bytes_per_token + \
            eng.pool.kv_scale_bytes_per_token
        assert s["kv_bytes_per_token_per_shard"] == per_tok // tp
        fams = {f["name"]: f for f in eng.metrics.prometheus_series()}
        fam = fams["tnn_serve_tp_degree"]
        assert fam["type"] == "gauge"
        assert fam["samples"][0][-1] == float(tp)
        assert eng.metrics.summary()["tp_degree"] == tp

    def test_health_gauges_expose_tp(self, tiny_lm, tp):
        """The commit-time gauge snapshot (what /healthz serves without
        engine access) carries the TP degree and per-shard KV footprint."""
        model, params = tiny_lm
        eng = InferenceEngine(model, params, tp=tp, **KW)
        sup = EngineSupervisor(eng)
        sup.submit(_prompts(1, seed=4)[0], 6)
        sup.run_sync()
        g = sup.health_gauges()
        assert g["tp_degree"] == tp
        assert g["kv_bytes_per_token_per_shard"] == \
            (eng.pool.kv_bytes_per_token +
             eng.pool.kv_scale_bytes_per_token) // tp

    def test_allreduce_span_traced(self, tiny_lm, tp):
        """With tracing on, TP dispatch wraps the step in a serve.allreduce
        span carrying the degree and per-step all-reduce count."""
        from tnn_tpu.profiling.profiler import Profiler

        model, params = tiny_lm
        prof = Profiler(source="tp-test")
        eng, _ = _run(model, params, _prompts(2, seed=8), tp=tp,
                      profiler=prof, trace=True)
        spans = [e for e in prof.events
                 if e.name.startswith("serve.allreduce")]
        assert spans, "no serve.allreduce span recorded"
        assert f"tp={tp}" in spans[0].name
        assert f"count={2 * model.num_layers}" in spans[0].name
