"""HTTP/SSE front-end tests: raw-socket clients against a real
``ServingServer`` listening on an ephemeral port.

The server runs on its own event-loop thread (as ``run_server`` would run
it), the engine on the supervisor's worker thread, and each test drives a
short-lived client loop via ``asyncio.run`` — so every hop crosses real
thread and socket boundaries, exactly like production.

Engine steps carry a small injected delay (``FaultPlan.step_delay_s``) so
cancellation and disconnect tests have a genuine in-flight window to race
against.
"""
import asyncio
import json
import threading
import time
from types import SimpleNamespace

import numpy as np
import pytest

import jax

from tnn_tpu.serving import (EngineSupervisor, FaultPlan, InferenceEngine,
                             RequestState, ServingServer, SupervisorState)


@pytest.fixture(scope="module")
def tiny_lm():
    from tnn_tpu.models.gpt2 import GPT2

    model = GPT2(vocab_size=128, max_len=64, num_layers=2, d_model=32,
                 num_heads=2)
    params = model.init(jax.random.PRNGKey(0), (1, 8))["params"]
    return model, params


def _greedy_ref(model, params, prompt, max_new, max_len):
    from tnn_tpu.models.gpt2 import generate

    return np.asarray(generate(model, params, prompt[None], max_new,
                               max_len=max_len))[0].tolist()


# -- stack plumbing -----------------------------------------------------------


def _start_stack(model, params, *, plan=None, engine_kw=None, sup_kw=None,
                 server_kw=None):
    ekw = dict(num_blocks=32, block_size=4, max_batch_size=4, max_seq_len=32,
               max_queue_depth=8)
    ekw.update(engine_kw or {})
    eng = InferenceEngine(model, params, faults=plan, **ekw)
    sup = EngineSupervisor(eng, **(sup_kw or {})).start()
    loop = asyncio.new_event_loop()
    thread = threading.Thread(target=loop.run_forever,
                              name="server-loop", daemon=True)
    thread.start()
    srv = ServingServer(sup, port=0, **(server_kw or {}))
    asyncio.run_coroutine_threadsafe(srv.start(), loop).result(timeout=30)
    return SimpleNamespace(eng=eng, sup=sup, srv=srv, loop=loop,
                           thread=thread, port=srv.port)


def _stop_stack(st):
    if not st.sup.finished:
        st.sup.request_drain("test teardown")
    st.sup.join(timeout=120)
    asyncio.run_coroutine_threadsafe(st.srv.stop(1.0),
                                     st.loop).result(timeout=30)
    st.loop.call_soon_threadsafe(st.loop.stop)
    st.thread.join(timeout=10)
    st.loop.close()


@pytest.fixture(scope="module")
def stack(tiny_lm):
    model, params = tiny_lm
    st = _start_stack(model, params,
                      plan=FaultPlan(step_delay_s=0.01))
    yield st
    _stop_stack(st)


# -- raw clients --------------------------------------------------------------


def _request_bytes(method, path, body=None):
    payload = b"" if body is None else (
        body if isinstance(body, bytes) else json.dumps(body).encode())
    return (f"{method} {path} HTTP/1.1\r\nHost: t\r\n"
            f"Content-Type: application/json\r\n"
            f"Content-Length: {len(payload)}\r\n\r\n").encode() + payload


async def _read_head(reader):
    status = int((await reader.readline()).split()[1])
    while (await reader.readline()) not in (b"\r\n", b""):
        pass
    return status


async def _http(port, method, path, body=None):
    """One-shot JSON request; the server closes after each response."""
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    writer.write(_request_bytes(method, path, body))
    await writer.drain()
    status = await _read_head(reader)
    data = await reader.read()
    writer.close()
    try:
        await writer.wait_closed()
    except (ConnectionError, OSError):
        pass
    return status, (json.loads(data) if data else None)


async def _read_sse(reader, limit=10_000):
    """Read SSE events until the terminal one (anything not start/token)."""
    events = []
    for _ in range(limit):
        ln = await reader.readline()
        if not ln:
            break
        if not ln.startswith(b"data: "):
            continue
        ev = json.loads(ln[len(b"data: "):])
        events.append(ev)
        if ev.get("event") not in ("start", "token"):
            break
    return events


async def _open_stream(port, body):
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    writer.write(_request_bytes("POST", "/v1/generate", body))
    await writer.drain()
    status = await _read_head(reader)
    return reader, writer, status


def _poll_state(eng, rid, timeout_s=60.0):
    """Wait for a request to turn terminal (dict/attr reads are GIL-atomic
    enough for a test-side poll; the worker owns all mutation)."""
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout_s:
        req = eng.requests.get(rid)
        if req is not None and req.is_terminal:
            return req
        time.sleep(0.01)
    raise AssertionError(f"request {rid} never reached a terminal state")


async def _http_text(port, method, path):
    """One-shot request returning (status, content-type, raw text body) —
    the /metrics scrape is text exposition, not JSON."""
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    writer.write(_request_bytes(method, path))
    await writer.drain()
    status = int((await reader.readline()).split()[1])
    ctype = ""
    while True:
        ln = await reader.readline()
        if ln in (b"\r\n", b""):
            break
        if ln.lower().startswith(b"content-type:"):
            ctype = ln.split(b":", 1)[1].strip().decode()
    data = await reader.read()
    writer.close()
    try:
        await writer.wait_closed()
    except (ConnectionError, OSError):
        pass
    return status, ctype, data.decode()


def _parse_prometheus(text):
    """Minimal 0.0.4 parser: (types, [(metric, labels, value), ...]).
    Raises on any line that is neither a comment nor a valid sample."""
    types, samples = {}, []
    for ln in text.splitlines():
        if not ln:
            continue
        if ln.startswith("# TYPE "):
            _, _, name, t = ln.split(" ", 3)
            types[name] = t
            continue
        if ln.startswith("#"):
            continue
        metric, value = ln.rsplit(" ", 1)
        labels = {}
        if "{" in metric:
            metric, _, rest = metric.partition("{")
            for pair in rest.rstrip("}").split(","):
                k, _, v = pair.partition("=")
                assert v.startswith('"') and v.endswith('"'), ln
                labels[k] = v.strip('"')
        samples.append((metric, labels, float(value)))
    return types, samples


# -- endpoint behavior --------------------------------------------------------


def test_health_and_stats(stack):
    async def go():
        hs, health = await _http(stack.port, "GET", "/v1/health")
        ss, stats = await _http(stack.port, "GET", "/v1/stats")
        return hs, health, ss, stats

    hs, health, ss, stats = asyncio.run(go())
    assert hs == 200
    assert health["status"] == "running" and not health["draining"]
    assert health["uptime_s"] >= 0
    assert ss == 200
    assert stats["supervisor_state"] == "running"
    assert stats["server_connections"] >= 2
    assert "uptime_s" in stats and "engine_restarts" in stats


def test_stream_generate_token_exact(stack, tiny_lm):
    model, params = tiny_lm
    prompt = list(range(1, 7))
    ref = _greedy_ref(model, params, np.asarray(prompt, np.int32), 5,
                      stack.eng.assembly_len)

    async def go():
        reader, writer, status = await _open_stream(
            stack.port, {"tokens": prompt, "max_new_tokens": 5})
        events = await _read_sse(reader)
        writer.close()
        return status, events

    status, events = asyncio.run(go())
    assert status == 200
    assert events[0]["event"] == "start" and isinstance(events[0]["id"], int)
    toks = [e["token"] for e in events if e["event"] == "token"]
    done = events[-1]
    assert done["event"] == "done"
    assert done["tokens"] == ref == toks
    assert done["finish_reason"] == "length"
    assert done["ttft_ms"] >= 0


def test_nonstream_generate(stack, tiny_lm):
    model, params = tiny_lm
    prompt = list(range(2, 8))
    ref = _greedy_ref(model, params, np.asarray(prompt, np.int32), 4,
                      stack.eng.assembly_len)
    status, body = asyncio.run(_http(
        stack.port, "POST", "/v1/generate",
        {"tokens": prompt, "max_new_tokens": 4, "stream": False}))
    assert status == 200
    assert body["event"] == "done" and body["tokens"] == ref


def test_cancel_endpoint_mid_stream(stack):
    async def go():
        reader, writer, status = await _open_stream(
            stack.port, {"tokens": [3, 4, 5, 6], "max_new_tokens": 25})
        assert status == 200
        start = (await _read_sse(reader, limit=1))[0]
        rid = start["id"]
        cs, cancelled = await _http(stack.port, "POST", "/v1/cancel",
                                    {"id": rid})
        rest = await _read_sse(reader)
        writer.close()
        return cs, cancelled, rest

    cs, cancelled, rest = asyncio.run(go())
    assert cs == 200 and cancelled["cancelled"] is True
    term = rest[-1]
    assert term["event"] == "cancelled"
    assert "cancelled via /v1/cancel" in term["reason"]


def test_cancel_unknown_id_is_benign(stack):
    status, body = asyncio.run(_http(stack.port, "POST", "/v1/cancel",
                                     {"id": 10_000_000}))
    assert status == 200 and body["cancelled"] is False


def test_metrics_scrape_parses(stack):
    """Raw-socket GET /metrics: text exposition 0.0.4 that a Prometheus
    scraper would accept — typed families, cumulative histogram buckets,
    counters that reflect served traffic."""
    async def go():
        # put at least one finished request on the books first
        await _http(stack.port, "POST", "/v1/generate",
                    {"tokens": [5, 6, 7], "max_new_tokens": 3,
                     "stream": False})
        return await _http_text(stack.port, "GET", "/metrics")

    status, ctype, text = asyncio.run(go())
    assert status == 200
    assert ctype == "text/plain; version=0.0.4; charset=utf-8"
    types, samples = _parse_prometheus(text)
    assert types["tnn_serve_ttft_seconds"] == "histogram"
    assert types["tnn_serve_requests_finished_total"] == "counter"
    assert types["tnn_serve_queue_depth"] == "gauge"
    by_name = {}
    for m, lb, v in samples:
        by_name.setdefault(m, []).append((lb, v))
    assert by_name["tnn_serve_requests_finished_total"][0][1] >= 1
    assert by_name["tnn_serve_steps_total"][0][1] >= 1
    # histogram contract: buckets cumulative, +Inf equals _count
    buckets = by_name["tnn_serve_ttft_seconds_bucket"]
    counts = [v for _, v in buckets]
    assert counts == sorted(counts)
    assert buckets[-1][0]["le"] == "+Inf"
    assert buckets[-1][1] == by_name["tnn_serve_ttft_seconds_count"][0][1] >= 1


def test_client_disconnect_cancels_request(stack):
    before = stack.srv.disconnect_cancels

    async def go():
        reader, writer, status = await _open_stream(
            stack.port, {"tokens": [7, 8, 9], "max_new_tokens": 25})
        assert status == 200
        start = (await _read_sse(reader, limit=1))[0]
        # drop the connection mid-stream, ungracefully
        writer.transport.abort()
        return start["id"]

    rid = asyncio.run(go())
    req = _poll_state(stack.eng, rid)
    assert req.state is RequestState.CANCELLED
    assert "client disconnected" in req.error
    t0 = time.monotonic()
    while stack.srv.disconnect_cancels <= before and \
            time.monotonic() - t0 < 10:
        time.sleep(0.01)
    assert stack.srv.disconnect_cancels > before


def test_malformed_payloads_rejected_cleanly(stack):
    """A seeded FaultPlan decides which requests a chaos client corrupts;
    corrupted ones get 400s, clean ones still stream fine — malformed
    input never takes down the server or leaks requests."""
    plan = FaultPlan(seed=3, malformed_request_calls=(1, 3, 4))
    garbage = [b"{not json", json.dumps({"tokens": "abc"}).encode(),
               json.dumps({"prompt": 7}).encode(),
               json.dumps({"nothing": True}).encode()]

    async def go():
        results = []
        g = 0
        for _ in range(6):
            if plan.malformed_request():
                status, body = await _http(
                    stack.port, "POST", "/v1/generate",
                    garbage[g % len(garbage)])
                g += 1
                results.append(("bad", status, body))
            else:
                status, body = await _http(
                    stack.port, "POST", "/v1/generate",
                    {"tokens": [5, 6, 7], "max_new_tokens": 2,
                     "stream": False})
                results.append(("ok", status, body))
        return results

    results = asyncio.run(go())
    kinds = [k for k, _, _ in results]
    assert kinds.count("bad") == 3
    for kind, status, body in results:
        if kind == "bad":
            assert status == 400 and "error" in body
        else:
            assert status == 200 and body["event"] == "done"
    hs, health = asyncio.run(_http(stack.port, "GET", "/v1/health"))
    assert hs == 200, "server unhealthy after malformed traffic"


def test_unknown_route_404(stack):
    status, body = asyncio.run(_http(stack.port, "GET", "/v2/nope"))
    assert status == 404


def test_bad_sampling_param_400(stack):
    status, body = asyncio.run(_http(
        stack.port, "POST", "/v1/generate",
        {"tokens": [1, 2], "temperature": "hot"}))
    assert status == 400 and "temperature" in body["error"]


# -- resilience paths (dedicated stacks) --------------------------------------


def test_metrics_router_labels_after_replica_kill(tiny_lm):
    """/metrics behind a Router front: per-replica series carry a
    ``replica`` label, the router's own series are labeled
    ``replica="router"``, and a hard replica kill leaves the scrape
    parseable with the survivor still reporting."""
    from tnn_tpu.serving import Router

    model, params = tiny_lm
    ekw = dict(num_blocks=32, block_size=4, max_batch_size=4, max_seq_len=32)
    sups = [EngineSupervisor(InferenceEngine(model, params, **ekw))
            for _ in range(2)]
    router = Router(sups, seed=0).start()
    loop = asyncio.new_event_loop()
    thread = threading.Thread(target=loop.run_forever, name="server-loop",
                              daemon=True)
    thread.start()
    srv = ServingServer(router, port=0)
    asyncio.run_coroutine_threadsafe(srv.start(), loop).result(timeout=30)
    try:
        async def go():
            # traffic through both replicas (JSQ spreads 4 over 2)
            await asyncio.gather(*[
                _http(srv.port, "POST", "/v1/generate",
                      {"tokens": [1 + i, 2, 3], "max_new_tokens": 3,
                       "stream": False}) for i in range(4)])
            return await _http_text(srv.port, "GET", "/metrics")

        status, ctype, text = asyncio.run(go())
        assert status == 200
        types, samples = _parse_prometheus(text)
        labels = {lb.get("replica") for _, lb, _ in samples}
        assert {"router", "0", "1"} <= labels
        assert types["tnn_serve_supervisor_restarts"] == "counter"
        done = {lb["replica"]: v for m, lb, v in samples
                if m == "tnn_serve_requests_finished_total"
                and lb.get("replica") in ("0", "1")}
        assert sum(done.values()) >= 4

        router.kill_replica(0)
        status2, _, text2 = asyncio.run(
            _http_text(srv.port, "GET", "/metrics"))
        assert status2 == 200
        _, samples2 = _parse_prometheus(text2)
        labels2 = {lb.get("replica") for _, lb, _ in samples2}
        assert "router" in labels2 and "1" in labels2, \
            "survivor series vanished after the kill"
    finally:
        if not router.finished:
            router.request_drain("test teardown")
        router.join(timeout=120)
        asyncio.run_coroutine_threadsafe(srv.stop(1.0),
                                         loop).result(timeout=30)
        loop.call_soon_threadsafe(loop.stop)
        thread.join(timeout=10)
        loop.close()


def test_read_timeout_408(tiny_lm):
    model, params = tiny_lm
    st = _start_stack(model, params,
                      server_kw=dict(read_timeout_s=0.2))
    try:
        async def go():
            reader, writer = await asyncio.open_connection(
                "127.0.0.1", st.port)
            # say nothing: the server must time the read out, not hang
            status = await asyncio.wait_for(_read_head(reader), 30)
            writer.close()
            return status

        assert asyncio.run(go()) == 408
    finally:
        _stop_stack(st)


def test_stalled_consumer_is_cancelled(tiny_lm):
    """A consumer that stops reading trips write_timeout_s and its request
    is cancelled — a stalled client must not pin KV blocks."""
    model, params = tiny_lm
    st = _start_stack(model, params,
                      plan=FaultPlan(step_delay_s=0.02),
                      server_kw=dict(write_timeout_s=0.2))
    try:
        # simulate a consumer whose socket never drains: every SSE write
        # hangs past write_timeout_s
        async def _never_drains(writer):
            await asyncio.sleep(3600)

        st.srv._drain = _never_drains

        async def client():
            reader, writer, _ = await _open_stream(
                st.port, {"tokens": [1, 2, 3, 4], "max_new_tokens": 25})
            # wait for the server to give up on us, reading nothing
            t0 = time.monotonic()
            while not st.srv.stall_cancels and time.monotonic() - t0 < 60:
                await asyncio.sleep(0.02)
            writer.close()

        asyncio.run(client())
        assert st.srv.stall_cancels >= 1
        rid = max(st.eng.requests)
        req = _poll_state(st.eng, rid)
        assert req.state is RequestState.CANCELLED
        assert "stalled consumer" in req.error
    finally:
        _stop_stack(st)
    assert st.eng.pool.num_allocated == 0
    st.eng.check_invariants()


def test_backpressure_503_rejected(tiny_lm):
    """Overload maps AdmissionRejected to a clean 503 {"rejected": true}
    instead of an error page or a hang."""
    model, params = tiny_lm
    st = _start_stack(model, params,
                      plan=FaultPlan(step_delay_s=0.05),
                      engine_kw=dict(max_queue_depth=1))
    try:
        async def go():
            return await asyncio.gather(*[
                _http(st.port, "POST", "/v1/generate",
                      {"tokens": [1, 2, 3], "max_new_tokens": 8,
                       "stream": False})
                for _ in range(5)])

        results = asyncio.run(go())
        rejected = [b for s, b in results if s == 503]
        served = [b for s, b in results if s == 200]
        assert rejected, "no request was shed under overload"
        assert all(b.get("rejected") for b in rejected)
        assert served, "every request was rejected — no backpressure, just dead"
        assert all(b["event"] == "done" for b in served)
    finally:
        _stop_stack(st)


def test_drain_over_http(tiny_lm):
    """The SIGTERM path as a client sees it: drain starts mid-stream; the
    in-flight stream still completes, new work gets 503 {"draining": true},
    health goes 503, and the supervisor exits 0 with drain_duration_s."""
    model, params = tiny_lm
    st = _start_stack(model, params, plan=FaultPlan(step_delay_s=0.01))
    try:
        async def go():
            reader, writer, status = await _open_stream(
                st.port, {"tokens": [2, 3, 4, 5], "max_new_tokens": 20})
            assert status == 200
            start = (await _read_sse(reader, limit=1))[0]
            # what loop.add_signal_handler does on SIGTERM:
            st.sup.request_drain("SIGTERM received")
            ds, dbody = await _http(st.port, "POST", "/v1/generate",
                                    {"tokens": [1], "stream": False})
            hs, health = await _http(st.port, "GET", "/v1/health")
            rest = await _read_sse(reader)
            writer.close()
            return start, ds, dbody, hs, health, rest

        start, ds, dbody, hs, health, rest = asyncio.run(go())
        assert ds == 503 and dbody["draining"] is True
        assert hs == 503 and health["status"] in ("draining", "stopped")
        assert rest[-1]["event"] == "done", rest[-1]
        assert len(rest[-1]["tokens"]) == 20
        assert st.sup.join(timeout=120)
        assert st.sup.state is SupervisorState.STOPPED
        assert st.sup.exit_code == 0
        assert st.sup.drain_duration_s is not None
        assert st.eng.metrics.summary()["drain_duration_s"] == \
            st.sup.drain_duration_s
    finally:
        _stop_stack(st)
    assert st.eng.pool.num_allocated == 0
    st.eng.check_invariants()
