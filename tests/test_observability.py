"""End-to-end serving observability tests (PR 10).

Four contracts:

* Tracing is FREE of semantic effect: a traced engine (spans + instants
  flowing into a ``Profiler``) produces token-identical output to an
  untraced one, on both decode paths, with speculative decoding and the
  prefix cache on — and stays clean under ``TNN_DEBUG_SYNC=1`` (tracing
  is host-side bookkeeping, never a device sync).
* The crash flight recorder: a bounded ring of per-step records owned by
  the supervisor, dumped as JSONL on crash/drain; the LAST record of a
  crash dump identifies the crashing step's batch.
* ``ServingMetrics`` sample series are bounded (fixed-size reservoir) —
  a week-long serve must not grow per-request lists without bound.
* The Prometheus text exposition parses: HELP/TYPE headers, cumulative
  histogram buckets, labeled per-replica series through the Router.
"""
import json

import numpy as np
import pytest

import jax

from tnn_tpu.profiling.profiler import Profiler
from tnn_tpu.serving import (EngineSupervisor, FaultPlan, InferenceEngine,
                             Router, ServingMetrics, SupervisorState,
                             render_prometheus)
from tnn_tpu.serving.metrics import (EXPOSITION, Reservoir, label_series,
                                     merge_series)
from tnn_tpu.serving.tracing import FlightRecorder, Tracer, span_name

KW = dict(num_blocks=32, block_size=4, max_batch_size=4, max_seq_len=48)


@pytest.fixture(scope="module")
def tiny_lm():
    from tnn_tpu.models.gpt2 import GPT2

    model = GPT2(vocab_size=128, max_len=64, num_layers=2, d_model=32,
                 num_heads=2)
    params = model.init(jax.random.PRNGKey(0), (1, 8))["params"]
    return model, params


def _spec_run(model, params, *, trace, decode_path="auto"):
    """Spec-decode + prefix-cache workload: shared 12-token prefix so the
    second wave forks cached KV, ngram drafting so the mixed step runs the
    verify path — the two features whose step shapes tracing must not
    perturb."""
    eng = InferenceEngine(model, params, spec="ngram", spec_k=3,
                          decode_path=decode_path, trace=trace, **KW)
    rng = np.random.default_rng(11)
    prefix = rng.integers(0, 128, 12).astype(np.int32)
    prompts = [np.concatenate([prefix, rng.integers(0, 128, n).astype(
        np.int32)]) for n in (3, 5, 2, 4)]
    rids = [eng.submit(p, 8) for p in prompts[:2]]
    eng.run_until_complete()                  # publishes the prefix
    rids += [eng.submit(p, 8) for p in prompts[2:]]
    out = eng.run_until_complete()
    assert eng.metrics.prefix_hits >= 1, "workload never hit the cache"
    return [out[r] for r in rids], eng


class TestSpanName:
    def test_attrs_appended_in_order(self):
        assert span_name("serve.step", trace="t3", rid=7, step=12) == \
            "serve.step trace=t3 rid=7 step=12"

    def test_none_attrs_dropped(self):
        assert span_name("serve.step", trace=None, rid=1) == "serve.step rid=1"

    def test_bare_base(self):
        assert span_name("serve.step") == "serve.step"


class TestTracer:
    def test_disabled_without_profiler(self):
        tr = Tracer()
        assert not tr.enabled
        with tr.span("serve.step", rid=1):
            pass
        tr.instant("serve.submit", rid=1)  # no-ops, nothing raised

    def test_span_and_instant_record_events(self):
        prof = Profiler(source="engine")
        tr = Tracer(prof)
        assert tr.enabled
        with tr.span("serve.step", trace="t0", step=1):
            pass
        tr.instant("serve.submit", trace="t0", rid=4)
        names = [ev.name for ev in prof.events]
        assert "serve.step trace=t0 step=1" in names
        assert "serve.submit trace=t0 rid=4" in names
        inst = [ev for ev in prof.events if ev.name.startswith("serve.submit")]
        assert inst[0].duration == 0.0


@pytest.fixture(scope="module")
def spec_ref(tiny_lm):
    """Untraced reference outputs per decode path, computed once — every
    traced run in this module diffs against these (an engine build + spec
    workload is the expensive part of this file; don't repeat it)."""
    cache = {}

    def get(path):
        if path not in cache:
            model, params = tiny_lm
            cache[path] = _spec_run(model, params, trace=False,
                                    decode_path=path)[0]
        return cache[path]

    return get


@pytest.fixture(scope="module")
def flight_run(tiny_lm, tmp_path_factory):
    """One supervised run shared by the flight-recorder and terminal-event
    tests: crash at step 3 (crash dump + migration of both running rids),
    run to completion, then a graceful drain (drain dump)."""
    model, params = tiny_lm
    flight_dir = str(tmp_path_factory.mktemp("flight"))
    plan = FaultPlan(step_crash_calls=(3,))
    eng = InferenceEngine(model, params, faults=plan, **KW)
    events = []
    sup = EngineSupervisor(eng, event_sink=events.append,
                           restart_backoff_s=0.0, max_restarts=2,
                           flight_dir=flight_dir)
    rng = np.random.default_rng(4)
    rids = [sup.submit(rng.integers(0, 128, n).astype(np.int32), 5)
            for n in (5, 6)]
    sup.run_sync()
    sup.request_drain("test")
    sup.run_sync()
    return sup, rids, events


class TestTracedTokenExact:
    # the standard path rides slow: paged is the default/production path
    # and the tier-1 budget is tight; `-m slow` covers the matrix
    @pytest.mark.parametrize("path", [
        pytest.param("standard", marks=pytest.mark.slow), "paged"])
    def test_traced_equals_untraced(self, tiny_lm, spec_ref, path):
        model, params = tiny_lm
        ref = spec_ref(path)
        got, eng = _spec_run(model, params, trace=True, decode_path=path)
        assert got == ref, f"tracing changed tokens on {path} decode"
        # and the trace is real: request-scoped events with trace ids
        names = [ev.name for ev in eng.profiler.events]
        assert any(n.startswith("serve.submit") for n in names)
        assert any(n.startswith("serve.finish") for n in names)
        assert any("trace=t0" in n for n in names)

    def test_traced_clean_under_debug_sync(self, tiny_lm, spec_ref,
                                           monkeypatch):
        """Tracing instants/spans are host-side bookkeeping: a traced step
        under jax.transfer_guard('disallow') neither syncs nor diverges."""
        model, params = tiny_lm
        ref = spec_ref("paged")
        monkeypatch.setenv("TNN_DEBUG_SYNC", "1")
        got, eng = _spec_run(model, params, trace=True, decode_path="paged")
        assert eng.debug_sync
        assert got == ref

    def test_terminal_event_carries_breakdown(self, flight_run):
        sup, rids, events = flight_run
        term = [e for e in events if e["event"] == "done"]
        assert len(term) == len(rids)
        for ev in term:
            assert ev["trace_id"] == f"t{ev['id']}"
            bd = ev["latency_breakdown"]
            assert set(bd) == {"queued_ms", "prefill_ms", "decode_ms",
                               "stalled_ms", "host_gap_ms", "preemptions",
                               "migrations"}
            assert bd["prefill_ms"] > 0 and bd["decode_ms"] > 0
        # both requests were RUNNING at the crash -> both crash-migrated,
        # and the breakdown says so
        assert all(ev["latency_breakdown"]["migrations"] >= 1 for ev in term)


class TestFlightRecorder:
    def test_ring_is_bounded(self):
        rec = FlightRecorder(capacity=4)
        for i in range(10):
            rec.record({"step_seq": i})
        assert len(rec) == 4
        assert [r["step_seq"] for r in rec.records()] == [6, 7, 8, 9]

    def test_dump_schema(self, tmp_path):
        rec = FlightRecorder(capacity=8)
        rec.record({"step_seq": 1, "queued": 0})
        path = rec.dump(str(tmp_path / "f.jsonl"), "drain",
                        extra={"restarts": 0})
        lines = [json.loads(ln) for ln in open(path) if ln.strip()]
        meta = lines[0]
        assert meta["kind"] == "flight_recorder_meta"
        assert meta["reason"] == "drain"
        assert meta["capacity"] == 8 and meta["records"] == 1
        assert meta["total_steps_seen"] == 1 and meta["restarts"] == 0
        assert lines[1]["step_seq"] == 1

    def test_crash_dump_last_record_is_crashing_step(self, flight_run):
        """Under faults.step_crash the supervisor writes a crash dump whose
        final record carries the crashing step's batch (the rids that were
        RUNNING), the crash marker, and the exception text."""
        sup, rids, _ = flight_run
        assert sup.restarts == 1
        crash_dumps = [p for p in sup.flight_dumps if "crash" in p]
        assert len(crash_dumps) == 1
        lines = [json.loads(ln) for ln in open(crash_dumps[0]) if ln.strip()]
        assert lines[0]["kind"] == "flight_recorder_meta"
        assert lines[0]["reason"] == "crash"
        last = lines[-1]
        assert last["crashed"] is True
        assert "EngineCrash" in last["error"]
        assert sorted(last["running_rids"]) == sorted(rids)
        assert last["step_seq"] == 3
        # the crashed step ends the dump — nothing recorded after it
        assert all("crashed" not in ln for ln in lines[1:-1])

    def test_drain_dump_and_step_record_shape(self, flight_run):
        sup, _, _ = flight_run
        assert sup.state is SupervisorState.STOPPED
        drain = [p for p in sup.flight_dumps if "drain" in p]
        assert len(drain) == 1
        lines = [json.loads(ln) for ln in open(drain[0]) if ln.strip()]
        assert len(lines) >= 2
        rec = lines[1]
        for key in ("step_seq", "queued", "running_rids", "programs",
                    "step_latency_s", "pool_allocated", "pool_evictable",
                    "faults_fired"):
            assert key in rec, f"step record lacks {key}"
        prog = rec["programs"][0]
        assert set(prog) == {"kind", "compile_key", "rids", "fill"}

    def test_no_dir_no_dump(self, tiny_lm):
        model, params = tiny_lm
        sup = EngineSupervisor(InferenceEngine(model, params, **KW))
        sup.flight.record({"step_seq": 1})
        assert sup._dump_flight("drain") is None    # flight_dir unset
        assert sup.flight_dumps == []


class TestReservoirCap:
    def test_algorithm_r_bounds_memory(self):
        r = Reservoir("ttft_s", cap=16)
        for i in range(10_000):
            r.append(float(i))
        assert len(r) == 16
        assert r.seen == 10_000
        assert all(0 <= x < 10_000 for x in r)

    def test_deterministic_for_fixed_name(self):
        a, b = Reservoir("x", cap=8), Reservoir("x", cap=8)
        for i in range(1000):
            a.append(float(i)), b.append(float(i))
        assert list(a) == list(b)

    def test_metrics_series_stay_bounded(self):
        """The regression this satellite exists for: per-request sample
        lists must not grow linearly with requests served."""
        m = ServingMetrics(reservoir_size=32)
        for i in range(5000):
            m.observe_ttft(0.001 * i)
            m.observe_decode(num_tokens=1, seconds=0.002, batch_width=1)
            m.observe_queue_wait(0.003)
            m.observe_step_latency(0.004)
        for series in (m.ttft_s, m.token_latency_s, m.queue_wait_s,
                       m.step_latency_s):
            assert len(series) <= 32
        s = m.summary()
        assert s["ttft_ms_p50"] > 0     # percentiles still answer
        # histograms keep EXACT counts even though the reservoir samples
        assert m.histograms["serve.ttft_s"].count == 5000


class TestPrometheusExposition:
    def _parse(self, text):
        """Minimal 0.0.4 parser: returns (helps, types, samples)."""
        helps, types, samples = {}, {}, []
        for ln in text.splitlines():
            if ln.startswith("# HELP "):
                _, _, name, h = ln.split(" ", 3)
                helps[name] = h
            elif ln.startswith("# TYPE "):
                _, _, name, t = ln.split(" ", 3)
                types[name] = t
            elif ln:
                metric, value = ln.rsplit(" ", 1)
                labels = {}
                if "{" in metric:
                    metric, _, rest = metric.partition("{")
                    for pair in rest.rstrip("}").split(","):
                        k, _, v = pair.partition("=")
                        labels[k] = v.strip('"')
                samples.append((metric, labels, float(value)))
        return helps, types, samples

    def test_exposition_parses(self):
        # direct ServingMetrics population: the engine-backed scrape path
        # is tier-1 in tests/test_server.py; this checks the text contract
        m = ServingMetrics()
        for i in range(3):
            m.observe_ttft(0.01 * (i + 1))
            m.observe_step_latency(0.002 * (i + 1))
            m.observe_decode(num_tokens=2, seconds=0.004, batch_width=2)
        m.observe_gauges(queue_depth=2, pool_occupancy=0.5)
        m.finished = 3
        text = render_prometheus(m.prometheus_series())
        helps, types, samples = self._parse(text)
        assert types["tnn_serve_ttft_seconds"] == "histogram"
        assert types["tnn_serve_steps_total"] == "counter"
        assert types["tnn_serve_queue_depth"] == "gauge"
        # every sample's family carries HELP and TYPE headers
        fams = {m.split("{")[0] for m, _, _ in samples}
        for fam in fams:
            base = fam
            for suffix in ("_bucket", "_sum", "_count"):
                if base.endswith(suffix):
                    base = base[: -len(suffix)]
                    break
            assert base in types and base in helps, f"bare series {fam}"
        # histogram contract: cumulative buckets, +Inf == count
        buckets = [(lb, v) for m, lb, v in samples
                   if m == "tnn_serve_step_latency_seconds_bucket"]
        counts = [v for _, v in buckets]
        assert counts == sorted(counts), "buckets must be cumulative"
        assert buckets[-1][0]["le"] == "+Inf"
        count = [v for m, lb, v in samples
                 if m == "tnn_serve_step_latency_seconds_count"][0]
        assert buckets[-1][1] == count > 0

    def test_every_exposition_key_renders(self, tiny_lm):
        """The registry IS the exposition: every registered family appears
        in the rendered text even at zero."""
        text = render_prometheus(ServingMetrics().prometheus_series())
        for name, _, _, _ in EXPOSITION.values():
            assert f"# TYPE {name.removesuffix('_total')}" in text or \
                f"# TYPE {name}" in text, f"{name} missing from exposition"

    def test_label_and_merge_series(self):
        fams = ServingMetrics().prometheus_series()
        a = label_series(fams, {"replica": "0"})
        b = label_series(fams, {"replica": "1"})
        merged = merge_series(a, b)
        names = [f["name"] for f in merged]
        assert len(names) == len(set(names)), "merge must dedupe families"
        one = merged[0]
        replicas = {lbls.get("replica") for _, lbls, _ in one["samples"]}
        assert replicas == {"0", "1"}

    @pytest.mark.slow   # tier-1 twin: test_server's raw-socket router scrape
    def test_router_labels_survive_replica_kill(self, tiny_lm):
        """After a replica dies the exposition still renders, keeps the
        router's own series, and keeps the survivor's labeled series."""
        model, params = tiny_lm
        sups = [EngineSupervisor(InferenceEngine(model, params, **KW))
                for _ in range(2)]
        router = Router(sups, seed=0, profiler=Profiler(source="router"))
        term = []
        for i in range(4):
            router.submit(np.arange(1, 6, dtype=np.int32) + i, 4,
                          listener=lambda ev: (
                              term.append(ev) if ev["event"] != "token"
                              else None))
        router.run_sync(max_rounds=500)
        assert len(term) == 4
        router.kill_replica(0)
        router.pump(5)
        text = render_prometheus(router.prometheus_series())
        helps, types, samples = self._parse(text)
        labels = {lb.get("replica") for _, lb, _ in samples}
        assert "router" in labels and "1" in labels
        # supervisor-level families present under the replica label
        assert any(m == "tnn_serve_supervisor_restarts" for m, _, _ in
                   samples)
