"""Native host runtime (libtnn_host.so) vs pure-Python differential tests.

The test pattern mirrors the reference's benchmark-with-verification harness
(benchmarks/gemm_benchmark.cpp:20-33): every native path is cross-checked against
the numpy reference before it is trusted.
"""
import os

import numpy as np
import pytest

from tnn_tpu import native

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native runtime not built")

from tnn_tpu.native import api  # noqa: E402


class TestGather:
    def test_gather_f32_matches_numpy(self):
        src = np.random.default_rng(0).standard_normal((200, 5, 7)).astype(np.float32)
        idx = np.array([0, 199, 17, 17, 3])
        np.testing.assert_array_equal(api.gather_rows(src, idx), src[idx])

    def test_gather_u8_matches_numpy(self):
        src = np.random.default_rng(1).integers(0, 256, (64, 31), dtype=np.uint8)
        idx = np.arange(63, -1, -1)
        np.testing.assert_array_equal(api.gather_rows(src, idx), src[idx])

    def test_gather_normalize_matches_formula(self):
        src = np.random.default_rng(2).integers(0, 256, (40, 8, 8, 3), dtype=np.uint8)
        idx = np.array([1, 39, 20])
        mean = np.array([0.48, 0.45, 0.40], np.float32)
        std = np.array([0.22, 0.23, 0.24], np.float32)
        got = api.gather_normalize(src, idx, mean, std)
        ref = (src[idx].astype(np.float32) / 255.0 - mean) / std
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)

    def test_gather_normalize_scale_only(self):
        src = np.random.default_rng(3).integers(0, 256, (10, 28, 28, 1), dtype=np.uint8)
        got = api.gather_normalize(src, np.array([4]))
        np.testing.assert_allclose(got, src[[4]].astype(np.float32) / 255.0,
                                   rtol=1e-6)

    def test_epoch_permutation(self):
        a = api.epoch_permutation(500, 7)
        b = api.epoch_permutation(500, 7)
        c = api.epoch_permutation(500, 8)
        np.testing.assert_array_equal(a, b)
        assert not np.array_equal(a, c)
        assert sorted(a.tolist()) == list(range(500))


class TestParsers:
    def test_mnist_csv_matches_python(self, tmp_path):
        rs = np.random.default_rng(4)
        imgs = rs.integers(0, 256, (12, 784))
        labels = rs.integers(0, 10, 12)
        p = tmp_path / "m.csv"
        with open(p, "w") as f:
            f.write("label," + ",".join(f"px{i}" for i in range(784)) + "\n")
            for lab, row in zip(labels, imgs):
                f.write(f"{lab}," + ",".join(map(str, row)) + "\n")
        gi, gl = api.mnist_csv(str(p), header=True)
        np.testing.assert_array_equal(gi, imgs.astype(np.uint8))
        np.testing.assert_array_equal(gl, labels.astype(np.int32))
        # loader-level equivalence vs the numpy fallback
        from tnn_tpu.data.datasets import load_mnist_csv

        raw = np.loadtxt(p, delimiter=",", skiprows=1, dtype=np.float32)
        ref = (raw[:, 1:] / 255.0).reshape(-1, 28, 28, 1)
        data, labs = load_mnist_csv(str(p))
        np.testing.assert_allclose(data, ref, rtol=1e-6)
        np.testing.assert_array_equal(labs, raw[:, 0].astype(np.int32))

    def test_mnist_csv_malformed_raises(self, tmp_path):
        p = tmp_path / "bad.csv"
        p.write_text("1,2,3\n")  # wrong field count
        with pytest.raises(ValueError, match="malformed"):
            api.mnist_csv(str(p), header=False)

    def test_cifar10_matches_python(self, tmp_path):
        rs = np.random.default_rng(5)
        n = 6
        recs = rs.integers(0, 256, (n, 1 + 3072), dtype=np.uint8)
        p = tmp_path / "data_batch_1.bin"
        recs.tofile(p)
        gi, gl = api.cifar10(str(p))
        ref_imgs = recs[:, 1:].reshape(n, 3, 32, 32).transpose(0, 2, 3, 1)
        np.testing.assert_array_equal(gi, ref_imgs)
        np.testing.assert_array_equal(gl, recs[:, 0].astype(np.int32))

    def test_cifar100_matches_python(self, tmp_path):
        rs = np.random.default_rng(6)
        n = 4
        recs = rs.integers(0, 256, (n, 2 + 3072), dtype=np.uint8)
        p = tmp_path / "train.bin"
        recs.tofile(p)
        gi, coarse, fine = api.cifar100(str(p))
        np.testing.assert_array_equal(coarse, recs[:, 0].astype(np.int32))
        np.testing.assert_array_equal(fine, recs[:, 1].astype(np.int32))
        ref_imgs = recs[:, 2:].reshape(n, 3, 32, 32).transpose(0, 2, 3, 1)
        np.testing.assert_array_equal(gi, ref_imgs)


class TestTokenFile:
    def test_windows_match_memmap(self, tmp_path):
        rs = np.random.default_rng(7)
        toks = rs.integers(0, 50257, 5000).astype(np.uint16)
        p = tmp_path / "t.bin"
        toks.tofile(p)
        tf = api.TokenFile(str(p))
        assert len(tf) == 5000
        offs = np.array([0, 1, 4000])
        got = tf.windows(offs, 129)
        for i, o in enumerate(offs):
            np.testing.assert_array_equal(got[i], toks[o:o + 129].astype(np.int32))
        tf.close()

    def test_loader_uses_native_and_matches(self, tmp_path):
        from tnn_tpu.data.token_stream import TokenStreamDataLoader

        rs = np.random.default_rng(8)
        toks = rs.integers(0, 1000, 300).astype(np.uint16)
        p = tmp_path / "t.bin"
        toks.tofile(p)
        dl = TokenStreamDataLoader(str(p), context_length=16)
        assert dl._native_tokens is not None
        data, labels = dl._get(np.array([0, 5]))
        np.testing.assert_array_equal(data[0], toks[0:16].astype(np.int32))
        np.testing.assert_array_equal(labels[1], toks[6:22].astype(np.int32))


def _train_tiny_bpe(corpus: str, num_merges: int):
    """Minimal BPE trainer producing a GPT-2-style merge-order vocab: 256 byte
    tokens, then merged tokens appended in merge order (id order == rank order,
    the property both BPE implementations rely on), then <|endoftext|>."""
    vocab = [bytes([i]) for i in range(256)]
    words = [[bytes([b]) for b in w.encode()] for w in corpus.split()]
    for _ in range(num_merges):
        counts = {}
        for w in words:
            for a, b in zip(w, w[1:]):
                counts[(a, b)] = counts.get((a, b), 0) + 1
        if not counts:
            break
        (a, b) = max(counts, key=lambda k: (counts[k], k))
        merged = a + b
        vocab.append(merged)
        for w in words:
            i = 0
            while i < len(w) - 1:
                if w[i] == a and w[i + 1] == b:
                    w[i:i + 2] = [merged]
                else:
                    i += 1
    vocab.append(b"<|endoftext|>")
    return vocab


class TestBpeTokenizer:
    @pytest.fixture(scope="class")
    def tokenizers(self, tmp_path_factory):
        from tnn_tpu.data.tokenizer import Tokenizer

        corpus = ("the quick brown fox jumps over the lazy dog "
                  "hello world this is a test of byte pair encoding "
                  "numbers 123 456 and punctuation !!! ... don't it's") * 3
        py = Tokenizer()
        py._vocab = _train_tiny_bpe(corpus, 120)
        py._build_encoder()
        vp = tmp_path_factory.mktemp("bpe") / "vocab.bin"
        py.save(str(vp))
        nat = api.BpeTokenizer(str(vp))
        return py, nat

    SAMPLES = [
        "the quick brown fox",
        "hello world!",
        "don't it's we'll I'm you've they'd",
        "  spaces   everywhere  ",
        "numbers 123 999 007",
        "tabs\tand\nnewlines\r\n",
        "unicode: café 北京 здравствуйте",
        "emoji 🚀 mixed with text",
        "a<|endoftext|>b",
        " <|endoftext|> x",
        "trail  <|endoftext|>",
        "",
        " ",
        "'",
        "unknown zzzqqq xyzzy",
        "MixedCase UPPER lower_snake",
    ]

    def test_metadata(self, tokenizers):
        py, nat = tokenizers
        assert nat.vocab_size == py.vocab_size
        assert nat.eot_token == py.eot_token

    def test_encode_matches_python(self, tokenizers):
        py, nat = tokenizers
        for s in self.SAMPLES:
            assert nat.encode(s).tolist() == py.encode(s), repr(s)

    def test_decode_matches_python_and_roundtrips(self, tokenizers):
        py, nat = tokenizers
        for s in self.SAMPLES:
            ids = py.encode(s)
            assert nat.decode(ids) == py.decode(ids)
        txt = "round trip of don't  stop 123!"
        assert nat.decode(nat.encode(txt)) == txt

    def test_long_text(self, tokenizers):
        import random
        import string

        py, nat = tokenizers
        random.seed(0)
        text = " ".join(
            "".join(random.choices(string.ascii_letters + string.digits + " .,!?'",
                                   k=random.randint(1, 12)))
            for _ in range(500))
        assert nat.encode(text).tolist() == py.encode(text)

    def test_out_of_range_decode(self, tokenizers):
        py, nat = tokenizers
        assert nat.decode_bytes(np.array([10 ** 6], np.int32)) == b"<unk>"


class TestLoaderIntegration:
    def test_array_loader_native_gather_equals_numpy(self):
        from tnn_tpu.data.loader import ArrayDataLoader

        rs = np.random.default_rng(9)
        data = rs.standard_normal((128, 6, 6, 3)).astype(np.float32)
        labels = rs.integers(0, 10, 128).astype(np.int32)
        dl = ArrayDataLoader(data, labels)
        assert dl._native_gather
        idx = rs.integers(0, 128, 32)
        d, lab = dl._get(idx)
        np.testing.assert_array_equal(d, data[idx])
        np.testing.assert_array_equal(lab, labels[idx])


@pytest.mark.skipif(not native.available(), reason="native runtime unavailable")
class TestNativePngDecode:
    """From-spec PNG decoder (native/src/image.cpp) vs PIL ground truth
    (parity: the reference's stb_image decode path)."""

    def test_all_color_types_exact(self, tmp_path):
        from PIL import Image

        from tnn_tpu.native import api

        rng = np.random.default_rng(0)
        paths, refs = [], []
        for i, mode in enumerate(["RGB", "L", "RGBA", "P", "LA"]):
            arr = rng.integers(0, 255, (20, 24, 3), np.uint8)
            im = Image.fromarray(arr).convert(mode)
            p = str(tmp_path / f"{i}_{mode}.png")
            im.save(p)
            paths.append(p)
            refs.append(np.asarray(im.convert("RGB"), np.uint8))
        out, ok = api.decode_png_batch(paths, 20, 24)
        assert ok.all()
        for got, ref in zip(out, refs):
            np.testing.assert_array_equal(got, ref)

    def test_resize_matches_python_bilinear(self, tmp_path):
        from PIL import Image

        from tnn_tpu.data.datasets import _resize_bilinear
        from tnn_tpu.native import api

        rng = np.random.default_rng(1)
        arr = rng.integers(0, 255, (33, 17, 3), np.uint8)
        p = str(tmp_path / "x.png")
        Image.fromarray(arr).save(p)
        out, ok = api.decode_png_batch([p, p], 16, 16)
        assert ok.all()
        ref = _resize_bilinear(arr[None], (16, 16))[0]
        assert np.abs(out[0].astype(int) - ref.astype(int)).max() <= 1

    def test_resize_bilinear_batch_matches_numpy(self):
        """The standalone threaded resize (npy loader path) agrees with the
        numpy reference to within 1 lsb of rounding."""
        from tnn_tpu.data.datasets import _resize_bilinear
        from tnn_tpu.native import api

        rng = np.random.default_rng(3)
        frames = rng.integers(0, 255, (7, 41, 29, 3), np.uint8)
        out = api.resize_bilinear_batch(frames, 24, 16)
        ref = _resize_bilinear(frames, (24, 16))
        assert out.shape == (7, 24, 16, 3)
        assert np.abs(out.astype(int) - ref.astype(int)).max() <= 1
        # identity size: pure memcpy
        same = api.resize_bilinear_batch(frames, 41, 29)
        np.testing.assert_array_equal(same, frames)

    def test_bad_file_falls_back_flag(self, tmp_path):
        from PIL import Image

        from tnn_tpu.native import api

        good = str(tmp_path / "good.png")
        Image.fromarray(np.zeros((8, 8, 3), np.uint8)).save(good)
        bad = str(tmp_path / "bad.png")
        with open(bad, "wb") as f:
            f.write(b"definitely not a png")
        out, ok = api.decode_png_batch([good, bad], 8, 8)
        assert ok[0] and not ok[1]
        assert out[1].sum() == 0  # failed slot zeroed for the fallback

    def test_loader_uses_native_and_matches_pil(self, tmp_path):
        from PIL import Image

        from tnn_tpu.data.datasets import ImageFolderDataLoader

        rng = np.random.default_rng(2)
        for c in range(2):
            d = tmp_path / f"class{c}"
            d.mkdir()
            for i in range(4):
                Image.fromarray(rng.integers(0, 255, (20, 20, 3),
                                             np.uint8)).save(str(d / f"{i}.png"))
        fast = ImageFolderDataLoader(str(tmp_path), image_size=(16, 16))
        assert fast._native_img
        a, la = fast.get_batch(8)
        # ground truth: PIL full-size decode (exact) + our python bilinear
        # (PIL's own BILINEAR downscale is a scaled triangle filter — a
        # different algorithm — so it is not the comparison target)
        from tnn_tpu.data.datasets import _resize_bilinear

        order = fast._order if fast._order is not None else np.arange(8)
        for j in range(8):
            path = fast._items[int(order[j])][1]
            full = np.asarray(Image.open(path).convert("RGB"), np.uint8)
            ref = _resize_bilinear(full[None], (16, 16))[0]
            got = (a[j] * 255.0 + 0.5).astype(np.uint8)
            assert np.abs(got.astype(int) - ref.astype(int)).max() <= 1


@pytest.mark.skipif(not native.available(), reason="native runtime unavailable")
class TestNativeJpegDecode:
    """From-spec baseline JPEG decoder (native/src/jpeg.cpp) vs PIL/libjpeg.

    JPEG decoders legitimately differ by a few counts (IDCT and chroma
    upsampling variants are all spec-conformant); libjpeg agreement within
    mean<1 / max<8 on these fixtures is far tighter than inter-decoder drift.
    """

    def _grad_image(self, h, w, rng):
        y, x = np.mgrid[0:h, 0:w]
        img = np.stack([x * 255 / w, y * 255 / h,
                        (x + y) * 127 / (w + h) + rng.standard_normal((h, w)) * 8],
                       -1)
        return np.clip(img, 0, 255).astype(np.uint8)

    @pytest.mark.parametrize("progressive", [False, True])
    @pytest.mark.parametrize("w,h,sub,mode,q", [
        (64, 64, 0, "RGB", 95),    # 4:4:4
        (128, 128, 1, "RGB", 85),  # 4:2:2
        (97, 53, 2, "RGB", 90),    # 4:2:0, odd dims (partial edge MCUs)
        (64, 64, 2, "L", 90),      # grayscale (PIL writes 2x2 factors)
    ])
    def test_matches_pil(self, tmp_path, w, h, sub, mode, q, progressive):
        from PIL import Image

        from tnn_tpu.native import api

        rng = np.random.default_rng(0)
        img = self._grad_image(h, w, rng)
        pim = Image.fromarray(img if mode == "RGB" else img[:, :, 0], mode)
        p = str(tmp_path / "t.jpg")
        pim.save(p, "JPEG", quality=q, subsampling=sub,
                 progressive=progressive)
        if progressive:  # really SOF2 (T.81 Annex G multi-scan path)
            assert b"\xff\xc2" in open(p, "rb").read()
        ref = np.asarray(Image.open(p).convert("RGB"), np.uint8)
        out, ok = api.decode_image_batch([p], h, w)
        assert ok[0]
        d = np.abs(out[0].astype(int) - ref.astype(int))
        assert d.mean() < 1.0 and d.max() <= 8, (d.mean(), d.max())

    @pytest.mark.parametrize("progressive", [False, True])
    def test_restart_markers(self, tmp_path, progressive):
        from PIL import Image

        from tnn_tpu.native import api

        rng = np.random.default_rng(1)
        img = self._grad_image(80, 96, rng)
        p = str(tmp_path / "r.jpg")
        Image.fromarray(img).save(p, "JPEG", quality=90, subsampling=2,
                                  restart_marker_blocks=4,
                                  progressive=progressive)
        assert b"\xff\xdd" in open(p, "rb").read()  # DRI present
        ref = np.asarray(Image.open(p).convert("RGB"), np.uint8)
        out, ok = api.decode_image_batch([p], 80, 96)
        assert ok[0]
        d = np.abs(out[0].astype(int) - ref.astype(int))
        assert d.mean() < 1.0 and d.max() <= 8

    def test_truncated_falls_back(self, tmp_path):
        from PIL import Image

        from tnn_tpu.native import api

        img = np.zeros((32, 32, 3), np.uint8)
        p = str(tmp_path / "t.jpg")
        Image.fromarray(img).save(p, "JPEG")
        data = open(p, "rb").read()
        bad = str(tmp_path / "trunc.jpg")
        with open(bad, "wb") as f:
            f.write(data[:40])  # headers cut mid-way
        out, ok = api.decode_image_batch([p, bad], 32, 32)
        assert ok[0] and not ok[1]
        assert out[1].sum() == 0

    def test_loader_uses_native_jpeg(self, tmp_path):
        from PIL import Image

        from tnn_tpu.data.datasets import ImageFolderDataLoader

        rng = np.random.default_rng(2)
        for c in range(2):
            d = tmp_path / f"class{c}"
            d.mkdir()
            for i in range(3):
                Image.fromarray(
                    self._grad_image(24, 24, rng)).save(
                        str(d / f"{i}.JPEG"), "JPEG", quality=92)
        fast = ImageFolderDataLoader(str(tmp_path), image_size=(24, 24))
        assert fast._native_img
        a, la = fast.get_batch(6)
        order = fast._order if fast._order is not None else np.arange(6)
        for j in range(6):
            path = fast._items[int(order[j])][1]
            ref = np.asarray(Image.open(path).convert("RGB"), np.uint8)
            got = (a[j] * 255.0 + 0.5).astype(np.uint8)
            d = np.abs(got.astype(int) - ref.astype(int))
            assert d.mean() < 1.5, d.mean()


    def test_corrupt_input_fuzz_no_crash(self, tmp_path):
        """Truncations, byte flips, and garbage tails of valid baseline and
        progressive files must decode-or-fallback, never crash (verified
        under ASan/UBSan with 240 cases; this keeps a deterministic slice in
        the suite)."""
        from PIL import Image

        from tnn_tpu.native import api

        rng = np.random.default_rng(5)
        img = self._grad_image(40, 48, rng)
        paths = []
        for prog in (False, True):
            base = str(tmp_path / f"s{prog}.jpg")
            Image.fromarray(img).save(base, "JPEG", quality=85, subsampling=2,
                                      progressive=prog)
            data = open(base, "rb").read()
            for i in range(12):
                d = bytearray(data)
                mode = i % 3
                if mode == 0:
                    d = d[:int(rng.integers(2, len(d)))]
                elif mode == 1:
                    for _ in range(4):
                        d[int(rng.integers(len(d)))] = int(rng.integers(256))
                else:
                    d = d[:int(rng.integers(2, len(d)))] + bytes(
                        rng.integers(0, 256, 30, dtype=np.uint8).tolist())
                pth = str(tmp_path / f"f{prog}_{i}.jpg")
                open(pth, "wb").write(bytes(d))
                paths.append(pth)
        out, ok = api.decode_image_batch(paths, 40, 48)  # must not crash
        assert out.shape == (len(paths), 40, 48, 3)
        # corruption this heavy must make SOME decodes fail (else the decoder
        # is accepting garbage and the fallback contract goes untested)
        assert not ok.all()
        for frame, good in zip(out, ok):
            if not good:
                assert frame.sum() == 0  # failed slots zeroed for PIL fallback

    @staticmethod
    def _patch_sof(data, patch):
        """Return data with `patch(payload bytearray)` applied to the first
        SOF0/SOF2 payload (payload starts at the precision byte)."""
        d = bytearray(data)
        i = 2
        while i + 4 <= len(d):
            assert d[i] == 0xFF
            m, seglen = d[i + 1], (d[i + 2] << 8) | d[i + 3]
            if m in (0xC0, 0xC2):
                patch(d, i + 4)
                return bytes(d)
            i += 2 + seglen
        raise AssertionError("no SOF marker found")

    def test_subsampled_luma_falls_back(self, tmp_path):
        """Y at 1x1 with chroma at 2x2 is spec-legal but the fast decoder's
        to_rgb assumes a full-resolution luma plane; such files must be
        rejected (PIL fallback), not OOB-read."""
        from PIL import Image

        from tnn_tpu.native import api

        img = self._grad_image(32, 32, np.random.default_rng(7))
        p = str(tmp_path / "s.jpg")
        Image.fromarray(img).save(p, "JPEG", quality=90, subsampling=0)

        def bump_chroma(d, off):
            # payload: prec, H(2), W(2), ncomp, then (id, hv, tq) per comp
            assert d[off + 5] == 3
            d[off + 7] = 0x11   # Y stays 1x1
            d[off + 10] = 0x22  # Cb 2x2
            d[off + 13] = 0x22  # Cr 2x2

        bad = str(tmp_path / "subluma.jpg")
        open(bad, "wb").write(self._patch_sof(open(p, "rb").read(),
                                              bump_chroma))
        out, ok = api.decode_image_batch([bad], 32, 32)
        assert not ok[0] and out[0].sum() == 0

    def test_oversized_dims_fall_back(self, tmp_path):
        """A corrupt SOF declaring 65535x65535 must be rejected up front
        (multi-GB allocations would otherwise abort a worker thread)."""
        from PIL import Image

        from tnn_tpu.native import api

        img = self._grad_image(16, 16, np.random.default_rng(8))
        p = str(tmp_path / "o.jpg")
        Image.fromarray(img).save(p, "JPEG", quality=90)

        def huge_dims(d, off):
            d[off + 1] = d[off + 2] = d[off + 3] = d[off + 4] = 0xFF

        bad = str(tmp_path / "huge.jpg")
        open(bad, "wb").write(self._patch_sof(open(p, "rb").read(), huge_dims))
        out, ok = api.decode_image_batch([bad], 16, 16)
        assert not ok[0] and out[0].sum() == 0
