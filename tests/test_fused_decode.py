"""Fused decode-stack kernel: equivalence with the unfused quantized decode
path, cache update correctness, and the end-to-end fused_generate loop
(interpret mode — the real-chip rows live in benchmarks/model_bench.py)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tnn_tpu.models.fused_decode import (caches_to_stacked, fused_generate,
                                         pick_chunks, stack_decode_weights)
from tnn_tpu.models.gpt2 import GPT2, generate
from tnn_tpu.nn.quant import quantize_for_decode
from tnn_tpu.ops.pallas.decode_stack import fused_decode_stack


@pytest.fixture(scope="module")
def small():
    model = GPT2(vocab_size=512, max_len=64, num_layers=2, d_model=256,
                 num_heads=4)
    v = model.init(jax.random.PRNGKey(0), (2, 16))
    return model, quantize_for_decode(v["params"])


def test_stack_shapes(small):
    model, qp = small
    s = stack_decode_weights(model, qp)
    d, f, L = 256, 1024, 2
    assert s["qkv_q"].shape == (L, 3 * d, d) and s["qkv_q"].dtype == jnp.int8
    assert s["fc_q"].shape == (L, f, d)
    assert s["proj_q"].shape == (L, d, f)
    assert s["ln1_s"].shape == (L, d) and s["ln1_s"].dtype == jnp.float32
    assert s["qkv_s"].shape == (L, 3 * d)


@pytest.mark.parametrize("chunks", [1, 2])
def test_fused_step_matches_unfused(small, chunks):
    model, qp = small
    B, P, T = 2, 8, 32
    rs = np.random.RandomState(0)
    prompt = jnp.asarray(rs.randint(0, 512, (B, P)).astype(np.int32))
    tok = jnp.asarray(rs.randint(0, 512, (B,)).astype(np.int32))

    caches = model.init_cache(B, T)
    _, caches = model.apply_cached(qp, prompt, caches, 0)

    # unfused reference step
    logits_u, caches_u = model.apply_cached(qp, tok[:, None], caches, P)
    logits_u = np.asarray(logits_u[:, -1], np.float32)

    # fused step (mirrors fused_generate's scan body)
    stacks = stack_decode_weights(model, qp)
    kc, vc = caches_to_stacked(caches)
    x, _ = model.wte.apply({"params": qp["wte"], "state": {}}, tok[:, None])
    x, _ = model.wpe.apply({"params": qp["wpe"], "state": {}}, x, offset=P)
    x_out, kc, vc = fused_decode_stack(
        x[:, 0, :], jnp.asarray(P, jnp.int32), kc, vc, stacks,
        num_heads=model.num_heads, chunks=chunks, interpret=True)
    xf, _ = model.ln_f.apply({"params": qp["ln_f"], "state": {}},
                             x_out[:, None, :])
    logits_f = np.asarray(model._head(qp, xf)[:, -1], np.float32)

    rel = np.max(np.abs(logits_f - logits_u)) / np.max(np.abs(logits_u))
    assert rel < 0.05, rel

    # the appended cache row matches the unfused path's row
    kc_u, vc_u = caches_to_stacked(caches_u)
    for got, want in ((kc, kc_u), (vc, vc_u)):
        got, want = np.asarray(got, np.float32), np.asarray(want, np.float32)
        row_err = (np.max(np.abs(got[:, :, P] - want[:, :, P]))
                   / (np.max(np.abs(want[:, :, P])) + 1e-9))
        assert row_err < 0.05, row_err
        # rows beyond P untouched (still zero-initialized)
        assert np.abs(got[:, :, P + 1:]).max() == 0.0
        # prefix rows bit-identical (the kernel never rewrites them)
        np.testing.assert_array_equal(got[:, :, :P], want[:, :, :P])


def test_fused_generate_matches_logits_teacher_forced(small):
    """Drive fused and unfused decode in lockstep on the SAME token stream and
    compare per-step logits — token-level compare would be flaky (greedy ties
    under quantization noise)."""
    model, qp = small
    B, P, steps, T = 1, 6, 4, 16
    rs = np.random.RandomState(1)
    stream = jnp.asarray(rs.randint(0, 512, (B, P + steps)).astype(np.int32))

    caches = model.init_cache(B, T)
    logits_u, caches = model.apply_cached(qp, stream[:, :P], caches, 0)

    stacks = stack_decode_weights(model, qp)
    kc, vc = caches_to_stacked(caches)
    for i in range(steps):
        tok = stream[:, P + i]
        logits_u, caches = model.apply_cached(qp, tok[:, None], caches, P + i)
        x, _ = model.wte.apply({"params": qp["wte"], "state": {}}, tok[:, None])
        x, _ = model.wpe.apply({"params": qp["wpe"], "state": {}}, x,
                               offset=P + i)
        x_out, kc, vc = fused_decode_stack(
            x[:, 0, :], jnp.asarray(P + i, jnp.int32), kc, vc, stacks,
            num_heads=model.num_heads, chunks=2, interpret=True)
        xf, _ = model.ln_f.apply({"params": qp["ln_f"], "state": {}},
                                 x_out[:, None, :])
        lf = np.asarray(model._head(qp, xf)[:, -1], np.float32)
        lu = np.asarray(logits_u[:, -1], np.float32)
        rel = np.max(np.abs(lf - lu)) / np.max(np.abs(lu))
        assert rel < 0.05, (i, rel)


def test_fused_generate_end_to_end(small):
    model, qp = small
    rs = np.random.RandomState(2)
    prompt = jnp.asarray(rs.randint(0, 512, (2, 8)).astype(np.int32))
    toks = fused_generate(model, qp, prompt, 5, interpret=True)
    assert toks.shape == (2, 5)
    assert ((np.asarray(toks) >= 0) & (np.asarray(toks) < 512)).all()
    # deterministic across calls (greedy, same rng)
    toks2 = fused_generate(model, qp, prompt, 5, interpret=True)
    np.testing.assert_array_equal(np.asarray(toks), np.asarray(toks2))


def test_fused_generate_rejects_float_params(small):
    model, _ = small
    v = model.init(jax.random.PRNGKey(3), (1, 8))
    with pytest.raises(ValueError, match="int8"):
        fused_generate(model, v["params"], jnp.zeros((1, 4), jnp.int32), 2,
                       interpret=True)


def test_fused_rejects_int8_cache_and_gqa_models():
    """Unsupported cache/head configs must fail loudly (callers catch
    ValueError and fall back to the standard generate path) — not feed raw
    int8 codes or mismatched heads into the kernel."""
    m8 = GPT2(vocab_size=128, max_len=32, num_layers=1, d_model=64,
              num_heads=2, kv_cache_dtype="int8")
    v8 = m8.init(jax.random.PRNGKey(0), (1, 8))
    with pytest.raises(ValueError, match="kv_cache_dtype"):
        stack_decode_weights(m8, quantize_for_decode(v8["params"]))
    mg = GPT2(vocab_size=128, max_len=32, num_layers=1, d_model=64,
              num_heads=4, num_kv_heads=2)
    vg = mg.init(jax.random.PRNGKey(0), (1, 8))
    with pytest.raises(ValueError, match="grouped-query"):
        stack_decode_weights(mg, quantize_for_decode(vg["params"]))


def test_pick_chunks():
    # gpt2-small at request-sized cache fits with 2 chunks
    assert pick_chunks(768, 3072, 1, 192) in (1, 2)
    # gpt2-large's qkv block alone busts the budget -> caller must fall back
    assert pick_chunks(1280, 5120, 1, 192) is None
