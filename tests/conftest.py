"""Test config: force an 8-device virtual CPU platform.

This is the TPU analog of the reference's IN_PROCESS endpoint trick
(include/distributed/endpoint.hpp:210, communicator.hpp:51-60): distributed logic is
tested in one process — here on a virtual 8-device mesh — without real hardware.

The dev box exposes a real TPU through a sitecustomize that pre-imports jax, so env vars
alone don't stick; the shared workaround lives in tnn_tpu.utils.platform.
TNN_TEST_PLATFORM overrides for running the suite on hardware.
"""
import os

# XLA compile effort: the suite is compile-bound on its 1-CPU CI host
# (hundreds of tiny-model jit programs, each engine/test rebuilding its
# own), and backend optimization buys nothing for correctness gates —
# parity tests compare two runs under the same flags. O0 halves the
# suite's wall time. Scoped to the forced-CPU test platform; hardware
# runs (TNN_TEST_PLATFORM=tpu) and any operator-provided setting keep
# XLA's defaults.
if os.environ.get("TNN_TEST_PLATFORM", "cpu") == "cpu" and \
        "--xla_backend_optimization_level" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                               " --xla_backend_optimization_level=0").strip()

# repo root reaches sys.path via pyproject's `pythonpath = ["."]` (or an
# editable install); no path munging needed here
from tnn_tpu.utils.platform import force_platform

jax = force_platform(os.environ.get("TNN_TEST_PLATFORM", "cpu"), n_devices=8)

import pytest  # noqa: E402


@pytest.fixture
def rng():
    return jax.random.PRNGKey(0)


@pytest.fixture(autouse=True)
def _force_kernel_interpret(request, monkeypatch):
    """@pytest.mark.kernel tests exercise Pallas kernel BODIES; off-TPU there
    is no Mosaic compiler, so pin interpret mode via the shared runtime knob
    (ops/pallas/runtime.interpret_default) rather than letting each call site
    guess. On real TPU hardware (TNN_TEST_PLATFORM=tpu) the flag is left
    alone and the kernels compile."""
    if request.node.get_closest_marker("kernel") \
            and jax.default_backend() != "tpu":
        monkeypatch.setenv("TNN_PALLAS_INTERPRET", "1")


@pytest.fixture
def tp():
    """Tensor-parallel degree for @pytest.mark.tp tests. The forced 8-device
    virtual platform above already provides the mesh without perturbing the
    O0 XLA flags; on an environment that really has fewer than 2 devices
    (TNN_TEST_PLATFORM=tpu on a single chip) the test skips instead."""
    if jax.device_count() < 2:
        pytest.skip("tensor-parallel tests need >=2 devices")
    return 2


@pytest.fixture
def sp():
    """Sequence-parallel (context mesh) degree for @pytest.mark.sp tests;
    same virtual-platform contract as ``tp``."""
    if jax.device_count() < 2:
        pytest.skip("sequence-parallel tests need >=2 devices")
    return 2


# -- test tiers ---------------------------------------------------------------
# Measured-slow tests (>15s on a 1-CPU host, mostly multi-minute mesh/pipeline
# XLA compiles) are auto-marked so `pytest -m "not slow"` is a fast dev tier;
# scripts/ci.sh still runs everything. Names come from --durations profiling;
# parametrized variants inherit the base name's mark.
_SLOW_TESTS = {
    "test_ulysses_grads_match_ring", "test_ring_attention_grads",
    "test_hetero_pipeline_wrn_family", "test_config_driven_seq_parallel_gpt",
    "test_dp_run_profiles_and_save", "test_hetero_pipeline_matches_grad_accum",
    "test_gpt2_cached_generate_matches_uncached", "test_augment_in_step",
    "test_hetero_pipeline_moe_aux_loss_flows",
    "test_stage_pipeline_batchnorm_matches_grad_accum",
    "test_hetero_pipeline_interleaved_matches_grad_accum",
    "test_gpt2_learns_real_bytes", "test_stage_pipeline_trains",
    "test_hetero_pipeline_composes_with_data_axis",
    "test_config_driven_pipeline_and_tp",
    "test_interleaved_pipeline_differentiable",
    "test_resume_continues_step_count",
    "test_expert_parallel_sharding_matches_replicated",
    "test_spmd_pipeline_differentiable", "test_moe_gpt2_trains_and_decodes",
    "test_config_file_and_resume", "test_fused_step_matches_unfused",
    "test_mid_epoch_resume_continues_cursor",
    "test_tp_sharding_rules", "test_train_step_fused_head_matches_standard",
    "test_sort_dispatch_matches_einsum",
    "test_fused_generate_matches_logits_teacher_forced",
    "test_resnet18_trains_one_step", "test_mesh_axes_dp_matches_single_device",
    "test_topk_routing_and_capacity",
    "test_worker_death_detected_and_rank_rejoins",
    "test_logits_close_and_top1_agrees",
    "test_loss_decreases_and_checkpoints",
    "test_nested_blocks_config_roundtrip", "test_wrn16_8_param_count",
    "test_gpt2_param_count_small",
    "test_serve_bench_smoke", "test_serve_bench_chaos",
    "test_tp_llama_matches_single_device",
    # TP-serving composition/failure tests: each builds several tp=2
    # shard_map engines (multi-second compiles on the 1-CPU host); the
    # cheap TP gates — tp=2 vs tp=1 parity on both decode paths,
    # validation, observability, the serve_bench --tp capacity gate —
    # stay tier-1, these deeper compositions ride the full CI tier to
    # keep tier-1 inside its 870 s budget
    "test_full_composition_exact", "test_preemption_parity",
    "test_sampled_rows_deterministic", "test_debug_sync_clean",
    "test_supervisor_crash_restart_exact", "test_chaos_gate_per_shard",
    # disaggregation: the composed-chaos PR gate runs two full 3-replica
    # fleets per decode path (~25 s each); the per-mechanism handoff
    # tests (boundary exactness, corrupt/slow/pressure degradation,
    # receiver death, fleet pulls) stay tier-1
    "test_disagg_composed_chaos_token_exact", "test_serve_bench_disagg",
}


# class-qualified entries for generic names that would otherwise collide
# with fast tests of the same name elsewhere in the suite
_SLOW_QUALIFIED = {"TestInferencer::test_round_trip"}


def pytest_collection_modifyitems(config, items):
    for item in items:
        base = item.nodeid.split("[")[0]
        if base.rsplit("::", 1)[-1] in _SLOW_TESTS \
                or any(base.endswith(q) for q in _SLOW_QUALIFIED):
            item.add_marker(pytest.mark.slow)
