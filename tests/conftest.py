"""Test config: force an 8-device virtual CPU platform.

This is the TPU analog of the reference's IN_PROCESS endpoint trick
(include/distributed/endpoint.hpp:210, communicator.hpp:51-60): distributed logic is
tested in one process — here on a virtual 8-device mesh — without real hardware.

The dev box exposes a real TPU through a sitecustomize that pre-imports jax, so env vars
alone don't stick; jax.config.update after import is required. TNN_TEST_PLATFORM
overrides for running the suite on hardware.
"""
import os

_platform = os.environ.get("TNN_TEST_PLATFORM", "cpu")
os.environ["JAX_PLATFORMS"] = _platform
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", _platform)

import pytest  # noqa: E402


@pytest.fixture
def rng():
    return jax.random.PRNGKey(0)
