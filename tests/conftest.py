"""Test config: force an 8-device virtual CPU platform.

This is the TPU analog of the reference's IN_PROCESS endpoint trick
(include/distributed/endpoint.hpp:210, communicator.hpp:51-60): distributed logic is
tested in one process — here on a virtual 8-device mesh — without real hardware.

The dev box exposes a real TPU through a sitecustomize that pre-imports jax, so env vars
alone don't stick; the shared workaround lives in tnn_tpu.utils.platform.
TNN_TEST_PLATFORM overrides for running the suite on hardware.
"""
import os

# repo root reaches sys.path via pyproject's `pythonpath = ["."]` (or an
# editable install); no path munging needed here
from tnn_tpu.utils.platform import force_platform

jax = force_platform(os.environ.get("TNN_TEST_PLATFORM", "cpu"), n_devices=8)

import pytest  # noqa: E402


@pytest.fixture
def rng():
    return jax.random.PRNGKey(0)
