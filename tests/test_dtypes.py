"""Dtype system tests (parity: reference bf16_test.cpp / fp16_test.cpp intent —
here bf16 is hardware-native so tests cover policy/cast semantics, not bit emulation)."""
import jax.numpy as jnp
import pytest

from tnn_tpu.core import dtypes as dt


def test_canonical_names():
    assert dt.canonical_name("f32") == "float32"
    assert dt.canonical_name("bf16") == "bfloat16"
    assert dt.canonical_name(jnp.float32) == "float32"
    assert dt.canonical_name(jnp.bfloat16) == "bfloat16"
    with pytest.raises(ValueError):
        dt.canonical_name("not_a_dtype")


def test_sizes():
    assert dt.size_of("float32") == 4
    assert dt.size_of("bfloat16") == 2
    assert dt.size_of("int8") == 1
    assert dt.size_of("float64") == 8


def test_policy_roundtrip():
    p = dt.DTypePolicy(io="bf16", param="f32", compute="bf16")
    cfg = p.to_config()
    p2 = dt.DTypePolicy.from_config(cfg)
    assert p == p2
    assert p2.compute_dtype == jnp.bfloat16


def test_policy_casts():
    p = dt.MIXED_BF16
    x = jnp.ones((4,), jnp.float32)
    assert p.cast_in(x).dtype == jnp.bfloat16
    ids = jnp.ones((4,), jnp.int32)
    assert p.cast_in(ids).dtype == jnp.int32  # ints pass through


def test_epsilon_ordering():
    assert dt.epsilon("float64") < dt.epsilon("float32") < dt.epsilon("bfloat16")
