"""Parallelism tests on the virtual 8-device CPU mesh — the TPU analog of the
reference's IN_PROCESS single-process distributed tests (SURVEY.md §4)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tnn_tpu import models, nn, parallel
from tnn_tpu.core import dtypes as dt
from tnn_tpu.nn import losses
from tnn_tpu.train import TrainState, create_train_state, make_train_step

F32 = dt.FP32

pytestmark = pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 devices")


def _mlp():
    return nn.Sequential([
        nn.Dense(32, activation="relu", policy=F32),
        nn.Dense(32, activation="relu", policy=F32),
        nn.Dense(4, policy=F32),
    ], policy=F32)


# -- mesh --------------------------------------------------------------------

def test_make_mesh_axes():
    mesh = parallel.make_mesh(data=2, pipe=4)
    assert mesh.shape["data"] == 2 and mesh.shape["pipe"] == 4
    assert parallel.mesh.axis_size(mesh, "model") == 1
    with pytest.raises(ValueError):
        parallel.make_mesh(data=16, pipe=2)


# -- data parallel -----------------------------------------------------------

def test_dp_matches_single_device(rng):
    """DP over 8 devices must be numerically identical to single-device training."""
    model = _mlp()
    opt = nn.SGD(lr=0.1)
    x = jnp.asarray(np.random.RandomState(0).randn(16, 8), jnp.float32)
    y = jnp.asarray(np.random.RandomState(1).randint(0, 4, 16), jnp.int32)

    state1 = create_train_state(model, opt, rng, (16, 8), input_dtype=jnp.float32)
    step1 = make_train_step(model, opt, donate=False)
    state1, m1 = step1(state1, x, y)

    mesh = parallel.make_mesh(data=8)
    state2 = create_train_state(model, opt, rng, (16, 8), input_dtype=jnp.float32)
    step, place_state, place_batch = parallel.make_dp_train_step(model, opt, mesh,
                                                                donate=False)
    state2 = place_state(state2)
    xd, yd = place_batch(x, y)
    state2, m2 = step(state2, xd, yd)

    np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(state1.params),
                    jax.tree_util.tree_leaves(state2.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)


def test_fsdp_shards_large_params(rng):
    mesh = parallel.make_mesh(data=2, fsdp=4)
    model = nn.Sequential([nn.Dense(512, policy=F32), nn.Dense(512, policy=F32)],
                          policy=F32)
    opt = nn.Adam(lr=1e-3)
    state = create_train_state(model, opt, rng, (8, 512), input_dtype=jnp.float32)
    step, place_state, place_batch = parallel.make_dp_train_step(model, opt, mesh,
                                                                fsdp=True, donate=False)
    state = place_state(state)
    kern = state.params["00_dense"]["kernel"]
    # 512x512 f32 = 1MB > min_size -> sharded over fsdp
    assert "fsdp" in str(kern.sharding.spec)
    x = jnp.asarray(np.random.RandomState(0).randn(8, 512), jnp.float32)
    y = jnp.asarray(np.random.RandomState(1).randint(0, 512, 8), jnp.int32)
    xd, yd = place_batch(x, y)
    state, m = step(state, xd, yd)
    assert np.isfinite(float(m["loss"]))


# -- partitioner -------------------------------------------------------------

def test_partitioner_balanced(rng):
    """Parity: partitioner_test.cpp intent — build a model, assert stage boundaries."""
    model = models.create("cifar100_wrn16_8", policy=F32)
    parts = parallel.balanced_partitions(model, 2, (8, 32, 32, 3))
    assert len(parts) == 2
    assert parts[0].start == 0
    assert parts[0].length + parts[1].length == len(model.children)
    # stages rebuild through configs and chain correctly
    stages = parallel.split(model, parts)
    shape = (2, 32, 32, 3)
    v0 = stages[0].init(rng, shape, input_dtype=jnp.float32)
    x = jnp.zeros(shape, jnp.float32)
    h = stages[0](v0, x)
    v1 = stages[1].init(rng, h.shape, input_dtype=h.dtype)
    out = stages[1](v1, h)
    assert out.shape == (2, 100)


def test_partitioner_uniform():
    model = _mlp()
    parts = parallel.partitioner.proportional_partitions(3, [1, 1, 1])
    assert [p.length for p in parts] == [1, 1, 1]


# -- spmd pipeline -----------------------------------------------------------

def test_spmd_pipeline_matches_sequential(rng):
    """Pipelined stack of identical blocks == running them sequentially."""
    mesh = parallel.make_mesh(pipe=4)
    d = 16
    layer = nn.Dense(d, activation="tanh", policy=F32)
    keys = jax.random.split(rng, 4)
    per_stage = [layer.init(k, (2, d))["params"] for k in keys]
    stacked = parallel.stack_stage_params(per_stage)

    def block_fn(params, x):
        return layer({"params": params, "state": {}}, x)

    num_mb, mb = 6, 2
    x = jnp.asarray(np.random.RandomState(0).randn(num_mb, mb, d), jnp.float32)
    out = parallel.spmd_pipeline(block_fn, stacked, x, mesh)
    assert out.shape == (num_mb, mb, d)

    # sequential reference
    ref = []
    for i in range(num_mb):
        h = x[i]
        for p in per_stage:
            h = block_fn(p, h)
        ref.append(h)
    np.testing.assert_allclose(np.asarray(out), np.asarray(jnp.stack(ref)),
                               rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("virtual,num_mb", [(2, 4), (3, 8)])
def test_interleaved_pipeline_matches_sequential(virtual, num_mb, rng):
    """Megatron-style interleaved schedule (v virtual stages per device) ==
    sequential application of all v*pp stages, for every microbatch."""
    pp, d = 4, 16
    mesh = parallel.make_mesh(pipe=pp)
    L = virtual * pp
    layer = nn.Dense(d, activation="tanh", policy=F32)
    keys = jax.random.split(rng, L)
    per_stage = [layer.init(k, (2, d))["params"] for k in keys]
    stacked = parallel.stack_stage_params(per_stage)

    def block_fn(params, x):
        return layer({"params": params, "state": {}}, x)

    mb = 2
    x = jnp.asarray(np.random.RandomState(0).randn(num_mb, mb, d), jnp.float32)
    out = parallel.spmd_pipeline_interleaved(block_fn, stacked, x, mesh,
                                             virtual=virtual)
    assert out.shape == (num_mb, mb, d)
    ref = []
    for i in range(num_mb):
        h = x[i]
        for p in per_stage:
            h = block_fn(p, h)
        ref.append(h)
    np.testing.assert_allclose(np.asarray(out), np.asarray(jnp.stack(ref)),
                               rtol=1e-5, atol=1e-6)


def test_interleaved_pipeline_differentiable(rng):
    """jax.grad through the interleaved scan == grad of the sequential chain."""
    pp, v, d, num_mb, mb = 2, 2, 8, 4, 2
    mesh = parallel.make_mesh(pipe=pp)
    L = v * pp
    layer = nn.Dense(d, activation="tanh", policy=F32)
    keys = jax.random.split(rng, L)
    per_stage = [layer.init(k, (mb, d))["params"] for k in keys]
    stacked = parallel.stack_stage_params(per_stage)

    def block_fn(params, x):
        return layer({"params": params, "state": {}}, x)

    x = jnp.asarray(np.random.RandomState(1).randn(num_mb, mb, d), jnp.float32)

    def loss_pipe(stacked):
        return jnp.sum(parallel.spmd_pipeline_interleaved(
            block_fn, stacked, x, mesh, virtual=v) ** 2)

    def loss_seq(stacked):
        total = 0.0
        for i in range(num_mb):
            h = x[i]
            for s in range(L):
                p = jax.tree_util.tree_map(lambda a, s=s: a[s], stacked)
                h = block_fn(p, h)
            total = total + jnp.sum(h ** 2)
        return total

    gp = jax.grad(loss_pipe)(stacked)
    gs = jax.grad(loss_seq)(stacked)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                                rtol=1e-4, atol=1e-5), gp, gs)


def test_interleaved_pipeline_validates():
    mesh = parallel.make_mesh(pipe=4)
    x = jnp.zeros((6, 2, 8), jnp.float32)  # 6 mbs not divisible by pp=4
    stacked = jnp.zeros((8, 8, 8), jnp.float32)
    with pytest.raises(ValueError, match="divisible"):
        parallel.spmd_pipeline_interleaved(lambda p, x: x, stacked, x, mesh,
                                           virtual=2)
    with pytest.raises(ValueError, match="leading dim"):
        parallel.spmd_pipeline_interleaved(
            lambda p, x: x, stacked, jnp.zeros((4, 2, 8)), mesh, virtual=3)


def test_spmd_pipeline_differentiable(rng):
    mesh = parallel.make_mesh(pipe=4)
    d = 8
    layer = nn.Dense(d, policy=F32)
    keys = jax.random.split(rng, 4)
    per_stage = [layer.init(k, (2, d))["params"] for k in keys]
    stacked = parallel.stack_stage_params(per_stage)

    def block_fn(params, x):
        return layer({"params": params, "state": {}}, x)

    x = jnp.asarray(np.random.RandomState(0).randn(4, 2, d), jnp.float32)

    def loss(stacked_params):
        out = parallel.spmd_pipeline(block_fn, stacked_params, x, mesh)
        return jnp.sum(out ** 2)

    grads = jax.grad(loss)(stacked)
    # compare against sequential grads
    def loss_seq(stacked_params):
        outs = []
        for i in range(x.shape[0]):
            h = x[i]
            for s in range(4):
                p = jax.tree_util.tree_map(lambda a: a[s], stacked_params)
                h = block_fn(p, h)
            outs.append(h)
        return jnp.sum(jnp.stack(outs) ** 2)

    grads_ref = jax.grad(loss_seq)(stacked)
    for a, b in zip(jax.tree_util.tree_leaves(grads), jax.tree_util.tree_leaves(grads_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5)


# -- compiled heterogeneous pipeline (shape-changing stages + BatchNorm) -----


def _conv_bn_net():
    """Shape-changing conv net with BatchNorm — the stage pattern of the
    reference's flagship pipeline model (WRN-16-8, example_models.cpp:130)."""
    return nn.Sequential([
        nn.Conv2D(8, 3, padding="same", use_bias=False),
        nn.BatchNorm(), nn.Activation("relu"),
        nn.Conv2D(16, 3, strides=2, padding="same", use_bias=False),
        nn.BatchNorm(), nn.Activation("relu"),
        nn.GlobalAvgPool(), nn.Dense(10),
    ], name="convbn")


def _global_key(part, local_key):
    """Stage-local child key ("01_batchnorm") -> the unsplit model's key
    ("04_batchnorm") — one place for the layer-naming convention."""
    j, typ = int(local_key.split("_")[0]), local_key.split("_", 1)[1]
    return f"{part.start + j:02d}_{typ}"


def _merge_stage_vars(parts, stage_vars, ref_params, ref_net):
    """Overlay per-stage {params,state} dicts onto the unsplit model's trees."""
    for part, sv in zip(parts, stage_vars):
        for lk, v in sv["params"].items():
            ref_params[_global_key(part, lk)] = v
        for lk, v in sv["state"].items():
            ref_net[_global_key(part, lk)] = v
    return ref_params, ref_net


def _align_ref_state(model, parts, pipe, pstate, opt, batch_shape):
    """Build a single-device TrainState carrying the pipeline's exact init."""
    rstate = create_train_state(model, opt, jax.random.PRNGKey(0), batch_shape)
    stage_vars = pipe.unpack_stage_variables(pstate.params, pstate.net_state)
    ref_params, ref_net = _merge_stage_vars(
        parts, stage_vars, dict(rstate.params), dict(rstate.net_state))
    return rstate._replace(params=ref_params, net_state=ref_net,
                           opt_state=opt.init(ref_params))


@pytest.mark.parametrize("remat", [False, True])
def test_hetero_pipeline_matches_grad_accum(remat):
    """pp=4 pipeline over shape-changing conv stages must reproduce
    single-device grad-accumulation EXACTLY — loss, accuracy, and BatchNorm
    running stats (the round-2 finding: StagePipeline froze BN; the compiled
    pipeline updates it per microbatch like the reference's per-mb caches).
    remat=True (stage rematerialization, the 1F1B memory benefit) must not
    change any numerics."""
    NUM_MB, MB = 4, 8
    B = NUM_MB * MB
    mesh = parallel.make_mesh(pipe=4)
    model = _conv_bn_net()
    parts = parallel.partitioner.proportional_partitions(len(model.children),
                                                         [1.0] * 4)
    stages = parallel.split(model, parts)
    opt = nn.SGD(lr=0.1, momentum=0.9)
    pipe, step_fn, init_fn = parallel.make_pipeline_train_step(
        stages, opt, mesh, (MB, 16, 16, 3), num_microbatches=NUM_MB,
        remat=remat)
    pstate = init_fn(jax.random.PRNGKey(0))

    ref_opt = nn.SGD(lr=0.1, momentum=0.9)
    rstate = _align_ref_state(model, parts, pipe, pstate, ref_opt,
                              (B, 16, 16, 3))
    ref_step = make_train_step(model, ref_opt, grad_accum=NUM_MB, donate=False)

    rs = np.random.RandomState(0)
    for _ in range(3):
        data = jnp.asarray(rs.randn(B, 16, 16, 3), jnp.bfloat16)
        labels = jnp.asarray(rs.randint(0, 10, B), jnp.int32)
        pstate, pm = step_fn(pstate, data, labels)
        rstate, rm = ref_step(rstate, data, labels)
        np.testing.assert_allclose(float(pm["loss"]), float(rm["loss"]),
                                   rtol=2e-2)
        np.testing.assert_allclose(float(pm["accuracy"]),
                                   float(rm["accuracy"]), atol=1e-6)

    # BatchNorm running stats must match the single-device run (not frozen)
    final_vars = pipe.unpack_stage_variables(pstate.params, pstate.net_state)
    checked = 0
    for part, sv in zip(parts, final_vars):
        for lk, v in sv["state"].items():
            ref_v = rstate.net_state[_global_key(part, lk)]
            for kk in v:
                np.testing.assert_allclose(np.asarray(v[kk]),
                                           np.asarray(ref_v[kk]), atol=1e-2)
                checked += 1
    assert checked >= 4  # both BN layers' mean+var went through the pipeline


def test_hetero_pipeline_interleaved_matches_grad_accum():
    """virtual=2 interleaved schedule over 8 heterogeneous stages (pp=4) must
    reproduce single-device grad accumulation exactly — same bar as the GPipe
    path, with the bubble halved (round-4: VERDICT asked for the interleaved
    schedule on the flagship hetero pipeline, not just homogeneous stacks)."""
    NUM_MB, MB = 4, 8
    B = NUM_MB * MB
    mesh = parallel.make_mesh(pipe=4)
    model = _conv_bn_net()
    parts = parallel.partitioner.proportional_partitions(len(model.children),
                                                         [1.0] * 8)
    stages = parallel.split(model, parts)
    opt = nn.SGD(lr=0.1, momentum=0.9)
    pipe, step_fn, init_fn = parallel.make_pipeline_train_step(
        stages, opt, mesh, (MB, 16, 16, 3), num_microbatches=NUM_MB,
        virtual=2)
    assert pipe.L == 8 and pipe.v == 2
    pstate = init_fn(jax.random.PRNGKey(0))

    ref_opt = nn.SGD(lr=0.1, momentum=0.9)
    rstate = _align_ref_state(model, parts, pipe, pstate, ref_opt,
                              (B, 16, 16, 3))
    ref_step = make_train_step(model, ref_opt, grad_accum=NUM_MB, donate=False)

    rs = np.random.RandomState(0)
    for _ in range(3):
        data = jnp.asarray(rs.randn(B, 16, 16, 3), jnp.bfloat16)
        labels = jnp.asarray(rs.randint(0, 10, B), jnp.int32)
        pstate, pm = step_fn(pstate, data, labels)
        rstate, rm = ref_step(rstate, data, labels)
        np.testing.assert_allclose(float(pm["loss"]), float(rm["loss"]),
                                   rtol=2e-2)
        np.testing.assert_allclose(float(pm["accuracy"]),
                                   float(rm["accuracy"]), atol=1e-6)
    # BN running stats flow through the interleaved schedule too
    final_vars = pipe.unpack_stage_variables(pstate.params, pstate.net_state)
    checked = 0
    for part, sv in zip(parts, final_vars):
        for lk, v in sv["state"].items():
            ref_v = rstate.net_state[_global_key(part, lk)]
            for kk in v:
                np.testing.assert_allclose(np.asarray(v[kk]),
                                           np.asarray(ref_v[kk]), atol=1e-2)
                checked += 1
    assert checked >= 4


def test_hetero_pipeline_interleaved_validates():
    mesh = parallel.make_mesh(pipe=4)
    model = _conv_bn_net()
    parts = parallel.partitioner.proportional_partitions(len(model.children),
                                                         [1.0] * 8)
    stages = parallel.split(model, parts)
    with pytest.raises(ValueError, match="virtual"):
        parallel.pipeline.HeteroPipeline(stages, mesh, (4, 16, 16, 3),
                                         virtual=3)
    with pytest.raises(ValueError, match="divisible"):
        parallel.pipeline.HeteroPipeline(stages, mesh, (4, 16, 16, 3),
                                         num_microbatches=6, virtual=2)


def test_hetero_pipeline_moe_aux_loss_flows():
    """An MoE stage inside the compiled pipeline must train load-BALANCED:
    the stage's aux_loss leaves reach the pipeline loss (round-4 fix; before,
    the packed state silently dropped them), matching single-device grad
    accumulation, and the router keeps expert usage near-uniform."""
    NUM_MB, MB, S, D = 4, 4, 6, 16
    B = NUM_MB * MB
    mesh = parallel.make_mesh(pipe=4)
    F32 = dt.FP32
    model = nn.Sequential([
        nn.Dense(32, policy=F32),
        nn.MoE(4, top_k=2, capacity_factor=2.0, aux_weight=0.05, policy=F32),
        nn.Dense(32, activation="relu", policy=F32),
        nn.Flatten(policy=F32),
        nn.Dense(10, policy=F32),
    ], name="moepipe")
    parts = parallel.partitioner.proportional_partitions(
        len(model.children), [1.0] * 4)
    stages = parallel.split(model, parts)
    opt = nn.SGD(lr=0.05)
    pipe, step_fn, init_fn = parallel.make_pipeline_train_step(
        stages, opt, mesh, (MB, S, D), input_dtype=jnp.float32,
        num_microbatches=NUM_MB)
    pstate = init_fn(jax.random.PRNGKey(0))

    ref_opt = nn.SGD(lr=0.05)
    rstate = _align_ref_state(model, parts, pipe, pstate, ref_opt, (B, S, D))
    ref_step = make_train_step(model, ref_opt, grad_accum=NUM_MB,
                               donate=False)

    rs = np.random.RandomState(0)
    for i in range(3):
        data = jnp.asarray(rs.randn(B, S, D), jnp.float32)
        labels = jnp.asarray(rs.randint(0, 10, B), jnp.int32)
        pstate, pm = step_fn(pstate, data, labels)
        rstate, rm = ref_step(rstate, data, labels)
        # the pipeline loss INCLUDES the aux term, like the reference step
        np.testing.assert_allclose(float(pm["loss"]), float(rm["loss"]),
                                   rtol=2e-2)
    # aux actually nonzero (the term exists) ...
    vars_ = pipe.unpack_stage_variables(pstate.params, pstate.net_state)
    aux_leaves = [v for sv in vars_ for k, v in
                  jax.tree_util.tree_flatten_with_path(sv["state"])[0]
                  if getattr(k[-1], "key", None) == "aux_loss"]
    assert aux_leaves and float(aux_leaves[0]) > 0
    # ... and expert usage stays near-uniform: probe the trained gate
    gate_w = next(sv["params"][k]["gate"]["kernel"]
                  for sv in vars_ for k in sv["params"] if k.endswith("_moe"))
    x = jnp.asarray(rs.randn(B, S, gate_w.shape[0]), jnp.float32)
    probs = jax.nn.softmax(x.reshape(-1, gate_w.shape[0]) @ gate_w, axis=-1)
    frac = np.asarray(jnp.mean(probs, axis=0))
    entropy = -float(np.sum(frac * np.log(frac + 1e-9)))
    assert entropy > 0.8 * np.log(4), (frac, entropy)  # near-uniform routing


def test_hetero_pipeline_composes_with_data_axis():
    """dp=2 x pp=4 in one program: loss tracks single-device training within
    ghost-BN tolerance and decreases (the reference cannot compose DP with PP;
    its DP also never all-reduces, coordinator.hpp:37-40)."""
    NUM_MB, MBG = 2, 8
    B = NUM_MB * MBG
    mesh = parallel.make_mesh(data=2, pipe=4)
    model = _conv_bn_net()
    parts = parallel.partitioner.proportional_partitions(len(model.children),
                                                         [1.0] * 4)
    stages = parallel.split(model, parts)
    opt = nn.SGD(lr=0.1, momentum=0.9)
    pipe, step_fn, init_fn = parallel.make_pipeline_train_step(
        stages, opt, mesh, (MBG, 16, 16, 3), num_microbatches=NUM_MB,
        data_axis="data")
    pstate = init_fn(jax.random.PRNGKey(0))
    ref_opt = nn.SGD(lr=0.1, momentum=0.9)
    rstate = _align_ref_state(model, parts, pipe, pstate, ref_opt,
                              (B, 16, 16, 3))
    ref_step = make_train_step(model, ref_opt, grad_accum=NUM_MB, donate=False)
    rs = np.random.RandomState(0)
    for _ in range(3):
        data = jnp.asarray(rs.randn(B, 16, 16, 3), jnp.bfloat16)
        labels = jnp.asarray(rs.randint(0, 10, B), jnp.int32)
        pstate, pm = step_fn(pstate, data, labels)
        rstate, rm = ref_step(rstate, data, labels)
        np.testing.assert_allclose(float(pm["loss"]), float(rm["loss"]),
                                   rtol=5e-2)


def test_hetero_pipeline_wrn_family():
    """A (small) WRN through the compiled pipeline: residual blocks with BN +
    downsampling stages train, loss decreases (flagship family smoke; the full
    WRN-16-8 equivalence runs out-of-suite — compile is minutes on the CPU
    mesh — via examples/trainer.py --mesh pipe=4)."""
    from tnn_tpu.models import resnet

    NUM_MB, MB = 2, 4
    B = NUM_MB * MB
    mesh = parallel.make_mesh(pipe=4)
    model = resnet.wrn(depth=10, widen=1, num_classes=10)
    stages = parallel.partition_model(model, 4, (MB, 16, 16, 3),
                                      strategy="balanced")
    opt = nn.SGD(lr=0.05, momentum=0.9)
    pipe, step_fn, init_fn = parallel.make_pipeline_train_step(
        stages, opt, mesh, (MB, 16, 16, 3), num_microbatches=NUM_MB)
    state = init_fn(jax.random.PRNGKey(0))
    rs = np.random.RandomState(0)
    pat = rs.randn(10, 16, 16, 3)
    y = rs.randint(0, 10, B)
    data = jnp.asarray(pat[y] * 0.5 + rs.randn(B, 16, 16, 3) * 0.1, jnp.bfloat16)
    labels = jnp.asarray(y, jnp.int32)
    state, m = step_fn(state, data, labels)
    l0 = float(m["loss"])
    for _ in range(10):
        state, m = step_fn(state, data, labels)
    assert float(m["loss"]) < l0, (l0, float(m["loss"]))


# -- host-orchestrated heterogeneous pipeline --------------------------------

def test_stage_pipeline_batchnorm_matches_grad_accum(rng):
    """StagePipeline must UPDATE BatchNorm stats (the round-2 finding: it
    froze them with train=False) and match single-device grad accumulation on
    a BN-bearing conv model — loss and running stats."""
    NUM_MB, MB = 4, 4
    B = NUM_MB * MB
    model = _conv_bn_net()
    parts = parallel.partitioner.proportional_partitions(len(model.children),
                                                         [1.0] * 2)
    stages = parallel.split(model, parts)
    pipe = parallel.StagePipeline(stages, nn.SGD(lr=0.1),
                                  losses.get("softmax_cross_entropy"),
                                  devices=jax.devices()[:2])
    pipe.init(rng, (MB, 16, 16, 3), input_dtype=jnp.bfloat16)

    # single-device twin with the same init
    ref_opt = nn.SGD(lr=0.1)
    rstate = create_train_state(model, ref_opt, jax.random.PRNGKey(0),
                                (B, 16, 16, 3))
    ref_params, ref_net = _merge_stage_vars(
        parts, pipe.variables, dict(rstate.params), dict(rstate.net_state))
    # stage params live on per-stage devices; the single-device twin needs one
    dev0 = jax.devices()[0]
    ref_params = jax.device_put(ref_params, dev0)
    ref_net = jax.device_put(ref_net, dev0)
    rstate = rstate._replace(params=ref_params, net_state=ref_net,
                             opt_state=ref_opt.init(ref_params))
    ref_step = make_train_step(model, ref_opt, grad_accum=NUM_MB, donate=False,
                               compute_accuracy=False)

    rs = np.random.RandomState(0)
    for _ in range(3):
        data = jnp.asarray(rs.randn(B, 16, 16, 3), jnp.bfloat16)
        lab = jnp.asarray(rs.randint(0, 10, B), jnp.int32)
        ploss = pipe.train_batch(data, lab, num_microbatches=NUM_MB)
        rstate, rm = ref_step(rstate, data, lab)
        np.testing.assert_allclose(ploss, float(rm["loss"]), rtol=2e-2)

    moved = 0.0
    for part, v in zip(parts, pipe.variables):
        for lk, sv in v["state"].items():
            ref_v = rstate.net_state[_global_key(part, lk)]
            for kk in sv:
                np.testing.assert_allclose(np.asarray(sv[kk]),
                                           np.asarray(ref_v[kk]), atol=1e-2)
                moved += float(jnp.abs(jnp.asarray(sv[kk])).sum())
    assert moved > 0  # stats actually updated, not frozen at init


def test_stage_pipeline_trains(rng):
    """2-stage heterogeneous pipeline learns a toy problem (parity:
    pipeline_benchmark.cpp / IN_PROCESS coordinator+worker run)."""
    model = _mlp()
    stages = parallel.partition_model(model, 2, (16, 8), strategy="uniform")
    pipe = parallel.StagePipeline(stages, nn.Adam(lr=1e-2), losses.get("softmax_cross_entropy"),
                                  devices=jax.devices()[:2])
    pipe.init(rng, (16, 8), input_dtype=jnp.float32)
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(16, 8), jnp.float32)
    y = jnp.asarray(rs.randint(0, 4, 16), jnp.int32)
    losses_seen = [pipe.train_batch(x, y, num_microbatches=4) for _ in range(60)]
    assert losses_seen[-1] < losses_seen[0] * 0.5, losses_seen[::20]
    out = pipe.forward(x)
    assert out.shape == (16, 4)


# -- ring attention ----------------------------------------------------------

@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_local(causal, rng):
    from tnn_tpu.nn.attention import sdpa

    mesh = parallel.make_mesh(seq=8)
    rs = np.random.RandomState(0)
    q = jnp.asarray(rs.randn(2, 2, 64, 16), jnp.float32)
    k = jnp.asarray(rs.randn(2, 2, 64, 16), jnp.float32)
    v = jnp.asarray(rs.randn(2, 2, 64, 16), jnp.float32)
    ref = sdpa(q, k, v, causal=causal)
    out = parallel.ring_attention(q, k, v, mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-5)


def test_ring_attention_grads(rng):
    from tnn_tpu.nn.attention import sdpa

    mesh = parallel.make_mesh(seq=4)
    rs = np.random.RandomState(1)
    q = jnp.asarray(rs.randn(1, 2, 32, 8), jnp.float32)
    k = jnp.asarray(rs.randn(1, 2, 32, 8), jnp.float32)
    v = jnp.asarray(rs.randn(1, 2, 32, 8), jnp.float32)
    g1 = jax.grad(lambda q: jnp.sum(parallel.ring_attention(q, k, v, mesh, causal=True) ** 2))(q)
    g2 = jax.grad(lambda q: jnp.sum(sdpa(q, k, v, causal=True) ** 2))(q)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), rtol=1e-4, atol=1e-5)


# -- ulysses (all-to-all) sequence parallelism --------------------------------

@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_local(causal, rng):
    from tnn_tpu.nn.attention import sdpa

    mesh = parallel.make_mesh(seq=8)
    rs = np.random.RandomState(0)
    q = jnp.asarray(rs.randn(2, 8, 64, 16), jnp.float32)  # heads % sp == 0
    k = jnp.asarray(rs.randn(2, 8, 64, 16), jnp.float32)
    v = jnp.asarray(rs.randn(2, 8, 64, 16), jnp.float32)
    ref = sdpa(q, k, v, causal=causal)
    out = parallel.ulysses_attention(q, k, v, mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-5)


def test_ulysses_grads_match_ring(rng):
    mesh = parallel.make_mesh(seq=4)
    rs = np.random.RandomState(1)
    q = jnp.asarray(rs.randn(1, 4, 32, 8), jnp.float32)
    k = jnp.asarray(rs.randn(1, 4, 32, 8), jnp.float32)
    v = jnp.asarray(rs.randn(1, 4, 32, 8), jnp.float32)
    gu = jax.grad(lambda q: jnp.sum(
        parallel.ulysses_attention(q, k, v, mesh, causal=True) ** 2))(q)
    gr = jax.grad(lambda q: jnp.sum(
        parallel.ring_attention(q, k, v, mesh, causal=True) ** 2))(q)
    np.testing.assert_allclose(np.asarray(gu), np.asarray(gr), rtol=1e-4, atol=1e-5)


def test_ulysses_rejects_indivisible_heads():
    mesh = parallel.make_mesh(seq=8)
    q = jnp.zeros((1, 4, 64, 8), jnp.float32)  # 4 heads, sp=8
    with pytest.raises(ValueError, match="num_heads"):
        parallel.ulysses_attention(q, q, q, mesh, causal=True)


def test_ulysses_context_drives_sdpa(rng):
    """ring_context(method='ulysses') reroutes every sdpa call — the config
    knob train_model exposes as seq_parallel_method."""
    from tnn_tpu.nn.attention import ring_context, sdpa

    mesh = parallel.make_mesh(seq=8)
    rs = np.random.RandomState(2)
    q = jnp.asarray(rs.randn(1, 8, 64, 16), jnp.float32)
    ref = sdpa(q, q, q, causal=True)
    with ring_context(mesh, method="ulysses"):
        out = sdpa(q, q, q, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-4,
                               atol=1e-5)


# -- tensor parallel ---------------------------------------------------------

def test_tp_sharding_rules(rng):
    mesh = parallel.make_mesh(model=8)
    model = models.GPT2(vocab_size=128, max_len=16, num_layers=2, d_model=64,
                        num_heads=8, policy=F32)
    v = model.init(rng, (1, 16))
    sharded = parallel.shard_params_tp(v["params"], mesh)
    qkv = sharded["h0"]["attn"]["qkv_kernel"]
    assert "model" in str(qkv.sharding.spec)
    # forward still correct under TP sharding
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 128, (1, 16)), jnp.int32)
    ref = model({"params": v["params"], "state": {}}, ids)
    with mesh:
        out = model({"params": sharded, "state": {}}, ids)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-4)


def test_pipeline_remat_policy_resolves_at_build():
    """Policy-name remat reaches the pipeline (not silently bool()ed to full
    remat), and a typo raises at BUILD time on this path like the
    single-device path."""
    mesh = parallel.make_mesh(pipe=2)
    model = _conv_bn_net()
    parts = parallel.partitioner.proportional_partitions(len(model.children),
                                                         [1.0] * 2)
    stages = parallel.split(model, parts)
    opt = nn.SGD(lr=0.1)
    pipe, _, _ = parallel.make_pipeline_train_step(
        stages, opt, mesh, (4, 16, 16, 3), num_microbatches=2, remat="dots")
    assert pipe.remat and pipe._remat_policy is not None
    with pytest.raises(ValueError, match="unknown remat policy"):
        parallel.make_pipeline_train_step(
            stages, opt, mesh, (4, 16, 16, 3), num_microbatches=2,
            remat="typo")


def test_ring_attention_gqa_matches_local(rng):
    """GQA through the ring: kv blocks rotate at H_kv size, repeat only at
    compute — output and dk/dv grads must match the local GQA kernels."""
    from tnn_tpu.nn.attention import sdpa

    mesh = parallel.make_mesh(seq=4)
    rs = np.random.RandomState(2)
    q = jnp.asarray(rs.randn(1, 4, 32, 8), jnp.float32)
    k = jnp.asarray(rs.randn(1, 2, 32, 8), jnp.float32)
    v = jnp.asarray(rs.randn(1, 2, 32, 8), jnp.float32)
    ref = sdpa(q, k, v, causal=True)
    out = parallel.ring_attention(q, k, v, mesh, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)
    g1 = jax.grad(lambda k: jnp.sum(
        parallel.ring_attention(q, k, v, mesh, causal=True) ** 2))(k)
    g2 = jax.grad(lambda k: jnp.sum(sdpa(q, k, v, causal=True) ** 2))(k)
    assert g1.shape == (1, 2, 32, 8)  # grads at H_kv size
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                               rtol=1e-4, atol=1e-5)


def test_tp_llama_matches_single_device(rng):
    """Llama (RoPE + SwiGLU + GQA) under dp x tp: the SwiGLU gate/up/down TP
    rules keep the product shard-local; loss must match the unsharded step."""
    from tnn_tpu.models.llama import Llama

    model = Llama(vocab_size=64, max_len=16, num_layers=2, d_model=32,
                  num_heads=4, num_kv_heads=2,
                  policy=dt.DTypePolicy(io="float32", param="float32",
                                        compute="float32"))
    opt = nn.SGD(lr=0.1)
    ids = jnp.asarray(np.random.RandomState(5).randint(0, 64, (4, 16)),
                      jnp.int32)
    ref_state = create_train_state(model, opt, jax.random.PRNGKey(0), (4, 16))
    step = make_train_step(model, opt, donate=False)
    _, ref_m = step(ref_state, ids, ids)

    mesh = parallel.make_mesh(data=2, model=2)
    tp_state = ref_state._replace(
        params=parallel.shard_params_tp(ref_state.params, mesh),
        opt_state=jax.device_put(ref_state.opt_state,
                                 parallel.replicated(mesh)),
        net_state=jax.device_put(ref_state.net_state,
                                 parallel.replicated(mesh)),
        step=jax.device_put(ref_state.step, parallel.replicated(mesh)),
        rng=jax.device_put(ref_state.rng, parallel.replicated(mesh)))
    sharded_ids = jax.device_put(ids, parallel.batch_sharding(mesh))
    with mesh:
        _, tp_m = step(tp_state, sharded_ids, sharded_ids)
    np.testing.assert_allclose(float(tp_m["loss"]), float(ref_m["loss"]),
                               rtol=1e-5)
    # the MLP kernels really are sharded, not silently replicated
    specs = parallel.tensor_parallel.spec_tree(ref_state.params)
    flat = jax.tree_util.tree_flatten_with_path(specs)[0]
    gate_specs = [s for path, s in flat
                  if "gate" in "/".join(str(p) for p in path)]
    assert gate_specs and all("model" in str(s) for s in gate_specs)
    # the MoE ROUTER gate must NOT be captured by the SwiGLU gate rule --
    # it replicates (nn/moe.py ep_rules invariant)
    moe_model = models.create("moe_gpt2_small", max_len=16)
    mv = moe_model.init(jax.random.PRNGKey(0), (1, 8))
    moe_specs = parallel.tensor_parallel.spec_tree(mv["params"])
    for path, s in jax.tree_util.tree_flatten_with_path(moe_specs)[0]:
        key = "/".join(str(p) for p in path)
        if "moe" in key and "gate" in key:
            assert "model" not in str(s), (key, s)
