"""Serving engine tests: paged KV pool bookkeeping, scheduler policy, and
end-to-end continuous batching with token-for-token parity against
models.gpt2.generate (the offline single-sequence reference path).

Parity methodology: the engine assembles per-request caches at the pool's
fixed width (blocks_per_seq * block_size) and generate() is run with
``max_len`` equal to that width, so both paths softmax over identically
shaped (masked) caches — greedy outputs must then match exactly.
"""
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tnn_tpu.serving import (TERMINAL_STATES, AdmissionRejected, Autoscaler,
                             BreakerState, CircuitBreaker, EngineCrash,
                             EngineSupervisor, FaultPlan, HostKVTier,
                             InferenceEngine, PagedKVPool, PoolExhausted,
                             PrefixCache, Request, RequestState, Router,
                             Scheduler, ShuttingDown, SupervisorState,
                             gather_kv, scatter_prefill, scatter_token)


# -- pool bookkeeping ---------------------------------------------------------


class TestPagedKVPool:
    def _pool(self, **kw):
        kw.setdefault("num_layers", 2)
        kw.setdefault("num_kv_heads", 2)
        kw.setdefault("head_dim", 4)
        kw.setdefault("num_blocks", 8)
        kw.setdefault("block_size", 4)
        return PagedKVPool(**kw)

    def test_alloc_free_roundtrip(self):
        pool = self._pool()
        assert pool.capacity == 7 and pool.num_free == 7
        blocks = pool.alloc(3)
        assert len(blocks) == 3 and PagedKVPool.SCRATCH not in blocks
        assert pool.num_allocated == 3
        pool.free(blocks)
        assert pool.num_free == 7 and pool.num_allocated == 0

    def test_exhaustion_raises(self):
        pool = self._pool()
        pool.alloc(7)
        assert not pool.can_alloc(1)
        with pytest.raises(PoolExhausted):
            pool.alloc(1)

    def test_double_free_raises(self):
        pool = self._pool()
        blocks = pool.alloc(2)
        pool.free(blocks)
        with pytest.raises(KeyError):
            pool.free(blocks)

    def test_refcount_fork(self):
        pool = self._pool()
        blocks = pool.alloc(2)
        pool.fork(blocks)
        pool.free(blocks)           # one ref left
        assert pool.num_allocated == 2
        pool.free(blocks)           # last ref
        assert pool.num_allocated == 0

    def test_blocks_for(self):
        pool = self._pool(block_size=4)
        assert pool.blocks_for(0) == 1   # even empty sequences hold a block
        assert pool.blocks_for(4) == 1
        assert pool.blocks_for(5) == 2

    def test_gather_after_fragmentation(self):
        """Logical order must follow the block TABLE, not block-id order —
        tables acquired after frees interleave arbitrarily in the pool."""
        pool = self._pool(num_layers=1, num_kv_heads=1, head_dim=2,
                          num_blocks=8, block_size=2)
        a = pool.alloc(2)
        b = pool.alloc(2)
        pool.free(a)
        c = pool.alloc(3)  # reuses a's blocks (LIFO) + one fresh: fragmented
        assert set(a) & set(c), "expected block reuse to fragment the table"
        seq = jnp.broadcast_to(
            jnp.arange(6, dtype=jnp.float32)[None, None, :, None],
            (1, 1, 6, 2))
        pool.update_pages(
            scatter_prefill(pool.pages_k, jnp.asarray(c), seq),
            scatter_prefill(pool.pages_v, jnp.asarray(c), -seq))
        table = jnp.asarray([pool.padded_table(c, 4)])
        kf, vf = gather_kv(pool.pages_k, pool.pages_v, table)
        got = np.asarray(kf)[0, 0, 0, :6, 0]
        np.testing.assert_array_equal(got, np.arange(6, dtype=np.float32))
        np.testing.assert_array_equal(np.asarray(vf)[0, 0, 0, :6, 0], -got)
        del b

    def test_scatter_token_lands_in_right_slot(self):
        pool = self._pool(num_layers=1, num_kv_heads=1, head_dim=2,
                          num_blocks=8, block_size=4)
        blocks = pool.alloc(2)
        tables = jnp.asarray([pool.padded_table(blocks, 2)])
        # position 5 = second block, slot 1
        rows = jnp.full((1, 1, 1, 2), 7.0)
        pages = scatter_token(pool.pages_k, tables, jnp.asarray([5]), rows)
        got = np.asarray(pages)[0, blocks[1], 0, 1]
        np.testing.assert_array_equal(got, [7.0, 7.0])


# -- scheduler policy ---------------------------------------------------------


def _req(rid, plen, max_new=4):
    return Request(rid=rid, prompt=np.zeros(plen, np.int32),
                   max_new_tokens=max_new)


class TestScheduler:
    def _pool(self):
        return PagedKVPool(num_layers=1, num_kv_heads=1, head_dim=2,
                           num_blocks=9, block_size=4)

    def test_fcfs_admission(self):
        sched = Scheduler(max_batch_size=2, token_budget=100)
        pool = self._pool()
        for i in range(3):
            sched.submit(_req(i, 4))
        plan = sched.schedule(pool)
        assert [r.rid for r in plan.prefills] == [0, 1]  # batch cap
        for r in plan.prefills:
            r.block_table = pool.alloc(1)
            sched.admit(r)
        assert sched.schedule(pool).prefills == []       # batch full

    def test_head_of_line_blocking(self):
        """A queue head that does not fit must block later (fitting) requests
        — out-of-order admission would starve big prompts forever."""
        sched = Scheduler(max_batch_size=4, token_budget=100)
        pool = self._pool()
        pool.alloc(6)                       # only 2 blocks (8 tokens) free
        sched.submit(_req(0, 12))           # needs 3 blocks: blocked
        sched.submit(_req(1, 4))            # would fit, but is behind 0
        assert sched.schedule(pool).prefills == []

    def test_token_budget_defers_prefill(self):
        sched = Scheduler(max_batch_size=4, token_budget=10)
        pool = self._pool()
        sched.submit(_req(0, 8))
        sched.submit(_req(1, 8))            # 16 > budget: second waits
        plan = sched.schedule(pool)
        assert [r.rid for r in plan.prefills] == [0]
        # an over-budget prompt still runs when it is the ONLY work
        sched2 = Scheduler(max_batch_size=4, token_budget=4)
        sched2.submit(_req(9, 8))
        assert [r.rid for r in sched2.schedule(pool).prefills] == [9]

    def test_requeue_goes_to_front(self):
        sched = Scheduler(max_batch_size=4, token_budget=100)
        a, b = _req(0, 4), _req(1, 4)
        sched.submit(a)
        sched.admit(sched.waiting.popleft())
        sched.submit(b)
        victim = sched.preempt_victim()
        assert victim is a
        sched.requeue(victim)
        assert [r.rid for r in sched.waiting] == [0, 1]
        assert victim.preemptions == 1

    def test_resume_tokens_carry_generated_prefix(self):
        r = _req(0, 3, max_new=8)
        r.out_tokens = [11, 12, 13]
        r.next_token = 13
        resume = r.resume_tokens
        assert resume.tolist() == [0, 0, 0, 11, 12]  # pending 13 excluded


# -- end-to-end on a tiny model ----------------------------------------------


@pytest.fixture(scope="module")
def tiny_lm():
    from tnn_tpu.models.gpt2 import GPT2

    model = GPT2(vocab_size=128, max_len=64, num_layers=2, d_model=32,
                 num_heads=2)
    params = model.init(jax.random.PRNGKey(0), (1, 8))["params"]
    return model, params


def _greedy_ref(model, params, prompt, max_new, max_len):
    from tnn_tpu.models.gpt2 import generate

    return np.asarray(generate(model, params, prompt[None], max_new,
                               max_len=max_len))[0].tolist()


class TestEngineTiny:
    def test_staggered_parity(self, tiny_lm):
        model, params = tiny_lm
        rng = np.random.default_rng(0)
        prompts = [rng.integers(0, 128, p).astype(np.int32)
                   for p in (5, 9, 16, 7)]
        eng = InferenceEngine(model, params, num_blocks=32, block_size=4,
                              max_batch_size=4, max_seq_len=32)
        rids = [eng.submit(prompts[0], 10)]
        eng.step(); eng.step()                        # r0 decodes alone
        rids += [eng.submit(p, 10) for p in prompts[1:]]
        out = eng.run_until_complete()
        for rid, p in zip(rids, prompts):
            assert out[rid] == _greedy_ref(model, params, p, 10,
                                           eng.assembly_len)

    def test_preemption_recovers_exactly(self, tiny_lm):
        """A pool too small for all requests must preempt (recompute-requeue)
        and still produce byte-identical greedy outputs, ending drained."""
        model, params = tiny_lm
        rng = np.random.default_rng(1)
        prompts = [rng.integers(0, 128, p).astype(np.int32)
                   for p in (5, 9, 16, 7)]
        eng = InferenceEngine(model, params, num_blocks=9, block_size=4,
                              max_batch_size=4, max_seq_len=32)
        for p in prompts:
            eng.submit(p, 10)
        out = eng.run_until_complete()
        assert eng.metrics.preemptions > 0, "pool was never exhausted"
        for rid, p in enumerate(prompts):
            assert out[rid] == _greedy_ref(model, params, p, 10,
                                           eng.assembly_len)
        assert eng.pool.num_allocated == 0
        # drained: only free + prefix-cache-evictable blocks remain
        assert eng.pool.num_free + eng.pool.num_evictable == eng.pool.capacity

    def test_mixed_sampling_params(self, tiny_lm):
        """Greedy and stochastic requests share one decode batch; stochastic
        rows stay in-vocab and the run terminates."""
        model, params = tiny_lm
        eng = InferenceEngine(model, params, num_blocks=32, block_size=4,
                              max_batch_size=4, max_seq_len=32, seed=3)
        p = np.arange(6, dtype=np.int32)
        g = eng.submit(p, 8)
        s = eng.submit(p, 8, temperature=0.9, top_k=16, top_p=0.9)
        out = eng.run_until_complete()
        assert out[g] == _greedy_ref(model, params, p, 8, eng.assembly_len)
        assert len(out[s]) == 8
        assert all(0 <= t < model.vocab_size for t in out[s])

    def test_stop_token_frees_early(self, tiny_lm):
        model, params = tiny_lm
        eng = InferenceEngine(model, params, num_blocks=32, block_size=4,
                              max_batch_size=2, max_seq_len=32)
        p = np.arange(5, dtype=np.int32)
        ref = _greedy_ref(model, params, p, 10, eng.assembly_len)
        stop = ref[3]
        rid = eng.submit(p, 10, stop_token=stop)
        out = eng.run_until_complete()
        assert out[rid] == ref[:4]
        assert eng.result(rid).finish_reason == "stop_token"
        assert eng.pool.num_allocated == 0

    def test_paged_parity_staggered(self, tiny_lm):
        """decode_path="paged" (no gather_kv, pages attended via block
        tables) must match "standard" token-for-token AND the offline
        reference, under staggered admission (ragged offsets)."""
        model, params = tiny_lm
        rng = np.random.default_rng(5)
        prompts = [rng.integers(0, 128, p).astype(np.int32)
                   for p in (5, 9, 16, 7)]

        def run(path):
            eng = InferenceEngine(model, params, num_blocks=32, block_size=4,
                                  max_batch_size=4, max_seq_len=32,
                                  decode_path=path)
            rids = [eng.submit(prompts[0], 10)]
            eng.step(); eng.step()
            rids += [eng.submit(p, 10) for p in prompts[1:]]
            out = eng.run_until_complete()
            return eng, [out[r] for r in rids]

        eng, paged = run("paged")
        assert eng._paged and eng.paged_fallback_reason is None
        assert eng.fused_fallback_reason == \
            "unused (paged decode path selected)"
        _, std = run("standard")
        assert paged == std
        for toks, p in zip(paged, prompts):
            assert toks == _greedy_ref(model, params, p, 10,
                                       eng.assembly_len)

    def test_paged_preemption_parity(self, tiny_lm):
        """Preemption-recovery (recompute-requeue) must be byte-identical
        between the paged and standard decode paths."""
        model, params = tiny_lm
        rng = np.random.default_rng(6)
        prompts = [rng.integers(0, 128, p).astype(np.int32)
                   for p in (5, 9, 16, 7)]

        def run(path):
            eng = InferenceEngine(model, params, num_blocks=9, block_size=4,
                                  max_batch_size=4, max_seq_len=32,
                                  decode_path=path)
            for p in prompts:
                eng.submit(p, 10)
            return eng, eng.run_until_complete()

        eng_p, out_p = run("paged")
        eng_s, out_s = run("standard")
        assert eng_p.metrics.preemptions > 0, "pool was never exhausted"
        assert out_p == out_s
        assert eng_p.pool.num_allocated == 0

    def test_paged_mixed_sampling(self, tiny_lm):
        """Stochastic rows ride the paged step too: same engine seed =>
        identical streams vs the standard path (same sampling draws over
        identical logits)."""
        model, params = tiny_lm

        def run(path):
            eng = InferenceEngine(model, params, num_blocks=32, block_size=4,
                                  max_batch_size=4, max_seq_len=32, seed=3,
                                  decode_path=path)
            p = np.arange(6, dtype=np.int32)
            g = eng.submit(p, 8)
            s = eng.submit(p, 8, temperature=0.9, top_k=16, top_p=0.9)
            out = eng.run_until_complete()
            return out[g], out[s]

        assert run("paged") == run("standard")

    def test_paged_probe_fallback(self, tiny_lm):
        """A model without apply_decode_paged falls back under auto (reason
        recorded); decode_path="paged" makes the failure fatal."""
        model, params = tiny_lm
        plain = type("NoPaged", (), {})()
        for attr in ("kv_cache_dtype", "max_len", "d_model", "num_heads",
                     "num_kv_heads", "num_layers", "policy", "moe_experts"):
            setattr(plain, attr, getattr(model, attr, None))
        eng = InferenceEngine.__new__(InferenceEngine)
        # probe in isolation: the full engine needs a real model elsewhere
        eng.model = plain
        with pytest.raises(ValueError, match="apply_decode_paged"):
            eng._probe_paged()
        eng2 = InferenceEngine(model, params, num_blocks=8, block_size=4,
                               max_batch_size=2, max_seq_len=16,
                               decode_path="standard")
        assert not eng2._paged
        assert "decode_path" in eng2.paged_fallback_reason

    def test_prefill_bucketing_bounds_compiles(self, tiny_lm):
        """Prompt lengths quantize to power-of-two block buckets: many
        distinct lengths share O(log) compiled prefill programs (legacy
        whole-prompt path; the chunked default compiles NO prefill programs
        — see TestChunkedPrefill.test_mixed_bucketing_bounds_compiles)."""
        model, params = tiny_lm
        eng = InferenceEngine(model, params, num_blocks=32, block_size=4,
                              max_batch_size=4, max_seq_len=32,
                              chunked_prefill=False)
        for n in (1, 2, 3, 4, 5, 7, 9, 11, 13, 15):
            eng.submit(np.arange(n, dtype=np.int32) % 128, 2)
        eng.run_until_complete()
        buckets = sorted(k[1] for k in eng._jit if k[0] == "prefill")
        # nb 1,2,3,4 -> buckets 1,2,4 -> padded 4,8,16 (cap: blocks_per_seq 8)
        assert buckets == [4, 8, 16]

    def test_submit_validation(self, tiny_lm):
        model, params = tiny_lm
        eng = InferenceEngine(model, params, num_blocks=4, block_size=4,
                              max_batch_size=2, max_seq_len=12)
        with pytest.raises(ValueError):
            eng.submit(np.arange(10, dtype=np.int32), 8)   # > max_seq_len
        with pytest.raises(ValueError):
            eng.submit(np.asarray([], np.int32), 4)        # empty prompt
        with pytest.raises(ValueError):
            eng.submit(np.arange(4, dtype=np.int32), 0)    # no tokens asked


# -- chunked prefill: the mixed prefill+decode step --------------------------


class TestChunkedPrefill:
    """The PR 4 tentpole: prompts advance chunk_size tokens per step inside
    the SAME compiled program as the decode rows. Every schedule must stay
    token-exact against the retired whole-prompt path, on both decode paths,
    with and without preemption."""

    def _run(self, tiny_lm, prompts, *, stagger=True, **kw):
        model, params = tiny_lm
        merged = dict(num_blocks=32, block_size=4, max_batch_size=4,
                      max_seq_len=32)
        merged.update(kw)
        eng = InferenceEngine(model, params, **merged)
        rids = [eng.submit(prompts[0], 10)]
        if stagger:
            eng.step(); eng.step()          # r0 mid-stream before the rest
        rids += [eng.submit(p, 10) for p in prompts[1:]]
        out = eng.run_until_complete()
        return eng, [out[r] for r in rids]

    def test_chunked_matches_whole_staggered(self, tiny_lm):
        """chunk_size=4 splits the 9/16-token prompts across several mixed
        steps; outputs must equal the whole-prompt path AND the offline
        reference, on the standard and paged decode paths alike."""
        model, params = tiny_lm
        rng = np.random.default_rng(7)
        prompts = [rng.integers(0, 128, p).astype(np.int32)
                   for p in (5, 9, 16, 7)]
        eng_c, chunked = self._run(tiny_lm, prompts, chunk_size=4)
        _, whole = self._run(tiny_lm, prompts, chunked_prefill=False)
        eng_p, chunked_paged = self._run(tiny_lm, prompts, chunk_size=4,
                                         decode_path="paged")
        _, whole_paged = self._run(tiny_lm, prompts, chunked_prefill=False,
                                   decode_path="paged")
        assert chunked == whole == chunked_paged == whole_paged
        for toks, p in zip(chunked, prompts):
            assert toks == _greedy_ref(model, params, p, 10,
                                       eng_c.assembly_len)
        # the 16-token prompt really took several chunks, and no legacy
        # prefill program was ever compiled
        assert eng_c.metrics.prefill_chunks >= 4 + 3 + 2 + 2
        assert not any(k[0] == "prefill" for k in eng_c._jit)
        assert eng_p._paged and not any(k[0] == "prefill" for k in eng_p._jit)
        _assert_drained(eng_c)

    def test_chunked_preemption_recovers_exactly(self, tiny_lm):
        """A starved pool preempts mid-stream; partially-prefilled work is
        re-chunked on resume and every stream stays byte-identical."""
        model, params = tiny_lm
        rng = np.random.default_rng(1)
        prompts = [rng.integers(0, 128, p).astype(np.int32)
                   for p in (5, 9, 16, 7)]
        for path in ("standard", "paged"):
            eng, outs = self._run(tiny_lm, prompts, stagger=False,
                                  num_blocks=9, chunk_size=4,
                                  decode_path=path)
            assert eng.metrics.preemptions > 0, "pool was never exhausted"
            for toks, p in zip(outs, prompts):
                assert toks == _greedy_ref(model, params, p, 10,
                                           eng.assembly_len)
            _assert_drained(eng)

    def test_mixed_bucketing_bounds_compiles(self, tiny_lm):
        """Chunk takes quantize to power-of-two query widths: many distinct
        prompt lengths share O(log chunk_size) compiled mixed programs, and
        the legacy prefill program is never built."""
        model, params = tiny_lm
        eng = InferenceEngine(model, params, num_blocks=32, block_size=4,
                              max_batch_size=4, max_seq_len=32, chunk_size=8)
        for n in (1, 2, 3, 4, 5, 7, 9, 11, 13, 15):
            eng.submit(np.arange(n, dtype=np.int32) % 128, 2)
        eng.run_until_complete()
        assert not any(k[0] == "prefill" for k in eng._jit)
        widths = {k[2] for k in eng._jit if k[0] == "mixed"}
        assert widths, "mixed step never ran"
        assert widths <= {1, 2, 4, 8}      # pow2 buckets, capped by chunk_size
        _assert_drained(eng)

    def test_mixed_sampling_in_chunked_steps(self, tiny_lm):
        """Greedy and stochastic rows share mixed steps with in-flight prompt
        chunks; the greedy stream stays exact and stochastic rows stay
        in-vocab. (Cross-schedule stochastic equality vs the whole-prompt
        path is NOT asserted: the two paths draw step keys at different
        points of the stream, so the draws legitimately differ.)"""
        model, params = tiny_lm
        eng = InferenceEngine(model, params, num_blocks=32, block_size=4,
                              max_batch_size=4, max_seq_len=32, seed=3,
                              chunk_size=4)
        p = np.arange(9, dtype=np.int32)
        g = eng.submit(p, 8)
        s = eng.submit(p, 8, temperature=0.9, top_k=16, top_p=0.9)
        out = eng.run_until_complete()
        assert out[g] == _greedy_ref(model, params, p, 8, eng.assembly_len)
        assert len(out[s]) == 8
        assert all(0 <= t < model.vocab_size for t in out[s])
        _assert_drained(eng)


# -- acceptance: gpt2_small, 8 staggered requests ----------------------------


@pytest.mark.slow
def test_gpt2_small_staggered_greedy():
    """The ISSUE's acceptance bar: >= 8 concurrent requests on gpt2_small
    (CPU), staggered submissions, greedy decoding, surviving pool exhaustion
    via preemption.

    Greedy correctness is asserted by TEACHER FORCING: feed each prompt plus
    the engine's output through one plain reference forward and require every
    engine token to be the argmax there (a handful of fp near-ties allowed).
    Whole-sequence equality against generate() is ill-posed on random weights
    at this depth: top-2 logit gaps run ~0.01-0.07 (std 0.55), below the f32
    reduction-order noise of differently-fused XLA programs — generate()
    itself emits different greedy tokens at batch 8 vs batch 1. Exact
    token-for-token parity is asserted on the tiny model above, where the
    gaps dwarf the noise (TestEngineTiny covers staggered AND preemption)."""
    from tnn_tpu.models.zoo import create

    model = create("gpt2_small")
    params = model.init(jax.random.PRNGKey(0), (1, 8))["params"]
    rng = np.random.default_rng(0)
    prompts = rng.integers(0, model.vocab_size, (8, 12)).astype(np.int32)
    max_new = 16

    # pool sized so 8 requests of 28 tokens (2 blocks each) exhaust it:
    # 13 usable blocks < 8 * 2 -> preemption must fire and recover
    eng = InferenceEngine(model, params, num_blocks=14, block_size=16,
                          max_batch_size=8, max_seq_len=32)
    rids = []
    for i, p in enumerate(prompts):
        rids.append(eng.submit(p, max_new))
        if i % 3 == 2:
            eng.step()  # staggered: some decode before others submit
    out = eng.run_until_complete()

    assert eng.metrics.preemptions > 0, "pool was never exhausted"
    assert eng.pool.num_allocated == 0
    assert all(len(out[rid]) == max_new for rid in rids)

    seqs = np.stack([np.concatenate([prompts[i], out[rids[i]]])
                     for i in range(len(rids))])
    caches = model.init_cache(len(rids), seqs.shape[1])
    logits, _ = model.apply_cached(params, jnp.asarray(seqs), caches, 0)
    logits = np.asarray(logits, np.float64)
    plen = prompts.shape[1]
    exact, ties = 0, []
    for i in range(len(rids)):
        for j in range(max_new):
            row = logits[i, plen + j - 1]
            chosen = seqs[i, plen + j]
            if chosen == row.argmax():
                exact += 1
            else:
                ties.append(float(row.max() - row[chosen]))
    total = len(rids) * max_new
    # measured: 124/128 exact, worst near-tie margin 0.0088 — far under the
    # ~0.01+ top-2 gaps a non-greedy bug would violate
    assert exact >= 0.9 * total, f"only {exact}/{total} tokens were argmax"
    assert all(m < 0.05 for m in ties), f"non-tie divergence: {ties}"


@pytest.mark.slow
def test_gpt2_small_paged_matches_standard():
    """Acceptance bar for the paged decode path: on gpt2_small, staggered
    submissions with preemption, decode_path="paged" must produce
    TOKEN-IDENTICAL streams to "standard".

    Unlike the teacher-forced test above, exact equality is well-posed here:
    both engines run the same schedule over the same weights, so every
    near-tie must resolve the same way — any divergence is a real paged-path
    bug (wrong page read/write, off-by-one kv length, table mix-up), not fp
    noise."""
    from tnn_tpu.models.zoo import create

    model = create("gpt2_small")
    params = model.init(jax.random.PRNGKey(0), (1, 8))["params"]
    rng = np.random.default_rng(0)
    prompts = rng.integers(0, model.vocab_size, (8, 12)).astype(np.int32)
    max_new = 16

    def run(path):
        eng = InferenceEngine(model, params, num_blocks=14, block_size=16,
                              max_batch_size=8, max_seq_len=32,
                              decode_path=path)
        rids = []
        for i, p in enumerate(prompts):
            rids.append(eng.submit(p, max_new))
            if i % 3 == 2:
                eng.step()
        out = eng.run_until_complete()
        return eng, [out[r] for r in rids]

    eng_p, paged = run("paged")
    eng_s, std = run("standard")
    assert eng_p.metrics.preemptions > 0, "pool was never exhausted"
    assert eng_s.metrics.preemptions > 0
    assert paged == std
    assert eng_p.pool.num_allocated == 0


@pytest.mark.slow
def test_gpt2_small_chunked_paged_matches_standard():
    """Chunked-prefill acceptance on gpt2_small: chunk_size=8 splits every
    12-token prompt across two mixed steps, the pool preempts under load,
    and the paged path must stay TOKEN-IDENTICAL to the standard path.

    As above, exact equality is well-posed because both engines run the same
    schedule over the same weights — identical near-tie resolution — so any
    divergence is a real mixed-step bug (ragged query gather, chunk scatter,
    per-row kv length), not fp noise."""
    from tnn_tpu.models.zoo import create

    model = create("gpt2_small")
    params = model.init(jax.random.PRNGKey(0), (1, 8))["params"]
    rng = np.random.default_rng(0)
    prompts = rng.integers(0, model.vocab_size, (8, 12)).astype(np.int32)
    max_new = 16

    def run(path):
        eng = InferenceEngine(model, params, num_blocks=14, block_size=16,
                              max_batch_size=8, max_seq_len=32,
                              decode_path=path, chunk_size=8)
        rids = []
        for i, p in enumerate(prompts):
            rids.append(eng.submit(p, max_new))
            if i % 3 == 2:
                eng.step()
        out = eng.run_until_complete()
        return eng, [out[r] for r in rids]

    eng_p, paged = run("paged")
    eng_s, std = run("standard")
    assert eng_p.metrics.preemptions > 0, "pool was never exhausted"
    assert eng_p.metrics.prefill_chunks > len(prompts), "prompts never split"
    assert paged == std
    assert eng_p.pool.num_allocated == 0
    assert eng_p.pool.num_free + eng_p.pool.num_evictable == \
        eng_p.pool.capacity


# -- fault tolerance: invariants, lifecycle, backpressure, chaos --------------


def _assert_drained(eng):
    """The chaos invariant: every submitted request terminal, no leaked
    blocks, bookkeeping clean. With the prefix cache on (the default),
    a drained pool may hold zero-ref EVICTABLE blocks — reclaimable cached
    KV — so the partition is free + evictable == capacity, allocated 0."""
    states = {r.rid: r.state for r in eng.requests.values()}
    assert all(s in TERMINAL_STATES for s in states.values()), states
    assert not eng.has_work
    assert eng.pool.num_allocated == 0
    assert eng.pool.num_free + eng.pool.num_evictable == eng.pool.capacity
    if eng.prefix_cache is None:
        assert eng.pool.num_evictable == 0
    eng.check_invariants()


def _finished(eng):
    return {rid: list(r.out_tokens) for rid, r in eng.requests.items()
            if r.state is RequestState.FINISHED}


class TestPoolInvariants:
    def _pool(self):
        return PagedKVPool(num_layers=1, num_kv_heads=1, head_dim=2,
                           num_blocks=8, block_size=4)

    def test_clean_pool_passes(self):
        pool = self._pool()
        blocks = pool.alloc(3)
        pool.check_invariants()
        pool.check_invariants([blocks])
        pool.free(blocks)
        pool.check_invariants([])

    def test_double_circulation_detected(self):
        pool = self._pool()
        blocks = pool.alloc(2)
        pool._free.append(blocks[0])      # corrupt: free AND allocated
        with pytest.raises(ValueError, match="both free and allocated"):
            pool.check_invariants()

    def test_scratch_never_circulates(self):
        pool = self._pool()
        pool._ref[PagedKVPool.SCRATCH] = 1
        with pytest.raises(ValueError, match="scratch"):
            pool.check_invariants()

    def test_leak_detected_via_tables(self):
        """A block allocated but owned by no live table is a leak."""
        pool = self._pool()
        blocks = pool.alloc(2)
        with pytest.raises(ValueError, match="leaked"):
            pool.check_invariants([])     # nobody claims `blocks`
        pool.check_invariants([blocks])   # claimed: clean
        del blocks

    def test_overshared_block_detected(self):
        pool = self._pool()
        blocks = pool.alloc(1)
        with pytest.raises(ValueError, match="mismatch"):
            pool.check_invariants([blocks, blocks])  # refcount 1, 2 tables
        pool.fork(blocks)
        pool.check_invariants([blocks, blocks])      # refcount 2: fine

    def test_count_mismatch_detected(self):
        pool = self._pool()
        pool._free.pop()                  # block vanishes entirely
        with pytest.raises(ValueError, match="capacity"):
            pool.check_invariants()

    def test_debug_mode_checks_on_free(self, monkeypatch):
        monkeypatch.setenv("TNN_POOL_DEBUG", "1")
        pool = self._pool()
        assert pool.debug
        a = pool.alloc(2)
        pool.free(a)                      # clean: no raise
        b = pool.alloc(1)
        pool._free.append(b[0])           # corrupt behind the pool's back
        with pytest.raises(ValueError):
            pool.free(b)


class TestFaultPlan:
    def test_nth_call_alloc_failure_is_exact(self):
        plan = FaultPlan(alloc_fail_calls=(3,))
        pool = PagedKVPool(num_layers=1, num_kv_heads=1, head_dim=2,
                           num_blocks=8, block_size=4)
        pool.fault_plan = plan
        pool.free(pool.alloc(1))
        pool.free(pool.alloc(1))
        with pytest.raises(PoolExhausted, match="injected"):
            pool.alloc(1)
        pool.free(pool.alloc(1))          # call 4: passes again
        assert plan.calls["pool.alloc"] == 4
        assert plan.fired["pool.alloc"] == 1
        pool.check_invariants()           # rejected alloc mutated nothing

    def test_seeded_plans_are_deterministic(self):
        def trace(plan):
            fires = []
            for _ in range(64):
                try:
                    plan.on_alloc(1, 8)
                    fires.append(False)
                except PoolExhausted:
                    fires.append(True)
            return fires

        a = trace(FaultPlan(seed=11, alloc_fail_prob=0.3))
        b = trace(FaultPlan(seed=11, alloc_fail_prob=0.3))
        c = trace(FaultPlan(seed=12, alloc_fail_prob=0.3))
        assert a == b
        assert any(a) and not all(a)
        assert a != c                     # different seed, different schedule

    def test_poison_rows_nth_call_hits_row_zero(self):
        plan = FaultPlan(nan_logit_calls=(2,))
        assert not plan.poison_rows(3).any()
        mask = plan.poison_rows(3)
        assert mask.tolist() == [True, False, False]
        assert plan.fired["decode.logits"] == 1

    def test_connection_sites_are_deterministic(self):
        """The client-side sites (disconnect / slow / malformed) draw from
        the same seeded rng as the engine sites: identical seeds produce
        identical fire traces, so a chaos soak replays bit-for-bit."""
        def trace(plan):
            return [(plan.client_disconnect(), plan.slow_consumer(),
                     plan.malformed_request()) for _ in range(48)]

        kw = dict(client_disconnect_prob=0.3, slow_consumer_prob=0.25,
                  malformed_request_prob=0.2)
        a = trace(FaultPlan(seed=5, **kw))
        b = trace(FaultPlan(seed=5, **kw))
        c = trace(FaultPlan(seed=6, **kw))
        assert a == b
        assert a != c
        assert any(t[0] for t in a) and any(t[1] for t in a) \
            and any(t[2] for t in a)
        plan = FaultPlan(seed=5, **kw)
        trace(plan)
        assert plan.calls["client.disconnect"] == 48
        assert plan.fired["client.disconnect"] == sum(t[0] for t in a)
        assert plan.fired["client.slow"] == sum(t[1] for t in a)
        assert plan.fired["client.malformed"] == sum(t[2] for t in a)

    def test_scheduled_connection_calls_fire_exactly(self):
        plan = FaultPlan(client_disconnect_calls=(2,),
                         malformed_request_calls=(1, 3))
        assert [plan.client_disconnect() for _ in range(3)] == \
            [False, True, False]
        assert [plan.malformed_request() for _ in range(3)] == \
            [True, False, True]

    def test_replica_sites_are_deterministic(self):
        """The router-side sites (replica.kill / net.delay / net.drop) draw
        from the same seeded rng: identical seeds replay identical kill and
        network-fault schedules, so a failover soak is reproducible."""
        def trace(plan):
            return [(plan.replica_kill(), plan.net_delay(), plan.net_drop())
                    for _ in range(48)]

        kw = dict(replica_kill_prob=0.2, net_delay_prob=0.3,
                  net_drop_prob=0.25)
        a = trace(FaultPlan(seed=5, **kw))
        b = trace(FaultPlan(seed=5, **kw))
        c = trace(FaultPlan(seed=6, **kw))
        assert a == b
        assert a != c
        assert any(t[0] for t in a) and any(t[1] for t in a) \
            and any(t[2] for t in a)
        plan = FaultPlan(seed=5, **kw)
        trace(plan)
        assert plan.calls["replica.kill"] == 48
        assert plan.fired["replica.kill"] == sum(t[0] for t in a)
        assert plan.fired["net.delay"] == sum(t[1] for t in a)
        assert plan.fired["net.drop"] == sum(t[2] for t in a)

    def test_scheduled_replica_calls_fire_exactly(self):
        plan = FaultPlan(replica_kill_calls=(3,), net_drop_calls=(1, 2))
        assert [plan.replica_kill() for _ in range(4)] == \
            [False, False, True, False]
        assert [plan.net_drop() for _ in range(3)] == [True, True, False]
        assert plan.fired["replica.kill"] == 1
        assert plan.fired["net.drop"] == 2

    def test_step_crash_fires_at_exact_call_and_escapes(self):
        """EngineCrash is deliberately NOT FaultInjected — nothing inside
        the engine may catch it (only the supervisor recovers)."""
        from tnn_tpu.serving import FaultInjected

        plan = FaultPlan(step_crash_calls=(3,))
        plan.on_step()
        plan.on_step()
        with pytest.raises(EngineCrash, match="step #3"):
            plan.on_step()
        plan.on_step()                    # call 4: passes again
        assert plan.fired["engine.step"] == 1
        assert not issubclass(EngineCrash, FaultInjected)

    def test_step_delay_calls_select_steps(self):
        plan = FaultPlan(step_delay_s=0.02, step_delay_calls=(2,))
        t0 = time.perf_counter()
        plan.on_step()
        fast = time.perf_counter() - t0
        t0 = time.perf_counter()
        plan.on_step()
        slow = time.perf_counter() - t0
        assert slow >= 0.02 > fast


class TestLifecycle:
    """Cancellation, deadlines, and bounded admission on the tiny model."""

    KW = dict(num_blocks=32, block_size=4, max_batch_size=4, max_seq_len=32)

    def test_cancel_while_queued(self, tiny_lm):
        model, params = tiny_lm
        eng = InferenceEngine(model, params, num_blocks=32, block_size=4,
                              max_batch_size=1, max_seq_len=32)
        p = np.arange(5, dtype=np.int32)
        r0 = eng.submit(p, 6)
        eng.step()                                  # r0 admitted
        r1 = eng.submit(p, 6)                       # stuck behind r0 (batch 1)
        assert eng.cancel(r1)
        assert eng.result(r1).state is RequestState.CANCELLED
        out = eng.run_until_complete()
        assert out[r0] == _greedy_ref(model, params, p, 6, eng.assembly_len)
        assert r1 not in out
        assert eng.metrics.cancelled == 1
        _assert_drained(eng)

    def test_cancel_while_running_frees_blocks(self, tiny_lm):
        model, params = tiny_lm
        eng = InferenceEngine(model, params, **self.KW)
        rid = eng.submit(np.arange(6, dtype=np.int32), 20)
        eng.step()
        assert eng.result(rid).state is RequestState.RUNNING
        assert eng.pool.num_allocated > 0
        assert eng.cancel(rid)
        assert eng.result(rid).state is RequestState.CANCELLED
        _assert_drained(eng)

    def test_cancel_terminal_or_unknown_is_noop(self, tiny_lm):
        model, params = tiny_lm
        eng = InferenceEngine(model, params, **self.KW)
        rid = eng.submit(np.arange(4, dtype=np.int32), 2)
        eng.run_until_complete()
        assert not eng.cancel(rid)                  # already FINISHED
        assert not eng.cancel(12345)                # never existed
        assert eng.result(rid).state is RequestState.FINISHED

    def test_deadline_expires_while_queued(self, tiny_lm):
        model, params = tiny_lm
        eng = InferenceEngine(model, params, **self.KW)
        rid = eng.submit(np.arange(4, dtype=np.int32), 4, deadline_s=0.0)
        events = eng.step()
        assert [rid_ for rid_, _ in events["timed_out"]] == [rid]
        req = eng.result(rid)
        assert req.state is RequestState.TIMED_OUT
        assert "deadline" in req.error
        assert eng.metrics.timed_out == 1
        _assert_drained(eng)

    def test_deadline_expires_while_running(self, tiny_lm):
        model, params = tiny_lm
        eng = InferenceEngine(model, params, **self.KW)
        rid = eng.submit(np.arange(4, dtype=np.int32), 25, deadline_s=0.15)
        eng.step()
        assert eng.result(rid).state is RequestState.RUNNING
        time.sleep(0.2)
        eng.step()
        req = eng.result(rid)
        assert req.state is RequestState.TIMED_OUT
        assert req.out_tokens, "made progress before the deadline"
        _assert_drained(eng)

    def test_max_queue_s_expires_only_queued(self, tiny_lm):
        model, params = tiny_lm
        eng = InferenceEngine(model, params, num_blocks=32, block_size=4,
                              max_batch_size=1, max_seq_len=32)
        p = np.arange(4, dtype=np.int32)
        r0 = eng.submit(p, 6)
        eng.step()                                  # r0 running
        r1 = eng.submit(p, 6, max_queue_s=0.0)      # expires at next step
        eng.step()
        assert eng.result(r1).state is RequestState.TIMED_OUT
        assert "max_queue_s" in eng.result(r1).error
        out = eng.run_until_complete()
        assert out[r0] == _greedy_ref(model, params, p, 6, eng.assembly_len)
        _assert_drained(eng)

    def test_admission_reject_backpressure(self, tiny_lm):
        model, params = tiny_lm
        eng = InferenceEngine(model, params, max_queue_depth=2,
                              admission_policy="reject", **self.KW)
        p = np.arange(4, dtype=np.int32)
        eng.submit(p, 4)
        eng.submit(p, 4)
        with pytest.raises(AdmissionRejected) as ei:
            eng.submit(p, 4)
        assert ei.value.queue_depth == 2
        assert ei.value.max_queue_depth == 2
        assert eng.metrics.rejected == 1
        assert len(eng.requests) == 2               # rejected never entered
        eng.run_until_complete()
        _assert_drained(eng)

    def test_admission_block_drains_then_accepts(self, tiny_lm):
        model, params = tiny_lm
        eng = InferenceEngine(model, params, max_queue_depth=1,
                              admission_policy="block", **self.KW)
        p = np.arange(4, dtype=np.int32)
        ref = _greedy_ref(model, params, p, 6, eng.assembly_len)
        rids = [eng.submit(p, 6) for _ in range(4)]  # blocks, never raises
        out = eng.run_until_complete()
        assert [out[r] for r in rids] == [ref] * 4
        assert eng.metrics.rejected == 0
        _assert_drained(eng)

    def test_preemption_budget_fails_victim_cleanly(self, tiny_lm):
        """With budget 0 the first would-be preemption victim FAILs (blocks
        freed) instead of thrashing; everyone else still finishes exactly."""
        model, params = tiny_lm
        rng = np.random.default_rng(1)
        prompts = [rng.integers(0, 128, n).astype(np.int32)
                   for n in (5, 9, 16, 7)]
        eng = InferenceEngine(model, params, num_blocks=9, block_size=4,
                              max_batch_size=4, max_seq_len=32,
                              preemption_budget=0)
        rids = [eng.submit(p, 10) for p in prompts]
        eng.run_until_complete()
        failed = [r for r in eng.requests.values()
                  if r.state is RequestState.FAILED]
        assert failed, "pool never filled — scenario broken"
        assert all("preemption budget" in r.error for r in failed)
        assert eng.metrics.preemptions == 0
        assert eng.metrics.failed == len(failed)
        out = _finished(eng)
        for rid, p in zip(rids, prompts):
            if rid in out:
                assert out[rid] == _greedy_ref(model, params, p, 10,
                                               eng.assembly_len)
        _assert_drained(eng)

    def test_stats_shape(self, tiny_lm):
        model, params = tiny_lm
        eng = InferenceEngine(model, params, **self.KW)
        rid = eng.submit(np.arange(4, dtype=np.int32), 3)
        eng.cancel(rid)
        eng.submit(np.arange(4, dtype=np.int32), 3)
        eng.run_until_complete()
        s = eng.stats()
        assert s["requests_cancelled"] == 1
        assert s["requests_finished"] == 1
        assert s["cancelled"] == 1
        assert s["pool_allocated_blocks"] == 0
        assert s["queue_depth"] == 0 and s["num_running"] == 0
        assert s["decode_path"] in ("paged", "fused", "standard")


class TestChaos:
    """Seeded FaultPlan runs: every request reaches a terminal state,
    survivors are token-identical to a fault-free run, zero leaked blocks."""

    KW = dict(num_blocks=32, block_size=4, max_batch_size=4, max_seq_len=32)

    def _prompts(self, n, seed=0):
        rng = np.random.default_rng(seed)
        return [rng.integers(0, 128, int(l)).astype(np.int32)
                for l in rng.integers(4, 14, n)]

    def _run(self, model, params, prompts, max_new=8, plan=None, **kw):
        merged = dict(self.KW)
        merged.update(kw)
        eng = InferenceEngine(model, params, faults=plan, **merged)
        rids = [eng.submit(p, max_new) for p in prompts]
        eng.run_until_complete()
        return eng, rids

    def test_alloc_failure_mid_prefill_is_isolated(self, tiny_lm):
        model, params = tiny_lm
        prompts = self._prompts(3)
        ref_eng, ref_rids = self._run(model, params, prompts)
        plan = FaultPlan(alloc_fail_calls=(2,))     # r1's prefill alloc
        eng, rids = self._run(model, params, prompts, plan=plan)
        assert eng.result(rids[1]).state is RequestState.FAILED
        assert "injected allocation failure" in eng.result(rids[1]).error
        out, ref = _finished(eng), _finished(ref_eng)
        for i in (0, 2):
            assert out[rids[i]] == ref[ref_rids[i]]
        _assert_drained(eng)

    def test_alloc_failure_mid_decode_is_isolated(self, tiny_lm):
        """Growth alloc fails for one request mid-decode; the other finishes
        token-exact — a pool fault no longer aborts unrelated requests."""
        model, params = tiny_lm
        p = np.arange(6, dtype=np.int32)
        ref_eng, ref_rids = self._run(model, params, [p, p])
        # alloc calls: prefill r0 (1), prefill r1 (2), growth r0 (3), ...
        plan = FaultPlan(alloc_fail_calls=(3,))
        eng, rids = self._run(model, params, [p, p], plan=plan)
        assert eng.result(rids[0]).state is RequestState.FAILED
        assert "mid-decode" in eng.result(rids[0]).error
        assert _finished(eng)[rids[1]] == _finished(ref_eng)[ref_rids[1]]
        assert plan.fired["pool.alloc"] == 1
        _assert_drained(eng)

    def test_alloc_failure_at_chunk_boundary_is_isolated(self, tiny_lm):
        """A chunked prompt's block alloc fails at a chunk boundary (between
        chunk 1 and chunk 2): only that request FAILs, its partial blocks are
        freed, and the co-scheduled request finishes token-exact."""
        model, params = tiny_lm
        prompts = [np.arange(12, dtype=np.int32),    # 3 chunks at chunk_size 4
                   np.arange(4, dtype=np.int32)]     # 1 chunk
        ref_eng, ref_rids = self._run(model, params, prompts, chunk_size=4)
        # alloc calls: step1 chunk r0 (1), chunk r1 (2); step2 chunk r0 (3)
        plan = FaultPlan(alloc_fail_calls=(3,))
        eng, rids = self._run(model, params, prompts, plan=plan,
                              chunk_size=4)
        victim = eng.result(rids[0])
        assert victim.state is RequestState.FAILED
        assert "at chunk boundary" in victim.error
        assert not victim.out_tokens, "failed mid-prefill, before any token"
        assert plan.fired["pool.alloc"] == 1
        assert _finished(eng)[rids[1]] == _finished(ref_eng)[ref_rids[1]]
        _assert_drained(eng)

    def test_nan_logits_in_decode_fail_one_row(self, tiny_lm):
        model, params = tiny_lm
        prompts = self._prompts(3, seed=2)
        ref_eng, ref_rids = self._run(model, params, prompts)
        plan = FaultPlan(nan_logit_calls=(2,))      # row 0 of decode call 2
        eng, rids = self._run(model, params, prompts, plan=plan)
        victim = eng.result(rids[0])
        assert victim.state is RequestState.FAILED
        assert "non-finite logits" in victim.error
        assert victim.out_tokens, "failed after producing valid tokens"
        out, ref = _finished(eng), _finished(ref_eng)
        for i in (1, 2):
            assert out[rids[i]] == ref[ref_rids[i]]
        _assert_drained(eng)

    def test_nan_logits_in_prefill_fail_request(self, tiny_lm):
        model, params = tiny_lm
        prompts = self._prompts(3, seed=3)
        ref_eng, ref_rids = self._run(model, params, prompts)
        plan = FaultPlan(nan_prefill_calls=(2,))
        eng, rids = self._run(model, params, prompts, plan=plan)
        assert eng.result(rids[1]).state is RequestState.FAILED
        assert "prefill" in eng.result(rids[1]).error
        out, ref = _finished(eng), _finished(ref_eng)
        for i in (0, 2):
            assert out[rids[i]] == ref[ref_rids[i]]
        _assert_drained(eng)

    def test_logit_guard_can_be_disabled(self, tiny_lm):
        """With the guard off a poisoned row is NOT failed — the garbage
        token streams through (caller's choice to run unguarded)."""
        model, params = tiny_lm
        plan = FaultPlan(nan_logit_calls=(2,))
        eng, rids = self._run(model, params, self._prompts(2, seed=4),
                              plan=plan, logit_guard=False)
        assert all(eng.result(r).state is RequestState.FINISHED
                   for r in rids)
        _assert_drained(eng)

    def test_transient_step_exception_retries_exactly(self, tiny_lm):
        """A transient decode fault is retried with the SAME key: outputs
        are bit-identical to a fault-free run — the fault is invisible."""
        model, params = tiny_lm
        prompts = self._prompts(3, seed=5)
        ref_eng, ref_rids = self._run(model, params, prompts)
        plan = FaultPlan(decode_exc_calls=(2,), transient_exc=True)
        eng, rids = self._run(model, params, prompts, plan=plan)
        assert plan.fired["decode"] == 1
        assert eng.metrics.step_retries == 1
        out, ref = _finished(eng), _finished(ref_eng)
        assert [out[r] for r in rids] == [ref[r] for r in ref_rids]
        _assert_drained(eng)

    def test_persistent_step_exception_aborts_batch_only(self, tiny_lm):
        """A hard decode failure fails the LIVE batch but the engine keeps
        serving: queued requests still complete token-exact."""
        model, params = tiny_lm
        p = np.arange(6, dtype=np.int32)
        ref_eng, ref_rids = self._run(model, params, [p, p, p],
                                      max_batch_size=2)
        plan = FaultPlan(decode_exc_calls=(1,), transient_exc=False)
        eng, rids = self._run(model, params, [p, p, p], plan=plan,
                              max_batch_size=2)
        for r in rids[:2]:                          # the aborted batch
            assert eng.result(r).state is RequestState.FAILED
            assert "injected persistent fault" in eng.result(r).error
        assert _finished(eng)[rids[2]] == _finished(ref_eng)[ref_rids[2]]
        _assert_drained(eng)

    def test_chaos_gate(self, tiny_lm):
        """The acceptance gate: >=10% pool-alloc failure probability plus
        injected NaN logits on the tiny gpt2. Every submitted request must
        reach a terminal state, survivors must be token-identical to a
        fault-free run, and the pool must end with zero leaked blocks."""
        model, params = tiny_lm
        prompts = self._prompts(8, seed=6)
        kw = dict(num_blocks=16, block_size=4, max_batch_size=4,
                  max_seq_len=32)
        ref_eng, ref_rids = self._run(model, params, prompts, **kw)
        plan = FaultPlan(seed=9, alloc_fail_prob=0.12, nan_logit_calls=(5,))
        eng, rids = self._run(model, params, prompts, plan=plan, **kw)
        assert plan.fired["pool.alloc"] >= 1, "chaos never fired — dead test"
        states = [eng.result(r).state for r in rids]
        assert all(s in TERMINAL_STATES for s in states)
        assert RequestState.FAILED in states, "no request failed"
        assert RequestState.FINISHED in states, "no request survived"
        out, ref = _finished(eng), _finished(ref_eng)
        for rid, ref_rid in zip(rids, ref_rids):
            if rid in out:
                assert out[rid] == ref[ref_rid], f"survivor {rid} diverged"
        _assert_drained(eng)

    def test_chaos_gate_paged_path(self, tiny_lm):
        """Same gate over the paged decode path (its own compiled step and
        KV plumbing must honor the same isolation)."""
        model, params = tiny_lm
        prompts = self._prompts(6, seed=7)
        kw = dict(num_blocks=16, block_size=4, max_batch_size=4,
                  max_seq_len=32, decode_path="paged")
        ref_eng, ref_rids = self._run(model, params, prompts, **kw)
        plan = FaultPlan(seed=13, alloc_fail_prob=0.12, nan_logit_calls=(4,))
        eng, rids = self._run(model, params, prompts, plan=plan, **kw)
        assert plan.fired["pool.alloc"] >= 1
        out, ref = _finished(eng), _finished(ref_eng)
        for rid, ref_rid in zip(rids, ref_rids):
            if rid in out:
                assert out[rid] == ref[ref_rid]
        _assert_drained(eng)


# -- prefix cache: hash-chain index, evictable pool, engine-level reuse -------


class TestPrefixCacheIndex:
    """Host-side hash-chain unit tests — no engine, no device arrays."""

    def test_chain_commits_to_whole_prefix(self):
        pc = PrefixCache(block_size=4)
        a = np.arange(8, dtype=np.int32)
        b = a.copy()
        b[0] ^= 1                       # differ only inside block 0
        ka, kb = pc.chain_keys(a), pc.chain_keys(b)
        assert ka[0] != kb[0]
        assert ka[1] != kb[1], "block-1 key must commit to the whole prefix"

    def test_no_false_sharing_on_divergent_prefix(self):
        """Identical block-1 TOKENS under a different block 0 must not match
        block 1 — the chain key commits to the entire preceding prefix."""
        pc = PrefixCache(block_size=4)
        a = np.arange(12, dtype=np.int32)
        pc.publish(a, [3, 4, 5], 8)     # blocks 0 and 1 of `a` indexed
        b = a.copy()
        b[0] ^= 1                       # blocks 1+ identical to a's
        assert pc.probe(b) == ([], 0, False)

    def test_probe_returns_longest_indexed_chain(self):
        pc = PrefixCache(block_size=4)
        toks = np.arange(12, dtype=np.int32)
        assert pc.probe(toks) == ([], 0, False)
        pc.publish(toks, [5, 6, 7], 12)
        ext = np.concatenate([toks, np.asarray([99], np.int32)])
        assert pc.probe(ext) == ([5, 6, 7], 12, False)
        div = ext.copy()
        div[9] ^= 1                     # diverges inside block 2
        assert pc.probe(div) == ([5, 6], 8, False)

    def test_full_cover_probe_caps_for_cow(self):
        """A fully-cached prompt still recomputes >= 1 token (it needs
        logits to sample its first output), so probe caps cached_len at
        total - 1 and flags that blocks[-1] needs a private COW copy."""
        pc = PrefixCache(block_size=4)
        toks = np.arange(8, dtype=np.int32)
        pc.publish(toks, [3, 4], 8)
        assert pc.probe(toks) == ([3, 4], 7, True)

    def test_min_hit_blocks_filters_short_matches(self):
        pc = PrefixCache(block_size=4, min_hit_blocks=2)
        toks = np.arange(12, dtype=np.int32)
        pc.publish(toks, [3, 4], 4)     # only block 0 is full-published
        assert pc.probe(toks) == ([], 0, False)
        pc.publish(toks, [3, 4], 8)     # now a 2-block chain
        assert pc.probe(toks) == ([3, 4], 8, False)

    def test_publish_first_wins_and_partial_excluded(self):
        pc = PrefixCache(block_size=4)
        toks = np.arange(10, dtype=np.int32)
        assert pc.publish(toks, [3, 4, 5], 10) == 2  # block 2 partial: skipped
        assert pc.publish(toks, [8, 9, 10], 10) == 0  # twin loses: dedupe
        assert pc.probe(toks)[0] == [3, 4]

    def test_drop_blocks_breaks_chain_at_parent(self):
        pc = PrefixCache(block_size=4)
        toks = np.arange(8, dtype=np.int32)
        pc.publish(toks, [3, 4], 8)
        pc.drop_blocks([3])             # parent reclaimed
        ext = np.concatenate([toks, np.asarray([9], np.int32)])
        assert pc.probe(ext) == ([], 0, False)   # probe walks from block 0
        assert len(pc) == 1 and pc.contains_block(4)  # orphaned child entry
        pc.drop_blocks([4, 99])         # unknown ids tolerated
        assert len(pc) == 0 and not pc.contains_block(4)


class TestEvictablePool:
    """free() parks zero-ref cache-indexed blocks in an evictable LRU;
    alloc() reclaims them on demand — cached KV never shrinks capacity."""

    def _pool(self, **kw):
        kw.setdefault("num_layers", 1)
        kw.setdefault("num_kv_heads", 1)
        kw.setdefault("head_dim", 2)
        kw.setdefault("num_blocks", 8)
        kw.setdefault("block_size", 4)
        pool = PagedKVPool(**kw)
        pool.evictable_filter = lambda b: True   # every block "indexed"
        return pool

    def test_free_parks_then_alloc_reclaims_lru(self):
        pool = self._pool()
        a = pool.alloc(3)
        pool.free(a)
        assert pool.num_evictable == 3 and pool.num_free == 4
        assert pool.num_allocated == 0 and pool.num_allocatable == 7
        pool.check_invariants([])
        reclaimed = []
        pool.reclaim_hook = reclaimed.extend
        pool.alloc(6)                   # needs 2 beyond the free list
        # free() parks deepest-first, so the LRU-oldest blocks are the
        # chain TAIL: a[2] then a[1] go first, the parent a[0] survives
        assert reclaimed == [a[2], a[1]]
        assert pool.num_evictable == 1
        pool.check_invariants()

    def test_fork_revives_evictable(self):
        pool = self._pool()
        a = pool.alloc(2)
        pool.free(a)
        assert pool.is_evictable(a[0]) and pool.is_evictable(a[1])
        table = pool.fork(a)            # cache hit on parked blocks
        assert pool.num_evictable == 0 and pool.num_allocated == 2
        pool.check_invariants([table])
        pool.free(table)
        assert pool.num_evictable == 2
        pool.check_invariants([])

    def test_filter_selects_which_blocks_park(self):
        pool = self._pool()
        a = pool.alloc(4)
        indexed = {a[1], a[3]}
        pool.evictable_filter = indexed.__contains__
        pool.free(a)
        assert pool.num_evictable == 2 and pool.num_free == 5
        assert all(pool.is_evictable(b) for b in indexed)
        pool.check_invariants([])

    def test_exhaustion_counts_evictable_as_capacity(self):
        pool = self._pool()
        a = pool.alloc(7)
        pool.free(a[:3])                # 3 evictable, 4 still held
        assert pool.num_allocatable == 3 and pool.can_alloc(3)
        with pytest.raises(PoolExhausted):
            pool.alloc(4)               # beyond free + evictable
        assert pool.num_evictable == 3, "failed alloc must reclaim nothing"
        got = pool.alloc(3)             # exactly the cached pages
        assert set(got) == set(a[:3])
        pool.check_invariants()

    def test_purge_evictable(self):
        pool = self._pool()
        dropped = []
        pool.reclaim_hook = dropped.extend
        a = pool.alloc(3)
        pool.free(a)
        assert sorted(pool.purge_evictable()) == sorted(a)
        assert sorted(dropped) == sorted(a)
        assert pool.num_evictable == 0 and pool.num_free == 7
        pool.check_invariants([])

    def test_invariants_catch_evictable_and_free(self):
        pool = self._pool()
        a = pool.alloc(2)
        pool.free(a)
        pool._free.append(a[0])         # corrupt: evictable AND free
        with pytest.raises(ValueError, match="evictable and free"):
            pool.check_invariants()

    def test_invariants_catch_evictable_with_refcount(self):
        pool = self._pool()
        a = pool.alloc(1)
        pool._evictable[a[0]] = None    # corrupt: allocated AND evictable
        with pytest.raises(ValueError, match="evictable and allocated"):
            pool.check_invariants()

    def test_invariants_catch_use_after_free(self):
        """A live table referencing an evictable block is use-after-free:
        a reclaim would hand that page to another request mid-decode."""
        pool = self._pool()
        a = pool.alloc(2)
        pool.free(a)
        with pytest.raises(ValueError, match="use-after-free"):
            pool.check_invariants([a])


class TestPrefixCacheEngine:
    """End-to-end KV reuse on the tiny model: cache-on must be token-exact
    vs cache-off while measurably skipping prefill compute."""

    KW = dict(num_blocks=32, block_size=4, max_batch_size=4, max_seq_len=32)

    def _shared_prompts(self, n=4, prefix_len=12, tail_len=5, seed=0):
        rng = np.random.default_rng(seed)
        prefix = rng.integers(0, 128, prefix_len).astype(np.int32)
        return [np.concatenate([prefix,
                                rng.integers(0, 128, tail_len)
                                .astype(np.int32)]) for _ in range(n)]

    def _run(self, model, params, prompts, max_new=8, stagger=0, **kw):
        merged = dict(self.KW)
        merged.update(kw)
        eng = InferenceEngine(model, params, **merged)
        rids = []
        for i, p in enumerate(prompts):
            rids.append(eng.submit(p, max_new))
            if stagger and i % stagger == stagger - 1:
                eng.step()
        out = eng.run_until_complete()
        return eng, [out[r] for r in rids]

    def test_cache_on_equals_cache_off_staggered(self, tiny_lm):
        model, params = tiny_lm
        prompts = self._shared_prompts()
        eng_on, on = self._run(model, params, prompts, stagger=1)
        eng_off, off = self._run(model, params, prompts, stagger=1,
                                 prefix_cache=False)
        assert on == off
        assert eng_off.prefix_cache is None
        assert eng_on.metrics.prefill_tokens_saved > 0, "cache never hit"
        assert eng_off.metrics.prefill_tokens_saved == 0
        s = eng_on.metrics.summary()
        assert s["prefix_hit_rate"] > 0
        assert s["prefill_tokens_saved"] == \
            eng_on.metrics.prefill_tokens_saved
        for p, toks in zip(prompts, on):
            assert toks == _greedy_ref(model, params, p, 8, eng_on.assembly_len)
        _assert_drained(eng_on)
        _assert_drained(eng_off)

    def test_cache_on_equals_cache_off_paged(self, tiny_lm):
        """Same A/B over the paged decode path: forked tables must read
        identically through the ragged paged-attention kernel."""
        model, params = tiny_lm
        prompts = self._shared_prompts(seed=1)
        eng_on, on = self._run(model, params, prompts, stagger=1,
                               decode_path="paged")
        eng_off, off = self._run(model, params, prompts, stagger=1,
                                 decode_path="paged", prefix_cache=False)
        assert on == off
        assert eng_on.metrics.prefill_tokens_saved > 0, "cache never hit"
        _assert_drained(eng_on)
        _assert_drained(eng_off)

    def test_cache_on_equals_cache_off_under_preemption(self, tiny_lm):
        """A pool too small for the shared-prefix batch: preemption churns
        tables through free -> evictable -> revived, and outputs must stay
        token-exact against cache-off AND the offline reference."""
        model, params = tiny_lm
        prompts = self._shared_prompts(seed=2)
        kw = dict(num_blocks=9, block_size=4, max_batch_size=4,
                  max_seq_len=32)
        eng_on, on = self._run(model, params, prompts, **kw)
        eng_off, off = self._run(model, params, prompts,
                                 prefix_cache=False, **kw)
        assert eng_on.metrics.preemptions > 0, "pool was never exhausted"
        assert on == off
        for p, toks in zip(prompts, on):
            assert toks == _greedy_ref(model, params, p, 8, eng_on.assembly_len)
        _assert_drained(eng_on)
        _assert_drained(eng_off)

    def test_cow_at_partial_block_boundary(self, tiny_lm):
        """Resubmitting an identical prompt is a FULL-COVER hit: every full
        block matches, so the matcher's first KV write (its recomputed last
        token) would land inside the last matched block. The engine must
        give it a private copy — and the published original must survive
        intact for the next twin."""
        model, params = tiny_lm
        p = np.arange(8, dtype=np.int32)   # exactly 2 full blocks
        eng = InferenceEngine(model, params, **self.KW)
        ref = _greedy_ref(model, params, p, 8, eng.assembly_len)
        r0 = eng.submit(p, 8)
        assert eng.run_until_complete()[r0] == ref
        assert eng.metrics.prefix_cows == 0
        r1 = eng.submit(p, 8)
        assert eng.run_until_complete()[r1] == ref
        assert eng.metrics.prefix_cows == 1
        assert eng.metrics.prefill_tokens_saved == 7  # all but the last token
        r2 = eng.submit(p, 8)              # the COW copy stayed private:
        assert eng.run_until_complete()[r2] == ref
        assert eng.metrics.prefix_cows == 2
        assert eng.metrics.prefill_tokens_saved == 14
        _assert_drained(eng)

    def test_eviction_under_pressure(self, tiny_lm):
        """Distinct prompts through a small pool: cached blocks must be
        reclaimed (LRU) to serve fresh allocations — the cache never
        reduces usable capacity and never leaks."""
        model, params = tiny_lm
        eng = InferenceEngine(model, params, num_blocks=9, block_size=4,
                              max_batch_size=2, max_seq_len=32)
        dropped = []
        inner = eng.pool.reclaim_hook
        eng.pool.reclaim_hook = lambda bs: (dropped.extend(bs), inner(bs))
        rng = np.random.default_rng(5)
        for _ in range(4):
            p = rng.integers(0, 128, 12).astype(np.int32)
            rid = eng.submit(p, 6)
            out = eng.run_until_complete()
            assert out[rid] == _greedy_ref(model, params, p, 6,
                                           eng.assembly_len)
            eng.check_invariants()
        assert dropped, "pool pressure never evicted a cached block"
        assert len(eng.prefix_cache) <= eng.pool.capacity
        _assert_drained(eng)

    def test_min_hit_blocks_suppresses_short_hits(self, tiny_lm):
        model, params = tiny_lm
        prompts = self._shared_prompts(n=2, prefix_len=8, tail_len=5, seed=3)
        eng, out = self._run(model, params, prompts, stagger=1,
                             prefix_cache_min_hit_blocks=3)
        assert eng.metrics.prefill_tokens_saved == 0  # 2-block prefix < 3
        assert eng.metrics.prefix_hits == 0
        for p, toks in zip(prompts, out):
            assert toks == _greedy_ref(model, params, p, 8, eng.assembly_len)
        _assert_drained(eng)

    def test_stats_gauges(self, tiny_lm):
        model, params = tiny_lm
        prompts = self._shared_prompts(seed=4)
        eng_on, _ = self._run(model, params, prompts, stagger=1)
        s = eng_on.stats()
        assert s["prefix_cache_enabled"]
        assert s["prefix_indexed_blocks"] == len(eng_on.prefix_cache) > 0
        assert s["pool_evictable_blocks"] == eng_on.pool.num_evictable > 0
        eng_off, _ = self._run(model, params, prompts, prefix_cache=False)
        s = eng_off.stats()
        assert not s["prefix_cache_enabled"]
        assert s["prefix_indexed_blocks"] == 0
        assert s["pool_evictable_blocks"] == 0

    def test_chaos_gate_shared_prefix(self, tiny_lm):
        """The chaos gate re-run over a shared-prefix workload: alloc faults
        and a poisoned decode row while publish/fork/COW/evict churn the
        index. Every request terminal, survivors token-identical to the
        fault-free run, zero leaked blocks, partition invariants clean."""
        model, params = tiny_lm
        rng = np.random.default_rng(11)
        prefix = rng.integers(0, 128, 8).astype(np.int32)
        prompts = [np.concatenate([prefix, rng.integers(0, 128, int(t))
                                   .astype(np.int32)])
                   for t in rng.integers(2, 8, 8)]
        kw = dict(num_blocks=16, block_size=4, max_batch_size=4,
                  max_seq_len=32)

        def run(plan=None):
            eng = InferenceEngine(model, params, faults=plan, **kw)
            rids = [eng.submit(p, 8) for p in prompts]
            eng.run_until_complete()
            return eng, rids

        ref_eng, ref_rids = run()
        assert ref_eng.metrics.prefill_tokens_saved > 0, \
            "workload never exercised the cache — dead test"
        plan = FaultPlan(seed=21, alloc_fail_prob=0.12, nan_logit_calls=(5,))
        eng, rids = run(plan)
        assert plan.fired["pool.alloc"] >= 1, "chaos never fired — dead test"
        states = [eng.result(r).state for r in rids]
        assert all(s in TERMINAL_STATES for s in states)
        assert RequestState.FINISHED in states, "no request survived"
        out, ref = _finished(eng), _finished(ref_eng)
        for rid, ref_rid in zip(rids, ref_rids):
            if rid in out:
                assert out[rid] == ref[ref_rid], f"survivor {rid} diverged"
        _assert_drained(eng)
        _assert_drained(ref_eng)


@pytest.mark.slow
def test_gpt2_small_prefix_cache_matches_uncached():
    """Cache-on vs cache-off A/B on gpt2_small with chunk boundaries aligned
    to the cached prefix (prefix = 1 block = 1 chunk): the sharers' uncached
    tail chunk starts at the same position with the same width in both runs,
    so the compiled programs match and exact token equality is well-posed
    (the cached KV is bit-identical to what a recompute would produce — it
    IS the publisher's pages)."""
    from tnn_tpu.models.zoo import create

    model = create("gpt2_small")
    params = model.init(jax.random.PRNGKey(0), (1, 8))["params"]
    rng = np.random.default_rng(0)
    prefix = rng.integers(0, model.vocab_size, 16).astype(np.int32)
    prompts = [np.concatenate([prefix,
                               rng.integers(0, model.vocab_size, 8)
                               .astype(np.int32)]) for _ in range(4)]

    def run(cache):
        eng = InferenceEngine(model, params, num_blocks=32, block_size=16,
                              max_batch_size=4, max_seq_len=48,
                              chunk_size=16, prefix_cache=cache)
        rids = [eng.submit(prompts[0], 8)]
        eng.step(); eng.step()      # r0's two chunks land; prefix published
        rids += [eng.submit(p, 8) for p in prompts[1:]]
        out = eng.run_until_complete()
        return eng, [out[r] for r in rids]

    eng_on, on = run(True)
    eng_off, off = run(False)
    assert on == off
    assert eng_on.metrics.prefill_tokens_saved == 16 * 3  # one block each
    assert eng_off.metrics.prefill_tokens_saved == 0
    _assert_drained(eng_on)
    _assert_drained(eng_off)


# -- supervised runtime -------------------------------------------------------


class TestSupervisor:
    """The resilience layer above the engine: graceful drain, crash
    recovery with a bounded restart budget, step-latency watchdog,
    disconnect-cancel, overload shedding — all driven synchronously
    (``run_sync``/``pump``) so every schedule is deterministic."""

    KW = dict(num_blocks=32, block_size=4, max_batch_size=4, max_seq_len=32)

    def _sup(self, tiny_lm, plan=None, *, engine_kw=None, **kw):
        model, params = tiny_lm
        ekw = dict(self.KW)
        ekw.update(engine_kw or {})
        eng = InferenceEngine(model, params, faults=plan, **ekw)
        events = []
        sup = EngineSupervisor(eng, event_sink=events.append,
                               restart_backoff_s=0.0, **kw)
        return sup, eng, events

    @staticmethod
    def _terminals(events):
        return [e for e in events if e["event"] != "token"]

    def test_graceful_drain_finishes_inflight(self, tiny_lm):
        model, params = tiny_lm
        sup, eng, events = self._sup(tiny_lm)
        rng = np.random.default_rng(3)
        prompts = [rng.integers(0, 128, n).astype(np.int32) for n in (5, 7, 4)]
        refs = [_greedy_ref(model, params, p, 6, eng.assembly_len)
                for p in prompts]
        rids = [sup.submit(p, 6) for p in prompts]
        sup.pump(2)                           # work now genuinely in flight
        sup.request_drain("test drain")
        assert sup.draining
        with pytest.raises(ShuttingDown, match="draining"):
            sup.submit(prompts[0], 2)
        sup.run_sync()
        assert sup.state is SupervisorState.STOPPED
        assert sup.exit_code == 0
        assert sup.drain_duration_s is not None
        assert eng.metrics.summary()["drain_duration_s"] == \
            sup.drain_duration_s
        done = {e["id"]: e for e in events if e["event"] == "done"}
        assert sorted(done) == sorted(rids)
        assert len(self._terminals(events)) == len(rids)  # exactly one each
        for rid, ref in zip(rids, refs):
            assert done[rid]["tokens"] == ref
            assert done[rid]["ttft_ms"] >= 0
        with pytest.raises(ShuttingDown, match="stopped"):
            sup.submit(prompts[0], 2)
        _assert_drained(eng)

    def test_drain_deadline_times_out_stragglers(self, tiny_lm):
        plan = FaultPlan(step_delay_s=0.03)
        sup, eng, events = self._sup(tiny_lm, plan, drain_deadline_s=0.02)
        rids = [sup.submit(np.arange(5, dtype=np.int32) + i, 8)
                for i in range(2)]
        sup.pump(1)
        sup.request_drain("deadline test")
        sup.run_sync()
        assert sup.state is SupervisorState.STOPPED  # drain is still clean
        assert sup.exit_code == 0
        touts = [e for e in self._terminals(events) if e["event"] == "timeout"]
        assert touts, "no request hit the drain deadline"
        assert all("drain deadline" in e["reason"] for e in touts)
        assert len(self._terminals(events)) == len(rids)
        assert eng.pool.num_allocated == 0
        eng.check_invariants()

    def test_watchdog_trips_and_recovers(self, tiny_lm):
        """A wedged step (injected latency) is treated like a crash. The
        engine is warmed with the exact same shapes first so compile time
        never reaches the watchdog — only the injected delay does."""
        model, params = tiny_lm
        eng = InferenceEngine(model, params, **self.KW)
        warm = [np.arange(5, dtype=np.int32), np.arange(6, dtype=np.int32)]
        for p in warm:
            eng.submit(p, 4)
        eng.run_until_complete()
        eng.faults = FaultPlan(step_delay_s=0.2, step_delay_calls=(2,))
        events = []
        sup = EngineSupervisor(eng, event_sink=events.append,
                               watchdog_step_s=0.05, max_restarts=2,
                               restart_backoff_s=0.0)
        refs = [_greedy_ref(model, params, p, 4, eng.assembly_len)
                for p in warm]
        rids = [sup.submit(p, 4) for p in warm]
        for _ in range(200):
            sup.pump(1)
            if sup.restarts:
                break
        # disarm for the recovery leg: the resumed requests re-prefill at
        # new lengths, and a compile there must not count as a wedge (same
        # caveat as the fresh-request leg below)
        sup.watchdog_step_s = None
        sup.run_sync()
        assert sup.restarts == 1
        assert sup.state is SupervisorState.RUNNING   # recovered, not dead
        term = {e["id"]: e for e in self._terminals(events)}
        assert sorted(term) == sorted(rids)
        # the wedged step cost the requests their KV, not their lives: both
        # migrate through the resume path and finish token-exact
        for rid, ref in zip(rids, refs):
            assert term[rid]["event"] == "done"
            assert term[rid]["tokens"] == ref
        assert eng.metrics.migrated_requests == 2
        assert eng.metrics.summary()["engine_restarts"] == 1
        assert eng.pool.num_allocated == 0
        eng.check_invariants()
        # the recovered engine still serves: a fresh request completes
        # (watchdog off for this leg — a solo request hits decode buckets
        # the warmup never compiled, and compiles must not count as wedges)
        sup.watchdog_step_s = None
        eng.faults = None
        ref = _greedy_ref(model, params, warm[0], 4, eng.assembly_len)
        rid = sup.submit(warm[0], 4)
        sup.run_sync()
        done = [e for e in events if e["event"] == "done" and e["id"] == rid]
        assert len(done) == 1 and done[0]["tokens"] == ref

    def test_engine_crash_restart_resumes_inflight(self, tiny_lm):
        """A crash no longer fails in-flight work: RUNNING requests lose
        their KV pages but keep their committed tokens, migrate through
        the recompute-resume path on restart, and finish token-exact —
        indistinguishable (to the client stream) from an uninterrupted
        run. QUEUED requests survive as before."""
        model, params = tiny_lm
        plan = FaultPlan(step_crash_calls=(2,))
        sup, eng, events = self._sup(tiny_lm, plan, max_restarts=2,
                                     engine_kw=dict(max_batch_size=2))
        rng = np.random.default_rng(9)
        prompts = [rng.integers(0, 128, n).astype(np.int32)
                   for n in (5, 6, 7, 8)]
        refs = [_greedy_ref(model, params, p, 5, eng.assembly_len)
                for p in prompts]
        rids = [sup.submit(p, 5) for p in prompts]
        sup.run_sync()
        assert sup.restarts == 1
        term = {e["id"]: e for e in self._terminals(events)}
        assert sorted(term) == sorted(rids)
        assert eng.metrics.migrated_requests == 2   # the in-flight batch
        for rid, ref in zip(rids, refs):
            assert term[rid]["event"] == "done"
            assert term[rid]["tokens"] == ref
            # the client stream never saw a duplicated or dropped token
            streamed = [e["token"] for e in events
                        if e["event"] == "token" and e["id"] == rid]
            assert streamed == ref
        _assert_drained(eng)

    def test_restart_budget_exhaustion_fails_everything(self, tiny_lm):
        plan = FaultPlan(step_crash_calls=(1, 2, 3))
        sup, eng, events = self._sup(tiny_lm, plan, max_restarts=2)
        rids = [sup.submit(np.arange(4, dtype=np.int32) + i, 4)
                for i in range(2)]
        sup.run_sync()
        assert sup.state is SupervisorState.FAILED
        assert sup.exit_code == 1
        assert sup.restarts == 3          # two recoveries + the fatal one
        term = {e["id"]: e for e in self._terminals(events)}
        assert sorted(term) == sorted(rids)
        assert all(e["event"] == "error" and
                   "restart budget exhausted (2)" in e["reason"]
                   for e in term.values())
        with pytest.raises(ShuttingDown, match="failed"):
            sup.submit(np.arange(4, dtype=np.int32), 2)
        assert eng.pool.num_allocated == 0
        eng.check_invariants()

    def test_migration_budget_exhaustion_fails_poison(self, tiny_lm):
        """A request that keeps crashing its engine is FAILED with a
        structured reason once its migration budget is spent — poison
        isolation, so one bad request cannot wedge the restart loop.
        The supervisor stays RUNNING and keeps serving."""
        model, params = tiny_lm
        # crashes spaced so the victim is re-admitted (RUNNING, charged a
        # migration) before each one — back-to-back crashes would only ever
        # see it QUEUED
        plan = FaultPlan(step_crash_calls=(2, 4, 6))
        sup, eng, events = self._sup(tiny_lm, plan, max_restarts=10,
                                     engine_kw=dict(migration_budget=2))
        sup.submit(np.arange(1, 6, dtype=np.int32), 8)
        sup.run_sync()
        term = self._terminals(events)
        assert len(term) == 1 and term[0]["event"] == "error"
        assert "migration budget exhausted (2)" in term[0]["reason"]
        assert sup.state is SupervisorState.RUNNING
        assert eng.metrics.migrated_requests == 2
        # the engine still serves a fresh request token-exact
        p = np.arange(6, dtype=np.int32)
        ref = _greedy_ref(model, params, p, 4, eng.assembly_len)
        rid2 = sup.submit(p, 4)
        sup.run_sync()
        done = [e for e in events
                if e["event"] == "done" and e["id"] == rid2]
        assert len(done) == 1 and done[0]["tokens"] == ref
        _assert_drained(eng)

    def test_restart_backoff_interruptible_by_drain(self, tiny_lm):
        """The restart backoff must not block shutdown: a drain arriving
        mid-backoff wakes the worker immediately instead of letting the
        process hang for the remaining (possibly seconds-long) sleep."""
        model, params = tiny_lm
        plan = FaultPlan(step_crash_calls=(1,))
        eng = InferenceEngine(model, params, faults=plan, **self.KW)
        events = []
        sup = EngineSupervisor(eng, event_sink=events.append,
                               max_restarts=2, restart_backoff_s=30.0,
                               restart_backoff_max_s=30.0).start()
        rid = sup.submit(np.arange(5, dtype=np.int32), 4)
        deadline = time.monotonic() + 10.0
        while sup.restarts < 1 and time.monotonic() < deadline:
            time.sleep(0.005)
        assert sup.restarts == 1, "crash never landed"
        sup.request_drain("test drain")       # worker is in its backoff
        assert sup.join(timeout=10.0), \
            "drain blocked behind the restart backoff sleep"
        assert sup.state is SupervisorState.STOPPED
        assert sup.exit_code == 0
        term = {e["id"]: e for e in self._terminals(events)}
        assert term[rid]["event"] == "done"
        _assert_drained(eng)

    def test_client_disconnect_cancels_request(self, tiny_lm):
        """A front end consulting plan.client_disconnect() drops a client
        mid-stream; cancelling from inside the listener (the sweep's
        dispatch) must be re-entrant and emit exactly one terminal."""
        model, params = tiny_lm
        plan = FaultPlan(client_disconnect_calls=(2,))
        sup, eng, events = self._sup(tiny_lm)
        p0, p1 = np.arange(5, dtype=np.int32), np.arange(6, dtype=np.int32)
        ref = _greedy_ref(model, params, p1, 6, eng.assembly_len)

        def flaky_listener(ev):
            if ev["event"] == "token" and plan.client_disconnect():
                sup.cancel(ev["id"], "client disconnected mid-stream")

        r0 = sup.submit(p0, 6, listener=flaky_listener)
        r1 = sup.submit(p1, 6)
        sup.run_sync()
        term = {e["id"]: e for e in self._terminals(events)}
        assert term[r0]["event"] == "cancelled"
        assert "client disconnected" in term[r0]["reason"]
        assert term[r1]["event"] == "done" and term[r1]["tokens"] == ref
        assert len(self._terminals(events)) == 2
        assert plan.fired["client.disconnect"] == 1
        _assert_drained(eng)

    def test_priority_shed_under_overload(self, tiny_lm):
        """Backpressure degrades background traffic first: a full queue
        sheds its least-important (largest priority value, newest) member
        for a more-important arrival; equal priority still rejects."""
        sup, eng, events = self._sup(
            tiny_lm, engine_kw=dict(max_queue_depth=2))
        p = np.arange(5, dtype=np.int32)
        bg1 = sup.submit(p, 4, priority=5)
        bg2 = sup.submit(p + 1, 4, priority=5)
        fg = sup.submit(p + 2, 4, priority=0)     # sheds bg2 (newest bg)
        with pytest.raises(AdmissionRejected):
            sup.submit(p + 3, 4, priority=5)      # no one less important
        sup.run_sync()
        term = {e["id"]: e for e in self._terminals(events)}
        assert term[bg2]["event"] == "error"
        assert "shed under overload" in term[bg2]["reason"]
        assert "priority 5" in term[bg2]["reason"]
        assert term[bg1]["event"] == "done"
        assert term[fg]["event"] == "done"
        s = sup.stats()
        assert s["shed_requests"] == 1
        assert s["rejected"] == 1
        assert s["supervisor_state"] == "running"
        _assert_drained(eng)

    def test_threaded_submit_stats_and_drain(self, tiny_lm):
        """The worker-thread path: submits/stats marshalled through the
        command queue, drain from another thread, clean join."""
        model, params = tiny_lm
        sup, eng, events = self._sup(tiny_lm)
        p = np.arange(6, dtype=np.int32)
        ref = _greedy_ref(model, params, p, 5, eng.assembly_len)
        sup.start()
        import queue as _q
        got: "_q.Queue[dict]" = _q.Queue()
        rid = sup.submit(p, 5, listener=got.put)
        ev = got.get(timeout=60)
        seen = [ev]
        while ev["event"] == "token":
            ev = got.get(timeout=60)
            seen.append(ev)
        assert ev["event"] == "done" and ev["tokens"] == ref
        assert [e["token"] for e in seen[:-1]] == ref
        assert sup.stats()["supervisor_state"] == "running"
        sup.request_drain("test over")
        assert sup.join(timeout=30)
        assert sup.state is SupervisorState.STOPPED
        assert sup.exit_code == 0
        with pytest.raises(ShuttingDown):
            sup.submit(p, 2)
        assert rid in {e["id"] for e in events}
        _assert_drained(eng)


class TestCrashResumeExactness:
    """The in-flight crash-survival contract, exhaustively: an engine
    crash at ANY point in a request's life — mid-prefill-chunk,
    mid-decode, mid-spec-draft — loses KV pages but never committed
    tokens. After the supervisor restart, every request migrates through
    the recompute-resume path and both the final output and the streamed
    token sequence are byte-identical to an uninterrupted run, across
    decode paths and with the prefix cache on or off."""

    @pytest.mark.parametrize("cache", [True, False],
                             ids=["cache", "nocache"])
    @pytest.mark.parametrize("path", ["standard", "paged"])
    @pytest.mark.parametrize(
        "site", ["prefill_chunk", "decode", "spec_draft"])
    def test_crash_resume_token_exact(self, tiny_lm, site, path, cache):
        model, params = tiny_lm
        kw = dict(num_blocks=32, block_size=4, max_batch_size=4,
                  max_seq_len=32, decode_path=path, prefix_cache=cache)
        if site == "spec_draft":
            kw.update(spec="ngram", spec_k=3)
            prompts = _cyclic_prompts(2, seed=3)
            crash_at = (4,)   # decode steps have drafts in flight
        elif site == "prefill_chunk":
            kw.update(chunk_size=4)
            rng = np.random.default_rng(1)
            prompts = [rng.integers(0, 128, n).astype(np.int32)
                       for n in (10, 9)]
            crash_at = (2,)   # first chunk landed; prompts mid-prefill
        else:
            rng = np.random.default_rng(2)
            prompts = [rng.integers(0, 128, n).astype(np.int32)
                       for n in (5, 7)]
            crash_at = (4,)   # several decode tokens already committed
        max_new = 6
        plan = FaultPlan(step_crash_calls=crash_at)
        eng = InferenceEngine(model, params, faults=plan, **kw)
        refs = [_greedy_ref(model, params, p, max_new, eng.assembly_len)
                for p in prompts]
        events = []
        sup = EngineSupervisor(eng, event_sink=events.append,
                               restart_backoff_s=0.0, max_restarts=2)
        rids = [sup.submit(p, max_new) for p in prompts]
        sup.run_sync()
        assert sup.restarts == 1
        assert plan.fired["engine.step"] == 1
        assert eng.metrics.migrated_requests >= 1
        term = {e["id"]: e for e in events if e["event"] != "token"}
        assert sorted(term) == sorted(rids)
        for rid, ref in zip(rids, refs):
            assert term[rid]["event"] == "done"
            assert term[rid]["tokens"] == ref
            streamed = [e["token"] for e in events
                        if e["event"] == "token" and e["id"] == rid]
            assert streamed == ref    # no token duplicated or dropped
        _assert_drained(eng)


class TestDegradation:
    """Overload degradation at the engine level: prefix-cache publish
    suspension under pool pressure (shedding is covered above)."""

    def test_publish_suspension_under_pool_pressure(self, tiny_lm):
        model, params = tiny_lm
        rng = np.random.default_rng(4)
        prefix = rng.integers(0, 128, 8).astype(np.int32)
        prompts = [np.concatenate([prefix,
                                   rng.integers(0, 128, 4).astype(np.int32)])
                   for _ in range(3)]
        # threshold 0.0: any live allocation counts as pressure, so every
        # publish is suspended and the index never grows
        eng = InferenceEngine(model, params, num_blocks=32, block_size=4,
                              max_batch_size=4, max_seq_len=32,
                              prefix_publish_max_occupancy=0.0)
        rids = [eng.submit(p, 4) for p in prompts]
        out = eng.run_until_complete()
        s = eng.stats()
        assert s["prefix_indexed_blocks"] == 0
        assert s["publish_suspended"] > 0
        assert s["prefix_hits"] == 0
        assert all(eng.result(r).state is RequestState.FINISHED
                   for r in rids)
        for r, p in zip(rids, prompts):
            assert out[r] == _greedy_ref(model, params, p, 4,
                                         eng.assembly_len)
        _assert_drained(eng)

    def test_default_threshold_publishes_normally(self, tiny_lm):
        model, params = tiny_lm
        rng = np.random.default_rng(4)
        prefix = rng.integers(0, 128, 8).astype(np.int32)
        prompts = [np.concatenate([prefix,
                                   rng.integers(0, 128, 4).astype(np.int32)])
                   for _ in range(3)]
        eng = InferenceEngine(model, params, num_blocks=32, block_size=4,
                              max_batch_size=4, max_seq_len=32)
        for p in prompts:
            eng.submit(p, 4)
        eng.run_until_complete()
        s = eng.stats()
        assert s["prefix_indexed_blocks"] > 0
        assert s["publish_suspended"] == 0
        _assert_drained(eng)


@pytest.mark.slow
def test_chaos_soak_supervised(tiny_lm):
    """The soak gate: hundreds of staggered requests through a supervised
    engine with chaos on — alloc faults, NaN rows, client disconnects, and
    one injected engine-loop crash. Asserts the full resilience contract:
    every request reaches exactly one terminal event, the supervisor
    recovers from the crash (restarts == 1) and drains cleanly, zero
    leaked blocks, and fault-free survivors are token-identical to the
    offline greedy reference."""
    model, params = tiny_lm
    rng = np.random.default_rng(42)
    uniq = [rng.integers(0, 128, int(n)).astype(np.int32)
            for n in rng.integers(4, 14, 8)]
    max_new = 6
    eng = InferenceEngine(model, params, num_blocks=32, block_size=4,
                          max_batch_size=4, max_seq_len=32,
                          max_queue_depth=24)
    refs = {i: _greedy_ref(model, params, p, max_new, eng.assembly_len)
            for i, p in enumerate(uniq)}
    plan = FaultPlan(seed=7, alloc_fail_prob=0.02, nan_logit_prob=0.01,
                     client_disconnect_prob=0.04, step_crash_calls=(60,))
    eng.faults = plan
    eng.pool.fault_plan = plan
    events = []
    sup = EngineSupervisor(eng, event_sink=events.append, max_restarts=3,
                           restart_backoff_s=0.0, drain_deadline_s=60.0)

    def flaky_listener(ev):
        if ev["event"] == "token" and plan.client_disconnect():
            sup.cancel(ev["id"], "client disconnected mid-stream")

    n_requests, rejected, submitted = 200, 0, {}
    for i in range(n_requests):
        which = int(rng.integers(0, len(uniq)))
        try:
            rid = sup.submit(uniq[which], max_new, priority=i % 3,
                             listener=flaky_listener)
            submitted[rid] = which
        except AdmissionRejected:
            rejected += 1
        sup.pump(1)                        # staggered: interleave with steps
    sup.run_sync()
    sup.request_drain("soak complete")
    sup.run_sync()

    # lifecycle: clean drain despite the injected crash
    assert sup.state is SupervisorState.STOPPED
    assert sup.exit_code == 0
    assert sup.restarts == 1, f"expected exactly one restart: {sup.restarts}"
    # every fault site actually exercised
    assert plan.fired["engine.step"] == 1
    assert plan.fired["pool.alloc"] > 0
    assert plan.fired["decode.logits"] > 0
    assert plan.fired["client.disconnect"] > 0
    assert rejected + len(submitted) == n_requests
    # exactly one terminal event per admitted request
    terminals = [e for e in events if e["event"] != "token"]
    per_rid = {}
    for e in terminals:
        per_rid[e["id"]] = per_rid.get(e["id"], 0) + 1
    assert sorted(per_rid) == sorted(submitted)
    assert all(c == 1 for c in per_rid.values()), per_rid
    states = {rid: eng.result(rid).state for rid in submitted}
    assert all(st in TERMINAL_STATES for st in states.values())
    # zero leaks after crash recovery + drain
    assert eng.pool.num_allocated == 0
    eng.check_invariants()
    # survivors are token-exact against the fault-free reference
    finished = [e for e in terminals if e["event"] == "done"]
    assert finished, "soak finished nothing"
    for e in finished:
        assert e["tokens"] == refs[submitted[e["id"]]], \
            f"rid {e['id']} diverged from fault-free reference"
    s = eng.stats()
    assert s["engine_restarts"] == 1
    assert s["drain_duration_s"] >= 0.0


# -- replicated failover router -----------------------------------------------


class TestCircuitBreaker:
    """Pure state-machine tests: CLOSED → OPEN on consecutive failures,
    OPEN → HALF_OPEN after cooldown, one probe decides re-CLOSE/re-OPEN."""

    def test_opens_after_consecutive_failures(self):
        b = CircuitBreaker(threshold=3, cooldown_s=60.0)
        assert b.state is BreakerState.CLOSED
        b.record_failure()
        b.record_failure()
        assert b.state is BreakerState.CLOSED and b.allows()
        b.record_failure()
        assert b.state is BreakerState.OPEN and not b.allows()

    def test_success_resets_consecutive_count(self):
        b = CircuitBreaker(threshold=2, cooldown_s=60.0)
        b.record_failure()
        b.record_success()
        b.record_failure()          # not consecutive: stays closed
        assert b.state is BreakerState.CLOSED and b.allows()

    def test_half_open_probe_success_recloses(self):
        b = CircuitBreaker(threshold=1, cooldown_s=0.0)
        b.record_failure()
        assert b.state is BreakerState.OPEN
        assert b.allows()                     # cooldown 0: probe admitted
        assert b.state is BreakerState.HALF_OPEN
        b.on_dispatch()
        assert not b.allows()                 # a single probe at a time
        b.record_success()
        assert b.state is BreakerState.CLOSED and b.allows()

    def test_half_open_probe_failure_reopens(self):
        b = CircuitBreaker(threshold=1, cooldown_s=0.0)
        b.trip()
        assert b.allows()
        b.on_dispatch()
        b.record_failure()                    # the probe failed
        assert b.state is BreakerState.OPEN

    def test_stale_success_cannot_close_open_breaker(self):
        """Regression: a success recorded while the breaker is OPEN (a
        stream that dispatched before the trip landing its terminal after
        it) must NOT close the breaker — only a HALF_OPEN probe or normal
        CLOSED traffic counts."""
        b = CircuitBreaker(threshold=1, cooldown_s=60.0)
        b.trip()
        assert b.state is BreakerState.OPEN
        b.record_success()                    # stale: from before the trip
        assert b.state is BreakerState.OPEN and not b.allows()

    def test_stale_success_race_after_probe_failure(self):
        """The precise race: probe admitted, probe fails (re-OPEN), THEN a
        stale success from an older stream arrives. The breaker must stay
        OPEN — otherwise one laggard ack reopens the floodgates onto a
        replica the probe just proved dead."""
        b = CircuitBreaker(threshold=1, cooldown_s=0.0)
        b.record_failure()
        assert b.allows()                     # cooldown 0: probe admitted
        b.on_dispatch()
        b.record_failure()                    # probe failed: re-OPEN
        assert b.state is BreakerState.OPEN
        b.record_success()                    # stale ack from an old stream
        assert b.state is BreakerState.OPEN


class TestHealthScore:
    """Unit tests for the EWMA health score: the healthy fixed point is
    exactly 1.0 (so a fresh fleet places as pure JSQ), and each signal
    contributes its documented weight."""

    def test_fresh_score_is_exactly_one(self):
        from tnn_tpu.serving import HealthScore

        hs = HealthScore()
        assert hs.score() == 1.0
        assert hs.samples == 0

    def test_dispatch_latency_ewma_blend(self):
        from tnn_tpu.serving import HealthScore

        hs = HealthScore()
        hs.observe_dispatch(1.0)
        assert hs.dispatch_latency_s == pytest.approx(HealthScore.ALPHA)
        assert hs.score() == pytest.approx(
            1.0 + HealthScore.W_DISPATCH * HealthScore.ALPHA)
        hs.observe_dispatch(1.0)
        a = HealthScore.ALPHA
        assert hs.dispatch_latency_s == pytest.approx((1 - a) * a + a)

    def test_gauge_sample_contributions(self):
        from tnn_tpu.serving import HealthScore

        hs = HealthScore()
        hs.observe_gauges(0.1, 4.0, 0.0)
        a = HealthScore.ALPHA
        assert hs.step_latency_s == pytest.approx(a * 0.1)
        assert hs.queue_depth == pytest.approx(a * 4.0)
        assert hs.score() == pytest.approx(
            1.0 + HealthScore.W_STEP * a * 0.1
            + HealthScore.W_QUEUE * a * 4.0)

    def test_error_rate_folds_and_decays(self):
        from tnn_tpu.serving import HealthScore

        hs = HealthScore()
        hs.observe_outcome(False)
        a = HealthScore.ALPHA
        assert hs.error_rate == pytest.approx(a)
        assert hs.score() == pytest.approx(1.0 + HealthScore.W_ERROR * a)
        hs.observe_outcome(True)              # success decays the EWMA
        assert hs.error_rate == pytest.approx((1 - a) * a)

    def test_staleness_grace_window(self):
        from tnn_tpu.serving import HealthScore

        hs = HealthScore()
        # inside the grace window: free — probe cadence jitter is normal
        hs.observe_gauges(0.0, 0.0, HealthScore.STALE_GRACE_S * 0.5)
        assert hs.score() == 1.0
        # past it: a wedged-but-responsive worker starts paying
        hs.observe_gauges(0.0, 0.0, HealthScore.STALE_GRACE_S + 2.0)
        assert hs.score() == pytest.approx(1.0 + HealthScore.W_STALE * 2.0)


class TestFaultPlanGraySites:
    """Seed-determinism and semantics of the gray-failure fault sites:
    replica.slow, net.partition (windowed), net.flaky (per-replica)."""

    def test_replica_slow_seed_deterministic(self):
        a = FaultPlan(seed=11, replica_slow_prob=0.3)
        b = FaultPlan(seed=11, replica_slow_prob=0.3)
        trace_a = [a.replica_slow() for _ in range(50)]
        trace_b = [b.replica_slow() for _ in range(50)]
        assert trace_a == trace_b
        assert any(trace_a) and not all(trace_a)
        assert a.fired["replica.slow"] == sum(trace_a)
        # a different seed yields a different schedule
        c = FaultPlan(seed=12, replica_slow_prob=0.3)
        assert [c.replica_slow() for _ in range(50)] != trace_a

    def test_replica_slow_scheduled_calls(self):
        p = FaultPlan(replica_slow_calls=(3,))
        assert [p.replica_slow() for _ in range(5)] == \
            [False, False, True, False, False]
        assert p.fired["replica.slow"] == 1

    def test_partition_window_semantics(self):
        """One hit opens a window of net_partition_rounds consults; every
        consult inside it reports active, then the window closes."""
        p = FaultPlan(net_partition_calls=(2,), net_partition_rounds=3)
        got = [p.net_partition() for _ in range(7)]
        assert got == [False, True, True, True, False, False, False]
        assert p.fired["net.partition"] == 1   # one HIT, one window

    def test_partition_active_is_a_pure_read(self):
        """partition_active never advances the rng stream: two identical
        plans, one read between every consult, fire identically."""
        a = FaultPlan(seed=7, net_partition_prob=0.2,
                      net_partition_rounds=2)
        b = FaultPlan(seed=7, net_partition_prob=0.2,
                      net_partition_rounds=2)
        trace_a, trace_b = [], []
        for _ in range(40):
            trace_a.append(a.net_partition())
            trace_b.append(b.net_partition())
            for _ in range(5):                 # hammer the pure read
                b.partition_active
        assert trace_a == trace_b
        assert a.fired["net.partition"] == b.fired["net.partition"] > 0

    def test_partition_active_tracks_window(self):
        p = FaultPlan(net_partition_calls=(1,), net_partition_rounds=2)
        assert not p.partition_active
        assert p.net_partition()               # hit: window opens
        assert p.partition_active              # one consult left
        assert p.net_partition()               # last consult of the window
        assert not p.partition_active
        assert not p.net_partition()

    def test_flaky_drop_only_consults_configured_replica(self):
        """Calls to healthy replicas never perturb the flaky schedule —
        the rng stream depends only on the flaky replica's own calls."""
        p = FaultPlan(flaky_replica=1, flaky_drop_calls=(1,))
        assert not p.flaky_drop(0)             # wrong replica: no consult
        assert p.calls["net.flaky"] == 0
        assert p.flaky_drop(1)                 # 1st consult = scheduled hit
        assert not p.flaky_drop(1)
        assert p.fired["net.flaky"] == 1
        # disabled site never consults at all
        q = FaultPlan(flaky_drop_prob=1.0)     # flaky_replica defaults -1
        assert not q.flaky_drop(0) and q.calls["net.flaky"] == 0

    def test_flaky_drop_seed_deterministic(self):
        a = FaultPlan(seed=5, flaky_replica=2, flaky_drop_prob=0.4)
        b = FaultPlan(seed=5, flaky_replica=2, flaky_drop_prob=0.4)
        # interleave irrelevant-replica calls on one plan only
        trace_a = [a.flaky_drop(2) for _ in range(40)]
        trace_b = []
        for _ in range(40):
            b.flaky_drop(0)
            trace_b.append(b.flaky_drop(2))
            b.flaky_drop(1)
        assert trace_a == trace_b
        assert any(trace_a) and not all(trace_a)


class TestRouter:
    """The failover front end over N supervised replicas, driven through
    the deterministic sync harness (``pump``/``run_sync``): placement,
    retries, mid-stream migration, breaker integration, cascade drain."""

    KW = dict(num_blocks=32, block_size=4, max_batch_size=4, max_seq_len=32)

    def _router(self, tiny_lm, n=3, *, plans=None, router_kw=None,
                engine_kw=None, sup_kw=None):
        model, params = tiny_lm
        ekw = dict(self.KW)
        ekw.update(engine_kw or {})
        skw = dict(restart_backoff_s=0.0)
        skw.update(sup_kw or {})
        plans = plans or [None] * n
        sups = [EngineSupervisor(
                    InferenceEngine(model, params, faults=plans[i], **ekw),
                    **skw)
                for i in range(n)]
        events = []
        router = Router(sups, event_sink=events.append, seed=0,
                        **(router_kw or {}))
        return router, sups, events

    @staticmethod
    def _terminals(events):
        return [e for e in events if e["event"] != "token"]

    def test_jsq_placement_spreads_load(self, tiny_lm):
        model, params = tiny_lm
        router, sups, events = self._router(tiny_lm, n=2)
        rng = np.random.default_rng(5)
        prompts = [rng.integers(0, 128, n).astype(np.int32)
                   for n in (5, 6, 7, 8)]
        refs = [_greedy_ref(model, params, p, 5,
                            sups[0].engine.assembly_len) for p in prompts]
        gids = [router.submit(p, 5) for p in prompts]
        # join-shortest-queue: 4 submits over 2 replicas → 2 each
        assert [len(h.live) for h in router.replicas] == [2, 2]
        router.run_sync()
        term = {e["id"]: e for e in self._terminals(events)}
        for gid, ref in zip(gids, refs):
            assert term[gid]["event"] == "done"
            assert term[gid]["tokens"] == ref
        assert router.stats()["router_retries"] == 0

    def test_kill_replica_midstream_migrates_token_exact(self, tiny_lm):
        """The headline failover: a replica is hard-killed with requests
        mid-decode; its live streams re-dispatch to the survivors and the
        client sees an uninterrupted token-exact stream."""
        model, params = tiny_lm
        router, sups, events = self._router(tiny_lm, n=3)
        rng = np.random.default_rng(6)
        prompts = [rng.integers(0, 128, n).astype(np.int32)
                   for n in (5, 6, 7, 8)]
        refs = [_greedy_ref(model, params, p, 8,
                            sups[0].engine.assembly_len) for p in prompts]
        gids = [router.submit(p, 8) for p in prompts]
        router.pump(3)                 # streams genuinely mid-flight
        victim = max(router.replicas, key=lambda h: len(h.live)).idx
        assert len(router.replicas[victim].live) > 0
        router.kill_replica(victim)
        router.run_sync()
        term = {e["id"]: e for e in self._terminals(events)}
        assert sorted(term) == sorted(gids)
        for gid, ref in zip(gids, refs):
            assert term[gid]["event"] == "done"
            assert term[gid]["tokens"] == ref
            streamed = [e["token"] for e in events
                        if e["event"] == "token" and e["id"] == gid]
            assert streamed == ref     # no token duplicated or dropped
        assert router.metrics.migrated_requests > 0
        st = router.stats()
        assert st["replicas"][victim]["killed"]
        assert st["replicas"][victim]["breaker_state"] == "open"
        # survivors leak nothing
        for h in router.replicas:
            if h.idx != victim:
                assert h.sup.engine.pool.num_allocated == 0
                h.sup.engine.check_invariants()

    def test_replica_internal_restart_is_invisible(self, tiny_lm):
        """An engine crash INSIDE a replica is the supervisor's problem:
        it restarts, migrates its own requests, and the router never even
        sees an error — no router-level migration, just replica_restarts
        in the stats."""
        model, params = tiny_lm
        plans = [FaultPlan(step_crash_calls=(2,)), None]
        router, sups, events = self._router(tiny_lm, n=2, plans=plans)
        rng = np.random.default_rng(7)
        prompts = [rng.integers(0, 128, n).astype(np.int32)
                   for n in (5, 6, 7, 8)]
        refs = [_greedy_ref(model, params, p, 5,
                            sups[0].engine.assembly_len) for p in prompts]
        gids = [router.submit(p, 5) for p in prompts]
        router.run_sync()
        term = {e["id"]: e for e in self._terminals(events)}
        for gid, ref in zip(gids, refs):
            assert term[gid]["event"] == "done"
            assert term[gid]["tokens"] == ref
        assert router.metrics.migrated_requests == 0
        st = router.stats()
        assert st["replica_restarts"] == 1
        assert all(r["breaker_state"] == "closed" for r in st["replicas"])

    def test_restart_budget_exhaustion_fails_over(self, tiny_lm):
        """A replica that crashes until its supervisor gives up emits
        'restart budget exhausted' for its requests — a replica-level
        failure the router turns into migration, not client errors."""
        model, params = tiny_lm
        plans = [FaultPlan(step_crash_calls=(1, 2, 3, 4, 5, 6)), None]
        router, sups, events = self._router(
            tiny_lm, n=2, plans=plans, sup_kw=dict(max_restarts=1))
        rng = np.random.default_rng(8)
        prompts = [rng.integers(0, 128, n).astype(np.int32)
                   for n in (5, 6)]
        refs = [_greedy_ref(model, params, p, 5,
                            sups[0].engine.assembly_len) for p in prompts]
        gids = [router.submit(p, 5) for p in prompts]
        router.run_sync()
        assert sups[0].state is SupervisorState.FAILED
        term = {e["id"]: e for e in self._terminals(events)}
        for gid, ref in zip(gids, refs):
            assert term[gid]["event"] == "done"
            assert term[gid]["tokens"] == ref
        assert router.metrics.migrated_requests >= 1

    def test_router_migration_budget_exhausts_poison(self, tiny_lm):
        """migration_budget=0: the first failover attempt FAILs the
        request with a structured reason instead of bouncing it around
        the fleet forever."""
        router, sups, events = self._router(
            tiny_lm, n=2, router_kw=dict(migration_budget=0))
        gid = router.submit(np.arange(5, dtype=np.int32), 8)
        router.pump(2)
        victim = next(h.idx for h in router.replicas if h.live)
        router.kill_replica(victim)
        router.run_sync()
        term = self._terminals(events)
        assert len(term) == 1 and term[0]["event"] == "error"
        assert term[0]["id"] == gid
        assert "router migration budget exhausted (0)" in term[0]["reason"]

    def test_net_drop_retries_then_succeeds(self, tiny_lm):
        model, params = tiny_lm
        router, sups, events = self._router(
            tiny_lm, n=2,
            router_kw=dict(faults=FaultPlan(net_drop_calls=(1,)),
                           retry_backoff_s=0.0, retry_jitter_s=0.0))
        p = np.arange(6, dtype=np.int32)
        ref = _greedy_ref(model, params, p, 5, sups[0].engine.assembly_len)
        gid = router.submit(p, 5)       # first call dropped, retry lands
        assert router.metrics.router_retries == 1
        router.run_sync()
        term = {e["id"]: e for e in self._terminals(events)}
        assert term[gid]["event"] == "done" and term[gid]["tokens"] == ref
        assert router.stats()["router_retries"] == 1

    def test_net_drop_exhausts_retries_and_raises(self, tiny_lm):
        router, sups, events = self._router(
            tiny_lm, n=2,
            router_kw=dict(faults=FaultPlan(net_drop_prob=1.0),
                           max_retries=2, retry_backoff_s=0.0,
                           retry_jitter_s=0.0))
        with pytest.raises(ConnectionError):
            router.submit(np.arange(5, dtype=np.int32), 4)
        assert router.metrics.router_retries == 2
        assert router.stats()["router_open_requests"] == 0

    def test_deadline_respected_during_retries(self, tiny_lm):
        """A retry whose backoff would overshoot the request deadline
        fails the request as a timeout instead of burning the budget."""
        router, sups, events = self._router(
            tiny_lm, n=2,
            router_kw=dict(faults=FaultPlan(net_drop_prob=1.0),
                           retry_backoff_s=5.0, retry_jitter_s=0.0))
        gid = router.submit(np.arange(5, dtype=np.int32), 4,
                            deadline_s=0.05)
        term = self._terminals(events)
        assert len(term) == 1 and term[0]["id"] == gid
        assert term[0]["event"] == "timeout"
        assert "deadline exceeded during failover" in term[0]["reason"]

    def test_all_replicas_dead_fails_cleanly(self, tiny_lm):
        router, sups, events = self._router(
            tiny_lm, n=2, router_kw=dict(retry_backoff_s=0.0,
                                         retry_jitter_s=0.0))
        gids = [router.submit(np.arange(5, dtype=np.int32) + i, 8)
                for i in range(2)]
        router.pump(1)
        router.kill_replica(0)
        router.kill_replica(1)
        router.run_sync()
        term = {e["id"]: e for e in self._terminals(events)}
        assert sorted(term) == sorted(gids)
        assert all(e["event"] == "error" and "replica" in e["reason"]
                   for e in term.values())
        assert router.state is SupervisorState.FAILED
        assert router.exit_code == 1
        with pytest.raises(ShuttingDown):
            router.submit(np.arange(5, dtype=np.int32), 2)

    def test_cascade_drain_stops_everything(self, tiny_lm):
        model, params = tiny_lm
        router, sups, events = self._router(tiny_lm, n=3)
        p = np.arange(6, dtype=np.int32)
        ref = _greedy_ref(model, params, p, 5, sups[0].engine.assembly_len)
        gid = router.submit(p, 5)
        router.pump(1)
        router.request_drain("test over")
        assert router.draining
        with pytest.raises(ShuttingDown):
            router.submit(p, 2)
        router.run_sync()
        assert router.state is SupervisorState.STOPPED
        assert router.exit_code == 0
        assert router.drain_duration_s is not None
        assert all(s.state is SupervisorState.STOPPED for s in sups)
        term = {e["id"]: e for e in self._terminals(events)}
        assert term[gid]["event"] == "done" and term[gid]["tokens"] == ref

    def test_stats_and_health_gauges_shape(self, tiny_lm):
        router, sups, _ = self._router(tiny_lm, n=2)
        router.submit(np.arange(5, dtype=np.int32), 4)
        st = router.stats()
        assert st["router_replicas"] == 2
        assert st["router_open_requests"] == 1
        assert len(st["replicas"]) == 2
        for r in st["replicas"]:
            assert r["breaker_state"] == "closed"
            assert not r["killed"]
        g = router.health_gauges()
        assert g["replicas_total"] == 2
        assert g["replicas_healthy"] == 2
        assert g["num_running"] == 1
        router.run_sync()
        assert router.stats()["router_open_requests"] == 0

    def test_threaded_router_submit_and_drain(self, tiny_lm):
        """The started (threaded) path: every replica on its own worker,
        the monitor probing health, drain from the outside."""
        model, params = tiny_lm
        router, sups, events = self._router(tiny_lm, n=2)
        p = np.arange(6, dtype=np.int32)
        ref = _greedy_ref(model, params, p, 5, sups[0].engine.assembly_len)
        router.start()
        import queue as _q
        got: "_q.Queue[dict]" = _q.Queue()
        gid = router.submit(p, 5, listener=got.put)
        ev = got.get(timeout=60)
        seen = [ev]
        while ev["event"] == "token":
            ev = got.get(timeout=60)
            seen.append(ev)
        assert ev["event"] == "done" and ev["tokens"] == ref
        assert [e["token"] for e in seen[:-1]] == ref
        router.request_drain("test over")
        assert router.join(timeout=30)
        assert router.state is SupervisorState.STOPPED
        assert router.exit_code == 0
        assert gid in {e["id"] for e in events}

    # -- gray-failure tolerance: health-scored placement -----------------------

    def test_uniform_scores_route_byte_identical_to_jsq(self, tiny_lm):
        """The degenerate case IS the old behaviour: with uniform health
        scores the weighted placement reduces to pure JSQ, down to the
        tie-breaks — replicas in index order, strictly-shorter wins."""
        router, sups, _ = self._router(tiny_lm, n=3)
        gids = [router.submit(np.arange(5, dtype=np.int32) + i, 4)
                for i in range(7)]
        placed = [router._open[g].replica for g in gids]
        assert placed == [0, 1, 2, 0, 1, 2, 0]
        assert [len(h.live) for h in router.replicas] == [3, 2, 2]
        router.run_sync()

    def test_dead_band_snaps_small_score_deltas_to_jsq(self, tiny_lm):
        """Scores inside the tolerance dead-band don't perturb placement:
        routing stays byte-identical to JSQ despite the noise."""
        router, sups, _ = self._router(tiny_lm, n=3)
        # score 1.x, ratio under 1 + score_tolerance (default 0.5)
        router.replicas[0].health.step_latency_s = 0.01
        gids = [router.submit(np.arange(5, dtype=np.int32) + i, 4)
                for i in range(7)]
        assert [router._open[g].replica for g in gids] == \
            [0, 1, 2, 0, 1, 2, 0]
        router.run_sync()

    def test_large_score_delta_steers_placement_away(self, tiny_lm):
        """A genuinely worse replica gets proportionally less work: its
        weighted queue key loses even at equal queue length."""
        router, sups, _ = self._router(tiny_lm, n=3)
        router.replicas[0].health.step_latency_s = 1.0   # score ~26
        gids = [router.submit(np.arange(5, dtype=np.int32) + i, 4)
                for i in range(6)]
        placed = [router._open[g].replica for g in gids]
        assert 0 not in placed
        assert [len(h.live) for h in router.replicas] == [0, 3, 3]
        router.run_sync()

    def test_score_tolerance_validated(self, tiny_lm):
        model, params = tiny_lm
        sup = EngineSupervisor(InferenceEngine(model, params, **self.KW))
        with pytest.raises(ValueError, match="score_tolerance"):
            Router([sup], score_tolerance=-0.1)

    def test_slow_replica_actuator(self, tiny_lm):
        """The replica.slow chaos actuator installs a per-step delay on a
        live engine (creating a FaultPlan when none exists) and delay<=0
        restores full speed."""
        router, sups, _ = self._router(tiny_lm, n=2)
        assert sups[0].engine.faults is None
        router.slow_replica(0, 0.02)
        assert sups[0].engine.faults.step_delay_s == 0.02
        assert sups[0].engine.faults.step_delay_calls == ()
        router.slow_replica(0, -1.0)
        assert sups[0].engine.faults.step_delay_s == 0.0

    # -- gray-failure tolerance: degraded-replica ejection ---------------------

    GRAY_KW = dict(hedge_budget=0.0, degrade_window_s=0.0,
                   degrade_cooldown_s=1000.0)

    def test_sustained_bad_score_ejects_replica(self, tiny_lm):
        """Score past degrade_factor × fleet median, sustained for the
        window, ejects the replica from placement: DEGRADED, not OPEN —
        its breaker is untouched because its calls still succeed."""
        router, sups, _ = self._router(
            tiny_lm, n=3, router_kw=dict(self.GRAY_KW))
        router.pump(1)
        router.replicas[0].health.step_latency_s = 1.0
        router._probe()                       # crossing: suspect_since set
        assert not router.replicas[0].degraded
        router._probe()                       # sustained: ejected
        assert router.replicas[0].degraded
        assert not router.replicas[0].available
        assert router.replicas[0].breaker.state is BreakerState.CLOSED
        assert router.metrics.degraded_ejections == 1
        # placement skips it entirely now
        gids = [router.submit(np.arange(5, dtype=np.int32) + i, 4)
                for i in range(4)]
        assert all(router._open[g].replica in (1, 2) for g in gids)
        router.run_sync()

    def test_ejection_proactively_migrates_live_streams(self, tiny_lm):
        """Ejecting a replica pulls its in-flight streams off BEFORE they
        fail: same token-exact recompute-resume as crash migration, old
        stream cancelled quietly, counted as proactive."""
        model, params = tiny_lm
        router, sups, events = self._router(
            tiny_lm, n=3, router_kw=dict(self.GRAY_KW, migration_budget=3))
        p = np.arange(6, dtype=np.int32)
        ref = _greedy_ref(model, params, p, 8, sups[0].engine.assembly_len)
        gid = router.submit(p, 8)
        assert router._open[gid].replica == 0
        router.pump(2)                        # stream genuinely mid-flight
        router.replicas[0].health.step_latency_s = 1.0
        router._probe()
        router._probe()                       # ejects + migrates proactively
        assert router.metrics.degraded_ejections == 1
        assert router.metrics.proactive_migrations == 1
        assert router._open[gid].replica in (1, 2)
        router.run_sync()
        term = {e["id"]: e for e in self._terminals(events)}
        assert term[gid]["event"] == "done" and term[gid]["tokens"] == ref
        streamed = [e["token"] for e in events if e["event"] == "token"]
        assert streamed == ref                # nothing duplicated or lost
        assert router.replicas[0].breaker.state is BreakerState.CLOSED

    def test_never_ejects_last_non_degraded_replica(self, tiny_lm):
        """The guard that keeps the fleet serving: however bad its score,
        the last non-degraded replica is never ejected."""
        router, sups, _ = self._router(
            tiny_lm, n=3, router_kw=dict(self.GRAY_KW))
        router.pump(1)
        router.replicas[1].degraded = True
        router.replicas[2].degraded = True
        router.replicas[0].health.step_latency_s = 5.0
        router._probe()
        router._probe()
        assert not router.replicas[0].degraded
        assert router.metrics.degraded_ejections == 0

    def test_recovered_replica_is_readmitted(self, tiny_lm):
        """Hysteresis readmission: once the score is back under
        readmit_factor × median for a sustained window, the replica
        rejoins placement."""
        router, sups, _ = self._router(
            tiny_lm, n=3, router_kw=dict(self.GRAY_KW))
        router.pump(1)
        router.replicas[0].health.step_latency_s = 1.0
        router._probe()
        router._probe()
        assert router.replicas[0].degraded
        router.replicas[0].health.step_latency_s = 0.0   # recovered
        router._probe()                       # back under: readmit timer
        router._probe()                       # sustained: readmitted
        assert not router.replicas[0].degraded
        assert router.replicas[0].available
        gids = [router.submit(np.arange(5, dtype=np.int32) + i, 4)
                for i in range(3)]
        assert sorted(router._open[g].replica for g in gids) == [0, 1, 2]
        router.run_sync()

    def test_recovery_probe_after_cooldown(self, tiny_lm):
        """Past the cooldown a degraded replica is offered ONE probe
        dispatch at a time so it can prove itself — no thundering herd
        back onto a replica that may still be sick."""
        router, sups, _ = self._router(
            tiny_lm, n=3, router_kw=dict(self.GRAY_KW))
        router.pump(1)
        router.replicas[0].health.step_latency_s = 1.0
        router._probe()
        router._probe()
        assert router.replicas[0].degraded
        g1 = router.submit(np.arange(5, dtype=np.int32), 6)
        g2 = router.submit(np.arange(6, dtype=np.int32), 6)
        assert {router._open[g].replica for g in (g1, g2)} == {1, 2}
        # cooldown elapses; the replica's score has recovered
        router.replicas[0].health.step_latency_s = 0.0
        router.degrade_cooldown_s = 0.0
        g3 = router.submit(np.arange(7, dtype=np.int32), 6)
        assert router._open[g3].replica == 0   # the probe dispatch
        assert router.replicas[0].recovery_probing
        g4 = router.submit(np.arange(8, dtype=np.int32), 6)
        assert router._open[g4].replica in (1, 2)   # one probe at a time
        router.run_sync()

    # -- gray-failure tolerance: hedged dispatch -------------------------------

    HEDGE_KW = dict(hedge_ttft_s=0.0, hedge_budget=1.0, degrade_factor=0.0)

    def test_overdue_request_hedges_and_dedupes(self, tiny_lm):
        """A first token past the threshold races a duplicate on another
        replica; the primary's first token wins, the duplicate is
        cancelled quietly, and the client stream carries every token
        exactly once."""
        model, params = tiny_lm
        router, sups, events = self._router(
            tiny_lm, n=2, router_kw=dict(self.HEDGE_KW))
        p = np.arange(6, dtype=np.int32)
        ref = _greedy_ref(model, params, p, 5, sups[0].engine.assembly_len)
        gid = router.submit(p, 5)
        router._probe()                       # threshold 0: fires at once
        assert router.metrics.hedges_fired == 1
        rec = router._open[gid]
        assert rec.hedge_replica == 1 and rec.hedge_epoch is not None
        assert [len(h.live) for h in router.replicas] == [1, 1]
        router.run_sync()
        term = {e["id"]: e for e in self._terminals(events)}
        assert list(term) == [gid]            # exactly one terminal
        assert term[gid]["event"] == "done" and term[gid]["tokens"] == ref
        streamed = [e["token"] for e in events if e["event"] == "token"]
        assert streamed == ref                # epoch guard deduped the race
        assert router.metrics.hedges_cancelled == 1
        # the loser never charges a breaker
        assert all(h.breaker.state is BreakerState.CLOSED
                   for h in router.replicas)
        for h in router.replicas:
            assert not h.live
            assert h.sup.engine.pool.num_allocated == 0
            h.sup.engine.check_invariants()

    def test_hedge_budget_bounds_amplification(self, tiny_lm):
        """The budget is consulted before EVERY fire: with every request
        overdue at once, only hedge_budget × open duplicates launch."""
        router, sups, _ = self._router(
            tiny_lm, n=3,
            router_kw=dict(self.HEDGE_KW, hedge_budget=0.4))
        for i in range(5):
            router.submit(np.arange(5, dtype=np.int32) + i, 4)
        router._probe()                       # all 5 overdue; cap = 2
        assert router.metrics.hedges_fired == 2
        router.run_sync()

    def test_hedge_disabled_when_budget_zero(self, tiny_lm):
        router, sups, _ = self._router(
            tiny_lm, n=2,
            router_kw=dict(self.HEDGE_KW, hedge_budget=0.0))
        router.submit(np.arange(5, dtype=np.int32), 4)
        router._probe()
        assert router.metrics.hedges_fired == 0
        router.run_sync()

    def test_hedge_fires_at_most_once_per_request(self, tiny_lm):
        router, sups, _ = self._router(
            tiny_lm, n=3, router_kw=dict(self.HEDGE_KW))
        router.submit(np.arange(5, dtype=np.int32), 4)
        router._probe()
        assert router.metrics.hedges_fired == 1
        router._probe()                       # still overdue, already hedged
        router._probe()
        assert router.metrics.hedges_fired == 1
        router.run_sync()

    def test_hedge_promoted_when_primary_dies(self, tiny_lm):
        """Primary replica hard-killed with a hedge in flight: the
        duplicate is promoted in place (hedges_won) — no fresh migration
        dispatch, and the stream finishes token-exact."""
        model, params = tiny_lm
        router, sups, events = self._router(
            tiny_lm, n=2, router_kw=dict(self.HEDGE_KW))
        p = np.arange(6, dtype=np.int32)
        ref = _greedy_ref(model, params, p, 5, sups[0].engine.assembly_len)
        gid = router.submit(p, 5)
        assert router._open[gid].replica == 0
        router._probe()                       # hedge racing on replica 1
        assert router.metrics.hedges_fired == 1
        router.kill_replica(0)
        rec = router._open[gid]
        assert rec.replica == 1               # duplicate promoted to primary
        assert router.metrics.hedges_won == 1
        assert router.metrics.migrated_requests == 0
        router.run_sync()
        term = {e["id"]: e for e in self._terminals(events)}
        assert term[gid]["event"] == "done" and term[gid]["tokens"] == ref
        streamed = [e["token"] for e in events if e["event"] == "token"]
        assert streamed == ref

    def test_adaptive_threshold_needs_ttft_samples(self, tiny_lm):
        """hedge_ttft_s=None means adaptive: no hedging until the rolling
        TTFT window holds enough samples to trust a p95."""
        router, sups, _ = self._router(
            tiny_lm, n=2,
            router_kw=dict(hedge_ttft_s=None, hedge_budget=1.0,
                           degrade_factor=0.0))
        assert router._hedge_threshold_locked() is None
        router.submit(np.arange(5, dtype=np.int32), 4)
        router._probe()                       # no threshold yet: no hedge
        assert router.metrics.hedges_fired == 0
        router._ttft_window.extend([0.01] * 8)
        thr = router._hedge_threshold_locked()
        assert thr == pytest.approx(0.01)
        router.run_sync()

    def test_fixed_threshold_wins_over_adaptive(self, tiny_lm):
        router, sups, _ = self._router(
            tiny_lm, n=2, router_kw=dict(hedge_ttft_s=0.123))
        router._ttft_window.extend([0.01] * 64)
        assert router._hedge_threshold_locked() == pytest.approx(0.123)

    # -- gray-failure tolerance: observability ---------------------------------

    def test_gray_failure_stats_and_gauges_shape(self, tiny_lm):
        router, sups, _ = self._router(tiny_lm, n=2)
        router.submit(np.arange(5, dtype=np.int32), 4)
        st = router.stats()
        for k in ("hedges_fired", "hedges_won", "hedges_cancelled",
                  "degraded_ejections", "proactive_migrations"):
            assert st[k] == 0
        for r in st["replicas"]:
            assert r["degraded"] is False
            assert r["health_score"] >= 1.0
        g = router.health_gauges()
        assert g["replicas_degraded"] == 0
        for k in ("hedges_fired", "hedges_won", "hedges_cancelled",
                  "degraded_ejections", "proactive_migrations"):
            assert g[k] == 0
        router.run_sync()

    def test_health_score_prometheus_family(self, tiny_lm):
        """The per-replica health-score gauge survives the router-label
        merge: one sample per replica, each keeping its own index."""
        router, sups, _ = self._router(tiny_lm, n=3)
        fams = {f["name"]: f for f in router.prometheus_series()}
        fam = fams["tnn_serve_replica_health_score"]
        assert fam["type"] == "gauge"
        labels = sorted(lbls["replica"] for _, lbls, _ in fam["samples"])
        assert labels == ["0", "1", "2"]
        assert all(v >= 1.0 for _, _, v in fam["samples"])
        for name in ("tnn_serve_hedges_fired_total",
                     "tnn_serve_hedges_won_total",
                     "tnn_serve_hedges_cancelled_total",
                     "tnn_serve_degraded_ejections_total",
                     "tnn_serve_proactive_migrations_total"):
            assert name in fams, name

    def test_gray_failure_metrics_counters(self):
        from tnn_tpu.serving.metrics import ServingMetrics

        m = ServingMetrics()
        m.observe_hedge_fired()
        m.observe_hedge_won()
        m.observe_hedge_cancelled()
        m.observe_ejection()
        m.observe_proactive_migration()
        m.observe_proactive_migration()
        s = m.summary()
        assert s["hedges_fired"] == 1
        assert s["hedges_won"] == 1
        assert s["hedges_cancelled"] == 1
        assert s["degraded_ejections"] == 1
        assert s["proactive_migrations"] == 2


@pytest.mark.slow
def test_gray_failure_chaos_soak(tiny_lm):
    """The gray-failure gate: 3 replicas with the full gray fault surface
    composed — one replica turned persistently slow on a seeded schedule
    (replica.slow), flaky per-replica call drops (net.flaky), a seeded
    router↔replica partition window (net.partition), and a mid-run hard
    kill — with hedging and degraded-ejection live. Asserts the whole
    contract: exactly one terminal per admitted request, hedged streams'
    tokens delivered exactly once, every finished stream token-exact
    against the fault-free reference, zero leaked blocks on survivors."""
    model, params = tiny_lm
    rng = np.random.default_rng(33)
    uniq = [rng.integers(0, 128, int(n)).astype(np.int32)
            for n in rng.integers(4, 12, 6)]
    max_new = 5
    sups = [EngineSupervisor(
                InferenceEngine(model, params, num_blocks=32, block_size=4,
                                max_batch_size=4, max_seq_len=32,
                                max_queue_depth=24),
                restart_backoff_s=0.0)
            for _ in range(3)]
    refs = {i: _greedy_ref(model, params, p, max_new,
                           sups[0].engine.assembly_len)
            for i, p in enumerate(uniq)}
    events = []
    net = FaultPlan(seed=41, flaky_replica=1, flaky_drop_prob=0.15,
                    net_partition_calls=(12,), net_partition_rounds=2)
    router = Router(sups, event_sink=events.append, seed=4, faults=net,
                    retry_backoff_s=0.0, retry_jitter_s=0.0,
                    hedge_ttft_s=0.05, hedge_budget=0.3,
                    degrade_factor=2.0, degrade_window_s=0.05,
                    degrade_cooldown_s=60.0)
    chaos = FaultPlan(seed=9, replica_slow_calls=(8,),
                      replica_kill_calls=(22,))
    n_requests, rejected, submitted = 40, 0, {}
    slow_idx, victim = None, None
    for i in range(n_requests):
        which = int(rng.integers(0, len(uniq)))
        try:
            gid = router.submit(uniq[which], max_new)
            submitted[gid] = which
        except (AdmissionRejected, ShuttingDown, ConnectionError):
            rejected += 1
        router.pump(1)
        if slow_idx is None and chaos.replica_slow():
            # the plan decides WHEN; the harness picks WHICH: the busiest
            slow_idx = max((h for h in router.replicas if not h.killed),
                           key=lambda h: len(h.live)).idx
            router.slow_replica(slow_idx, 0.02)
        if victim is None and chaos.replica_kill():
            victim = max((h for h in router.replicas
                          if not h.killed and h.idx != slow_idx),
                         key=lambda h: len(h.live)).idx
            router.kill_replica(victim)
    router.run_sync()
    router.request_drain("gray soak complete")
    router.run_sync()

    # every composed fault actually fired
    assert chaos.fired["replica.slow"] == 1 and slow_idx is not None
    assert chaos.fired["replica.kill"] == 1 and victim is not None
    assert net.fired["net.partition"] == 1
    assert net.fired["net.flaky"] >= 1
    assert router.state is SupervisorState.STOPPED
    assert router.exit_code == 0
    assert rejected + len(submitted) == n_requests
    # exactly one terminal event per admitted request
    terminals = [e for e in events if e["event"] != "token"]
    per_gid = {}
    for e in terminals:
        per_gid[e["id"]] = per_gid.get(e["id"], 0) + 1
    assert sorted(per_gid) == sorted(submitted)
    assert all(c == 1 for c in per_gid.values()), per_gid
    # finished streams token-exact, hedged tokens delivered exactly once
    finished = [e for e in terminals if e["event"] == "done"]
    assert finished, "gray soak finished nothing"
    for e in finished:
        assert e["tokens"] == refs[submitted[e["id"]]], \
            f"gid {e['id']} diverged from fault-free reference"
        streamed = [t["token"] for t in events
                    if t["event"] == "token" and t["id"] == e["id"]]
        assert streamed == e["tokens"], \
            f"gid {e['id']}: hedged stream duplicated or dropped tokens"
    # zero leaked blocks on the survivors
    for h in router.replicas:
        if h.idx != victim:
            assert h.sup.engine.pool.num_allocated == 0
            h.sup.engine.check_invariants()


@pytest.mark.slow
def test_chaos_soak_router(tiny_lm):
    """The replicated soak gate: 3 replicas behind the router with chaos
    at every layer — alloc faults and NaN rows inside each replica, one
    replica hard-killed mid-run on a seeded schedule. Asserts the full
    failover contract: exactly one terminal event per request, finished
    streams (migrants included) token-exact against the fault-free
    reference, zero leaked blocks on the survivors, clean cascade drain."""
    model, params = tiny_lm
    rng = np.random.default_rng(21)
    uniq = [rng.integers(0, 128, int(n)).astype(np.int32)
            for n in rng.integers(4, 14, 8)]
    max_new = 6
    sups = []
    for i in range(3):
        plan = FaultPlan(seed=100 + i, alloc_fail_prob=0.02,
                         nan_logit_prob=0.01)
        eng = InferenceEngine(model, params, num_blocks=32, block_size=4,
                              max_batch_size=4, max_seq_len=32,
                              max_queue_depth=24, faults=plan)
        eng.pool.fault_plan = plan
        sups.append(EngineSupervisor(eng, restart_backoff_s=0.0,
                                     max_restarts=5))
    refs = {i: _greedy_ref(model, params, p, max_new,
                           sups[0].engine.assembly_len)
            for i, p in enumerate(uniq)}
    events = []
    router = Router(sups, event_sink=events.append, seed=3)
    kill_plan = FaultPlan(seed=9, replica_kill_calls=(40,))
    n_requests, rejected, submitted = 120, 0, {}
    victim = None
    for i in range(n_requests):
        which = int(rng.integers(0, len(uniq)))
        try:
            gid = router.submit(uniq[which], max_new, priority=i % 3)
            submitted[gid] = which
        except (AdmissionRejected, ShuttingDown, ConnectionError):
            rejected += 1
        router.pump(1)
        if victim is None and kill_plan.replica_kill():
            victim = max((h for h in router.replicas if not h.killed),
                         key=lambda h: len(h.live)).idx
            router.kill_replica(victim)
    router.run_sync()
    router.request_drain("soak complete")
    router.run_sync()

    assert victim is not None, "the seeded kill never fired"
    assert kill_plan.fired["replica.kill"] == 1
    assert router.state is SupervisorState.STOPPED
    assert router.exit_code == 0
    assert rejected + len(submitted) == n_requests
    # exactly one terminal event per admitted request
    terminals = [e for e in events if e["event"] != "token"]
    per_gid = {}
    for e in terminals:
        per_gid[e["id"]] = per_gid.get(e["id"], 0) + 1
    assert sorted(per_gid) == sorted(submitted)
    assert all(c == 1 for c in per_gid.values()), per_gid
    # the kill migrated live work, and the migrants landed
    assert router.metrics.migrated_requests > 0
    finished = [e for e in terminals if e["event"] == "done"]
    assert finished, "soak finished nothing"
    for e in finished:
        assert e["tokens"] == refs[submitted[e["id"]]], \
            f"gid {e['id']} diverged from fault-free reference"
    # zero leaked blocks on the survivors
    for h in router.replicas:
        if h.idx != victim:
            assert h.sup.engine.pool.num_allocated == 0
            h.sup.engine.check_invariants()


# -- speculative decoding: drafters, rollback, token-exact verification -------


def _cyclic_prompts(n, seed=0, vocab=128):
    """Short-period cyclic token streams. The n-gram drafter finds its own
    suffix immediately, and a greedy model on repetitive context tends to
    keep the loop going — so drafts are reliably proposed AND accepted
    without depending on trained weights."""
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        motif = rng.integers(0, vocab, int(rng.integers(2, 5)))
        out.append(np.tile(motif, int(rng.integers(3, 6))).astype(np.int32))
    return out


@pytest.fixture(scope="module")
def draft_lm(tiny_lm):
    """The zoo's draft-model config, sharing the target's vocab/max_len."""
    from tnn_tpu.models.zoo import create

    model, _ = tiny_lm
    draft = create("gpt2_tiny", vocab_size=model.vocab_size,
                   max_len=model.max_len)
    params = draft.init(jax.random.PRNGKey(1), (1, 8))["params"]
    return draft, params


class TestDrafters:
    """Host-side drafter unit tests — no engine, no pool."""

    def _req(self, prompt, out=()):
        import types

        return types.SimpleNamespace(
            prompt=np.asarray(prompt, np.int32), out_tokens=list(out))

    def test_ngram_copies_continuation_of_repeated_suffix(self):
        from tnn_tpu.serving.spec_decode import NGramDrafter

        d = NGramDrafter(max_n=3)
        req = self._req([1, 2, 3, 1, 2, 3, 1, 2])
        assert d.draft(req, 3) == [3, 1, 2]
        assert d.draft(req, 1) == [3]

    def test_ngram_silent_on_novel_context(self):
        from tnn_tpu.serving.spec_decode import NGramDrafter

        assert NGramDrafter().draft(self._req(np.arange(8)), 4) == []

    def test_ngram_sees_generated_tokens(self):
        """The lookup context is prompt + out_tokens (including the pending
        next_token), so output-side loops draft themselves too."""
        from tnn_tpu.serving.spec_decode import NGramDrafter

        req = self._req([7, 8], out=[9, 7, 8])
        assert NGramDrafter().draft(req, 2) == [9, 7]

    def test_ngram_validates_orders(self):
        from tnn_tpu.serving.spec_decode import NGramDrafter

        with pytest.raises(ValueError, match="min_n"):
            NGramDrafter(max_n=2, min_n=3)

    def test_draft_model_deterministic_and_in_vocab(self, draft_lm):
        from tnn_tpu.serving.spec_decode import DraftModelDrafter

        model, params = draft_lm
        d = DraftModelDrafter(model, params)
        req = self._req(np.arange(8) % 128)
        a, b = d.draft(req, 4), d.draft(req, 4)
        assert a == b and len(a) == 4
        assert all(0 <= t < model.vocab_size for t in a)

    def test_draft_model_clamps_at_position_cap(self, draft_lm):
        """Near the draft model's own max_len the proposal shrinks; at the
        cap it vanishes — never an out-of-range position."""
        from tnn_tpu.serving.spec_decode import DraftModelDrafter

        model, params = draft_lm
        d = DraftModelDrafter(model, params)
        assert d.draft(
            self._req(np.zeros(model.max_len, np.int32)), 4) == []
        near = d.draft(self._req(np.zeros(model.max_len - 2, np.int32)), 4)
        assert len(near) == 2


class TestSchedulerSpecBudget:
    def _sched(self, spec_tokens):
        sched = Scheduler(max_batch_size=4, token_budget=10, chunk_size=8,
                          spec_tokens=spec_tokens)
        dec = _req(0, 4, max_new=8)
        dec.prefill_len = 4
        dec.cache_len = 4                 # decode phase
        pre = _req(1, 12, max_new=8)
        pre.prefill_len = 12
        pre.cache_len = 4                 # mid-prefill: 8 prompt tokens left
        sched.admit(dec)
        sched.admit(pre)
        return sched

    def test_decode_rows_reserve_draft_budget(self):
        pool = PagedKVPool(num_layers=1, num_kv_heads=1, head_dim=2,
                           num_blocks=9, block_size=4)
        assert self._sched(0).schedule(pool).chunks == {1: 8}
        # each decode row now costs 1 + spec_tokens of the step budget:
        # 10 - 5 leaves a 5-token chunk grant instead of 8
        assert self._sched(4).schedule(pool).chunks == {1: 5}

    def test_negative_spec_tokens_rejected(self):
        with pytest.raises(ValueError, match="spec_tokens"):
            Scheduler(max_batch_size=4, token_budget=10, spec_tokens=-1)


class TestPoolTruncate:
    """truncate() is the speculative-rollback primitive; check_invariants
    grew per-row seq_len checks to catch both ways it can go wrong."""

    def _pool(self, **kw):
        kw.setdefault("num_layers", 1)
        kw.setdefault("num_kv_heads", 1)
        kw.setdefault("head_dim", 2)
        kw.setdefault("num_blocks", 8)
        kw.setdefault("block_size", 4)
        return PagedKVPool(**kw)

    def test_truncate_frees_rejected_tail(self):
        pool = self._pool()
        table = pool.alloc(4)              # headroom for 16 positions
        kept = pool.truncate(table, 9)     # verifier kept 9 resident tokens
        assert kept == table[:3]
        assert pool.num_allocated == 3
        pool.check_invariants([kept], [9])

    def test_truncate_noop_when_table_tight(self):
        pool = self._pool()
        table = pool.alloc(2)
        assert pool.truncate(table, 8) == table
        assert pool.num_allocated == 2

    def test_truncate_to_zero_frees_everything(self):
        pool = self._pool()
        table = pool.alloc(3)
        assert pool.truncate(table, 0) == []
        assert pool.num_allocated == 0 and pool.num_free == pool.capacity

    def test_truncate_parks_indexed_blocks_evictable(self):
        """Rollback preserves the free/allocated/evictable partition: freed
        tail blocks the prefix cache still indexes park in the LRU instead
        of returning to the free list."""
        pool = self._pool()
        table = pool.alloc(4)
        cached = set(table[2:])
        pool.evictable_filter = cached.__contains__
        kept = pool.truncate(table, 5)
        assert kept == table[:2]
        assert pool.num_evictable == 2 and pool.num_allocated == 2
        assert pool.num_free + pool.num_evictable + pool.num_allocated \
            == pool.capacity
        pool.check_invariants([kept], [5])

    def test_truncated_too_deep_detected(self):
        pool = self._pool()
        table = pool.alloc(1)              # covers 4 positions only
        with pytest.raises(ValueError, match="truncated too deep"):
            pool.check_invariants([table], [9])

    def test_stale_draft_tail_detected(self):
        """A row that grew blocks for 1+k candidates but skipped rollback
        after rejection holds more than blocks_for(n + 1) blocks."""
        pool = self._pool()
        table = pool.alloc(4)
        with pytest.raises(ValueError, match="stale tail"):
            pool.check_invariants([table], [4])   # 4 resident: max 2 blocks
        pool.check_invariants([pool.truncate(table, 4)], [4])

    def test_seq_lens_must_parallel_tables(self):
        pool = self._pool()
        table = pool.alloc(1)
        with pytest.raises(ValueError, match="not parallel"):
            pool.check_invariants([table], [4, 4])


class TestSpecDecode:
    """The PR 7 tentpole: drafted tokens ride the EXISTING mixed step as
    ragged q_lens = k+1 rows; greedy verification must be token-exact
    against the offline reference under every schedule, and rollback must
    leave pool bookkeeping clean."""

    KW = dict(num_blocks=32, block_size=4, max_batch_size=4, max_seq_len=32)

    def _eng(self, tiny_lm, draft_lm=None, spec="ngram", **kw):
        model, params = tiny_lm
        merged = dict(self.KW)
        merged.update(kw)
        if spec == "draft":
            dm, dp = draft_lm
            merged.update(draft_model=dm, draft_params=dp)
        return InferenceEngine(model, params, spec=spec, **merged)

    def _staggered(self, eng, prompts, max_new=10):
        rids = [eng.submit(prompts[0], max_new)]
        eng.step(); eng.step()
        rids += [eng.submit(p, max_new) for p in prompts[1:]]
        out = eng.run_until_complete()
        return [out[r] for r in rids]

    @pytest.mark.parametrize(
        "path", [pytest.param("standard", marks=pytest.mark.slow), "paged"])
    def test_ngram_staggered_parity(self, tiny_lm, path):
        model, params = tiny_lm
        prompts = _cyclic_prompts(4, seed=0)
        eng = self._eng(tiny_lm, decode_path=path)
        outs = self._staggered(eng, prompts)
        for toks, p in zip(outs, prompts):
            assert toks == _greedy_ref(model, params, p, 10,
                                       eng.assembly_len)
        s = eng.metrics.summary()
        assert s["spec_draft_tokens"] > 0, "drafter never fired — dead test"
        assert s["spec_acceptance_rate"] > 0
        # spec rows compile under their own key; widths stay pow2-bucketed
        spec_keys = [k for k in eng._jit
                     if k[0] == "mixed" and k[-1] == "spec"]
        assert spec_keys, "no spec mixed program was ever compiled"
        assert all(k[2] & (k[2] - 1) == 0 for k in spec_keys)
        _assert_drained(eng)

    # both variants re-pay the draft-model jit cache; the draft axis keeps
    # a tier-1 gate via the spec_draft crash-resume matrix entry
    @pytest.mark.slow
    @pytest.mark.parametrize("path", ["standard", "paged"])
    def test_draft_model_staggered_parity(self, tiny_lm, draft_lm, path):
        model, params = tiny_lm
        prompts = _cyclic_prompts(4, seed=1)
        eng = self._eng(tiny_lm, draft_lm, spec="draft", decode_path=path)
        outs = self._staggered(eng, prompts)
        for toks, p in zip(outs, prompts):
            assert toks == _greedy_ref(model, params, p, 10,
                                       eng.assembly_len)
        assert eng.metrics.summary()["spec_draft_tokens"] > 0
        _assert_drained(eng)

    def test_spec_off_engine_is_untouched(self, tiny_lm):
        """spec="off" must not even build spec programs: every mixed compile
        key keeps its legacy 4-tuple shape, and the gauges say so."""
        eng = self._eng(tiny_lm, spec="off")
        self._staggered(eng, _cyclic_prompts(4, seed=0))
        assert all(len(k) == 4 for k in eng._jit if k[0] == "mixed")
        s = eng.stats()
        assert s["spec"] == "off" and s["spec_k"] == 0
        assert eng.metrics.summary()["mean_accepted_per_step"] == 0.0

    def test_preemption_parity_with_rollback(self, tiny_lm):
        """A starved pool preempts speculating rows mid-stream; rollback +
        recompute-requeue must stay byte-identical to the offline reference
        and drain with zero leaks."""
        model, params = tiny_lm
        prompts = _cyclic_prompts(4, seed=2)
        eng = self._eng(tiny_lm, num_blocks=9)
        for p in prompts:
            eng.submit(p, 10)
        out = eng.run_until_complete()
        assert eng.metrics.preemptions > 0, "pool was never exhausted"
        for rid, p in enumerate(prompts):
            assert out[rid] == _greedy_ref(model, params, p, 10,
                                           eng.assembly_len)
        _assert_drained(eng)

    def test_prefix_cache_hits_stay_exact(self, tiny_lm):
        """Shared-prefix admission (forked tables, COW) composes with
        speculation: cached rows still verify token-exact."""
        model, params = tiny_lm
        rng = np.random.default_rng(3)
        prefix = np.tile(rng.integers(0, 128, 3), 4).astype(np.int32)
        prompts = [np.concatenate([prefix, rng.integers(0, 128, 4)
                                   .astype(np.int32)]) for _ in range(4)]
        eng = self._eng(tiny_lm)
        rids = []
        for p in prompts:
            rids.append(eng.submit(p, 8))
            eng.step()
        out = eng.run_until_complete()
        assert eng.metrics.prefill_tokens_saved > 0, "cache never hit"
        for rid, p in zip(rids, prompts):
            assert out[rid] == _greedy_ref(model, params, p, 8,
                                           eng.assembly_len)
        _assert_drained(eng)

    def test_stop_token_mid_draft_clips_commit(self, tiny_lm):
        """A stop token inside an accepted draft run clips the commit at the
        stop position — trailing accepted tokens are discarded, exactly as
        sequential decode would never have produced them."""
        model, params = tiny_lm
        p = _cyclic_prompts(1, seed=4)[0]
        eng = self._eng(tiny_lm)
        ref = _greedy_ref(model, params, p, 10, eng.assembly_len)
        stop = ref[3]
        rid = eng.submit(p, 10, stop_token=stop)
        out = eng.run_until_complete()
        # cyclic streams repeat tokens: the FIRST occurrence wins, exactly
        # as sequential decode would have stopped
        assert out[rid] == ref[:ref.index(stop) + 1]
        assert eng.result(rid).finish_reason == "stop_token"
        _assert_drained(eng)

    def test_max_new_clamp_never_overshoots(self, tiny_lm):
        """k is clamped to the remaining generation budget, so accepted
        drafts can never commit past max_new_tokens."""
        model, params = tiny_lm
        p = _cyclic_prompts(1, seed=5)[0]
        eng = self._eng(tiny_lm, spec_k=6)
        ref = _greedy_ref(model, params, p, 5, eng.assembly_len)
        rid = eng.submit(p, 5)
        out = eng.run_until_complete()
        assert out[rid] == ref
        assert eng.result(rid).finish_reason == "length"
        _assert_drained(eng)

    def test_stochastic_spec_stays_in_vocab(self, tiny_lm):
        """The rejection-sampling path: stochastic rows speculate too, and
        co-batched greedy rows stay exact. (Cross-schedule distributional
        equality is the verifier's rejection-sampling construction; draw
        sequences legitimately differ from the spec-off stream.)"""
        model, params = tiny_lm
        eng = self._eng(tiny_lm, seed=3)
        p = _cyclic_prompts(1, seed=6)[0]
        g = eng.submit(p, 8)
        s = eng.submit(p, 8, temperature=0.9, top_k=16, top_p=0.9)
        out = eng.run_until_complete()
        assert out[g] == _greedy_ref(model, params, p, 8, eng.assembly_len)
        assert len(out[s]) == 8
        assert all(0 <= t < model.vocab_size for t in out[s])
        _assert_drained(eng)

    def test_spec_metrics_and_stats(self, tiny_lm):
        eng = self._eng(tiny_lm, spec_k=4)
        for p in _cyclic_prompts(4, seed=0):
            eng.submit(p, 12)
        eng.run_until_complete()
        s = eng.metrics.summary()
        assert s["spec_draft_tokens"] >= s["spec_accepted_tokens"] > 0
        assert 0 < s["spec_acceptance_rate"] <= 1
        assert s["mean_accepted_per_step"] > 1, \
            "speculation never beat sequential decode on cyclic prompts"
        assert "token_latency_ms_p99" in s
        st = eng.stats()
        assert st["spec"] == "ngram" and st["spec_k"] == 4
        assert st["compiled_step_signatures"] == len(eng._jit) >= 1

    def test_custom_drafter_instance_accepted(self, tiny_lm):
        from tnn_tpu.serving.spec_decode import NGramDrafter

        eng = self._eng(tiny_lm, spec=NGramDrafter(max_n=2))
        assert eng.stats()["spec"] == "ngram"
        p = _cyclic_prompts(1, seed=7)[0]
        model, params = tiny_lm
        rid = eng.submit(p, 8)
        out = eng.run_until_complete()
        assert out[rid] == _greedy_ref(model, params, p, 8,
                                       eng.assembly_len)

    def test_constructor_validation(self, tiny_lm, draft_lm):
        model, params = tiny_lm
        with pytest.raises(ValueError, match="unknown spec"):
            InferenceEngine(model, params, spec="turbo", **self.KW)
        with pytest.raises(ValueError, match="draft_model"):
            InferenceEngine(model, params, spec="draft", **self.KW)
        with pytest.raises(ValueError, match="spec_k"):
            InferenceEngine(model, params, spec="ngram", spec_k=0,
                            **self.KW)
        with pytest.raises(ValueError, match="chunked_prefill"):
            InferenceEngine(model, params, spec="ngram",
                            chunked_prefill=False, **self.KW)
        from tnn_tpu.models.gpt2 import gpt2_tiny

        wrong = gpt2_tiny(vocab_size=64, max_len=64)
        wp = wrong.init(jax.random.PRNGKey(2), (1, 8))["params"]
        with pytest.raises(ValueError, match="vocab"):
            InferenceEngine(model, params, spec="draft", draft_model=wrong,
                            draft_params=wp, **self.KW)


class TestSpecChaos:
    """Chaos gate over speculation: alloc faults + NaN rows + poisoned
    drafts. Every request terminal, survivors byte-identical to a
    fault-free spec-OFF run (speculation plus faults may never change a
    committed token), zero leaked blocks."""

    KW = dict(num_blocks=16, block_size=4, max_batch_size=4, max_seq_len=32)

    @pytest.mark.parametrize(
        "spec", ["ngram", pytest.param("draft", marks=pytest.mark.slow)])
    def test_chaos_gate_spec(self, tiny_lm, draft_lm, spec):
        model, params = tiny_lm
        prompts = _cyclic_prompts(8, seed=7)
        kw = dict(self.KW)
        if spec == "draft":
            kw.update(draft_model=draft_lm[0], draft_params=draft_lm[1])
        ref_eng = InferenceEngine(model, params, **self.KW)
        ref_rids = [ref_eng.submit(p, 8) for p in prompts]
        ref_eng.run_until_complete()
        plan = FaultPlan(seed=9, alloc_fail_prob=0.12, nan_logit_calls=(3,),
                         draft_poison_prob=0.3)
        eng = InferenceEngine(model, params, spec=spec, faults=plan, **kw)
        rids = [eng.submit(p, 8) for p in prompts]
        eng.run_until_complete()
        assert plan.fired["pool.alloc"] >= 1, "alloc chaos never fired"
        assert plan.fired["draft.poison"] >= 1, "draft chaos never fired"
        states = [eng.result(r).state for r in rids]
        assert all(st in TERMINAL_STATES for st in states)
        assert RequestState.FINISHED in states, "no request survived"
        out, ref = _finished(eng), _finished(ref_eng)
        for rid, ref_rid in zip(rids, ref_rids):
            if rid in out:
                assert out[rid] == ref[ref_rid], f"survivor {rid} diverged"
        _assert_drained(eng)

    def test_poisoned_drafts_cost_acceptance_only(self, tiny_lm):
        """Poison EVERY draft: output still exact, acceptance reflects that
        corrupted proposals were rejected wholesale."""
        model, params = tiny_lm
        p = _cyclic_prompts(1, seed=8)[0]
        plan = FaultPlan(draft_poison_prob=1.0)
        eng = InferenceEngine(model, params, spec="ngram", faults=plan,
                              **self.KW)
        rid = eng.submit(p, 10)
        out = eng.run_until_complete()
        assert out[rid] == _greedy_ref(model, params, p, 10,
                                       eng.assembly_len)
        assert plan.fired["draft.poison"] > 0
        s = eng.metrics.summary()
        assert s["spec_draft_tokens"] > 0
        _assert_drained(eng)


@pytest.mark.slow
def test_gpt2_small_spec_ngram_staggered():
    """Acceptance bar for speculation at model scale: 8 staggered cyclic
    prompts on gpt2_small with spec="ngram", surviving preemption.

    Correctness is asserted by TEACHER FORCING, like
    test_gpt2_small_staggered_greedy: the spec verifier runs a differently
    fused program than sequential decode, so whole-sequence equality against
    a spec-off engine is ill-posed at this depth (top-2 logit gaps sit below
    f32 reduction noise). Every committed token must be the reference argmax
    up to fp near-ties, and speculation must actually accept drafts."""
    from tnn_tpu.models.zoo import create

    model = create("gpt2_small")
    params = model.init(jax.random.PRNGKey(0), (1, 8))["params"]
    rng = np.random.default_rng(0)
    prompts = [np.tile(rng.integers(0, model.vocab_size, 3), 4)
               .astype(np.int32) for _ in range(8)]
    max_new = 16
    eng = InferenceEngine(model, params, num_blocks=14, block_size=16,
                          max_batch_size=8, max_seq_len=32, spec="ngram")
    rids = []
    for i, p in enumerate(prompts):
        rids.append(eng.submit(p, max_new))
        if i % 3 == 2:
            eng.step()
    out = eng.run_until_complete()
    assert all(len(out[rid]) == max_new for rid in rids)
    assert eng.metrics.summary()["spec_accepted_tokens"] > 0, \
        "speculation never accepted a draft on cyclic prompts"

    seqs = np.stack([np.concatenate([prompts[i], out[rids[i]]])
                     for i in range(len(rids))])
    caches = model.init_cache(len(rids), seqs.shape[1])
    logits, _ = model.apply_cached(params, jnp.asarray(seqs), caches, 0)
    logits = np.asarray(logits, np.float64)
    plen = len(prompts[0])
    exact, ties = 0, []
    for i in range(len(rids)):
        for j in range(max_new):
            row = logits[i, plen + j - 1]
            chosen = seqs[i, plen + j]
            if chosen == row.argmax():
                exact += 1
            else:
                ties.append(float(row.max() - row[chosen]))
    total = len(rids) * max_new
    assert exact >= 0.9 * total, f"only {exact}/{total} tokens were argmax"
    assert all(m < 0.05 for m in ties), f"non-tie divergence: {ties}"
    _assert_drained(eng)


# -- host-RAM KV tier + elastic fleet (PR: elastic fleet resilience) ----------


class TestFaultPlanFleetSites:
    """Seed-determinism for the tier/scaling chaos sites, in the same
    shape as the client/replica site tests above: identical seeds replay
    identical fire schedules, scheduled calls fire at exact positions."""

    def test_tier_sites_are_deterministic(self):
        def trace(plan):
            return [(plan.tier_demote_fail(), plan.tier_corrupt(),
                     plan.tier_slow_readmit()) for _ in range(48)]

        kw = dict(tier_demote_fail_prob=0.3, tier_corrupt_prob=0.25,
                  tier_slow_readmit_prob=0.2)
        a = trace(FaultPlan(seed=5, **kw))
        b = trace(FaultPlan(seed=5, **kw))
        c = trace(FaultPlan(seed=6, **kw))
        assert a == b
        assert a != c
        assert any(t[0] for t in a) and any(t[1] for t in a) \
            and any(t[2] for t in a)
        plan = FaultPlan(seed=5, **kw)
        trace(plan)
        assert plan.calls["tier.demote_fail"] == 48
        assert plan.fired["tier.demote_fail"] == sum(t[0] for t in a)
        assert plan.fired["tier.corrupt"] == sum(t[1] for t in a)
        assert plan.fired["tier.slow_readmit"] == sum(t[2] for t in a)

    def test_scheduled_tier_calls_fire_exactly(self):
        plan = FaultPlan(tier_demote_fail_calls=(2,),
                         tier_corrupt_calls=(1, 3),
                         tier_slow_readmit_calls=(2,))
        assert [plan.tier_demote_fail() for _ in range(3)] == \
            [False, True, False]
        assert [plan.tier_corrupt() for _ in range(3)] == \
            [True, False, True]
        assert [plan.tier_slow_readmit() for _ in range(3)] == \
            [False, True, False]
        assert plan.fired["tier.demote_fail"] == 1
        assert plan.fired["tier.corrupt"] == 2
        assert plan.fired["tier.slow_readmit"] == 1

    def test_scale_join_site_is_deterministic(self):
        def trace(plan):
            return [plan.scale_join_fail() for _ in range(48)]

        a = trace(FaultPlan(seed=5, scale_join_fail_prob=0.3))
        b = trace(FaultPlan(seed=5, scale_join_fail_prob=0.3))
        c = trace(FaultPlan(seed=6, scale_join_fail_prob=0.3))
        assert a == b
        assert a != c
        assert any(a) and not all(a)
        plan = FaultPlan(seed=5, scale_join_fail_prob=0.3)
        trace(plan)
        assert plan.calls["scale.join_fail"] == 48
        assert plan.fired["scale.join_fail"] == sum(a)

    def test_scheduled_scale_join_calls_fire_exactly(self):
        plan = FaultPlan(scale_join_fail_calls=(1, 3))
        assert [plan.scale_join_fail() for _ in range(4)] == \
            [True, False, True, False]
        assert plan.fired["scale.join_fail"] == 2


class TestHostKVTier:
    """Tier unit tests — no engine, no pool: demote/verify roundtrip,
    digest enforcement, LRU bounds, fault sites, byte accounting."""

    def _leaves(self, seed=0, shape=(2, 4, 2), dtype=np.float32):
        rng = np.random.default_rng(seed)
        k = rng.standard_normal(shape).astype(dtype)
        v = rng.standard_normal(shape).astype(dtype)
        return (k, v)

    def test_demote_verify_roundtrip(self):
        tier = HostKVTier(1 << 20)
        leaves = self._leaves(1)
        assert tier.demote(b"key-a", leaves)
        assert b"key-a" in tier and len(tier) == 1
        assert tier.bytes_used == sum(x.nbytes for x in leaves)
        out = tier.verify_readmit(b"key-a")
        assert out is not None
        np.testing.assert_array_equal(out[0], leaves[0])
        np.testing.assert_array_equal(out[1], leaves[1])
        # a successful readmit REMOVES the entry (it is device-resident
        # again and will re-demote on its next eviction)
        assert b"key-a" not in tier and tier.bytes_used == 0
        s = tier.stats()
        assert s["tier_demotions"] == 1 and s["tier_readmits"] == 1
        assert s["tier_corrupt_dropped"] == 0
        tier.check_invariants()

    def test_miss_returns_none(self):
        tier = HostKVTier(1 << 20)
        assert tier.verify_readmit(b"never-demoted") is None
        assert tier.stats()["tier_corrupt_dropped"] == 0

    def test_real_corruption_is_dropped_not_served(self):
        """Bit rot planted straight into the stored leaf (no fault plan):
        the digest recomputation catches it, the entry is dropped, the
        caller sees an uncached miss — never wrong KV."""
        tier = HostKVTier(1 << 20)
        tier.demote(b"key-a", self._leaves(2))
        entry = tier._entries[b"key-a"]
        entry.leaves[0].reshape(-1).view(np.uint8)[3] ^= 0x40
        assert tier.verify_readmit(b"key-a") is None
        assert b"key-a" not in tier
        assert tier.bytes_used == 0
        assert tier.stats()["tier_corrupt_dropped"] == 1
        tier.check_invariants()

    def test_digest_binds_dtype_and_shape(self):
        """tier_digest covers dtype and shape, not just raw bytes — a
        reinterpreted payload cannot pass verification."""
        from tnn_tpu.serving.kv_tier import tier_digest

        arr = np.arange(8, dtype=np.float32)
        base = tier_digest(b"k", (arr,))
        assert tier_digest(b"k", (arr.reshape(2, 4),)) != base
        assert tier_digest(b"k", (arr.view(np.int32),)) != base
        assert tier_digest(b"other", (arr,)) != base
        assert tier_digest(b"k", (arr.copy(),)) == base

    def test_lru_bound_displaces_oldest(self):
        leaves = self._leaves(3)
        per = sum(x.nbytes for x in leaves)
        tier = HostKVTier(per * 2)      # room for exactly two entries
        assert tier.demote(b"a", leaves)
        assert tier.demote(b"b", leaves)
        assert tier.demote(b"c", leaves)   # displaces "a" (LRU-oldest)
        assert tier.keys() == [b"b", b"c"]
        assert tier.bytes_used == per * 2
        assert tier.stats()["tier_evictions"] == 1
        tier.check_invariants()

    def test_oversize_entry_degrades_to_plain_eviction(self):
        leaves = self._leaves(4)
        tier = HostKVTier(sum(x.nbytes for x in leaves) - 1)
        assert not tier.demote(b"big", leaves)
        assert len(tier) == 0 and tier.bytes_used == 0
        assert tier.stats()["tier_demote_failures"] == 1
        tier.check_invariants()

    def test_redemote_same_key_replaces_exactly(self):
        tier = HostKVTier(1 << 20)
        old, new = self._leaves(5), self._leaves(6)
        tier.demote(b"k", old)
        tier.demote(b"k", new)           # re-published prefix: newest wins
        assert len(tier) == 1
        assert tier.bytes_used == sum(x.nbytes for x in new)
        out = tier.verify_readmit(b"k")
        np.testing.assert_array_equal(out[0], new[0])
        tier.check_invariants()

    def test_demote_fail_fault_degrades(self):
        plan = FaultPlan(tier_demote_fail_calls=(1,))
        tier = HostKVTier(1 << 20, fault_plan=plan)
        leaves = self._leaves(7)
        assert not tier.demote(b"a", leaves)   # injected: plain eviction
        assert tier.demote(b"b", leaves)       # call 2 passes
        assert plan.fired["tier.demote_fail"] == 1
        assert tier.stats()["tier_demote_failures"] == 1
        assert len(tier) == 1

    def test_corrupt_fault_caught_by_digest(self):
        """The injected corruption flips a byte of a COPY and keeps the
        stored digest — so the verifier genuinely detects it, the same
        code path real bit rot takes."""
        plan = FaultPlan(tier_corrupt_calls=(1,))
        tier = HostKVTier(1 << 20, fault_plan=plan)
        leaves = self._leaves(8)
        tier.demote(b"k", leaves)
        assert tier.verify_readmit(b"k") is None
        assert plan.fired["tier.corrupt"] == 1
        assert tier.stats()["tier_corrupt_dropped"] == 1
        assert b"k" not in tier and tier.bytes_used == 0
        tier.check_invariants()

    def test_slow_readmit_stalls_but_succeeds(self):
        plan = FaultPlan(tier_slow_readmit_calls=(1,),
                         tier_slow_readmit_s=0.02)
        tier = HostKVTier(1 << 20, fault_plan=plan)
        leaves = self._leaves(9)
        tier.demote(b"k", leaves)
        t0 = time.perf_counter()
        out = tier.verify_readmit(b"k")
        assert time.perf_counter() - t0 >= 0.02
        assert out is not None             # late, not wrong
        np.testing.assert_array_equal(out[0], leaves[0])
        assert plan.fired["tier.slow_readmit"] == 1

    def test_int8_leaves_halve_footprint(self):
        shape = (2, 4, 8)
        f32 = (np.zeros(shape, np.float32), np.zeros(shape, np.float32))
        q = (np.zeros(shape, np.int8), np.zeros((2, 4, 1), np.float32),
             np.zeros(shape, np.int8), np.zeros((2, 4, 1), np.float32))
        tier = HostKVTier(1 << 20)
        tier.demote(b"f32", f32)
        f32_bytes = tier.bytes_used
        tier.clear()
        tier.demote(b"int8", q)
        assert tier.bytes_used < f32_bytes * 0.6

    def test_clear_drops_everything(self):
        tier = HostKVTier(1 << 20)
        tier.demote(b"a", self._leaves(10))
        tier.demote(b"b", self._leaves(11))
        tier.clear()
        assert len(tier) == 0 and tier.bytes_used == 0
        assert tier.verify_readmit(b"a") is None
        tier.check_invariants()

    def test_validation(self):
        with pytest.raises(ValueError, match="max_bytes"):
            HostKVTier(0)


class TestTierEngine:
    """Tier <-> engine integration: demotion under pool pressure, verified
    re-admission through the revive path, token-exactness with the full
    feature stack composed, corrupt entries degrading to uncached misses."""

    def _prompts(self, n=6, prefix_len=8, tail_len=4, seed=0):
        """Prompts sharing a cyclic prefix (spec-friendly) + unique tails."""
        rng = np.random.default_rng(seed)
        motif = rng.integers(0, 128, 4)
        prefix = np.tile(motif, prefix_len // 4).astype(np.int32)
        return [np.concatenate([prefix,
                                rng.integers(0, 128, tail_len)
                                   .astype(np.int32)])
                for _ in range(n)]

    def _engine(self, tiny_lm, *, tier_bytes, **kw):
        model, params = tiny_lm
        merged = dict(num_blocks=10, block_size=4, max_batch_size=2,
                      max_seq_len=32, chunk_size=8,
                      host_tier_bytes=tier_bytes)
        merged.update(kw)
        return InferenceEngine(model, params, **merged)

    def _serve_serially(self, eng, prompts, max_new=6):
        """One request at a time: each finish releases evictable blocks,
        each next admission's alloc pressure demotes them — the working
        set cycles through the tier instead of fitting in the pool."""
        out = []
        for p in prompts:
            rid = eng.submit(p, max_new)
            res = eng.run_until_complete()
            out.append(res[rid])
            del eng.requests[rid]
        return out

    @pytest.mark.slow
    @pytest.mark.parametrize("path", ["standard", "paged"])
    def test_tier_token_exact_composed(self, tiny_lm, path):
        """The acceptance gate: tier-on output must equal tier-off output
        token-for-token with prefix cache + ngram speculation + overlap +
        int8 KV all composed, on both decode paths — and the tier must
        have genuinely carried traffic (demotions and readmits observed),
        while the tier-off twin saw none."""
        prompts = self._prompts()
        compose = dict(decode_path=path, spec="ngram", spec_k=3,
                       overlap=True, kv_dtype="int8")
        on = self._engine(tiny_lm, tier_bytes=1 << 20, **compose)
        off = self._engine(tiny_lm, tier_bytes=0, **compose)
        # two passes: the first populates device cache + tier, the second
        # readmits what pool pressure demoted
        on_toks = [self._serve_serially(on, prompts) for _ in range(2)][1]
        off_toks = [self._serve_serially(off, prompts) for _ in range(2)][1]
        assert on_toks == off_toks
        s_on, s_off = on.stats(), off.stats()
        assert s_on["tier_demotions"] > 0, "pool pressure never demoted"
        assert s_on["tier_readmits"] > 0, "no prefix hit readmitted"
        assert s_on["tier_corrupt_dropped"] == 0
        assert s_off["tier_readmits"] == 0
        _assert_drained(on)
        _assert_drained(off)
        on.check_invariants()

    def test_tier_metrics_and_gauges_flow(self, tiny_lm):
        """stats() and health_gauges() surface the tier counters the
        dashboards scrape."""
        eng = self._engine(tiny_lm, tier_bytes=1 << 20)
        prompts = self._prompts(seed=1)
        self._serve_serially(eng, prompts)
        self._serve_serially(eng, prompts)
        s = eng.stats()
        assert s["host_tier_enabled"]
        assert s["tier_demotions"] > 0
        assert s["tier_bytes"] <= s["tier_max_bytes"] == 1 << 20
        m = eng.metrics.summary()
        assert m["tier_hits"] >= s["tier_readmits"] > 0
        assert m["tier_corrupt"] == 0
        assert m["tier_blocks"] == s["tier_blocks"]
        assert m["tier_bytes"] == s["tier_bytes"]
        # the Prometheus scrape surface carries the tier families
        from tnn_tpu.serving.metrics import render_prometheus

        text = render_prometheus(eng.metrics.prometheus_series())
        for name in ("tnn_serve_tier_blocks", "tnn_serve_tier_bytes",
                     "tnn_serve_tier_hits_total",
                     "tnn_serve_tier_corrupt_total", "tnn_serve_replicas"):
            assert name in text, f"{name} missing from exposition"

    @pytest.mark.slow
    def test_planted_corruption_degrades_to_uncached_miss(self, tiny_lm):
        """A seeded tier.corrupt on the first readmit: the digest check
        drops the entry, the request recomputes the prefix (uncached
        miss), the output stays token-exact, and the corruption counter
        fires — wrong KV is never adopted."""
        plan = FaultPlan(tier_corrupt_calls=(1,))
        eng = self._engine(tiny_lm, tier_bytes=1 << 20, faults=plan)
        ref = self._engine(tiny_lm, tier_bytes=0)
        prompts = self._prompts(seed=2)
        self._serve_serially(eng, prompts)
        got = self._serve_serially(eng, prompts)
        self._serve_serially(ref, prompts)
        want = self._serve_serially(ref, prompts)
        assert got == want
        assert plan.fired["tier.corrupt"] == 1
        s = eng.stats()
        assert s["tier_corrupt_dropped"] == 1
        assert eng.metrics.tier_corrupt == 1
        _assert_drained(eng)

    @pytest.mark.slow
    def test_tier_cleared_on_crash_recovery(self, tiny_lm):
        """Crash recovery re-zeroes the pool; everything demoted before
        the crash is conservatively untrusted and the tier must come back
        empty — stale KV may never survive a restart."""
        plan = FaultPlan(step_crash_calls=(6,))
        eng = self._engine(tiny_lm, tier_bytes=1 << 20, faults=plan)
        model, params = tiny_lm
        prompts = self._prompts(seed=3)
        refs = [_greedy_ref(model, params, p, 4, eng.assembly_len)
                for p in prompts]
        events = []
        sup = EngineSupervisor(eng, event_sink=events.append,
                               restart_backoff_s=0.0, max_restarts=2)
        rids = [sup.submit(p, 4) for p in prompts]
        sup.run_sync()
        assert sup.restarts == 1
        assert len(eng.kv_tier) == 0 or eng.stats()["tier_demotions"] > 0
        term = {e["id"]: e for e in events if e["event"] != "token"}
        for rid, r in zip(rids, refs):
            assert term[rid]["event"] == "done"
            assert term[rid]["tokens"] == r
        _assert_drained(eng)


class TestElasticFleet:
    """Router join/retire primitives: live scale-up, zero-loss scale-down
    with proactive token-exact migration, injected join failures."""

    KW = dict(num_blocks=32, block_size=4, max_batch_size=4, max_seq_len=32)

    def _sup(self, tiny_lm, **ekw):
        model, params = tiny_lm
        kw = dict(self.KW)
        kw.update(ekw)
        return EngineSupervisor(InferenceEngine(model, params, **kw),
                                restart_backoff_s=0.0)

    def _router(self, tiny_lm, n=2, *, faults=None):
        sups = [self._sup(tiny_lm) for _ in range(n)]
        events = []
        router = Router(sups, event_sink=events.append, seed=0,
                        faults=faults)
        return router, sups, events

    @pytest.mark.slow
    def test_add_replica_joins_and_serves(self, tiny_lm):
        model, params = tiny_lm
        router, sups, events = self._router(tiny_lm, n=1)
        rng = np.random.default_rng(30)
        prompts = [rng.integers(0, 128, n).astype(np.int32)
                   for n in (5, 6, 7, 8)]
        refs = [_greedy_ref(model, params, p, 5,
                            sups[0].engine.assembly_len) for p in prompts]
        gids = [router.submit(p, 5) for p in prompts[:2]]
        router.pump(2)
        idx = router.add_replica(lambda: self._sup(tiny_lm))
        assert idx == 1 and router.num_active_replicas() == 2
        gids += [router.submit(p, 5) for p in prompts[2:]]
        # join-shortest-queue places new work on the (empty) joiner
        assert len(router.replicas[1].live) > 0
        router.run_sync()
        term = {e["id"]: e for e in events if e["event"] != "token"}
        for gid, ref in zip(gids, refs):
            assert term[gid]["event"] == "done"
            assert term[gid]["tokens"] == ref
        assert len(router.stats()["replicas"]) == 2
        for h in router.replicas:
            assert h.sup.engine.pool.num_allocated == 0

    def test_join_fail_raises_and_leaves_fleet_intact(self, tiny_lm):
        plan = FaultPlan(scale_join_fail_calls=(1,))
        router, sups, events = self._router(tiny_lm, n=1, faults=plan)
        built = []
        with pytest.raises(ConnectionError):
            router.add_replica(lambda: built.append(1) or
                               self._sup(tiny_lm))
        assert built == [], "join fault fired AFTER the factory ran"
        assert router.num_active_replicas() == 1
        assert plan.fired["scale.join_fail"] == 1
        # the next attempt (site passes) succeeds
        assert router.add_replica(lambda: self._sup(tiny_lm)) == 1
        assert router.num_active_replicas() == 2

    @pytest.mark.slow
    def test_retire_migrates_live_streams_token_exact(self, tiny_lm):
        """The zero-loss scale-down gate: a replica with streams
        mid-decode retires; every stream finishes token-exact with
        exactly one terminal event, nothing is dropped, and the retired
        replica takes no further placements."""
        model, params = tiny_lm
        router, sups, events = self._router(tiny_lm, n=2)
        rng = np.random.default_rng(31)
        prompts = [rng.integers(0, 128, n).astype(np.int32)
                   for n in (5, 6, 7, 8)]
        refs = [_greedy_ref(model, params, p, 8,
                            sups[0].engine.assembly_len) for p in prompts]
        gids = [router.submit(p, 8) for p in prompts]
        router.pump(3)                   # streams genuinely mid-flight
        victim = max(router.replicas, key=lambda h: len(h.live)).idx
        assert len(router.replicas[victim].live) > 0
        assert router.retire_replica(victim)
        assert router.num_active_replicas() == 1
        # retired replicas take no new placements
        extra = router.submit(prompts[0], 8)
        assert extra not in router.replicas[victim].live
        router.run_sync()
        term = {}
        for e in events:
            if e["event"] != "token":
                term.setdefault(e["id"], []).append(e)
        assert sorted(term) == sorted(gids + [extra])
        assert all(len(v) == 1 for v in term.values()), \
            "a migrated stream double-terminated"
        for gid, ref in zip(gids, refs):
            assert term[gid][0]["event"] == "done"
            assert term[gid][0]["tokens"] == ref
            streamed = [e["token"] for e in events
                        if e["event"] == "token" and e["id"] == gid]
            assert streamed == ref
        assert router.metrics.proactive_migrations > 0
        assert router.stats()["replicas"][victim]["retired"]
        for h in router.replicas:
            assert h.sup.engine.pool.num_allocated == 0
            h.sup.engine.check_invariants()

    def test_retire_refuses_last_replica(self, tiny_lm):
        router, sups, events = self._router(tiny_lm, n=2)
        assert router.retire_replica(0)
        assert not router.retire_replica(1), \
            "retired the last replica standing"
        assert not router.retire_replica(0)   # already retired: False
        assert router.num_active_replicas() == 1
        router.run_sync()


class _StubRouter:
    """Duck-typed router for deterministic Autoscaler control-law tests:
    load and TTFT are set directly, actions mutate counters."""

    def __init__(self, active=1, open_requests=0):
        self.active = active
        self.open_requests = open_requests
        self.draining = False
        self.finished = False
        self.p95 = None
        self.adds = 0
        self.retires = 0
        self.fail_joins = 0

    def num_active_replicas(self):
        return self.active

    def ttft_quantile(self, q):
        return self.p95

    def add_replica(self, factory):
        if self.fail_joins > 0:
            self.fail_joins -= 1
            raise ConnectionError("injected join failure")
        factory()
        self.active += 1
        self.adds += 1
        return self.active - 1

    def retire_replica(self, idx, reason="scale-down"):
        if self.active <= 1:
            return False
        self.active -= 1
        self.retires += 1
        return True

    def replica_load(self):
        return {i: i for i in range(self.active)}


class TestAutoscaler:
    """Control-law unit tests on the stub router with an injected clock:
    thresholds, hysteresis, cooldown, bounds, bounded join retry."""

    def _scaler(self, router, **kw):
        merged = dict(min_replicas=1, max_replicas=4, up_load=4.0,
                      down_load=1.0, hysteresis_s=1.0, cooldown_s=2.0,
                      join_retries=2)
        merged.update(kw)
        return Autoscaler(router, lambda: object(), **merged)

    def test_validation(self):
        r = _StubRouter()
        with pytest.raises(ValueError, match="min_replicas"):
            self._scaler(r, min_replicas=0)
        with pytest.raises(ValueError, match="max_replicas"):
            self._scaler(r, min_replicas=3, max_replicas=2)
        with pytest.raises(ValueError, match="dead band"):
            self._scaler(r, up_load=1.0, down_load=1.0)
        with pytest.raises(ValueError, match="slo_ttft_s"):
            self._scaler(r, slo_ttft_s=0.0)
        with pytest.raises(ValueError, match="join_retries"):
            self._scaler(r, join_retries=-1)
        with pytest.raises(ValueError, match="interval_s"):
            self._scaler(r, interval_s=0.0)

    def test_scale_up_on_load_and_cooldown_locks(self):
        r = _StubRouter(active=1, open_requests=10)   # load 10 > 4
        s = self._scaler(r, cooldown_s=2.0)
        assert s.tick(now=0.0) == "up" and r.active == 2
        r.open_requests = 20                          # still way over
        assert s.tick(now=1.0) is None, "cooldown did not lock scale-up"
        assert s.tick(now=2.5) == "up" and r.active == 3
        assert s.stats()["scale_ups"] == 2

    def test_max_replicas_bounds_scale_up(self):
        r = _StubRouter(active=2, open_requests=100)
        s = self._scaler(r, max_replicas=2)
        assert s.tick(now=0.0) is None
        assert r.adds == 0

    def test_hysteresis_requires_sustained_low(self):
        r = _StubRouter(active=3, open_requests=0)    # load 0 < 1
        s = self._scaler(r, hysteresis_s=1.0, cooldown_s=0.0)
        assert s.tick(now=0.0) is None                # starts the timer
        assert s.tick(now=0.9) is None                # not sustained yet
        assert s.tick(now=1.0) == "down" and r.active == 2
        assert s.stats()["scale_downs"] == 1

    def test_dead_band_resets_hysteresis_timer(self):
        r = _StubRouter(active=3, open_requests=0)
        s = self._scaler(r, hysteresis_s=1.0, cooldown_s=0.0)
        assert s.tick(now=0.0) is None                # low: timer starts
        r.open_requests = 6                           # load 2: dead band
        assert s.tick(now=0.5) is None                # timer must reset
        r.open_requests = 0
        assert s.tick(now=1.1) is None, \
            "a dead-band excursion did not reset the hysteresis timer"
        assert s.tick(now=2.1) == "down"

    def test_high_load_resets_hysteresis_timer(self):
        r = _StubRouter(active=3, open_requests=0)
        s = self._scaler(r, hysteresis_s=1.0, cooldown_s=0.0,
                         max_replicas=3)
        assert s.tick(now=0.0) is None
        r.open_requests = 30                          # spike: load 10
        assert s.tick(now=0.5) is None                # at max: no up
        r.open_requests = 0
        assert s.tick(now=1.1) is None, \
            "a load spike did not reset the hysteresis timer"

    def test_min_replicas_bounds_scale_down(self):
        r = _StubRouter(active=1, open_requests=0)
        s = self._scaler(r, hysteresis_s=0.0, cooldown_s=0.0)
        assert s.tick(now=0.0) is None
        assert s.tick(now=10.0) is None
        assert r.retires == 0

    def test_slo_breach_scales_up_at_moderate_load(self):
        r = _StubRouter(active=1, open_requests=2)    # load 2: dead band
        r.p95 = 0.5
        s = self._scaler(r, slo_ttft_s=0.25)
        assert s.tick(now=0.0) == "up", \
            "a TTFT SLO breach must scale up even inside the load band"
        r.p95 = 0.1
        r.open_requests = 2
        assert s.tick(now=10.0) is None               # SLO healthy again

    def test_join_retry_is_bounded(self):
        r = _StubRouter(active=1, open_requests=10)
        r.fail_joins = 10
        s = self._scaler(r, join_retries=2, cooldown_s=0.0)
        assert s.tick(now=0.0) is None
        assert s.stats()["join_failures"] == 3        # 1 try + 2 retries
        assert r.fail_joins == 7, "retry loop was not bounded"
        assert r.adds == 0
        # a failed scale-up must NOT start the cooldown: the next tick
        # (faults cleared) succeeds immediately
        r.fail_joins = 0
        assert s.tick(now=0.0) == "up"

    def test_draining_router_is_left_alone(self):
        r = _StubRouter(active=1, open_requests=100)
        r.draining = True
        s = self._scaler(r)
        assert s.tick(now=0.0) is None and r.adds == 0

    def test_collapsed_fleet_is_left_alone(self):
        r = _StubRouter(active=0, open_requests=5)
        s = self._scaler(r)
        assert s.tick(now=0.0) is None

    def test_victim_is_least_loaded(self):
        seen = []
        r = _StubRouter(active=3, open_requests=0)
        r.retire_replica = lambda idx, reason="scale-down": \
            seen.append(idx) or True
        s = self._scaler(r, hysteresis_s=0.0, cooldown_s=0.0)
        assert s.tick(now=0.0) == "down"
        assert seen == [0], "did not pick the least-loaded replica"

    def test_thread_driver_start_stop(self, tiny_lm):
        r = _StubRouter(active=1, open_requests=0)
        s = self._scaler(r, interval_s=0.01)
        assert s.start() is s
        with pytest.raises(RuntimeError, match="already started"):
            s.start()
        deadline = time.monotonic() + 2.0
        while s.ticks == 0 and time.monotonic() < deadline:
            time.sleep(0.01)
        s.stop()
        assert s.ticks > 0
        s.stop()                       # idempotent


@pytest.mark.slow
def test_spike_soak_elastic_fleet(tiny_lm):
    """The elastic-fleet soak gate: a Poisson burst over a 1-replica
    fleet with an autoscaler (deterministic injected clock), tier demote
    faults inside every replica, and one replica hard-killed mid-scale-up.
    Asserts the full contract: exactly one terminal event per admitted
    request, finished streams token-exact against the fault-free
    reference, the scaler actually grew the fleet, and zero leaked blocks
    in every surviving device pool AND every host tier."""
    model, params = tiny_lm
    rng = np.random.default_rng(40)
    uniq = [rng.integers(0, 128, int(n)).astype(np.int32)
            for n in rng.integers(4, 14, 8)]
    max_new = 6
    built = []

    def make_sup(i):
        plan = FaultPlan(seed=200 + i, tier_demote_fail_prob=0.1,
                         tier_corrupt_prob=0.1)
        eng = InferenceEngine(model, params, num_blocks=16, block_size=4,
                              max_batch_size=4, max_seq_len=32,
                              max_queue_depth=24, chunk_size=8,
                              host_tier_bytes=1 << 20, faults=plan)
        sup = EngineSupervisor(eng, restart_backoff_s=0.0, max_restarts=5)
        built.append(sup)
        return sup

    refs = {}
    probe = InferenceEngine(model, params, num_blocks=16, block_size=4,
                            max_batch_size=4, max_seq_len=32)
    for i, p in enumerate(uniq):
        refs[i] = _greedy_ref(model, params, p, max_new,
                              probe.assembly_len)
    events = []
    router = Router([make_sup(0)], event_sink=events.append, seed=4)
    scaler = Autoscaler(router, lambda: make_sup(len(built)),
                        min_replicas=1, max_replicas=3,
                        up_load=2.0, down_load=0.5,
                        hysteresis_s=0.3, cooldown_s=0.1, join_retries=2)
    n_requests, rejected, submitted = 90, 0, {}
    victim = None
    clock = 0.0
    for i in range(n_requests):
        # Poisson arrivals: burst in the middle third, trickle elsewhere
        lam = 3.0 if n_requests // 3 <= i < 2 * n_requests // 3 else 0.5
        for _ in range(max(1, int(rng.poisson(lam)))):
            which = int(rng.integers(0, len(uniq)))
            try:
                gid = router.submit(uniq[which], max_new, priority=i % 3)
                submitted[gid] = which
            except (AdmissionRejected, ShuttingDown, ConnectionError):
                rejected += 1
        router.pump(1)
        clock += 0.05
        scaler.tick(now=clock)
        # hard-kill a grown replica mid-run, once, while work is live
        if victim is None and scaler.ups > 0 and i > n_requests // 2:
            alive = [h for h in router.replicas
                     if not h.killed and not h.retired]
            if len(alive) > 1:
                victim = max(alive, key=lambda h: len(h.live)).idx
                router.kill_replica(victim)
    router.run_sync()
    router.request_drain("soak complete")
    router.run_sync()

    assert scaler.ups >= 1, "the burst never scaled the fleet up"
    assert victim is not None, "no grown replica was ever killed"
    assert router.state is SupervisorState.STOPPED
    assert router.exit_code == 0
    # exactly one terminal event per admitted request
    terminals = [e for e in events if e["event"] != "token"]
    per_gid = {}
    for e in terminals:
        per_gid[e["id"]] = per_gid.get(e["id"], 0) + 1
    assert sorted(per_gid) == sorted(submitted)
    assert all(c == 1 for c in per_gid.values()), per_gid
    # finished streams token-exact against the fault-free reference
    finished = [e for e in terminals if e["event"] == "done"]
    assert finished, "spike soak finished nothing"
    for e in finished:
        assert e["tokens"] == refs[submitted[e["id"]]], \
            f"gid {e['id']} diverged from fault-free reference"
    # tier demote faults genuinely exercised the degradation paths
    fired_demote = sum(s.engine.faults.fired["tier.demote_fail"]
                      for s in built)
    assert fired_demote > 0 or sum(
        s.engine.stats()["tier_demotions"] for s in built) > 0
    # zero leaks: every surviving device pool empty, every tier's byte
    # accounting exact and within bound (the killed replica's pool was
    # torn down with it)
    for h in router.replicas:
        if h.idx != victim:
            assert h.sup.engine.pool.num_allocated == 0
            h.sup.engine.check_invariants()   # includes the tier's
        if h.sup.engine.kv_tier is not None:
            h.sup.engine.kv_tier.check_invariants()


# -- disaggregated prefill/decode serving -------------------------------------


class TestDisagg:
    """Prefill/decode disaggregation through the deterministic sync
    harness: role placement, the first-token boundary handoff (real KV
    wire transfer and the recompute-resume baseline), chaos degradation
    (corrupt/slow wire blocks, a receiver dying mid-adopt), and the
    fleet-wide shared prefix cache."""

    KW = dict(num_blocks=64, block_size=4, max_batch_size=4, max_seq_len=64,
              chunk_size=8, chunked_prefill=True, prefix_cache=True,
              decode_path="paged")
    THRESH = 16

    def _fleet(self, tiny_lm, n=2, *, plans=None, router_kw=None,
               engine_kw=None, sup_kw=None):
        model, params = tiny_lm
        ekw = dict(self.KW)
        ekw.update(engine_kw or {})
        skw = dict(restart_backoff_s=0.0)
        skw.update(sup_kw or {})
        plans = plans or [None] * n
        sups = [EngineSupervisor(
                    InferenceEngine(model, params, faults=plans[i], **ekw),
                    **skw)
                for i in range(n)]
        rkw = dict(roles=["prefill"] + ["decode"] * (n - 1),
                   disagg_prompt_threshold=self.THRESH)
        rkw.update(router_kw or {})
        events = []
        router = Router(sups, event_sink=events.append, seed=0, **rkw)
        return router, sups, events

    def _long(self, rng, max_new=6):
        return rng.integers(0, 128, self.THRESH + 8).astype(np.int32), max_new

    @staticmethod
    def _terminals(events):
        return [e for e in events if e["event"] != "token"]

    def _no_leaks(self, router, skip=()):
        for h in router.replicas:
            if h.idx in skip:
                continue
            assert h.sup.engine.pool.num_allocated == 0
            h.sup.engine.check_invariants()

    def test_roles_validation(self, tiny_lm):
        model, params = tiny_lm
        sups = [EngineSupervisor(InferenceEngine(model, params, **self.KW),
                                 restart_backoff_s=0.0) for _ in range(2)]
        with pytest.raises(ValueError, match="every replica"):
            Router(sups, roles=["prefill"])
        with pytest.raises(ValueError, match="unknown replica role"):
            Router(sups, roles=["prefill", "gpu"])
        with pytest.raises(ValueError, match="at least one decode"):
            Router(sups, roles=["prefill", "prefill"])

    @pytest.mark.parametrize("path", ["standard", "paged"])
    @pytest.mark.parametrize("kv", [True, False])
    def test_boundary_handoff_token_exact(self, tiny_lm, path, kv):
        """The tentpole, both decode paths: a long prompt lands on the
        prefill replica, crosses to the decode replica at the first-token
        boundary (KV wire transfer or recompute-resume), and the client
        sees one uninterrupted token-exact stream."""
        model, params = tiny_lm
        router, sups, events = self._fleet(
            tiny_lm, router_kw=dict(handoff_kv=kv),
            engine_kw=dict(decode_path=path))
        rng = np.random.default_rng(11)
        lp, ln = self._long(rng)
        sp = rng.integers(0, 128, 6).astype(np.int32)
        alen = sups[0].engine.assembly_len
        refs = [_greedy_ref(model, params, lp, ln, alen),
                _greedy_ref(model, params, sp, 5, alen)]
        glong = router.submit(lp, ln)
        gshort = router.submit(sp, 5)
        # role placement: the long prompt prefers the prefill replica,
        # the short one the decode replica
        assert glong in router.replicas[0].live
        assert gshort in router.replicas[1].live
        router.run_sync()
        term = {e["id"]: e for e in self._terminals(events)}
        assert term[glong]["event"] == "done"
        assert term[glong]["tokens"] == refs[0]
        assert term[gshort]["tokens"] == refs[1]
        streamed = [e["token"] for e in events
                    if e["event"] == "token" and e["id"] == glong]
        assert streamed == refs[0]     # no token duplicated or dropped
        st = router.stats()
        assert st["boundary_handoffs"] == 1
        recv = sups[1].engine.metrics.summary()
        if kv:
            assert st["handoff_fallbacks"] == 0
            assert recv["handoff_adopted_blocks"] > 0
            # the resume prefill hit the adopted blocks instead of
            # recomputing them
            assert recv["prefill_tokens_saved"] > 0
        else:
            assert recv["handoff_adopted_blocks"] == 0
        self._no_leaks(router)

    def test_boundary_handoff_overlap_single_chunk_ships_kv(self, tiny_lm):
        """Overlap defers prefix publishes to idle time; a single-chunk
        long prompt commits its whole chain AND its first token in the
        same tick, so the boundary export races the deferred publish and
        (before the fix) found nothing resident — every handoff silently
        degraded to recompute-resume. export_prefix now drains the
        deferred queue first; the wire must actually ship."""
        model, params = tiny_lm
        router, sups, events = self._fleet(
            tiny_lm, router_kw=dict(handoff_kv=True),
            engine_kw=dict(overlap=True, chunk_size=64))
        rng = np.random.default_rng(23)
        lp, ln = self._long(rng)
        alen = sups[0].engine.assembly_len
        ref = _greedy_ref(model, params, lp, ln, alen)
        g = router.submit(lp, ln)
        router.run_sync()
        term = {e["id"]: e for e in self._terminals(events)}
        assert term[g]["tokens"] == ref
        st = router.stats()
        assert st["boundary_handoffs"] == 1
        assert st["handoff_fallbacks"] == 0, \
            "single-chunk overlap handoff degraded: export raced the " \
            "deferred publish"
        recv = sups[1].engine.metrics.summary()
        # the FULL chain crossed: every complete prompt block adopted
        assert recv["handoff_adopted_blocks"] == len(lp) // 4
        assert recv["prefill_tokens_saved"] > 0
        self._no_leaks(router)

    def test_corrupt_wire_block_degrades_to_recompute(self, tiny_lm):
        """handoff.corrupt chaos: the receiver's digest check catches the
        damage, adopts nothing, and the handoff falls back to token-exact
        recompute-resume — never a wrong token."""
        model, params = tiny_lm
        plans = [None, FaultPlan(handoff_corrupt_calls=(1,))]
        router, sups, events = self._fleet(tiny_lm, plans=plans)
        rng = np.random.default_rng(12)
        lp, ln = self._long(rng)
        ref = _greedy_ref(model, params, lp, ln, sups[0].engine.assembly_len)
        gid = router.submit(lp, ln)
        router.run_sync()
        term = {e["id"]: e for e in self._terminals(events)}
        assert term[gid]["event"] == "done" and term[gid]["tokens"] == ref
        st = router.stats()
        assert st["boundary_handoffs"] == 1
        assert st["handoff_fallbacks"] == 1
        recv = sups[1].engine.metrics.summary()
        assert recv["handoff_corrupt"] == 1
        assert recv["handoff_adopted_blocks"] == 0
        self._no_leaks(router)

    def test_slow_wire_adopt_is_late_not_wrong(self, tiny_lm):
        """handoff.slow chaos: a congested transfer stalls the adopt but
        does not fail it — the blocks still land, verified."""
        model, params = tiny_lm
        plans = [None, FaultPlan(handoff_slow_calls=(1,),
                                 handoff_slow_s=0.005)]
        router, sups, events = self._fleet(tiny_lm, plans=plans)
        rng = np.random.default_rng(13)
        lp, ln = self._long(rng)
        ref = _greedy_ref(model, params, lp, ln, sups[0].engine.assembly_len)
        gid = router.submit(lp, ln)
        router.run_sync()
        term = {e["id"]: e for e in self._terminals(events)}
        assert term[gid]["event"] == "done" and term[gid]["tokens"] == ref
        st = router.stats()
        assert st["boundary_handoffs"] == 1
        assert st["handoff_fallbacks"] == 0
        assert sups[1].engine.faults.fired["handoff.slow"] == 1
        assert sups[1].engine.metrics.summary()["handoff_adopted_blocks"] > 0
        self._no_leaks(router)

    def test_receiver_pool_pressure_degrades(self, tiny_lm):
        """A full receiver pool ends the adopt walk early (here: at zero
        blocks, via an injected alloc failure) — handoff still happens,
        as recompute-resume."""
        model, params = tiny_lm
        plans = [None, FaultPlan(alloc_fail_calls=(1,))]
        router, sups, events = self._fleet(tiny_lm, plans=plans)
        rng = np.random.default_rng(14)
        lp, ln = self._long(rng)
        ref = _greedy_ref(model, params, lp, ln, sups[0].engine.assembly_len)
        gid = router.submit(lp, ln)
        router.run_sync()
        term = {e["id"]: e for e in self._terminals(events)}
        assert term[gid]["event"] == "done" and term[gid]["tokens"] == ref
        st = router.stats()
        assert st["boundary_handoffs"] == 1
        assert st["handoff_fallbacks"] == 1
        assert sups[1].engine.metrics.summary()[
            "handoff_adopted_blocks"] == 0
        self._no_leaks(router)

    def test_no_decode_target_finishes_in_place(self, tiny_lm):
        """Roles are preferences, never admission gates: with every decode
        replica dead, the long prompt finishes on the prefill replica."""
        model, params = tiny_lm
        router, sups, events = self._fleet(tiny_lm)
        router.kill_replica(1)
        rng = np.random.default_rng(15)
        lp, ln = self._long(rng)
        ref = _greedy_ref(model, params, lp, ln, sups[0].engine.assembly_len)
        gid = router.submit(lp, ln)
        router.run_sync()
        term = {e["id"]: e for e in self._terminals(events)}
        assert term[gid]["event"] == "done" and term[gid]["tokens"] == ref
        st = router.stats()
        assert st["boundary_handoffs"] == 0
        assert st["handoff_fallbacks"] == 0
        self._no_leaks(router, skip=(1,))

    def test_receiver_killed_mid_adopt_degrades(self, tiny_lm, monkeypatch):
        """The receiver dies DURING the adopt call: the handoff degrades
        to recompute-resume on a surviving replica — never a dropped
        request."""
        model, params = tiny_lm
        router, sups, events = self._fleet(tiny_lm)
        rng = np.random.default_rng(16)
        lp, ln = self._long(rng)
        ref = _greedy_ref(model, params, lp, ln, sups[0].engine.assembly_len)

        def dying_adopt(exports):
            router.kill_replica(1)
            raise EngineCrash("receiver died mid-adopt")

        monkeypatch.setattr(sups[1], "adopt_prefix", dying_adopt)
        gid = router.submit(lp, ln)
        router.run_sync()
        term = {e["id"]: e for e in self._terminals(events)}
        assert term[gid]["event"] == "done" and term[gid]["tokens"] == ref
        st = router.stats()
        assert st["boundary_handoffs"] == 1
        assert st["handoff_fallbacks"] == 1
        self._no_leaks(router, skip=(1,))

    def test_fleet_prefix_pull_then_local_hit(self, tiny_lm):
        """Fleet-wide shared prefix cache: a prefix published on the
        prefill replica is pulled over on a miss from the decode replica
        (verified wire path), after which the same prefix hits locally —
        no second pull."""
        model, params = tiny_lm
        router, sups, events = self._fleet(
            tiny_lm, router_kw=dict(disagg_prompt_threshold=12,
                                    fleet_prefix=True))
        rng = np.random.default_rng(17)
        prefix = rng.integers(0, 128, 8).astype(np.int32)
        seeder = np.concatenate(
            [prefix, rng.integers(0, 128, 4).astype(np.int32)])
        shorts = [np.concatenate(
            [prefix, rng.integers(0, 128, 3).astype(np.int32)])
            for _ in range(2)]
        alen = sups[0].engine.assembly_len
        g1 = router.submit(seeder, 1)   # 12 tokens -> the prefill replica
        assert g1 in router.replicas[0].live
        router.run_sync()
        router._refresh_prefix_dir()
        g2 = router.submit(shorts[0], 4)  # 11 tokens -> the decode replica
        assert g2 in router.replicas[1].live
        router.run_sync()
        st = router.stats()
        assert st["fleet_prefix_pulls"] == 1
        recv = sups[1].engine.metrics.summary()
        assert recv["prefill_tokens_saved"] >= 8   # two pulled blocks
        # the adopted keys are now local: same prefix, no second pull
        router._refresh_prefix_dir()
        g3 = router.submit(shorts[1], 4)
        router.run_sync()
        assert router.stats()["fleet_prefix_pulls"] == 1
        term = {e["id"]: e for e in self._terminals(events)}
        assert term[g1]["tokens"] == _greedy_ref(model, params, seeder,
                                                 1, alen)
        for g, p in ((g2, shorts[0]), (g3, shorts[1])):
            assert term[g]["event"] == "done"
            assert term[g]["tokens"] == _greedy_ref(model, params, p,
                                                    4, alen)
        self._no_leaks(router)

    def test_auto_roles_assignment(self, tiny_lm):
        """roles="auto": the probe loop dedicates the healthiest half to
        decode and the rest to prefill; a fleet shrunk to one alive
        replica reverts to mixed."""
        router, sups, _ = self._fleet(tiny_lm, n=3,
                                      router_kw=dict(roles="auto"))
        assert [h.role for h in router.replicas] == ["mixed"] * 3
        router._probe()
        roles = [h.role for h in router.replicas]
        assert roles.count("decode") == 2 and roles.count("prefill") == 1
        router.kill_replica(1)
        router.kill_replica(2)
        router._probe()
        assert router.replicas[0].role == "mixed"

    def test_role_singleton_never_ejected(self, tiny_lm):
        """Role-aware ejection: the lone prefill replica is structurally
        slower than its decode peers (it eats every long prompt) — judged
        only against same-role peers, a singleton is never ejected for
        doing its job."""
        router, sups, _ = self._fleet(tiny_lm, n=3)
        # plant a fleet-median-breaking score on the prefill replica: under
        # the old fleet-wide median this ejects; role-aware it must not
        router.replicas[0].health.dispatch_latency_s = 10.0
        for _ in range(3):
            router._update_health()
            time.sleep(0.01)
        assert not router.replicas[0].degraded
        assert router.stats()["degraded_ejections"] == 0
        # ... and an ejection stranded in a group of one heals: plant the
        # degraded state a pre-role-aware run could have left behind
        router.replicas[0].degraded = True
        router._update_health()
        assert not router.replicas[0].degraded

    def test_handoff_pending_requests_are_never_hedged(self, tiny_lm):
        """Handoff-aware hedging: a long prompt mid-prefill on the prefill
        tier is slow BY SELECTION — the boundary handoff is already its
        migration, so the hedge scan must skip it."""
        router, sups, _ = self._fleet(
            tiny_lm, router_kw=dict(hedge_ttft_s=0.0, hedge_budget=1.0))
        rng = np.random.default_rng(18)
        lp, ln = self._long(rng)
        gid = router.submit(lp, ln)
        rec = router._open[gid]
        assert rec.prefer_role == "prefill"
        # every request is overdue at threshold 0.0 — yet the pending
        # handoff must be exempt
        router._maybe_hedge()
        assert router.stats()["hedges_fired"] == 0
        assert rec.hedge_epoch is None
        router.run_sync()
        assert router.stats()["boundary_handoffs"] == 1
        self._no_leaks(router)

    @pytest.mark.parametrize("path", ["standard", "paged"])
    def test_disagg_composed_chaos_token_exact(self, tiny_lm, path):
        """The PR gate, both decode paths: disagg-on vs disagg-off with
        prefix cache + ngram spec + overlap + int8 KV composed, under
        handoff chaos (seeded corrupt + slow wire blocks, one decode
        replica killed mid-run) — every stream token-exact against the
        greedy reference, zero leaked blocks on the survivors."""
        model, params = tiny_lm
        ekw = dict(decode_path=path, kv_dtype="int8", spec="ngram",
                   spec_k=3, overlap=True)
        rng = np.random.default_rng(19)
        prefix = rng.integers(0, 128, 8).astype(np.int32)
        prompts = [rng.integers(0, 128, self.THRESH + 4 + i).astype(np.int32)
                   for i in range(4)]
        prompts += [np.concatenate(
            [prefix, rng.integers(0, 128, 3 + i).astype(np.int32)])
            for i in range(4)]
        max_new = 6

        def run(disagg):
            plans = [None,
                     FaultPlan(seed=3, handoff_corrupt_prob=0.4,
                               handoff_slow_prob=0.4, handoff_slow_s=0.001),
                     FaultPlan(seed=4, handoff_corrupt_prob=0.4,
                               handoff_slow_prob=0.4, handoff_slow_s=0.001)]
            rkw = (dict(roles=["prefill", "decode", "decode"],
                        disagg_prompt_threshold=self.THRESH,
                        handoff_kv=True, fleet_prefix=True)
                   if disagg else dict(roles=None,
                                       disagg_prompt_threshold=0))
            router, sups, events = self._fleet(
                tiny_lm, n=3, plans=plans, router_kw=rkw, engine_kw=ekw)
            gids = [router.submit(p, max_new) for p in prompts]
            router.pump(2)
            router.kill_replica(2)       # a receiver dies mid-fleet
            router.run_sync()
            term = {e["id"]: e for e in self._terminals(events)}
            toks = []
            for g in gids:
                assert term[g]["event"] == "done"
                toks.append(term[g]["tokens"])
            self._no_leaks(router, skip=(2,))
            return toks, router.stats(), sups[0].engine.assembly_len

        on_toks, on_st, _ = run(True)
        off_toks, off_st, _ = run(False)
        # the disagg contract is on == off: crossing the prefill/decode
        # boundary (with chaos-degraded KV handoffs in the mix) must not
        # change a single token relative to the same engines un-split.
        # The f32 greedy reference is NOT the baseline here — int8 KV is
        # argmax-sensitive on some prompts, identically on both sides,
        # and that quantization contract is tested elsewhere.
        assert on_toks == off_toks, \
            "disagg-on diverged from the disagg-off twin"
        assert on_st["boundary_handoffs"] >= 1
        assert off_st["boundary_handoffs"] == 0


class TestPrefixCacheAdoptEdges:
    """PrefixCache.adopt (the wire/tier re-admission entry) against the
    races the engine sees in production: duplicate adoption of one chain
    key, and a block reclaimed out from under a just-adopted entry."""

    def test_duplicate_adopt_same_key_loses(self):
        pc = PrefixCache(block_size=4)
        assert pc.adopt(b"k1", 3)
        assert not pc.adopt(b"k1", 9)      # occupied key: first wins
        assert not pc.adopt(b"k2", 3)      # block already serves a chain
        assert pc.block_of(b"k1") == 3 and pc.block_of(b"k2") is None
        assert len(pc) == 1

    def test_adopt_after_concurrent_reclaim(self):
        pc = PrefixCache(block_size=4)
        assert pc.adopt(b"k1", 3)
        pc.drop_blocks([3])                # the pool reclaimed it mid-race
        assert pc.block_of(b"k1") is None and len(pc) == 0
        assert not pc.contains_block(3)
        # the key is free again: a later adopt re-admits under a new block
        assert pc.adopt(b"k1", 7)
        assert pc.block_of(b"k1") == 7


class TestHostTierTPExclusion:
    """The host-RAM KV tier is incompatible with tensor-parallel pool
    sharding (demoted page slices would need a cross-shard gather/
    scatter): both the engine constructor and the CLI must fail fast with
    a clear one-line error, not crash somewhere in kernel wiring."""

    def test_engine_rejects_tier_with_tp(self, tiny_lm):
        model, params = tiny_lm
        with pytest.raises(ValueError, match="tp>1 is unsupported"):
            InferenceEngine(model, params, num_blocks=8, block_size=4,
                            max_batch_size=2, max_seq_len=16,
                            chunked_prefill=True, prefix_cache=True,
                            host_tier_bytes=1 << 20, tp=2)

    def test_cli_rejects_tier_with_tp(self, capsys):
        from tnn_tpu.cli import serve as serve_cli
        with pytest.raises(SystemExit):
            serve_cli.main(["--host-tier-bytes", "1048576", "--tp", "2"])
        err = capsys.readouterr().err
        assert "--host-tier-bytes is incompatible with --tp" in err
