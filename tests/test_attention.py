"""Attention tests — numeric reference checks (parity intent: attention_block_test.cpp)
plus pallas-vs-xla differential testing (the reference's CPU-vs-GPU pattern)."""
import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tnn_tpu import nn
from tnn_tpu.core import dtypes as dt
from tnn_tpu.nn.attention import sdpa

F32 = dt.FP32


def _ref_attention(q, k, v, causal=False):
    """NumPy reference."""
    b, h, s, d = q.shape
    skv = k.shape[2]
    logits = np.einsum("bhqd,bhkd->bhqk", q, k) / math.sqrt(d)
    if causal:
        mask = np.tril(np.ones((s, skv), bool), k=skv - s)
        logits = np.where(mask, logits, -1e9)
    e = np.exp(logits - logits.max(-1, keepdims=True))
    p = e / e.sum(-1, keepdims=True)
    return np.einsum("bhqk,bhkd->bhqd", p, v)


@pytest.mark.parametrize("causal", [False, True])
def test_sdpa_matches_numpy(causal):
    rs = np.random.RandomState(0)
    q = rs.randn(2, 3, 16, 8).astype(np.float32)
    k = rs.randn(2, 3, 16, 8).astype(np.float32)
    v = rs.randn(2, 3, 16, 8).astype(np.float32)
    out = sdpa(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal=causal)
    np.testing.assert_allclose(np.asarray(out), _ref_attention(q, k, v, causal),
                               rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("seq", [128, 200])  # aligned and ragged
def test_flash_attention_matches_xla(causal, seq):
    """Differential: pallas blockwise kernel vs XLA path (reference pattern:
    benchmarks/gemm_benchmark.cpp check_match)."""
    rs = np.random.RandomState(1)
    shape = (1, 2, seq, 64)
    q = jnp.asarray(rs.randn(*shape), jnp.float32)
    k = jnp.asarray(rs.randn(*shape), jnp.float32)
    v = jnp.asarray(rs.randn(*shape), jnp.float32)
    ref = sdpa(q, k, v, causal=causal, backend="xla")
    out = sdpa(q, k, v, causal=causal, backend="pallas")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-3, atol=2e-3)


def test_flash_attention_grads_match_xla():
    rs = np.random.RandomState(2)
    shape = (1, 2, 128, 32)
    q = jnp.asarray(rs.randn(*shape), jnp.float32)
    k = jnp.asarray(rs.randn(*shape), jnp.float32)
    v = jnp.asarray(rs.randn(*shape), jnp.float32)

    def loss_xla(q, k, v):
        return jnp.sum(sdpa(q, k, v, causal=True, backend="xla") ** 2)

    def loss_pallas(q, k, v):
        return jnp.sum(sdpa(q, k, v, causal=True, backend="pallas") ** 2)

    gx = jax.grad(loss_xla, argnums=(0, 1, 2))(q, k, v)
    gp = jax.grad(loss_pallas, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gx, gp):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-3)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("seq", [130, 256])  # 130 exercises q/k padding rows
def test_flash_backward_blockwise_matches_xla(causal, seq):
    """The Pallas dq/dk/dv kernels (multi-block path, block 128 over seq>128)
    vs XLA autodiff — covers causal block skipping and padded-row handling."""
    rs = np.random.RandomState(3)
    shape = (2, 2, seq, 64)
    q = jnp.asarray(rs.randn(*shape), jnp.float32)
    k = jnp.asarray(rs.randn(*shape), jnp.float32)
    v = jnp.asarray(rs.randn(*shape), jnp.float32)
    g = jnp.asarray(rs.randn(*shape), jnp.float32)

    from tnn_tpu.ops.pallas.flash_attention import flash_attention

    def loss_flash(q, k, v):
        return jnp.vdot(flash_attention(q, k, v, causal, None, 128, 128), g)

    def loss_xla(q, k, v):
        return jnp.vdot(sdpa(q, k, v, causal=causal, backend="xla"), g)

    gp = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gx = jax.grad(loss_xla, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("dq dk dv".split(), gp, gx):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=5e-3,
                                   atol=5e-3, err_msg=name)


def test_flash_backward_independent_geometry():
    """Backward block geometry independent of the forward's: fwd runs a single
    256-block while bwd runs 64-blocks over seq=200 — exercising the +inf
    re-padding of the unpadded lse residual (rows 200..255 must contribute
    p=0 to dK/dV, not NaN/garbage)."""
    rs = np.random.RandomState(7)
    shape = (1, 2, 200, 64)
    q = jnp.asarray(rs.randn(*shape), jnp.float32)
    k = jnp.asarray(rs.randn(*shape), jnp.float32)
    v = jnp.asarray(rs.randn(*shape), jnp.float32)
    g = jnp.asarray(rs.randn(*shape), jnp.float32)

    from tnn_tpu.ops.pallas.flash_attention import flash_attention

    def loss_flash(q, k, v):
        return jnp.vdot(
            flash_attention(q, k, v, True, None, 256, 256, 64, 64), g)

    def loss_xla(q, k, v):
        return jnp.vdot(sdpa(q, k, v, causal=True, backend="xla"), g)

    gp = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gx = jax.grad(loss_xla, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("dq dk dv".split(), gp, gx):
        assert np.all(np.isfinite(np.asarray(a))), name
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=5e-3,
                                   atol=5e-3, err_msg=name)


def test_flash_backward_memory_scales_with_blocks():
    """The backward must not materialize the (S, S) matrix: its jaxpr contains
    no S x S-shaped intermediate (the whole point vs the XLA recompute path)."""
    S = 512
    q = jnp.zeros((1, 1, S, 64), jnp.float32)

    from tnn_tpu.ops.pallas.flash_attention import flash_attention

    # block 128 forces the MULTI-block path (4x4 grid): any full-sequence
    # materialization would show up as an (S, S) intermediate in the jaxpr
    jaxpr = jax.make_jaxpr(
        jax.grad(lambda q, k, v: flash_attention(q, k, v, True, None,
                                                 128, 128).sum(),
                 argnums=(0, 1, 2)))(q, q, q)
    shapes = [v.aval.shape for eqn in jaxpr.eqns for v in eqn.outvars
              if hasattr(v.aval, "shape")]
    assert not any(s.count(S) >= 2 for s in shapes), (
        f"found S x S intermediate in backward: "
        f"{[s for s in shapes if s.count(S) >= 2]}")


def test_mha_shapes_and_causality(rng):
    mha = nn.MultiHeadAttention(num_heads=4, causal=True, policy=F32)
    v = mha.init(rng, (2, 10, 32))
    x = jnp.asarray(np.random.RandomState(3).randn(2, 10, 32), jnp.float32)
    y = mha(v, x)
    assert y.shape == (2, 10, 32)
    # causality: output at position t must not depend on inputs at positions > t
    x2 = x.at[:, 7:].set(0.0)
    y2 = mha(v, x2)
    np.testing.assert_allclose(np.asarray(y[:, :7]), np.asarray(y2[:, :7]),
                               rtol=1e-4, atol=1e-5)


def test_mha_cached_decode_matches_full(rng):
    """KV-cache decode must reproduce full-sequence forward exactly."""
    mha = nn.MultiHeadAttention(num_heads=2, causal=True, policy=F32)
    v = mha.init(rng, (1, 8, 16))
    x = jnp.asarray(np.random.RandomState(4).randn(1, 8, 16), jnp.float32)
    full = mha(v, x)
    cache = mha.init_cache(1, 8, 16)
    # prefill 5, then decode 3 one at a time
    out_pre, cache = mha.apply_cached(v, x[:, :5], cache, 0)
    outs = [out_pre]
    for t in range(5, 8):
        o, cache = mha.apply_cached(v, x[:, t:t + 1], cache, t)
        outs.append(o)
    stitched = jnp.concatenate(outs, axis=1)
    np.testing.assert_allclose(np.asarray(stitched), np.asarray(full),
                               rtol=1e-4, atol=1e-5)


def test_gpt_block_roundtrip_and_forward(rng):
    from tnn_tpu.core.module import module_from_config

    blk = nn.GPTBlock(num_heads=4, policy=F32)
    cfg = blk.get_config()
    assert module_from_config(cfg).get_config() == cfg
    v = blk.init(rng, (2, 6, 32))
    y = blk(v, jnp.asarray(np.random.RandomState(5).randn(2, 6, 32), jnp.float32))
    assert y.shape == (2, 6, 32)


class TestFlashMaskAndOffset:
    """mask/kv_offset support in the Pallas kernel (round-4: cached decode and
    masked attention no longer fall back to XLA)."""

    def _qkv(self, b=2, h=2, sq=64, skv=None, d=32, seed=0):
        rs = np.random.RandomState(seed)
        skv = skv or sq
        return (jnp.asarray(rs.randn(b, h, sq, d), jnp.float32),
                jnp.asarray(rs.randn(b, h, skv, d), jnp.float32),
                jnp.asarray(rs.randn(b, h, skv, d), jnp.float32))

    @pytest.mark.parametrize("causal,mask_shape", [
        (False, (2, 1, 64, 64)),   # padding mask, broadcast over heads
        (True, (2, 2, 64, 64)),    # per-head mask composed with causal
        (False, (64, 64)),         # shared 2-D mask
    ])
    def test_masked_forward_matches_xla(self, causal, mask_shape):
        from tnn_tpu.nn.attention import local_xla_attention
        from tnn_tpu.ops.pallas.flash_attention import flash_attention

        q, k, v = self._qkv()
        mask = jnp.asarray(np.random.RandomState(1).rand(*mask_shape) > 0.25)
        ref = local_xla_attention(q, k, v, causal=causal, mask=mask)
        got = flash_attention(q, k, v, causal, None, 32, 32, mask=mask)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_fully_masked_rows_are_zero(self):
        """Convention check: a row that attends to nothing outputs 0 (the XLA
        path's bare softmax would silently give uniform attention)."""
        from tnn_tpu.nn.attention import local_xla_attention
        from tnn_tpu.ops.pallas.flash_attention import flash_attention

        q, k, v = self._qkv()
        mask = np.ones((64, 64), bool)
        mask[7, :] = False  # row 7 attends to nothing
        mask = jnp.asarray(mask)
        for fn in (lambda: flash_attention(q, k, v, False, None, 32, 32,
                                           mask=mask),
                   lambda: local_xla_attention(q, k, v, mask=mask)):
            out = np.asarray(fn())
            assert np.all(out[:, :, 7] == 0)
            assert np.isfinite(out).all()

    def test_masked_grads_match_xla(self):
        from tnn_tpu.nn.attention import local_xla_attention
        from tnn_tpu.ops.pallas.flash_attention import flash_attention

        q, k, v = self._qkv()
        mask = jnp.asarray(np.random.RandomState(2).rand(2, 2, 64, 64) > 0.2)

        def g(fn):
            return jax.grad(lambda q, k, v: jnp.sum(fn(q, k, v) ** 2),
                            argnums=(0, 1, 2))(q, k, v)

        gf = g(lambda q, k, v: flash_attention(q, k, v, True, None, 32, 32,
                                               32, 32, mask=mask))
        gx = g(lambda q, k, v: local_xla_attention(q, k, v, causal=True,
                                                   mask=mask))
        for a, b in zip(gf, gx):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-4)

    def test_kv_offset_decode_matches_xla(self):
        """S_q=4 new tokens attending into a 64-slot cache at offset 60 — the
        cached-decode geometry, including a TRACED offset."""
        from tnn_tpu.nn.attention import local_xla_attention
        from tnn_tpu.ops.pallas.flash_attention import flash_attention

        q, k, v = self._qkv(sq=4, skv=64)
        off = jnp.asarray(60, jnp.int32)
        ref = local_xla_attention(q, k, v, causal=True, kv_offset=off)
        got = jax.jit(lambda q, k, v, off: flash_attention(
            q, k, v, True, None, 32, 32, kv_offset=off))(q, k, v, off)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_cached_decode_uses_pallas_backend(self, rng):
        """A backend='pallas' MHA decodes through the flash kernel (no
        NotImplementedError) and matches the full forward."""
        mha = nn.MultiHeadAttention(num_heads=4, causal=True,
                                    backend="pallas", policy=F32)
        x = jnp.asarray(np.random.RandomState(3).randn(2, 8, 32), jnp.float32)
        v = mha.init(rng, x.shape)
        full = mha(v, x)
        cache = mha.init_cache(2, 8, 32)
        out, cache = mha.apply_cached(v, x[:, :5], cache, 0)
        outs = [out]
        for t in range(5, 8):
            o, cache = mha.apply_cached(v, x[:, t:t + 1], cache, t)
            outs.append(o)
        stitched = jnp.concatenate(outs, axis=1)
        np.testing.assert_allclose(np.asarray(stitched), np.asarray(full),
                                   rtol=1e-4, atol=1e-5)


def test_fused_bwd_matches_split_bwd(monkeypatch):
    """The single-pass fused backward (5 matmuls/tile, full-seq dQ scratch)
    must produce the same gradients as the split dq/dkv kernels, including
    with a padding mask, ragged seq, and kv_offset."""
    from tnn_tpu.ops.pallas import flash_attention as fa

    rs = np.random.RandomState(11)
    b, h, sq, skv, d = 2, 2, 200, 256, 64
    q = jnp.asarray(rs.randn(b, h, sq, d), jnp.float32)
    k = jnp.asarray(rs.randn(b, h, skv, d), jnp.float32)
    v = jnp.asarray(rs.randn(b, h, skv, d), jnp.float32)
    g = jnp.asarray(rs.randn(b, h, sq, d), jnp.float32)
    mask = jnp.asarray(rs.rand(b, 1, sq, skv) > 0.1)

    def grads(q, k, v):
        def loss(q, k, v):
            return jnp.vdot(fa.flash_attention(
                q, k, v, True, None, 128, 128, 64, 64, mask=mask,
                kv_offset=skv - sq), g)
        return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

    monkeypatch.setenv("TNN_FLASH_FUSED_BWD", "0")
    split = grads(q, k, v)
    monkeypatch.setenv("TNN_FLASH_FUSED_BWD", "1")
    fused = grads(q, k, v)
    assert fa._fused_bwd_applicable(256, d)  # the fused path really ran
    for name, a, b_ in zip("dq dk dv".split(), fused, split):
        assert np.all(np.isfinite(np.asarray(a))), name
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), rtol=1e-5,
                                   atol=1e-5, err_msg=name)


def test_fused_bwd_causal_short_query_no_offset(monkeypatch):
    """causal + sq < skv + kv_offset=None: trailing k blocks' first live q row
    lands past the last q block; the fused backward's clamped fetch index must
    stay in range (regression: unguarded max() overflowed the q BlockSpec)."""
    from tnn_tpu.ops.pallas import flash_attention as fa

    rs = np.random.RandomState(13)
    q = jnp.asarray(rs.randn(1, 2, 100, 64), jnp.float32)
    k = jnp.asarray(rs.randn(1, 2, 256, 64), jnp.float32)
    v = jnp.asarray(rs.randn(1, 2, 256, 64), jnp.float32)
    g = jnp.asarray(rs.randn(1, 2, 100, 64), jnp.float32)

    def grads(q, k, v):
        def loss(q, k, v):
            return jnp.vdot(fa.flash_attention(
                q, k, v, True, None, 64, 64, 64, 64), g)
        return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

    monkeypatch.setenv("TNN_FLASH_FUSED_BWD", "0")
    split = grads(q, k, v)
    monkeypatch.setenv("TNN_FLASH_FUSED_BWD", "1")
    fused = grads(q, k, v)
    for name, a, b_ in zip("dq dk dv".split(), fused, split):
        assert np.all(np.isfinite(np.asarray(a))), name
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), rtol=1e-5,
                                   atol=1e-5, err_msg=name)


class TestGQA:
    """Grouped-query attention (beyond reference): H_kv < H shares kv heads
    across query groups; the pallas kernel maps q-head grid indices to kv
    heads in its BlockSpecs (zero materialization)."""

    def _qkv(self, hq=4, hkv=2, sq=128, skv=128, d=32):
        rs = np.random.RandomState(21)
        q = jnp.asarray(rs.randn(2, hq, sq, d), jnp.float32)
        k = jnp.asarray(rs.randn(2, hkv, skv, d), jnp.float32)
        v = jnp.asarray(rs.randn(2, hkv, skv, d), jnp.float32)
        return q, k, v

    @pytest.mark.parametrize("hkv", [2, 1])  # grouped and MQA (single kv head)
    def test_flash_gqa_matches_repeated_kv(self, hkv):
        from tnn_tpu.ops.pallas.flash_attention import flash_attention

        q, k, v = self._qkv(hkv=hkv)
        out = flash_attention(q, k, v, True, None, 64, 64)
        g = 4 // hkv
        ref = flash_attention(q, jnp.repeat(k, g, axis=1),
                              jnp.repeat(v, g, axis=1), True, None, 64, 64)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_xla_gqa_matches_repeated_kv(self):
        q, k, v = self._qkv()
        out = sdpa(q, k, v, causal=True, backend="xla")
        ref = sdpa(q, jnp.repeat(k, 2, axis=1), jnp.repeat(v, 2, axis=1),
                   causal=True, backend="xla")
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    @pytest.mark.parametrize("fused", ["1", "0"])
    def test_gqa_grads_match_repeated_kv(self, monkeypatch, fused):
        """dK/dV for a shared kv head must equal the SUM of its group's
        per-head grads — both fused and split backward paths."""
        from tnn_tpu.ops.pallas.flash_attention import flash_attention

        monkeypatch.setenv("TNN_FLASH_FUSED_BWD", fused)
        q, k, v = self._qkv()
        g = jnp.asarray(np.random.RandomState(3).randn(*q.shape), jnp.float32)

        def loss(q, k, v):
            return jnp.vdot(flash_attention(q, k, v, True, None, 64, 64,
                                            64, 64), g)

        def loss_rep(q, k2, v2):
            return jnp.vdot(flash_attention(q, jnp.repeat(k2, 2, axis=1),
                                            jnp.repeat(v2, 2, axis=1),
                                            True, None, 64, 64, 64, 64), g)

        got = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        want = jax.grad(loss_rep, argnums=(0, 1, 2))(q, k, v)
        for name, a, b in zip("dq dk dv".split(), got, want):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-4, err_msg=name)

    @pytest.mark.parametrize("backend", ["xla", "pallas"])
    def test_mha_gqa_cached_decode_matches_full(self, rng, backend):
        mha = nn.MultiHeadAttention(num_heads=4, num_kv_heads=2, causal=True,
                                    backend=backend, policy=F32)
        x = jnp.asarray(np.random.RandomState(5).randn(2, 8, 32), jnp.float32)
        v = mha.init(rng, x.shape)
        full = mha(v, x)
        cache = mha.init_cache(2, 8, 32)
        assert cache["k"].shape == (2, 2, 8, 8)  # H_kv=2 sized cache
        out, cache = mha.apply_cached(v, x[:, :5], cache, 0)
        outs = [out]
        for t in range(5, 8):
            o, cache = mha.apply_cached(v, x[:, t:t + 1], cache, t)
            outs.append(o)
        np.testing.assert_allclose(np.asarray(jnp.concatenate(outs, axis=1)),
                                   np.asarray(full), rtol=1e-4, atol=1e-5)

    def test_gqa_config_roundtrip(self, rng):
        from tnn_tpu.core.module import module_from_config

        mha = nn.MultiHeadAttention(num_heads=6, num_kv_heads=3, causal=True)
        m2 = module_from_config(mha.get_config())
        assert m2.num_kv_heads == 3 and m2.num_heads == 6

    def test_bad_head_ratio_raises(self):
        with pytest.raises(ValueError):
            nn.MultiHeadAttention(num_heads=6, num_kv_heads=4)


def test_gqa_ulysses_indivisible_kv_heads_raises():
    """GQA + ulysses with H_kv not divisible by the shard count must fail
    loudly (the kv head all-to-all cannot split), not silently attend within
    each seq shard. Divisible H_kv proceeds; the ring method is always
    GQA-aware (test_parallel.test_ring_attention_gqa_matches_local)."""
    from tnn_tpu import parallel
    from tnn_tpu.nn import attention as attn_mod

    rs = np.random.RandomState(0)
    q = jnp.asarray(rs.randn(1, 4, 16, 8), jnp.float32)
    k = jnp.asarray(rs.randn(1, 2, 16, 8), jnp.float32)
    mesh = parallel.make_mesh(seq=4)  # 2 kv heads cannot split over 4
    attn_mod._RING_CTX["mesh"] = mesh
    prev = attn_mod._RING_CTX.get("method")
    attn_mod._RING_CTX["method"] = "ulysses"
    try:
        with pytest.raises(ValueError, match="kv heads"):
            sdpa(q, k, k, causal=True)
    finally:
        attn_mod._RING_CTX["mesh"] = None
        attn_mod._RING_CTX["method"] = prev


def test_gqa_ulysses_divisible_kv_heads_matches_local():
    """H_kv % shards == 0: the ulysses kv all-to-all splits fine — verify
    against the local GQA kernels."""
    from tnn_tpu import parallel

    rs = np.random.RandomState(1)
    q = jnp.asarray(rs.randn(1, 4, 32, 8), jnp.float32)
    k = jnp.asarray(rs.randn(1, 2, 32, 8), jnp.float32)
    v = jnp.asarray(rs.randn(1, 2, 32, 8), jnp.float32)
    mesh = parallel.make_mesh(seq=2)
    ref = sdpa(q, k, v, causal=True)
    out = parallel.ulysses_attention(q, k, v, mesh, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


class TestInt8KVCache:
    """kv_cache_dtype='int8': per-row symmetric int8 cache halves decode
    cache residency/traffic (composes with GQA)."""

    def test_cached_decode_close_to_full(self, rng):
        mha = nn.MultiHeadAttention(num_heads=4, causal=True,
                                    kv_cache_dtype="int8", policy=F32)
        x = jnp.asarray(np.random.RandomState(9).randn(2, 8, 32), jnp.float32)
        v = mha.init(rng, x.shape)
        full = mha(v, x)
        cache = mha.init_cache(2, 8, 32)
        assert cache["k"].dtype == jnp.int8
        assert cache["k_scale"].shape == (2, 4, 8, 1)
        out, cache = mha.apply_cached(v, x[:, :5], cache, 0)
        outs = [out]
        for t in range(5, 8):
            o, cache = mha.apply_cached(v, x[:, t:t + 1], cache, t)
            outs.append(o)
        got = np.asarray(jnp.concatenate(outs, axis=1))
        # int8 KV quantization noise: ~0.4% relative per row; attention keeps
        # it near that level. This is a closeness check, not bit-exactness.
        err = np.max(np.abs(got - np.asarray(full))) / max(
            1e-6, float(np.max(np.abs(np.asarray(full)))))
        assert err < 0.03, f"int8 cache decode rel err {err}"

    def test_cache_bytes_halved(self, rng):
        full = nn.MultiHeadAttention(num_heads=4, causal=True, policy=F32)
        q8 = nn.MultiHeadAttention(num_heads=4, causal=True,
                                   kv_cache_dtype="int8", policy=F32)
        c_full = full.init_cache(1, 128, 64)
        c_q8 = q8.init_cache(1, 128, 64)
        nb = lambda c: sum(np.asarray(v).nbytes for v in c.values())  # noqa: E731
        # f32 policy cache = 2*S*dh*4B; int8 = 2*S*(dh + 4)B
        assert nb(c_q8) < 0.4 * nb(c_full)

    def test_gpt2_generate_with_int8_cache(self):
        from tnn_tpu.models.gpt2 import GPT2, generate

        m = GPT2(vocab_size=96, max_len=32, num_layers=2, d_model=32,
                 num_heads=4, kv_cache_dtype="int8")
        variables = m.init(jax.random.PRNGKey(0), (1, 8))
        toks = generate(m, variables["params"],
                        jnp.asarray([[1, 2, 3]], jnp.int32), 5)
        assert toks.shape == (1, 5)  # generate returns the NEW tokens

    def test_config_roundtrip(self):
        from tnn_tpu.core.module import module_from_config
        from tnn_tpu.models.gpt2 import GPT2

        m = GPT2(vocab_size=96, max_len=32, num_layers=1, d_model=32,
                 num_heads=4, kv_cache_dtype="int8")
        m2 = module_from_config(m.get_config())
        assert m2.kv_cache_dtype == "int8"
        assert m2.blocks[0].attn.kv_cache_dtype == "int8"

    def test_bad_dtype_raises(self):
        with pytest.raises(ValueError, match="kv_cache_dtype"):
            nn.MultiHeadAttention(num_heads=2, kv_cache_dtype="int4")
