"""Smoke tests for the CLI examples (parity: the reference's executables,
examples/CMakeLists.txt:2-27, exercised here as importable mains)."""
import json
import os
import threading

import numpy as np

from tnn_tpu.cli import dist_worker, gpt2_inference, inferencer, trainer


class TestTrainer:
    def test_synthetic_end_to_end(self, tmp_path, monkeypatch):

        monkeypatch.chdir(tmp_path)  # .env isolation
        state, history = trainer.main([
            "--model", "mnist_cnn", "--dataset", "synthetic",
            "--epochs", "1", "--batch-size", "16", "--num-classes", "10",
            "--snapshot-dir", str(tmp_path / "snap"),
        ])
        assert len(history) == 1
        assert np.isfinite(history[0]["train_loss"])
        assert (tmp_path / "snap").is_dir()

    def test_config_file_and_resume(self, tmp_path, monkeypatch):

        monkeypatch.chdir(tmp_path)
        cfgf = tmp_path / "cfg.json"
        cfgf.write_text(json.dumps({
            "model_name": "mnist_cnn", "epochs": 1, "batch_size": 16,
            "snapshot_dir": str(tmp_path / "snap"),
        }))
        _, h1 = trainer.main(["--config", str(cfgf)])
        # resume from the epoch checkpoint and train one more epoch
        step_dirs = [d for d in os.listdir(tmp_path / "snap")
                     if d.startswith("step_")]
        assert step_dirs
        _, h2 = trainer.main(["--config", str(cfgf),
                              "--resume", str(tmp_path / "snap")])
        assert len(h2) == 1


class TestInferencer:
    def test_round_trip(self, tmp_path, monkeypatch, capsys):

        from tnn_tpu import checkpoint as ckpt_lib
        from tnn_tpu import models
        import jax

        monkeypatch.chdir(tmp_path)
        model = models.create("cifar10_resnet9")
        variables = model.init(jax.random.PRNGKey(0), (4, 32, 32, 3))
        mf = tmp_path / "m.tnn"
        ckpt_lib.save_model(str(mf), model, variables["params"],
                            variables["state"])
        inferencer.main(["--model-file", str(mf), "--dataset", "synthetic",
                         "--batch-size", "8"])
        out = capsys.readouterr().out
        assert "accuracy" in out and "samples/s" in out


class TestGpt2Inference:
    def test_smoke_generation(self, tmp_path, monkeypatch, capsys):

        monkeypatch.chdir(tmp_path)
        # tiny model instead of gpt2_small to keep the test fast
        from tnn_tpu.models import zoo
        from tnn_tpu.models.gpt2 import GPT2

        zoo.register("_test_tiny_gpt")(
            lambda **kw: GPT2(vocab_size=256, max_len=64, num_layers=2,
                              d_model=32, num_heads=2))
        gpt2_inference.main(["--model", "_test_tiny_gpt", "--prompt", "hi there",
                             "-n", "8"])
        outp = capsys.readouterr().out
        assert "tok/s" in outp


class TestDistExamples:
    def test_coordinator_worker_pair(self, tmp_path):
        """Full orchestration: coordinator deploys a 1-epoch synthetic config to
        one worker, both barriers fire, shutdown completes."""

        port = 0
        # patch: run coordinator with ephemeral port, discover it for the worker
        from tnn_tpu.distributed import Coordinator

        config = {"model_name": "mnist_cnn", "epochs": 1, "batch_size": 16,
                  "max_steps": 2, "snapshot_dir": str(tmp_path / "s"),
                  "dataset_name": "synthetic"}
        coord = Coordinator(num_workers=1, port=0)
        err = []

        def run_worker():
            try:
                dist_worker.main(["--coordinator", f"127.0.0.1:{coord.port()}"])
            except Exception as e:
                err.append(e)

        t = threading.Thread(target=run_worker, daemon=True)
        t.start()
        coord.wait_for_workers(timeout=30)
        coord.deploy_config(config, timeout=30)
        coord.barrier("start", timeout=60)
        coord.barrier("done", timeout=300)
        coord.shutdown()
        t.join(timeout=30)
        assert not err, err
