"""Loss/optimizer/scheduler/train-step tests.

End-to-end convergence on a synthetic task is the analog of the reference's
train-loop integration coverage (src/nn/train.cpp paths)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tnn_tpu import nn
from tnn_tpu.core import dtypes as dt
from tnn_tpu.nn import losses, optimizers, schedulers
from tnn_tpu.train import create_train_state, make_eval_step, make_train_step

F32 = dt.FP32


# -- losses ------------------------------------------------------------------

def test_softmax_cross_entropy_matches_numpy():
    logits = np.random.RandomState(0).randn(8, 5).astype(np.float32)
    labels = np.array([0, 1, 2, 3, 4, 0, 1, 2])
    loss = losses.softmax_cross_entropy(jnp.asarray(logits), jnp.asarray(labels))
    p = np.exp(logits - logits.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    ref = -np.mean(np.log(p[np.arange(8), labels]))
    np.testing.assert_allclose(float(loss), ref, rtol=1e-5)


def test_losses_basic():
    a = jnp.asarray([[1.0, 2.0], [3.0, 4.0]])
    b = jnp.asarray([[1.5, 2.0], [2.0, 4.0]])
    np.testing.assert_allclose(float(losses.mse(a, b)), (0.25 + 1.0) / 4, rtol=1e-6)
    np.testing.assert_allclose(float(losses.mae(a, b)), (0.5 + 1.0) / 4, rtol=1e-6)
    h = float(losses.huber(a, b, delta=1.0))
    np.testing.assert_allclose(h, (0.5 * 0.25 + 0.5) / 4, rtol=1e-6)


def test_label_smoothing_matches_manual_mix():
    rs = np.random.RandomState(3)
    logits = rs.randn(6, 4).astype(np.float32)
    labels = np.array([0, 1, 2, 3, 0, 1])
    a = 0.1
    got = float(losses.softmax_cross_entropy(
        jnp.asarray(logits), jnp.asarray(labels), label_smoothing=a))
    logp = logits - logits.max(-1, keepdims=True)
    logp = logp - np.log(np.exp(logp).sum(-1, keepdims=True))
    target = np.eye(4)[labels] * (1 - a) + a / 4
    ref = float(np.mean(-(target * logp).sum(-1)))
    np.testing.assert_allclose(got, ref, rtol=1e-5)
    # a=0 is exactly the unsmoothed loss
    np.testing.assert_allclose(
        float(losses.softmax_cross_entropy(
            jnp.asarray(logits), jnp.asarray(labels), label_smoothing=0.0)),
        float(losses.softmax_cross_entropy(jnp.asarray(logits),
                                           jnp.asarray(labels))), rtol=1e-7)


def test_loss_config_dict_reaches_kwargs():
    """{"type": name, **kwargs} configs bind loss options — the path a JSON
    TrainingConfig takes (config.loss -> make_train_step -> losses.get)."""
    rs = np.random.RandomState(5)
    logits = jnp.asarray(rs.randn(4, 3), jnp.float32)
    labels = jnp.asarray([0, 1, 2, 0], jnp.int32)
    fn = losses.get({"type": "softmax_cross_entropy", "label_smoothing": 0.2})
    np.testing.assert_allclose(
        float(fn(logits, labels)),
        float(losses.softmax_cross_entropy(logits, labels,
                                           label_smoothing=0.2)), rtol=1e-7)
    assert losses.get("mse") is losses.mse


def test_onehot_and_int_labels_agree():
    logits = jnp.asarray(np.random.randn(4, 3), jnp.float32)
    ints = jnp.asarray([0, 2, 1, 0], jnp.int32)
    onehot = jax.nn.one_hot(ints, 3)
    np.testing.assert_allclose(
        float(losses.softmax_cross_entropy(logits, ints)),
        float(losses.softmax_cross_entropy(logits, onehot)), rtol=1e-6)


# -- optimizers --------------------------------------------------------------

def _quad_params():
    return {"w": jnp.asarray([5.0, -3.0], jnp.float32)}


def _quad_grads(params):
    return {"w": 2 * params["w"]}  # grad of ||w||^2


@pytest.mark.parametrize("opt", [
    optimizers.SGD(lr=0.1),
    optimizers.SGD(lr=0.05, momentum=0.9),
    optimizers.SGD(lr=0.05, momentum=0.9, nesterov=True),
    optimizers.Adam(lr=0.3),
    optimizers.Adam(lr=0.3, amsgrad=True),
    optimizers.AdamW(lr=0.3, weight_decay=0.01),
])
def test_optimizers_minimize_quadratic(opt):
    params = _quad_params()
    state = opt.init(params)
    for _ in range(150):
        params, state = opt.update(_quad_grads(params), state, params)
    assert float(jnp.abs(params["w"]).max()) < 0.1, f"{opt.opt_name} failed to converge"


def test_sgd_matches_closed_form():
    opt = optimizers.SGD(lr=0.1)
    params = {"w": jnp.asarray([1.0])}
    state = opt.init(params)
    new_params, _ = opt.update({"w": jnp.asarray([0.5])}, state, params)
    np.testing.assert_allclose(np.asarray(new_params["w"]), [0.95], rtol=1e-6)


def test_grad_clipping():
    opt = optimizers.SGD(lr=1.0, grad_clip_norm=1.0)
    params = {"w": jnp.zeros((2,))}
    state = opt.init(params)
    new_params, _ = opt.update({"w": jnp.asarray([30.0, 40.0])}, state, params)
    # clipped grad has norm 1 -> step of norm 1
    np.testing.assert_allclose(float(jnp.linalg.norm(new_params["w"])), 1.0, rtol=1e-4)


def test_optimizer_config_roundtrip():
    opt = optimizers.Adam(lr=0.01, beta1=0.8, amsgrad=True, weight_decay=0.1)
    cfg = opt.get_config()
    opt2 = optimizers.from_config(cfg)
    assert opt2.get_config() == cfg


# -- schedulers --------------------------------------------------------------

def test_step_lr():
    s = schedulers.StepLR(step_size=10, gamma=0.1)
    assert float(s.scale(0)) == pytest.approx(1.0)
    assert float(s.scale(9)) == pytest.approx(1.0)
    assert float(s.scale(10)) == pytest.approx(0.1)
    assert float(s.scale(25)) == pytest.approx(0.01)


def test_multistep_lr():
    s = schedulers.MultiStepLR([5, 15], gamma=0.5)
    assert float(s.scale(4)) == pytest.approx(1.0)
    assert float(s.scale(5)) == pytest.approx(0.5)
    assert float(s.scale(20)) == pytest.approx(0.25)


def test_cosine():
    s = schedulers.CosineAnnealingLR(t_max=100)
    assert float(s.scale(0)) == pytest.approx(1.0)
    assert float(s.scale(50)) == pytest.approx(0.5, abs=1e-6)
    assert float(s.scale(100)) == pytest.approx(0.0, abs=1e-6)


def test_warmup_cosine():
    s = schedulers.WarmupCosineAnnealing(warmup=10, t_max=110)
    assert float(s.scale(0)) == pytest.approx(0.0)
    assert float(s.scale(5)) == pytest.approx(0.5)
    assert float(s.scale(10)) == pytest.approx(1.0)
    assert float(s.scale(110)) == pytest.approx(0.0, abs=1e-6)


def test_cosine_restarts():
    s = schedulers.CosineAnnealingWarmRestarts(t_0=10, t_mult=2)
    assert float(s.scale(0)) == pytest.approx(1.0)
    assert float(s.scale(10)) == pytest.approx(1.0)  # restart
    assert float(s.scale(30)) == pytest.approx(1.0)  # second restart (10+20)


def test_reduce_on_plateau():
    s = schedulers.ReduceLROnPlateau(factor=0.5, patience=1)
    assert s.observe(1.0) == 1.0
    assert s.observe(0.5) == 1.0   # improved
    assert s.observe(0.6) == 1.0   # bad 1
    assert s.observe(0.6) == 0.5   # bad 2 > patience -> reduce
    assert float(s.scale(0)) == 0.5


def test_scheduler_config_roundtrip():
    for s in [schedulers.StepLR(10), schedulers.MultiStepLR([1, 2]),
              schedulers.ExponentialLR(0.9), schedulers.CosineAnnealingLR(50),
              schedulers.WarmupCosineAnnealing(5, 50), schedulers.NoOp()]:
        cfg = s.get_config()
        assert schedulers.from_config(cfg).get_config() == cfg


def test_scheduler_traces_in_jit():
    s = schedulers.WarmupCosineAnnealing(warmup=10, t_max=100)

    @jax.jit
    def f(t):
        return s.scale(t)

    assert float(f(jnp.asarray(5))) == pytest.approx(0.5)


# -- end-to-end train step ---------------------------------------------------

def _spiral_data(n=256, seed=0):
    """Two-class spiral — small but not linearly separable."""
    rs = np.random.RandomState(seed)
    n2 = n // 2
    theta = np.linspace(0.5, 3 * np.pi, n2)
    r = theta / (3 * np.pi)
    x0 = np.stack([r * np.cos(theta), r * np.sin(theta)], -1)
    x1 = -x0
    x = np.concatenate([x0, x1]) + rs.randn(n, 2) * 0.02
    y = np.concatenate([np.zeros(n2), np.ones(n2)]).astype(np.int32)
    return x.astype(np.float32), y


def test_train_step_learns_spiral(rng):
    model = nn.Sequential([
        nn.Dense(64, activation="tanh", policy=F32),
        nn.Dense(64, activation="tanh", policy=F32),
        nn.Dense(2, policy=F32),
    ], policy=F32)
    opt = nn.Adam(lr=1e-2)
    state = create_train_state(model, opt, rng, (256, 2), input_dtype=jnp.float32)
    step = make_train_step(model, opt)
    x, y = _spiral_data()
    data, labels = jnp.asarray(x), jnp.asarray(y)
    for _ in range(150):
        state, metrics = step(state, data, labels)
    assert float(metrics["accuracy"]) > 0.95
    assert float(metrics["loss"]) < 0.3


def test_train_step_mixed_precision(rng):
    """bf16 io/compute with f32 params — the TPU-native default policy."""
    model = nn.Sequential([
        nn.Dense(32, activation="relu"),
        nn.Dense(2),
    ])
    opt = nn.SGD(lr=0.1, momentum=0.9)
    state = create_train_state(model, opt, rng, (64, 2))
    step = make_train_step(model, opt)
    x, y = _spiral_data(64)
    data = jnp.asarray(x, jnp.bfloat16)
    labels = jnp.asarray(y)
    for _ in range(30):
        state, metrics = step(state, data, labels)
    # params stay f32 master copies
    assert state.params["00_dense"]["kernel"].dtype == jnp.float32
    assert np.isfinite(float(metrics["loss"]))


def test_eval_step_uses_running_stats(rng):
    model = nn.Sequential([nn.Dense(16, policy=F32), nn.BatchNorm(policy=F32),
                           nn.Dense(2, policy=F32)], policy=F32)
    opt = nn.SGD(lr=0.01)
    state = create_train_state(model, opt, rng, (32, 2), input_dtype=jnp.float32)
    train_step = make_train_step(model, opt)
    eval_step = make_eval_step(model)
    x, y = _spiral_data(32)
    state, _ = train_step(state, jnp.asarray(x), jnp.asarray(y))
    m = eval_step(state, jnp.asarray(x), jnp.asarray(y))
    assert "loss" in m and "corrects" in m


def test_plateau_scheduler_affects_jitted_step(rng):
    """Regression: host-driven scheduler factor must NOT constant-fold into the
    compiled step — it is threaded in as a runtime operand."""
    model = nn.Sequential([nn.Dense(2, policy=F32)], policy=F32)
    opt = nn.SGD(lr=0.1)
    sched = schedulers.ReduceLROnPlateau(factor=0.5, patience=0)
    state = create_train_state(model, opt, rng, (4, 2), input_dtype=jnp.float32)
    step = make_train_step(model, opt, scheduler=sched)
    x = jnp.ones((4, 2), jnp.float32)
    y = jnp.asarray([0, 1, 0, 1], jnp.int32)
    state, m1 = step(state, x, y)
    assert float(m1["lr_scale"]) == 1.0
    sched.observe(1.0)
    sched.observe(1.0)  # no improvement -> reduce
    state, m2 = step(state, x, y)
    assert float(m2["lr_scale"]) == 0.5


def test_int8_labels_route_to_onehot():
    logits = jnp.asarray(np.random.RandomState(0).randn(6, 3), jnp.float32)
    l8 = jnp.asarray([0, 1, 2, 0, 1, 2], jnp.int8)
    l32 = l8.astype(jnp.int32)
    np.testing.assert_allclose(float(losses.softmax_cross_entropy(logits, l8)),
                               float(losses.softmax_cross_entropy(logits, l32)), rtol=1e-6)
