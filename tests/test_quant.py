"""Int8 weight-only quantization: kernel numerics, layer transparency, and
end-to-end quantized GPT-2 decode (round-4 decode-roofline work; the
reference declares CompressionType::QUANTIZATION but never implements it,
include/distributed/packet.hpp:10-57)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tnn_tpu.ops.pallas.quant_matmul import (Int8Weight, qmatmul,
                                             quantize_int8)


class TestKernel:
    @pytest.mark.parametrize("m,k,n", [
        (1, 768, 2304),   # bs=1 decode projection
        (8, 768, 768),
        (17, 300, 130),   # ragged, forces padding in every dim
        (4, 1280, 5120),  # gpt2-large MLP width
    ])
    def test_matches_dequant_reference(self, m, k, n):
        rs = np.random.RandomState(0)
        w = rs.randn(k, n).astype(np.float32)
        x = jnp.asarray(rs.randn(m, k), jnp.bfloat16)
        iw = quantize_int8(w)
        ref = x.astype(jnp.float32) @ iw.dequant(jnp.float32)
        got = qmatmul(x, iw)
        assert got.dtype == x.dtype
        rel = float(jnp.max(jnp.abs(got.astype(jnp.float32) - ref))
                    / jnp.max(jnp.abs(ref)))
        assert rel < 0.02, rel

    def test_quantization_error_bounded(self):
        rs = np.random.RandomState(1)
        w = rs.randn(512, 256).astype(np.float32)
        iw = quantize_int8(w)
        # symmetric per-channel int8: max error is scale/2 = absmax/254
        err = np.abs(np.asarray(iw.dequant()) - w)
        bound = np.abs(w).max(0, keepdims=True) / 254 + 1e-7
        assert (err <= bound).all()

    def test_int8_weight_is_pytree(self):
        iw = quantize_int8(np.eye(128, dtype=np.float32))
        leaves = jax.tree_util.tree_leaves(iw)
        assert len(leaves) == 2
        out = jax.jit(lambda w, x: qmatmul(x, w))(
            iw, jnp.ones((2, 128), jnp.bfloat16))
        assert out.shape == (2, 128)

    @pytest.mark.parametrize("k,n", [(768, 2304), (300, 130)])
    def test_w8a8_matches_float_reference(self, k, n):
        from tnn_tpu.ops.pallas.quant_matmul import w8a8_matmul

        rs = np.random.RandomState(3)
        w = rs.randn(k, n).astype(np.float32)
        iw = quantize_int8(w)
        x = jnp.asarray(rs.randn(4, k), jnp.bfloat16)
        ref = np.asarray(x.astype(jnp.float32) @ jnp.asarray(w))
        got = np.asarray(w8a8_matmul(x, iw, out_dtype=jnp.float32))
        # weight + per-row activation int8 error: a couple percent relative
        rel = np.max(np.abs(got - ref)) / np.max(np.abs(ref))
        assert rel < 0.05, rel

    def test_qmatmul_rank_stable_across_paths(self):
        # the row-count dispatch (w8a8 vs pallas kernel) must not change the
        # output rank: 1-D in -> 1-D out, 3-D in -> 3-D out on both routes
        from tnn_tpu.ops.pallas import quant_matmul as qm

        iw = quantize_int8(np.random.RandomState(4)
                           .randn(256, 128).astype(np.float32))
        x1 = jnp.ones((256,), jnp.bfloat16)
        x3 = jnp.ones((2, 3, 256), jnp.bfloat16)
        assert qmatmul(x1, iw).shape == (128,)          # w8a8 route
        assert qmatmul(x3, iw).shape == (2, 3, 128)
        big = jnp.ones((qm.W8A8_MAX_ROWS + 1, 256), jnp.bfloat16)
        assert qmatmul(big, iw).shape == (qm.W8A8_MAX_ROWS + 1, 128)  # pallas

    def test_qmatmul_float_path_unchanged(self):
        rs = np.random.RandomState(2)
        x = jnp.asarray(rs.randn(4, 64), jnp.float32)
        w = jnp.asarray(rs.randn(64, 32), jnp.float32)
        np.testing.assert_allclose(np.asarray(qmatmul(x, w)),
                                   np.asarray(x @ w), rtol=1e-5)


class TestQuantizedGPT2:
    @pytest.fixture(scope="class")
    def setup(self):
        from tnn_tpu.models.gpt2 import GPT2
        from tnn_tpu.nn.quant import quantize_for_decode

        m = GPT2(vocab_size=512, max_len=96, num_layers=2, d_model=256,
                 num_heads=4)
        v = m.init(jax.random.PRNGKey(0), (2, 16))
        return m, v["params"], quantize_for_decode(v["params"])

    def test_selection_and_bytes(self, setup):
        from tnn_tpu.nn.quant import quantized_bytes

        _, params, qp = setup
        q_leaves = [l for l in jax.tree_util.tree_leaves(
            qp, is_leaf=lambda x: isinstance(x, Int8Weight))
            if isinstance(l, Int8Weight)]
        # 2 blocks x (qkv, out, 2 mlp kernels) + wte = 9
        assert len(q_leaves) == 9
        # positional table must stay float (it is sliced, not matmul'd)
        assert not isinstance(qp["wpe"]["pos"], Int8Weight)
        assert quantized_bytes(qp) < 0.45 * quantized_bytes(params)

    def test_logits_close_and_top1_agrees(self, setup):
        m, params, qp = setup
        ids = jnp.asarray(np.random.RandomState(0).randint(0, 512, (2, 16)),
                          jnp.int32)
        lf, _ = m.apply({"params": params, "state": {}}, ids)
        lq, _ = m.apply({"params": qp, "state": {}}, ids)
        rel = float(jnp.max(jnp.abs(lq - lf)) / jnp.max(jnp.abs(lf)))
        assert rel < 0.05, rel
        agree = float(jnp.mean(
            (jnp.argmax(lq, -1) == jnp.argmax(lf, -1)).astype(jnp.float32)))
        assert agree > 0.9, agree

    def test_generate_runs_quantized(self, setup):
        from tnn_tpu.models.gpt2 import generate

        m, params, qp = setup
        ids = jnp.asarray(np.random.RandomState(1).randint(0, 512, (1, 8)),
                          jnp.int32)
        toks = generate(m, qp, ids, 6)
        assert toks.shape == (1, 6)
        # greedy decode from the same random model: float and int8 agree on
        # the first token (later tokens may legitimately diverge)
        tf = generate(m, params, ids, 6)
        assert int(toks[0, 0]) == int(tf[0, 0])

    def test_checkpoint_rejects_quantized_params(self, setup, tmp_path):
        """Int8Weight must not silently round-trip through checkpoints as a
        plain dict (quantize AFTER load; float params are the stored form)."""
        from tnn_tpu import checkpoint as ck

        m, _, qp = setup
        with pytest.raises(ValueError, match="Int8Weight"):
            ck.save_model(str(tmp_path / "q.tnn"), m, qp, {})
