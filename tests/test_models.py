"""Model zoo tests (parity intent: the reference's model creators,
src/nn/example_models.cpp, exercised through small shapes)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tnn_tpu import models, nn
from tnn_tpu.core import dtypes as dt
from tnn_tpu.core.module import module_from_config, param_count
from tnn_tpu.models import gpt2 as gpt2_lib

F32 = dt.FP32


def test_zoo_inventory():
    expected = {
        "mnist_cnn", "cifar10_vgg", "cifar10_resnet9", "cifar100_resnet18",
        "cifar100_wrn16_8", "tiny_imagenet_resnet18", "tiny_imagenet_wrn16_8",
        "tiny_imagenet_resnet50", "resnet50_imagenet", "tiny_imagenet_vit", "flash_vit",
        "gpt2_small", "gpt2_medium", "gpt2_large",
        "flash_gpt2_small", "flash_gpt2_medium", "flash_gpt2_large",
    }
    assert expected <= set(models.names())


def test_mnist_cnn_forward(rng):
    model = models.create("mnist_cnn", policy=F32)
    v = model.init(rng, (2, 28, 28, 1), input_dtype=jnp.float32)
    y = model(v, jnp.zeros((2, 28, 28, 1), jnp.float32))
    assert y.shape == (2, 10)


def test_resnet9_forward(rng):
    model = models.create("cifar10_resnet9", policy=F32)
    v = model.init(rng, (2, 32, 32, 3), input_dtype=jnp.float32)
    y = model(v, jnp.zeros((2, 32, 32, 3), jnp.float32))
    assert y.shape == (2, 10)


def test_wrn16_8_param_count(rng):
    """WRN-16-8 must be the ~11M-param flagship (sanity vs the known torch count 11.0M)."""
    model = models.create("cifar100_wrn16_8", policy=F32)
    v = model.init(rng, (2, 32, 32, 3), input_dtype=jnp.float32)
    n = param_count(v["params"])
    assert 10.5e6 < n < 11.5e6, f"unexpected WRN-16-8 param count {n}"
    y = model(v, jnp.zeros((2, 32, 32, 3), jnp.float32))
    assert y.shape == (2, 100)


def test_resnet18_trains_one_step(rng):
    from tnn_tpu.train import create_train_state, make_train_step

    model = models.create("cifar100_resnet18", policy=F32)
    opt = nn.SGD(lr=0.1, momentum=0.9)
    state = create_train_state(model, opt, rng, (4, 32, 32, 3), input_dtype=jnp.float32)
    step = make_train_step(model, opt)
    x = jnp.asarray(np.random.RandomState(0).randn(4, 32, 32, 3), jnp.float32)
    y = jnp.asarray([0, 1, 2, 3], jnp.int32)
    state, m = step(state, x, y)
    assert np.isfinite(float(m["loss"]))


def test_vit_forward(rng):
    model = models.ViT(num_classes=10, patch_size=8, d_model=64, num_layers=2,
                       num_heads=4, policy=F32)
    v = model.init(rng, (2, 32, 32, 3))
    y = model(v, jnp.zeros((2, 32, 32, 3), jnp.float32))
    assert y.shape == (2, 10)
    cfg = model.get_config()
    assert module_from_config(cfg).get_config() == cfg


def test_gpt2_tiny_forward_and_config(rng):
    model = models.GPT2(vocab_size=100, max_len=32, num_layers=2, d_model=32,
                        num_heads=4, policy=F32)
    v = model.init(rng, (2, 16))
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 100, (2, 16)), jnp.int32)
    logits = model(v, ids)
    assert logits.shape == (2, 16, 100)
    cfg = model.get_config()
    assert module_from_config(cfg).get_config() == cfg


def test_gpt2_param_count_small(rng):
    """GPT-2 small must match the canonical 124M (tied embeddings)."""
    model = models.create("gpt2_small", policy=F32)
    v = model.init(rng, (1, 8))
    n = param_count(v["params"])
    assert 123e6 < n < 125e6, f"unexpected GPT-2 small param count {n}"


def test_gpt2_cached_generate_matches_uncached(rng):
    """KV-cache generation must produce the same tokens as full recompute."""
    model = models.GPT2(vocab_size=50, max_len=24, num_layers=2, d_model=32,
                        num_heads=4, policy=F32)
    v = model.init(rng, (1, 8))
    params = v["params"]
    prompt = jnp.asarray([[3, 1, 4, 1, 5]], jnp.int32)
    toks = gpt2_lib.generate(model, params, prompt, max_new_tokens=6)
    assert toks.shape == (1, 6)
    # uncached greedy reference: full forward each step (the reference's approach)
    ids = prompt
    ref = []
    for _ in range(6):
        logits = model({"params": params, "state": {}}, ids)
        nxt = jnp.argmax(logits[:, -1], axis=-1)
        ref.append(int(nxt[0]))
        ids = jnp.concatenate([ids, nxt[:, None]], axis=1)
    assert [int(t) for t in toks[0]] == ref


def test_gpt2_trains_one_step(rng):
    from tnn_tpu.nn import losses
    from tnn_tpu.train import create_train_state, make_train_step

    model = models.GPT2(vocab_size=64, max_len=16, num_layers=2, d_model=32,
                        num_heads=4, policy=F32)
    opt = nn.AdamW(lr=1e-3)
    state = create_train_state(model, opt, rng, (2, 16))
    step = make_train_step(model, opt, loss_fn="softmax_cross_entropy")
    ids = jnp.asarray(np.random.RandomState(1).randint(0, 64, (2, 16)), jnp.int32)
    # next-token: input ids, labels shifted
    state, m = step(state, ids, jnp.roll(ids, -1, axis=1))
    assert np.isfinite(float(m["loss"]))


class TestRoPE:
    def test_rope_rotation_preserves_norm_and_offset_consistency(self):
        from tnn_tpu.nn.attention import apply_rope

        rs = np.random.RandomState(0)
        x = jnp.asarray(rs.randn(1, 2, 8, 16), jnp.float32)
        r = apply_rope(x, 0)
        # rotation preserves per-pair norms
        np.testing.assert_allclose(np.linalg.norm(np.asarray(x), axis=-1),
                                   np.linalg.norm(np.asarray(r), axis=-1),
                                   rtol=1e-5)
        # position 0 is the identity rotation
        np.testing.assert_allclose(np.asarray(r[..., 0, :]),
                                   np.asarray(x[..., 0, :]), rtol=1e-6)
        # offset=t on a length-1 slice equals position t of the full pass
        r3 = apply_rope(x[..., 3:4, :], 3)
        np.testing.assert_allclose(np.asarray(r3[..., 0, :]),
                                   np.asarray(r[..., 3, :]), rtol=1e-5,
                                   atol=1e-6)

    def test_rope_attention_is_shift_invariant(self):
        """The defining property: attention logits depend only on RELATIVE
        positions, so shifting q and k by the same offset leaves q.k^T
        unchanged."""
        from tnn_tpu.nn.attention import apply_rope

        rs = np.random.RandomState(1)
        q = jnp.asarray(rs.randn(1, 1, 6, 16), jnp.float32)
        k = jnp.asarray(rs.randn(1, 1, 6, 16), jnp.float32)
        dots0 = jnp.einsum("bhqd,bhkd->bhqk", apply_rope(q, 0),
                           apply_rope(k, 0))
        dots7 = jnp.einsum("bhqd,bhkd->bhqk", apply_rope(q, 7),
                           apply_rope(k, 7))
        np.testing.assert_allclose(np.asarray(dots0), np.asarray(dots7),
                                   rtol=1e-4, atol=1e-5)

    def test_odd_head_dim_raises(self):
        from tnn_tpu.nn.attention import apply_rope

        with pytest.raises(ValueError, match="even"):
            apply_rope(jnp.zeros((1, 1, 4, 7)), 0)


class TestLlama:
    def _tiny(self, **kw):
        from tnn_tpu.models.llama import Llama

        return Llama(vocab_size=64, max_len=16, num_layers=2, d_model=32,
                     num_heads=4, num_kv_heads=2, **kw)

    @pytest.mark.parametrize("backend", ["xla", "pallas"])
    def test_cached_decode_matches_full(self, backend):
        """RoPE offsets through the KV cache: stitched cached logits must
        equal the full forward (the rotation is position-absolute)."""
        m = self._tiny(backend=backend)
        v = m.init(jax.random.PRNGKey(0), (1, 8))
        ids = jnp.asarray(np.random.RandomState(2).randint(0, 64, (1, 8)),
                          jnp.int32)
        full, _ = m.apply(v, ids, train=False)
        caches = m.init_cache(1, 8)
        out, caches = m.apply_cached(v["params"], ids[:, :5], caches, 0)
        outs = [out]
        for t in range(5, 8):
            o, caches = m.apply_cached(v["params"], ids[:, t:t + 1], caches, t)
            outs.append(o)
        got = jnp.concatenate(outs, axis=1)
        np.testing.assert_allclose(np.asarray(got), np.asarray(full),
                                   rtol=2e-2, atol=2e-3)

    def test_causality(self):
        m = self._tiny()
        v = m.init(jax.random.PRNGKey(0), (1, 8))
        ids = jnp.asarray(np.random.RandomState(3).randint(0, 64, (1, 8)),
                          jnp.int32)
        a, _ = m.apply(v, ids, train=False)
        b, _ = m.apply(v, ids.at[:, 6:].set(0), train=False)
        np.testing.assert_allclose(np.asarray(a[:, :6]), np.asarray(b[:, :6]),
                                   rtol=1e-4, atol=1e-4)

    def test_config_roundtrip(self):
        from tnn_tpu.core.module import module_from_config

        m = self._tiny(kv_cache_dtype="int8")
        m2 = module_from_config(m.get_config())
        assert (m2.num_kv_heads, m2.mlp_hidden, m2.rope_theta,
                m2.kv_cache_dtype) == (2, m.mlp_hidden, 10000.0, "int8")
        assert not m2.blocks[0].attn.use_bias

    def test_no_bias_and_no_wpe_params(self):
        m = self._tiny()
        v = m.init(jax.random.PRNGKey(0), (1, 8))
        flat = jax.tree_util.tree_flatten_with_path(v["params"])[0]
        keys = ["/".join(str(k) for k in path) for path, _ in flat]
        assert not any("bias" in k for k in keys)
        assert not any("wpe" in k for k in keys)

    def test_chunked_lm_head_loss_path(self):
        from tnn_tpu import nn
        from tnn_tpu.train import create_train_state, make_train_step

        m = self._tiny()
        opt = nn.AdamW(lr=1e-3)
        st = create_train_state(m, opt, jax.random.PRNGKey(0), (2, 8))
        step = make_train_step(m, opt, compute_accuracy=False,
                               lm_head_chunk=32)
        ids = jnp.asarray(np.random.RandomState(4).randint(0, 64, (2, 8)),
                          jnp.int32)
        st, mt = step(st, ids, ids)
        assert np.isfinite(float(mt["loss"]))
