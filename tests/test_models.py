"""Model zoo tests (parity intent: the reference's model creators,
src/nn/example_models.cpp, exercised through small shapes)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tnn_tpu import models, nn
from tnn_tpu.core import dtypes as dt
from tnn_tpu.core.module import module_from_config, param_count
from tnn_tpu.models import gpt2 as gpt2_lib

F32 = dt.FP32


def test_zoo_inventory():
    expected = {
        "mnist_cnn", "cifar10_vgg", "cifar10_resnet9", "cifar100_resnet18",
        "cifar100_wrn16_8", "tiny_imagenet_resnet18", "tiny_imagenet_wrn16_8",
        "tiny_imagenet_resnet50", "resnet50_imagenet", "tiny_imagenet_vit", "flash_vit",
        "gpt2_small", "gpt2_medium", "gpt2_large",
        "flash_gpt2_small", "flash_gpt2_medium", "flash_gpt2_large",
    }
    assert expected <= set(models.names())


def test_mnist_cnn_forward(rng):
    model = models.create("mnist_cnn", policy=F32)
    v = model.init(rng, (2, 28, 28, 1), input_dtype=jnp.float32)
    y = model(v, jnp.zeros((2, 28, 28, 1), jnp.float32))
    assert y.shape == (2, 10)


def test_resnet9_forward(rng):
    model = models.create("cifar10_resnet9", policy=F32)
    v = model.init(rng, (2, 32, 32, 3), input_dtype=jnp.float32)
    y = model(v, jnp.zeros((2, 32, 32, 3), jnp.float32))
    assert y.shape == (2, 10)


def test_wrn16_8_param_count(rng):
    """WRN-16-8 must be the ~11M-param flagship (sanity vs the known torch count 11.0M)."""
    model = models.create("cifar100_wrn16_8", policy=F32)
    v = model.init(rng, (2, 32, 32, 3), input_dtype=jnp.float32)
    n = param_count(v["params"])
    assert 10.5e6 < n < 11.5e6, f"unexpected WRN-16-8 param count {n}"
    y = model(v, jnp.zeros((2, 32, 32, 3), jnp.float32))
    assert y.shape == (2, 100)


def test_resnet18_trains_one_step(rng):
    from tnn_tpu.train import create_train_state, make_train_step

    model = models.create("cifar100_resnet18", policy=F32)
    opt = nn.SGD(lr=0.1, momentum=0.9)
    state = create_train_state(model, opt, rng, (4, 32, 32, 3), input_dtype=jnp.float32)
    step = make_train_step(model, opt)
    x = jnp.asarray(np.random.RandomState(0).randn(4, 32, 32, 3), jnp.float32)
    y = jnp.asarray([0, 1, 2, 3], jnp.int32)
    state, m = step(state, x, y)
    assert np.isfinite(float(m["loss"]))


def test_vit_forward(rng):
    model = models.ViT(num_classes=10, patch_size=8, d_model=64, num_layers=2,
                       num_heads=4, policy=F32)
    v = model.init(rng, (2, 32, 32, 3))
    y = model(v, jnp.zeros((2, 32, 32, 3), jnp.float32))
    assert y.shape == (2, 10)
    cfg = model.get_config()
    assert module_from_config(cfg).get_config() == cfg


def test_gpt2_tiny_forward_and_config(rng):
    model = models.GPT2(vocab_size=100, max_len=32, num_layers=2, d_model=32,
                        num_heads=4, policy=F32)
    v = model.init(rng, (2, 16))
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 100, (2, 16)), jnp.int32)
    logits = model(v, ids)
    assert logits.shape == (2, 16, 100)
    cfg = model.get_config()
    assert module_from_config(cfg).get_config() == cfg


def test_gpt2_param_count_small(rng):
    """GPT-2 small must match the canonical 124M (tied embeddings)."""
    model = models.create("gpt2_small", policy=F32)
    v = model.init(rng, (1, 8))
    n = param_count(v["params"])
    assert 123e6 < n < 125e6, f"unexpected GPT-2 small param count {n}"


def test_gpt2_cached_generate_matches_uncached(rng):
    """KV-cache generation must produce the same tokens as full recompute."""
    model = models.GPT2(vocab_size=50, max_len=24, num_layers=2, d_model=32,
                        num_heads=4, policy=F32)
    v = model.init(rng, (1, 8))
    params = v["params"]
    prompt = jnp.asarray([[3, 1, 4, 1, 5]], jnp.int32)
    toks = gpt2_lib.generate(model, params, prompt, max_new_tokens=6)
    assert toks.shape == (1, 6)
    # uncached greedy reference: full forward each step (the reference's approach)
    ids = prompt
    ref = []
    for _ in range(6):
        logits = model({"params": params, "state": {}}, ids)
        nxt = jnp.argmax(logits[:, -1], axis=-1)
        ref.append(int(nxt[0]))
        ids = jnp.concatenate([ids, nxt[:, None]], axis=1)
    assert [int(t) for t in toks[0]] == ref


def test_gpt2_trains_one_step(rng):
    from tnn_tpu.nn import losses
    from tnn_tpu.train import create_train_state, make_train_step

    model = models.GPT2(vocab_size=64, max_len=16, num_layers=2, d_model=32,
                        num_heads=4, policy=F32)
    opt = nn.AdamW(lr=1e-3)
    state = create_train_state(model, opt, rng, (2, 16))
    step = make_train_step(model, opt, loss_fn="softmax_cross_entropy")
    ids = jnp.asarray(np.random.RandomState(1).randint(0, 64, (2, 16)), jnp.int32)
    # next-token: input ids, labels shifted
    state, m = step(state, ids, jnp.roll(ids, -1, axis=1))
    assert np.isfinite(float(m["loss"]))
