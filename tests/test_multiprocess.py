"""REAL multi-process integration tests: N OS processes running
examples/dist_worker.py against an in-process Coordinator.

The in-thread tests (test_distributed.py) prove protocol logic; these prove the
control plane composes with actual worker processes doing actual training —
the analog of the reference's docker-compose multi-node runs (sample_logs/),
which it only ever ran manually. Workers force the CPU platform via
TNN_PLATFORM (subprocesses must not touch the TPU relay during tests).
"""
import os
import signal
import subprocess
import sys
import tempfile

import pytest

from tnn_tpu.checkpoint import Checkpoint
from tnn_tpu.distributed import Coordinator

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _spawn_worker(port: int, rank=None, log=None):
    env = dict(os.environ, TNN_PLATFORM="cpu", TNN_NUM_DEVICES="1")
    # Sanitizer lanes (scripts/ci.sh --sanitize) LD_PRELOAD lib{a,t}san into
    # pytest. Do NOT propagate that into worker subprocesses: ASan's
    # __cxa_throw interceptor hard-aborts ("real___cxa_throw != 0" CHECK)
    # when jaxlib's bundled MLIR bindings throw C++ exceptions during the
    # worker's jit compile — an ASan-runtime/jaxlib incompatibility, nothing
    # of ours. The parent keeps full instrumentation (coordinator side of the
    # native control plane + decoders); workers run the release lib.
    preload = env.get("LD_PRELOAD", "")
    if "asan" in preload or "tsan" in preload:
        env.pop("LD_PRELOAD", None)
        env.pop("TNN_NATIVE_LIB", None)  # sanitized .so needs the preload
    # -m with cwd=REPO resolves tnn_tpu from the clone even when the package
    # is not pip-installed (a bare `python examples/dist_worker.py` would not)
    cmd = [sys.executable, "-m", "tnn_tpu.cli.dist_worker",
           "--coordinator", f"127.0.0.1:{port}"]
    if rank is not None:
        cmd += ["--rank", str(rank)]
    return subprocess.Popen(cmd, env=env, cwd=REPO, stdout=log or subprocess.DEVNULL,
                            stderr=subprocess.STDOUT)


def _base_config(tmp: str):
    return {
        "epochs": 1, "batch_size": 16, "max_steps": 5, "model_name": "mnist_cnn",
        "dataset_name": "synthetic", "snapshot_dir": os.path.join(tmp, "snaps"),
        "progress_print_interval": 1, "profiler_type": "NORMAL",
    }


def _cleanup(procs, coord):
    for p in procs:
        if p.poll() is None:
            p.kill()
    for p in procs:
        try:
            p.wait(timeout=10)
        except subprocess.TimeoutExpired:
            pass
    coord.close()


class TestMultiProcess:
    def test_dp_run_profiles_and_save(self, tmp_path):
        """Two worker PROCESSES train to completion; profiles merge across
        process boundaries; a mid-run save RPC lands from every rank."""
        tmp = str(tmp_path)
        coord = Coordinator(num_workers=2)
        procs = [_spawn_worker(coord.port()), _spawn_worker(coord.port())]
        try:
            ranks = coord.wait_for_workers(timeout=90)
            assert ranks == [0, 1]
            coord.start_profiling()
            coord.deploy_config(_base_config(tmp), timeout=300)
            coord.barrier("start", timeout=300)  # jax import + compile
            # mid-run save: must succeed while training is in flight
            coord.save_all(os.path.join(tmp, "mid"), timeout=300)
            for r in (0, 1):
                assert Checkpoint(
                    os.path.join(tmp, "mid", f"rank{r}")).latest_path(), \
                    f"rank {r} did not save"
            coord.barrier("done", timeout=300)
            merged = coord.collect_profiles(timeout=120)
            sources = {e.source for e in merged.events}
            assert {"worker0", "worker1"} <= sources, sources
            coord.shutdown(timeout=30)
            for p in procs:
                assert p.wait(timeout=60) == 0
        finally:
            _cleanup(procs, coord)

    def test_worker_death_detected_and_rank_rejoins(self, tmp_path):
        """SIGKILL one worker process mid-run: the coordinator detects it via
        disconnect, and a fresh process re-admits the dead rank (the
        reference's recovery commands are unimplemented stubs,
        worker.hpp:216-277)."""
        tmp = str(tmp_path)
        coord = Coordinator(num_workers=2, heartbeat_timeout=600)
        procs = [_spawn_worker(coord.port(), rank=0),
                 _spawn_worker(coord.port(), rank=1)]
        try:
            coord.wait_for_workers(timeout=90)
            cfg = dict(_base_config(tmp), epochs=50, max_steps=-1)
            # config ack + barrier deadlines are generous because a fresh
            # process pays a full jax import, and on a 1-CPU host under
            # concurrent suite load that alone has exceeded two minutes
            coord.deploy_config(cfg, timeout=300)
            coord.barrier("start", timeout=300)
            procs[0].send_signal(signal.SIGKILL)  # hard crash, no goodbye
            # event-driven: the kernel's RST on the dead pipe wakes the wait
            coord.wait_failed(0, timeout=120)
            # restart rank 0 in a new process: rejoin path (woken by the
            # rejoin HANDSHAKE, not a polling lap)
            procs.append(_spawn_worker(coord.port(), rank=0))
            coord.wait_alive(0, timeout=300)
        finally:
            _cleanup(procs, coord)
