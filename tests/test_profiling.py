"""Profiler subsystem tests (reference: include/profiling/, merge semantics
profiler.hpp:52-63, communicator counters communicator.hpp:157-184)."""
import json
import threading
import time

import pytest

from tnn_tpu.profiling import Event, EventType, Profiler, profiled
from tnn_tpu.profiling import profiler as prof_mod


def test_scope_records_event():
    p = Profiler(source="t")
    with p.scope("work", EventType.COMPUTE):
        time.sleep(0.01)
    evs = p.events
    assert len(evs) == 1
    assert evs[0].name == "work"
    assert evs[0].type is EventType.COMPUTE
    assert evs[0].source == "t"
    assert evs[0].duration >= 0.009


def test_counters_accumulate():
    p = Profiler()
    p.tick("send", 0.5)
    p.tick("send", 0.25)
    p.tick("recv", 1.0)
    assert p.counters == {"send": 0.75, "recv": 1.0}


def test_thread_safety():
    p = Profiler()

    def worker(i):
        for _ in range(100):
            p.add_event(EventType.OTHER, 0.0, 1.0, f"w{i}")
            p.tick("n", 1.0)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(p.events) == 800
    assert p.counters["n"] == 800.0


def test_merge_rebases_timeline():
    a = Profiler(source="coord")
    b = Profiler(source="worker1")
    # simulate b's clock starting at a different origin
    b._origin = a._origin - 100.0
    b.add_event(EventType.COMPUTE, b._origin + 1.0, b._origin + 2.0, "fwd")
    a.add_event(EventType.COMPUTE, a._origin + 1.0, a._origin + 2.0, "loss")
    a.merge(b)
    evs = {e.name: e for e in a.events}
    # after rebase both events sit at origin+1..origin+2 on a's clock
    assert evs["fwd"].start == pytest.approx(evs["loss"].start)
    assert evs["fwd"].source == "worker1"


def test_merge_accumulates_counters():
    a, b = Profiler(), Profiler()
    a.tick("bytes", 1.0)
    b.tick("bytes", 2.0)
    a.merge(b)
    assert a.counters["bytes"] == 3.0


def test_dict_roundtrip():
    p = Profiler(source="w0")
    with p.scope("step", EventType.COMMUNICATION):
        pass
    p.tick("k", 0.125)
    q = Profiler.from_dict(json.loads(json.dumps(p.to_dict())))
    assert q.source == "w0"
    assert len(q.events) == 1
    assert q.events[0].type is EventType.COMMUNICATION
    assert q.counters == {"k": 0.125}
    assert q._origin == p._origin


def test_summary():
    p = Profiler()
    p.add_event(EventType.COMPUTE, 0.0, 1.0, "step")
    p.add_event(EventType.COMPUTE, 1.0, 3.0, "step")
    s = p.summary()
    assert s["step"]["count"] == 2
    assert s["step"]["total_s"] == pytest.approx(3.0)
    assert s["step"]["mean_s"] == pytest.approx(1.5)


def test_chrome_trace_export(tmp_path):
    p = Profiler(source="host0")
    p.add_event(EventType.COMPUTE, 0.0, 0.5, "fwd", source="stage0")
    p.add_event(EventType.COMMUNICATION, 0.5, 0.6, "sendrecv", source="stage1")
    path = tmp_path / "trace.json"
    trace = p.to_chrome_trace(str(path))
    loaded = json.loads(path.read_text())["traceEvents"]
    assert loaded == trace
    rows = [t for t in trace if t.get("ph") == "X"]
    assert {r["cat"] for r in rows} == {"compute", "communication"}
    # distinct sources land on distinct tids (one Gantt row per source)
    assert len({r["tid"] for r in rows}) == 2


def test_profiled_noop_when_disabled():
    prof_mod.enable(False)
    before = len(prof_mod.GlobalProfiler.events)
    with profiled("x"):
        pass
    assert len(prof_mod.GlobalProfiler.events) == before


def test_profiled_records_when_enabled():
    prof_mod.enable(True)
    try:
        before = len(prof_mod.GlobalProfiler.events)
        with profiled("y"):
            pass
        assert len(prof_mod.GlobalProfiler.events) == before + 1
    finally:
        prof_mod.enable(False)
        prof_mod.GlobalProfiler.clear()


def test_explicit_profiler_ignores_enable_flag():
    prof_mod.enable(False)
    p = Profiler()
    with profiled("z", profiler=p):
        pass
    assert len(p.events) == 1
