"""Tests: tensor-file round trip, model save/load, full training-state checkpoints."""
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tnn_tpu import checkpoint as ckpt_lib
from tnn_tpu import models, nn
from tnn_tpu.data import SyntheticDataLoader
from tnn_tpu.train import TrainState, create_train_state, make_train_step


def small_model():
    return models.create("mnist_cnn")


class TestTensorFile:
    def test_round_trip_dtypes(self, tmp_path):
        trees = {
            "a": {"x": jnp.arange(6, dtype=jnp.float32).reshape(2, 3),
                  "y": {"z": jnp.ones((4,), jnp.bfloat16)}},
            "b": jnp.asarray(3, jnp.int32),
        }
        path = str(tmp_path / "t.tnn")
        ckpt_lib.save_tensors(path, trees, meta={"k": 1})
        flat, meta = ckpt_lib.read_tensor_file(path)
        assert meta == {"k": 1}
        assert set(flat) == {"a/x", "a/y/z", "b"}
        loaded, _ = ckpt_lib.load_tensors(path, {
            "a": jax.tree_util.tree_map(jnp.zeros_like, trees["a"]),
            "b": jnp.zeros((), jnp.int32)})
        np.testing.assert_array_equal(np.asarray(loaded["a"]["x"]),
                                      np.asarray(trees["a"]["x"]))
        assert str(np.asarray(loaded["a"]["y"]["z"]).dtype) == "bfloat16"
        assert int(loaded["b"]) == 3

    def test_structure_mismatch_raises(self, tmp_path):
        path = str(tmp_path / "t.tnn")
        ckpt_lib.save_tensors(path, {"a": {"x": jnp.zeros((2,))}})
        with pytest.raises(KeyError):
            ckpt_lib.load_tensors(path, {"a": {"x": jnp.zeros((2,)),
                                               "extra": jnp.zeros((1,))}})
        with pytest.raises(ValueError):
            ckpt_lib.load_tensors(path, {"a": {"x": jnp.zeros((3,))}})


class TestModelSaveLoad:
    def test_model_round_trip(self, tmp_path):
        model = small_model()
        variables = model.init(jax.random.PRNGKey(0), (2, 28, 28, 1))
        path = str(tmp_path / "model.tnn")
        ckpt_lib.save_model(path, model, variables["params"], variables["state"])

        model2, vars2 = ckpt_lib.load_model(path, input_shape=(2, 28, 28, 1))
        assert model2.get_config() == model.get_config()
        x = jnp.ones((2, 28, 28, 1), jnp.bfloat16)
        y1 = model(variables, x)
        y2 = model2(vars2, x)
        np.testing.assert_allclose(np.asarray(y1, np.float32),
                                   np.asarray(y2, np.float32), atol=1e-5)

    def test_model_load_without_template(self, tmp_path):
        model = small_model()
        variables = model.init(jax.random.PRNGKey(0), (2, 28, 28, 1))
        path = str(tmp_path / "model.tnn")
        ckpt_lib.save_model(path, model, variables["params"])
        model2, vars2 = ckpt_lib.load_model(path)
        flat1 = jax.tree_util.tree_leaves(variables["params"])
        flat2 = jax.tree_util.tree_leaves(vars2["params"])
        assert sum(x.size for x in flat1) == sum(x.size for x in flat2)


class TestFullCheckpoint:
    def _state_and_step(self):
        model = small_model()
        opt = nn.SGD(lr=0.05, momentum=0.9)
        state = create_train_state(model, opt, jax.random.PRNGKey(0), (8, 28, 28, 1))
        step = make_train_step(model, opt, donate=False)
        return model, opt, state, step

    def test_save_restore_exact_resume(self, tmp_path):
        model, opt, state, step = self._state_and_step()
        rs = np.random.RandomState(0)
        data = jnp.asarray(rs.randn(8, 28, 28, 1), jnp.bfloat16)
        labels = jnp.asarray(rs.randint(0, 10, 8), jnp.int32)

        state, _ = step(state, data, labels)
        ckpt = ckpt_lib.Checkpoint(str(tmp_path / "ck"))
        sched = nn.ReduceLROnPlateau(patience=0)
        sched.observe(1.0)
        sched.observe(2.0)  # triggers a cut -> non-default state
        loader = SyntheticDataLoader(32, (28, 28, 1), 10)
        loader.shuffle()
        loader.get_batch(8)
        ckpt.save(state, model=model, scheduler=sched, loader=loader,
                  extra={"note": "e2e"})

        # continue the "original" run one more step
        state_cont, m_cont = step(state, data, labels)

        # restore into a FRESH state and take the same step -> identical result
        model2, opt2, fresh, step2 = self._state_and_step()
        sched2 = nn.ReduceLROnPlateau(patience=0)
        loader2 = SyntheticDataLoader(32, (28, 28, 1), 10)
        restored, meta = ckpt.restore(fresh, scheduler=sched2, loader=loader2)
        assert int(restored.step) == int(state.step)
        assert sched2.current_scale() == sched.current_scale()
        assert loader2.state_dict() == loader.state_dict()
        assert meta["extra"]["note"] == "e2e"

        state_re, m_re = step2(restored, data, labels)
        np.testing.assert_allclose(float(m_re["loss"]), float(m_cont["loss"]),
                                   rtol=1e-6)
        for a, b in zip(jax.tree_util.tree_leaves(state_re.params),
                        jax.tree_util.tree_leaves(state_cont.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_llama_model_round_trip(self, tmp_path):
        """The registry round-trip handles the Llama family (bias-free MHA,
        RoPE, int8 cache config) — save_model/load_model reproduce outputs."""
        from tnn_tpu.models.llama import Llama

        m = Llama(vocab_size=64, max_len=16, num_layers=1, d_model=32,
                  num_heads=4, num_kv_heads=2, kv_cache_dtype="int8")
        v = m.init(jax.random.PRNGKey(0), (1, 8))
        p = str(tmp_path / "llama.tnn")
        ckpt_lib.save_model(p, m, v["params"])
        m2, v2 = ckpt_lib.load_model(p, rng=jax.random.PRNGKey(1),
                                     input_shape=(1, 8))
        assert (m2.num_kv_heads, m2.kv_cache_dtype) == (2, "int8")
        ids = jnp.asarray(np.random.RandomState(0).randint(0, 64, (1, 8)),
                          jnp.int32)
        o1, _ = m.apply({"params": v["params"], "state": {}}, ids, train=False)
        o2, _ = m2.apply({"params": v2["params"], "state": {}}, ids,
                         train=False)
        np.testing.assert_allclose(np.asarray(o1), np.asarray(o2), rtol=1e-6)

    def test_async_save_matches_blocking(self, tmp_path):
        """block=False must produce an identical checkpoint even when the
        donated train state is immediately reused for more steps (the write
        runs from a host snapshot taken before returning)."""
        model, opt, state, step = self._state_and_step()
        rs = np.random.RandomState(1)
        data = jnp.asarray(rs.randn(8, 28, 28, 1), jnp.bfloat16)
        labels = jnp.asarray(rs.randint(0, 10, 8), jnp.int32)
        state, _ = step(state, data, labels)

        ck_async = ckpt_lib.Checkpoint(str(tmp_path / "a"))
        ck_sync = ckpt_lib.Checkpoint(str(tmp_path / "s"))
        ck_sync.save(state, model=model)
        ck_async.save(state, model=model, block=False)
        # hammer the donated buffers while the write is in flight
        for _ in range(3):
            state, _ = step(state, data, labels)
        ck_async.wait()

        _, _, fresh_a, _ = self._state_and_step()
        _, _, fresh_s, _ = self._state_and_step()
        ra, _ = ck_async.restore(fresh_a)
        rs_, _ = ck_sync.restore(fresh_s)
        for a, b in zip(jax.tree_util.tree_leaves(ra.params),
                        jax.tree_util.tree_leaves(rs_.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree_util.tree_leaves(ra.opt_state),
                        jax.tree_util.tree_leaves(rs_.opt_state)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_async_save_failure_surfaces_on_wait(self, tmp_path, monkeypatch):
        """A failed background write must raise at wait(), not vanish."""
        model, opt, state, step = self._state_and_step()
        ck = ckpt_lib.Checkpoint(str(tmp_path / "x"))

        def boom(*a, **kw):
            raise OSError("disk full")

        monkeypatch.setattr(ckpt_lib, "save_tensors", boom)
        ck.save(state, block=False)
        with pytest.raises(OSError, match="disk full"):
            ck.wait()
        ck.wait()  # error is consumed; a second wait is a clean no-op

    def test_retention_and_best(self, tmp_path):
        model, opt, state, step = self._state_and_step()
        ckpt = ckpt_lib.Checkpoint(str(tmp_path / "ck"), keep=2)
        for i in range(4):
            state = state._replace(step=jnp.asarray(i, jnp.int32))
            ckpt.save(state, model=model)
        steps = sorted(ckpt._step_dirs())
        assert steps == [2, 3]
        ckpt.save(state, model=model, best=True)
        assert os.path.isdir(os.path.join(str(tmp_path / "ck"), "best"))
        assert ckpt.latest_path().endswith("step_3")

    def test_restore_from_concrete_dir(self, tmp_path):
        """resume='.../best' (or a step_N dir) resolves directly, not via step_* scan."""
        model, opt, state, step = self._state_and_step()
        ckpt = ckpt_lib.Checkpoint(str(tmp_path / "ck"))
        ckpt.save(state, model=model, best=True)
        _, _, fresh, _ = self._state_and_step()
        restored, _ = ckpt_lib.Checkpoint(
            str(tmp_path / "ck" / "best")).restore(fresh)
        assert int(restored.step) == int(state.step)
