"""Configuration: ``[tool.tnnlint]`` in pyproject.toml.

Layout::

    [tool.tnnlint]
    paths = ["tnn_tpu"]
    exclude = ["__pycache__"]
    baseline = "tools/tnnlint/baseline.json"
    ignore = []                       # rule names to skip entirely

    [tool.tnnlint.rules.unbounded-compile-key]
    bucket_helpers = ["pow2_bucket"]

Loading prefers :mod:`tomllib` (3.11+) / :mod:`tomli`; on the 3.10 base
image neither ships, so a minimal TOML-subset parser below handles exactly
what this file needs — ``[section]`` headers, string/int/float/bool scalars
and (possibly multi-line) homogeneous string lists.  Anything fancier in
*other* sections of pyproject is skipped, not parsed.
"""
from __future__ import annotations

import json
import re
from pathlib import Path
from typing import Any, Dict, Optional

DEFAULTS: Dict[str, Any] = {
    "paths": ["tnn_tpu"],
    "exclude": [r"__pycache__"],
    "baseline": "tools/tnnlint/baseline.json",
    "ignore": [],
    "rules": {},
}


def _parse_scalar(text: str) -> Any:
    text = text.strip()
    if text.startswith("[") and text.endswith("]"):
        # homogeneous list of scalars; JSON accepts the common cases once
        # single quotes are normalized and trailing commas removed
        body = re.sub(r",\s*]", "]", text.replace("'", '"'))
        return json.loads(body)
    if text in ("true", "false"):
        return text == "true"
    if (text.startswith('"') and text.endswith('"')) or \
            (text.startswith("'") and text.endswith("'")):
        return text[1:-1]
    try:
        return int(text)
    except ValueError:
        pass
    try:
        return float(text)
    except ValueError:
        return text


def _parse_toml_subset(source: str) -> Dict[str, Any]:
    """Section -> {key: value} for the sections this tool reads."""
    out: Dict[str, Dict[str, Any]] = {}
    section = ""
    pending_key, pending_val = None, ""
    for raw in source.splitlines():
        line = raw.strip()
        if pending_key is not None:
            pending_val += " " + line
            if pending_val.count("[") == pending_val.count("]"):
                out[section][pending_key] = _parse_scalar(pending_val)
                pending_key = None
            continue
        if not line or line.startswith("#"):
            continue
        m = re.match(r"^\[(?P<name>[^\]]+)\]$", line)
        if m:
            section = m.group("name").strip()
            out.setdefault(section, {})
            continue
        m = re.match(r"^(?P<key>[\w.-]+|\"[^\"]+\")\s*=\s*(?P<val>.*)$", line)
        if not m or section not in out:
            continue
        key = m.group("key").strip('"')
        val = m.group("val").split("#")[0].rstrip() \
            if not m.group("val").lstrip().startswith("[") else m.group("val")
        if val.count("[") != val.count("]"):
            pending_key, pending_val = key, val
            continue
        out[section][key] = _parse_scalar(val)
    return out


def _load_toml(path: Path) -> Dict[str, Any]:
    data = path.read_text(encoding="utf-8")
    try:
        import tomllib                              # 3.11+
        return tomllib.loads(data)
    except ImportError:
        pass
    try:
        import tomli                                # optional backport
        return tomli.loads(data)
    except ImportError:
        pass
    # flatten the subset parse back into a nested dict
    flat = _parse_toml_subset(data)
    nested: Dict[str, Any] = {}
    for section, values in flat.items():
        node = nested
        for part in section.split("."):
            node = node.setdefault(part, {})
        node.update(values)
    return nested


def find_pyproject(start: Optional[Path] = None) -> Optional[Path]:
    d = Path(start).resolve() if start is not None else Path.cwd()
    for parent in [d, *d.parents]:
        p = parent / "pyproject.toml"
        if p.is_file():
            return p
    return None


def load_config(start: Optional[Path] = None) -> Dict[str, Any]:
    """DEFAULTS overlaid with ``[tool.tnnlint]`` from the nearest
    pyproject.toml (searched upward from ``start``/cwd)."""
    cfg = {k: (dict(v) if isinstance(v, dict) else list(v)
               if isinstance(v, list) else v) for k, v in DEFAULTS.items()}
    pyproject = find_pyproject(start)
    if pyproject is None:
        return cfg
    section = _load_toml(pyproject).get("tool", {}).get("tnnlint", {})
    for key, value in section.items():
        if key == "rules":
            cfg["rules"].update(value)
        else:
            cfg[key] = value
    cfg["_pyproject_dir"] = str(pyproject.parent)
    return cfg
