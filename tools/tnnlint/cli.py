"""``tnn-lint`` entry point.

Exit status: 0 clean (or everything baselined), 1 violations, 2 usage error.
"""
from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import List, Optional

from .baseline import compare, read_baseline, write_baseline
from .config import load_config
from .core import Violation, lint_paths, rule_registry


def _report_text(fresh: List[Violation], stale: List[str],
                 total: int, out) -> None:
    for v in fresh:
        print(v.render(), file=out)
    for fp in stale:
        print(f"stale baseline entry {fp}: finding no longer present — "
              f"rerun with --write-baseline to prune", file=out)
    if fresh or stale:
        suppressed = total - len(fresh)
        tail = f" ({suppressed} baselined)" if suppressed else ""
        print(f"{len(fresh)} violation(s){tail}, "
              f"{len(stale)} stale baseline entr(y/ies)", file=out)
    else:
        print("clean", file=out)


def _report_json(fresh: List[Violation], stale: List[str],
                 total: int, out) -> None:
    payload = {
        "violations": [
            {"rule": v.rule, "path": v.path, "line": v.line,
             "col": v.col + 1, "message": v.message,
             "fingerprint": v.fingerprint()}
            for v in fresh
        ],
        "stale_baseline": stale,
        "baselined": total - len(fresh),
    }
    json.dump(payload, out, indent=2)
    out.write("\n")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tnn-lint",
        description="Static contract checks for the TNN-TPU serving stack "
                    "(see docs/lint.md).")
    p.add_argument("paths", nargs="*",
                   help="files or directories (default: [tool.tnnlint] paths)")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--select", action="append", default=None, metavar="RULE",
                   help="run only these rules (repeatable)")
    p.add_argument("--ignore", action="append", default=[], metavar="RULE",
                   help="skip these rules (repeatable)")
    p.add_argument("--baseline", default=None, metavar="FILE",
                   help="baseline file (default: [tool.tnnlint] baseline)")
    p.add_argument("--no-baseline", action="store_true",
                   help="report everything, ignoring any baseline file")
    p.add_argument("--write-baseline", action="store_true",
                   help="accept all current findings into the baseline")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    out = sys.stdout

    if args.list_rules:
        registry = rule_registry()
        width = max(len(n) for n in registry)
        for name in sorted(registry):
            print(f"{name:<{width}}  {registry[name].description}", file=out)
        return 0

    cfg = load_config()
    root = Path(cfg.get("_pyproject_dir", "."))
    paths = args.paths or [str(root / p) for p in cfg["paths"]]
    baseline_path = Path(args.baseline) if args.baseline \
        else root / cfg["baseline"]

    try:
        violations = lint_paths(
            paths, options=cfg["rules"], select=args.select,
            ignore=list(cfg["ignore"]) + args.ignore,
            exclude=cfg["exclude"])
    except ValueError as e:
        print(f"tnn-lint: {e}", file=sys.stderr)
        return 2

    if args.write_baseline:
        write_baseline(baseline_path, violations)
        print(f"wrote {len(violations)} finding(s) to {baseline_path}",
              file=out)
        return 0

    baseline = {} if args.no_baseline else read_baseline(baseline_path)
    fresh, stale = compare(violations, baseline)
    reporter = _report_json if args.format == "json" else _report_text
    reporter(fresh, stale, len(violations), out)
    return 1 if (fresh or stale) else 0


if __name__ == "__main__":
    sys.exit(main())
